"""Event stream pub/sub: snapshot+follow subscriptions, FSM publishing,
and the streaming Subscribe RPC across a leader change.

Parity model: agent/consul/stream/event_publisher_test.go +
agent/rpc/subscribe/subscribe_test.go (snapshot, end-of-snapshot
marker, live follow, reset on store abandon).
"""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import wait_for_leader

from consul_tpu.stream import (
    TOPIC_KV,
    TOPIC_SERVICE_HEALTH,
    Event,
    EventPublisher,
    SubscriptionClosed,
)

from test_cluster_agents import make_server, shutdown_all, start_cluster
from consul_tpu.net.transport import InMemoryNetwork


# ---------------------------------------------------------------------------
# publisher unit tests
# ---------------------------------------------------------------------------


def test_snapshot_then_live():
    async def main():
        pub = EventPublisher()
        pub.register_snapshot_handler(
            "t", lambda key: (7, [Event("t", key, 7, {"snap": key})])
        )
        sub = pub.subscribe("t", "a")
        ev = await sub.next()
        assert ev.payload == {"snap": "a"}
        eos = await sub.next()
        assert eos.end_of_snapshot and eos.index == 7
        pub.publish([Event("t", "a", 8, {"live": 1})])
        live = await sub.next()
        assert live.payload == {"live": 1} and live.index == 8

    asyncio.run(main())


def test_key_filtering_and_multiple_subscribers():
    async def main():
        pub = EventPublisher()
        sub_a = pub.subscribe("t", "a")
        sub_all = pub.subscribe("t", "")
        pub.publish([Event("t", "b", 1, "B"), Event("t", "a", 1, "A")])
        assert (await sub_a.next()).payload == "A"
        assert (await sub_all.next()).payload == "B"
        assert (await sub_all.next()).payload == "A"
        # sub_a never sees b's event; a timeout proves the filter.
        with pytest.raises(asyncio.TimeoutError):
            await sub_a.next(timeout=0.05)

    asyncio.run(main())


def test_slow_subscriber_misses_nothing():
    async def main():
        pub = EventPublisher()
        sub = pub.subscribe("t", "")
        for i in range(50):
            pub.publish([Event("t", "k", i + 1, i)])
        got = [(await sub.next()).payload for _ in range(50)]
        assert got == list(range(50))

    asyncio.run(main())


def test_close_all_wakes_and_raises():
    async def main():
        pub = EventPublisher()
        sub = pub.subscribe("t", "")
        waiter = asyncio.create_task(sub.next())
        await asyncio.sleep(0.01)
        pub.close_all()
        with pytest.raises(SubscriptionClosed):
            await waiter

    asyncio.run(main())


# ---------------------------------------------------------------------------
# cluster end-to-end: Subscribe RPC through the muxed stream
# ---------------------------------------------------------------------------


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=120))


class TestSubscribeRPC:
    async def _collect(self, events, it):
        async for ev in it:
            events.append(ev)

    async def test_snapshot_then_live_across_leader_change(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        follower = next(s for s in servers if not s.is_leader())

        # Seed one instance of 'web' BEFORE subscribing: it must arrive
        # in the snapshot.
        await leader.rpc_client.call(
            f"{leader.node_id}:rpc", "Catalog.Register",
            {"node": "n1", "address": "10.0.0.1",
             "service": {"id": "web1", "service": "web", "port": 80}},
        )

        # Wait for the registration to replicate to the follower so the
        # snapshot (served from ITS store) contains it.
        await wait_until(
            lambda: follower.store.check_service_nodes("web")[1],
            msg="registration replicated to follower",
        )

        events: list = []
        it = follower.rpc_client.stream(
            f"{follower.node_id}:rpc", "Subscribe.Subscribe",
            {"topic": TOPIC_SERVICE_HEALTH, "key": "web"},
        )
        task = asyncio.create_task(self._collect(events, it))

        await wait_until(
            lambda: any(e.get("end_of_snapshot") for e in events),
            msg="snapshot delivered",
        )
        snap = [e for e in events if not e.get("end_of_snapshot")]
        assert snap and any(
            r["service"]["id"] == "web1" for r in snap[0]["payload"]
        )

        # Live follow: another instance registers.
        await leader.rpc_client.call(
            f"{leader.node_id}:rpc", "Catalog.Register",
            {"node": "n2", "address": "10.0.0.2",
             "service": {"id": "web2", "service": "web", "port": 80}},
        )
        await wait_until(
            lambda: any(
                not e.get("end_of_snapshot")
                and e.get("payload") is not None
                and len(e["payload"]) == 2
                for e in events
            ),
            msg="live event with both instances",
        )

        # Leader change: the subscription is served from the follower's
        # local store, which keeps applying the new leader's commits.
        await leader.shutdown()
        remaining = [s for s in servers if s is not leader]
        new_leader = await wait_for_leader(remaining)
        count_before = len(events)
        await new_leader.rpc_client.call(
            f"{new_leader.node_id}:rpc",
            "Catalog.Register",
            {"node": "n3", "address": "10.0.0.3",
             "service": {"id": "web3", "service": "web", "port": 80}},
        )
        await wait_until(
            lambda: len(events) > count_before,
            msg="live event after leader change",
        )
        task.cancel()
        await shutdown_all(*remaining)

    async def test_kv_topic(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        events: list = []
        it = leader.rpc_client.stream(
            f"{leader.node_id}:rpc", "Subscribe.Subscribe",
            {"topic": TOPIC_KV, "key": "app/config"},
        )
        task = asyncio.create_task(self._collect(events, it))
        await wait_until(
            lambda: any(e.get("end_of_snapshot") for e in events),
            msg="kv snapshot",
        )
        await leader.rpc_client.call(
            f"{leader.node_id}:rpc", "KVS.Apply",
            {"op": "set", "entry": {"key": "app/config", "value": b"v1"}},
        )
        await wait_until(
            lambda: any(
                (e.get("payload") or {}).get("value") == b"v1" for e in events
            ),
            msg="kv live event",
        )
        # A different key's write must NOT arrive.
        await leader.rpc_client.call(
            f"{leader.node_id}:rpc", "KVS.Apply",
            {"op": "set", "entry": {"key": "other", "value": b"z"}},
        )
        await asyncio.sleep(0.1)
        assert not any(e.get("key") == "other" for e in events)
        task.cancel()
        await shutdown_all(*servers)
