"""equivlint: the exactness-ladder prover (E1), the golden
program-fingerprint gate (E2/E3), and the Pallas DMA-discipline rules
(P1-P3).

Tier-1 carries the whole certification story: the canonicalizer's
algebraic properties, every declared EQUIV_PAIR closing as PROVED or
WITNESSED (zero FAILED — this is the gate that let the runtime
bit-equality duplicates move behind ``-m slow``), the committed golden
snapshot diffing clean, and the planted DMA fixtures firing with
file:line provenance while the real ring kernel passes silent.
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import pytest

from consul_tpu.analysis.equivlint import (
    EQUIV_RULES,
    canonical_hash,
    canonicalize,
    changed_program_keys,
    diff_golden,
    fingerprint,
    git_changed_files,
    load_golden,
    pallas_findings,
    prove_pairs,
    write_golden,
)
from consul_tpu.sim.engine import EQUIV_PAIRS, SimProgram, jaxlint_registry

SDS = jax.ShapeDtypeStruct
_VEC = SDS((16,), jnp.float32)


def _hash(fn, *args):
    return canonical_hash(jax.make_jaxpr(fn)(*args))


def _program(name, fn, *args):
    return SimProgram(name=name, entrypoint=name,
                      build=lambda: (fn, tuple(args)), n=0)


# ---------------------------------------------------------------------------
# Registry fixtures: trace once per module, share across tests.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_programs():
    return jaxlint_registry(include=("small",))


@pytest.fixture(scope="module")
def small_traces(small_programs):
    return {n: p.trace() for n, p in small_programs.items()}


@pytest.fixture(scope="module")
def small_verdicts(small_programs, small_traces):
    return prove_pairs(small_programs, traces=small_traces)


# ---------------------------------------------------------------------------
# Canonicalizer properties: what must NOT move the hash, and what must.
# ---------------------------------------------------------------------------


class TestCanonicalizer:
    def test_alpha_renamed_locals_identical(self):
        def a(x, y):
            acc = x * 2.0
            gain = acc + y
            return gain - 1.0

        def b(p, q):
            t0 = p * 2.0
            t1 = t0 + q
            return t1 - 1.0

        assert _hash(a, _VEC, _VEC) == _hash(b, _VEC, _VEC)

    def test_commutative_operand_permutation_identical(self):
        def a(x, y):
            return x + y, x * y, jnp.maximum(x, y)

        def b(x, y):
            return y + x, y * x, jnp.maximum(y, x)

        assert _hash(a, _VEC, _VEC) == _hash(b, _VEC, _VEC)

    def test_noncommutative_operand_swap_differs(self):
        # The sort is restricted to commutative primitives: x - y and
        # y - x are DIFFERENT programs and must hash apart.
        assert (_hash(lambda x, y: x - y, _VEC, _VEC)
                != _hash(lambda x, y: y - x, _VEC, _VEC))

    def test_dead_code_padding_identical(self):
        def lean(x):
            return x * 3.0

        def padded(x):
            waste = jnp.sum(jnp.sin(x)) + 41.0  # dead: never escapes
            del waste
            return x * 3.0

        assert _hash(lean, _VEC) == _hash(padded, _VEC)

    def test_changed_constant_differs(self):
        # The anti-property: a genuinely different program (the fanout
        # knob moved) must NOT canonicalize together.
        assert (_hash(lambda x: x * 3.0, _VEC)
                != _hash(lambda x: x * 4.0, _VEC))

    def test_dead_code_in_scan_body_identical(self):
        def lean(c, xs):
            return jax.lax.scan(lambda c, x: (c + x, c), c, xs)

        def padded(c, xs):
            def tick(c, x):
                waste = jnp.cos(x) * 7.0
                del waste
                return c + x, c

            return jax.lax.scan(tick, c, xs)

        args = (SDS((), jnp.float32), SDS((8,), jnp.float32))
        assert _hash(lean, *args) == _hash(padded, *args)

    def test_canonical_text_has_no_addresses(self, small_traces):
        # Process stability: id()-derived reprs (0x7f...) in any param
        # would make the committed golden machine-local garbage.
        text = canonicalize(
            small_traces["sharded_broadcast@small/D1/ring"]
        )
        assert "0x" not in text


# ---------------------------------------------------------------------------
# E1: the declared ladder closes — the certificate that retired the
# runtime bit-equality duplicates into -m slow.
# ---------------------------------------------------------------------------


class TestPairGate:
    def test_every_pair_closes(self, small_verdicts):
        bad = [v for v in small_verdicts
               if v.verdict not in ("PROVED", "WITNESSED")]
        assert len(small_verdicts) == len(EQUIV_PAIRS)
        assert not bad, "\n".join(v.format() for v in bad)

    def test_explicit_default_pairs_prove_structurally(self,
                                                      small_verdicts):
        # The defaults-are-defaults rungs (streamcast uniform policy,
        # telemetry=False, amortize auto-resolution) are projection-
        # free and must close WITHOUT spending a witness execution.
        proved = {v.pair for v in small_verdicts
                  if v.verdict == "PROVED"}
        for key in ("streamcast@small/uniform",
                    "broadcast@small/notelemetry",
                    "sparse@small/amortize"):
            assert any(key in p for p in proved), (key, proved)

    def test_every_family_keeps_a_witnessed_rung(self, small_verdicts):
        # Satellite contract: one WITNESSED representative per sharded
        # family stays in tier-1 so the ladder is exercised end to end
        # even with the duplicate runtime tests behind -m slow.
        witnessed = " ".join(v.pair for v in small_verdicts
                             if v.verdict == "WITNESSED")
        for family in ("broadcast", "membership", "sparse",
                       "streamcast", "geo", "swim"):
            assert family in witnessed, (family, witnessed)

    def test_witness_divergence_is_loud(self):
        # A pair that is NOT equivalent must come back FAILED with the
        # divergence named — never silently dropped.  Structurally
        # distinct (different constant), so the prover spends the
        # witness execution, which catches the bit divergence.
        from consul_tpu.sim.engine import EquivPair

        key_sds = SDS((2,), jnp.uint32)

        def _p(name, k):
            return SimProgram(
                name=name, entrypoint=name,
                build=lambda: (lambda x, key: x * k, (_VEC, key_sds)),
                n=0, init=lambda: jnp.ones(16, jnp.float32),
            )

        progs = {"three@t": _p("three@t", 3.0),
                 "four@t": _p("four@t", 4.0)}
        bad = EquivPair(a="three@t", b="four@t",
                        relation="planted-divergence", family="test")
        [v] = prove_pairs(progs, pairs=(bad,))
        assert v.verdict == "FAILED"
        assert v.detail

    def test_witness_without_init_fails_loudly(self):
        # A registry entry predating the init seam cannot be silently
        # skipped: the verdict is FAILED and names the hole.
        from consul_tpu.sim.engine import EquivPair

        progs = {
            "a@t": _program("a@t", lambda x: x * 3.0, _VEC),
            "b@t": _program("b@t", lambda x: x * 4.0, _VEC),
        }
        pair = EquivPair(a="a@t", b="b@t", relation="no-init",
                         family="test")
        [v] = prove_pairs(progs, pairs=(pair,))
        assert v.verdict == "FAILED"
        assert "init" in v.detail

    def test_missing_side_skips_loudly(self, small_programs):
        from consul_tpu.sim.engine import EquivPair

        ghost = EquivPair(a="broadcast@small", b="nonesuch@small",
                          relation="ghost", family="test")
        [v] = prove_pairs(small_programs, pairs=(ghost,))
        assert v.verdict == "SKIPPED"
        assert "nonesuch" in v.detail


# ---------------------------------------------------------------------------
# E2/E3: the golden fingerprint gate.
# ---------------------------------------------------------------------------


class TestGoldenGate:
    @pytest.fixture(scope="class")
    def live(self, small_programs, small_traces):
        return {n: fingerprint(p, traced=small_traces[n])
                for n, p in small_programs.items()}

    def test_small_registry_diff_clean(self, live):
        # The committed snapshot matches the live registry — the gate
        # that replaced test_jaxlint's hand-pinned eqn counts.
        findings = diff_golden(live, subset=True)
        assert not findings, "\n".join(f.format() for f in findings)

    def test_golden_covers_both_tiers(self):
        golden = load_golden()["programs"]
        assert any("@small" in n for n in golden)
        assert any(n.endswith("@1m") for n in golden)

    def test_drift_fires_e2_with_detail(self, live):
        import dataclasses

        name = "broadcast@small"
        gold = load_golden()
        mutated = dict(live)
        mutated[name] = dataclasses.replace(
            live[name], hash="0" * 64, eqns=live[name].eqns + 50,
        )
        rules = {f.rule for f in diff_golden(mutated, gold, subset=True)
                 if f.program == name}
        assert rules == {"E2"}
        [f] = [f for f in diff_golden(mutated, gold, subset=True)
               if f.program == name]
        assert "eqns" in f.message  # says WHAT moved, not just that

    def test_coverage_holes_fire_e3_both_directions(self, live):
        gold = load_golden()
        pruned = {
            "meta": gold["meta"],
            "programs": {k: v for k, v in gold["programs"].items()
                         if k != "broadcast@small"},
        }
        live_extra = dict(live)
        live_extra["newcomer@small"] = live["broadcast@small"]
        findings = diff_golden(live_extra, pruned, subset=False)
        holes = {f.program for f in findings if f.rule == "E3"}
        assert "broadcast@small" in holes  # live without golden
        # golden-without-live (the full small+big golden vs the small
        # slice) is suppressed under subset=True only:
        assert not [f for f in diff_golden(live, pruned, subset=True)
                    if f.program not in live]

    def test_write_golden_round_trip_and_merge(self, live, tmp_path):
        path = tmp_path / "programs.json"
        first = {"broadcast@small": live["broadcast@small"]}
        write_golden(first, path=str(path))
        second = {"membership@small": live["membership@small"]}
        write_golden(second, path=str(path))  # merge keeps broadcast
        snap = load_golden(str(path))
        assert set(snap["programs"]) == {"broadcast@small",
                                         "membership@small"}
        assert not diff_golden(
            {k: live[k] for k in snap["programs"]}, snap, subset=True
        )

    def test_eqn_counts_ride_the_golden(self, live):
        # The successor of test_jaxlint's PINS table: the exact eqn
        # counts now live in the committed snapshot, compared with
        # equality (not +-20%) because the hash pins the whole jaxpr.
        golden = load_golden()["programs"]
        for name in ("broadcast@small", "membership@small",
                     "sparse@small"):
            assert live[name].eqns == golden[name]["eqns"]


# ---------------------------------------------------------------------------
# P1-P3: Pallas DMA discipline — planted fixtures fire, the real ring
# kernel is silent.
# ---------------------------------------------------------------------------


class TestPallasRules:
    @pytest.fixture(scope="class")
    def fixture_findings(self):
        import equivlint_fixtures as fx

        out = {}
        for name, (fn, args) in fx.EQUIVLINT_PROGRAMS.items():
            out[name] = pallas_findings(name, jax.make_jaxpr(fn)(*args))
        return out

    def _rules(self, findings):
        return [f.rule for f in findings]

    def test_clean_fixtures_silent(self, fixture_findings):
        assert fixture_findings["clean_local"] == []
        assert fixture_findings["p2_clean_double_buffer"] == []

    def test_p1_missing_wait(self, fixture_findings):
        [f] = fixture_findings["p1_missing_wait"]
        assert f.rule == "P1"
        assert "equivlint_fixtures.py" in f.where

    def test_p1_wait_without_start(self, fixture_findings):
        [f] = fixture_findings["p1_wait_without_start"]
        assert f.rule == "P1"

    def test_p2_slot_reuse(self, fixture_findings):
        # The h%2 double-buffer race: the planted P2 plus the
        # consequent P1 (the clobbered first start is never waited).
        rules = self._rules(fixture_findings["p2_slot_reuse"])
        assert "P2" in rules
        [p2] = [f for f in fixture_findings["p2_slot_reuse"]
                if f.rule == "P2"]
        assert "equivlint_fixtures.py" in p2.where
        assert "slot" in p2.message

    def test_p2_touch_dst(self, fixture_findings):
        [f] = fixture_findings["p2_touch_dst"]
        assert f.rule == "P2"
        assert "destination" in f.message

    def test_p3_barrier_fixtures(self, fixture_findings):
        [a] = fixture_findings["p3_barrier_under_interpret"]
        [b] = fixture_findings["p3_barrier_no_collective_id"]
        assert a.rule == b.rule == "P3"
        assert "interpret" in a.message
        assert "collective_id" in b.message

    def test_ring_registry_programs_clean(self, small_traces):
        # The production kernel (start(h+1)-before-wait(h) double
        # buffering, barrier behind the interpret seam): every sharded
        # /ring registry entry must pass P1-P3 silent.
        ring = {n: t for n, t in small_traces.items() if "/ring" in n}
        assert ring, "registry lost its ring-backend entries"
        for name, traced in ring.items():
            assert pallas_findings(name, traced) == [], name


# ---------------------------------------------------------------------------
# --changed: git-diff-aware program selection.
# ---------------------------------------------------------------------------


class TestChangedSelection:
    NAMES = ("broadcast@small", "sharded_broadcast@small/ring",
             "sweep_swim@small/U8", "sparse@big", "streamcast@small",
             "geo@small", "lifeguard@small")

    def _progs(self):
        return {n: None for n in self.NAMES}

    def test_family_edit_selects_family_twins(self):
        keys = changed_program_keys(
            self._progs(), ["consul_tpu/models/broadcast.py"]
        )
        assert keys == {"broadcast@small",
                        "sharded_broadcast@small/ring"}

    def test_membership_edit_selects_sparse_too(self):
        keys = changed_program_keys(
            self._progs(), ["consul_tpu/models/membership.py"]
        )
        assert "sparse@big" in keys

    def test_core_edit_selects_everything(self):
        for core in ("consul_tpu/sim/engine.py",
                     "consul_tpu/ops/ring_exchange.py",
                     "consul_tpu/parallel/shard.py"):
            assert changed_program_keys(
                self._progs(), [core]
            ) == set(self.NAMES), core

    def test_unrelated_edit_selects_nothing(self):
        assert changed_program_keys(
            self._progs(), ["README.md", "tests/test_equivlint.py"]
        ) == set()

    def test_git_changed_files_runs(self):
        assert isinstance(git_changed_files(), list)


# ---------------------------------------------------------------------------
# CLI contract (mirrors cli jaxlint: nonzero on findings, --format json
# for CI, planted fixtures through --module).
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, argv):
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(argv)
        return asyncio.run(args.fn(args))

    def test_list_rules(self, capsys):
        assert self._run(["equivlint", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in EQUIV_RULES:
            assert rule in out

    def test_planted_fixtures_exit_nonzero(self, capsys):
        import equivlint_fixtures as fx

        assert self._run(["equivlint", "--module", fx.__file__]) == 1
        out = capsys.readouterr().out
        for rule in ("P1", "P2", "P3"):
            assert rule in out
        assert "equivlint_fixtures.py" in out

    def test_planted_fixtures_json(self, capsys):
        import equivlint_fixtures as fx

        assert self._run(["equivlint", "--module", fx.__file__,
                          "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert {f["rule"] for f in payload} == {"P1", "P2", "P3"}

    def test_check_parser_accepts_changed_flags(self):
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["check", "--changed", "--no-witness"]
        )
        assert args.changed and args.no_witness

    @pytest.mark.slow
    def test_small_set_structural_clean(self, capsys):
        # --no-witness: structural proofs + golden gate only.  The
        # witnessed ladder is tier-1's TestPairGate; this is the CLI
        # exit-code contract over the same registry.
        assert self._run(["equivlint", "--set", "small",
                          "--no-witness"]) == 0
