"""Multi-segment (multi-DC) broadcast: two edge classes, as the
reference structures them — per-segment LAN serf pools bridged by a
server-only WAN pool (server.go:506,534; flood.go:27-60;
memberlist/config.go:315-326 WAN timing).
"""

import jax
import numpy as np

from consul_tpu.models.multidc import (
    MultiDCConfig,
    multidc_init,
    multidc_round,
)
from consul_tpu.parallel import make_mesh, shard_state
from consul_tpu.sim.engine import multidc_scan, run_multidc
import pytest


def test_wan_disabled_confines_event_to_origin_segment():
    """The defining property of the two-edge-class structure: without
    the WAN pool, segments are isolated gossip universes."""
    cfg = MultiDCConfig(n=4096, segments=8, wan_enabled=False)
    rep = run_multidc(cfg, steps=40, seed=0, origin=100, warmup=False)
    assert rep.segments_reached() == 1
    # ...but the origin segment itself fully converges.
    assert rep.per_segment[-1][0] == cfg.seg_size


def test_event_crosses_all_segments_via_wan():
    cfg = MultiDCConfig(n=4096, segments=8, bridges_per_segment=3)
    # Origin is a NON-bridge member: the event must reach segment 0's
    # servers by LAN, cross on the WAN class, and re-enter the other
    # segments through their servers.
    rep = run_multidc(cfg, steps=80, seed=1, origin=50, warmup=False)
    assert rep.segments_reached() == 8
    assert rep.infected[-1] == cfg.n


def test_wan_hop_adds_latency():
    """Remote segments converge later than the origin segment — the WAN
    cadence (500 ms vs 200 ms) and the extra hops are visible in the
    per-segment curves."""
    cfg = MultiDCConfig(n=8192, segments=8, bridges_per_segment=3)
    rep = run_multidc(cfg, steps=100, seed=2, origin=10, warmup=False)
    t_origin = rep.segment_t99_ms(0)
    remote = [rep.segment_t99_ms(s) for s in range(1, 8)]
    assert t_origin is not None and all(t is not None for t in remote)
    assert min(remote) > t_origin


@pytest.mark.slow  # ~26s at CPU: comparative loss sweeps
def test_wan_loss_slows_cross_segment_convergence():
    base = MultiDCConfig(n=4096, segments=8, bridges_per_segment=3)
    lossy = MultiDCConfig(
        n=4096, segments=8, bridges_per_segment=3, loss_wan=0.5
    )
    r0 = run_multidc(base, steps=100, seed=3, origin=20, warmup=False)
    r1 = run_multidc(lossy, steps=100, seed=3, origin=20, warmup=False)
    assert r1.time_to_ms(0.99) >= r0.time_to_ms(0.99)


@pytest.mark.slow  # ~28s at CPU: multi-seed distribution bands
def test_aggregate_matches_edges_distributionally():
    """Same convergence curve from the exact scatter path and the
    Poissonized path, averaged over seeds (the multidc analogue of
    tests/test_aggregate.py)."""
    t99 = {}
    for delivery in ("edges", "aggregate"):
        cfg = MultiDCConfig(
            n=4096, segments=8, bridges_per_segment=3, delivery=delivery
        )
        ts = []
        for seed in range(4):
            rep = run_multidc(cfg, steps=80, seed=seed, origin=9,
                              warmup=False)
            # A lone straggler after budget exhaustion is legitimate
            # (real gossip leaves it to push/pull; this model has none).
            assert rep.infected[-1] >= 0.999 * cfg.n
            ts.append(np.argmax(rep.infected >= 0.99 * cfg.n))
        t99[delivery] = np.mean(ts)
    assert abs(t99["edges"] - t99["aggregate"]) <= 3.0, t99


def test_sharded_equals_unsharded():
    """One segment per device: the sharded program computes the exact
    same trajectory as the single-device one (determinism across
    shardings, SURVEY.md §5 race-discipline)."""
    cfg = MultiDCConfig(n=2048, segments=8, bridges_per_segment=3)
    key = jax.random.PRNGKey(7)
    st = multidc_init(cfg, origin=33)
    _, (plain_total, plain_seg) = multidc_scan(st, key, cfg, 40)
    mesh = make_mesh()
    st_sh = shard_state(multidc_init(cfg, origin=33), mesh)
    _, (sh_total, sh_seg) = multidc_scan(st_sh, key, cfg, 40)
    np.testing.assert_array_equal(np.asarray(plain_total), np.asarray(sh_total))
    np.testing.assert_array_equal(np.asarray(plain_seg), np.asarray(sh_seg))


def test_bridge_budget_scales_with_wan_pool():
    cfg = MultiDCConfig(n=4096, segments=8, bridges_per_segment=3)
    # LAN budget scales with segment size, WAN with the bridge count —
    # two different pools, two different retransmit scales
    # (memberlist/util.go:72-76 applied per pool).
    assert cfg.tx_limit_lan != cfg.tx_limit_wan or cfg.seg_size == cfg.n_bridges
    st = multidc_init(cfg, origin=0)  # origin 0 IS a bridge
    assert int(st.tx_wan[0]) == cfg.tx_limit_wan
    assert int(st.tx_lan[0]) == cfg.tx_limit_lan
    st2 = multidc_init(cfg, origin=10)  # non-bridge: no WAN budget
    assert int(st2.tx_wan[10]) == 0
