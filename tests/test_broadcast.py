"""Event-broadcast model tests: infection dynamics, dedup, retransmit
budgets, loss tolerance.  Small-N studies run exact; convergence targets
follow the epidemic O(log N) expectation (SWIM paper / serf docs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.models import (
    BroadcastConfig,
    broadcast_init,
    broadcast_round,
)
from consul_tpu.sim import run_broadcast, time_to_fraction


def test_init_only_origin_knows():
    cfg = BroadcastConfig(n=64)
    st = broadcast_init(cfg, origin=7)
    assert int(jnp.sum(st.knows)) == 1
    assert bool(st.knows[7])
    assert int(st.tx_left[7]) == cfg.tx_limit
    assert int(st.tx_left[0]) == 0


def test_infection_is_monotone_and_total():
    cfg = BroadcastConfig(n=128, fanout=3, loss=0.0)
    st = broadcast_init(cfg)
    key = jax.random.PRNGKey(0)
    prev = 1
    for i in range(40):
        st = broadcast_round(st, jax.random.fold_in(key, i), cfg)
        cur = int(jnp.sum(st.knows))
        assert cur >= prev, "infection can never regress (dedup ring keeps events)"
        prev = cur
    assert prev == 128, "lossless broadcast must reach everyone"


def test_convergence_is_log_n_rounds():
    # Epidemic broadcast with fanout 3 should reach 99% of 1k nodes in
    # O(log N) rounds — well under 20 ticks (4s simulated LAN time);
    # cf. serf's 'leave propagates to 99.99% of 100k in 3s' basis
    # (lib/serf/serf.go:26-30).
    report = run_broadcast(BroadcastConfig(n=1000, fanout=3), steps=40, seed=1)
    t99 = time_to_fraction(report.infected, 1000, 0.99)
    assert t99 is not None and t99 < 20


def test_tx_budget_depletes_and_gossip_stops():
    cfg = BroadcastConfig(n=16, fanout=3)
    st = broadcast_init(cfg)
    key = jax.random.PRNGKey(2)
    for i in range(200):
        st = broadcast_round(st, jax.random.fold_in(key, i), cfg)
    assert int(jnp.max(st.tx_left)) == 0, "all budgets spent after enough ticks"


def test_total_loss_never_spreads():
    cfg = BroadcastConfig(n=64, loss=1.0)
    st = broadcast_init(cfg)
    key = jax.random.PRNGKey(3)
    for i in range(20):
        st = broadcast_round(st, jax.random.fold_in(key, i), cfg)
    assert int(jnp.sum(st.knows)) == 1


def test_heavy_loss_still_converges():
    # 30% loss (the BASELINE WAN config) must still infect everyone,
    # just slower — epidemic broadcast is loss-tolerant by design.
    r_lossy = run_broadcast(
        BroadcastConfig(n=500, fanout=3, loss=0.30), steps=60, seed=4
    )
    r_clean = run_broadcast(
        BroadcastConfig(n=500, fanout=3, loss=0.0), steps=60, seed=4
    )
    t99_lossy = time_to_fraction(r_lossy.infected, 500, 0.99)
    t99_clean = time_to_fraction(r_clean.infected, 500, 0.99)
    assert t99_lossy is not None
    assert t99_lossy >= t99_clean


def test_dead_nodes_do_not_relay():
    cfg = BroadcastConfig(n=64, fanout=3)
    alive = jnp.ones((64,), jnp.bool_).at[10:40].set(False)
    st = broadcast_init(cfg, origin=0)
    key = jax.random.PRNGKey(5)
    for i in range(40):
        st = broadcast_round(st, jax.random.fold_in(key, i), cfg, alive=alive)
    knows = np.asarray(st.knows)
    assert not knows[10:40].any(), "deaf/dead nodes never learn the event"
    assert knows[np.r_[0:10, 40:64]].all(), "live nodes all converge"


def test_determinism_same_key_same_curve():
    cfg = BroadcastConfig(n=256, fanout=3, loss=0.1)
    r1 = run_broadcast(cfg, steps=30, seed=7)
    r2 = run_broadcast(cfg, steps=30, seed=7)
    assert np.array_equal(r1.infected, r2.infected)


def test_retransmit_budget_matches_formula():
    # 4 * ceil(log10(n+1)): n=1000 -> 16.
    assert BroadcastConfig(n=1000).tx_limit == 16
    assert BroadcastConfig(n=100_000).tx_limit == 24
