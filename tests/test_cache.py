"""Agent cache: single-flight fetch, background blocking refresh, TTL
eviction, Notify watchers, and DNS served from cache with a measured
hit rate (agent/cache/cache_test.go + cache-types behavior)."""

import asyncio

import pytest

from helpers import wait_for as wait_until

from consul_tpu.agent.cache import (
    HEALTH_SERVICES,
    AgentCache,
    CacheType,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


class FakeRPC:
    """Counts calls; blocking-query aware (returns on index change)."""

    def __init__(self):
        self.calls = 0
        self.index = 1
        self.value = ["a"]
        self._changed = asyncio.Event()

    def set(self, value):
        self.index += 1
        self.value = value
        self._changed.set()

    async def __call__(self, method, body):
        self.calls += 1
        await asyncio.sleep(0.02)  # a real RPC suspends the caller
        min_idx = int(body.get("min_query_index", 0) or 0)
        if min_idx >= self.index:
            self._changed.clear()
            try:
                await asyncio.wait_for(
                    self._changed.wait(), body.get("max_query_time", 1.0)
                )
            except asyncio.TimeoutError:
                pass
        return {"nodes": list(self.value), "meta": {"index": self.index}}


TYPES = {
    "t": CacheType("t", "Fake.Method", refresh=True, ttl=2.0,
                   key_fields=("service",)),
    "nt": CacheType("nt", "Fake.Method", refresh=False, ttl=0.3,
                    key_fields=("service",)),
}


def test_hit_miss_and_single_flight():
    async def main():
        rpc = FakeRPC()
        cache = AgentCache(rpc, types=TYPES)
        # Concurrent first Gets share one fetch (single-flight).
        out = await asyncio.gather(
            *[cache.get("t", {"service": "web"}) for _ in range(5)]
        )
        assert all(o["nodes"] == ["a"] for o in out)
        # One foreground fetch for 5 concurrent Gets; the background
        # refresh loop may have issued its own (blocking) call.
        assert rpc.calls <= 2
        assert cache.misses == 5 and cache.hits == 0
        # Warm read is a hit, no RPC.
        calls_before = rpc.calls
        out2 = await cache.get("t", {"service": "web"})
        assert out2["nodes"] == ["a"]
        assert cache.hits == 1
        # (the background refresh loop may have issued its own RPC;
        # the *foreground* path must not)
        assert rpc.calls - calls_before <= 1
        cache.stop()

    run(main())


def test_background_refresh_updates_entry_and_notifies():
    async def main():
        rpc = FakeRPC()
        cache = AgentCache(rpc, types=TYPES, refresh_timeout=5.0)
        await cache.get("t", {"service": "web"})
        q: asyncio.Queue = asyncio.Queue()
        cache.notify("t", {"service": "web"}, q)
        rpc.set(["a", "b"])
        # The refresh loop's blocking query returns with the new value;
        # the watcher hears about it without any foreground get().
        update = await asyncio.wait_for(q.get(), 5)
        assert update["nodes"] == ["a", "b"]
        # And the cached value itself is fresh (still a hit).
        out = await cache.get("t", {"service": "web"})
        assert out["nodes"] == ["a", "b"]
        assert cache.hits >= 1
        cache.stop()

    run(main())


def test_ttl_eviction_stops_refresh():
    async def main():
        rpc = FakeRPC()
        types = {"t": CacheType("t", "Fake.Method", refresh=True, ttl=0.2,
                                key_fields=("service",))}
        cache = AgentCache(rpc, types=types, refresh_timeout=0.05)
        await cache.get("t", {"service": "web"})
        entry = next(iter(cache._entries.values()))
        await wait_until(
            lambda: not cache._entries, timeout=5,
            msg="entry evicted after ttl disuse",
        )
        await wait_until(
            lambda: entry.refresh_task is None or entry.refresh_task.done(),
            timeout=5, msg="refresh loop stopped",
        )
        cache.stop()

    run(main())


def test_errors_surface_but_do_not_poison():
    async def main():
        calls = {"n": 0}

        async def rpc(method, body):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return {"nodes": [], "meta": {"index": 1}}

        cache = AgentCache(rpc, types=TYPES)
        with pytest.raises(RuntimeError):
            await cache.get("nt", {"service": "web"})
        out = await cache.get("nt", {"service": "web"})
        assert out["nodes"] == []
        cache.stop()

    run(main())


def test_dns_served_from_cache_with_hit_rate():
    """VERDICT r1 acceptance: DNS answers served from cache with a
    measured hit rate, and background refresh keeps them current."""

    async def main():
        from test_http_dns import dev_stack, dns_query
        from consul_tpu.agent.dns import TYPE_A

        async with dev_stack() as (agent, _addr, _dns, dns_addr):
            agent.add_service({"id": "web1", "service": "web", "port": 80,
                               "address": "10.1.1.1"})
            await wait_until(
                lambda: agent.delegate.store.check_service_nodes("web")[1],
                msg="service synced to catalog",
            )
            _txid, _flags, answers = await dns_query(
                dns_addr, "web.service.consul", TYPE_A
            )
            assert answers, "first DNS answer"
            misses = agent.cache.misses
            for _ in range(9):
                _t, _f, answers = await dns_query(
                    dns_addr, "web.service.consul", TYPE_A
                )
                assert answers
            # The 9 follow-ups were all cache hits.
            assert agent.cache.misses == misses
            assert agent.cache.hits >= 9
            assert agent.cache.hit_rate >= 0.8

            # Background refresh: register a second instance; the cache
            # updates via its blocking query, and DNS starts answering
            # with two records WITHOUT any cache invalidation call.
            agent.add_service({"id": "web2", "service": "web", "port": 81,
                               "address": "10.1.1.2"})
            await wait_until(
                lambda: len(
                    agent.delegate.store.check_service_nodes("web")[1]
                ) == 2,
                msg="second instance in catalog",
            )

            async def two_answers():
                _t, _f, ans = await dns_query(
                    dns_addr, "web.service.consul", TYPE_A
                )
                return len(ans) >= 2

            await wait_until(two_answers, timeout=15,
                             msg="DNS reflects refreshed cache")

    run(main())
