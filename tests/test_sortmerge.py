"""ops/sortmerge.py: the sort-merge delivery kernel, pinned to a
brute-force numpy reference.

The kernel's contract (module docstring) over randomized arrival
streams: duplicate (receiver, subject) groups collapse to one
representative carrying the max value / max suspicion / any-may-
allocate, seated subjects merge in place, unseated allocation-worthy
subjects claim distinct slots in rank order (empties first, then
evictable cells), and every drop or remembered-cell eviction is
counted — never silent.  The reference below re-derives all of that
with dicts and loops; the property tests sweep duplicates, value
ties, eviction pressure, and overflow accounting.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from consul_tpu.ops.sortmerge import (
    merge_deliveries,
    row_locate,
    sort_slot_rows,
)


def make_rows(rng, n, K, fill):
    """Rows holding the sorted-row invariant: per row, ``fill`` distinct
    subjects ascending, empties (-1) last."""
    slot_subj = np.full((n, K), -1, np.int32)
    for i in range(n):
        k = int(rng.integers(1, fill + 1))
        subs = np.sort(rng.choice(n, size=min(k, n), replace=False))
        slot_subj[i, : len(subs)] = subs
    return slot_subj


def ref_merge(slot_subj, evictable, remembers, arrivals, default_val):
    """Brute-force reference of merge_deliveries (dicts + loops)."""
    n, K = slot_subj.shape
    groups = {}
    for r, s, v, su, ok, al in arrivals:
        if not ok:
            continue
        g = groups.setdefault((r, s), [-1, -1, False])
        g[0] = max(g[0], v)
        g[1] = max(g[1], su)
        g[2] = g[2] or (al and v > default_val)

    new_subj = slot_subj.copy()
    claimed = np.zeros((n, K), bool)
    key_rx = np.full((n, K), -1, np.int32)
    sus_rx = np.full((n, K), -1, np.int32)
    dropped = forgot = 0
    for r in range(n):
        seated = set(slot_subj[r][slot_subj[r] >= 0].tolist())
        cls = np.where(
            slot_subj[r] < 0, 0, np.where(evictable[r], 1, 2)
        )
        order = np.argsort(cls * K + np.arange(K), kind="stable")
        n_claim = int((cls < 2).sum())
        # Unseated allocation-worthy subjects rank in ascending subject
        # order (the lex-sorted stream order) and claim that rank's
        # entry in the row's claim order.
        newsub = sorted(
            s for (rr, s), (_, _, el) in groups.items()
            if rr == r and el and s not in seated
        )
        chosen = {}
        for rank, s in enumerate(newsub):
            if rank < n_claim:
                c = int(order[rank])
                chosen[s] = c
                claimed[r, c] = True
                new_subj[r, c] = s
                if remembers[r, c]:
                    forgot += 1
            else:
                dropped += 1
        for (rr, s), (vmax, sumax, el) in groups.items():
            if rr != r:
                continue
            if s in seated:
                p = int(np.where(slot_subj[r] == s)[0][0])
                if claimed[r, p]:
                    # The group's cell was evicted this tick: its news
                    # drops, counted when it could have allocated.
                    dropped += el
                    continue
                key_rx[r, p] = vmax
                sus_rx[r, p] = sumax
            elif s in chosen:
                p = chosen[s]
                key_rx[r, p] = vmax
                sus_rx[r, p] = sumax
            # else: absent and not allocation-worthy — silent drop.
    return new_subj, claimed, key_rx, sus_rx, dropped, forgot


def random_stream(rng, n, A, val_hi=12):
    recv = rng.integers(0, n, A).astype(np.int32)
    subj = rng.integers(0, n, A).astype(np.int32)
    # Small val range forces ties; 0 == default exercises the
    # not-allocation-worthy class.
    val = rng.integers(0, val_hi, A).astype(np.int32)
    sus = rng.integers(-1, 6, A).astype(np.int32)
    ok = rng.random(A) < 0.75
    alloc = rng.random(A) < 0.6
    return recv, subj, val, sus, ok, alloc


def run_both(slot_subj, evictable, remembers, stream, allocate=True):
    recv, subj, val, sus, ok, alloc = stream
    got = merge_deliveries(
        jnp.asarray(slot_subj), jnp.asarray(recv), jnp.asarray(subj),
        jnp.asarray(val), jnp.asarray(sus), jnp.asarray(ok),
        jnp.asarray(alloc),
        evictable=jnp.asarray(evictable),
        remembers=jnp.asarray(remembers),
        default_val=0, allocate=allocate,
    )
    want = ref_merge(
        slot_subj, evictable, remembers,
        list(zip(recv, subj, val, sus, ok, alloc)), 0,
    )
    return got, want


class TestMergeDeliveries:
    @pytest.mark.parametrize("seed", range(8))
    def test_property_random_streams(self, seed):
        """Randomized duplicates/ties/partial tables vs the reference."""
        rng = np.random.default_rng(seed)
        n = int(rng.integers(2, 12))
        K = int(rng.integers(2, 7))
        A = int(rng.integers(1, 120))
        slot_subj = make_rows(rng, n, K, fill=K)
        evictable = rng.random((n, K)) < 0.5
        remembers = (rng.random((n, K)) < 0.5) & (slot_subj >= 0)
        got, want = run_both(
            slot_subj, evictable, remembers, random_stream(rng, n, A)
        )
        for g, w, name in zip(
            got, want,
            ("slot_subj", "claimed", "key_rx", "sus_rx", "dropped",
             "forgot"),
        ):
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(w), err_msg=name
            )

    def test_eviction_pressure_and_overflow_accounting(self):
        """Full rows, few claimable slots, heavy churn: every lost
        group must land in dropped, every remembered eviction in
        forgot."""
        rng = np.random.default_rng(99)
        n, K, A = 6, 3, 200
        slot_subj = make_rows(rng, n, K, fill=K)
        # Full rows with ~one evictable slot each.
        evictable = rng.random((n, K)) < 0.3
        remembers = (slot_subj >= 0) & (rng.random((n, K)) < 0.8)
        stream = random_stream(rng, n, A, val_hi=30)
        got, want = run_both(slot_subj, evictable, remembers, stream)
        assert int(got[4]) == want[4] and want[4] > 0, "overflow pins"
        assert int(got[5]) == want[5], "forgotten pins"

    def test_full_table_reduces_to_scatter_max(self):
        """allocate=False over a full table is exactly the per-arrival
        scatter-max the kernel replaces (the K == n parity mode)."""
        rng = np.random.default_rng(3)
        n = 9
        ident = np.broadcast_to(
            np.arange(n, dtype=np.int32)[None, :], (n, n)
        ).copy()
        stream = random_stream(rng, n, 150)
        got, want = run_both(
            ident, np.zeros((n, n), bool), np.zeros((n, n), bool),
            stream, allocate=False,
        )
        recv, subj, val, sus, ok, _ = stream
        ref_key = np.full((n, n), -1, np.int32)
        ref_sus = np.full((n, n), -1, np.int32)
        for i in range(len(recv)):
            if ok[i]:
                r, s = recv[i], subj[i]
                ref_key[r, s] = max(ref_key[r, s], val[i])
                ref_sus[r, s] = max(ref_sus[r, s], sus[i])
        np.testing.assert_array_equal(np.asarray(got[2]), ref_key)
        np.testing.assert_array_equal(np.asarray(got[3]), ref_sus)
        assert not np.asarray(got[1]).any(), "nothing claimed"

    def test_duplicate_groups_collapse_to_one_claim(self):
        """Many duplicate arrivals for one unseated subject must claim
        exactly one slot (the stage-hash collision class is gone)."""
        n, K = 4, 3
        slot_subj = np.full((n, K), -1, np.int32)
        slot_subj[:, 0] = np.arange(n)
        A = 12
        stream = (
            np.full(A, 2, np.int32),        # all to receiver 2
            np.full(A, 0, np.int32),        # all about subject 0
            np.arange(1, A + 1, dtype=np.int32),
            np.full(A, -1, np.int32),
            np.ones(A, bool),
            np.ones(A, bool),
        )
        got, want = run_both(
            slot_subj, np.zeros((n, K), bool), np.zeros((n, K), bool),
            stream,
        )
        assert int(np.asarray(got[1]).sum()) == 1
        assert int(np.asarray(got[2]).max()) == A  # max value won
        assert int(got[4]) == 0 and int(got[5]) == 0


class TestRowPrimitives:
    def test_row_locate_matches_linear_scan(self):
        rng = np.random.default_rng(1)
        for K in (1, 2, 3, 5, 8, 48, 64):
            n = 7
            slot_subj = make_rows(rng, n, K, fill=min(K, n))
            recv = rng.integers(0, n, 64).astype(np.int32)
            subj = rng.integers(0, n, 64).astype(np.int32)
            got = np.asarray(
                row_locate(jnp.asarray(slot_subj), jnp.asarray(recv),
                           jnp.asarray(subj))
            )
            for i in range(64):
                pos = np.where(slot_subj[recv[i]] == subj[i])[0]
                assert got[i] == (pos[0] if len(pos) else -1)

    def test_sort_slot_rows_restores_invariant(self):
        rng = np.random.default_rng(2)
        n, K = 5, 6
        slot_subj = make_rows(rng, n, K, fill=K)
        plane = rng.integers(0, 100, (n, K)).astype(np.int32)
        # Empty slots hold default contents as a model invariant, so
        # their relative order is unobservable; pin them to one value.
        plane[slot_subj < 0] = 0
        # Scramble the columns, then sort back.
        perm = rng.permutation(K)
        ss, pl = sort_slot_rows(
            jnp.asarray(slot_subj[:, perm]), jnp.asarray(plane[:, perm])
        )
        np.testing.assert_array_equal(np.asarray(ss), slot_subj)
        np.testing.assert_array_equal(np.asarray(pl), plane)


class TestScanChunksPadding:
    """The bool-padding footgun: jnp.full((pad,), -1, bool) is True, so
    chunk padding used to VALIDATE synthetic arrivals whenever the
    stream length wasn't a chunk multiple."""

    def test_bool_arrays_pad_false(self):
        from consul_tpu.models.membership_sparse import _scan_chunks

        total = _scan_chunks(
            lambda c, ok: c + jnp.sum(ok.astype(jnp.int32)),
            jnp.int32(0),
            (jnp.ones((5,), bool),),   # 5 % 4 != 0 → 3 padding slots
            4,
        )
        assert int(total) == 5

    def test_int_arrays_still_pad_invalid(self):
        from consul_tpu.models.membership_sparse import _scan_chunks

        seen = _scan_chunks(
            lambda c, r: c + jnp.sum((r >= 0).astype(jnp.int32)),
            jnp.int32(0),
            (jnp.arange(5, dtype=jnp.int32),),
            4,
        )
        assert int(seen) == 5
