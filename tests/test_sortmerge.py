"""ops/sortmerge.py: the sort-merge delivery kernel, pinned to a
brute-force numpy reference.

The kernel's contract (module docstring) over randomized arrival
streams: duplicate (receiver, subject) groups collapse to one
representative carrying the max value / max suspicion / any-may-
allocate, seated subjects merge in place, unseated allocation-worthy
subjects claim distinct slots in rank order (empties first, then
evictable cells), and every drop or remembered-cell eviction is
counted — never silent.  The reference below re-derives all of that
with dicts and loops; the property tests sweep duplicates, value
ties, eviction pressure, and overflow accounting.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import consul_tpu.ops.sortmerge as sortmerge
from consul_tpu.ops.sortmerge import (
    insert_rows_one,
    merge_deliveries,
    merge_into_rows,
    row_locate,
    row_locate_lo,
    sort_slot_rows,
)


def make_rows(rng, n, K, fill):
    """Rows holding the sorted-row invariant: per row, ``fill`` distinct
    subjects ascending, empties (-1) last."""
    slot_subj = np.full((n, K), -1, np.int32)
    for i in range(n):
        k = int(rng.integers(1, fill + 1))
        subs = np.sort(rng.choice(n, size=min(k, n), replace=False))
        slot_subj[i, : len(subs)] = subs
    return slot_subj


def ref_merge(slot_subj, evictable, remembers, arrivals, default_val):
    """Brute-force reference of merge_deliveries (dicts + loops)."""
    n, K = slot_subj.shape
    groups = {}
    for r, s, v, su, ok, al in arrivals:
        if not ok:
            continue
        g = groups.setdefault((r, s), [-1, -1, False])
        g[0] = max(g[0], v)
        g[1] = max(g[1], su)
        g[2] = g[2] or (al and v > default_val)

    new_subj = slot_subj.copy()
    claimed = np.zeros((n, K), bool)
    key_rx = np.full((n, K), -1, np.int32)
    sus_rx = np.full((n, K), -1, np.int32)
    dropped = forgot = 0
    for r in range(n):
        seated = set(slot_subj[r][slot_subj[r] >= 0].tolist())
        cls = np.where(
            slot_subj[r] < 0, 0, np.where(evictable[r], 1, 2)
        )
        order = np.argsort(cls * K + np.arange(K), kind="stable")
        n_claim = int((cls < 2).sum())
        # Unseated allocation-worthy subjects rank in ascending subject
        # order (the lex-sorted stream order) and claim that rank's
        # entry in the row's claim order.
        newsub = sorted(
            s for (rr, s), (_, _, el) in groups.items()
            if rr == r and el and s not in seated
        )
        chosen = {}
        for rank, s in enumerate(newsub):
            if rank < n_claim:
                c = int(order[rank])
                chosen[s] = c
                claimed[r, c] = True
                new_subj[r, c] = s
                if remembers[r, c]:
                    forgot += 1
            else:
                dropped += 1
        for (rr, s), (vmax, sumax, el) in groups.items():
            if rr != r:
                continue
            if s in seated:
                p = int(np.where(slot_subj[r] == s)[0][0])
                if claimed[r, p]:
                    # The group's cell was evicted this tick: its news
                    # drops, counted when it could have allocated.
                    dropped += el
                    continue
                key_rx[r, p] = vmax
                sus_rx[r, p] = sumax
            elif s in chosen:
                p = chosen[s]
                key_rx[r, p] = vmax
                sus_rx[r, p] = sumax
            # else: absent and not allocation-worthy — silent drop.
    return new_subj, claimed, key_rx, sus_rx, dropped, forgot


def random_stream(rng, n, A, val_hi=12):
    recv = rng.integers(0, n, A).astype(np.int32)
    subj = rng.integers(0, n, A).astype(np.int32)
    # Small val range forces ties; 0 == default exercises the
    # not-allocation-worthy class.
    val = rng.integers(0, val_hi, A).astype(np.int32)
    sus = rng.integers(-1, 6, A).astype(np.int32)
    ok = rng.random(A) < 0.75
    alloc = rng.random(A) < 0.6
    return recv, subj, val, sus, ok, alloc


def run_both(slot_subj, evictable, remembers, stream, allocate=True):
    recv, subj, val, sus, ok, alloc = stream
    got = merge_deliveries(
        jnp.asarray(slot_subj), jnp.asarray(recv), jnp.asarray(subj),
        jnp.asarray(val), jnp.asarray(sus), jnp.asarray(ok),
        jnp.asarray(alloc),
        evictable=jnp.asarray(evictable),
        remembers=jnp.asarray(remembers),
        default_val=0, allocate=allocate,
    )
    want = ref_merge(
        slot_subj, evictable, remembers,
        list(zip(recv, subj, val, sus, ok, alloc)), 0,
    )
    return got, want


class TestMergeDeliveries:
    # 4 seeds in tier-1; the kernel is now the REFERENCE path (the
    # product path pins bit-equal to it below), and the slow-tier
    # extended sweep widens both.
    @pytest.mark.parametrize(
        "seed",
        list(range(4)) + [pytest.param(s, marks=pytest.mark.slow)
                          for s in range(4, 8)],
    )
    def test_property_random_streams(self, seed):
        """Randomized duplicates/ties/partial tables vs the reference."""
        rng = np.random.default_rng(seed)
        n = int(rng.integers(2, 12))
        K = int(rng.integers(2, 7))
        A = int(rng.integers(1, 120))
        slot_subj = make_rows(rng, n, K, fill=K)
        evictable = rng.random((n, K)) < 0.5
        remembers = (rng.random((n, K)) < 0.5) & (slot_subj >= 0)
        got, want = run_both(
            slot_subj, evictable, remembers, random_stream(rng, n, A)
        )
        for g, w, name in zip(
            got, want,
            ("slot_subj", "claimed", "key_rx", "sus_rx", "dropped",
             "forgot"),
        ):
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(w), err_msg=name
            )

    def test_eviction_pressure_and_overflow_accounting(self):
        """Full rows, few claimable slots, heavy churn: every lost
        group must land in dropped, every remembered eviction in
        forgot."""
        rng = np.random.default_rng(99)
        n, K, A = 6, 3, 200
        slot_subj = make_rows(rng, n, K, fill=K)
        # Full rows with ~one evictable slot each.
        evictable = rng.random((n, K)) < 0.3
        remembers = (slot_subj >= 0) & (rng.random((n, K)) < 0.8)
        stream = random_stream(rng, n, A, val_hi=30)
        got, want = run_both(slot_subj, evictable, remembers, stream)
        assert int(got[4]) == want[4] and want[4] > 0, "overflow pins"
        assert int(got[5]) == want[5], "forgotten pins"

    def test_full_table_reduces_to_scatter_max(self):
        """allocate=False over a full table is exactly the per-arrival
        scatter-max the kernel replaces (the K == n parity mode)."""
        rng = np.random.default_rng(3)
        n = 9
        ident = np.broadcast_to(
            np.arange(n, dtype=np.int32)[None, :], (n, n)
        ).copy()
        stream = random_stream(rng, n, 150)
        got, want = run_both(
            ident, np.zeros((n, n), bool), np.zeros((n, n), bool),
            stream, allocate=False,
        )
        recv, subj, val, sus, ok, _ = stream
        ref_key = np.full((n, n), -1, np.int32)
        ref_sus = np.full((n, n), -1, np.int32)
        for i in range(len(recv)):
            if ok[i]:
                r, s = recv[i], subj[i]
                ref_key[r, s] = max(ref_key[r, s], val[i])
                ref_sus[r, s] = max(ref_sus[r, s], sus[i])
        np.testing.assert_array_equal(np.asarray(got[2]), ref_key)
        np.testing.assert_array_equal(np.asarray(got[3]), ref_sus)
        assert not np.asarray(got[1]).any(), "nothing claimed"

    def test_duplicate_groups_collapse_to_one_claim(self):
        """Many duplicate arrivals for one unseated subject must claim
        exactly one slot (the stage-hash collision class is gone)."""
        n, K = 4, 3
        slot_subj = np.full((n, K), -1, np.int32)
        slot_subj[:, 0] = np.arange(n)
        A = 12
        stream = (
            np.full(A, 2, np.int32),        # all to receiver 2
            np.full(A, 0, np.int32),        # all about subject 0
            np.arange(1, A + 1, dtype=np.int32),
            np.full(A, -1, np.int32),
            np.ones(A, bool),
            np.ones(A, bool),
        )
        got, want = run_both(
            slot_subj, np.zeros((n, K), bool), np.zeros((n, K), bool),
            stream,
        )
        assert int(np.asarray(got[1]).sum()) == 1
        assert int(np.asarray(got[2]).max()) == A  # max value won
        assert int(got[4]) == 0 and int(got[5]) == 0


def full_sort_path(slot_subj, planes, defaults, stream, evictable,
                   remembers, allocate):
    """The pre-amortization reference pipeline: merge_deliveries +
    claimed-plane reset + sort_slot_rows — what merge_into_rows must
    reproduce bit-for-bit on identical inputs."""
    recv, subj, val, sus, ok, alloc = stream
    new_subj, claimed, key_rx, sus_rx, dropped, forgot = merge_deliveries(
        jnp.asarray(slot_subj), jnp.asarray(recv), jnp.asarray(subj),
        jnp.asarray(val), jnp.asarray(sus), jnp.asarray(ok),
        jnp.asarray(alloc),
        evictable=jnp.asarray(evictable),
        remembers=jnp.asarray(remembers),
        default_val=0, allocate=allocate,
    )
    planes = [jnp.asarray(p) for p in planes]
    if allocate:
        planes = [
            jnp.where(claimed, jnp.asarray(d, p.dtype), p)
            for p, d in zip(planes, defaults)
        ]
        out = sort_slot_rows(new_subj, *planes, key_rx, sus_rx)
        new_subj, planes = out[0], out[1:-2]
        key_rx, sus_rx = out[-2], out[-1]
    return new_subj, tuple(planes), key_rx, sus_rx, dropped, forgot


def _random_case(seed, val_hi=12):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 12))
    K = int(rng.integers(2, 7))
    A = int(rng.integers(1, 150))
    slot_subj = make_rows(rng, n, K, fill=K)
    evictable = (rng.random((n, K)) < 0.5) & (slot_subj >= 0)
    remembers = (rng.random((n, K)) < 0.5) & (slot_subj >= 0)
    defaults = (0, -1, 0, 0)
    planes = tuple(
        np.where(slot_subj < 0, d, rng.integers(1, 50, (n, K)))
        .astype(dt)
        for dt, d in zip(
            (np.int32, np.int16, np.int8, np.int8), defaults)
    )
    stream = random_stream(rng, n, A, val_hi=val_hi)
    return (slot_subj, planes, defaults, stream, evictable, remembers,
            bool(rng.integers(0, 2)))


def _assert_same(a, b, ctx):
    names = ("slot_subj", "planes", "key_rx", "sus_rx", "dropped",
             "forgot")
    for x, y, nm in zip(a, b, names):
        if nm == "planes":
            for i, (p, q) in enumerate(zip(x, y)):
                np.testing.assert_array_equal(
                    np.asarray(p), np.asarray(q),
                    err_msg=f"{ctx}: plane{i}")
        else:
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"{ctx}: {nm}")


@pytest.mark.slow
class TestMergeIntoRowsExtended:
    """Wider random sweep of the bit-equality pin — slow tier per the
    standing tier-1 budget policy (the tier-1 twin above keeps the
    per-class coverage)."""

    @pytest.mark.parametrize("seed", range(3, 12))
    def test_bit_equal_to_full_sort_path(self, seed):
        (slot_subj, planes, defaults, stream, evictable, remembers,
         allocate) = _random_case(seed)
        want = full_sort_path(slot_subj, planes, defaults, stream,
                              evictable, remembers, allocate)
        recv, subj, val, sus, ok, alloc = stream
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), defaults,
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(remembers),
            default_val=0, allocate=allocate,
        )
        _assert_same(got, want, f"seed {seed} alloc={allocate}")


class TestMergeIntoRows:
    """The amortized incremental kernel, pinned BIT-EQUAL to the
    full-sort path (merge_deliveries + reset + sort_slot_rows) on
    identical inputs — duplicates, ties, eviction pressure and the
    overflow/forgotten accounting all transfer through the pin, since
    the full-sort path itself is pinned to the brute-force reference
    above."""

    @pytest.mark.parametrize("seed", range(3))
    def test_bit_equal_to_full_sort_path(self, seed):
        (slot_subj, planes, defaults, stream, evictable, remembers,
         allocate) = _random_case(seed)
        want = full_sort_path(slot_subj, planes, defaults, stream,
                              evictable, remembers, allocate)
        recv, subj, val, sus, ok, alloc = stream
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), defaults,
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(remembers),
            default_val=0, allocate=allocate,
        )
        _assert_same(got, want, f"seed {seed} alloc={allocate}")

    def test_eviction_pressure_accounting_transfers(self):
        """Heavy churn with few claimable slots: dropped/forgot equal
        the full-sort path's (whose counts are reference-pinned)."""
        (slot_subj, planes, defaults, _, evictable, remembers, _) = \
            _random_case(99)
        rng = np.random.default_rng(7)
        stream = random_stream(rng, slot_subj.shape[0], 200, val_hi=30)
        want = full_sort_path(slot_subj, planes, defaults, stream,
                              evictable, remembers, True)
        recv, subj, val, sus, ok, alloc = stream
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), defaults,
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(remembers),
            default_val=0, allocate=True,
        )
        _assert_same(got, want, "pressure")
        assert int(got[4]) == int(want[4]) and int(want[4]) > 0

    def test_blocked_construction_matches_simple(self, monkeypatch):
        """The huge-table row-block construction (in-place scan carry)
        is the same math as the whole-table scatter path."""
        for seed in (3,):
            (slot_subj, planes, defaults, stream, evictable, remembers,
             _) = _random_case(seed)
            rng = np.random.default_rng(seed + 500)
            n, K = slot_subj.shape
            rx = (
                jnp.asarray(np.where(rng.random((n, K)) < 0.5,
                                     rng.integers(0, 90, (n, K)), -1)
                            .astype(np.int32)),
                jnp.asarray(np.where(rng.random((n, K)) < 0.5,
                                     rng.integers(0, 9, (n, K)), -1)
                            .astype(np.int32)),
            )
            recv, subj, val, sus, ok, alloc = stream
            args = (
                jnp.asarray(slot_subj),
                tuple(jnp.asarray(p) for p in planes), defaults,
                jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
                jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            )
            kw = dict(evictable=jnp.asarray(evictable),
                      remembers=jnp.asarray(remembers),
                      default_val=0, allocate=True, rx=rx)
            monkeypatch.setattr(sortmerge, "_BLOCK_ROWS", 1 << 21)
            simple = merge_into_rows(*args, **kw)
            monkeypatch.setattr(sortmerge, "_BLOCK_ROWS", 2)
            blocked = merge_into_rows(*args, **kw)
            _assert_same(blocked, simple, f"blocked seed {seed}")

    def test_rx_accumulators_extend_and_reset_on_eviction(self):
        """rx planes passed in accumulate (max) at surviving cells and
        reset with claimed/evicted cells — the contract the chunked
        driver carries one rx pair across chunks with."""
        n, K = 3, 2
        slot_subj = np.array(
            [[5, 9], [1, -1], [0, 7]], np.int32)
        planes = (np.array([[3, 7], [2, 0], [0, 4]], np.int32),
                  np.full((n, K), -1, np.int16),
                  np.zeros((n, K), np.int8), np.zeros((n, K), np.int8))
        rx = (jnp.asarray(np.array([[4, 6], [-1, -1], [2, -1]],
                                   np.int32)),
              jnp.asarray(np.full((n, K), -1, np.int32)))
        # Row 0: subject 2 arrives (unseated, alloc) -> evicts the
        # settled slot (subject 5, evictable) at column 0.
        stream = (np.array([0], np.int32), np.array([2], np.int32),
                  np.array([8], np.int32), np.array([-1], np.int32),
                  np.array([True]), np.array([True]))
        evictable = np.array([[True, False], [False, False],
                              [False, False]])
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), (0, -1, 0, 0),
            *[jnp.asarray(a) for a in stream],
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(np.zeros((n, K), bool)),
            default_val=0, allocate=True, rx=rx,
        )
        new_subj = np.asarray(got[0])
        key_rx = np.asarray(got[2])
        assert list(new_subj[0]) == [2, 9]      # 5 evicted, 2 claimed
        assert key_rx[0, 0] == 8                # the claimer's news
        assert key_rx[0, 1] == 6                # survivor kept its rx
        assert key_rx[2, 0] == 2                # untouched rows keep rx

    def test_fast_path_is_pure_scatter_max(self):
        """A stream with every subject seated must leave the table
        untouched and scatter-max raw values (the steady-state tick)."""
        rng = np.random.default_rng(5)
        n = 9
        ident = np.broadcast_to(
            np.arange(n, dtype=np.int32)[None, :], (n, n)).copy()
        planes = (np.zeros((n, n), np.int32),
                  np.full((n, n), -1, np.int16),
                  np.zeros((n, n), np.int8), np.zeros((n, n), np.int8))
        stream = random_stream(rng, n, 120)
        recv, subj, val, sus, ok, alloc = stream
        got = merge_into_rows(
            jnp.asarray(ident), tuple(jnp.asarray(p) for p in planes),
            (0, -1, 0, 0),
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.asarray(np.zeros((n, n), bool)),
            remembers=jnp.asarray(np.zeros((n, n), bool)),
            default_val=0, allocate=False,
        )
        np.testing.assert_array_equal(np.asarray(got[0]), ident)
        ref_key = np.full((n, n), -1, np.int32)
        for i in range(len(recv)):
            if ok[i]:
                r, s = recv[i], subj[i]
                ref_key[r, s] = max(ref_key[r, s], val[i])
        np.testing.assert_array_equal(np.asarray(got[2]), ref_key)
        assert int(got[4]) == 0 and int(got[5]) == 0


class TestInsertRowsOne:
    """The bounded single-claim insertion (probe maturities): same
    claim preference as the merge kernel, rows stay sorted, claimed
    cell resets to defaults."""

    @pytest.mark.parametrize("seed", range(2))
    def test_matches_claim_then_sort_reference(self, seed):
        rng = np.random.default_rng(1000 + seed)
        n = int(rng.integers(2, 10))
        K = int(rng.integers(2, 7))
        slot_subj = make_rows(rng, n, K, fill=K)
        evictable = (rng.random((n, K)) < 0.4) & (slot_subj >= 0)
        remembers = (rng.random((n, K)) < 0.5) & (slot_subj >= 0)
        defaults = (0, -1, 0, 0)
        planes = tuple(
            np.where(slot_subj < 0, d, rng.integers(1, 50, (n, K)))
            .astype(dt)
            for dt, d in zip(
                (np.int32, np.int16, np.int8, np.int8), defaults)
        )
        want = rng.random(n) < 0.6
        new_subj = np.zeros(n, np.int32)
        for i in range(n):
            absent = [x for x in range(n + K)
                      if x not in set(slot_subj[i].tolist())]
            new_subj[i] = int(rng.choice(absent))
        # Reference: first-empty-else-first-evictable claim + reset +
        # row sort.
        exp_subj = slot_subj.copy()
        exp_planes = [p.copy() for p in planes]
        exp_can = np.zeros(n, bool)
        exp_forgot = 0
        for i in range(n):
            if not want[i]:
                continue
            emp = np.where(slot_subj[i] < 0)[0]
            setl = np.where(evictable[i] & (slot_subj[i] >= 0))[0]
            if len(emp):
                v = emp[0]
            elif len(setl):
                v = setl[0]
            else:
                continue
            exp_can[i] = True
            exp_forgot += int(remembers[i, v])
            exp_subj[i, v] = new_subj[i]
            for p, d in zip(exp_planes, defaults):
                p[i, v] = d
        srt = sort_slot_rows(
            jnp.asarray(exp_subj), *[jnp.asarray(p) for p in exp_planes]
        )
        got_subj, got_planes, can, pos, forgot = insert_rows_one(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), defaults,
            jnp.asarray(want), jnp.asarray(new_subj),
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(remembers),
        )
        np.testing.assert_array_equal(
            np.asarray(got_subj), np.asarray(srt[0]))
        for g, w in zip(got_planes, srt[1:]):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
        np.testing.assert_array_equal(np.asarray(can), exp_can)
        assert int(forgot) == exp_forgot
        got_subj = np.asarray(got_subj)
        pos = np.asarray(pos)
        for i in range(n):
            if exp_can[i]:
                assert got_subj[i, pos[i]] == new_subj[i]


class TestRowPrimitives:
    def test_row_locate_lo_insertion_points(self):
        """lo = #real subjects strictly below the query — including
        the full-row regression (the fixed-trip binary search used to
        run lo past K once converged)."""
        rng = np.random.default_rng(4)
        for K in (2, 3, 5, 8, 64):
            n = 6
            slot_subj = make_rows(rng, n, K, fill=K)
            recv = rng.integers(0, n, 64).astype(np.int32)
            subj = rng.integers(0, n + 3, 64).astype(np.int32)
            _, lo = row_locate_lo(
                jnp.asarray(slot_subj), jnp.asarray(recv),
                jnp.asarray(subj))
            lo = np.asarray(lo)
            for i in range(64):
                row = slot_subj[recv[i]]
                want = int((row[row >= 0] < subj[i]).sum())
                assert lo[i] == want, (K, recv[i], subj[i])

    def test_row_locate_matches_linear_scan(self):
        rng = np.random.default_rng(1)
        for K in (1, 2, 3, 5, 8, 48, 64):
            n = 7
            slot_subj = make_rows(rng, n, K, fill=min(K, n))
            recv = rng.integers(0, n, 64).astype(np.int32)
            subj = rng.integers(0, n, 64).astype(np.int32)
            got = np.asarray(
                row_locate(jnp.asarray(slot_subj), jnp.asarray(recv),
                           jnp.asarray(subj))
            )
            for i in range(64):
                pos = np.where(slot_subj[recv[i]] == subj[i])[0]
                assert got[i] == (pos[0] if len(pos) else -1)

    def test_sort_slot_rows_restores_invariant(self):
        rng = np.random.default_rng(2)
        n, K = 5, 6
        slot_subj = make_rows(rng, n, K, fill=K)
        plane = rng.integers(0, 100, (n, K)).astype(np.int32)
        # Empty slots hold default contents as a model invariant, so
        # their relative order is unobservable; pin them to one value.
        plane[slot_subj < 0] = 0
        # Scramble the columns, then sort back.
        perm = rng.permutation(K)
        ss, pl = sort_slot_rows(
            jnp.asarray(slot_subj[:, perm]), jnp.asarray(plane[:, perm])
        )
        np.testing.assert_array_equal(np.asarray(ss), slot_subj)
        np.testing.assert_array_equal(np.asarray(pl), plane)


class TestScanChunksPadding:
    """The bool-padding footgun: jnp.full((pad,), -1, bool) is True, so
    chunk padding used to VALIDATE synthetic arrivals whenever the
    stream length wasn't a chunk multiple."""

    def test_bool_arrays_pad_false(self):
        from consul_tpu.models.membership_sparse import _scan_chunks

        total = _scan_chunks(
            lambda c, ok: c + jnp.sum(ok.astype(jnp.int32)),
            jnp.int32(0),
            (jnp.ones((5,), bool),),   # 5 % 4 != 0 → 3 padding slots
            4,
        )
        assert int(total) == 5

    def test_int_arrays_still_pad_invalid(self):
        from consul_tpu.models.membership_sparse import _scan_chunks

        seen = _scan_chunks(
            lambda c, r: c + jnp.sum((r >= 0).astype(jnp.int32)),
            jnp.int32(0),
            (jnp.arange(5, dtype=jnp.int32),),
            4,
        )
        assert int(seen) == 5


class TestPrioritizedAdmission:
    """The allocation-substream admission order (ISSUE 13 satellite):
    allocation-worthy arrivals (suspect/dead/never-seated news) admit
    AHEAD of never-allocating alive traffic, so a cold K << n
    push/pull-heavy tick — thousands of alive@inc rows early in stream
    order, the suspect news at the tail — can no longer spend the
    budget before the news arrives."""

    def _cold_pp_stream(self, n=8, K=4, heads=120, worthy=6):
        """The cold pp-heavy shape: ``heads`` ok never-allocating
        unseated arrivals (alive rows, alloc=False — the pull leg of a
        cold exchange) FIRST in stream order, then ``worthy`` suspect
        arrivals for distinct unseated subjects."""
        rng = np.random.default_rng(0)
        slot_subj = np.full((n, K), -1, np.int32)
        slot_subj[:, 0] = np.arange(n)          # self slot only: cold
        recv, subj, val, sus, ok, alloc = [], [], [], [], [], []
        for _ in range(heads):
            r = int(rng.integers(0, n))
            s = (r + 1 + int(rng.integers(0, n - 1))) % n
            recv.append(r); subj.append(s); val.append(3)
            sus.append(-1); ok.append(True); alloc.append(False)
        picks = set()
        while len(picks) < worthy:
            r = int(rng.integers(0, n))
            s = (r + 1 + int(rng.integers(0, n - 1))) % n
            picks.add((r, s))
        for r, s in sorted(picks):
            recv.append(r); subj.append(s); val.append(9)
            sus.append(2); ok.append(True); alloc.append(True)
        stream = tuple(np.asarray(a, dt) for a, dt in zip(
            (recv, subj, val, sus, ok, alloc),
            (np.int32, np.int32, np.int32, np.int32, bool, bool),
        ))
        return slot_subj, stream, worthy

    def test_worthy_news_admits_ahead_of_alive_traffic(self):
        slot_subj, stream, worthy = self._cold_pp_stream()
        n, K = slot_subj.shape
        recv, subj, val, sus, ok, alloc = stream
        budget = 16   # << the 120 alive arrivals ahead in stream order
        # The premise of the regression: under stream-order admission
        # the budget would fill with never-allocating traffic before
        # any worthy arrival (first `budget` unseated arrivals are all
        # alloc=False).
        assert not alloc[:budget].any()
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            (jnp.asarray(slot_subj * 0),), (0,),
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.zeros((n, K), bool),
            remembers=jnp.zeros((n, K), bool),
            default_val=0, allocate=True, alloc_budget=budget,
        )
        new_subj, _planes, key_rx, _sus_rx, dropped, _forgot = got
        assert int(dropped) == 0, "worthy news dropped despite priority"
        # Every worthy (recv, subj) pair is now seated with its value.
        new_subj = np.asarray(new_subj)
        key_rx = np.asarray(key_rx)
        seated = 0
        for r, s, v, al in zip(recv, subj, val, alloc):
            if not al:
                continue
            cols = np.flatnonzero(new_subj[r] == s)
            assert cols.size == 1, (r, s)
            assert key_rx[r, cols[0]] == v
            seated += 1
        assert seated == worthy

    def test_exact_budget_still_bit_equal_to_full_sort(self):
        # With no budget pressure the prioritized order is pure
        # permutation — the lex-sort erases it, so the full-sort pin
        # holds unchanged (the wider sweep lives in the classes above;
        # this pins the reordered-substream path specifically).
        (slot_subj, planes, defaults, stream, evictable, remembers,
         _alloc) = _random_case(17)
        want = full_sort_path(slot_subj, planes, defaults, stream,
                              evictable, remembers, True)
        recv, subj, val, sus, ok, alloc = stream
        got = merge_into_rows(
            jnp.asarray(slot_subj),
            tuple(jnp.asarray(p) for p in planes), defaults,
            jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
            jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            evictable=jnp.asarray(evictable),
            remembers=jnp.asarray(remembers),
            default_val=0, allocate=True,
            alloc_budget=len(np.asarray(recv)),
        )
        _assert_same(got, want, "prioritized, no pressure")

    def test_amortize_false_pins_slow_branch_bit_equal(self):
        # The static escape hatch (vmapped sweeps): amortize=False runs
        # the slow branch unconditionally and must be bit-equal on the
        # same inputs — including a claim-free stream, where the slow
        # branch's permutation is the identity.
        for seed in (3, 17):
            (slot_subj, planes, defaults, stream, evictable, remembers,
             allocate) = _random_case(seed)
            recv, subj, val, sus, ok, alloc = stream
            args = (
                jnp.asarray(slot_subj),
                tuple(jnp.asarray(p) for p in planes), defaults,
                jnp.asarray(recv), jnp.asarray(subj), jnp.asarray(val),
                jnp.asarray(sus), jnp.asarray(ok), jnp.asarray(alloc),
            )
            kw = dict(evictable=jnp.asarray(evictable),
                      remembers=jnp.asarray(remembers),
                      default_val=0, allocate=allocate)
            _assert_same(
                merge_into_rows(*args, **kw, amortize=False),
                merge_into_rows(*args, **kw),
                f"amortize seed {seed}",
            )
