"""Top-K sparse membership model: exact parity + scale.

The sparse model (models/membership_sparse.py) must be:
  1. BIT-EXACT against the dense model at K == n (identity slot layout
     consumes the same random draws in the same shapes), and
  2. semantically exact at K < n while ``overflow`` stays 0 (the
     representation drops nothing), with detection dynamics
     statistically matching the dense model, and
  3. runnable far past the dense model's O(N²) memory wall.
"""

import numpy as np
import pytest

from consul_tpu.models.membership import (
    MembershipConfig,
    RANK_DEAD,
    key_rank,
    membership_init,
    membership_round,
)
from consul_tpu.models.membership_sparse import (
    SparseMembershipConfig,
    densify,
    sparse_membership_init,
    sparse_membership_round,
)
from consul_tpu.protocol import LAN

import jax


def _run_dense(cfg, steps, seed):
    from consul_tpu.sim import membership_scan

    state, _ = membership_scan(
        membership_init(cfg), jax.random.PRNGKey(seed), cfg, steps
    )
    return state


def _run_sparse(scfg, steps, seed):
    from consul_tpu.sim import sparse_membership_scan

    state, _ = sparse_membership_scan(
        sparse_membership_init(scfg), jax.random.PRNGKey(seed), scfg, steps
    )
    return state


class TestExactParity:
    def test_k_equals_n_matches_dense_bit_for_bit(self):
        n = 48
        cfg = MembershipConfig(
            n=n, loss=0.2, profile=LAN,
            fail_at=((5, 3), (17, 8)), leave_at=((30, 12),),
        )
        scfg = SparseMembershipConfig(base=cfg, k_slots=n)
        steps = 50
        dense = _run_dense(cfg, steps, seed=7)
        sparse = _run_sparse(scfg, steps, seed=7)
        key, since, conf, tx = densify(sparse, n)
        np.testing.assert_array_equal(np.asarray(key),
                                      np.asarray(dense.key))
        np.testing.assert_array_equal(np.asarray(since),
                                      np.asarray(dense.suspect_since))
        np.testing.assert_array_equal(np.asarray(conf),
                                      np.asarray(dense.confirms))
        np.testing.assert_array_equal(np.asarray(tx),
                                      np.asarray(dense.tx))
        np.testing.assert_array_equal(np.asarray(sparse.own_inc),
                                      np.asarray(dense.own_inc))
        np.testing.assert_array_equal(np.asarray(sparse.awareness),
                                      np.asarray(dense.awareness))
        assert int(sparse.overflow) == 0

    @pytest.mark.slow
    def test_k_equals_n_no_failures_stays_quiet(self):
        # Corollary of the bit-for-bit parity pin above on a separate
        # no-failure program (tier-1 budget policy: the bit-for-bit
        # pin keeps the K == n claim in tier-1).
        n = 32
        cfg = MembershipConfig(n=n, loss=0.3, profile=LAN)
        scfg = SparseMembershipConfig(base=cfg, k_slots=n)
        dense = _run_dense(cfg, 40, seed=3)
        sparse = _run_sparse(scfg, 40, seed=3)
        key, _, _, _ = densify(sparse, n)
        np.testing.assert_array_equal(np.asarray(key),
                                      np.asarray(dense.key))


class TestSparseRegime:
    def test_small_k_detects_failure_without_overflow(self):
        """One crash, K far below n: every live observer still converges
        to DEAD for the subject, and no news is dropped (overflow 0 =
        the sparse run is exact, not approximate)."""
        n, K = 192, 16
        # loss small enough that false-positive suspicion campaigns
        # don't dominate the working set — K must cover the ACTIVE news
        # per row (failures in flight + draining retransmits), and at
        # loss=0.02 one crash is the only campaign.  (High-loss studies
        # need K sized to the sustained campaign count; the overflow
        # gauge below makes undersizing visible, never silent.)
        cfg = MembershipConfig(n=n, loss=0.02, profile=LAN,
                               fail_at=((42, 5),))
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        state = _run_sparse(scfg, 170, seed=1)
        # No urgent news dropped; settled-cell evictions (forgotten) are
        # allowed — that's the bounded-memory trade the model documents.
        assert int(state.overflow) == 0
        # Count observers holding a DEAD slot for 42.
        subj = np.asarray(state.slot_subj)
        ranks = np.asarray(key_rank(state.key))
        dead_view = ((subj == 42) & (ranks == RANK_DEAD)).any(axis=1)
        live = np.ones(n, bool)
        live[42] = False
        assert dead_view[live].mean() > 0.99

    @pytest.mark.slow  # 4 x 150-tick runs (~35 s); tier-1 detection
    # coverage stays on test_small_k_detects_failure_without_overflow
    def test_detection_time_statistics_match_dense(self):
        """K ≪ n with zero overflow is EXACT in distribution — its
        detection-time curve must land inside the dense model's own
        seed-to-seed band."""
        n, K = 128, 32
        steps = 150

        def dead_counts(run_state):
            if hasattr(run_state, "slot_subj"):
                subj = np.asarray(run_state.slot_subj)
                ranks = np.asarray(key_rank(run_state.key))
                return ((subj == 9) & (ranks == RANK_DEAD)).any(axis=1).sum()
            ranks = np.asarray(key_rank(run_state.key))
            return (ranks[:, 9] == RANK_DEAD).sum()

        cfg = MembershipConfig(n=n, loss=0.05, profile=LAN,
                               fail_at=((9, 5),))
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        dense_final = [dead_counts(_run_dense(cfg, steps, s))
                       for s in range(2)]
        sparse_final = [dead_counts(_run_sparse(scfg, steps, s))
                        for s in range(2)]
        # Both converge: nearly all live observers know the death.
        assert min(dense_final) > 0.95 * (n - 1)
        assert min(sparse_final) > 0.95 * (n - 1)

    def test_overflow_counts_when_slots_exhaust(self):
        """More concurrent churn than K slots can hold must surface in
        the overflow gauge, never silently."""
        n, K = 64, 4
        fails = tuple((i, 3) for i in range(1, 24))
        cfg = MembershipConfig(n=n, loss=0.0, profile=LAN,
                               fail_at=fails)
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        state = _run_sparse(scfg, 60, seed=0)
        assert int(state.overflow) > 0

    @pytest.mark.slow  # ~45s at CPU: 20k-node eager (unjitted) rounds
    def test_large_n_memory_footprint(self):
        """n = 20k (dense would need ~8 GB across its five [n, n]
        arrays) initializes and steps in O(n·K).  Behind -m slow per
        the tier-1 budget policy for large-n runs (PR 3); the sparse
        regime's tier-1 coverage stays on the small-n configs."""
        n, K = 20_000, 32
        cfg = MembershipConfig(n=n, loss=0.1, profile=LAN,
                               fail_at=((7, 1),))
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        state = sparse_membership_init(scfg)
        assert state.key.size == n * K
        key = jax.random.PRNGKey(0)
        for k in jax.random.split(key, 2):
            state = sparse_membership_round(state, k, scfg)
        assert int(state.tick) == 2


def test_join_schedules_rejected():
    cfg = MembershipConfig(n=8, join_at=((3, 5),))
    with pytest.raises(ValueError, match="join_at"):
        SparseMembershipConfig(base=cfg, k_slots=8)


class TestChunkedDelivery:
    """The 10M-scale chunked driver (_deliver_chunked), exercised at
    tiny n by forcing the trigger: detection converges, the exactness
    ladder stays loud, and the sorted-row invariant holds every
    tick."""

    @pytest.mark.slow  # 170 jitted chunked ticks (~35 s); the kernel-
    # level chunk coverage stays tier-1 in test_sortmerge
    def test_chunked_driver_converges_with_clean_accounting(
            self, monkeypatch):
        import consul_tpu.models.membership_sparse as ms

        n, K = 192, 16
        cfg = MembershipConfig(n=n, loss=0.02, profile=LAN,
                               fail_at=((42, 5),))
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        monkeypatch.setattr(ms, "_CHUNK_A", 512)
        monkeypatch.setattr(ms, "_CHUNK_TARGET", 512)
        state = sparse_membership_init(scfg)
        key = jax.random.PRNGKey(1)
        step = jax.jit(
            lambda s, k: sparse_membership_round(s, k, scfg))
        for _ in range(170):
            key, k = jax.random.split(key)
            state = step(state, k)
        assert int(state.overflow) == 0
        subj = np.asarray(state.slot_subj)
        ranks = np.asarray(key_rank(state.key))
        dead_view = ((subj == 42) & (ranks == RANK_DEAD)).any(axis=1)
        live = np.ones(n, bool)
        live[42] = False
        assert dead_view[live].mean() > 0.99
        # Sorted-row invariant after 170 chunked ticks.
        keyed = np.where(subj < 0, np.iinfo(np.int32).max, subj)
        assert (np.diff(keyed, axis=1) >= 0).all()
        occ = subj >= 0
        assert all(
            len(set(subj[i][occ[i]])) == occ[i].sum() for i in range(n)
        )

    def test_age_packed_since_reconstructs_absolute_ticks(self):
        """densify() reconstructs the absolute suspicion-start tick
        from the int16 age plane exactly (the sentinel-packing
        contract the K == n dense-parity pin rides on)."""
        from consul_tpu.models.membership_sparse import (
            AGE_NONE,
            SINCE_DTYPE,
        )

        n, K = 64, 8
        cfg = MembershipConfig(n=n, loss=0.3, profile=LAN,
                               fail_at=((7, 3),))
        scfg = SparseMembershipConfig(base=cfg, k_slots=K)
        state = _run_sparse(scfg, 40, seed=2)
        assert state.suspect_since.dtype == SINCE_DTYPE
        age = np.asarray(state.suspect_since)
        assert age.min() >= AGE_NONE
        _, since, _, _ = densify(state, n)
        since = np.asarray(since)
        t = int(state.tick)
        never = np.iinfo(np.int32).max
        recon = np.unique(since[since != never])
        # Every reconstructed start tick lies within the run horizon.
        assert ((recon >= 0) & (recon <= t)).all()
