"""Streamcast (consul_tpu/streamcast): the pipelined chunked
event-broadcast plane.

The ladder of guarantees, weakest precondition first:

  * window allocator == numpy brute-force reference (arrival,
    Lamport-supersede coalescing, eviction under overflow pressure) —
    property-tested over random scenarios.
  * W=1/E=1 single-event streamcast is BIT-EQUAL to broadcast_scan
    (delivery-time vector, both delivery modes): streamcast provably
    generalizes the point-event model rather than forking it.
  * pipelined bandwidth: per-round transmitted chunk copies stay under
    n x chunk_budget x fanout however many events are in flight.
  * accounting: offered == delivered + quiesced + overflow + coalesced
    + in-flight, always (the loud-never-silent window contract).
  * sharded exactness: D=1 bit-equal, D=2 == D=1 with outbox overflow
    0, ring == all_to_all.
  * faults: a LossRamp degrades throughput gracefully, never silently.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax

from consul_tpu.models.broadcast import (
    BroadcastConfig,
    broadcast_init,
    broadcast_round,
)
from consul_tpu.sim.engine import run_streamcast, streamcast_scan
from consul_tpu.streamcast import (
    POLICIES,
    StreamcastConfig,
    admit,
    arrival_arrays,
    chunk_validity,
    select_chunk,
    streamcast_init,
    streamcast_round,
)

# ---------------------------------------------------------------------------
# Window allocator vs numpy brute force.
# ---------------------------------------------------------------------------

W_SLOTS, K_EVENTS = 4, 12

# Round-by-round tests drive the SAME per-tick programs the scan runs,
# jitted once per config so a 20-tick loop costs dispatch, not tracing.
_round = jax.jit(streamcast_round, static_argnames=("cfg",))
_bround = jax.jit(broadcast_round, static_argnames=("cfg",))

# One shared config for the engine + sharded-exactness tests, so the
# module pays one compile per DISTINCT program per policy (unsharded,
# D1, D2, D2/ring) — the test_shard.py budget discipline.  The arrival
# schedule is EXPLICIT (tick, origin, name) entries rather than a
# Poisson draw: the tests below need events inside the shared 12-step
# window, and a derived schedule makes that a property of the seed and
# of the key-derivation scheme — PR 14's rederivation silently emptied
# the window at seed 0 and the module was re-seeded around it.  A
# scheduled stream pins the arrivals themselves, so future schedule-
# derivation changes cannot move them.  Names 1 and 2 repeat, so the
# Lamport-supersede path stays exercised.
_SHARDED_SCHEDULE = (
    (0, 5, 1), (1, 17, -1), (3, 40, 2), (5, 63, 1),
    (7, 80, -1), (9, 101, 2), (10, 22, -1),
)
_SHARDED_CFG = StreamcastConfig(
    n=128, chunks=2, window=4, fanout=3, chunk_budget=2,
    schedule=_SHARDED_SCHEDULE, loss=0.05, delivery="edges",
)


def _admit_ref(slot_event, slot_birth, arrive, ev_name, tick):
    """Sequential reference: a superseded occupant is replaced IN ITS
    OWN SLOT by the newest same-name arrival (serf coalesce: the
    latest payload takes over the name's delivery), same-tick older
    duplicates never allocate, and the remaining arrivals admit in
    Lamport order into ascending free slots — past-capacity arrivals
    dropped and counted."""
    slot_event = slot_event.copy()
    slot_birth = slot_birth.copy()
    k = arrive.size
    freed = np.zeros(slot_event.size, bool)
    claimed = np.zeros(k, bool)
    coalesced = 0
    for w, ev in enumerate(slot_event):
        if ev >= 0 and ev_name[ev] >= 0:
            winners = [j for j in range(k)
                       if arrive[j] and j > ev
                       and ev_name[j] == ev_name[ev]]
            if winners:
                freed[w] = True
                coalesced += 1
                slot_event[w] = max(winners)
                slot_birth[w] = tick
                claimed[max(winners)] = True
    sup = np.zeros(k, bool)
    for i in range(k):
        if not arrive[i] or ev_name[i] < 0:
            continue
        for j in range(k):
            if arrive[j] and j > i and ev_name[j] == ev_name[i]:
                sup[i] = True
    coalesced += int((arrive & sup).sum())
    filled = freed.copy()
    overflow = 0
    for i in range(k):
        if arrive[i] and not sup[i] and not claimed[i]:
            free = np.nonzero(slot_event < 0)[0]
            if free.size:
                slot_event[free[0]] = i
                slot_birth[free[0]] = tick
                filled[free[0]] = True
            else:
                overflow += 1
    return slot_event, slot_birth, filled, freed, overflow, coalesced


@functools.lru_cache(maxsize=1)
def _jit_admit():
    return jax.jit(admit)


class TestWindowAllocator:
    def _case(self, rng):
        """A consistent random window scenario: occupants are event
        ids strictly below every arriving id (they arrived earlier in
        Lamport order)."""
        ids = rng.permutation(K_EVENTS)
        n_occ = rng.integers(0, W_SLOTS + 1)
        split = rng.integers(n_occ, K_EVENTS + 1)
        older = np.sort(ids[:split])
        occupants = rng.choice(older, size=n_occ, replace=False) \
            if n_occ else np.empty(0, int)
        slot_event = np.full(W_SLOTS, -1, np.int32)
        slots = rng.choice(W_SLOTS, size=n_occ, replace=False)
        slot_event[slots] = np.sort(occupants)[::-1]
        slot_birth = rng.integers(0, 5, W_SLOTS).astype(np.int32)
        arrive = np.zeros(K_EVENTS, bool)
        newer = ids[split:]
        if newer.size:
            take = rng.integers(0, newer.size + 1)
            arrive[rng.choice(newer, size=take, replace=False)] = True
        ev_name = rng.integers(-1, 3, K_EVENTS).astype(np.int32)
        return slot_event, slot_birth, arrive, ev_name

    def test_matches_bruteforce_reference(self):
        fn = _jit_admit()
        rng = np.random.default_rng(7)
        checked_overflow = checked_coalesce = 0
        for case in range(60):
            se, sb, arrive, names = self._case(rng)
            tick = np.int32(5 + case)
            got = [np.asarray(x) for x in fn(se, sb, arrive, names,
                                             tick)]
            want = _admit_ref(se, sb, arrive, names, tick)
            for i, (g, w) in enumerate(zip(got, want)):
                assert (np.asarray(g) == np.asarray(w)).all(), (
                    f"case {case} output {i}: {g} != {w}\n"
                    f"slots={se} arrive={np.nonzero(arrive)[0]} "
                    f"names={names}"
                )
            checked_overflow += int(got[4])
            checked_coalesce += int(got[5])
        # The generator must actually exercise the pressure paths.
        assert checked_overflow > 0, "no overflow pressure generated"
        assert checked_coalesce > 0, "no coalescing pressure generated"

    def test_full_window_drops_and_counts(self):
        fn = _jit_admit()
        se = np.arange(W_SLOTS, dtype=np.int32)  # all occupied
        sb = np.zeros(W_SLOTS, np.int32)
        arrive = np.zeros(K_EVENTS, bool)
        arrive[W_SLOTS:W_SLOTS + 3] = True
        names = np.full(K_EVENTS, -1, np.int32)
        out = fn(se, sb, arrive, names, np.int32(1))
        assert int(out[4]) == 3           # every arrival dropped
        assert int(out[5]) == 0
        assert (np.asarray(out[0]) == se).all()

    def test_superseder_claims_its_slot_under_full_window(self):
        # Full window, same tick: arrival 6 supersedes occupant 1
        # (same name) while unrelated arrival 5 also wants a slot.
        # The superseder must take the slot it freed — NOT race ranked
        # admission and overflow while its name's slot goes to the
        # competitor (which would lose both payloads of the name).
        fn = _jit_admit()
        se = np.arange(W_SLOTS, dtype=np.int32)   # occupants 0..3
        sb = np.zeros(W_SLOTS, np.int32)
        names = np.full(K_EVENTS, -1, np.int32)
        names[1] = names[6] = 9
        arrive = np.zeros(K_EVENTS, bool)
        arrive[5] = arrive[6] = True
        out = fn(se, sb, arrive, names, np.int32(3))
        new_se = np.asarray(out[0])
        assert new_se[1] == 6                      # in-place claim
        assert int(out[4]) == 1                    # arrival 5 overflows
        assert int(out[5]) == 1                    # occupant 1 coalesced

    def test_supersede_frees_then_refills_same_tick(self):
        fn = _jit_admit()
        se = np.full(W_SLOTS, -1, np.int32)
        se[:W_SLOTS] = np.arange(W_SLOTS)  # events 0..3 occupy all
        sb = np.zeros(W_SLOTS, np.int32)
        names = np.full(K_EVENTS, -1, np.int32)
        names[1] = names[6] = 5            # event 6 supersedes event 1
        arrive = np.zeros(K_EVENTS, bool)
        arrive[6] = True
        out = fn(se, sb, np.asarray(arrive), names, np.int32(2))
        new_se = np.asarray(out[0])
        assert 1 not in new_se             # superseded occupant gone
        assert 6 in new_se                 # newer event took the slot
        assert int(out[4]) == 0 and int(out[5]) == 1


# ---------------------------------------------------------------------------
# The broadcast bit-equality pin: W=1, E=1, one scheduled event.
# ---------------------------------------------------------------------------


class TestBroadcastPin:
    N, F, LOSS, STEPS = 128, 3, 0.05, 20

    @pytest.mark.parametrize("delivery", ["edges", "aggregate"])
    def test_single_event_delivery_times_bit_equal(self, delivery):
        scfg = StreamcastConfig(
            n=self.N, window=1, chunks=1, fanout=self.F,
            loss=self.LOSS, schedule=((0, 0, -1),), delivery=delivery,
        )
        bcfg = BroadcastConfig(n=self.N, fanout=self.F, loss=self.LOSS,
                               delivery=delivery)
        sched = arrival_arrays(scfg, jax.random.PRNGKey(0))
        sst = streamcast_init(scfg)
        bst = broadcast_init(bcfg, origin=0)
        keys = jax.random.split(jax.random.PRNGKey(3), self.STEPS)
        first_s = np.full(self.N, -1)
        first_b = np.full(self.N, -1)
        first_b[0] = 0  # origin knows at arrival/init
        first_s[0] = 0
        for t in range(self.STEPS):
            sst, outs = _round(sst, keys[t], scfg, sched)
            bst = _bround(bst, keys[t], bcfg)
            b_knows = np.asarray(bst.knows)
            if int(np.asarray(outs[0])[0]) == 0:
                # Slot alive: the chunk plane must equal knows
                # BIT-FOR-BIT (slot snapshot is pre-retirement, so the
                # completion round is still compared).
                s_knows = np.asarray(sst.chunks[:, 0, 0]) \
                    if int(np.asarray(outs[2])[0]) < self.N \
                    else np.ones(self.N, bool)
                assert (s_knows == b_knows).all(), f"tick {t}"
                first_s[(s_knows) & (first_s < 0)] = t
            first_b[(b_knows) & (first_b < 0)] = t
        # Delivery-time vectors agree wherever the stream observed
        # them (the slot retires at completion; broadcast keeps going).
        seen = first_s >= 0
        assert (first_s[seen] == first_b[seen]).all()
        assert int(sst.delivered) == 1, "event never fully delivered"
        # Full coverage: the event completed, so every node's delivery
        # time was observed.
        assert seen.all()

    @pytest.mark.parametrize("policy", [
        "uniform", "pipeline",
        # rarest rides the slow tier (tier-1 budget: same degenerate
        # argument, lower-value third compile).
        pytest.param("rarest", marks=pytest.mark.slow),
    ])
    def test_scan_curve_matches_broadcast_scan(self, policy):
        # At E=1 every policy selects chunk 0 and only ``uniform``
        # draws the chunk key, yet k_sel/k_loss ride a separate split
        # — so the pin holds for ALL THREE policies: each one's
        # degenerate case really is broadcast_scan.
        from consul_tpu.sim.engine import broadcast_scan

        scfg = StreamcastConfig(
            n=self.N, window=1, chunks=1, fanout=self.F,
            loss=self.LOSS, schedule=((0, 0, -1),), delivery="edges",
            policy=policy,
        )
        bcfg = BroadcastConfig(n=self.N, fanout=self.F, loss=self.LOSS,
                               delivery="edges")
        key = jax.random.PRNGKey(3)
        _, infected = broadcast_scan(
            broadcast_init(bcfg, origin=0), key, bcfg, self.STEPS
        )
        _, outs = streamcast_scan(
            streamcast_init(scfg), key, scfg, self.STEPS
        )
        infected = np.asarray(infected)
        done = np.asarray(outs[2])[:, 0]
        alive = np.asarray(outs[0])[:, 0] == 0
        assert alive.any()
        assert (done[alive] == infected[alive]).all()


# ---------------------------------------------------------------------------
# Pipelining, accounting, coalescing, overflow.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _pressure_run():
    """One cached heavy-pressure study shared by the invariants below:
    Poisson arrivals with a small name space over a small window."""
    cfg = StreamcastConfig(
        n=192, events=130, chunks=3, window=4, fanout=3,
        chunk_budget=2, rate=1.0, names=12, loss=0.05,
        delivery="edges",
    )
    final, outs = streamcast_scan(
        streamcast_init(cfg), jax.random.PRNGKey(0), cfg, 70
    )
    return cfg, jax.tree_util.tree_map(np.asarray, (final, outs))


class TestPipelineInvariants:
    def test_constant_bandwidth_bound(self):
        # The pipelined-gossip claim: per-round transmitted chunk
        # copies never exceed n x chunk_budget x fanout, no matter how
        # many events are in flight.
        cfg, (_final, outs) = _pressure_run()
        sent = outs[8]
        assert (sent <= cfg.n * cfg.chunk_budget * cfg.fanout).all()
        assert (sent > 0).any()

    def test_window_accounting_identity(self):
        # offered == delivered + quiesced + window_overflow +
        # coalesced + in-flight: every offered event lands in exactly
        # one bucket — the loud-never-silent contract.
        _cfg, (final, _outs) = _pressure_run()
        in_flight = int((final.slot_event >= 0).sum())
        assert int(final.offered) == (
            int(final.delivered) + int(final.quiesced)
            + int(final.window_overflow) + int(final.coalesced)
            + in_flight
        )

    def test_pressure_run_exercises_every_bucket(self):
        _cfg, (final, _outs) = _pressure_run()
        assert int(final.offered) > 0
        assert int(final.delivered) > 0
        assert int(final.window_overflow) > 0
        assert int(final.coalesced) > 0

    @pytest.mark.slow  # tier-1 budget: the bound itself stays pinned
    # every run by test_constant_bandwidth_bound on the cached
    # pressure study; this 1-vs-8 comparison pays two extra compiles.
    def test_many_in_flight_same_bandwidth_as_one(self):
        # 8 simultaneous events through the pipe pay the same per-round
        # budget as 1: the window multiplies THROUGHPUT, not bandwidth.
        def peak_sent(n_events):
            cfg = StreamcastConfig(
                n=128, chunks=2, window=8, fanout=3, chunk_budget=2,
                loss=0.0,
                schedule=tuple((0, i, -1) for i in range(n_events)),
            )
            sched = arrival_arrays(cfg, jax.random.PRNGKey(0))
            st = streamcast_init(cfg)
            keys = jax.random.split(jax.random.PRNGKey(1), 12)
            peak = 0
            for t in range(12):
                st, outs = _round(st, keys[t], cfg, sched)
                peak = max(peak, int(outs[8]))
            return peak

        bound = 128 * 2 * 3
        assert peak_sent(1) <= bound
        assert peak_sent(8) <= bound


class TestCoalescing:
    def test_newer_same_name_supersedes_in_flight(self):
        cfg = StreamcastConfig(
            n=128, chunks=2, window=4, fanout=3, chunk_budget=2,
            loss=0.0, schedule=((0, 5, 7), (6, 9, 7)),
        )
        sched = arrival_arrays(cfg, jax.random.PRNGKey(0))
        st = streamcast_init(cfg)
        keys = jax.random.split(jax.random.PRNGKey(2), 30)
        seen_events = set()
        for t in range(30):
            st, outs = _round(st, keys[t], cfg, sched)
            seen_events |= set(
                int(e) for e in np.asarray(outs[0]) if e >= 0
            )
            if t == 5:
                assert 0 in seen_events  # event 0 in flight pre-arrival
        assert int(st.coalesced) == 1     # event 0 superseded at t=6
        assert int(st.delivered) == 1     # only event 1 completes
        assert 1 in seen_events

    def test_window_overflow_drops_loudly(self):
        # W=1 and two distinct same-tick events: Lamport-older wins the
        # slot, the other is DROPPED and counted.
        cfg = StreamcastConfig(
            n=64, chunks=1, window=1, fanout=3, loss=0.0,
            schedule=((0, 1, -1), (0, 2, -1)),
        )
        sched = arrival_arrays(cfg, jax.random.PRNGKey(0))
        st = streamcast_init(cfg)
        st, outs = _round(
            st, jax.random.PRNGKey(1), cfg, sched
        )
        assert int(np.asarray(outs[0])[0]) == 0
        assert int(st.window_overflow) == 1
        assert int(st.coalesced) == 0


class TestFaultSchedules:
    def test_loss_ramp_degrades_gracefully(self):
        # A mid-run brownout must reduce delivered throughput
        # monotonically-ish with severity and never crash or go
        # silent: the LossRamp rungs deliver a non-increasing event
        # count, and accounting stays exact at every rung.
        from consul_tpu.sim.faults import FaultSchedule, LossRamp

        delivered = []
        for scale in (0.0, 1.0):
            cfg = StreamcastConfig(
                n=192, chunks=2, window=6, fanout=3, chunk_budget=2,
                loss=0.02,
                schedule=tuple((2 * i, (7 * i) % 192, -1)
                               for i in range(12)),
                faults=FaultSchedule(
                    ramps=(LossRamp(pieces=((0, 0.85),), scale=scale),)
                ),
            )
            final, _outs = streamcast_scan(
                streamcast_init(cfg), jax.random.PRNGKey(0), cfg, 60
            )
            in_flight = int(np.asarray(final.slot_event >= 0).sum())
            assert int(final.offered) == (
                int(final.delivered) + int(final.quiesced)
                + int(final.window_overflow) + int(final.coalesced)
                + in_flight
            )
            delivered.append(int(final.delivered))
        assert delivered[0] > 0
        # The 85% brownout must actually bite (not a dead knob) while
        # degrading gracefully — fewer events land, nothing crashes or
        # goes unaccounted.
        assert delivered[1] < delivered[0]

    def test_node_fault_primitives_rejected_loudly(self):
        from consul_tpu.sim.faults import DegradedSet, FaultSchedule

        with pytest.raises(ValueError, match="loss ramps only"):
            StreamcastConfig(
                n=64, events=4, rate=0.1,
                faults=FaultSchedule(
                    degraded=(DegradedSet(frac=0.1),)
                ),
            )


# ---------------------------------------------------------------------------
# The selection-policy seam (model.select_chunk).
# ---------------------------------------------------------------------------


class TestSelectChunk:
    """Unit pins of the policy kernel on hand-built held-chunk planes
    (4 nodes x 1 slot x 4 chunks; serviced everywhere)."""

    E = 4

    def _cfg(self, policy):
        return StreamcastConfig(
            n=4, window=1, chunks=self.E, schedule=((0, 0, -1),),
            policy=policy,
        )

    def _drive(self, policy, held_row, rounds):
        """Select ``rounds`` times against a FIXED held mask, carrying
        the cursor; returns [rounds, 4] selections."""
        cfg = self._cfg(policy)
        rows = jax.numpy.arange(4, dtype=jax.numpy.int32)
        held = jax.numpy.broadcast_to(
            jax.numpy.asarray(held_row, bool)[None, None, :],
            (4, 1, self.E),
        )
        cursor = jax.numpy.zeros((4, 1), jax.numpy.int8)
        serviced = jax.numpy.ones((4, 1), bool)
        sels = []
        for t in range(rounds):
            sel, cursor = select_chunk(
                cfg, jax.random.PRNGKey(t), rows, held, cursor,
                serviced,
            )
            sels.append(np.asarray(sel)[:, 0])
        return np.stack(sels)

    def test_pipeline_cycles_every_held_chunk(self):
        # The paper's round-robin claim: a full holder pushes each of
        # its E chunks exactly once per E serviced rounds — uniform
        # needs ~E·H(E) rounds for the same coverage by coupon
        # collection, which is exactly the duplicate-budget waste the
        # pipeline schedule removes.
        sels = self._drive("pipeline", [1, 1, 1, 1], 8)
        for node in range(4):
            assert sorted(sels[:4, node]) == [0, 1, 2, 3]
            assert (sels[:4, node] == sels[4:, node]).all()

    def test_pipeline_skips_unheld_chunks(self):
        sels = self._drive("pipeline", [1, 0, 1, 0], 4)
        for node in range(4):
            assert sorted(sels[:2, node]) == [0, 2]
            assert (sels[:2, node] == sels[2:, node]).all()

    def test_pipeline_cursor_holds_without_service(self):
        cfg = self._cfg("pipeline")
        rows = jax.numpy.arange(4, dtype=jax.numpy.int32)
        held = jax.numpy.ones((4, 1, self.E), bool)
        cursor = jax.numpy.full((4, 1), 2, jax.numpy.int8)
        idle = jax.numpy.zeros((4, 1), bool)
        sel, nxt = select_chunk(
            cfg, jax.random.PRNGKey(0), rows, held, cursor, idle
        )
        assert (np.asarray(sel) == 2).all()      # nearest from cursor
        assert (np.asarray(nxt) == 2).all()      # no advance unserviced
        assert nxt.dtype == cursor.dtype

    def test_rarest_cycles_lowest_index_first(self):
        # Greedy cycle memory: lowest held index not yet pushed this
        # cycle, wrap restarting at the lowest — a MEMORYLESS
        # lowest-index greedy would push chunk 1 forever here (and at
        # the origin would never release chunks 1..E-1 at all, the
        # degenerate zero-delivery schedule).
        sels = self._drive("rarest", [0, 1, 0, 1], 4)
        assert (sels == np.array([1, 3, 1, 3])[:, None]).all()

    def test_rarest_full_holder_cycles_all_chunks(self):
        sels = self._drive("rarest", [1, 1, 1, 1], 8)
        for node in range(4):
            assert sorted(sels[:4, node]) == [0, 1, 2, 3]
            assert (sels[:4, node] == sels[4:, node]).all()

    def test_uniform_covers_held_support(self):
        # Uniform is random but must stay inside the held set.
        sels = self._drive("uniform", [0, 1, 0, 1], 12)
        assert set(np.unique(sels)) <= {1, 3}
        assert len(set(np.unique(sels))) == 2  # both held chunks drawn

    def test_pipeline_beats_uniform_on_the_shared_schedule(self):
        # The end-to-end claim at module scale: same schedule, same
        # seed, pipeline retires at least as many events as uniform
        # inside the shared 12-step window (the knee-raising mechanism
        # measured at n=100k in bench "streaming").
        uni = _sharded_runs("uniform")["unsharded"]
        pipe = _sharded_runs("pipeline")["unsharded"]
        # outs[4] = cumulative delivered; [2] = per-slot done counts.
        assert int(pipe[4][-1]) >= int(uni[4][-1])
        assert int(pipe[2].sum()) > int(uni[2].sum())


# ---------------------------------------------------------------------------
# Adversarial offered load (sim/load.py): standing backlog,
# heavy-tailed sizes, hotspot origins.
# ---------------------------------------------------------------------------


class TestAdversarialLoad:
    def test_backlog_pins_prefix_only(self):
        base = StreamcastConfig(n=64, events=20, rate=0.2, chunks=4)
        adv = dataclasses.replace(base, backlog=6)
        key = jax.random.PRNGKey(0)
        t0, o0, n0, c0 = [np.asarray(x) for x in
                          arrival_arrays(base, key)]
        t1, o1, n1, c1 = [np.asarray(x) for x in
                          arrival_arrays(adv, key)]
        assert (t1[:6] == 0).all()
        assert (t1[6:] == t0[6:]).all()   # the tail stream untouched
        assert (o1 == o0).all() and (n1 == n0).all()
        assert (c0 == 4).all()            # size_tail=0: full E always

    def test_hotspot_reoriginates_without_reshuffling(self):
        base = StreamcastConfig(n=64, events=40, rate=0.2)
        key = jax.random.PRNGKey(1)
        _, o0, _, _ = arrival_arrays(base, key)
        _, o1, _, _ = arrival_arrays(
            dataclasses.replace(base, hotspot=1.0, hotspot_node=7), key
        )
        _, o2, _, _ = arrival_arrays(
            dataclasses.replace(base, hotspot=0.0), key
        )
        assert (np.asarray(o1) == 7).all()
        assert (np.asarray(o2) == np.asarray(o0)).all()

    def test_paced_arrivals_are_deterministic_same_side_streams(self):
        # The staggered stream: event i born at floor(i/rate), zero
        # burst variance — and the origin/name/size draws are the
        # SAME as the Poisson twin's (only timing changes).
        base = StreamcastConfig(n=64, events=30, rate=0.25, chunks=4)
        paced = dataclasses.replace(base, arrivals="paced")
        key = jax.random.PRNGKey(0)
        _, o0, n0, c0 = arrival_arrays(base, key)
        t1, o1, n1, c1 = arrival_arrays(paced, key)
        assert (np.asarray(t1)
                == np.floor(np.arange(30) / 0.25)).all()
        for a, b in ((o0, o1), (n0, n1), (c0, c1)):
            assert (np.asarray(a) == np.asarray(b)).all()

    def test_heavy_tail_sizes_in_range_and_tailed(self):
        cfg = StreamcastConfig(n=64, events=200, rate=0.5, chunks=4,
                               size_tail=1.0)
        _, _, _, sizes = arrival_arrays(cfg, jax.random.PRNGKey(2))
        sizes = np.asarray(sizes)
        assert sizes.min() >= 1 and sizes.max() <= 4
        # Pareto(1) over [1, 4]: ~half the mass at 1, a real tail at 4.
        assert (sizes == 1).sum() > 50
        assert (sizes == 4).sum() > 10

    def test_chunk_validity_matches_reference(self):
        # The numpy brute-force twin of model.chunk_validity.
        rng = np.random.default_rng(3)
        for _ in range(20):
            k, w, e = 10, 5, 6
            ev_chunks = rng.integers(1, e + 1, k).astype(np.int32)
            slot_event = rng.integers(-1, k, w).astype(np.int32)
            got = np.asarray(chunk_validity(
                jax.numpy.asarray(slot_event),
                jax.numpy.asarray(ev_chunks), e,
            ))
            want = np.zeros((w, e), bool)
            for wi in range(w):
                nch = ev_chunks[max(slot_event[wi], 0)]
                want[wi, :nch] = True
            assert (got == want).all()

    def test_masked_chunks_born_delivered_and_complete_early(self):
        # A 1-real-chunk event over an E=3 ceiling: padding chunks are
        # True at EVERY node from the fill tick, completion requires
        # only chunk 0 — so the event retires as delivered while a
        # full-width twin of the same schedule is still spreading.
        def run(nchunks):
            cfg = StreamcastConfig(
                n=96, chunks=3, window=2, fanout=3, chunk_budget=2,
                loss=0.0, schedule=((0, 0, -1, nchunks),),
            )
            sched = arrival_arrays(cfg, jax.random.PRNGKey(0))
            st = streamcast_init(cfg)
            keys = jax.random.split(jax.random.PRNGKey(4), 20)
            first_done = None
            for t in range(20):
                st, outs = _round(st, keys[t], cfg, sched)
                if t == 0 and nchunks == 1:
                    assert bool(np.asarray(st.chunks)[:, 0, 1:].all())
                if first_done is None and int(st.delivered) == 1:
                    first_done = t
            assert first_done is not None, "event never delivered"
            return first_done

        assert run(1) <= run(3)

    @pytest.mark.parametrize("rate", [
        # The low-pressure rung rides the slow tier (tier-1 budget);
        # the saturating rung carries the tier-1 claim.
        pytest.param(0.5, marks=pytest.mark.slow),
        1.5,
    ])
    def test_accounting_identity_under_adversarial_pressure(self, rate):
        # The loud-window contract re-pinned under ALL THREE regimes
        # at once (standing backlog + heavy tail + hotspot), at two
        # pressure levels: offered == delivered + quiesced + overflow
        # + coalesced + in-flight, and the backlog makes tick 0 itself
        # offer a windowful.
        cfg = StreamcastConfig(
            n=192, events=int(rate * 60 * 1.5), chunks=3, window=4,
            fanout=3, chunk_budget=2, rate=rate, names=8, loss=0.05,
            backlog=6, size_tail=1.0, hotspot=0.5, policy="pipeline",
        )
        final, outs = streamcast_scan(
            streamcast_init(cfg), jax.random.PRNGKey(0), cfg, 60
        )
        in_flight = int(np.asarray(final.slot_event >= 0).sum())
        assert int(final.offered) == (
            int(final.delivered) + int(final.quiesced)
            + int(final.window_overflow) + int(final.coalesced)
            + in_flight
        )
        # 6 pre-seeded arrivals into a W=4 window: the backlog bites
        # at tick 0 — loudly.
        offered_t0 = int(np.asarray(outs[3])[0])
        assert offered_t0 >= 6
        assert int(final.window_overflow) > 0
        assert int(final.delivered) > 0


# ---------------------------------------------------------------------------
# Config validation: the arrival-mode and shape contracts.
# ---------------------------------------------------------------------------


class TestConfigValidation:
    def test_exactly_one_arrival_mode(self):
        with pytest.raises(ValueError, match="exactly one arrival"):
            StreamcastConfig(n=64, events=4, rate=0.2,
                             schedule=((0, 1, -1),))
        with pytest.raises(ValueError, match="exactly one arrival"):
            StreamcastConfig(n=64, events=4)  # neither

    def test_poisson_needs_capacity(self):
        with pytest.raises(ValueError, match="events=K"):
            StreamcastConfig(n=64, rate=0.2)

    def test_schedule_validated_on_host(self):
        with pytest.raises(ValueError, match="non-decreasing"):
            StreamcastConfig(n=64, schedule=((5, 1, -1), (2, 3, -1)))
        with pytest.raises(ValueError, match="outside"):
            StreamcastConfig(n=64, schedule=((0, 64, -1),))
        with pytest.raises(ValueError, match="3-tuples"):
            StreamcastConfig(n=64, schedule=((0, 1),))

    def test_done_frac_contract(self):
        # Default 1.0 = every node (the broadcast-pin semantics);
        # sustained-load studies relax it — the epidemic tail means
        # the last straggler of a big n may never land.
        full = StreamcastConfig(n=1000, schedule=((0, 0, -1),))
        assert full.done_target == 1000
        most = StreamcastConfig(n=1000, schedule=((0, 0, -1),),
                                done_frac=0.999)
        assert most.done_target == 999
        with pytest.raises(ValueError, match="done_frac"):
            StreamcastConfig(n=64, schedule=((0, 0, -1),),
                             done_frac=0.0)
        with pytest.raises(ValueError, match="done_frac"):
            StreamcastConfig(n=64, schedule=((0, 0, -1),),
                             done_frac=1.5)

    def test_tx_budget_scales_with_chunks(self):
        one = StreamcastConfig(n=256, schedule=((0, 0, -1),), chunks=1)
        four = StreamcastConfig(n=256, schedule=((0, 0, -1),),
                                chunks=4)
        assert four.tx_limit == 4 * one.tx_limit

    def test_policy_and_arrivals_validated(self):
        with pytest.raises(ValueError, match="not a chunk-selection"):
            StreamcastConfig(n=64, events=4, rate=0.1,
                             policy="pipelined")
        with pytest.raises(ValueError, match="not an arrival"):
            StreamcastConfig(n=64, events=4, rate=0.1,
                             arrivals="bursty")

    def test_adversarial_knobs_validated(self):
        with pytest.raises(ValueError, match="backlog=-1"):
            StreamcastConfig(n=64, events=4, rate=0.1, backlog=-1)
        with pytest.raises(ValueError, match="exceeds the schedule"):
            StreamcastConfig(n=64, events=4, rate=0.1, backlog=9)
        with pytest.raises(ValueError, match="size_tail"):
            StreamcastConfig(n=64, events=4, rate=0.1, size_tail=-1.0)
        with pytest.raises(ValueError, match="hotspot=1.5"):
            StreamcastConfig(n=64, events=4, rate=0.1, hotspot=1.5)
        with pytest.raises(ValueError, match="hotspot_node"):
            StreamcastConfig(n=64, events=4, rate=0.1,
                             hotspot_node=64)

    def test_adversarial_knobs_rejected_in_scheduled_mode(self):
        # A scheduled stream expresses backlog/sizes/origins/pacing
        # explicitly; the Poisson shapers on top would be silently
        # ambiguous — loudly refused instead.
        for kw in ({"backlog": 1}, {"size_tail": 1.0},
                   {"hotspot": 0.5}, {"arrivals": "paced"}):
            with pytest.raises(ValueError, match="POISSON"):
                StreamcastConfig(n=64, schedule=((0, 0, -1),), **kw)

    def test_schedule_4tuple_chunk_counts_validated(self):
        ok = StreamcastConfig(n=64, chunks=4,
                              schedule=((0, 0, -1, 2),))
        assert ok.k_events == 1
        with pytest.raises(ValueError, match="chunk count"):
            StreamcastConfig(n=64, chunks=4, schedule=((0, 0, -1, 5),))
        with pytest.raises(ValueError, match="chunk count"):
            StreamcastConfig(n=64, chunks=4, schedule=((0, 0, -1, 0),))


# ---------------------------------------------------------------------------
# Engine wiring + the one-program contract.
# ---------------------------------------------------------------------------


class TestEngine:
    @pytest.mark.single_trace(entrypoints=("streamcast_scan",))
    def test_run_streamcast_report_and_single_trace(self):
        # The exact (cfg, steps) the sharded ladder uses, so the whole
        # module pays ONE unsharded compile.  The cfg's EXPLICIT
        # schedule guarantees in-window arrivals at every seed (the
        # seed only drives transmission RNG).
        cfg = _SHARDED_CFG
        rep = run_streamcast(cfg, steps=12, seed=0, warmup=False)
        # warmup=False + a second seed through the SAME program: the
        # single_trace guard asserts one compile for both.
        rep2 = run_streamcast(cfg, steps=12, seed=1, warmup=False)
        s = rep.summary()
        for key in ("events_offered", "events_delivered",
                    "window_overflow", "saturated",
                    "delivered_events_per_sim_s", "t50_ms_median",
                    "t99_ms_median", "peak_chunks_sent_per_round"):
            assert key in s, key
        assert s["events_offered"] > 0
        assert rep2.offered_total >= 0
        assert rep.shard_overflow is None

    def test_exchange_without_mesh_rejected(self):
        cfg = StreamcastConfig(n=64, events=4, rate=0.1)
        with pytest.raises(ValueError, match="requires mesh="):
            run_streamcast(cfg, steps=4, exchange="ring")

    def test_scenario_preset_registered(self):
        from consul_tpu.sim.scenarios import SCENARIOS, stream100k

        assert "stream100k" in SCENARIOS
        out = stream100k(n=192, steps=40)
        assert out["scenario"] == "stream100k"
        assert out["events_offered"] > 0
        assert "window_overflow" in out
        assert out["policy"] == "uniform"

    def test_cli_policy_choices_pin_the_registry(self):
        # cli.py keeps a literal twin of POLICIES (the parser must
        # build without importing the JAX-heavy sim tree); this pin is
        # what stops the copies drifting when a policy is added.
        from consul_tpu.cli import SIM_POLICY_CHOICES

        assert SIM_POLICY_CHOICES == POLICIES

    def test_scenario_policy_threading(self):
        # --policy lands in the config and echoes in the summary; a
        # typo fails loudly at config construction, and non-streamcast
        # presets reject the flag before any JAX work.
        from consul_tpu.sim.scenarios import run_scenario, stream100k

        out = stream100k(n=96, steps=20, policy="pipeline")
        assert out["policy"] == "pipeline"
        with pytest.raises(ValueError, match="not a chunk-selection"):
            stream100k(n=192, steps=4, policy="pipelined")
        with pytest.raises(ValueError, match="does not support "
                                             "--policy"):
            run_scenario("probe1k", policy="pipeline")


# ---------------------------------------------------------------------------
# Sharded exactness ladder (parallel/shard.py): the outbox seam.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _sharded_runs(policy: str = "uniform"):
    """One config per policy, every plane: unsharded, D=1, D=2,
    D=2/ring — the module pays one compile per distinct program (the
    policy is trace-time static, so each policy is its own ladder)."""
    from consul_tpu.parallel import make_mesh
    from consul_tpu.parallel.shard import sharded_streamcast_scan

    cfg = dataclasses.replace(_SHARDED_CFG, policy=policy)
    key = jax.random.PRNGKey(0)  # schedule is explicit: any seed works
    steps = 12
    runs = {}
    _, runs["unsharded"] = streamcast_scan(
        streamcast_init(cfg), key, cfg, steps
    )
    for label, d, ex in (("D1", 1, "alltoall"), ("D2", 2, "alltoall"),
                         ("D2/ring", 2, "ring")):
        mesh = make_mesh(jax.devices()[:d])
        _, runs[label] = sharded_streamcast_scan(
            streamcast_init(cfg), key, cfg, steps, mesh, ex
        )
    return jax.tree_util.tree_map(np.asarray, runs)


# The acceptance ladder is pinned per policy: uniform (the original
# program) and pipeline (the paper schedule) in tier-1; rarest rides
# the slow tier (same ladder, lower-value duplicate of the seam).
_TIER1_POLICIES = ("uniform", "pipeline")


class TestSharded:
    @pytest.mark.parametrize("policy", _TIER1_POLICIES)
    def test_d1_bit_equal_to_unsharded(self, policy):
        runs = _sharded_runs(policy)
        for i, (a, b) in enumerate(zip(runs["unsharded"],
                                       runs["D1"][:-1])):
            assert (a == b).all(), f"D1 out {i}"
        assert int(runs["D1"][-1][-1]) == 0  # no outbox traffic at D=1

    @pytest.mark.parametrize("policy", _TIER1_POLICIES)
    def test_d2_equals_d1_with_zero_outbox_overflow(self, policy):
        runs = _sharded_runs(policy)
        for i, (a, b) in enumerate(zip(runs["D1"][:-1],
                                       runs["D2"][:-1])):
            assert (a == b).all(), f"D2 out {i}"
        assert int(runs["D2"][-1][-1]) == 0

    @pytest.mark.parametrize("policy", _TIER1_POLICIES)
    def test_ring_bit_equal_to_alltoall(self, policy):
        runs = _sharded_runs(policy)
        for i, (a, b) in enumerate(zip(runs["D2"], runs["D2/ring"])):
            assert (a == b).all(), f"ring out {i}"

    def test_policy_mesh_exchange_never_retrace(self):
        # Exactly one program per (policy, mesh, exchange): warm every
        # grid point (lru-cached — free when the ladder tests above
        # already ran, self-contained when this test runs standalone),
        # snapshot the compile caches, then REPLAY the whole
        # (policy × D × backend) grid — ZERO new traces allowed.
        from consul_tpu.analysis.guards import (
            check_all,
            guard_entrypoints,
        )
        from consul_tpu.parallel import make_mesh
        from consul_tpu.parallel.shard import sharded_streamcast_scan

        for policy in _TIER1_POLICIES:
            _sharded_runs(policy)
        guards = guard_entrypoints(
            entrypoints=("sharded_streamcast_scan", "streamcast_scan"),
            max_traces=0,
        )
        key = jax.random.PRNGKey(0)
        for policy in _TIER1_POLICIES:
            cfg = dataclasses.replace(_SHARDED_CFG, policy=policy)
            streamcast_scan(streamcast_init(cfg), key, cfg, 12)
            for d, ex in ((1, "alltoall"), (2, "alltoall"),
                          (2, "ring")):
                mesh = make_mesh(jax.devices()[:d])
                sharded_streamcast_scan(
                    streamcast_init(cfg), key, cfg, 12, mesh, ex
                )
        check_all(guards)

    @pytest.mark.slow
    def test_rarest_ladder(self):
        runs = _sharded_runs("rarest")
        for i, (a, b) in enumerate(zip(runs["unsharded"],
                                       runs["D1"][:-1])):
            assert (a == b).all(), f"D1 out {i}"
        for i, (a, b) in enumerate(zip(runs["D1"][:-1],
                                       runs["D2"][:-1])):
            assert (a == b).all(), f"D2 out {i}"
        for i, (a, b) in enumerate(zip(runs["D2"], runs["D2/ring"])):
            assert (a == b).all(), f"ring out {i}"

    def test_run_streamcast_mesh_reports_shard_overflow(self):
        from consul_tpu.parallel import make_mesh

        rep = run_streamcast(
            _SHARDED_CFG, steps=12, seed=0, warmup=False,
            mesh=make_mesh(jax.devices()[:2]),
        )
        assert rep.shard_overflow == 0


# ---------------------------------------------------------------------------
# Long-horizon 1M sustained load (slow tier, per the tier-1 budget
# policy for 1M-scale runs).
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["uniform", "pipeline"])
def test_streamcast_1m_sustained_load(policy):
    """The north-star shape end to end, per selection policy (the
    long-horizon policy comparison lives in the slow tier per the
    tier-1 budget discipline): 1M nodes, 4-chunk events, 8-slot
    window under Poisson load — events must fully deliver at 1M and
    the accounting identity must hold at scale."""
    import bench as _bench

    avail = _bench._available_memory_gb()
    if jax.default_backend() == "cpu" and (
            avail is None or avail < 24):
        pytest.skip(f"needs ~24GB on CPU, have {avail}")
    cfg = StreamcastConfig(
        n=1_000_000, events=64, chunks=4, window=8, fanout=4,
        chunk_budget=2, rate=0.1, names=16, loss=0.05,
        done_frac=0.999, delivery="aggregate", policy=policy,
    )
    rep = run_streamcast(cfg, steps=100, seed=0, warmup=False)
    s = rep.summary()
    assert s["events_offered"] > 0
    assert s["events_delivered"] > 0, s
    final_in_flight = (
        s["events_offered"] - s["events_delivered"]
        - s["events_quiesced"] - s["window_overflow"]
        - s["events_coalesced"]
    )
    assert 0 <= final_in_flight <= cfg.window
    assert s["t50_ms_median"] is not None
