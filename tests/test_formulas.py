"""Golden tests pinning the protocol math to the reference formulas.

Every expected value here is computed by hand from the Go sources cited in
consul_tpu/protocol/formulas.py — these are the parity anchors (SURVEY.md
§7 P1)."""

import math

import pytest

from consul_tpu.protocol import (
    LAN,
    WAN,
    LOCAL,
    push_pull_scale,
    remaining_suspicion_timeout,
    retransmit_limit,
    suspicion_timeout,
    suspicion_timeout_bounds,
    scale_with_cluster_size,
)


class TestSuspicionTimeout:
    # memberlist/util.go:64-69; BASELINE.md "Suspicion max timeout @10k
    # nodes = 120s" = 6 * 4 * log10(10k) * 1s.
    def test_ten_k_nodes_lan_bounds(self):
        # 6*4*log10(10_000)*1s = 96s max, 16s min.  (BASELINE.md's table
        # says "120s @10k" but 120s is the formula's value at 100k nodes;
        # the formula in util.go:64-69 is the ground truth.)
        lo, hi = suspicion_timeout_bounds(
            LAN.suspicion_mult, LAN.suspicion_max_timeout_mult, 10_000, 1000.0
        )
        assert hi == pytest.approx(96_000.0)
        assert lo == pytest.approx(16_000.0)

    def test_hundred_k_nodes_lan_max_is_120s(self):
        lo, hi = suspicion_timeout_bounds(
            LAN.suspicion_mult, LAN.suspicion_max_timeout_mult, 100_000, 1000.0
        )
        assert hi == pytest.approx(120_000.0)

    def test_small_clusters_clamp_node_scale_to_one(self):
        # nodeScale = max(1, log10(max(1, n))): n<=10 gives scale 1.
        for n in (0, 1, 5, 10):
            assert suspicion_timeout(4, n, 1000.0) == pytest.approx(4000.0)

    def test_fixed_point_truncation_matches_go(self):
        # Go keeps nodeScale to 1/1000 precision via int truncation:
        # n=50 -> log10(50)=1.69897 -> 1698/1000 * 4 * 1s = 6.792s... with
        # floor(1.69897*1000)=1698.
        got = suspicion_timeout(4, 50, 1000.0)
        assert got == pytest.approx(4 * 1698 * 1000.0 / 1000.0)

    def test_wan_mult(self):
        assert suspicion_timeout(WAN.suspicion_mult, 10_000, 5000.0) == (
            pytest.approx(6 * 4 * 5000.0)
        )


class TestLifeguardRemaining:
    # memberlist/suspicion.go:86-97.
    def test_zero_confirmations_is_max(self):
        assert remaining_suspicion_timeout(0, 2, 4000.0, 24_000.0) == 24_000.0

    def test_k_confirmations_reaches_min(self):
        assert remaining_suspicion_timeout(2, 2, 4000.0, 24_000.0) == 4000.0

    def test_log_scale_midpoint(self):
        # frac = log(2)/log(3) = 0.6309; raw = 24000 - .6309*20000
        got = remaining_suspicion_timeout(1, 2, 4000.0, 24_000.0)
        frac = math.log(2.0) / math.log(3.0)
        assert got == pytest.approx(math.floor(24_000.0 - frac * 20_000.0))

    def test_k_zero_is_min(self):
        assert remaining_suspicion_timeout(0, 0, 4000.0, 24_000.0) == 4000.0

    def test_clamped_to_min(self):
        assert remaining_suspicion_timeout(50, 2, 4000.0, 24_000.0) == 4000.0


class TestRetransmitLimit:
    # memberlist/util.go:72-76; LAN mult 4 -> 4*ceil(log10(n+1)).
    @pytest.mark.parametrize(
        "n,expected",
        [(0, 0), (9, 4), (10, 8), (99, 8), (100, 12), (10_000, 20),
         (100_000, 24), (1_000_000, 28)],
    )
    def test_lan_values(self, n, expected):
        assert retransmit_limit(LAN.retransmit_mult, n) == expected

    def test_local_profile_mult(self):
        assert retransmit_limit(LOCAL.retransmit_mult, 100_000) == 12


class TestPushPullScale:
    # memberlist/util.go:89-97, threshold 32.
    def test_no_scale_at_or_below_threshold(self):
        assert push_pull_scale(30_000.0, 32) == 30_000.0

    @pytest.mark.parametrize(
        "n,mult", [(33, 2), (64, 2), (65, 3), (128, 3), (129, 4)]
    )
    def test_doubling_scale(self, n, mult):
        assert push_pull_scale(30_000.0, n) == mult * 30_000.0


class TestAeScale:
    # agent/ae/ae.go:33-38, threshold 128.
    @pytest.mark.parametrize(
        "n,factor", [(1, 1), (128, 1), (129, 2), (256, 2), (257, 3), (8192, 7)]
    )
    def test_scale_factor(self, n, factor):
        assert scale_with_cluster_size(n) == factor


class TestProfiles:
    # BASELINE.md protocol constants table.
    def test_lan(self):
        assert (LAN.probe_interval_ms, LAN.probe_timeout_ms) == (1000, 500)
        assert (LAN.gossip_interval_ms, LAN.gossip_nodes) == (200, 3)
        assert LAN.push_pull_interval_ms == 30_000
        assert (LAN.suspicion_mult, LAN.suspicion_max_timeout_mult) == (4, 6)
        assert LAN.probe_interval_ticks == 5

    def test_wan(self):
        assert (WAN.probe_interval_ms, WAN.probe_timeout_ms) == (5000, 3000)
        assert (WAN.gossip_interval_ms, WAN.gossip_nodes) == (500, 4)
        assert WAN.suspicion_mult == 6

    def test_local(self):
        assert (LOCAL.probe_timeout_ms, LOCAL.indirect_checks) == (200, 1)
        assert LOCAL.retransmit_mult == 2

    def test_packet_budget(self):
        assert LAN.udp_buffer_size == 1400
        assert LAN.event_buffer_size == 512
        assert LAN.max_user_event_size == 512
