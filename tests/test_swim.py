"""SWIM failure-detection model tests: detection latency, suspicion
timing, refutation, loss behavior.  Expected timings derive from the
protocol constants (BASELINE.md) — LAN: probe every 5 ticks, suspicion
min 4*log10(n) s, max 6*min."""

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.models import (
    SwimConfig,
    swim_init,
    swim_round,
    VIEW_ALIVE,
    VIEW_DEAD,
    VIEW_SUSPECT,
)
from consul_tpu.models.swim import _lifeguard_timeout_ticks, NEVER
from consul_tpu.protocol import remaining_suspicion_timeout
from consul_tpu.sim import run_swim
import pytest


def advance(st, cfg, steps, seed=0):
    key = jax.random.PRNGKey(seed)
    for i in range(steps):
        st = swim_round(st, jax.random.fold_in(key, i), cfg)
    return st


class TestDetection:
    def test_dead_subject_gets_suspected_then_dead(self):
        # 64 nodes, LAN: expected first suspicion within a few probe
        # intervals (each of 63 probers hits f w.p. 1/63 per interval ->
        # ~63% per interval; P(no suspicion after 5 intervals) < 1%).
        cfg = SwimConfig(n=64, subject=3)
        report = run_swim(cfg, steps=400, seed=0)
        assert report.summary()["first_suspect_ms"] is not None
        assert report.summary()["first_suspect_ms"] <= 6 * 1000.0
        # Suspicion min timeout at n=64: 4*log10(64)*1s = 7.2s = 36 ticks;
        # dead must be declared after that and spread to everyone.
        assert report.summary()["first_dead_ms"] is not None
        assert report.dead_known[-1] == 63, "all 63 live nodes converge to DEAD"

    def test_no_failure_no_suspicion_without_loss(self):
        cfg = SwimConfig(n=32, subject=0, subject_alive=True, loss=0.0)
        st = advance(swim_init(cfg), cfg, 100)
        assert int(jnp.sum(st.view == VIEW_SUSPECT)) == 0
        assert int(jnp.sum(st.view == VIEW_DEAD)) == 0

    def test_detection_under_30pct_loss(self):
        # The BASELINE 1M-node config uses 30% loss WAN; at small scale,
        # detection must still complete, only slower.
        cfg = SwimConfig(n=64, subject=1, loss=0.30)
        report = run_swim(cfg, steps=600, seed=2)
        assert report.summary()["first_dead_ms"] is not None
        assert report.dead_known[-1] >= 0.99 * 63


class TestSuspicionTiming:
    def test_suspicion_not_declared_before_min_timeout(self):
        # With zero confirmations the timer stays at max; no node may
        # declare dead before min timeout ticks have elapsed from its own
        # suspicion start (state.go:1186-1199).
        cfg = SwimConfig(n=64, subject=2)
        lo, hi = cfg.suspicion_bounds_ticks
        report = run_swim(cfg, steps=400, seed=3)
        first_sus = report.first_tick(report.suspecting)
        first_dead = report.first_tick(report.dead_known)
        assert first_dead is not None
        assert first_dead - first_sus >= lo

    def test_lifeguard_matches_scalar_reference(self):
        cfg = SwimConfig(n=1000, subject=0)
        lo, hi = cfg.suspicion_bounds_ticks
        k = cfg.confirmations_k
        confs = jnp.arange(0, k + 1, dtype=jnp.int32)
        vec = np.asarray(_lifeguard_timeout_ticks(cfg, confs))
        for c in range(k + 1):
            want = remaining_suspicion_timeout(c, k, lo, hi)
            assert abs(vec[c] - want) <= 1.0, (c, vec[c], want)

    def test_confirmations_k_small_cluster_is_zero(self):
        # state.go:1191-1196: n-2 < k -> k=0.
        assert SwimConfig(n=3).confirmations_k == 0
        assert SwimConfig(n=64).confirmations_k == 2


class TestRefutation:
    def test_live_subject_refutes_false_suspicion(self):
        # Force a false suspicion by hand-marking a suspector, then let
        # the refute epidemic win: the subject hears the suspicion,
        # bumps incarnation, and all nodes return to ALIVE @ era 1
        # (state.go:1166-1170, aliveNode incarnation rules).
        cfg = SwimConfig(n=32, subject=5, subject_alive=True, loss=0.0)
        st = swim_init(cfg)
        st = st._replace(
            view=st.view.at[20].set(VIEW_SUSPECT),
            suspect_since=st.suspect_since.at[20].set(0),
            tx_suspect=st.tx_suspect.at[20].set(cfg.tx_limit),
        )
        st = advance(st, cfg, 120, seed=4)
        assert int(st.subject_inc) >= 1
        assert int(jnp.sum(st.view == VIEW_DEAD)) == 0
        assert int(jnp.sum(st.view == VIEW_SUSPECT)) == 0
        assert int(jnp.sum((st.view == VIEW_ALIVE) & (st.inc_seen == 1))) > 0

    def test_stale_dead_loses_to_refuted_alive(self):
        # A laggard whose suspicion timer expired before the refute
        # reached it broadcasts dead @ era 0; nodes already at refuted
        # ALIVE @ era 1 must ignore it (deadNode ignores lower
        # incarnations, state.go:1228-1232).
        cfg = SwimConfig(n=32, subject=5, subject_alive=True, loss=0.0)
        st = swim_init(cfg)
        st = st._replace(
            inc_seen=jnp.ones_like(st.inc_seen),  # all refuted @ era 1
            view=st.view.at[20].set(VIEW_DEAD),
            tx_dead=st.tx_dead.at[20].set(cfg.tx_limit),
        )
        st = st._replace(inc_seen=st.inc_seen.at[20].set(0))
        st = advance(st, cfg, 60, seed=11)
        dead = np.asarray(st.view == VIEW_DEAD)
        assert dead.sum() == 1 and dead[20], (
            "stale era-0 dead must not spread into an era-1 cluster"
        )

    def test_subject_never_suspects_itself(self):
        # memberlist state.go:1166-1170: a node refutes a suspicion about
        # itself and explicitly does not mark itself suspect.
        cfg = SwimConfig(n=16, subject=2, subject_alive=True, loss=0.0)
        st = swim_init(cfg)
        st = st._replace(
            view=st.view.at[9].set(VIEW_SUSPECT),
            suspect_since=st.suspect_since.at[9].set(0),
            tx_suspect=st.tx_suspect.at[9].set(cfg.tx_limit),
        )
        key = jax.random.PRNGKey(12)
        for i in range(80):
            st = swim_round(st, jax.random.fold_in(key, i), cfg)
            assert int(st.view[2]) != VIEW_SUSPECT
            assert int(st.view[2]) != VIEW_DEAD

    @pytest.mark.slow  # ~16s at CPU: long flapping horizon
    def test_flapping_recurs_at_higher_incarnations(self):
        # Under heavy loss a live subject keeps getting falsely suspected;
        # each cycle must run at a higher incarnation (suspect@k ->
        # refute@k+1 -> re-suspect@k+1 -> ...), like the reference — the
        # cluster must never wedge in a state where re-suspicion is
        # impossible (aliveNode/suspectNode incarnation rules).
        cfg = SwimConfig(n=32, subject=4, subject_alive=True, loss=0.35)
        # p(probe failure) ~ 0.27/probe; with ~31 probers one fails most
        # probe intervals, so several refute cycles happen in 600 ticks.
        st = advance(swim_init(cfg), cfg, 600, seed=13)
        assert int(st.subject_inc) >= 2, (
            "subject must have refuted repeatedly (flapping), got "
            f"{int(st.subject_inc)}"
        )

    def test_refuted_nodes_ignore_stale_suspect_msgs(self):
        cfg = SwimConfig(n=16, subject=0, subject_alive=True)
        st = swim_init(cfg)
        # Node 3 already accepted the refute (era 1)...
        st = st._replace(inc_seen=st.inc_seen.at[3].set(1))
        # ...and node 7 still gossips the stale era-0 suspicion.
        st = st._replace(
            view=st.view.at[7].set(VIEW_SUSPECT),
            suspect_since=st.suspect_since.at[7].set(0),
            tx_suspect=st.tx_suspect.at[7].set(cfg.tx_limit),
        )
        st = advance(st, cfg, 30, seed=5)
        assert int(st.view[3]) == VIEW_ALIVE, "era-1 node never regresses to era-0 suspicion"


class TestStateMachine:
    def test_dead_overrides_suspect(self):
        cfg = SwimConfig(n=16, subject=0)
        st = swim_init(cfg)
        st = st._replace(
            view=st.view.at[4].set(VIEW_SUSPECT).at[8].set(VIEW_DEAD),
            suspect_since=st.suspect_since.at[4].set(0),
            tx_dead=st.tx_dead.at[8].set(cfg.tx_limit),
        )
        st = advance(st, cfg, 40, seed=6)
        assert int(st.view[4]) == VIEW_DEAD

    def test_probe_pending_matures_after_probe_interval(self):
        cfg = SwimConfig(n=64, subject=9)
        st = swim_init(cfg)
        key = jax.random.PRNGKey(7)
        # Run exactly one probe cycle: any node with a pending probe at
        # tick 0 must not be SUSPECT before probe_interval_ticks pass.
        for i in range(cfg.probe_interval_ticks):
            st = swim_round(st, jax.random.fold_in(key, i), cfg)
            if i < cfg.probe_interval_ticks - 1:
                assert int(jnp.sum(st.view == VIEW_SUSPECT)) == 0

    def test_determinism(self):
        cfg = SwimConfig(n=128, subject=1, loss=0.2)
        r1 = run_swim(cfg, steps=100, seed=9)
        r2 = run_swim(cfg, steps=100, seed=9)
        assert np.array_equal(r1.dead_known, r2.dead_known)
        assert np.array_equal(r1.suspecting, r2.suspecting)
