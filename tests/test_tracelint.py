"""tracelint + retrace guards: the correctness tooling of the
simulation plane (consul_tpu.analysis).

Per rule: a bad-snippet fixture the rule must fire on and a clean twin
it must stay silent on.  Then the gate itself: the repo's own models/
sim/ ops/ trees lint clean, and the jitted study entrypoints hold the
single-trace contract under the runtime guards.
"""

import asyncio
import pathlib
import subprocess
import sys

import pytest

import consul_tpu
from consul_tpu.analysis import (
    RULES,
    RetraceError,
    lint_paths,
    lint_source,
    trace_guard,
)

PKG_ROOT = pathlib.Path(consul_tpu.__file__).resolve().parent
LINT_TREES = [
    PKG_ROOT / "models", PKG_ROOT / "sim", PKG_ROOT / "ops",
    PKG_ROOT / "parallel", PKG_ROOT / "sweep", PKG_ROOT / "streamcast",
    PKG_ROOT / "geo", PKG_ROOT / "obs",
]


def rules_at(src: str, rule: str = None):
    vs = lint_source(src)
    return [v.rule for v in vs if rule is None or v.rule == rule]


# ---------------------------------------------------------------------------
# Rule fixtures: each fires on its bad snippet, stays silent on the twin.
# ---------------------------------------------------------------------------

# (rule, bad snippet, clean twin)
SNIPPETS = [
    ("R1", """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
""", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return jnp.where(x > 0, x, -x)
"""),
    ("R1", """
import jax
@jax.jit
def f(x):
    assert x > 0
    return x
""", """
import jax
from typing import Optional
@jax.jit
def f(x, alive: Optional[jax.Array] = None):
    if alive is not None:
        x = x * alive
    assert isinstance(x, object)
    return x
"""),
    ("R2", """
import jax
@jax.jit
def f(x):
    return float(x)
""", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return x.astype(jnp.float32)
"""),
    ("R2", """
import jax
import numpy as np
@jax.jit
def f(x):
    return np.asarray(x).sum()
""", """
import numpy as np
def report(counts: np.ndarray):
    # Host report plane: np.asarray on host data is fine.
    return int(np.asarray(counts).sum())
"""),
    ("R3", """
import jax.numpy as jnp
def init(n: int):
    return jnp.zeros((n,))
""", """
import jax.numpy as jnp
def init(n: int):
    return jnp.zeros((n,), jnp.int32), jnp.ones((n,), dtype=jnp.float32)
"""),
    ("R3", """
import jax.numpy as jnp
def widen(x):
    return x.astype(jnp.float64)
""", """
import jax.numpy as jnp
def keep(x):
    return x.astype(jnp.float32)
"""),
    ("R3", """
import jax.numpy as jnp
def init(pairs):
    return jnp.asarray([t for _, t in pairs])
""", """
import jax.numpy as jnp
def init(pairs):
    return jnp.asarray([t for _, t in pairs], jnp.int32)
"""),
    ("R3", """
import jax.numpy as jnp
def init(xs):
    return jnp.array(xs)
""", """
import jax.numpy as jnp
def init(xs):
    return jnp.array(xs, dtype=jnp.float32)
"""),
    ("R4", """
import jax, time
@jax.jit
def f(x):
    return x + time.time()
""", """
import jax, time
def run(scan_fn, state):
    t0 = time.time()  # host timing around the jitted call: fine
    out = scan_fn(state)
    return out, time.time() - t0
"""),
    ("R4", """
import jax
import numpy as np
@jax.jit
def f(x):
    return x + np.random.rand()
""", """
import jax
@jax.jit
def f(x, key: jax.Array):
    return x + jax.random.uniform(key)
"""),
    ("R5", """
import functools, jax
@functools.partial(jax.jit, static_argnames=("missing",))
def f(x, cfg):
    return x
""", """
import functools, jax
@functools.partial(jax.jit, static_argnames=("cfg", "steps"))
def f(state, key, cfg, steps: int):
    return state
"""),
    ("R5", """
import functools, jax
@functools.partial(jax.jit, static_argnames=("cfg",))
def f(x, cfg: list):
    return x
""", """
import functools, jax
@functools.partial(jax.jit, static_argnames=("cfg",))
def f(x, cfg: tuple = ()):
    return x
"""),
    ("R6", """
import jax
@jax.jit
def f(x):
    return x[x > 0]
""", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return jnp.where(x > 0, x, 0).sum()
"""),
    ("R6", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x):
    return jnp.where(x > 0)
""", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x, idx: jax.Array):
    return x[idx]  # integer gather keeps shapes static
"""),
    ("R7", """
import jax
@jax.jit
def f(x):
    return [v + 1 for v in x]
""", """
import jax
def init(cfg: FaultSchedule):
    return [s for s, _ in cfg.pieces]  # static tuple: fine
"""),
    ("R7", """
import jax
@jax.jit
def f(x):
    total = 0.0
    for v in x:
        total = total + v
    return total
""", """
import jax
import jax.numpy as jnp
@jax.jit
def f(x, cfg: SwimConfig):
    for ramp in cfg.ramps:  # static config tuple: unrolls by design
        x = x + ramp
    return jnp.sum(x)
"""),
    ("R8", """
import jax
@jax.jit
def f(state):
    state.count = state.count + 1
    return state
""", """
import jax
@jax.jit
def f(state):
    return state._replace(count=state.count + 1)
"""),
    ("R8", """
import jax
@jax.jit
def f(x):
    x[0] = 1.0
    return x
""", """
import jax
@jax.jit
def f(x):
    return x.at[0].set(1.0)
"""),
    # R9: the kw/positional jit-cache gotcha — a static flag of a
    # module-level jitted twin passed by keyword (directly or through
    # functools.partial) mints a second compiled program alongside the
    # positional call sites.
    ("R9", """
import functools
import jax

def _impl(state, key, cfg, steps: int, telemetry: bool = False):
    return state

my_scan = jax.jit(_impl, static_argnames=("cfg", "steps", "telemetry"))

def run(state, key, cfg):
    out = my_scan(state, key, cfg, steps=8)
    part = functools.partial(my_scan, telemetry=True)
    return out, part
""", """
import jax

def _impl(state, key, cfg, steps: int, telemetry: bool = False):
    return state

my_scan = jax.jit(_impl, static_argnames=("cfg", "steps", "telemetry"))

def run(state, key, cfg):
    out = my_scan(state, key, cfg, 8)

    def part(st, k, c):  # positional statics: one program per shape
        return my_scan(st, k, c, 8, True)

    return out, part
"""),
]


class TestRules:
    @pytest.mark.parametrize(
        "rule,bad,clean",
        SNIPPETS,
        ids=[f"{r}-{i}" for i, (r, _, _) in enumerate(SNIPPETS)],
    )
    def test_fires_on_bad_silent_on_clean(self, rule, bad, clean):
        assert rule in rules_at(bad), f"{rule} must fire on its fixture"
        assert rules_at(clean, rule) == [], (
            f"{rule} must stay silent on the clean twin: "
            f"{lint_source(clean)}"
        )

    def test_every_rule_has_a_fixture(self):
        covered = {r for r, _, _ in SNIPPETS}
        assert covered == set(RULES), (
            f"rules without fixtures: {set(RULES) - covered}"
        )


class TestTracedFunctionDiscovery:
    def test_scan_body_is_traced(self):
        src = """
import jax
def body(carry, x):
    if carry > 0:
        carry = carry - 1
    return carry, x
def run(xs):
    return jax.lax.scan(body, 0, xs)
"""
        assert "R1" in rules_at(src)

    def test_annotation_seeds_tracing(self):
        src = """
import jax
def round_fn(state, key: jax.Array, cfg: SwimConfig):
    if state.tick > 0:
        return state
    return state
"""
        assert "R1" in rules_at(src)

    def test_state_annotation_alone_seeds_tracing(self):
        # Carry types end in "State" (SwimState, MembershipState...) —
        # a function with ONLY a state param is still traced code.
        src = """
def densify(state: SparseMembershipState, n: int):
    if state.tick > 0:
        return state
    return state
"""
        assert "R1" in rules_at(src)

    def test_static_config_branch_is_silent(self):
        src = """
import jax
def round_fn(state, key: jax.Array, cfg: SwimConfig):
    if cfg.delivery == "edges":
        return state
    return state
"""
        assert rules_at(src) == []

    def test_nested_function_inherits_trace(self):
        src = """
import jax
import jax.numpy as jnp
@jax.jit
def outer(x):
    def rx(era):
        if era > 0:
            return era
        return -era
    return rx(x)
"""
        assert "R1" in rules_at(src)

    def test_static_container_of_traced_values_iterates_clean(self):
        # A Python list literal holding traced arrays has a
        # trace-time-static length: iterating it is pytree plumbing
        # (membership_sparse.py's arrs pattern), not an R7 loop.
        src = """
import jax
import jax.numpy as jnp
@jax.jit
def f(x, y):
    arrs = [(x, y)]
    arrs.append((y, x))
    return jnp.concatenate([a[0] for a in arrs])
"""
        assert rules_at(src) == []

    def test_static_container_elements_stay_traced(self):
        # Iterating the container is fine (no R7), but the loop
        # variable holds tracers — branching on it still fires R1.
        src = """
import jax
@jax.jit
def f(x, y):
    arrs = [x, y]
    for a in arrs:
        if a > 0:
            return a
    return x
"""
        rules = rules_at(src)
        assert "R1" in rules and "R7" not in rules

    def test_lambda_object_is_not_traced_data(self):
        src = """
import jax
@jax.jit
def f(x):
    g = lambda v: v + 1
    if g:
        return g(x)
    return x
"""
        assert rules_at(src) == []

    def test_plain_host_function_is_untraced(self):
        src = """
import time
def timed(fn, state):
    t0 = time.perf_counter()
    if state:
        fn(state)
    return time.perf_counter() - t0
"""
        assert rules_at(src) == []


class TestSuppression:
    def test_line_comment_suppresses_named_rule(self):
        src = """
import jax.numpy as jnp
def init(n: int):
    return jnp.zeros((n,))  # tracelint: disable=R3
"""
        assert rules_at(src) == []

    def test_bare_disable_suppresses_all(self):
        src = """
import jax
@jax.jit
def f(x):
    if x > 0:  # tracelint: disable
        return float(x)  # tracelint: disable
    return x
"""
        assert rules_at(src) == []

    def test_other_rule_not_suppressed(self):
        src = """
import jax.numpy as jnp
def init(n: int):
    return jnp.zeros((n,))  # tracelint: disable=R1
"""
        assert rules_at(src) == ["R3"]

    def test_rules_filter(self):
        src = """
import jax
@jax.jit
def f(x):
    if x > 0:
        return float(x)
    return x
"""
        assert {v.rule for v in lint_source(src)} == {"R1", "R2"}
        assert {v.rule for v in lint_source(src, rules={"R2"})} == {"R2"}
        with pytest.raises(ValueError):
            lint_source(src, rules={"R99"})


class TestRepoGate:
    """The gate the CI story rides on: the simulation plane lints clean."""

    def test_models_sim_ops_are_clean(self):
        violations = lint_paths(LINT_TREES)
        assert violations == [], "\n".join(v.format() for v in violations)

    def test_sortmerge_kernel_is_covered_and_clean(self):
        # The sort-merge delivery kernel is traced code end to end; pin
        # it into the zero-violations gate by name so a future tree
        # reshuffle can't silently drop it from LINT_TREES.
        target = PKG_ROOT / "ops" / "sortmerge.py"
        assert any(
            target.is_relative_to(tree) for tree in LINT_TREES
        ), "ops/sortmerge.py left the linted trees"
        assert lint_paths([target]) == []

    def test_owned_draws_and_compaction_are_covered_and_clean(self):
        # The owned per-(round, node) randomness plane and the shared
        # budget compaction are traced code under every scan; pin both
        # into the zero-violations gate by name so a tree reshuffle
        # can't silently drop them from LINT_TREES.
        for target in (PKG_ROOT / "ops" / "sampling.py",
                       PKG_ROOT / "ops" / "compact.py"):
            assert any(
                target.is_relative_to(tree) for tree in LINT_TREES
            ), f"{target.name} left the linted trees"
            assert lint_paths([target]) == []

    def test_ring_exchange_kernel_is_covered_and_clean(self):
        # The Pallas ring-DMA exchange kernel is the newest traced
        # code; pin it into the zero-violations gate by name so a
        # future tree reshuffle can't silently drop it from LINT_TREES.
        target = PKG_ROOT / "ops" / "ring_exchange.py"
        assert any(
            target.is_relative_to(tree) for tree in LINT_TREES
        ), "ops/ring_exchange.py left the linted trees"
        assert lint_paths([target]) == []

    def test_obs_plane_is_covered_and_clean(self):
        # The in-scan telemetry plane (metric emitters run INSIDE
        # every scan body; the bridge/profile halves are host code in
        # the same tree) is traced code; pin consul_tpu/obs/ into the
        # gate BY NAME so a tree reshuffle can't silently drop the
        # newest traced subsystem from LINT_TREES.
        target = PKG_ROOT / "obs"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/obs left the linted trees"
        assert lint_paths([target]) == []

    def test_parallel_plane_is_covered_and_clean(self):
        # The sharded multi-chip plane (shard_map rounds + outbox
        # collectives) is traced code end to end; pin consul_tpu/
        # parallel/ into the gate BY NAME so a tree reshuffle can't
        # silently drop the newest traced subsystem from LINT_TREES.
        target = PKG_ROOT / "parallel"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/parallel left the linted trees"
        violations = lint_paths([target])
        assert violations == [], "\n".join(
            v.format() for v in violations
        )

    def test_streamcast_plane_is_covered_and_clean(self):
        # The pipelined event-stream subsystem (windowed chunk gossip
        # + the in-flight allocator) is traced code end to end; pin
        # consul_tpu/streamcast into the zero-violations gate BY NAME
        # so a tree reshuffle can't silently drop the newest traced
        # subsystem from LINT_TREES.
        target = PKG_ROOT / "streamcast"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/streamcast left the linted trees"
        violations = lint_paths([target])
        assert violations == [], "\n".join(
            v.format() for v in violations
        )

    def test_load_generators_are_covered_and_clean(self):
        # The adversarial-load schedule shapers (sim/load.py) are
        # traced code consumed inside every streamcast program —
        # same by-name pin as the streamcast tree.
        target = PKG_ROOT / "sim" / "load.py"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/sim/load.py left the linted trees"
        violations = lint_paths([target])
        assert violations == [], "\n".join(
            v.format() for v in violations
        )

    def test_sweep_plane_is_covered_and_clean(self):
        # The universe-sweep subsystem (vmapped batched scans + the
        # traced knob-rebuild path) is traced code; pin consul_tpu/
        # sweep/ into the gate BY NAME so a tree reshuffle can't
        # silently drop the newest traced subsystem from LINT_TREES.
        target = PKG_ROOT / "sweep"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/sweep left the linted trees"
        violations = lint_paths([target])
        assert violations == [], "\n".join(
            v.format() for v in violations
        )

    def test_geo_plane_is_covered_and_clean(self):
        # The geo/WAN subsystem (latency-delayed bandwidth-capped link
        # plane + the adaptive anti-entropy controller) is traced code
        # end to end; pin consul_tpu/geo into the zero-violations gate
        # BY NAME so a tree reshuffle can't silently drop the newest
        # traced subsystem from LINT_TREES.
        target = PKG_ROOT / "geo"
        assert any(
            target == tree or target.is_relative_to(tree)
            for tree in LINT_TREES
        ), "consul_tpu/geo left the linted trees"
        violations = lint_paths([target])
        assert violations == [], "\n".join(
            v.format() for v in violations
        )

    def test_cli_lint_clean_exits_zero(self):
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["lint", *[str(p) for p in LINT_TREES]]
        )
        assert asyncio.run(args.fn(args)) == 0

    def test_cli_lint_violations_exit_nonzero(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(["lint", str(bad)])
        assert asyncio.run(args.fn(args)) == 1
        out = capsys.readouterr().out
        assert f"{bad}:4:" in out and "R1" in out

    def test_cli_lint_list_rules(self, capsys):
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(["lint", "--list-rules"])
        assert asyncio.run(args.fn(args)) == 0
        out = capsys.readouterr().out
        for rule in RULES:
            assert rule in out

    def test_cli_lint_format_json(self, tmp_path, capsys):
        # The machine-readable contract CI and bench.py consume.
        import json

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax.numpy as jnp\n"
            "def init(n: int):\n"
            "    return jnp.zeros((n,))\n"
        )
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(
            ["lint", str(bad), "--format", "json"]
        )
        assert asyncio.run(args.fn(args)) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["files"] == 1
        assert [v["rule"] for v in payload["violations"]] == ["R3"]
        assert payload["violations"][0]["line"] == 3

        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        args = build_parser().parse_args(
            ["lint", str(clean), "--format", "json"]
        )
        assert asyncio.run(args.fn(args)) == 0
        assert json.loads(capsys.readouterr().out)["violations"] == []

    def test_module_entrypoint(self):
        # python -m consul_tpu.analysis.tracelint defaults to the
        # simulation plane and needs no JAX (accelerator-free lint).
        proc = subprocess.run(
            [sys.executable, "-m", "consul_tpu.analysis.tracelint"],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Runtime retrace guards.
# ---------------------------------------------------------------------------


class TestTraceGuard:
    def test_guard_counts_and_passes_single_trace(self):
        import jax.numpy as jnp

        calls = []

        def f(x):
            calls.append(1)
            return x * 2

        g = trace_guard(f)
        g(jnp.ones((4,), jnp.float32))
        g(jnp.zeros((4,), jnp.float32))
        assert g.traces == 1 and g.calls == 2
        assert len(calls) == 1, "second call must reuse the program"

    def test_guard_fails_deliberate_retrace(self):
        import jax.numpy as jnp

        g = trace_guard(lambda x: x + 1, name="retracer")
        g(jnp.ones((4,), jnp.float32))
        with pytest.raises(RetraceError, match="retracer"):
            # New shape -> new static signature -> second program.
            g(jnp.ones((5,), jnp.float32))

    def test_guard_budget_two_allows_warmup_pair(self):
        import jax.numpy as jnp

        g = trace_guard(lambda x: x + 1, max_traces=2)
        g(jnp.ones((4,), jnp.float32))
        g(jnp.ones((5,), jnp.float32))
        assert g.traces == 2

    def test_reset_resnapshots(self):
        import jax.numpy as jnp

        g = trace_guard(lambda x: x * 3)
        g(jnp.ones((4,), jnp.float32))
        g.reset()
        assert g.traces == 0
        g(jnp.ones((4,), jnp.float32))
        g.check()

    def test_rejects_unjittable_wrapper(self):
        with pytest.raises(TypeError):
            from consul_tpu.analysis.guards import TraceGuard

            TraceGuard(print)

    @pytest.mark.single_trace(
        entrypoints=("broadcast_scan", "swim_scan", "lifeguard_scan")
    )
    def test_engine_entrypoints_hold_single_trace(self, retrace_guard):
        # The named scans must run a study end to end on ONE program
        # each — the marker re-verifies at teardown.
        from consul_tpu.models import LifeguardConfig
        from consul_tpu.models.broadcast import BroadcastConfig
        from consul_tpu.models.swim import SwimConfig
        from consul_tpu.sim.engine import (
            run_broadcast,
            run_lifeguard,
            run_swim,
        )

        bcfg = BroadcastConfig(n=64)
        scfg = SwimConfig(n=64, subject=1, loss=0.05)
        lcfg = LifeguardConfig(n=64, subject=1, subject_alive=True)
        for seed in (0, 1):
            run_broadcast(bcfg, steps=8, seed=seed, warmup=False)
            run_swim(scfg, steps=8, seed=seed, warmup=False)
            run_lifeguard(lcfg, steps=8, seed=seed, warmup=False)
        for name in ("broadcast_scan", "swim_scan", "lifeguard_scan"):
            assert retrace_guard[name].traces <= 1

    @pytest.mark.single_trace(
        entrypoints=("sharded_broadcast_scan",), max_traces=4
    )
    def test_sharded_entrypoint_one_trace_per_mesh(self, retrace_guard):
        # Resharding discipline: a distinct (mesh, exchange backend)
        # pair is a distinct static signature (one program per combo),
        # but repeating a combo already compiled must NOT retrace —
        # D ∈ {1, 2} x {alltoall, ring} on eight runs stays at exactly
        # four programs (in particular the exchange-backend flag never
        # retraces per round or per call).
        from consul_tpu.models.broadcast import (
            BroadcastConfig,
            broadcast_init,
        )
        from consul_tpu.parallel import make_mesh
        from consul_tpu.sim.engine import sharded_broadcast_scan

        import jax

        cfg = BroadcastConfig(n=64, fanout=3)
        key = jax.random.PRNGKey(0)
        for _ in range(2):
            for d in (1, 2):
                mesh = make_mesh(jax.devices()[:d])
                for exchange in ("alltoall", "ring"):
                    sharded_broadcast_scan(
                        broadcast_init(cfg), key, cfg, 4, mesh, exchange
                    )
        assert retrace_guard["sharded_broadcast_scan"].traces == 4

    def test_sweep_builder_one_program_per_entrypoint_u(self):
        # The universe-sweep discipline (consul_tpu/sweep): make_sweep
        # compiles exactly ONE program per (entrypoint, U) across
        # repeated calls — knob VALUES and seeds never retrace, only a
        # new U (or entrypoint) does.
        from consul_tpu.analysis.guards import TraceGuard
        from consul_tpu.models.swim import SwimConfig
        from consul_tpu.sweep import Universe
        from consul_tpu.sweep.universe import make_sweep, stacked_init

        cfg = SwimConfig(n=48, subject=1, loss=0.05)
        guards = {
            u: TraceGuard(make_sweep("swim", u), max_traces=1,
                          name=f"sweep_swim_U{u}")
            for u in (1, 4)
        }
        for seed in (0, 1):
            for loss_base in (0.0, 0.3):
                for u in (1, 4):
                    uni = Universe(
                        entrypoint="swim", cfg=cfg, steps=3,
                        seeds=tuple(range(seed, seed + u)),
                        knobs=("loss",),
                        values=(tuple(loss_base + 0.01 * i
                                      for i in range(u)),),
                    )
                    make_sweep("swim", u)(
                        stacked_init(uni), uni.keys(),
                        uni.knob_arrays(), cfg, 3, uni.knobs, (),
                    )
        for u, guard in guards.items():
            guard.check()
            assert guard.traces == 1, (u, guard.traces)
        # make_sweep itself is the cache: same wrapper per (e, U).
        assert make_sweep("swim", 4) is guards[4]._fn

    @pytest.mark.single_trace(entrypoints=("sparse_membership_scan",))
    def test_sparse_entrypoint_holds_single_trace(self, retrace_guard):
        # The rewired sort-merge delivery path must still compile the
        # whole sparse study to ONE program across seeds.
        from consul_tpu.models import SparseMembershipConfig
        from consul_tpu.models.membership import MembershipConfig
        from consul_tpu.sim.engine import run_membership_sparse

        cfg = SparseMembershipConfig(
            base=MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),)),
            k_slots=8,
        )
        for seed in (0, 1):
            run_membership_sparse(cfg, steps=6, seed=seed, warmup=False)
        assert retrace_guard["sparse_membership_scan"].traces <= 1
