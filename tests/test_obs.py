"""The in-scan telemetry plane (consul_tpu/obs).

Contracts pinned here, per ISSUE 10:

  * telemetry=off is the EXACT current program (no retrace when the
    flag is passed explicitly; exactly one extra program per
    entrypoint when on) and telemetry=on is bit-equal on every
    existing output — per model, small-n;
  * the sharded twins emit the identical [steps, M] trace through one
    integer psum: D == 1 bit-equal to unsharded, D == 2 == D == 1;
  * sweeps stack the trace to [U, steps, M] for free through vmap,
    U == 1 bit-equal to the unbatched trace;
  * the host bridge replays a trace into telemetry.Metrics under the
    reference metric names (go-metrics DisplayMetrics shape:
    Labels on gauges, Stddev on samples);
  * the XLA profile harness (obs/profile.py) reads cost_analysis /
    memory_analysis and the trace/compile/execute wall split.
"""

import functools

import numpy as np
import pytest

import jax

from consul_tpu.geo.model import GeoConfig
from consul_tpu.models.broadcast import BroadcastConfig
from consul_tpu.models.lifeguard import LifeguardConfig
from consul_tpu.models.membership import MembershipConfig
from consul_tpu.models.membership_sparse import SparseMembershipConfig
from consul_tpu.models.swim import SwimConfig
from consul_tpu.obs import (
    METRIC_SPECS,
    bridge_report,
    bridge_trace,
    metric_count,
    metric_names,
    profile_program,
    profile_registry,
    sum_mask,
)
from consul_tpu.sim.engine import (
    run_broadcast,
    run_geo,
    run_lifeguard,
    run_membership,
    run_membership_sparse,
    run_streamcast,
    run_swim,
    run_sweep,
)
from consul_tpu.streamcast.model import StreamcastConfig
from consul_tpu.sweep import Universe
from consul_tpu.telemetry import Metrics

STEPS = 8

# The registry-small shapes (sim/engine.py jaxlint_registry): reusing
# them keeps this module's compiles shared with the rest of the suite.
BCFG = BroadcastConfig(n=64, fanout=3, delivery="edges")
MCFG = MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),))
SCFG = SparseMembershipConfig(base=MCFG, k_slots=8)
SWCFG = SwimConfig(n=64, subject=1, loss=0.05)
LGCFG = LifeguardConfig(n=64, subject=1, subject_alive=True)
STCFG = StreamcastConfig(n=64, events=12, chunks=2, window=4, fanout=3,
                         chunk_budget=2, rate=0.4, names=3, loss=0.05,
                         delivery="edges")
GECFG = GeoConfig(n=64, segments=8, bridges_per_segment=2, events=4,
                  wan_window=4, wan_msg_bytes=100,
                  wan_capacity_bytes=800.0, wan_queue_bytes=1600.0,
                  ae_batch=4, loss_wan=0.05)

FAMILIES = ("swim", "lifeguard", "broadcast", "membership", "sparse",
            "streamcast", "geo")

# Dedicated shapes (n=32, used nowhere else in this module) for the
# program-identity pins: the jit cache must be COLD there — the
# "exactly one extra program" count would read 0 if an earlier test
# had already compiled the telemetry=on program for the shared
# configs.
SWCFG_ID = SwimConfig(n=32, subject=1, loss=0.05)
BCFG_ID = BroadcastConfig(n=32, fanout=3, delivery="edges")


def _report(out):
    """Normalize the run_* results (sparse returns (report, overflow))."""
    return out[0] if isinstance(out, tuple) else out


@functools.lru_cache(maxsize=None)
def study(family: str, telemetry: bool = False, devices: int = 0):
    """One compiled-and-executed study per distinct program, shared
    across every test in this module."""
    mesh = None
    if devices:
        from consul_tpu.parallel import make_mesh

        mesh = make_mesh(jax.devices()[:devices])
    kw = dict(steps=STEPS, seed=0, warmup=False, telemetry=telemetry)
    if family == "swim":
        assert not devices
        return run_swim(SWCFG, **kw)
    if family == "lifeguard":
        assert not devices
        return run_lifeguard(LGCFG, **kw)
    if family == "broadcast":
        return run_broadcast(BCFG, mesh=mesh, **kw)
    if family == "membership":
        return run_membership(MCFG, track=(3,), mesh=mesh, **kw)
    if family == "sparse":
        return run_membership_sparse(SCFG, track=(3,), mesh=mesh, **kw)
    if family == "streamcast":
        return run_streamcast(STCFG, mesh=mesh, **kw)
    if family == "geo":
        return run_geo(GECFG, mesh=mesh, **kw)
    raise AssertionError(family)


# ---------------------------------------------------------------------------
# The static registry.
# ---------------------------------------------------------------------------


class TestMetricSpecs:
    def test_every_scan_family_registered(self):
        assert set(METRIC_SPECS) == set(FAMILIES)

    def test_names_ordered_unique_and_consul_shaped(self):
        for family in FAMILIES:
            names = metric_names(family)
            assert names, family
            assert len(set(names)) == len(names), family
            for n in names:
                root = n.split(".", 1)[0]
                assert root in ("memberlist", "serf", "consul"), n

    def test_issue_named_series_present(self):
        # The four series ISSUE 10 names explicitly.
        assert "memberlist.msg.suspect" in metric_names("swim")
        assert "serf.queue.Event" in metric_names("broadcast")
        assert ("consul.streamcast.window_overflow"
                in metric_names("streamcast"))
        assert "consul.geo.wan.admitted" in metric_names("geo")

    def test_kinds_and_reduce_modes(self):
        for family, specs in METRIC_SPECS.items():
            assert metric_count(family) == len(specs)
            assert len(sum_mask(family)) == len(specs)
            for s in specs:
                assert s.kind in ("counter", "gauge")
                assert s.reduce in ("sum", "rep")

    def test_unknown_family_rejected_loudly(self):
        with pytest.raises(ValueError, match="no metric specs"):
            metric_names("multidc")


# ---------------------------------------------------------------------------
# Bit-equality + program identity (the retrace guard).
# ---------------------------------------------------------------------------


def _existing_outputs(report):
    """The pre-telemetry output arrays of a run_* report."""
    d = {}
    for k, v in vars(report).items():
        if k in ("metrics_trace", "metric_names", "wall_s"):
            continue
        if isinstance(v, np.ndarray):
            d[k] = v
    return d


class TestBitEquality:
    # The telemetry == off identity is a declared EQUIV_PAIR for every
    # family, witnessed in tier-1 by the equivlint ladder
    # (tests/test_equivlint.py TestPairGate) — the full-size runtime
    # duplicate rides the slow tier.
    @pytest.mark.slow
    @pytest.mark.parametrize("family", FAMILIES)
    def test_telemetry_on_is_bit_equal_on_every_output(self, family):
        off = _report(study(family, False))
        on = _report(study(family, True))
        outs_off = _existing_outputs(off)
        outs_on = _existing_outputs(on)
        assert set(outs_off) == set(outs_on) and outs_off
        for k in outs_off:
            assert (outs_off[k] == outs_on[k]).all(), (family, k)
        if family == "sparse":
            assert study(family, False)[1] == study(family, True)[1]

    @pytest.mark.parametrize("family", FAMILIES)
    def test_trace_shape_dtype_and_integrality(self, family):
        rep = _report(study(family, True))
        trace = rep.metrics_trace
        assert trace.shape == (STEPS, metric_count(family))
        assert trace.dtype == np.float32
        # Every emitter reduces to an int32 count — the exactness
        # contract the sharded psum assembly relies on.
        assert (trace == np.round(trace)).all()
        assert rep.metric_names == metric_names(family)
        assert _report(study(family, False)).metrics_trace is None


class TestProgramIdentity:
    """telemetry is positional-static: the off call shape (flag
    OMITTED — the run_* seams' discipline, since jit caches omitted
    defaults and explicit positionals separately, the standing
    kw/positional gotcha) never retraces, and telemetry=True compiles
    exactly ONE extra program per entrypoint with reruns cached."""

    CASES = [
        ("swim_scan", "swim_scan",
         lambda scan, st, k: scan(st, k, SWCFG_ID, STEPS)),
        ("broadcast_scan", "broadcast_scan",
         lambda scan, st, k: scan(st, k, BCFG_ID, STEPS)),
    ]

    @pytest.mark.parametrize("name,entry,call",
                             CASES, ids=[c[0] for c in CASES])
    def test_off_identity_and_one_extra_program_when_on(
            self, name, entry, call):
        from consul_tpu.analysis.guards import TraceGuard
        from consul_tpu.models.broadcast import broadcast_init
        from consul_tpu.models.swim import swim_init
        from consul_tpu.sim import engine

        scan = getattr(engine, entry)
        init = {
            "swim_scan": lambda: swim_init(SWCFG_ID),
            "broadcast_scan": lambda: broadcast_init(BCFG_ID),
        }[entry]
        key = jax.random.PRNGKey(0)
        call(scan, init(), key)  # the off program (may be cache-warm)
        guard = TraceGuard(scan, max_traces=0)
        # Repeated off calls: zero new programs — the flag's existence
        # did not change the off program's cache identity.
        call(scan, init(), key)
        call(scan, init(), key)
        guard.check()
        on_guard = TraceGuard(scan, max_traces=1)
        call(lambda st, k, c, s: scan(st, k, c, s, True), init(), key)
        call(lambda st, k, c, s: scan(st, k, c, s, True), init(), key)
        on_guard.check()
        assert on_guard.traces == 1  # exactly one extra program


# ---------------------------------------------------------------------------
# Sharded twins: the one-psum trace assembly.
# ---------------------------------------------------------------------------


# One family stays tier-1: the D2 == D1 metrics-trace claim is NOT an
# equivlint pair (the ladder pins D1 == unsharded and ring == alltoall,
# not cross-D trace assembly), so broadcast — the cheapest compile —
# keeps the reduce_over_mesh path exercised.  The rest ride the slow
# tier: each parametrization compiles two fresh sharded programs and
# exercises the same assembly, and the equivlint ladder witnesses every
# family's sharded outputs in tier-1.
SHARDED = ("broadcast",)
SHARDED_SLOW = ("streamcast", "membership", "sparse", "geo")


class TestShardedParity:
    @pytest.mark.parametrize("family", SHARDED)
    def test_d1_bit_equal_and_d2_equals_d1(self, family):
        self._check(family)

    @pytest.mark.slow
    @pytest.mark.parametrize("family", SHARDED_SLOW)
    def test_d1_bit_equal_and_d2_equals_d1_slow_tier(self, family):
        self._check(family)

    def _check(self, family):
        un = _report(study(family, True))
        d1 = _report(study(family, True, devices=1))
        d2 = _report(study(family, True, devices=2))
        assert (un.metrics_trace == d1.metrics_trace).all(), family
        assert (d1.metrics_trace == d2.metrics_trace).all(), family
        # The existing outputs ride along bit-equal too.
        for k, v in _existing_outputs(un).items():
            assert (v == _existing_outputs(d2)[k]).all(), (family, k)


# ---------------------------------------------------------------------------
# Sweep plane: [U, steps, M] through vmap.
# ---------------------------------------------------------------------------


class TestSweepTelemetry:
    def test_u1_bit_equal_to_unbatched_trace(self):
        u1 = Universe(entrypoint="swim", cfg=SWCFG, steps=STEPS,
                      seeds=(0,))
        rep = run_sweep(u1, warmup=False, telemetry=True)
        un = study("swim", True)
        assert rep.metrics_trace.shape == (1, STEPS,
                                           metric_count("swim"))
        assert (rep.metrics_trace[0] == un.metrics_trace).all()
        assert rep.metric_names == metric_names("swim")

    def test_u2_stacks_and_off_is_unchanged(self):
        u2 = Universe(entrypoint="broadcast", cfg=BCFG, steps=STEPS,
                      seeds=(0, 1))
        on = run_sweep(u2, warmup=False, telemetry=True)
        off = run_sweep(u2, warmup=False)
        assert on.metrics_trace.shape == (2, STEPS,
                                          metric_count("broadcast"))
        assert off.metrics_trace is None
        # Existing sweep metrics bit-equal with telemetry on.
        for name, v in off.metrics.items():
            assert (np.asarray(v) == np.asarray(on.metrics[name])).all()


# ---------------------------------------------------------------------------
# Host bridge: trace -> telemetry.Metrics under the reference names.
# ---------------------------------------------------------------------------


class TestBridge:
    def test_counter_and_gauge_semantics(self):
        rep = _report(study("broadcast", True))
        sink = bridge_report("broadcast", rep, Metrics())
        snap = sink.snapshot()
        trace = rep.metrics_trace
        names = metric_names("broadcast")
        counters = {c["Name"]: c for c in snap["Counters"]}
        gauges = {g["Name"]: g for g in snap["Gauges"]}
        for j, spec in enumerate(METRIC_SPECS["broadcast"]):
            col = trace[:, j]
            if spec.kind == "counter":
                assert counters[spec.name]["Count"] == STEPS
                assert counters[spec.name]["Sum"] == pytest.approx(
                    float(col.sum())
                )
            else:
                assert gauges[spec.name]["Value"] == float(col[-1])
        assert set(counters) | set(gauges) == set(names)

    def test_snapshot_is_the_agent_metrics_shape(self):
        snap = bridge_report(
            "swim", study("swim", True), Metrics()
        ).snapshot()
        assert set(snap) == {"Timestamp", "Gauges", "Counters",
                             "Samples"}
        for g in snap["Gauges"]:
            assert set(g) == {"Name", "Value", "Labels"}
        for c in snap["Counters"]:
            assert {"Name", "Count", "Sum", "Min", "Max", "Mean",
                    "Stddev", "Labels"} <= set(c)

    def test_stddev_matches_sample_formula(self):
        m = Metrics()
        vals = [1.0, 2.0, 4.0, 8.0]
        for v in vals:
            m.add_sample("x", v)
        samples = {s["Name"]: s for s in m.snapshot()["Samples"]}
        assert samples["x"]["Stddev"] == pytest.approx(
            float(np.std(vals, ddof=1)), abs=1e-6
        )
        m2 = Metrics()
        m2.add_sample("one", 3.0)
        assert m2.snapshot()["Samples"][0]["Stddev"] == 0.0

    def test_sweep_trace_bridges_per_universe_with_labels(self):
        # The PR-10 leftover closed: a whole-sweep [U, steps, M] trace
        # bridges in ONE call, universe index as a metric Label, each
        # universe its own series under the reference names.
        u2 = Universe(entrypoint="broadcast", cfg=BCFG, steps=STEPS,
                      seeds=(0, 1))
        rep = run_sweep(u2, warmup=False, telemetry=True)
        sink = bridge_report("broadcast", rep, Metrics())
        snap = sink.snapshot()
        trace = rep.metrics_trace
        for u in (0, 1):
            labels = {"universe": str(u)}
            for j, spec in enumerate(METRIC_SPECS["broadcast"]):
                col = trace[u, :, j]
                if spec.kind == "counter":
                    assert sink.get_counter(
                        spec.name, labels=labels
                    ) == STEPS
                else:
                    assert sink.get_gauge(
                        spec.name, labels=labels
                    ) == float(col[-1])
        # The snapshot carries the Labels maps (DisplayMetrics shape).
        labelled = [g for g in snap["Gauges"]
                    if g["Labels"].get("universe") in ("0", "1")]
        assert labelled
        # Per-universe series are DISTINCT when the universes diverge.
        g0 = {g["Name"]: g["Value"] for g in snap["Gauges"]
              if g["Labels"].get("universe") == "0"}
        g1 = {g["Name"]: g["Value"] for g in snap["Gauges"]
              if g["Labels"].get("universe") == "1"}
        assert set(g0) == set(g1)

    def test_composed_sweep_trace_bridges_per_universe(self):
        # The PR-13 leftover closed: a COMPOSED (D > 1) sweep's psum'd
        # [U, steps, M] trace gets the same universe-Label treatment —
        # the sharded twins assemble the identical trace via one
        # integer psum, so the composed bridge is the unsharded bridge
        # on the same shapes, universe index as a metric Label.
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        from consul_tpu.parallel import make_mesh

        mesh = make_mesh(jax.devices()[:2])
        u2 = Universe(entrypoint="broadcast", cfg=BCFG, steps=STEPS,
                      seeds=(0, 1))
        rep = run_sweep(u2, warmup=False, telemetry=True, mesh=mesh)
        assert rep.metrics_trace.shape[0] == 2
        assert rep.devices == 2
        sink = bridge_report("broadcast", rep, Metrics())
        for u in (0, 1):
            labels = {"universe": str(u)}
            for j, spec in enumerate(METRIC_SPECS["broadcast"]):
                col = rep.metrics_trace[u, :, j]
                if spec.kind == "counter":
                    assert sink.get_counter(
                        spec.name, labels=labels
                    ) == STEPS
                else:
                    assert sink.get_gauge(
                        spec.name, labels=labels
                    ) == float(col[-1])
        # Composed == unsharded sweep trace (U=… x D=2 parity): the
        # psum'd assembly reproduces the unsharded trace bit-for-bit.
        rep_u = run_sweep(u2, warmup=False, telemetry=True)
        assert np.array_equal(rep.metrics_trace, rep_u.metrics_trace)

    def test_bad_trace_and_missing_trace_rejected_loudly(self):
        with pytest.raises(ValueError, match="expected a"):
            bridge_trace("swim", np.zeros((4, 3), np.float32),
                         Metrics())
        with pytest.raises(ValueError, match="telemetry=True"):
            bridge_report("swim", study("swim", False), Metrics())
        with pytest.raises(ValueError, match="no metric specs"):
            bridge_trace("multidc", np.zeros((4, 3), np.float32),
                         Metrics())

    def test_scenario_metrics_snapshot(self):
        # cli sim --metrics rides run_scenario(telemetry=True): the
        # preset returns the bridged snapshot; presets without the
        # seam reject it loudly.
        from consul_tpu.sim.scenarios import run_scenario

        out = run_scenario("dev3", telemetry=True)
        assert out["metrics"]["Counters"] or out["metrics"]["Gauges"]
        names = {c["Name"] for c in out["metrics"]["Counters"]}
        assert "memberlist.gossip" in names
        with pytest.raises(ValueError, match="--metrics"):
            run_scenario("suspect1m", telemetry=True)


# ---------------------------------------------------------------------------
# XLA cost/profile harness.
# ---------------------------------------------------------------------------


def _tiny_registry():
    from consul_tpu.sim.engine import jaxlint_registry

    regs = jaxlint_registry(include=("small",), sharded_devices=())
    return {"broadcast@small": regs["broadcast@small"],
            "swim@small": regs["swim@small"]}


class TestProfileHarness:
    def test_cost_and_walls(self):
        prog = _tiny_registry()["broadcast@small"]
        p = profile_program(prog, execute=True)
        assert p.trace_s > 0 and p.compile_s > 0
        assert p.execute_s is not None and p.execute_s > 0
        # CPU XLA implements both analyses; accept None only as an
        # explicit backend gap, never a crash.
        if p.flops is not None:
            assert p.flops > 0
        if p.bytes_accessed is not None:
            assert p.bytes_accessed > 0
        if p.output_bytes is not None:
            assert p.output_bytes > 0
        json_row = p.to_json()
        assert json_row["name"] == "broadcast@small"

    def test_execute_budget_skips_loudly(self):
        profiles = profile_registry(
            _tiny_registry(), execute=True, execute_budget_s=1e-9
        )
        assert profiles[0].execute_s is not None
        assert profiles[1].execute_s is None
        assert "exhausted" in profiles[1].execute_skipped

    def test_deadline_skips_everything_loudly(self):
        import time

        profiles = profile_registry(
            _tiny_registry(), deadline=time.monotonic() - 1.0
        )
        assert all(
            p.execute_skipped == "section budget exhausted"
            for p in profiles
        )
