"""ADS-shaped xDS export golden tests.

Parity model: ``agent/xds/golden_test.go`` + the per-family
``*_test.go`` tables — generated clusters/endpoints/listeners/routes
for a representative chain-split snapshot are pinned structure-for-
structure against JSON golden files in ``tests/golden/``.  Regenerate
with ``GOLDEN_UPDATE=1 pytest tests/test_xds.py``.
"""

import json
import os
import pathlib

import pytest

from helpers import requires_crypto

from consul_tpu.connect.discoverychain import compile_chain
from consul_tpu.connect.xds import (
    CLUSTER_TYPE,
    ENDPOINT_TYPE,
    LISTENER_TYPE,
    ROUTE_TYPE,
    ads_snapshot,
    clusters_from_snapshot,
    endpoints_from_snapshot,
    listeners_from_snapshot,
    rbac_rules_from_intentions,
    routes_from_snapshot,
)

GOLDEN_DIR = pathlib.Path(__file__).parent / "golden"


def golden(name: str, got) -> None:
    """golden_test.go golden(): compare (or update) the pinned file."""
    path = GOLDEN_DIR / f"{name}.golden.json"
    text = json.dumps(got, indent=2, sort_keys=True) + "\n"
    if os.environ.get("GOLDEN_UPDATE"):
        GOLDEN_DIR.mkdir(exist_ok=True)
        path.write_text(text)
    assert path.exists(), f"golden file missing: {path} " \
        "(run GOLDEN_UPDATE=1 pytest tests/test_xds.py)"
    assert json.loads(path.read_text()) == json.loads(text), \
        f"{name} diverged from golden (GOLDEN_UPDATE=1 to regenerate)"


def _chain_split_snapshot() -> dict:
    """A proxy snapshot for 'web' with one upstream 'db' whose chain is
    an http router + 90/10 splitter over v1/v2 subsets — the
    'chain-and-splitter' class of case from golden_test.go testdata."""
    entries = {
        "services": {"db": {"kind": "service-defaults", "name": "db",
                            "protocol": "http"}},
        "routers": {"db": {
            "kind": "service-router", "name": "db",
            "routes": [
                {"match": {"http": {"path_prefix": "/admin"}},
                 "destination": {"service": "db", "service_subset": "v2"}},
            ],
        }},
        "splitters": {"db": {
            "kind": "service-splitter", "name": "db",
            "splits": [
                {"weight": 90, "service_subset": "v1"},
                {"weight": 10, "service_subset": "v2"},
            ],
        }},
        "resolvers": {"db": {
            "kind": "service-resolver", "name": "db",
            "subsets": {"v1": {"filter": 'Service.Meta.version == "1"'},
                        "v2": {"filter": 'Service.Meta.version == "2"'}},
        }},
        "global_proxy": None,
    }
    chain = compile_chain("db", "dc1", entries)
    instances = {
        tid: [{"address": f"10.0.0.{i + 1}", "port": 8080 + i,
               "proxy_id": f"db-{tid}-{i}", "node": f"n{i}"}
              for i in range(2)]
        for tid in chain["targets"]
    }
    return {
        "proxy_id": "web-proxy",
        "destination_service": "web",
        "datacenter": "dc1",
        "local_service_address": "127.0.0.1:9090",
        "roots": [{"id": "root-1", "active": True,
                   "trust_domain": "11111111-2222.consul",
                   "root_cert_pem": "-----BEGIN CERT-----fake\n"}],
        "active_root_id": "root-1",
        "leaf": {"cert_pem": "-----BEGIN CERT-----leaf\n",
                 "private_key_pem": "-----BEGIN KEY-----leaf\n",
                 "root_id": "root-1"},
        "intentions": [
            {"source": "api", "action": "allow"},
            {"source": "*", "action": "deny"},
        ],
        "default_allow": True,
        "upstreams": {"db": {
            "chain": chain,
            "instances": instances,
            "local_bind_port": 5000,
            "local_bind_address": "127.0.0.1",
            "datacenter": "",
        }},
    }


class TestGolden:
    def test_clusters_golden(self):
        golden("clusters_chain_split",
               clusters_from_snapshot(_chain_split_snapshot()))

    def test_endpoints_golden(self):
        golden("endpoints_chain_split",
               endpoints_from_snapshot(_chain_split_snapshot()))

    def test_listeners_golden(self):
        golden("listeners_chain_split",
               listeners_from_snapshot(_chain_split_snapshot(),
                                       public_port=20000))

    def test_routes_golden(self):
        golden("routes_chain_split",
               routes_from_snapshot(_chain_split_snapshot()))


class TestStructure:
    def test_cluster_names_are_snis_and_local_app_present(self):
        snap = _chain_split_snapshot()
        clusters = clusters_from_snapshot(snap)
        names = {c["name"] for c in clusters}
        assert "local_app" in names
        assert "v1.db.default.dc1.internal.11111111-2222.consul" in names
        assert "v2.db.default.dc1.internal.11111111-2222.consul" in names
        for c in clusters:
            if c["name"] == "local_app":
                continue
            assert c["type"] == "EDS"
            assert c["transport_socket"]["typed_config"]["sni"] == c["name"]

    def test_endpoints_cover_every_cluster(self):
        snap = _chain_split_snapshot()
        cluster_names = {c["name"] for c in clusters_from_snapshot(snap)
                         if c["name"] != "local_app"}
        las = endpoints_from_snapshot(snap)
        assert {la["cluster_name"] for la in las} == cluster_names
        for la in las:
            eps = la["endpoints"][0]["lb_endpoints"]
            assert len(eps) == 2
            assert eps[0]["endpoint"]["address"]["socket_address"][
                "port_value"] == 8080

    def test_route_config_splits_to_weighted_clusters(self):
        snap = _chain_split_snapshot()
        routes = routes_from_snapshot(snap)
        assert len(routes) == 1
        vh = routes[0]["virtual_hosts"][0]
        # Router: /admin → v2 exact cluster; catch-all → 90/10 split.
        admin = vh["routes"][0]
        assert admin["match"]["prefix"] == "/admin"
        assert admin["route"]["cluster"].startswith("v2.db.")
        catchall = vh["routes"][-1]
        wc = catchall["route"]["weighted_clusters"]
        weights = {c["name"].split(".")[0]: c["weight"]
                   for c in wc["clusters"]}
        assert weights == {"v1": 9000, "v2": 1000}
        assert wc["total_weight"] == 10000

    def test_listeners_public_rbac_and_outbound_rds(self):
        snap = _chain_split_snapshot()
        listeners = listeners_from_snapshot(snap, public_port=20000)
        public = listeners[0]
        assert public["name"].startswith("public_listener:")
        chain0 = public["filter_chains"][0]
        assert chain0["tls_context"]["require_client_certificate"] is True
        assert chain0["filters"][0]["name"] == "envoy.filters.network.rbac"
        # http chain → hcm with rds pointing at the route config.
        outbound = listeners[1]
        hcm = outbound["filter_chains"][0]["filters"][0]
        assert hcm["name"] == "envoy.http_connection_manager"
        assert hcm["typed_config"]["rds"]["route_config_name"] == "db"

    def test_ads_snapshot_families(self):
        snap = _chain_split_snapshot()
        ads = ads_snapshot(snap, 7, public_port=20000)
        assert ads["version_info"] == "7"
        assert set(ads["resources"]) == {
            CLUSTER_TYPE, ENDPOINT_TYPE, LISTENER_TYPE, ROUTE_TYPE}


class TestRBAC:
    TD = "td.consul"

    def test_default_allow_denies_listed_sources(self):
        rules = rbac_rules_from_intentions(
            [{"source": "evil", "action": "deny"}], True, self.TD)
        assert rules["action"] == "DENY"
        assert set(rules["policies"]) == {"consul-intentions-layer4-evil"}
        principal = rules["policies"][
            "consul-intentions-layer4-evil"]["principals"][0]
        assert "/svc/evil$" in principal["authenticated"][
            "principal_name"]["safe_regex"]["regex"]

    def test_default_deny_allows_listed_sources(self):
        rules = rbac_rules_from_intentions(
            [{"source": "api", "action": "allow"}], False, self.TD)
        assert rules["action"] == "ALLOW"
        assert set(rules["policies"]) == {"consul-intentions-layer4-api"}

    def test_wildcard_deny_with_exact_allow_carveout(self):
        # api allowed, everything else denied, default allow: the
        # wildcard DENY must NOT match api (rbac.go
        # removeSourcePrecedence's and-not distribution).
        rules = rbac_rules_from_intentions(
            [{"source": "api", "action": "allow"},
             {"source": "*", "action": "deny"}], True, self.TD)
        assert rules["action"] == "DENY"
        wild = rules["policies"]["consul-intentions-layer4-*"]
        ids = wild["principals"][0]["and_ids"]["ids"]
        assert any("not_id" in i for i in ids)

    def test_same_source_lower_precedence_dropped(self):
        rules = rbac_rules_from_intentions(
            [{"source": "api", "action": "deny"},
             {"source": "api", "action": "allow"}], True, self.TD)
        # First (most precedent) wins: api is denied.
        assert set(rules["policies"]) == {"consul-intentions-layer4-api"}


class TestHTTPSurface:
    @requires_crypto
    async def test_xds_feed_over_http(self):
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            agent.add_service({"service": "web", "port": 9090})
            agent.add_service({
                "service": "web-proxy", "kind": "connect-proxy",
                "port": 0,
                "proxy": {"destination_service": "web",
                          "upstreams": [{"destination_name": "db",
                                         "local_bind_port": 5000}]},
            })
            st, hdrs, body = await http_call(
                addr, "GET", "/v1/agent/connect/proxy/web-proxy/xds")
            assert st == 200, body
            assert int(hdrs.get("x-consul-index", "0")) >= 1
            res = body["resources"]
            # Type-URL keys and Envoy wire names are NOT camelized.
            assert CLUSTER_TYPE in res
            clusters = res[CLUSTER_TYPE]
            assert any(c["name"] == "local_app" for c in clusters)
            assert all("connect_timeout" in c for c in clusters
                       if c["name"] != "local_app")
            listeners = res[LISTENER_TYPE]
            assert listeners[0]["name"].startswith("public_listener:")
            assert "filter_chains" in listeners[0]
            # 404 for unknown proxies.
            st, _, _b = await http_call(
                addr, "GET", "/v1/agent/connect/proxy/nope/xds")
            assert st == 404
