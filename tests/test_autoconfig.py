"""Auto-config bootstrap: JWT intro token → full client runtime.

Parity model: agent/consul/auto_config_endpoint.go
(InitialConfiguration: JWT validation + claim assertions → cluster
settings, gossip keys, ACL token, TLS identity) + agent/auto-config/
(the client fetches BEFORE joining gossip, because the response carries
the keys gossip needs).
"""

import asyncio

import pytest

# Every bootstrap response carries gossip keys AND a signed TLS leaf
# (auto_encrypt shape): without the optional crypto toolkit the server
# cannot answer and the client retries forever.
pytest.importorskip("cryptography")

from helpers import wait_for as wait_until

from consul_tpu.acl.jwt import encode_hs256
from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.rpc import RPCError
from consul_tpu.net.security import generate_key
from consul_tpu.net.transport import InMemoryNetwork

SECRET = "introspectable"
AUTHORIZER = {
    "jwt_secret": SECRET,
    "bound_issuer": "https://provisioner",
    "claim_mappings": {"node": "node"},
    # The claimed node name must match the JWT's node claim
    # (auto_config_endpoint.go claim assertions with @@node@@).
    "claim_assertions": ['value.node == "${node}"'],
}


def _server(net, encrypt=True, acl=True):
    return Agent(
        AgentConfig(
            node_name="ac-server", bootstrap_expect=1,
            gossip_interval_scale=0.05, sync_interval_s=0.3,
            sync_retry_interval_s=0.2, reconcile_interval_s=0.2,
            encrypt_key=generate_key() if encrypt else "",
            acl_enabled=acl, acl_default_policy="deny",
            acl_master_token="root",
            auto_config_authorizer=AUTHORIZER,
        ),
        gossip_transport=net.new_transport("acs:gossip"),
        rpc_transport=net.new_transport("acs:rpc"),
    )


def _client(net, name="ac-client", jwt=None):
    return Agent(
        AgentConfig(
            node_name=name, server=False,
            gossip_interval_scale=0.05, sync_interval_s=0.3,
            sync_retry_interval_s=0.2,
            auto_config_enabled=True,
            auto_config_intro_token=jwt if jwt is not None else
            encode_hs256({"iss": "https://provisioner", "node": name},
                         SECRET),
            auto_config_server_addresses=("acs:rpc",),
        ),
        gossip_transport=net.new_transport(f"{name}:gossip"),
        rpc_transport=net.new_transport(f"{name}:rpc"),
    )


class TestAutoConfig:
    async def test_jwt_boots_client_into_encrypted_acl_cluster(self):
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        client = _client(net)
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="server leader")
            # The client has ONLY a server address + JWT: start()
            # performs the bootstrap before gossip.
            await client.start()
            # Gossip keys arrived → the ENCRYPTED join succeeds.
            assert client.keyring is not None
            assert await client.join(["acs:gossip"]) == 1
            await wait_until(
                lambda: "ac-client" in server.serf.members,
                msg="client joined encrypted gossip",
            )
            # The minted agent token carries the client's node identity:
            # node anti-entropy works under default-deny ACLs (service
            # registration still needs its own service:write token —
            # node identities deliberately grant only node:write +
            # service:read, structs/acl.go ACLNodeIdentity).
            assert client.config.acl_agent_token
            authz = server.delegate.acl.resolve(
                client.config.acl_agent_token)
            assert authz.node_write("ac-client")
            assert not authz.service_write("web")
            await wait_until(lambda: client.delegate.routers.servers(),
                             msg="client discovered server")
            await wait_until(
                lambda: server.delegate.store.node("ac-client")[1],
                timeout=10, msg="node synced under ACL enforcement",
            )
            # TLS identity issued (the auto-encrypt shape).
            assert client.tls_identity["leaf"]["cert_pem"]
            assert client.tls_identity["roots"]
        finally:
            await client.shutdown()
            await server.shutdown()

    async def test_forged_jwt_is_refused(self):
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        forged = encode_hs256(
            {"iss": "https://provisioner", "node": "ac-client"}, "wrong")
        client = _client(net, jwt=forged)
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="server leader")
            with pytest.raises(RPCError, match="Permission denied"):
                await client.start()
        finally:
            await client.shutdown()
            await server.shutdown()

    async def test_node_claim_assertion_enforced(self):
        """A JWT minted for node A cannot bootstrap node B
        (the ${node} claim assertion)."""
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        stolen = encode_hs256(
            {"iss": "https://provisioner", "node": "other-node"}, SECRET)
        client = _client(net, jwt=stolen)
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="server leader")
            with pytest.raises(RPCError, match="Permission denied"):
                await client.start()
        finally:
            await client.shutdown()
            await server.shutdown()

    async def test_disabled_server_refuses(self):
        net = InMemoryNetwork()
        server = Agent(
            AgentConfig(node_name="plain", bootstrap_expect=1,
                        gossip_interval_scale=0.05,
                        reconcile_interval_s=0.2),
            gossip_transport=net.new_transport("acs:gossip"),
            rpc_transport=net.new_transport("acs:rpc"),
        )
        await server.start()
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="leader")
            out = server.delegate.rpc_server
            with pytest.raises(Exception, match="disabled"):
                await out.dispatch_local(
                    "AutoConfig.InitialConfiguration",
                    {"node": "x", "jwt": "y"})
        finally:
            await server.shutdown()


class TestAutoConfigHardening:
    async def test_bootstrap_repoints_datacenter(self):
        """A client built with the default dc must follow the server's
        dc after bootstrap — serf tag, router filter, and config all
        re-point (otherwise ServerManager finds zero servers)."""
        net = InMemoryNetwork()
        server = Agent(
            AgentConfig(node_name="east-server", datacenter="east",
                        bootstrap_expect=1, gossip_interval_scale=0.05,
                        reconcile_interval_s=0.2,
                        auto_config_authorizer=AUTHORIZER),
            gossip_transport=net.new_transport("acs:gossip"),
            rpc_transport=net.new_transport("acs:rpc"),
        )
        await server.start()
        client = _client(net)
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="leader")
            await client.start()
            assert client.config.datacenter == "east"
            assert client.delegate.routers.datacenter == "east"
            assert await client.join(["acs:gossip"]) == 1
            await wait_until(lambda: client.delegate.routers.servers(),
                             msg="client finds the east server")
        finally:
            await client.shutdown()
            await server.shutdown()

    async def test_token_mint_is_idempotent_per_node(self):
        """Bootstrap retries must reuse the node's token, not mint a new
        one per call (auto_config_endpoint.go updateTokenResponse)."""
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="leader")
            jwt = encode_hs256(
                {"iss": "https://provisioner", "node": "n1"}, SECRET)
            body = {"node": "n1", "jwt": jwt}
            out1 = await server.delegate.rpc_server.dispatch_local(
                "AutoConfig.InitialConfiguration", body)
            out2 = await server.delegate.rpc_server.dispatch_local(
                "AutoConfig.InitialConfiguration", body)
            t1 = out1["config"]["acl"]["tokens"]["agent"]
            t2 = out2["config"]["acl"]["tokens"]["agent"]
            assert t1 == t2
            _, tokens = server.delegate.store.acl_token_list()
            autoconf = [t for t in tokens
                        if "auto-config" in t.get("description", "")]
            assert len(autoconf) == 1
        finally:
            await server.shutdown()

    async def test_bexpr_injection_in_node_name_rejected(self):
        """The node name interpolates into claim assertions — bexpr
        metacharacters must be refused outright."""
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="leader")
            evil = 'x" or "1" == "1'
            jwt = encode_hs256(
                {"iss": "https://provisioner", "node": "a"}, SECRET)
            with pytest.raises(Exception, match="invalid node name"):
                await server.delegate.rpc_server.dispatch_local(
                    "AutoConfig.InitialConfiguration",
                    {"node": evil, "jwt": jwt})
        finally:
            await server.shutdown()

    async def test_full_keyring_shipped(self):
        """Mid-rotation bootstrap: the response carries the WHOLE ring
        (primary first), or new nodes drop old-key traffic."""
        net = InMemoryNetwork()
        server = _server(net)
        await server.start()
        try:
            await wait_until(lambda: server.delegate.is_leader(),
                             msg="leader")
            old = generate_key()
            server.keyring.install(old)
            jwt = encode_hs256(
                {"iss": "https://provisioner", "node": "n2"}, SECRET)
            out = await server.delegate.rpc_server.dispatch_local(
                "AutoConfig.InitialConfiguration",
                {"node": "n2", "jwt": jwt})
            keys = out["gossip_keys"]
            assert len(keys) == 2
            assert keys[0] == server.keyring.primary_b64()
            assert old in keys
        finally:
            await server.shutdown()
