"""Connect MVP (built-in CA, SPIFFE leaves, intentions), prepared-query
cross-DC failover, and serf event coalescing.

Parity models: agent/connect/ca/provider_consul_test.go,
consul/intention_endpoint_test.go, consul/prepared_query_endpoint_test
(queryFailover), serf/coalesce_test.go.
"""

import asyncio
import json

import pytest

from helpers import wait_for as wait_until

from consul_tpu.connect import BuiltinCA, spiffe_service, verify_leaf


def run(coro, timeout=90):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


# ---------------------------------------------------------------------------
# CA unit
# ---------------------------------------------------------------------------


def test_ca_root_and_leaf_lifecycle():
    ca = BuiltinCA("dc1")
    root = ca.generate_root()
    assert root["active"] and root["trust_domain"] == ca.trust_domain

    leaf = ca.sign_leaf("web")
    expected_uri = spiffe_service(ca.trust_domain, "dc1", "web")
    assert leaf["uri"] == expected_uri
    assert "BEGIN CERTIFICATE" in leaf["cert_pem"]
    assert "BEGIN PRIVATE KEY" in leaf["key_pem"]

    # The leaf verifies against the signing root and yields its URI.
    assert verify_leaf(leaf["cert_pem"], root["root_cert"]) == expected_uri

    # ...but not against an unrelated root.
    other = BuiltinCA("dc1")
    other_root = other.generate_root()
    assert verify_leaf(leaf["cert_pem"], other_root["root_cert"]) is None


def test_ca_rotation_keeps_old_root_verifiable():
    ca = BuiltinCA("dc1")
    root1 = ca.generate_root()
    leaf1 = ca.sign_leaf("db")
    root2 = ca.rotate()
    leaf2 = ca.sign_leaf("db")
    assert root1["id"] != root2["id"]
    # New leaves chain to the new root; old leaves still chain to the
    # old (retained) root.
    assert verify_leaf(leaf2["cert_pem"], root2["root_cert"]) is not None
    assert verify_leaf(leaf1["cert_pem"], root1["root_cert"]) is not None
    assert verify_leaf(leaf1["cert_pem"], root2["root_cert"]) is None


# ---------------------------------------------------------------------------
# HTTP end-to-end
# ---------------------------------------------------------------------------


def test_connect_http_leaf_and_intentions():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            # Leaf + roots: the leaf must verify against the active root.
            st, _, leaf = await http_call(
                addr, "GET", "/v1/agent/connect/ca/leaf/web")
            assert st == 200, leaf
            st, _, roots = await http_call(addr, "GET", "/v1/connect/ca/roots")
            assert st == 200 and roots["Roots"]
            active = next(
                r for r in roots["Roots"] if r["ID"] == roots["ActiveRootID"]
            )
            assert verify_leaf(leaf["CertPEM"], active["RootCert"]) \
                == leaf["URI"]

            # Intentions: deny web -> db, everything else default-allow
            # (ACLs disabled).
            st, _, created = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"SourceName": "web", "DestinationName": "db"}
                           ).encode())
            # Our shape uses source/destination.
            st, _, created = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "web", "Destination": "db",
                            "Action": "deny"}).encode())
            assert st == 200 and created["ID"]

            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=web&destination=db")
            assert st == 200 and out["Allowed"] is False
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=api&destination=db")
            assert st == 200 and out["Allowed"] is True

            # Wildcard deny beats default but loses to exact allow.
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "*", "Destination": "db",
                            "Action": "deny"}).encode())
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "billing", "Destination": "db",
                            "Action": "allow"}).encode())
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=billing&destination=db")
            assert out["Allowed"] is True
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=other&destination=db")
            assert out["Allowed"] is False

            # Proxy authorize with the leaf's SPIFFE URI as client cert.
            st, _, out = await http_call(
                addr, "POST", "/v1/agent/connect/authorize",
                json.dumps({"Target": "db",
                            "ClientCertURI": leaf["URI"]}).encode())
            assert st == 200 and out["Authorized"] is False  # web->db deny

    run(main())


# ---------------------------------------------------------------------------
# prepared-query cross-DC failover
# ---------------------------------------------------------------------------


def test_prepared_query_fails_over_to_remote_dc():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_multidc_host import start_two_dcs, shutdown_all

        dc1, dc2 = await start_two_dcs()
        # Failover needs the WAN view: wait until every dc1 server's
        # router can see dc2 (the flooder finishes federating).
        await wait_until(
            lambda: all(
                "dc2" in s.router.servers_by_dc() for s in dc1
            ),
            msg="dc1 servers see dc2 over WAN",
        )
        # 'web' exists ONLY in dc2.
        await dc2[0].rpc_client.call(
            "b0.dc2:rpc", "Catalog.Register",
            {"node": "n2", "address": "10.2.0.1",
             "service": {"id": "web1", "service": "web", "port": 80}},
        )
        out = await dc1[0].rpc_client.call(
            "a0.dc1:rpc", "PreparedQuery.Apply",
            {"op": "create",
             "query": {"name": "find-web",
                       "service": {"service": "web",
                                   "failover": {"nearest_n": 1}}}},
        )
        qid = out["result"]
        res = await dc1[0].rpc_client.call(
            "a0.dc1:rpc", "PreparedQuery.Execute", {"query_id": qid}
        )
        assert res["nodes"], res
        assert res["datacenter"] == "dc2"
        assert res["failovers"] == 1
        assert res["nodes"][0]["service"]["id"] == "web1"
        await shutdown_all(*dc1, *dc2)

    run(main())


# ---------------------------------------------------------------------------
# coalescing
# ---------------------------------------------------------------------------


def test_member_event_coalescing():
    async def main():
        from consul_tpu.eventing.cluster import (
            Cluster,
            ClusterConfig,
            EventType,
        )
        from consul_tpu.net.transport import InMemoryNetwork

        net = InMemoryNetwork()
        events = []
        c1 = Cluster(
            ClusterConfig(name="c1", interval_scale=0.02,
                          coalesce_period_s=10.0,  # * scale = 200ms
                          on_event=lambda ev: events.append(ev)),
            net.new_transport("mem://c1"),
        )
        await c1.start()
        others = []
        for i in range(4):
            c = Cluster(ClusterConfig(name=f"m{i}", interval_scale=0.02),
                        net.new_transport(f"mem://m{i}"))
            await c.start()
            await c.join(["mem://c1"])
            others.append(c)
        # A burst of joins coalesces: wait past the window, then the
        # join events arrive batched (fewer events than joins, members
        # grouped by type), not one per transition.
        await wait_until(
            lambda: sum(
                len(e.members)
                for e in events
                if e.type == EventType.MEMBER_JOIN
            ) >= 5,
            msg="all joins delivered (coalesced)",
        )
        join_events = [e for e in events if e.type == EventType.MEMBER_JOIN]
        total_members = sum(len(e.members) for e in join_events)
        assert total_members >= 5
        assert len(join_events) < total_members  # batching happened
        for c in [c1] + others:
            await c.shutdown()

    run(main())
