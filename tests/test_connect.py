"""Connect MVP (built-in CA, SPIFFE leaves, intentions), prepared-query
cross-DC failover, and serf event coalescing.

Parity models: agent/connect/ca/provider_consul_test.go,
consul/intention_endpoint_test.go, consul/prepared_query_endpoint_test
(queryFailover), serf/coalesce_test.go.
"""

import asyncio
import json

import pytest

from helpers import wait_for as wait_until
from helpers import requires_crypto

from consul_tpu.connect import BuiltinCA, spiffe_service, verify_leaf


def run(coro, timeout=90):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


# ---------------------------------------------------------------------------
# CA unit
# ---------------------------------------------------------------------------


@requires_crypto
def test_ca_root_and_leaf_lifecycle():
    ca = BuiltinCA("dc1")
    root = ca.generate_root()
    assert root["active"] and root["trust_domain"] == ca.trust_domain

    leaf = ca.sign_leaf("web")
    expected_uri = spiffe_service(ca.trust_domain, "dc1", "web")
    assert leaf["uri"] == expected_uri
    assert "BEGIN CERTIFICATE" in leaf["cert_pem"]
    assert "BEGIN PRIVATE KEY" in leaf["key_pem"]

    # The leaf verifies against the signing root and yields its URI.
    assert verify_leaf(leaf["cert_pem"], root["root_cert"]) == expected_uri

    # ...but not against an unrelated root.
    other = BuiltinCA("dc1")
    other_root = other.generate_root()
    assert verify_leaf(leaf["cert_pem"], other_root["root_cert"]) is None


@requires_crypto
def test_ca_rotation_keeps_old_root_verifiable():
    ca = BuiltinCA("dc1")
    root1 = ca.generate_root()
    leaf1 = ca.sign_leaf("db")
    root2 = ca.rotate()
    leaf2 = ca.sign_leaf("db")
    assert root1["id"] != root2["id"]
    # New leaves chain to the new root; old leaves still chain to the
    # old (retained) root.
    assert verify_leaf(leaf2["cert_pem"], root2["root_cert"]) is not None
    assert verify_leaf(leaf1["cert_pem"], root1["root_cert"]) is not None
    assert verify_leaf(leaf1["cert_pem"], root2["root_cert"]) is None


# ---------------------------------------------------------------------------
# HTTP end-to-end
# ---------------------------------------------------------------------------


@requires_crypto
def test_connect_http_leaf_and_intentions():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            # Leaf + roots: the leaf must verify against the active root.
            st, _, leaf = await http_call(
                addr, "GET", "/v1/agent/connect/ca/leaf/web")
            assert st == 200, leaf
            st, _, roots = await http_call(addr, "GET", "/v1/connect/ca/roots")
            assert st == 200 and roots["Roots"]
            active = next(
                r for r in roots["Roots"] if r["ID"] == roots["ActiveRootID"]
            )
            assert verify_leaf(leaf["CertPEM"], active["RootCert"]) \
                == leaf["URI"]

            # Intentions: deny web -> db, everything else default-allow
            # (ACLs disabled).
            st, _, created = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"SourceName": "web", "DestinationName": "db"}
                           ).encode())
            # Our shape uses source/destination.
            st, _, created = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "web", "Destination": "db",
                            "Action": "deny"}).encode())
            assert st == 200 and created["ID"]

            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=web&destination=db")
            assert st == 200 and out["Allowed"] is False
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=api&destination=db")
            assert st == 200 and out["Allowed"] is True

            # Wildcard deny beats default but loses to exact allow.
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "*", "Destination": "db",
                            "Action": "deny"}).encode())
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "billing", "Destination": "db",
                            "Action": "allow"}).encode())
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=billing&destination=db")
            assert out["Allowed"] is True
            st, _, out = await http_call(
                addr, "GET",
                "/v1/connect/intentions/check?source=other&destination=db")
            assert out["Allowed"] is False

            # Proxy authorize with the leaf's SPIFFE URI as client cert.
            st, _, out = await http_call(
                addr, "POST", "/v1/agent/connect/authorize",
                json.dumps({"Target": "db",
                            "ClientCertURI": leaf["URI"]}).encode())
            assert st == 200 and out["Authorized"] is False  # web->db deny

    run(main())


@requires_crypto
def test_mtls_service_to_service():
    """Full Connect data path (connect/service.go): two services get
    SPIFFE leaves from the agent, speak mutual TLS, and the server side
    authorizes the client's certificate identity against intentions."""

    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call
        from consul_tpu.connect import Service

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            web = await Service("web", addr).ready()
            api = await Service("api", addr).ready()
            assert web.uri.endswith("/svc/web")

            served: list[bytes] = []

            async def echo(reader, writer):
                data = await reader.read(64)
                served.append(data)
                writer.write(b"hello " + data)
                await writer.drain()
                writer.close()

            server, srv_addr = await web.listen(echo)

            # Default policy (ACLs off) allows: api can reach web.
            r, w = await api.dial(srv_addr)
            w.write(b"api")
            await w.drain()
            assert await r.read(64) == b"hello api"
            w.close()

            # Deny api -> web: TLS still handshakes (identity is valid),
            # but the intention check drops the connection.
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "api", "Destination": "web",
                            "Action": "deny"}).encode())
            assert st == 200
            r, w = await api.dial(srv_addr)
            w.write(b"again")
            try:
                await w.drain()
            except ConnectionError:
                pass
            assert await r.read(64) == b""  # closed without data
            w.close()

            # A plain-TLS client with no certificate can't even
            # handshake (CERT_REQUIRED).
            import ssl as _ssl

            naked = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
            naked.check_hostname = False
            naked.verify_mode = _ssl.CERT_NONE
            host, port = srv_addr.rsplit(":", 1)
            # CERT_REQUIRED rejects the certificate-less client server-
            # side with a fatal alert; asyncio surfaces that to the
            # client as EOF (TLS 1.3 defers it past the handshake), so
            # the observable property is: no data, and the handler
            # NEVER runs (vs the allowed path, which served above).
            handled_before = len(served)
            try:
                r2, w2 = await asyncio.open_connection(
                    host, int(port), ssl=naked
                )
                w2.write(b"naked")
                await w2.drain()
                assert await r2.read(64) == b""
                w2.close()
            except (_ssl.SSLError, ConnectionError, OSError):
                pass  # equally acceptable: handshake failed outright
            assert len(served) == handled_before
            assert served == [b"api"]  # only the authorized dial ran

            # Destination pinning (connect/tls.go
            # verifyServerCertMatchesURI): the TLS-level identity check
            # is client-side, independent of intentions.  Expecting
            # "web" matches web's leaf; expecting "db" must fail even
            # though the leaf chains to the same roots.
            r, w = await api.dial(srv_addr, destination="web")
            w.close()
            from consul_tpu.connect.service import ConnectError

            with pytest.raises(ConnectError):
                await api.dial(srv_addr, destination="db")

            server.close()
            web.close()
            api.close()

    run(main())


# ---------------------------------------------------------------------------
# prepared-query cross-DC failover
# ---------------------------------------------------------------------------


def test_prepared_query_fails_over_to_remote_dc():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_multidc_host import start_two_dcs, shutdown_all

        dc1, dc2 = await start_two_dcs()
        # Failover needs the WAN view: wait until every dc1 server's
        # router can see dc2 (the flooder finishes federating).
        await wait_until(
            lambda: all(
                "dc2" in s.router.servers_by_dc() for s in dc1
            ),
            msg="dc1 servers see dc2 over WAN",
        )
        # 'web' exists ONLY in dc2.
        await dc2[0].rpc_client.call(
            "b0.dc2:rpc", "Catalog.Register",
            {"node": "n2", "address": "10.2.0.1",
             "service": {"id": "web1", "service": "web", "port": 80}},
        )
        out = await dc1[0].rpc_client.call(
            "a0.dc1:rpc", "PreparedQuery.Apply",
            {"op": "create",
             "query": {"name": "find-web",
                       "service": {"service": "web",
                                   "failover": {"nearest_n": 1}}}},
        )
        qid = out["result"]
        res = await dc1[0].rpc_client.call(
            "a0.dc1:rpc", "PreparedQuery.Execute", {"query_id": qid}
        )
        assert res["nodes"], res
        assert res["datacenter"] == "dc2"
        assert res["failovers"] == 1
        assert res["nodes"][0]["service"]["id"] == "web1"
        await shutdown_all(*dc1, *dc2)

    run(main())


# ---------------------------------------------------------------------------
# coalescing
# ---------------------------------------------------------------------------


def test_member_event_coalescing():
    async def main():
        from consul_tpu.eventing.cluster import (
            Cluster,
            ClusterConfig,
            EventType,
        )
        from consul_tpu.net.transport import InMemoryNetwork

        net = InMemoryNetwork()
        events = []
        c1 = Cluster(
            ClusterConfig(name="c1", interval_scale=0.02,
                          coalesce_period_s=10.0,  # * scale = 200ms
                          on_event=lambda ev: events.append(ev)),
            net.new_transport("mem://c1"),
        )
        await c1.start()
        others = []
        for i in range(4):
            c = Cluster(ClusterConfig(name=f"m{i}", interval_scale=0.02),
                        net.new_transport(f"mem://m{i}"))
            await c.start()
            await c.join(["mem://c1"])
            others.append(c)
        # A burst of joins coalesces: wait past the window, then the
        # join events arrive batched (fewer events than joins, members
        # grouped by type), not one per transition.
        await wait_until(
            lambda: sum(
                len(e.members)
                for e in events
                if e.type == EventType.MEMBER_JOIN
            ) >= 5,
            msg="all joins delivered (coalesced)",
        )
        join_events = [e for e in events if e.type == EventType.MEMBER_JOIN]
        total_members = sum(len(e.members) for e in join_events)
        assert total_members >= 5
        assert len(join_events) < total_members  # batching happened
        for c in [c1] + others:
            await c.shutdown()

    run(main())


@requires_crypto
def test_auto_encrypt_client_bootstrap():
    """auto_encrypt_endpoint.go Sign: a client agent fetches an
    agent-kind SPIFFE leaf + CA roots from the servers at startup."""

    async def main():
        from consul_tpu.agent.agent import Agent, AgentConfig
        from consul_tpu.net.transport import InMemoryNetwork

        net = InMemoryNetwork()
        server = Agent(
            AgentConfig(node_name="srv", bootstrap_expect=1,
                        gossip_interval_scale=0.05, sync_interval_s=0.3,
                        sync_retry_interval_s=0.2,
                        reconcile_interval_s=0.2),
            gossip_transport=net.new_transport("srv:gossip"),
            rpc_transport=net.new_transport("srv:rpc"),
        )
        await server.start()
        await wait_until(lambda: server.delegate.is_leader(), msg="leader")

        client = Agent(
            AgentConfig(node_name="cli", server=False,
                        gossip_interval_scale=0.05, sync_interval_s=0.3,
                        sync_retry_interval_s=0.2, auto_encrypt=True),
            gossip_transport=net.new_transport("cli:gossip"),
            rpc_transport=net.new_transport("cli:rpc"),
        )
        await client.start()
        await client.join(["srv:gossip"])

        await wait_until(
            lambda: client.tls_identity is not None,
            timeout=15, msg="auto-encrypt identity issued",
        )
        ident = client.tls_identity
        leaf, roots = ident["leaf"], ident["roots"]
        assert "/agent/client/dc/dc1/id/cli" in leaf["uri"]
        active = next(r for r in roots if r.get("active"))
        assert verify_leaf(leaf["cert_pem"], active["root_cert"]) \
            == leaf["uri"]

        await client.shutdown()
        await server.shutdown()

    run(main())


@requires_crypto
def test_rotation_cross_signs_for_old_root_verifiers():
    """provider_consul.go CrossSignCA: after rotation, leaves signed by
    the NEW root must verify for a peer still pinned to the OLD root,
    via the cross-signed intermediate carried in the leaf chain."""
    from consul_tpu.connect.ca import (
        BuiltinCA,
        verify_leaf,
        verify_leaf_chain,
    )

    ca = BuiltinCA("dc1", trust_domain="td.consul")
    ca.generate_root()
    old_root_pem = ca.root_pem()

    rec = ca.rotate()
    assert rec.get("cross_signed_cert")
    leaf = ca.sign_leaf("web")
    assert leaf["intermediate_pems"] == [rec["cross_signed_cert"]]

    # Pinned to the NEW root: direct verification.
    assert verify_leaf(leaf["cert_pem"], ca.root_pem())
    # Pinned to the OLD root: direct fails, the chain succeeds.
    assert verify_leaf(leaf["cert_pem"], old_root_pem) is None
    uri = verify_leaf_chain(
        leaf["cert_pem"], leaf["intermediate_pems"], old_root_pem)
    assert uri == leaf["uri"]
    # Garbage intermediates never help.
    assert verify_leaf_chain(leaf["cert_pem"], ["junk"], old_root_pem) \
        is None
