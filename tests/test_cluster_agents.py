"""Full in-process server/client clusters over the in-memory network.

Parity model: ``agent/consul/cluster_test.go`` + ``client_test.go`` —
spin N servers, join LAN, wait for leader, drive RPCs through a client,
kill the leader, watch failover (SURVEY.md §4.3).
"""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import wait_for_leader

from consul_tpu.agent.client import Client, ClientConfig
from consul_tpu.agent.server import Server, ServerConfig
from consul_tpu.net.transport import InMemoryNetwork
from consul_tpu.protocol import LAN


def make_server(net, name, expect=3, **kw):
    # Fast staging→voter promotion (late joiners are non-voters until
    # autopilot promotes them).
    kw.setdefault("autopilot_interval_s", 0.3)
    kw.setdefault("autopilot_server_stabilization_s", 0.3)
    cfg = ServerConfig(
        node_name=name,
        bootstrap_expect=expect,
        gossip_interval_scale=0.05,  # fast protocol for tests
        reconcile_interval_s=0.2,
        coordinate_update_period_s=0.1,
        session_ttl_sweep_s=0.1,
        **kw,
    )
    return Server(
        cfg,
        gossip_transport=net.new_transport(f"{name}:gossip"),
        rpc_transport=net.new_transport(f"{name}:rpc"),
    )


def make_client(net, name):
    cfg = ClientConfig(node_name=name, gossip_interval_scale=0.05)
    return Client(
        cfg,
        gossip_transport=net.new_transport(f"{name}:gossip"),
        rpc_transport=net.new_transport(f"{name}:rpc"),
    )


async def start_cluster(net, n=3):
    servers = [make_server(net, f"s{i}", expect=n) for i in range(n)]
    for s in servers:
        await s.start()
    for s in servers[1:]:
        await s.join(["s0:gossip"])
    await wait_for_leader(servers)
    return servers


async def shutdown_all(*nodes):
    for n in nodes:
        await n.shutdown()
    await asyncio.sleep(0)


class TestServerCluster:
    async def test_three_servers_elect_and_replicate(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())

        out = await leader.rpc_client.call(
            f"{leader.node_id}:rpc", "KVS.Apply",
            {"op": "set", "entry": {"key": "a", "value": b"1"}},
        )
        assert out["result"] is True

        # Replicated to every server's store (follower stale read).
        await wait_until(
            lambda: all(
                s.store.kv_get("a")[1] is not None for s in servers
            ),
            msg="kv replicated to all followers",
        )
        await shutdown_all(*servers)

    async def test_follower_forwards_write_to_leader(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        follower = next(s for s in servers if not s.is_leader())

        out = await follower.rpc_client.call(
            f"{follower.node_id}:rpc", "KVS.Apply",
            {"op": "set", "entry": {"key": "fwd", "value": b"x"}},
        )
        assert out["result"] is True
        leader = next(s for s in servers if s.is_leader())
        assert leader.store.kv_get("fwd")[1]["value"] == b"x"
        await shutdown_all(*servers)

    async def test_serf_membership_reconciled_into_catalog(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())

        await wait_until(
            lambda: len(leader.store.nodes()[1]) == 3,
            msg="all serf members registered in catalog",
        )
        _, checks = leader.store.node_checks("s1")
        assert checks and checks[0]["check_id"] == "serfHealth"
        assert checks[0]["status"] == "passing"
        await shutdown_all(*servers)

    async def test_leader_failover(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        rest = [s for s in servers if s is not leader]

        await leader.shutdown()
        new_leader = await wait_for_leader(rest, timeout=10)
        out = await new_leader.rpc_client.call(
            f"{new_leader.node_id}:rpc", "KVS.Apply",
            {"op": "set", "entry": {"key": "post-failover", "value": b"ok"}},
        )
        assert out["result"] is True
        await shutdown_all(*rest)


class TestClientAgent:
    async def test_client_discovers_servers_and_rpcs(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        client = make_client(net, "c0")
        await client.start()
        await client.join(["s0:gossip"])

        await wait_until(
            lambda: len(client.routers.servers()) == 3,
            msg="client sees 3 servers via serf tags",
        )

        out = await client.rpc(
            "KVS.Apply", {"op": "set", "entry": {"key": "via-client", "value": b"v"}}
        )
        assert out["result"] is True
        got = await client.rpc("KVS.Get", {"key": "via-client"})
        assert got["entries"][0]["value"] == b"v"
        assert got["meta"]["index"] >= 1
        await shutdown_all(client, *servers)

    async def test_client_blocking_query_wakes_on_write(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        client = make_client(net, "c0")
        await client.start()
        await client.join(["s0:gossip"])
        await wait_until(lambda: client.routers.servers(), msg="servers known")

        await client.rpc(
            "KVS.Apply", {"op": "set", "entry": {"key": "w", "value": b"1"}}
        )
        got = await client.rpc("KVS.Get", {"key": "w"})
        idx = got["meta"]["index"]

        async def blocked():
            return await client.rpc(
                "KVS.Get",
                {"key": "w", "min_query_index": idx, "max_query_time": 5},
                timeout=10,
            )

        task = asyncio.create_task(blocked())
        await asyncio.sleep(0.1)
        assert not task.done()
        await client.rpc(
            "KVS.Apply", {"op": "set", "entry": {"key": "w", "value": b"2"}}
        )
        got2 = await asyncio.wait_for(task, 5)
        assert got2["entries"][0]["value"] == b"2"
        await shutdown_all(client, *servers)

    async def test_catalog_health_session_flow(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        client = make_client(net, "c0")
        await client.start()
        await client.join(["s0:gossip"])
        await wait_until(lambda: client.routers.servers(), msg="servers known")

        # Register an EXTERNAL node (no serfHealth — such nodes are
        # exempt from the leader's reconcileReaped pass, like external
        # services in the reference).
        out = await client.rpc("Catalog.Register", {
            "node": "web-1", "address": "10.1.1.1",
            "service": {"service": "web", "port": 80, "tags": ["v1"]},
            "checks": [
                {"check_id": "web-alive", "status": "passing"},
                {"check_id": "web-http", "service_id": "web",
                 "status": "passing"},
            ],
        })
        assert out["result"] is True

        nodes = await client.rpc("Health.ServiceNodes",
                                 {"service": "web", "passing_only": True})
        assert len(nodes["nodes"]) == 1
        assert nodes["nodes"][0]["service"]["port"] == 80

        svc = await client.rpc("Catalog.ServiceNodes",
                               {"service": "web", "tag": "v1"})
        assert len(svc["nodes"]) == 1
        none = await client.rpc("Catalog.ServiceNodes",
                                {"service": "web", "tag": "v9"})
        assert none["nodes"] == []

        # Session + lock through the full stack (explicit check set:
        # this external node has no serfHealth).
        sess = await client.rpc("Session.Apply", {
            "op": "create",
            "session": {"node": "web-1", "ttl": "10s",
                        "checks": ["web-alive"]},
        })
        sid = sess["result"]
        lock = await client.rpc("KVS.Apply", {
            "op": "lock",
            "entry": {"key": "svc/leader", "value": b"web-1", "session": sid},
        })
        assert lock["result"] is True
        rec = await client.rpc("KVS.Get", {"key": "svc/leader"})
        assert rec["entries"][0]["session"] == sid
        await shutdown_all(client, *servers)

    async def test_session_ttl_expires_without_renew(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        client = make_client(net, "c0")
        await client.start()
        await client.join(["s0:gossip"])
        await wait_until(lambda: client.routers.servers(), msg="servers known")

        # External node (no serfHealth): stays in the catalog so the
        # session can only vanish through the leader's TTL sweep — the
        # code actually under test here.
        await client.rpc("Catalog.Register", {
            "node": "n-ttl", "address": "10.2.2.2",
        })
        sess = await client.rpc("Session.Apply", {
            "op": "create",
            "session": {"node": "n-ttl", "ttl": "0.2s", "checks": []},
        })
        sid = sess["result"]
        assert leader.store.session_get(sid)[1] is not None
        # TTL x2 + sweep interval: should be destroyed by the leader.
        await wait_until(
            lambda: leader.store.session_get(sid)[1] is None,
            timeout=5,
            msg="session invalidated after TTL",
        )
        await shutdown_all(client, *servers)


class TestCoordinateBatching:
    async def test_updates_flush_in_one_batch(self):
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        client = make_client(net, "c0")
        await client.start()
        await client.join(["s0:gossip"])
        await wait_until(lambda: client.routers.servers(), msg="servers known")

        await client.rpc("Catalog.Register",
                         {"node": "n1", "address": "10.0.0.1"})
        await client.rpc("Coordinate.Update", {
            "node": "n1", "coord": {"vec": [0.1] * 8, "height": 1e-5,
                                    "adjustment": 0.0, "error": 1.5},
        })
        await wait_until(
            lambda: any(
                s.store.coordinate("n1") is not None for s in servers
            ),
            msg="coordinate flushed via raft batch",
        )
        await shutdown_all(client, *servers)


class TestBootstrapGuards:
    async def test_late_joiner_does_not_depose_leader(self):
        """A server joining an established cluster at the expect
        threshold must NOT live-bootstrap its own voter set: it probes
        Status.Peers, disables bootstrap, and waits for the leader's
        reconcile to add it (server_serf.go:318-401)."""
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        term_before = leader.raft.current_term

        late = make_server(net, "s9", expect=3)
        await late.start()
        await late.join(["s0:gossip"])

        # The late joiner must end up a follower in the SAME cluster.
        await wait_until(
            lambda: late.raft is not None
            and "s9" in late.raft.voters
            and late.raft.leader_id == leader.node_id,
            timeout=10,
            msg="late joiner folded in as follower",
        )
        assert late._bootstrap_disabled is True
        assert not late.is_leader()
        # Leadership never churned: same leader, same term.
        assert leader.is_leader()
        assert leader.raft.current_term == term_before
        await shutdown_all(late, *servers)


class TestLockDelay:
    async def test_invalidated_session_lock_delay_blocks_reacquire(self):
        """KVSLock honors the lock-delay window set when a lock-holding
        session dies (kvs_endpoint.go:67-82, state/session.go:348-368)."""
        net = InMemoryNetwork()
        servers = await start_cluster(net)
        leader = next(s for s in servers if s.is_leader())
        addr = f"{leader.node_id}:rpc"
        call = leader.rpc_client.call

        await call(addr, "Catalog.Register", {
            "node": "n-ld", "address": "10.9.9.9",
        })
        s1 = (await call(addr, "Session.Apply", {
            "op": "create",
            "session": {"node": "n-ld", "lock_delay": 0.4, "checks": []},
        }))["result"]
        got = await call(addr, "KVS.Apply", {
            "op": "lock", "entry": {"key": "svc/lead", "session": s1},
        })
        assert got["result"] is True

        # Session dies while holding the lock -> delay window opens.
        await call(addr, "Session.Apply",
                   {"op": "destroy", "session": {"id": s1}})
        s2 = (await call(addr, "Session.Apply", {
            "op": "create",
            "session": {"node": "n-ld", "lock_delay": 0.4, "checks": []},
        }))["result"]
        denied = await call(addr, "KVS.Apply", {
            "op": "lock", "entry": {"key": "svc/lead", "session": s2},
        })
        assert denied["result"] is False

        await asyncio.sleep(0.5)  # let the delay lapse
        allowed = await call(addr, "KVS.Apply", {
            "op": "lock", "entry": {"key": "svc/lead", "session": s2},
        })
        assert allowed["result"] is True
        await shutdown_all(*servers)


class TestNetworkSegments:
    async def test_segment_rings_isolate_clients_but_reach_servers(self):
        """server_serf.go:50 segmentLAN: clients of different segments
        never see each other's gossip, the server bridges all rings,
        and reconcile folds every segment's nodes into one catalog with
        their segment recorded."""
        from consul_tpu.agent.agent import Agent, AgentConfig

        net = InMemoryNetwork()
        srv = Server(
            ServerConfig(
                node_name="seg-server", bootstrap_expect=1,
                gossip_interval_scale=0.05, reconcile_interval_s=0.2,
                coordinate_update_period_s=0.1, session_ttl_sweep_s=0.1,
                segments=("alpha", "beta"),
            ),
            gossip_transport=net.new_transport("srv:gossip"),
            rpc_transport=net.new_transport("srv:rpc"),
            segment_transports={
                "alpha": net.new_transport("srv:alpha"),
                "beta": net.new_transport("srv:beta"),
            },
        )
        await srv.start()

        def client(name, segment):
            return Agent(
                AgentConfig(node_name=name, server=False,
                            gossip_interval_scale=0.05,
                            sync_interval_s=0.3,
                            sync_retry_interval_s=0.2, segment=segment),
                gossip_transport=net.new_transport(f"{name}:gossip"),
                rpc_transport=net.new_transport(f"{name}:rpc"),
            )

        ca = client("c-alpha", "alpha")
        cb = client("c-beta", "beta")
        await ca.start()
        await cb.start()
        try:
            await wait_until(lambda: srv.is_leader(), msg="leader")
            assert await ca.join(["srv:alpha"]) == 1
            assert await cb.join(["srv:beta"]) == 1
            await wait_until(
                lambda: "c-alpha" in srv.segment_serfs["alpha"].members
                and "c-beta" in srv.segment_serfs["beta"].members,
                msg="server bridges both segments",
            )
            # Isolation: alpha's ring never learns beta's client.
            await asyncio.sleep(0.5)
            assert "c-beta" not in ca.serf.members
            assert "c-alpha" not in cb.serf.members
            # The main ring holds only the server itself.
            assert set(srv.serf.members) == {"seg-server"}
            # Reconcile registers both segment clients in the catalog
            # with their segment in node meta.
            await wait_until(
                lambda: srv.store.node("c-alpha")[1] is not None
                and srv.store.node("c-beta")[1] is not None,
                timeout=10, msg="segment nodes reconciled into catalog",
            )
            assert srv.store.node("c-alpha")[1]["meta"]["segment"] == \
                "alpha"
            assert srv.store.node("c-beta")[1]["meta"]["segment"] == \
                "beta"
        finally:
            await ca.shutdown()
            await cb.shutdown()
            await srv.shutdown()

    async def test_segment_http_surface(self):
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            st, _, segs = await http_call(addr, "GET", "/v1/agent/segments")
            assert st == 200 and segs == [""]
            st, _, _x = await http_call(
                addr, "GET", "/v1/agent/members?segment=nope")
            assert st == 404
