"""Agent composition: local state anti-entropy, checks, user events.

Parity model: ``agent/local/state_test.go`` (sync full/changes),
``agent/checks/check_test.go`` (TTL expiry), ``agent/user_event.go``
dedup, ``ae/ae.go`` scale function.
"""

import asyncio

import pytest

from helpers import wait_for as wait_until

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.local import sync_scale_factor
from consul_tpu.net.transport import InMemoryNetwork
from consul_tpu.store.state import HEALTH_CRITICAL, HEALTH_PASSING


def make_agent(net, name, server=True, expect=1, **kw):
    cfg = AgentConfig(
        node_name=name,
        server=server,
        bootstrap_expect=expect,
        gossip_interval_scale=0.05,
        sync_interval_s=0.3,
        sync_retry_interval_s=0.2,
        reconcile_interval_s=0.2,
        **kw,
    )
    return Agent(
        cfg,
        gossip_transport=net.new_transport(f"{name}:gossip"),
        rpc_transport=net.new_transport(f"{name}:rpc"),
    )




def test_sync_scale_factor():
    # ae/ae.go:25-38 — 1.0 below threshold, +log2 above.
    assert sync_scale_factor(1) == 1.0
    assert sync_scale_factor(128) == 1.0
    assert sync_scale_factor(256) == 2.0
    assert sync_scale_factor(1024) == 4.0


class TestAntiEntropy:
    async def test_service_syncs_into_catalog(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        a.add_service({"service": "web", "port": 80, "tags": ["v1"]})
        store = a.delegate.store
        await wait_until(
            lambda: store.service_nodes("web")[1],
            msg="service pushed by anti-entropy",
        )
        svc = store.service_nodes("web")[1][0]
        assert svc["port"] == 80 and svc["node"] == "a0"
        await a.shutdown()

    async def test_remove_service_deregisters(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        a.add_service({"service": "web", "port": 80})
        store = a.delegate.store
        await wait_until(lambda: store.service_nodes("web")[1], msg="registered")
        a.remove_service("web")
        await wait_until(
            lambda: not store.service_nodes("web")[1],
            msg="service deregistered after removal",
        )
        await a.shutdown()

    async def test_full_sync_is_idempotent_no_spurious_writes(self):
        # Regression: normalization mismatch (None vs '') used to mark
        # every entry dirty and re-register the world each interval.
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        a.add_service({"service": "web", "port": 80})
        store = a.delegate.store
        await wait_until(
            lambda: store.service_nodes("web")[1], msg="registered"
        )
        await a.local.sync_full()  # settle
        idx_before = store.max_index("services", "checks")
        for _ in range(3):
            await a.local.sync_full()
        assert store.max_index("services", "checks") == idx_before
        assert all(e.in_sync for e in a.local.services.values())
        await a.shutdown()

    async def test_remote_only_service_purged_on_full_sync(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        await wait_until(lambda: a.delegate.is_leader(), msg="leader")
        # An old incarnation left a stray service in the catalog.
        await a.rpc("Catalog.Register", {
            "node": "a0", "address": "x",
            "service": {"service": "ghost", "id": "ghost"},
        })
        store = a.delegate.store
        assert store.service_nodes("ghost")[1]
        await wait_until(
            lambda: not store.service_nodes("ghost")[1],
            msg="stray service purged by next full sync",
        )
        await a.shutdown()


class TestChecks:
    async def test_ttl_check_lifecycle(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        a.add_service(
            {"service": "web", "port": 80},
            checks=[{"ttl": "0.4s"}],
        )
        store = a.delegate.store
        # Starts critical (no heartbeat yet) — reference default.
        await wait_until(
            lambda: any(
                c["check_id"] == "service:web"
                for c in store.node_checks("a0")[1]
            ),
            msg="ttl check registered",
        )

        assert a.update_ttl_check("service:web", HEALTH_PASSING, "all good")
        await wait_until(
            lambda: any(
                c["check_id"] == "service:web" and c["status"] == HEALTH_PASSING
                for c in store.node_checks("a0")[1]
            ),
            msg="check passing after heartbeat",
        )

        # Stop heartbeating: TTL flips it critical.
        await wait_until(
            lambda: any(
                c["check_id"] == "service:web" and c["status"] == HEALTH_CRITICAL
                for c in store.node_checks("a0")[1]
            ),
            msg="check critical after TTL lapse",
        )
        await a.shutdown()

    async def test_monitor_check_runs_command(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0")
        await a.start()
        a.add_check({"check_id": "always-ok", "script": "true", "interval": "0.1s"})
        store = a.delegate.store
        await wait_until(
            lambda: any(
                c["check_id"] == "always-ok" and c["status"] == HEALTH_PASSING
                for c in store.node_checks("a0")[1]
            ),
            msg="script check passing",
        )
        await a.shutdown()


class TestUserEvents:
    async def test_fire_and_receive_with_dedup(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0", expect=1)
        b = make_agent(net, "b0", server=False)
        await a.start()
        await b.start()
        await b.join(["a0:gossip"])
        await wait_until(
            lambda: len(b.serf.members) == 2, msg="gossip converged"
        )

        await a.fire_event("deploy", b"v1.2.3")
        await wait_until(
            lambda: any(e.name == "deploy" for e in b.events),
            msg="event reached the other agent",
        )
        ev = next(e for e in b.events if e.name == "deploy")
        assert ev.payload == b"v1.2.3"
        count = sum(1 for e in b.events if e.name == "deploy")
        await asyncio.sleep(0.3)  # rebroadcasts keep gossiping
        assert sum(1 for e in b.events if e.name == "deploy") == count  # deduped
        await b.shutdown()
        await a.shutdown()

    async def test_client_agent_rpc_via_server(self):
        net = InMemoryNetwork()
        a = make_agent(net, "a0", expect=1)
        b = make_agent(net, "b0", server=False)
        await a.start()
        await b.start()
        await b.join(["a0:gossip"])
        await wait_until(
            lambda: b.delegate.routers.servers(), msg="client found server"
        )
        b.add_service({"service": "db", "port": 5432})
        await wait_until(
            lambda: a.delegate.store.service_nodes("db")[1],
            msg="client service synced through server",
        )
        node = a.delegate.store.service_nodes("db")[1][0]["node"]
        assert node == "b0"
        await b.shutdown()
        await a.shutdown()
