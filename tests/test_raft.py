"""Raft consensus tests over the in-memory transport.

Mirrors the reference's raft test harness style (raft/testing.go:
in-proc clusters over InmemTransport, SURVEY.md §4.2): elect, replicate,
partition, heal, snapshot-install, membership change.
"""

import asyncio

import pytest

from consul_tpu.consensus.raft import (
    Entry,
    FSM,
    InmemRaftNet,
    NotLeaderError,
    RaftConfig,
    RaftNode,
)


class DictFSM(FSM):
    """Tiny KV FSM: entries are ("set", k, v); snapshot is the dict."""

    def __init__(self):
        self.data: dict = {}
        self.applied: list = []

    def apply(self, entry: Entry):
        op, k, v = entry.data
        assert op == "set"
        self.data[k] = v
        self.applied.append(entry.index)
        return ("ok", k, v)

    def snapshot(self):
        return dict(self.data)

    def restore(self, snap):
        self.data = dict(snap)
        self.applied = []


def make_cluster(n, net=None, **cfg_kwargs):
    net = net or InmemRaftNet()
    ids = [f"s{i}" for i in range(n)]
    nodes = []
    for nid in ids:
        fsm = DictFSM()
        node = RaftNode(RaftConfig(node_id=nid, **cfg_kwargs), fsm, net, ids)
        nodes.append(node)
    return net, nodes


from helpers import wait_for_leader  # noqa: E402 — canonical copy


async def shutdown_all(nodes):
    for n in nodes:
        await n.shutdown()
    await asyncio.sleep(0)


class TestElection:
    def test_single_node_self_elects_and_applies(self):
        async def run():
            net, nodes = make_cluster(1)
            await nodes[0].start()
            leader = await wait_for_leader(nodes)
            res = await leader.apply(("set", "a", 1))
            assert res == ("ok", "a", 1)
            assert leader.fsm.data == {"a": 1}
            await shutdown_all(nodes)

        asyncio.run(run())

    def test_three_node_elects_exactly_one_leader(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            assert sum(n.is_leader() for n in nodes) == 1
            assert all(n.current_term == leader.current_term for n in nodes)
            await shutdown_all(nodes)

        asyncio.run(run())

    def test_follower_apply_raises_not_leader_with_hint(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            follower = next(n for n in nodes if not n.is_leader())
            with pytest.raises(NotLeaderError) as ei:
                await follower.apply(("set", "x", 1))
            assert ei.value.leader_id == leader.id
            await shutdown_all(nodes)

        asyncio.run(run())


class TestReplication:
    def test_writes_replicate_to_all_fsms(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            for i in range(20):
                await leader.apply(("set", f"k{i}", i))
            # Followers apply asynchronously on the next heartbeat.
            await asyncio.sleep(0.3)
            for n in nodes:
                assert n.fsm.data == {f"k{i}": i for i in range(20)}
            await shutdown_all(nodes)

        asyncio.run(run())

    def test_leader_partition_reelects_and_old_leader_steps_down(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            await leader.apply(("set", "before", 1))
            rest = [n for n in nodes if n is not leader]
            net.partition({leader.id}, {n.id for n in rest})
            new_leader = await wait_for_leader(rest)
            assert new_leader.id != leader.id
            await new_leader.apply(("set", "after", 2))
            # Old leader cannot commit in minority.
            with pytest.raises((NotLeaderError, asyncio.TimeoutError)):
                await leader.apply(("set", "lost", 3), timeout=0.5)
            net.heal()
            await asyncio.sleep(0.6)
            assert not leader.is_leader() or leader.id == new_leader.id
            # Everyone converges; the minority write never committed.
            for n in nodes:
                assert n.fsm.data.get("after") == 2
                assert "lost" not in n.fsm.data
            await shutdown_all(nodes)

        asyncio.run(run())

    def test_divergent_follower_log_is_overwritten(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            follower = next(n for n in nodes if not n.is_leader())
            # Partition a follower, write on the majority side.
            net.partition(
                {follower.id}, {n.id for n in nodes if n is not follower}
            )
            for i in range(5):
                await leader.apply(("set", f"m{i}", i))
            net.heal()
            await asyncio.sleep(0.5)
            assert follower.fsm.data == leader.fsm.data
            assert follower.last_index() == leader.last_index()
            await shutdown_all(nodes)

        asyncio.run(run())


class TestSnapshot:
    def test_log_compaction_and_install_snapshot(self):
        async def run():
            net, nodes = make_cluster(
                3, snapshot_threshold=32, snapshot_trailing=8
            )
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            follower = next(n for n in nodes if not n.is_leader())
            net.partition(
                {follower.id}, {n.id for n in nodes if n is not follower}
            )
            # Enough writes to trip compaction on the majority side.
            for i in range(100):
                await leader.apply(("set", f"k{i}", i))
            await asyncio.sleep(0.2)
            assert leader.snapshot_index > 0
            assert len(leader.log) < 100
            # Healing forces an InstallSnapshot (follower is behind horizon).
            net.heal()
            await asyncio.sleep(1.0)
            assert follower.fsm.data == leader.fsm.data
            assert follower.snapshot_index > 0
            await shutdown_all(nodes)

        asyncio.run(run())


class TestMembership:
    def test_add_voter_catches_up_and_votes(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            await leader.apply(("set", "seed", 1))

            newcomer = RaftNode(
                RaftConfig(node_id="s9"), DictFSM(), net, voters=["s9"]
            )
            newcomer.voters = []  # joins with no vote until config entry
            await newcomer.start()
            await leader.add_voter("s9")
            await asyncio.sleep(0.5)
            assert "s9" in leader.voters
            assert newcomer.fsm.data.get("seed") == 1
            await leader.apply(("set", "post", 2))
            await asyncio.sleep(0.3)
            assert newcomer.fsm.data.get("post") == 2
            await shutdown_all(nodes + [newcomer])

        asyncio.run(run())

    def test_remove_server_shrinks_quorum(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            victim = next(n for n in nodes if not n.is_leader())
            await leader.remove_server(victim.id)
            await victim.shutdown()
            # 2-node cluster still commits (quorum 2 of 2).
            await leader.apply(("set", "still", 1))
            assert leader.fsm.data["still"] == 1
            await shutdown_all(nodes)

        asyncio.run(run())


class TestBarrier:
    def test_barrier_sees_prior_commits(self):
        async def run():
            net, nodes = make_cluster(3)
            for n in nodes:
                await n.start()
            leader = await wait_for_leader(nodes)
            for i in range(5):
                await leader.apply(("set", f"b{i}", i))
            await leader.barrier()
            assert len(leader.fsm.data) == 5
            await shutdown_all(nodes)

        asyncio.run(run())
