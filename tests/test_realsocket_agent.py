"""Tier-4 black-box: full agents over REAL sockets.

The reference's sdk/testutil/server.go:205-264 boots real consul
binaries and drives them over localhost; this is the same level for the
framework — server + client agents with UDP gossip/RPC transports on
real ports, a real HTTP server, and a real DNS socket.  Everything the
in-memory suites prove must also hold when actual packets move.
"""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import requires_crypto

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.dns import DNSServer
from consul_tpu.agent.http import HTTPApi
from consul_tpu.net.transport import UDPTransport

from test_http_dns import dns_query, http_call


async def _real_agent(name, server=True, bootstrap_expect=1):
    gossip = UDPTransport("127.0.0.1", 0)
    rpc = UDPTransport("127.0.0.1", 0)
    await gossip.start()
    await rpc.start()
    agent = Agent(
        AgentConfig(node_name=name, server=server,
                    bootstrap_expect=bootstrap_expect,
                    gossip_interval_scale=0.05, sync_interval_s=0.3,
                    sync_retry_interval_s=0.2, reconcile_interval_s=0.2),
        gossip_transport=gossip,
        rpc_transport=rpc,
    )
    await agent.start()
    return agent, gossip.local_addr()


class TestRealSocketCluster:
    async def test_join_kv_dns_over_real_sockets(self):
        s1, s1_addr = await _real_agent("rs-server", server=True)
        c1, _ = await _real_agent("rs-client", server=False)
        api = None
        dns = None
        try:
            await wait_until(lambda: s1.delegate.is_leader(),
                             msg="server elected itself")
            # Client joins over real UDP gossip.
            assert await c1.join([s1_addr]) == 1
            await wait_until(
                lambda: set(s1.serf.members) >= {"rs-server", "rs-client"},
                msg="gossip converged over real sockets",
            )
            await wait_until(lambda: c1.delegate.routers.servers(),
                             msg="client discovered the server")

            # HTTP against the CLIENT agent: the KV write crosses the
            # real RPC socket to the server's raft.
            api = HTTPApi(c1)
            addr = await api.start()
            st, _, ok = await http_call(addr, "PUT", "/v1/kv/rs/x", b"v1")
            assert st == 200 and ok is True
            assert s1.delegate.store.kv_get("rs/x")[1]["value"] == b"v1"
            st, _, rows = await http_call(addr, "GET", "/v1/kv/rs/x")
            assert st == 200 and rows[0]["Key"] == "rs/x"

            # Service registration syncs through anti-entropy, then
            # resolves over a real DNS socket.
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/service/register",
                b'{"Name": "web", "Port": 8080}')
            assert st == 200
            await wait_until(
                lambda: s1.delegate.store.service_nodes("web")[1],
                msg="service synced to the catalog",
            )
            dns = DNSServer(c1)
            dns_addr = await dns.start()
            _, rcode, answers = await dns_query(
                dns_addr, "web.service.consul")
            assert rcode == 0 and answers
        finally:
            if dns:
                await dns.stop()
            if api:
                await api.stop()
            await c1.shutdown()
            await s1.shutdown()


class TestMaintenanceMode:
    async def test_service_and_node_maintenance(self):
        from test_http_dns import dev_stack

        async with dev_stack() as (agent, addr, _dns, dns_addr):
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/service/register",
                b'{"Name": "web", "Port": 8080}')
            assert st == 200
            await wait_until(
                lambda: agent.delegate.store.check_service_nodes(
                    "web", passing_only=True)[1],
                msg="service passing",
            )
            # Enable service maintenance: a critical synthetic check
            # pulls it from passing-only discovery (agent.go:3411).
            st, _, ok = await http_call(
                addr, "PUT",
                "/v1/agent/service/maintenance/web?enable=true"
                "&reason=redeploy")
            assert st == 200 and ok is True
            await wait_until(
                lambda: not agent.delegate.store.check_service_nodes(
                    "web", passing_only=True)[1],
                msg="maintenance hides the service",
            )
            # The synthetic check carries the reason.
            st, _, checks = await http_call(addr, "GET", "/v1/agent/checks")
            mcheck = checks.get("_service_maintenance:web")
            assert mcheck and "redeploy" in mcheck["Notes"]
            # Disable restores discovery.
            st, _, _x = await http_call(
                addr, "PUT",
                "/v1/agent/service/maintenance/web?enable=false")
            assert st == 200
            await wait_until(
                lambda: agent.delegate.store.check_service_nodes(
                    "web", passing_only=True)[1],
                msg="service visible again",
            )
            # Node-wide maintenance.
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/maintenance?enable=true")
            assert st == 200
            assert agent.in_node_maintenance()
            await wait_until(
                lambda: not agent.delegate.store.check_service_nodes(
                    "web", passing_only=True)[1],
                msg="node maintenance hides every service",
            )
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/maintenance?enable=false")
            assert st == 200
            assert not agent.in_node_maintenance()
            # Bad query param is a 400.
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/maintenance")
            assert st == 400
            # Unknown service id is a 404.
            st, _, _x = await http_call(
                addr, "PUT",
                "/v1/agent/service/maintenance/ghost?enable=true")
            assert st == 404


class TestNewWatchTypes:
    @requires_crypto
    async def test_connect_roots_leaf_and_agent_service_watches(self):
        from test_http_dns import dev_stack

        from consul_tpu.api import ConsulClient, parse_watch

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            st, _, _x = await http_call(
                addr, "PUT", "/v1/agent/service/register",
                b'{"Name": "web", "Port": 8080}')
            assert st == 200
            c = ConsulClient(addr)
            # Prime the CA (it initializes lazily on first sign) so the
            # roots watch has something to deliver.
            st, _, leaf0 = await http_call(
                addr, "GET", "/v1/agent/connect/ca/leaf/web")
            assert st == 200 and leaf0["CertPEM"]
            seen = {"roots": [], "leaf": [], "svc": []}

            plans = []
            for wtype, params, bucket in (
                ("connect_roots", {}, "roots"),
                ("connect_leaf", {"service": "web"}, "leaf"),
                ("agent_service", {"service_id": "web"}, "svc"),
            ):
                plan = parse_watch({"type": wtype, **params}, c)
                plan.on_change(
                    lambda idx, data, b=bucket: seen[b].append(data))
                plan.start()
                plans.append(plan)
            try:
                await wait_until(
                    lambda: (seen["roots"]
                             and seen["roots"][-1]["Roots"]
                             and seen["leaf"] and seen["svc"]),
                    timeout=15, msg="all three watches fired",
                )
            finally:
                for plan in plans:
                    plan.stop()
            assert seen["roots"][-1]["Roots"][0]["RootCert"]
            assert seen["leaf"][0]["CertPEM"]
            assert seen["svc"][0]["Service"] == "web"
            # The cached leaf is STABLE: the watch must not refire with
            # a fresh signature every poll.
            assert len(seen["leaf"]) == 1


def test_unknown_watch_type_rejected():
    from consul_tpu.api import parse_watch

    with pytest.raises(ValueError, match="unknown watch type"):
        parse_watch({"type": "nope"}, None)
    with pytest.raises(ValueError, match="requires"):
        parse_watch({"type": "agent_service"}, None)
