"""ACL system end-to-end: policy precedence, token resolution, RPC/HTTP
enforcement (403s), bootstrap, and list filtering.

Parity model: acl/policy_test.go + acl/acl_test.go (precedence),
agent/consul/acl_endpoint_test.go (bootstrap one-shot),
agent/http_test.go (parseToken, 403 mapping).
"""

import asyncio
import contextlib
import json

import pytest

from helpers import wait_for as wait_until
from helpers import requires_crypto

from consul_tpu.acl.engine import (
    ACLError,
    ACLResolver,
    Authorizer,
    DENY,
    READ,
    WRITE,
    parse_policy,
)
from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.http import HTTPApi
from consul_tpu.net.transport import InMemoryNetwork

from test_http_dns import http_call


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


# ---------------------------------------------------------------------------
# engine precedence (acl/policy.go + acl.go)
# ---------------------------------------------------------------------------


def test_longest_prefix_wins_and_exact_beats_prefix():
    p = parse_policy({
        "key_prefix": {"": {"policy": "deny"},
                       "app/": {"policy": "read"}},
        "key": {"app/rw": {"policy": "write"}},
    })
    a = Authorizer([p])
    assert not a.key_read("other")          # "" prefix deny
    assert a.key_read("app/x")              # app/ read
    assert not a.key_write("app/x")
    assert a.key_write("app/rw")            # exact write beats app/ read


def test_merged_policies_deny_wins_on_tie():
    p1 = parse_policy({"key_prefix": {"a/": {"policy": "write"}}})
    p2 = parse_policy({"key_prefix": {"a/": {"policy": "deny"}}})
    a = Authorizer([p1, p2])
    assert not a.key_read("a/x")


def test_resolver_unknown_token_and_cache():
    tokens = {"s1": {"secret_id": "s1", "policies": ["p1"]}}
    policies = {"p1": {"id": "p1", "rules": json.dumps(
        {"key_prefix": {"": {"policy": "read"}}}
    )}}
    r = ACLResolver(tokens.get, policies.get, enabled=True,
                    default_policy="deny", ttl_s=60)
    with pytest.raises(ACLError):
        r.resolve("nope")
    a = r.resolve("s1")
    assert a.key_read("anything") and not a.key_write("anything")
    # Anonymous under default deny.
    assert not r.resolve("").key_read("x")


# ---------------------------------------------------------------------------
# HTTP end-to-end
# ---------------------------------------------------------------------------

MASTER = "root-token"


@contextlib.asynccontextmanager
async def acl_stack(default_policy="deny", master=MASTER):
    net = InMemoryNetwork()
    agent = Agent(
        AgentConfig(node_name="dev", bootstrap_expect=1,
                    gossip_interval_scale=0.05, sync_interval_s=0.3,
                    sync_retry_interval_s=0.2, reconcile_interval_s=0.2,
                    acl_enabled=True, acl_default_policy=default_policy,
                    acl_master_token=master, acl_agent_token=master),
        gossip_transport=net.new_transport("dev:gossip"),
        rpc_transport=net.new_transport("dev:rpc"),
    )
    await agent.start()
    await wait_until(lambda: agent.delegate.is_leader(), msg="leader")
    api = HTTPApi(agent)
    addr = await api.start()
    try:
        yield agent, addr
    finally:
        await api.stop()
        await agent.shutdown()


class TestHTTPEnforcement:
    async def test_anonymous_denied_master_allowed(self):
        async with acl_stack() as (_agent, addr):
            st, _, body = await http_call(addr, "PUT", "/v1/kv/app/x", b"v")
            assert st == 403, body
            st, _, _b = await http_call(addr, "GET", "/v1/kv/app/x")
            assert st == 403
            st, _, ok = await http_call(
                addr, "PUT", f"/v1/kv/app/x?token={MASTER}", b"v"
            )
            assert st == 200 and ok is True
            st, _, rows = await http_call(
                addr, "GET", "/v1/kv/app/x",
                headers={"X-Consul-Token": MASTER},
            )
            assert st == 200 and rows

    async def test_policy_token_read_write_deny_precedence(self):
        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            # Policy: read under app/, write on the exact app/rw,
            # deny under app/secret/.
            rules = json.dumps({
                "key_prefix": {"app/": {"policy": "read"},
                               "app/secret/": {"policy": "deny"}},
                "key": {"app/rw": {"policy": "write"}},
            })
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "app", "Rules": rules}).encode(),
                headers=mk,
            )
            assert st == 200, pol
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Policies": [pol["ID"]]}).encode(),
                headers=mk,
            )
            assert st == 200, tok
            secret = tok["SecretID"]
            hdr = {"X-Consul-Token": secret}

            # Seed data as master.
            for k in ("app/a", "app/secret/s", "outside"):
                st, _, _x = await http_call(
                    addr, "PUT", f"/v1/kv/{k}?token={MASTER}", b"v")
                assert st == 200

            # read allowed under app/
            st, _, rows = await http_call(addr, "GET", "/v1/kv/app/a",
                                          headers=hdr)
            assert st == 200 and rows
            # write denied under app/ (read-only)
            st, _, _x = await http_call(addr, "PUT", "/v1/kv/app/a", b"w",
                                        headers=hdr)
            assert st == 403
            # exact write rule allows the write
            st, _, ok = await http_call(addr, "PUT", "/v1/kv/app/rw", b"w",
                                        headers=hdr)
            assert st == 200 and ok is True
            # deny rule beats the read prefix
            st, _, _x = await http_call(addr, "GET", "/v1/kv/app/secret/s",
                                        headers=hdr)
            assert st == 403
            # outside any rule: default deny
            st, _, _x = await http_call(addr, "GET", "/v1/kv/outside",
                                        headers=hdr)
            assert st == 403

            # Recursive list is FILTERED, not denied (consul/filter.go):
            # app/secret/s drops out, app/a and app/rw remain.
            st, _, rows = await http_call(addr, "GET", "/v1/kv/app?recurse",
                                          headers=hdr)
            assert st == 200
            keys = {r["Key"] for r in rows}
            assert keys == {"app/a", "app/rw"}

    async def test_service_catalog_enforcement(self):
        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            st, _, _x = await http_call(
                addr, "PUT", "/v1/catalog/register",
                json.dumps({"Node": "n1", "Address": "10.0.0.1",
                            "Service": {"Service": "web", "Port": 80}}
                           ).encode(),
            )
            assert st == 403
            st, _, _x = await http_call(
                addr, "PUT", "/v1/catalog/register",
                json.dumps({"Node": "n1", "Address": "10.0.0.1",
                            "Service": {"Service": "web", "Port": 80}}
                           ).encode(),
                headers=mk,
            )
            assert st == 200
            st, _, _x = await http_call(addr, "GET",
                                        "/v1/health/service/web")
            assert st == 403
            st, _, rows = await http_call(addr, "GET",
                                          "/v1/health/service/web",
                                          headers=mk)
            assert st == 200 and rows

    async def test_token_secrets_redacted_without_acl_write(self):
        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            rules = json.dumps({"acl": "read"})
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "aclread", "Rules": rules}).encode(),
                headers=mk,
            )
            assert st == 200
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Policies": [pol["ID"]]}).encode(),
                headers=mk,
            )
            assert st == 200
            st, _, tokens = await http_call(
                addr, "GET", "/v1/acl/tokens",
                headers={"X-Consul-Token": tok["SecretID"]},
            )
            assert st == 200
            assert all(t["SecretID"] == "<hidden>" for t in tokens)


class TestBootstrap:
    async def test_bootstrap_once(self):
        async with acl_stack(master="") as (_agent, addr):
            st, _, tok = await http_call(addr, "PUT", "/v1/acl/bootstrap")
            assert st == 200 and tok["Type"] == "management"
            secret = tok["SecretID"]
            # The bootstrap token is a working management token.
            st, _, ok = await http_call(
                addr, "PUT", f"/v1/kv/x?token={secret}", b"v")
            assert st == 200 and ok is True
            # Second bootstrap is refused.
            st, _, err = await http_call(addr, "PUT", "/v1/acl/bootstrap")
            assert st == 400
            assert "no longer allowed" in str(err)


class TestHardenedSurfaces:
    """Round-3 ACL hardening: keyring, force-leave, AutoEncrypt.Sign,
    Subscribe streaming, and delete-tree subtree checks (reference:
    internal_endpoint.go:414-422, agent_endpoint.go:499,
    subscribe.go filterByAuth, acl.go KeyWritePrefix)."""

    async def test_keyring_requires_acl(self):
        async with acl_stack() as (_agent, addr):
            st, _, _b = await http_call(addr, "GET", "/v1/operator/keyring")
            assert st == 403
            st, _, _b = await http_call(
                addr, "POST", "/v1/operator/keyring",
                json.dumps({"Key": "x"}).encode())
            assert st == 403
            # Master passes the ACL gate (the op itself may 400 when
            # gossip encryption is off — that is not a 403).
            st, _, _b = await http_call(
                addr, "GET", "/v1/operator/keyring",
                headers={"X-Consul-Token": MASTER})
            assert st != 403

    async def test_client_agent_enforces_via_servers(self):
        """CLIENT agents have no resolver — the check must resolve
        through the servers (Internal.ACLAuthorize), not silently
        no-op (consul/acl.go ResolveToken from non-servers)."""
        async with acl_stack() as (server_agent, _addr):
            net = server_agent.serf.memberlist.transport._net
            client = Agent(
                AgentConfig(node_name="c1", server=False,
                            gossip_interval_scale=0.05, acl_enabled=True),
                gossip_transport=net.new_transport("c1:gossip"),
                rpc_transport=net.new_transport("c1:rpc"),
            )
            await client.start()
            try:
                await client.join(["dev:gossip"])
                await wait_until(lambda: client.delegate.routers.servers(),
                                 msg="client found server")
                capi = HTTPApi(client)
                caddr = await capi.start()
                try:
                    st, _, _b = await http_call(
                        caddr, "GET", "/v1/operator/keyring")
                    assert st == 403
                    st, _, _b = await http_call(
                        caddr, "PUT", "/v1/agent/force-leave/ghost")
                    assert st == 403
                    st, _, _b = await http_call(
                        caddr, "GET", "/v1/operator/keyring",
                        headers={"X-Consul-Token": MASTER})
                    assert st != 403
                finally:
                    await capi.stop()
            finally:
                await client.shutdown()

    async def test_force_leave_requires_operator_write(self):
        async with acl_stack() as (_agent, addr):
            st, _, _b = await http_call(
                addr, "PUT", "/v1/agent/force-leave/ghost")
            assert st == 403
            st, _, _b = await http_call(
                addr, "PUT", "/v1/agent/force-leave/ghost",
                headers={"X-Consul-Token": MASTER})
            assert st == 404  # gate passed; no such failed member

    @requires_crypto
    async def test_auto_encrypt_sign_requires_node_write(self):
        from consul_tpu.agent.rpc import RPCError

        async with acl_stack() as (agent, _addr):
            with pytest.raises(RPCError, match="Permission denied"):
                await agent.rpc("AutoEncrypt.Sign", {"node": "mallory"})
            out = await agent.rpc(
                "AutoEncrypt.Sign", {"node": "n1", "token": MASTER})
            assert out["leaf"]["cert_pem"] and out["roots"]

    async def test_subscribe_filters_unreadable_events(self):
        async with acl_stack() as (agent, addr):
            mk = {"X-Consul-Token": MASTER}
            rules = json.dumps({"key_prefix": {"pub/": {"policy": "read"}}})
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "pubread", "Rules": rules}).encode(),
                headers=mk)
            assert st == 200
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Policies": [pol["ID"]]}).encode(), headers=mk)
            assert st == 200
            for k in ("pub/a", "priv/b"):
                st, _, _x = await http_call(
                    addr, "PUT", f"/v1/kv/{k}?token={MASTER}", b"v")
                assert st == 200

            server = agent.delegate
            gen = server.rpc_server._endpoints["Subscribe"].subscribe(
                {"topic": "kv", "token": tok["SecretID"]})
            seen = []
            async for ev in gen:
                if ev.get("end_of_snapshot"):
                    break
                seen.append(ev["key"])
            assert seen == ["pub/a"]  # priv/b filtered, not denied

            # Live phase: the unreadable write never surfaces.
            for k in ("priv/d", "pub/c"):
                st, _, _x = await http_call(
                    addr, "PUT", f"/v1/kv/{k}?token={MASTER}", b"v")
                assert st == 200
            ev = await asyncio.wait_for(gen.__anext__(), timeout=5)
            assert ev["key"] == "pub/c"
            await gen.aclose()

    async def test_delete_tree_needs_write_on_whole_subtree(self):
        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            rules = json.dumps({
                "key_prefix": {"": {"policy": "write"},
                               "app/secret/": {"policy": "deny"}},
            })
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "almost-all", "Rules": rules}).encode(),
                headers=mk)
            assert st == 200
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Policies": [pol["ID"]]}).encode(), headers=mk)
            assert st == 200
            hdr = {"X-Consul-Token": tok["SecretID"]}
            for k in ("app/a", "app/secret/s"):
                st, _, _x = await http_call(
                    addr, "PUT", f"/v1/kv/{k}?token={MASTER}", b"v")
                assert st == 200

            # Longest-prefix on "app/" alone would say write — but the
            # subtree holds a denied child, so the recursive delete is
            # refused outright (acl.KeyWritePrefix).
            st, _, _x = await http_call(
                addr, "DELETE", "/v1/kv/app/?recurse", headers=hdr)
            assert st == 403
            st, _, rows = await http_call(
                addr, "GET", "/v1/kv/app/secret/s", headers=mk)
            assert st == 200 and rows
            # A subtree with no deny rules inside deletes fine.
            st, _, ok = await http_call(
                addr, "DELETE", "/v1/kv/other/?recurse", headers=hdr)
            assert st == 200

            # The same guard holds through /v1/txn (txn_endpoint.go
            # vets each op like the single-op path).
            st, _, _x = await http_call(
                addr, "PUT", "/v1/txn",
                json.dumps([{"KV": {"Verb": "delete-tree",
                                    "Key": "app/"}}]).encode(),
                headers=hdr)
            assert st == 403
            st, _, rows = await http_call(
                addr, "GET", "/v1/kv/app/secret/s", headers=mk)
            assert st == 200 and rows


# ---------------------------------------------------------------------------
# roles / auth methods / binding rules / login (acl_endpoint.go,
# acl_authmethod.go, authmethod/authmethods.go)
# ---------------------------------------------------------------------------


def test_role_expansion_and_identities_in_resolver():
    roles = {"r1": {"id": "r1", "name": "ops", "policies": ["p1"],
                    "service_identities": [{"service_name": "web"}]}}
    policies = {"p1": {"id": "p1", "rules": json.dumps(
        {"key_prefix": {"cfg/": {"policy": "write"}}})}}
    tokens = {"s1": {"secret_id": "s1", "roles": ["r1"]}}
    r = ACLResolver(tokens.get, policies.get, enabled=True,
                    default_policy="deny", role_lookup=roles.get)
    a = r.resolve("s1")
    assert a.key_write("cfg/x")                 # via role -> policy
    assert a.service_write("web")               # via role -> identity
    assert a.service_write("web-sidecar-proxy")
    assert a.service_read("other")              # discovery read
    assert not a.service_write("other")
    assert not a.key_read("elsewhere")


def test_expired_token_resolves_as_not_found():
    import time as _time
    tokens = {"s1": {"secret_id": "s1", "policies": [],
                     "expiration_time": _time.time() - 1}}
    r = ACLResolver(tokens.get, lambda _p: None, enabled=True,
                    default_policy="deny")
    with pytest.raises(ACLError):
        r.resolve("s1")


def test_jwt_hs256_roundtrip_and_bindings():
    from consul_tpu.acl import jwt as jwt_mod

    tok = jwt_mod.encode_hs256(
        {"iss": "idp", "aud": "consul", "sub": "alice",
         "ns": "team-a", "groups": ["dev", "ops"]}, "sekrit")
    claims = jwt_mod.validate(tok, secret="sekrit", bound_issuer="idp",
                              bound_audiences=["consul"])
    assert claims["sub"] == "alice"
    with pytest.raises(jwt_mod.JWTError):
        jwt_mod.validate(tok, secret="wrong")
    with pytest.raises(jwt_mod.JWTError):
        jwt_mod.validate(tok, secret="sekrit", bound_issuer="other")
    import time as _time
    expired = jwt_mod.encode_hs256(
        {"iss": "idp", "exp": _time.time() - 3600}, "sekrit")
    with pytest.raises(jwt_mod.JWTError):
        jwt_mod.validate(expired, secret="sekrit")
    sel, proj = jwt_mod.identity_from_claims(
        claims, {"sub": "user", "ns": "namespace"}, {"groups": "groups"})
    assert sel["value"] == {"user": "alice", "namespace": "team-a"}
    assert sel["list"]["groups"] == ["dev", "ops"]
    assert proj["user"] == "alice"


class TestRolesAndLogin:
    async def test_role_crud_and_token_with_role(self):
        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            rules = json.dumps(
                {"key_prefix": {"cfg/": {"policy": "write"}}})
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "cfg", "Rules": rules}).encode(),
                headers=mk)
            assert st == 200
            st, _, role = await http_call(
                addr, "PUT", "/v1/acl/role",
                json.dumps({"Name": "ops",
                            "Policies": [pol["ID"]]}).encode(),
                headers=mk)
            assert st == 200, role
            # read by name
            st, _, got = await http_call(
                addr, "GET", "/v1/acl/role/name/ops", headers=mk)
            assert st == 200 and got["ID"] == role["ID"]
            # duplicate name refused
            st, _, err = await http_call(
                addr, "PUT", "/v1/acl/role",
                json.dumps({"Name": "ops"}).encode(), headers=mk)
            assert st == 400, err
            # token linked to the role gets the role's policies
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Roles": [role["ID"]]}).encode(), headers=mk)
            assert st == 200
            hdr = {"X-Consul-Token": tok["SecretID"]}
            st, _, ok = await http_call(
                addr, "PUT", "/v1/kv/cfg/a", b"v", headers=hdr)
            assert st == 200 and ok is True
            st, _, _x = await http_call(
                addr, "PUT", "/v1/kv/other", b"v", headers=hdr)
            assert st == 403

    async def test_login_flow_end_to_end(self):
        from consul_tpu.acl import jwt as jwt_mod

        async with acl_stack() as (_agent, addr):
            mk = {"X-Consul-Token": MASTER}
            # policy + role the binding rule will bind to
            rules = json.dumps(
                {"key_prefix": {"team-a/": {"policy": "write"}}})
            st, _, pol = await http_call(
                addr, "PUT", "/v1/acl/policy",
                json.dumps({"Name": "team-a-kv",
                            "Rules": rules}).encode(), headers=mk)
            assert st == 200
            st, _, _role = await http_call(
                addr, "PUT", "/v1/acl/role",
                json.dumps({"Name": "team-a",
                            "Policies": [pol["ID"]]}).encode(),
                headers=mk)
            assert st == 200
            # jwt auth method + binding rule with selector and
            # interpolated bind name
            st, _, meth = await http_call(
                addr, "PUT", "/v1/acl/auth-method",
                json.dumps({
                    "Name": "idp", "Type": "jwt",
                    "MaxTokenTTLS": 60,
                    "Config": {
                        "JwtSecret": "sekrit",
                        "BoundIssuer": "https://idp",
                        "ClaimMappings": {"team": "team"},
                    },
                }).encode(), headers=mk)
            assert st == 200, meth
            st, _, br = await http_call(
                addr, "PUT", "/v1/acl/binding-rule",
                json.dumps({
                    "AuthMethod": "idp",
                    "Selector": 'value.team == "team-a"',
                    "BindType": "role",
                    "BindName": "${team}",
                }).encode(), headers=mk)
            assert st == 200, br

            # login with a matching JWT
            bearer = jwt_mod.encode_hs256(
                {"iss": "https://idp", "team": "team-a"}, "sekrit")
            st, _, tok = await http_call(
                addr, "POST", "/v1/acl/login",
                json.dumps({"AuthMethod": "idp",
                            "BearerToken": bearer}).encode())
            assert st == 200, tok
            assert tok["AuthMethod"] == "idp"
            assert tok["ExpirationTime"] > 0
            hdr = {"X-Consul-Token": tok["SecretID"]}
            st, _, ok = await http_call(
                addr, "PUT", "/v1/kv/team-a/x", b"v", headers=hdr)
            assert st == 200 and ok is True
            st, _, _x = await http_call(
                addr, "PUT", "/v1/kv/other", b"v", headers=hdr)
            assert st == 403

            # wrong team -> selector mismatch -> 403, no token minted
            bad = jwt_mod.encode_hs256(
                {"iss": "https://idp", "team": "team-b"}, "sekrit")
            st, _, err = await http_call(
                addr, "POST", "/v1/acl/login",
                json.dumps({"AuthMethod": "idp",
                            "BearerToken": bad}).encode())
            assert st == 403, err
            # bad signature -> 403
            forged = jwt_mod.encode_hs256(
                {"iss": "https://idp", "team": "team-a"}, "wrong")
            st, _, err = await http_call(
                addr, "POST", "/v1/acl/login",
                json.dumps({"AuthMethod": "idp",
                            "BearerToken": forged}).encode())
            assert st == 403, err

            # logout destroys the login token
            st, _, _x = await http_call(
                addr, "POST", "/v1/acl/logout", headers=hdr)
            assert st == 200
            st, _, _x = await http_call(
                addr, "PUT", "/v1/kv/team-a/y", b"v", headers=hdr)
            assert st == 403
            # a non-login token (master) cannot log out
            st, _, _x = await http_call(
                addr, "POST", "/v1/acl/logout", headers=mk)
            assert st == 403

    async def test_token_ttl_expires_and_reaps(self):
        async with acl_stack() as (agent, addr):
            agent.delegate.config.acl_token_reap_interval_s = 0.2
            mk = {"X-Consul-Token": MASTER}
            st, _, tok = await http_call(
                addr, "PUT", "/v1/acl/token",
                json.dumps({"Policies": [],
                            "ExpirationTTLS": 0.5}).encode(), headers=mk)
            assert st == 200 and tok["ExpirationTime"] > 0
            secret = tok["SecretID"]
            # valid now (resolves; default-deny means 403 on kv, but
            # NOT "ACL not found")
            st, _, _x = await http_call(
                addr, "GET", "/v1/kv/x",
                headers={"X-Consul-Token": secret})
            assert st == 403
            await asyncio.sleep(0.7)
            # expired: resolution now fails as not-found (still 403 at
            # HTTP), and the leader reaper deletes the row
            st, _, _x = await http_call(
                addr, "GET", "/v1/kv/x",
                headers={"X-Consul-Token": secret})
            assert st == 403
            await wait_until(
                lambda: agent.delegate.store.acl_token_get(secret) is None,
                msg="expired token reaped")

    async def test_auth_method_delete_cascades(self):
        from consul_tpu.acl import jwt as jwt_mod

        async with acl_stack() as (agent, addr):
            mk = {"X-Consul-Token": MASTER}
            st, _, _m = await http_call(
                addr, "PUT", "/v1/acl/auth-method",
                json.dumps({"Name": "idp", "Type": "jwt",
                            "Config": {"JwtSecret": "s"}}).encode(),
                headers=mk)
            assert st == 200
            st, _, br = await http_call(
                addr, "PUT", "/v1/acl/binding-rule",
                json.dumps({"AuthMethod": "idp", "BindType": "service",
                            "BindName": "api"}).encode(), headers=mk)
            assert st == 200
            bearer = jwt_mod.encode_hs256({"sub": "x"}, "s")
            st, _, tok = await http_call(
                addr, "POST", "/v1/acl/login",
                json.dumps({"AuthMethod": "idp",
                            "BearerToken": bearer}).encode())
            assert st == 200
            # the login token carries a service identity -> can
            # register/write the bound service
            authz = agent.delegate.acl.resolve(tok["SecretID"])
            assert authz.service_write("api")
            st, _, _x = await http_call(
                addr, "DELETE", "/v1/acl/auth-method/idp", headers=mk)
            assert st == 200
            # cascade: binding rule + minted token both gone
            store = agent.delegate.store
            assert store.acl_binding_rule_get(br["ID"]) is None
            assert store.acl_token_get(tok["SecretID"]) is None


class TestMonitorACL:
    async def test_monitor_requires_agent_read(self):
        """/v1/agent/monitor is gated on agent:read
        (agent_endpoint.go AgentMonitor)."""
        async with acl_stack() as (_agent, addr):
            st, _, _b = await http_call(addr, "GET", "/v1/agent/monitor")
            assert st == 403
            # Master token passes the gate: status line says 200 and the
            # response is a chunked stream (read just the head).
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write((
                "GET /v1/agent/monitor HTTP/1.1\r\n"
                f"Host: {host}\r\nX-Consul-Token: {MASTER}\r\n\r\n"
            ).encode())
            await writer.drain()
            status_line = await asyncio.wait_for(reader.readline(), 10)
            assert b"200" in status_line
            writer.close()
