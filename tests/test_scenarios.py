"""Scenario preset smoke tests (the million-node presets are exercised
by bench.py on real hardware; here only the CPU-scale ones run)."""

import pytest

from consul_tpu.sim import SCENARIOS, run_scenario


def test_registry_covers_baseline_configs():
    assert set(SCENARIOS) == {
        "dev3", "probe1k", "event100k", "suspect1m", "multidc1m"
    }


def test_dev3_converges():
    out = run_scenario("dev3")
    assert out["infected_final"] == 3
    assert out["t99_ms"] is not None


def test_unknown_scenario_raises():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("nope")
