"""BASELINE.json study configs: registry + TIMING pins.

Configs 2-5 get the same treatment test_swim_paper.py gives the paper
curve: convergence times asserted against bounds DERIVED from the
protocol's own formulas (probe cadence, suspicion timeout scaling,
serf's convergence basis) — not magic numbers — so a regression in the
underlying models cannot hide behind a smoke-level "it ran" check.
(The million-node presets also run on real hardware via bench.py; here
they run CPU-scale/virtual-mesh.)
"""

import math

import pytest

from consul_tpu.protocol import LAN, WAN, suspicion_timeout_bounds
from consul_tpu.sim import SCENARIOS, run_scenario


def test_registry_covers_baseline_configs():
    assert set(SCENARIOS) == {
        "dev3", "probe1k", "event100k", "stream100k", "geo100k",
        "suspect1m", "multidc1m", "degraded1m",
    }


def test_dev3_converges():
    out = run_scenario("dev3")
    assert out["infected_final"] == 3
    assert out["t99_ms"] is not None


def test_unknown_scenario_raises():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("nope")


@pytest.mark.slow  # ~35s at CPU: full probe1k scenario sims
def test_probe1k_timing_pins():
    """Config 2: 1k nodes, 1% concurrent crashes, fanout 3.

    First suspicion is the probe plane's job: with n-10 live probers
    each probing once per ProbeInterval (LAN 1 s), a crashed subject is
    probed on average once per interval — detection lands within a few
    intervals, never before one.  Convergence to DEAD adds the
    Lifeguard suspicion window: min timeout = SuspicionMult * log10(n)
    * ProbeInterval (suspicion.go), plus dissemination slack."""
    out = run_scenario("probe1k")
    assert out["all_detected"] is True

    probe_ms = LAN.probe_interval_ms
    assert probe_ms <= out["mean_first_suspect_ms"] <= 10 * probe_ms

    sus_lo_ms, _hi = suspicion_timeout_bounds(
        LAN.suspicion_mult, LAN.suspicion_max_timeout_mult, 1000,
        LAN.probe_interval_ms,
    )
    # Death can't be declared before the minimum suspicion window after
    # first suspicion; full convergence follows within ~2x the window.
    assert out["mean_converged_ms"] >= sus_lo_ms
    assert out["mean_converged_ms"] <= out["mean_first_suspect_ms"] \
        + 2 * sus_lo_ms


def test_event100k_timing_pins():
    """Config 3: 100k-node broadcast, LAN fanout 4 — serf's own
    convergence basis (lib/serf docs: ~log-time full infection, well
    under 3 s simulated for 100k on LAN timing)."""
    out = run_scenario("event100k")
    assert out["infected_final"] == 100_000
    # Epidemic lower bound: can't beat log_fanout(n) rounds.
    min_rounds = math.log(100_000) / math.log(1 + 4)
    assert out["t99_ms"] >= min_rounds * LAN.gossip_interval_ms / 2
    assert out["t9999_ms"] <= 3000


@pytest.mark.slow  # ~36s at CPU: full 1M multi-DC scenario
def test_multidc1m_timing_pins():
    """Config 5: 1M nodes, 8 segments, sharded over the device mesh.
    Behind -m slow per the long-horizon-1M policy (PR 3/4, like
    suspect1m).
    Every segment must be reached; cross-segment spread rides the
    slower WAN cadence, so whole-cluster t99 sits above the one-segment
    LAN figure but within a small multiple of it."""
    out = run_scenario("multidc1m")
    assert out["infected_final"] == 1_000_000
    assert out["segments_reached"] == 8
    origin_t99 = out["segment_t99_ms"][0]
    assert out["t99_ms"] >= origin_t99  # remote segments lag the origin
    assert out["t99_ms"] <= 4 * origin_t99
    assert out["t99_ms"] <= 10_000  # absolute sanity vs LAN basis


@pytest.mark.slow  # ~4 min of 1M-node scan at CPU: the same
# long-horizon 1M distributional class as probe1k's pins above — the
# multichip-era tier-1 budget (870s) can't carry a single 250s test;
# run with -m slow (bench.py banks the same numbers every run).
def test_suspect1m_timing_pins():
    """Config 4 (the headline): 1M nodes, 30% loss, WAN timing.

    First suspicion within a handful of WAN probe intervals; the
    SUSPECT->DEAD transition cannot land before the 1M-node minimum
    suspicion window (SuspicionMult * log10(1e6) * ProbeInterval =
    180 s at WAN cadence), and 99% dead-knowledge follows within ~10%
    of it.  The slowest test in the suite (~2 min of 1M-node scan on
    CPU) — it pins the exact numbers the headline bench banks on."""
    out = run_scenario("suspect1m")
    probe_ms = WAN.probe_interval_ms
    assert probe_ms <= out["first_suspect_ms"] <= 10 * probe_ms

    sus_lo_ms, _hi = suspicion_timeout_bounds(
        WAN.suspicion_mult, WAN.suspicion_max_timeout_mult, 1_000_000,
        WAN.probe_interval_ms,
    )
    assert out["first_dead_ms"] >= sus_lo_ms
    assert out["t99_dead_known_ms"] <= 1.25 * sus_lo_ms
    assert out["dead_known_final"] >= 0.99 * (1_000_000 - 1)
