"""Mesh data plane end-to-end: proxycfg snapshots + built-in L4 proxy.

Parity model: ``agent/proxycfg/manager_test.go`` (snapshot assembly +
change propagation) and ``connect/proxy/proxy_test.go`` (listener data
path, intention enforcement, cert rotation) — re-designed: snapshots
are JSON over the agent's blocking HTTP feed instead of Envoy xDS.
"""

import asyncio
import json
import socket
import sys

import pytest

sys.path.insert(0, "tests")

from helpers import wait_for as wait_until
from helpers import requires_crypto  # noqa: E402

from consul_tpu.connect.proxy import (  # noqa: E402
    ConnectProxy,
    chain_candidates,
    evaluate_intentions,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=120))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# pure pieces
# ---------------------------------------------------------------------------


def test_evaluate_intentions_precedence_and_default():
    intentions = [
        {"source": "api", "action": "deny"},
        {"source": "*", "action": "allow"},
    ]
    assert not evaluate_intentions(intentions, "api", default_allow=True)
    assert evaluate_intentions(intentions, "other", default_allow=False)
    assert evaluate_intentions([], "anyone", default_allow=True)
    assert not evaluate_intentions([], "anyone", default_allow=False)


def test_chain_candidates_resolver_failover_order():
    upstream = {"chain": {
        "start_node": "resolver:web@dc1",
        "nodes": {"resolver:web@dc1": {
            "type": "resolver",
            "resolver": {"target": "web@dc1",
                         "failover": {"targets": ["web@dc2", "web@dc3"]}},
        }},
    }}
    assert chain_candidates(upstream) == ["web@dc1", "web@dc2", "web@dc3"]


def test_chain_candidates_router_takes_catch_all():
    upstream = {"chain": {
        "start_node": "router:web",
        "nodes": {
            "router:web": {"type": "router", "routes": [
                {"next_node": "resolver:admin@dc1"},
                {"next_node": "resolver:web@dc1"},
            ]},
            "resolver:web@dc1": {
                "type": "resolver",
                "resolver": {"target": "web@dc1", "failover": None}},
        },
    }}
    assert chain_candidates(upstream) == ["web@dc1"]


def test_chain_candidates_without_chain_falls_back_to_instances():
    assert chain_candidates({"instances": {"web@dc1": []}}) == ["web@dc1"]


# ---------------------------------------------------------------------------
# end-to-end
# ---------------------------------------------------------------------------


@requires_crypto
def test_mesh_end_to_end():
    """VERDICT r2 'done' criteria: A reaches B through two spawned
    proxies; an intention flip to deny severs new connections; a CA
    root rotation rolls certs without downtime."""

    async def main():
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            # The web application: a local echo server.
            served = []

            async def echo(reader, writer):
                data = await reader.read(64)
                served.append(data)
                writer.write(b"web:" + data)
                await writer.drain()
                writer.close()

            app = await asyncio.start_server(echo, "127.0.0.1", 0)
            app_port = app.sockets[0].getsockname()[1]

            web_proxy_port = free_port()
            upstream_port = free_port()

            # Register service + sidecar pairs (structs.NodeService
            # Kind=connect-proxy with a Proxy block).
            agent.add_service({"service": "web", "port": app_port})
            agent.add_service({
                "service": "web-proxy", "kind": "connect-proxy",
                "address": "127.0.0.1", "port": web_proxy_port,
                "proxy": {"destination_service": "web",
                          "local_service_port": app_port},
            })
            agent.add_service({"service": "api", "port": 0})
            agent.add_service({
                "service": "api-proxy", "kind": "connect-proxy",
                "address": "127.0.0.1", "port": free_port(),
                "proxy": {
                    "destination_service": "api",
                    "local_service_port": 1,
                    "upstreams": [{"destination_name": "web",
                                   "local_bind_port": upstream_port}],
                },
            })
            store = agent.delegate.store
            await wait_until(
                lambda: store.connect_service_nodes("web")[1],
                msg="web proxy in catalog",
            )

            web_proxy = await ConnectProxy(
                "web-proxy", addr, public_port=web_proxy_port).start()
            api_proxy = await ConnectProxy("api-proxy", addr).start()
            # The api proxy needs web instances in its snapshot before
            # its upstream dial can succeed.
            await wait_until(
                lambda: (api_proxy.snapshot or {}).get("upstreams", {})
                .get("web", {}).get("instances", {}).get("web@dc1"),
                msg="api proxy sees web instances",
            )

            async def call(payload: bytes) -> bytes:
                r, w = await asyncio.open_connection(
                    "127.0.0.1", upstream_port)
                w.write(payload)
                await w.drain()
                out = await asyncio.wait_for(r.read(64), 10)
                w.close()
                return out

            # 1. A → B through both proxies.
            assert await call(b"ping") == b"web:ping"

            # 2. Intention flip to deny severs NEW connections.
            st, _, created = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "api", "Destination": "web",
                            "Action": "deny"}).encode())
            assert st == 200
            intention_id = created["ID"]

            def intent_action():
                return next(
                    (i.get("action")
                     for i in (web_proxy.snapshot or {}).get(
                         "intentions", [])
                     if i.get("source") == "api"), None)

            await wait_until(lambda: intent_action() == "deny",
                             msg="deny in web proxy snapshot")
            assert await call(b"denied?") == b""

            # Other sources unaffected (default allow): a raw Service
            # identity still passes.
            from consul_tpu.connect import Service

            other = await Service("batch", addr).ready()
            r, w = await other.dial(web_proxy.public_addr,
                                    destination="web")
            w.write(b"direct")
            await w.drain()
            assert await asyncio.wait_for(r.read(64), 10) == b"web:direct"
            w.close()

            # Flip the SAME intention back to allow (a second create
            # for the pair is rejected as a duplicate).
            st, _, _x = await http_call(
                addr, "POST", "/v1/connect/intentions",
                json.dumps({"Source": "api", "Destination": "web",
                            "Action": "allow"}).encode())
            assert st == 400
            st, _, _x = await http_call(
                addr, "PUT", f"/v1/connect/intentions/{intention_id}",
                json.dumps({"Source": "api", "Destination": "web",
                            "Action": "allow"}).encode())
            assert st == 200
            await wait_until(lambda: intent_action() == "allow",
                             msg="allow in web proxy snapshot")
            assert await call(b"back") == b"web:back"

            # 3. CA rotation rolls certs without downtime.
            old_root = (web_proxy.snapshot or {}).get("active_root_id")
            out = await agent.rpc("ConnectCA.Rotate", {})
            assert out["root_id"] and out["root_id"] != old_root
            await wait_until(
                lambda: (web_proxy.snapshot or {}).get("active_root_id")
                == out["root_id"]
                and (web_proxy.snapshot or {}).get("leaf", {}).get(
                    "root_id") == out["root_id"],
                msg="web proxy rolled to the new root",
            )
            await wait_until(
                lambda: (api_proxy.snapshot or {}).get("leaf", {}).get(
                    "root_id") == out["root_id"],
                msg="api proxy rolled to the new root",
            )
            # New connections handshake under the new root.
            assert await call(b"rotated") == b"web:rotated"

            await api_proxy.stop()
            await web_proxy.stop()
            app.close()
            other.close()

    run(main())


@requires_crypto
def test_proxy_config_http_feed_blocks_and_versions():
    """The blocking snapshot feed itself (xDS stream stand-in)."""

    async def main():
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            agent.add_service({"service": "web", "port": 1234})
            agent.add_service({
                "service": "web-proxy", "kind": "connect-proxy",
                "port": free_port(),
                "proxy": {"destination_service": "web",
                          "local_service_port": 1234},
            })
            st, hdrs, snap = await http_call(
                addr, "GET", "/v1/agent/connect/proxy/web-proxy")
            assert st == 200
            assert snap["DestinationService"] == "web"
            assert snap["Leaf"]["CertPEM"]
            assert snap["Roots"]
            version = int(hdrs["x-consul-index"])
            assert version >= 1

            # A blocking read wakes on intention change.
            async def flip():
                await asyncio.sleep(0.2)
                await http_call(
                    addr, "POST", "/v1/connect/intentions",
                    json.dumps({"Source": "x", "Destination": "web",
                                "Action": "deny"}).encode())

            flip_task = asyncio.create_task(flip())
            st, hdrs, snap = await http_call(
                addr, "GET",
                f"/v1/agent/connect/proxy/web-proxy?index={version}&wait=10s")
            await flip_task
            assert st == 200
            assert int(hdrs["x-consul-index"]) > version
            assert any(i["Source"] == "x" for i in snap["Intentions"])

            # Unknown proxy → 404.
            st, _, _x = await http_call(
                addr, "GET", "/v1/agent/connect/proxy/nope")
            assert st == 404

    run(main())
