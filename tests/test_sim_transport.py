"""The north-star seam: a real host Memberlist/Cluster running over the
XLA-simulated gossip pool via SimTransport (SURVEY.md §2.5; reference
seam memberlist/transport.go:28-66, precedent mock_transport.go:14-66).

What these tests pin:
  * a host agent JOINs a simulated pool through the standard push/pull
    path and sees every simulated member,
  * simulated failures are detected by the simulated protocol machinery
    and reach the host as member events through gossiped obituaries,
  * a user event fired by the host agent infects the simulated
    population epidemically,
  * the population learns the host exists and probes it (the host's
    refutation path answers),
  * it works at 10k+ simulated members.
"""

import asyncio

import numpy as np
import pytest

from consul_tpu.net.memberlist import Memberlist, MemberlistConfig, NodeStatus
from consul_tpu.net.sim_transport import SimBridge, SimPoolConfig, sim_addr
from consul_tpu.eventing.cluster import Cluster, ClusterConfig, EventType
from consul_tpu.protocol.profiles import GossipProfile, LAN

SCALE = 0.01

# A detection-accelerated profile for big-N tests: probes every gossip
# tick, minimal suspicion multiplier — protocol structure identical,
# constants shrunk so a 10k-member failure resolves in tens of ticks.
FAST = GossipProfile(
    name="fast",
    probe_interval_ms=200,
    probe_timeout_ms=200,
    indirect_checks=3,
    suspicion_mult=2,
    suspicion_max_timeout_mult=2,
    awareness_max_multiplier=8,
    gossip_interval_ms=200,
    gossip_nodes=3,
    gossip_to_the_dead_ms=30_000,
    retransmit_mult=4,
    push_pull_interval_ms=30_000,
)


def run(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


async def attach_host(bridge, name="host0", profile=LAN):
    transport = bridge.transport(f"sim-host://{name}")
    m = Memberlist(
        MemberlistConfig(name=name, profile=profile, interval_scale=SCALE),
        transport,
    )
    await m.start()
    return m, transport


def test_host_joins_simulated_pool():
    async def main():
        n = 512
        bridge = SimBridge(SimPoolConfig(n=n, interval_scale=SCALE,
                                         realtime=False))
        host, transport = await attach_host(bridge)
        assert await host.join([sim_addr(0)]) == 1
        # One push/pull returned the full simulated membership.
        assert len(host.members()) == n + 1
        assert {m.name for m in host.members()} >= {"sim-0", "sim-511"}
        # The joined-through member knows the host; knowledge spreads.
        assert bridge.host_awareness(transport) > 0
        await bridge.run_ticks(20)
        assert bridge.host_awareness(transport) > 0.9
        await host.shutdown()
        await bridge.shutdown()

    run(main())


def test_simulated_failure_reaches_host_as_member_event():
    async def main():
        n = 512
        failed = 7
        leaves: list[str] = []
        bridge = SimBridge(
            SimPoolConfig(
                n=n,
                profile=FAST,
                interval_scale=SCALE,
                fail_at=((failed, 5),),
                realtime=False,
            )
        )
        transport = bridge.transport("sim-host://host0")
        host = Memberlist(
            MemberlistConfig(
                name="host0",
                profile=FAST,
                interval_scale=SCALE,
                notify_leave=lambda node: leaves.append(node.name),
            ),
            transport,
        )
        await host.start()
        assert await host.join([sim_addr(0)]) == 1

        # Pump until the simulated protocol detects the crash and the
        # obituary reaches the host through gossip.
        for _ in range(30):
            await bridge.run_ticks(5)
            node = host.nodes.get("sim-7")
            if node is not None and node.status == NodeStatus.DEAD:
                break
        assert host.nodes["sim-7"].status == NodeStatus.DEAD
        assert "sim-7" in leaves
        # Everyone else stays alive in the host's view.
        alive = [m.name for m in host.members()]
        assert "sim-7" not in alive
        assert len(alive) >= n  # n-1 sim members + host itself
        await host.shutdown()
        await bridge.shutdown()

    run(main())


def test_host_user_event_infects_population():
    async def main():
        n = 512
        bridge = SimBridge(
            SimPoolConfig(n=n, interval_scale=SCALE, realtime=False)
        )
        transport = bridge.transport("sim-host://host0")
        cluster = Cluster(
            ClusterConfig(name="host0", interval_scale=SCALE), transport
        )
        await cluster.start()
        assert await cluster.join([sim_addr(0)]) == 1
        await bridge.run_ticks(3)

        await cluster.user_event("deploy", b"v2-rollout")
        # Let the host's gossip loop seed a few simulated members, then
        # the infection spreads on device.
        await asyncio.sleep(0.05)
        await bridge.run_ticks(30)
        coverage = bridge.event_coverage(b"v2-rollout")
        assert coverage > 0.95, coverage
        await cluster.shutdown()
        await bridge.shutdown()

    run(main())


def test_population_probes_host_and_host_refutes():
    async def main():
        n = 256
        bridge = SimBridge(
            SimPoolConfig(n=n, interval_scale=SCALE, realtime=False)
        )
        host, transport = await attach_host(bridge)
        assert await host.join([sim_addr(0)]) == 1
        await bridge.run_ticks(40)
        # The pool probed the host at least once and the host acked
        # every probe (no missed pings -> no standing suspicion).
        assert transport.ping_seq > 0
        assert transport.missed_pings == 0
        assert host.local_node().status == NodeStatus.ALIVE
        await host.shutdown()
        await bridge.shutdown()

    run(main())


def test_push_pull_backstop_syncs_host():
    """If the transmit window is missed, the host's periodic push/pull
    against a random simulated member recovers the full state
    (state.go:622-657)."""

    async def main():
        n = 256
        bridge = SimBridge(
            SimPoolConfig(
                n=n,
                profile=FAST,
                interval_scale=SCALE,
                fail_at=((3, 2),),
                realtime=False,
            )
        )
        host, transport = await attach_host(bridge, profile=FAST)
        # Let the sim converge on the death of node 3 BEFORE joining, so
        # the gossip window is long past.
        await bridge.run_ticks(40)
        assert await host.join([sim_addr(0)]) == 1
        # The join push/pull snapshot reflects the converged state: the
        # dead member is NOT among the live membership.  (Like the
        # reference, obituaries about never-seen nodes don't create
        # entries — mergeState routes dead through suspect/dead handlers
        # which ignore unknown names, state.go:1283+.)
        alive = {m.name for m in host.members()}
        assert "sim-3" not in alive
        assert len(alive) == n  # n-1 live sim members + the host
        await host.shutdown()
        await bridge.shutdown()

    run(main())


@pytest.mark.slow  # ~105s at CPU: the 10k pool compiles big scans
def test_ten_thousand_member_pool():
    """The VERDICT acceptance bar: a real Memberlist joins a 10k+-member
    simulated pool, hears about a simulated failure, and a user event
    fired by the host infects the population."""

    async def main():
        n = 10_000
        failed = 4242
        leaves: list[str] = []
        bridge = SimBridge(
            SimPoolConfig(
                n=n,
                profile=FAST,
                interval_scale=SCALE,
                fail_at=((failed, 3),),
                realtime=False,
            )
        )
        transport = bridge.transport("sim-host://host0")
        cluster = Cluster(
            ClusterConfig(name="host0", profile=FAST, interval_scale=SCALE),
            transport,
        )
        cluster.config.on_event = lambda ev: (
            leaves.extend(m.name for m in ev.members)
            if ev.type == EventType.MEMBER_FAILED
            else None
        )
        await cluster.start()
        assert await cluster.join([sim_addr(17)]) == 1
        assert len(cluster.memberlist.members()) == n + 1

        await cluster.user_event("deploy", b"big-pool-event")
        await asyncio.sleep(0.05)

        detected = False
        for _ in range(12):
            await bridge.run_ticks(5)
            node = cluster.memberlist.nodes.get(f"sim-{failed}")
            if node is not None and node.status == NodeStatus.DEAD:
                detected = True
                break
        assert detected, "simulated failure never reached the host"
        coverage = bridge.event_coverage(b"big-pool-event")
        assert coverage > 0.9, coverage
        await cluster.shutdown()
        await bridge.shutdown()

    run(main())
