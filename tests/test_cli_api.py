"""API client library + watch plans + CLI black-box tests.

Parity model: ``api/*_test.go`` (client over a live agent),
``api/watch/watch_test.go`` (plans fire on change), and the
``sdk/testutil.TestServer`` subprocess pattern (SURVEY.md §4.4): the
CLI test execs the real ``agent -dev`` process and drives it with CLI
subcommands over HTTP.
"""

import asyncio
import contextlib
import json
import os
import signal
import sys

import pytest

from helpers import wait_for as wait_until

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.http import HTTPApi
from consul_tpu.api import ConsulClient, parse_watch
from consul_tpu.api.client import QueryOptions
from consul_tpu.net.transport import InMemoryNetwork


@contextlib.asynccontextmanager
async def dev_api():
    net = InMemoryNetwork()
    agent = Agent(
        AgentConfig(node_name="dev", bootstrap_expect=1,
                    gossip_interval_scale=0.05, sync_interval_s=0.3,
                    sync_retry_interval_s=0.2, reconcile_interval_s=0.2),
        gossip_transport=net.new_transport("dev:gossip"),
        rpc_transport=net.new_transport("dev:rpc"),
    )
    await agent.start()
    await wait_until(lambda: agent.delegate.is_leader(), msg="leader")
    api = HTTPApi(agent)
    addr = await api.start()
    try:
        yield agent, ConsulClient(addr)
    finally:
        await api.stop()
        await agent.shutdown()


class TestAPIClient:
    async def test_kv_roundtrip(self):
        async with dev_api() as (_, c):
            assert await c.kv.put("app/db", b"postgres") is True
            entry, meta = await c.kv.get("app/db")
            assert entry["Value"] == b"postgres" and meta.index >= 1
            entries, _ = await c.kv.list("app/")
            assert [e["Key"] for e in entries] == ["app/db"]
            keys, _ = await c.kv.keys("", separator="/")
            assert keys == ["app/"]
            assert await c.kv.delete("app/db") is True
            entry, _ = await c.kv.get("app/db")
            assert entry is None

    async def test_catalog_health_session(self):
        async with dev_api() as (agent, c):
            await c.catalog.register({
                "Node": "db-1", "Address": "10.5.5.5",
                "Service": {"Service": "db", "Port": 5432},
                "Checks": [{"CheckID": "db-alive", "ServiceID": "db",
                            "Status": "passing"}],
            })
            nodes, _ = await c.catalog.nodes()
            assert {n["Node"] for n in nodes} >= {"db-1", "dev"}
            rows, _ = await c.health.service("db", passing=True)
            assert rows[0]["Service"]["Port"] == 5432

            sid = await c.session.create({"Node": "db-1",
                                          "Checks": ["db-alive"]})
            assert await c.kv.put("locks/db", b"db-1", acquire=sid) is True
            sess, _ = await c.session.info(sid)
            assert sess["Node"] == "db-1"
            assert await c.kv.put("locks/db", b"", release=sid) is True
            await c.session.destroy(sid)

    async def test_query_and_txn(self):
        async with dev_api() as (_, c):
            await c.catalog.register({
                "Node": "c1", "Address": "10.6.0.1",
                "Service": {"Service": "cache", "Port": 6379},
            })
            qid = await c.query.create({"Name": "cache-q",
                                        "Service": {"Service": "cache"}})
            out, _ = await c.query.execute(qid)
            assert out["Nodes"][0]["Service"]["Port"] == 6379
            out, _ = await c.query.execute("cache-q")  # by name too
            assert out["Nodes"]

            res = await c.txn.apply([
                {"KV": {"Verb": "set", "Key": "t/a", "Value": b"1"}},
                {"KV": {"Verb": "get", "Key": "t/a"}},
            ])
            assert res["Errors"] == [] and len(res["Results"]) == 2

    async def test_status_and_operator(self):
        async with dev_api() as (_, c):
            assert await c.status.leader()
            peers = await c.status.peers()
            assert len(peers) == 1
            raft = await c.operator.raft_configuration()
            assert raft["Servers"][0]["Leader"] is True


class TestWatchPlans:
    async def test_key_watch_fires_on_change(self):
        async with dev_api() as (_, c):
            await c.kv.put("watched", b"v1")
            plan = parse_watch({"type": "key", "key": "watched"}, c)
            fired = []
            plan.on_change(lambda idx, data: fired.append((idx, data)))
            plan.start()
            await wait_until(lambda: len(fired) == 1, msg="initial fire")
            assert fired[0][1]["Key"] == "watched"
            await c.kv.put("watched", b"v2")
            await wait_until(lambda: len(fired) == 2, msg="change fire")
            assert fired[1][0] > fired[0][0]
            plan.stop()

    async def test_service_watch(self):
        async with dev_api() as (_, c):
            plan = parse_watch({"type": "service", "service": "web"}, c)
            fired = []
            plan.on_change(lambda idx, data: fired.append(data))
            plan.start()
            await wait_until(lambda: fired, msg="initial empty fire")
            assert fired[0] == []
            await c.catalog.register({
                "Node": "w1", "Address": "10.7.0.1",
                "Service": {"Service": "web", "Port": 80},
            })
            await wait_until(lambda: len(fired) >= 2, msg="service appears")
            assert fired[-1][0]["Service"]["Service"] == "web"
            plan.stop()

    async def test_parse_watch_validation(self):
        c = ConsulClient("127.0.0.1:1")
        with pytest.raises(ValueError, match="unknown watch type"):
            parse_watch({"type": "bogus"}, c)
        with pytest.raises(ValueError, match="requires 'key'"):
            parse_watch({"type": "key"}, c)


class TestCLIBlackBox:
    """Exec the real CLI binary: the sdk/testutil.TestServer pattern."""

    async def test_dev_agent_and_cli_commands(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "consul_tpu.cli", "agent", "-dev",
            "-http-port", "0", "-dns-port", "0",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=env,
        )
        http_addr = None
        try:
            # Scrape the HTTP address from the boot banner.
            while True:
                line = await asyncio.wait_for(proc.stdout.readline(), 30)
                assert line, "agent exited before banner"
                text = line.decode()
                if "HTTP addr:" in text:
                    http_addr = text.split("HTTP addr:")[1].strip()
                if "agent running" in text and http_addr:
                    break
                if http_addr and "Gossip via" in text:
                    break

            async def cli(*cli_args):
                p = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "consul_tpu.cli", *cli_args,
                    "-http-addr", http_addr,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.PIPE,
                    env=env,
                )
                out, err = await asyncio.wait_for(p.communicate(), 30)
                return p.returncode, out.decode(), err.decode()

            # Wait until the embedded server has a leader.
            async def has_leader():
                code, out, _ = await cli("info")
                return code == 0 and json.loads(out)["leader"]

            await wait_until(has_leader, timeout=30, msg="leader via CLI")

            code, out, err = await cli("kv", "put", "greeting", "hello")
            assert code == 0, err
            code, out, err = await cli("kv", "get", "greeting")
            assert code == 0 and out.strip() == "hello"

            code, out, _ = await cli("members")
            assert code == 0 and "dev" in out and "server" in out

            code, out, _ = await cli("catalog", "datacenters")
            assert code == 0 and out.strip() == "dc1"

            code, out, _ = await cli("operator", "raft", "list-peers")
            assert code == 0 and "leader" in out

            code, out, _ = await cli("version")
            assert code == 0 and "consul-tpu" in out

            code, out, err = await cli("event", "-name", "deploy", "v1")
            assert code == 0 and "Event ID" in out

            code, out, _ = await cli(
                "watch", "-type", "key", "-key", "greeting", "-once"
            )
            assert code == 0
            watched = json.loads(out)
            assert watched["data"]["Key"] == "greeting"
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                await asyncio.wait_for(proc.wait(), 10)
            except asyncio.TimeoutError:
                proc.kill()


class TestValidateReload:
    def test_validate_good_and_bad(self, tmp_path, capsys):
        from consul_tpu.cli import main as cli_main

        good = tmp_path / "ok.json"
        good.write_text('{"dns_config": {"node_ttl_s": 7}}')
        assert cli_main(["validate", str(good)]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text('{"unknown_key_xyz": 1}')
        assert cli_main(["validate", str(bad)]) == 1

    async def test_reload_endpoint(self):
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            # No reload handler registered (library embedding): 400.
            st, _, err = await http_call(addr, "PUT", "/v1/agent/reload")
            assert st == 400
            fired = []
            agent.reload_handler = lambda: fired.append(1)
            st, _, ok = await http_call(addr, "PUT", "/v1/agent/reload")
            assert st == 200 and ok is True and fired == [1]
