"""Fault-schedule unit tests: each primitive's evaluator against its
scalar expectation, plus composition semantics (independent drop
processes combine as 1 - prod(1 - p))."""

import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.sim.faults import (
    ChurnWindow,
    DegradedSet,
    FaultSchedule,
    LossRamp,
    Partition,
    combine_loss,
    degraded_late,
    degraded_mask,
    degraded_send_ok,
    edge_block_prob,
    extra_loss_at,
    offline_prob_at,
    online_mask,
    partition_severity_at,
    segment_ids,
)

import jax


class TestLossRamp:
    def test_piecewise_values_and_boundaries(self):
        sched = FaultSchedule(
            ramps=(LossRamp(pieces=((10, 0.3), (20, 0.1), (30, 0.0))),)
        )
        got = [float(extra_loss_at(sched, jnp.int32(t)))
               for t in (0, 9, 10, 19, 20, 29, 30, 1000)]
        assert np.allclose(got, [0.0, 0.0, 0.3, 0.3, 0.1, 0.1, 0.0, 0.0],
                           atol=1e-6)

    def test_unsorted_pieces_rejected(self):
        with pytest.raises(ValueError, match="sorted"):
            LossRamp(pieces=((20, 0.1), (10, 0.3)))

    def test_out_of_range_loss_rejected(self):
        with pytest.raises(ValueError, match="outside"):
            LossRamp(pieces=((0, 1.5),))

    def test_two_ramps_combine_independently(self):
        sched = FaultSchedule(
            ramps=(
                LossRamp(pieces=((0, 0.2),)),
                LossRamp(pieces=((0, 0.5),)),
            )
        )
        got = float(extra_loss_at(sched, jnp.int32(5)))
        assert abs(got - combine_loss(0.2, 0.5)) < 1e-6
        assert abs(got - 0.6) < 1e-6  # 1 - 0.8*0.5

    def test_empty_schedule_is_lossless(self):
        assert float(extra_loss_at(FaultSchedule(), jnp.int32(0))) == 0.0


class TestDegraded:
    def test_frac_respected_and_deterministic(self):
        sched = FaultSchedule(
            degraded=(DegradedSet(frac=0.1, drop=0.5, seed=7),)
        )
        m1 = np.asarray(degraded_mask(sched, 10_000))
        m2 = np.asarray(degraded_mask(sched, 10_000))
        assert np.array_equal(m1, m2), "membership must be deterministic"
        assert 0.07 < m1.mean() < 0.13
        ok = np.asarray(degraded_send_ok(sched, 10_000))
        assert np.allclose(ok[m1], 0.5) and np.allclose(ok[~m1], 1.0)

    def test_zero_frac_is_healthy(self):
        sched = FaultSchedule(degraded=(DegradedSet(frac=0.0),))
        assert not np.asarray(degraded_mask(sched, 64)).any()
        assert np.allclose(np.asarray(degraded_send_ok(sched, 64)), 1.0)

    def test_late_only_set_counts_as_degraded(self):
        sched = FaultSchedule(
            degraded=(DegradedSet(frac=0.2, drop=0.0, late=0.5, seed=3),)
        )
        m = np.asarray(degraded_mask(sched, 4096))
        late = np.asarray(degraded_late(sched, 4096))
        assert m.any()
        assert np.allclose(late[m], 0.5) and np.allclose(late[~m], 0.0)
        # drop=0 -> sends unaffected
        assert np.allclose(np.asarray(degraded_send_ok(sched, 4096)), 1.0)

    def test_overlapping_sets_drop_independently(self):
        # Same seed + frac -> same membership; drops should compose as
        # independent processes: ok = (1-a)(1-b).
        sched = FaultSchedule(
            degraded=(
                DegradedSet(frac=0.5, drop=0.4, seed=1),
                DegradedSet(frac=0.5, drop=0.5, seed=1),
            )
        )
        m = np.asarray(degraded_mask(sched, 1024))
        ok = np.asarray(degraded_send_ok(sched, 1024))
        assert np.allclose(ok[m], 0.6 * 0.5)


class TestPartition:
    def test_cross_segment_blocked_only_in_window(self):
        part = Partition(start=10, heal=20, segments=2, severity=1.0)
        sched = FaultSchedule(partitions=(part,))
        n = 8
        seg = np.asarray(segment_ids(part, n))
        assert set(seg[:4]) == {0} and set(seg[4:]) == {1}
        src = jnp.arange(n, dtype=jnp.int32)[:, None]
        dst = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :],
                               (n, n))
        during = np.asarray(edge_block_prob(sched, jnp.int32(15), src, dst, n))
        before = np.asarray(edge_block_prob(sched, jnp.int32(9), src, dst, n))
        after = np.asarray(edge_block_prob(sched, jnp.int32(20), src, dst, n))
        cross = seg[:, None] != seg[None, :]
        assert np.allclose(during[cross], 1.0)
        assert np.allclose(during[~cross], 0.0)
        assert np.allclose(before, 0.0), "no blocking before start"
        assert np.allclose(after, 0.0), "heal tick restores all edges"

    def test_partial_severity(self):
        part = Partition(start=0, heal=10, segments=2, severity=0.25)
        assert abs(float(partition_severity_at(part, jnp.int32(5))) - 0.25) \
            < 1e-6
        assert float(partition_severity_at(part, jnp.int32(10))) == 0.0


class TestChurn:
    def test_offline_probability_windows(self):
        sched = FaultSchedule(churn=(ChurnWindow(start=5, end=10,
                                                 p_offline=0.3),))
        assert float(offline_prob_at(sched, jnp.int32(4))) == 0.0
        assert abs(float(offline_prob_at(sched, jnp.int32(5))) - 0.3) < 1e-6
        assert float(offline_prob_at(sched, jnp.int32(10))) == 0.0

    def test_online_mask_rate(self):
        sched = FaultSchedule(churn=(ChurnWindow(start=0, end=100,
                                                 p_offline=0.25),))
        m = np.asarray(online_mask(sched, jax.random.PRNGKey(0),
                                   jnp.int32(3), 20_000))
        assert 0.71 < m.mean() < 0.79

    def test_no_churn_everyone_online(self):
        m = np.asarray(online_mask(FaultSchedule(), jax.random.PRNGKey(0),
                                   jnp.int32(0), 64))
        assert m.all()


class TestCompose:
    def test_compose_unions_every_primitive(self):
        a = FaultSchedule(
            ramps=(LossRamp(pieces=((0, 0.2),)),),
            degraded=(DegradedSet(frac=0.1, seed=1),),
        )
        b = FaultSchedule(
            ramps=(LossRamp(pieces=((0, 0.5),)),),
            partitions=(Partition(start=0, heal=5),),
            churn=(ChurnWindow(start=0, end=5, p_offline=0.1),),
        )
        c = a.compose(b)
        assert len(c.ramps) == 2 and len(c.partitions) == 1
        assert len(c.degraded) == 1 and len(c.churn) == 1
        assert c.has_faults and not FaultSchedule().has_faults
        # Loss combines as independent drops regardless of compose order.
        lc = float(extra_loss_at(c, jnp.int32(0)))
        lr = float(extra_loss_at(b.compose(a), jnp.int32(0)))
        assert abs(lc - combine_loss(0.2, 0.5)) < 1e-6
        assert abs(lc - lr) < 1e-6

    def test_composed_schedule_is_hashable_static_arg(self):
        # jit static args require hashability — the whole schedule must
        # stay a pure-literal pytree of tuples.
        a = FaultSchedule(ramps=(LossRamp(pieces=((0, 0.2),)),))
        b = FaultSchedule(degraded=(DegradedSet(frac=0.1),))
        assert hash(a.compose(b)) == hash(a.compose(b))
        assert a.compose(b) == a.compose(b)
