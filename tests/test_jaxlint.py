"""jaxlint: jaxpr-level rules J1-J6 + the peak-HBM footprint gate.

Per rule: a planted-violation program the rule must fire on and a
clean twin it must stay silent on.  Then the gates the CI story rides
on: every registered entrypoint (sharded D in {1, 2} included) lints
clean at default thresholds, the 1M-node configs fit the per-chip
HBM budget, and the J3-driven ``donate_argnums`` fix shows a
peak-bytes reduction of at least one full state copy in the
estimator's before/after numbers.
"""

import asyncio
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consul_tpu.analysis.guards import ENGINE_ENTRYPOINTS
from consul_tpu.analysis.jaxlint import (
    RULES,
    analyze_jaxpr,
    estimate_peak,
    format_bytes,
    lint_programs,
)
from consul_tpu.sim.engine import SimProgram, jaxlint_registry

SDS = jax.ShapeDtypeStruct
F32 = jnp.float32
I32 = jnp.int32
BUDGET_16GB = 16 << 30


def _program(name, fn, *args, x64=False):
    return SimProgram(name=name, entrypoint=name,
                      build=lambda: (fn, tuple(args)), n=0, x64=x64)


def _rules(program, **kw):
    findings, _ = analyze_jaxpr(
        program.name, program.trace(), **kw
    )
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# Registry fixtures: trace once per module, share across tests.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_programs():
    return jaxlint_registry(include=("small",))


@pytest.fixture(scope="module")
def small_traces(small_programs):
    return {n: p.trace() for n, p in small_programs.items()}


@pytest.fixture(scope="module")
def big_programs():
    return jaxlint_registry(include=("big",))


@pytest.fixture(scope="module")
def big_traces(big_programs):
    return {n: p.trace() for n, p in big_programs.items()}


# ---------------------------------------------------------------------------
# Rule fixtures: fire on the planted violation, silent on the twin.
# ---------------------------------------------------------------------------


class TestJ1HostCallbackInScan:
    def test_fires_on_debug_print_in_scan(self):
        def bad(c, xs):
            def tick(carry, x):
                jax.debug.print("tick {}", carry)
                return carry + x, carry

            return jax.lax.scan(tick, c, xs)

        assert "J1" in _rules(_program("j1bad", bad, SDS((), F32),
                                       SDS((4,), F32)))

    def test_fires_on_pure_callback_in_scan(self):
        def bad(c, xs):
            def tick(carry, x):
                y = jax.pure_callback(
                    lambda v: v, jax.ShapeDtypeStruct((), np.float32),
                    carry,
                )
                return carry + y, carry

            return jax.lax.scan(tick, c, xs)

        assert "J1" in _rules(_program("j1cb", bad, SDS((), F32),
                                       SDS((4,), F32)))

    def test_silent_on_plain_scan_and_toplevel_callback(self):
        def clean(c, xs):
            final, ys = jax.lax.scan(
                lambda carry, x: (carry + x, carry), c, xs
            )
            # A host callback OUTSIDE the loop is one round-trip per
            # study, not per tick — J1 leaves it alone.
            jax.debug.print("done {}", final)
            return final, ys

        assert _rules(_program("j1clean", clean, SDS((), F32),
                               SDS((4,), F32))) == []


class TestJ2DtypeWidening:
    def test_fires_on_f64_widening(self):
        def bad(x):
            return x.astype(jnp.float64) * 2.0

        assert "J2" in _rules(_program("j2bad", bad, SDS((8,), F32),
                                       x64=True))

    def test_silent_when_x32(self):
        def clean(x):
            return x.astype(jnp.float32) * 2.0

        assert _rules(_program("j2clean", clean, SDS((8,), I32))) == []

    def test_silent_when_program_starts_x64(self):
        # Inputs already 64-bit: deliberately an x64 program, not a
        # silent widening — J2 stays quiet.
        def passthrough(x):
            return x + 1.0

        assert "J2" not in _rules(
            _program("j2x64in", passthrough,
                     SDS((8,), jnp.float64), x64=True)
        )


class TestJ3UndonatedLargeBuffer:
    BIG = SDS((32 << 20,), F32)  # 128 MiB, abstract — nothing allocated

    def test_fires_on_undonated_large_input(self):
        f = jax.jit(lambda x: x * 2.0)
        assert "J3" in _rules(_program("j3bad", lambda x: f(x), self.BIG))

    def test_silent_when_donated(self):
        f = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
        assert _rules(_program("j3clean", lambda x: f(x), self.BIG)) == []

    def test_silent_below_threshold(self):
        f = jax.jit(lambda x: x * 2.0)
        assert _rules(
            _program("j3small", lambda x: f(x), SDS((1024,), F32))
        ) == []


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
class TestJ4CollectiveConsistency:
    def _mesh(self):
        from consul_tpu.parallel import make_mesh

        return make_mesh(jax.devices()[:2])

    def test_fires_on_unreduced_replicated_output(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        # The check_rep=False footgun: a local sum returned through a
        # replicated out_spec silently yields device 0's partial.
        bad = shard_map(
            lambda x: jnp.sum(x), mesh=self._mesh(),
            in_specs=(P("nodes"),), out_specs=P(), check_rep=False,
        )
        assert "J4" in _rules(_program("j4bad", bad, SDS((16,), F32)))

    def test_silent_when_psummed(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        clean = shard_map(
            lambda x: jax.lax.psum(jnp.sum(x), "nodes"),
            mesh=self._mesh(),
            in_specs=(P("nodes"),), out_specs=P(), check_rep=False,
        )
        assert _rules(_program("j4clean", clean, SDS((16,), F32))) == []

    def test_silent_on_sharded_output(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        # Device-varying data under a SHARDED out_spec is the normal
        # case, not a violation.
        clean = shard_map(
            lambda x: x * 2.0, mesh=self._mesh(),
            in_specs=(P("nodes"),), out_specs=P("nodes"),
            check_rep=False,
        )
        assert _rules(_program("j4shard", clean, SDS((16,), F32))) == []


class TestJ5BakedConstant:
    def test_fires_on_closure_captured_host_array(self):
        w = np.ones((1 << 19,), np.float32)  # 2 MiB > the 1 MiB default

        def bad(x):
            return x * w

        assert "J5" in _rules(_program("j5bad", bad,
                                       SDS((1 << 19,), F32)))

    def test_silent_when_computed_in_program(self):
        def clean(x):
            return x * jnp.ones((1 << 19,), F32)

        assert _rules(_program("j5clean", clean,
                               SDS((1 << 19,), F32))) == []


class TestJ6HbmBudget:
    def _prog(self):
        f = jax.jit(lambda x: x * 2.0)
        return _program("j6", lambda x: f(x), SDS((1 << 20,), F32))

    def test_fires_over_budget(self):
        findings, peak = analyze_jaxpr(
            "j6", self._prog().trace(), budget_bytes=1 << 20,
        )
        assert "J6" in [f.rule for f in findings]
        assert peak.total_bytes > 1 << 20

    def test_silent_under_budget(self):
        findings, _ = analyze_jaxpr(
            "j6", self._prog().trace(), budget_bytes=BUDGET_16GB,
        )
        assert findings == []

    def test_every_rule_has_a_fixture(self):
        # The classes above cover the whole table.
        covered = {"J1", "J2", "J3", "J4", "J5", "J6"}
        assert covered == set(RULES)


class TestPallasTraversal:
    """``pallas_call`` eqns (the ring-exchange DMA kernel,
    ops/ring_exchange.py) are OPAQUE to the rule walk: no false J4
    hits on the ring collective's in-kernel axis_index/DMA ops, J6
    prices the declared out_shapes + scratch operands, and the
    replication taint still flows through the call (any tainted input
    taints every output)."""

    def _mesh(self):
        from consul_tpu.parallel import make_mesh

        return make_mesh(jax.devices()[:2])

    @staticmethod
    def _ring(x):
        from consul_tpu.ops.ring_exchange import ring_exchange

        (ib,) = ring_exchange((x,), interpret=True)
        return ib

    def test_clean_on_ring_collective(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        # The real usage shape: per-device [D, budget] outbox in, the
        # inbox staying sharded.  Zero findings — in particular no J4
        # from the kernel-internal axis_index / remote-DMA primitives.
        clean = shard_map(
            lambda x: self._ring(x).reshape(x.shape),
            mesh=self._mesh(), in_specs=(P("nodes", None),),
            out_specs=P("nodes", None), check_rep=False,
        )
        prog = _program("ring_clean", clean, SDS((4, 8), I32))
        findings, peak = analyze_jaxpr(
            "ring_clean", prog.trace(), budget_bytes=BUDGET_16GB
        )
        assert findings == []
        assert peak.chip_bytes > 0

    def test_j4_fires_on_replicated_pallas_output(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        # The planted violation: the kernel's inbox is device-varying
        # (it came FROM device-varying outbox planes), so returning a
        # local reduction of it through a replicated out_spec is the
        # check_rep=False footgun.  Without the opaque-taint rule the
        # kernel's empty outvar list would launder the taint away.
        bad = shard_map(
            lambda x: jnp.sum(self._ring(x), dtype=I32),
            mesh=self._mesh(), in_specs=(P("nodes", None),),
            out_specs=P(), check_rep=False,
        )
        assert "J4" in _rules(_program("ring_j4", bad, SDS((4, 8), I32)))

    def test_j6_counts_declared_out_shapes(self):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kern(in_ref, out_ref, sem):
            copy = pltpu.make_async_copy(in_ref, out_ref.at[0], sem)
            copy.start()
            copy.wait()

        def fan_out(x):
            return pl.pallas_call(
                kern,
                out_shape=SDS((1024, *x.shape), I32),  # 256 MiB out
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
                scratch_shapes=[pltpu.SemaphoreType.DMA],
                interpret=True,
            )(x)

        prog = _program("pallas_j6", fan_out, SDS((256, 256), I32))
        traced = prog.trace()
        findings, peak = analyze_jaxpr(
            "pallas_j6", traced, budget_bytes=64 << 20
        )
        assert "J6" in [f.rule for f in findings]
        # The kernel body is opaque: the declared out_shape must be
        # priced even though nothing inside the body allocates it.
        assert peak.total_bytes >= 1024 * 256 * 256 * 4
        clean, _ = analyze_jaxpr(
            "pallas_j6", traced, budget_bytes=BUDGET_16GB
        )
        assert clean == []

    def test_registry_covers_ring_backend(self, small_programs):
        # The ring twins keep the Pallas program under every jaxlint
        # gate (zero-findings small/big walks above).
        for model in ("broadcast", "membership", "sparse"):
            for d in (1, 2):
                assert (
                    f"sharded_{model}@small/D{d}/ring" in small_programs
                )


# ---------------------------------------------------------------------------
# The estimator.
# ---------------------------------------------------------------------------


class TestTelemetryFootprint:
    """J6 pins what telemetry=on costs in HBM: the persistent (output)
    footprint delta of every on/off program pair is EXACTLY the
    [steps, M] float32 trace plane the scan returns — telemetry adds
    no hidden plane — and the live-peak delta is bounded by that plane
    plus at most ONE elementwise emitter temporary over the model's
    widest state plane (zero peak movement for five of the seven
    families; the dense-membership diff masks are the exception)."""

    STEPS = 8
    # (off name, metric family, widest state-plane cells at small-n)
    PAIRS = [
        ("broadcast@small", "broadcast", 64),
        ("membership@small", "membership", 48 * 48),
        ("sparse@small", "sparse", 48 * 8),
        ("swim@small", "swim", 64),
        ("lifeguard@small", "lifeguard", 64),
        ("streamcast@small", "streamcast", 64 * 4 * 2),
        ("geo@small", "geo", 64 * 4),
    ]

    @staticmethod
    def _out_bytes(tr):
        from consul_tpu.analysis.jaxlint import _aval_bytes

        return sum(_aval_bytes(v.aval) for v in tr.jaxpr.outvars)

    def test_trace_plane_delta_exact(self, small_traces):
        from consul_tpu.obs import metric_count

        for name, family, _cells in self.PAIRS:
            plane = self.STEPS * metric_count(family) * 4
            delta = (self._out_bytes(small_traces[name + "/telemetry"])
                     - self._out_bytes(small_traces[name]))
            assert delta == plane, (name, delta, plane)

    def test_sharded_trace_plane_delta_exact(self, small_traces):
        from consul_tpu.obs import metric_count

        for d in (1, 2):
            for model in ("broadcast", "membership", "sparse",
                          "streamcast", "geo"):
                name = f"sharded_{model}@small/D{d}"
                if name not in small_traces:
                    continue  # single-device process
                plane = self.STEPS * metric_count(model) * 4
                delta = (
                    self._out_bytes(small_traces[name + "/telemetry"])
                    - self._out_bytes(small_traces[name])
                )
                assert delta == plane, (name, delta, plane)

    def test_peak_delta_bounded_by_plane_plus_one_temp(
            self, small_traces):
        from consul_tpu.obs import metric_count

        for name, family, cells in self.PAIRS:
            plane = self.STEPS * metric_count(family) * 4
            delta = (
                estimate_peak(small_traces[name + "/telemetry"])
                .total_bytes
                - estimate_peak(small_traces[name]).total_bytes
            )
            assert 0 <= delta <= plane + 4 * cells, (name, delta)


class TestPeakEstimator:
    N = 4096

    def _scan_program(self, donate):
        kw = {"donate_argnums": (0,)} if donate else {}
        f = jax.jit(
            lambda s, ks: jax.lax.scan(
                lambda c, k: (c + 1.0, jnp.sum(c)), s, ks
            ),
            **kw,
        )
        return _program("scan", lambda s, ks: f(s, ks),
                        SDS((self.N,), F32), SDS((8,), F32))

    def test_donation_saves_exactly_one_state_copy(self):
        donated = estimate_peak(self._scan_program(True).trace())
        undonated = estimate_peak(self._scan_program(False).trace())
        assert undonated.total_bytes - donated.total_bytes == self.N * 4

    def test_ignore_donation_reproduces_undonated_peak(self):
        tr = self._scan_program(True).trace()
        before = estimate_peak(tr, ignore_donation=True)
        undonated = estimate_peak(self._scan_program(False).trace())
        assert before.total_bytes == undonated.total_bytes

    def test_peak_at_least_inputs_plus_outputs(self):
        tr = self._scan_program(False).trace()
        # state in (N) + state out (N) + keys/ys noise.
        assert estimate_peak(tr).total_bytes >= 2 * self.N * 4

    def test_format_bytes(self):
        assert format_bytes(512) == "512 B"
        assert format_bytes(4 << 20) == "4.00 MiB"
        assert format_bytes(16 << 30) == "16.00 GiB"


# ---------------------------------------------------------------------------
# The repo gates.
# ---------------------------------------------------------------------------


class TestRepoGate:
    def test_registry_covers_every_engine_entrypoint(self, small_programs):
        covered = {p.entrypoint for p in small_programs.values()}
        # The vmapped universe-sweep programs (consul_tpu/sweep) ride
        # the registry under their own entrypoint tag.
        assert covered == set(ENGINE_ENTRYPOINTS) | {"sweep_scan"}

    def test_registry_covers_sharded_d1_and_d2(self, small_programs):
        for d in (1, 2):
            for model in ("broadcast", "membership", "sparse"):
                assert f"sharded_{model}@small/D{d}" in small_programs

    def test_registry_covers_sweep_u1_and_u8(self, small_programs):
        # Every sweepable model's vmapped program at U in {1, 8}, so
        # the zero-findings walks above cover the batched plane (and
        # the traced knob-rebuild path) for the whole family.
        for model in ("swim", "lifeguard", "broadcast", "membership",
                      "sparse", "streamcast", "geo"):
            for u in (1, 8):
                assert f"sweep_{model}@small/U{u}" in small_programs

    def test_registry_covers_geo(self, small_programs):
        # The geo/WAN plane: the unsharded scan plus the sharded twins
        # at D in {1, 2} over BOTH exchange backends, all under every
        # zero-findings gate.
        assert "geo@small" in small_programs
        for d in (1, 2):
            assert f"sharded_geo@small/D{d}" in small_programs
            assert f"sharded_geo@small/D{d}/ring" in small_programs

    def test_registry_covers_streamcast(self, small_programs):
        # The pipelined event-stream plane: the unsharded scan plus
        # the sharded twins at D in {1, 2} over BOTH exchange backends
        # (the /ring twins walk the Pallas program), all under every
        # zero-findings gate.
        assert "streamcast@small" in small_programs
        for d in (1, 2):
            assert f"sharded_streamcast@small/D{d}" in small_programs
            assert (
                f"sharded_streamcast@small/D{d}/ring" in small_programs
            )

    def test_registry_covers_streamcast_policies(self, small_programs):
        # The selection-policy seam: each non-uniform policy is a
        # DISTINCT program (policy is trace-time static), so the
        # pipeline/rarest twins — unsharded, sharded at D in {1, 2},
        # and the batched sweep at U in {1, 8} — sit under every
        # zero-findings gate, as does the adversarial-load twin
        # (standing backlog + heavy-tail sizes + hotspot).
        for pol in ("pipeline", "rarest"):
            assert f"streamcast@small/{pol}" in small_programs
            for d in (1, 2):
                assert (f"sharded_streamcast@small/{pol}/D{d}"
                        in small_programs)
            for u in (1, 8):
                assert (f"sweep_streamcast@small/{pol}/U{u}"
                        in small_programs)
        assert "streamcast@small/adversarial" in small_programs

    def test_registry_covers_telemetry_twins(self, small_programs):
        # The in-scan telemetry plane (consul_tpu/obs): telemetry=on
        # twins of all seven entrypoints, of the five sharded scans at
        # D in {1, 2} (the one-psum trace assembly), and of one
        # batched sweep — all under every zero-findings gate below.
        for model in ("broadcast", "membership", "sparse", "swim",
                      "lifeguard", "streamcast", "geo"):
            assert f"{model}@small/telemetry" in small_programs
        for d in (1, 2):
            for model in ("broadcast", "membership", "sparse",
                          "streamcast", "geo"):
                assert (f"sharded_{model}@small/D{d}/telemetry"
                        in small_programs)
        assert "sweep_swim@small/U8/telemetry" in small_programs

    def test_small_registry_zero_findings(self, small_programs,
                                          small_traces):
        findings = []
        for name, tr in small_traces.items():
            found, _ = analyze_jaxpr(name, tr, budget_bytes=BUDGET_16GB)
            findings += found
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_big_registry_zero_findings_within_budget(self, big_traces):
        """The acceptance gate: every 1M-node config — dense ceiling,
        sparse, and the sharded per-chip twins — lints clean INCLUDING
        the 16 GB per-chip J6 budget."""
        findings = []
        for name, tr in big_traces.items():
            found, _ = analyze_jaxpr(name, tr, budget_bytes=BUDGET_16GB)
            findings += found
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_big_registry_reports_1m_peaks(self, big_traces):
        for name in ("broadcast@1m", "sparse@1m", "swim@1m",
                     "membership@16k"):
            assert estimate_peak(big_traces[name]).total_bytes > 0
        sharded = [n for n in big_traces if n.startswith("sharded_")]
        assert sharded, "big registry lost its per-chip entries"
        for name in sharded:
            peak = estimate_peak(big_traces[name])
            assert peak.per_chip_bytes is not None, name
            assert 0 < peak.per_chip_bytes <= BUDGET_16GB, name

    def test_streamcast_1m_footprint_pinned(self, big_traces):
        # J6 prices the sustained-load plane at the north-star shape
        # (n=1M, W=8, E=4): the peak must cover at least the persistent
        # chunk plane plus one [n, W, E] float32 delivery draw, and
        # stay far inside the 16 GB/chip gate — the headroom that says
        # W (and therefore the sustainable offered load) can grow ~50x
        # before sharding becomes mandatory.
        peak = estimate_peak(big_traces["streamcast@1m"]).chip_bytes
        n, w, e = 1_000_000, 8, 4
        floor = n * w * e * (1 + 4)  # bool chunks + f32 uniform draw
        assert floor <= peak <= BUDGET_16GB, peak

    def test_geo_1m_footprint_pinned(self, big_traces):
        # J6 prices the geo/WAN plane at the north-star shape (n=1M,
        # E=16): the peak must cover at least the persistent [n, E]
        # chunk-of-state planes (bool knows + int32 tx_lan) plus one
        # [n, E] float32 LAN delivery draw, and stay far inside the
        # 16 GB/chip gate — the headroom that says events (and the
        # anti-entropy load) can grow ~50x before sharding becomes
        # mandatory.
        peak = estimate_peak(big_traces["geo@1m"]).chip_bytes
        n, e = 1_000_000, 16
        floor = n * e * (1 + 4 + 4)  # bool knows + i32 tx + f32 draw
        assert floor <= peak <= BUDGET_16GB, peak

    def test_lint_programs_end_to_end(self, small_programs):
        findings, peaks = lint_programs(
            small_programs, budget_gb=16.0,
        )
        assert findings == []
        assert set(peaks) == set(small_programs)


class TestDonationPins:
    """The J3-driven donate_argnums fix, pinned via the estimator's
    before/after peak-bytes delta (the satellite acceptance)."""

    def test_dense_membership_donation_saves_a_state_copy(self,
                                                          big_traces):
        tr = big_traces["membership@16k"]
        after = estimate_peak(tr).total_bytes
        before = estimate_peak(tr, ignore_donation=True).total_bytes
        # Four [n, n] int32 planes dominate the dense state.
        n = 16384
        assert before - after >= int(0.99 * 4 * n * n * 4)

    def test_sparse_membership_donation_saves_a_state_copy(self,
                                                           big_traces):
        tr = big_traces["sparse@1m"]
        after = estimate_peak(tr).total_bytes
        before = estimate_peak(tr, ignore_donation=True).total_bytes
        # Five [n, K] slot planes dominate the sparse state — 12
        # bytes/cell after the PR 12 narrowing/packing (2 int32 planes
        # + int16 age-packed suspect_since + int8 confirms + int8 tx).
        assert before - after >= int(0.99 * 1_000_000 * 64 * 12)

    def test_sharded_twins_donation_visible_per_chip(self, big_traces):
        for name in big_traces:
            if not (name.startswith("sharded_membership")
                    or name.startswith("sharded_sparse")):
                continue
            after = estimate_peak(big_traces[name])
            before = estimate_peak(big_traces[name],
                                   ignore_donation=True)
            assert before.per_chip_bytes > after.per_chip_bytes, name

    def test_undonated_entrypoints_have_zero_delta(self, big_traces):
        for name in ("swim@1m", "broadcast@1m", "lifeguard@1m"):
            tr = big_traces[name]
            assert (estimate_peak(tr, ignore_donation=True).total_bytes
                    == estimate_peak(tr).total_bytes), name

    def test_donation_is_wired_on_the_jitted_entrypoint(self,
                                                        small_traces):
        from consul_tpu.analysis.jaxlint import _top_level_donated

        donated = _top_level_donated(small_traces["membership@small"].jaxpr)
        # 9 MembershipState leaves donated, the PRNG key not.
        assert sum(donated) == 9
        assert donated[-1] is False


class TestSweepFootprint:
    """J6 over the batched plane (consul_tpu/sweep): U multiplies the
    per-universe state planes, so U is the knob that blows the 16 GB
    gate first.  Pin the sparse@100k x U=8 footprint and the
    estimator's ~linear-in-U scaling — the two numbers bench.py's
    max-U-per-chip table rides on."""

    N, K = 100_000, 64

    def _peak_at(self, u):
        from consul_tpu.models import SparseMembershipConfig
        from consul_tpu.models.membership import MembershipConfig
        from consul_tpu.protocol import LAN
        from consul_tpu.sweep.universe import abstract_sweep_program

        # The big registry's exact sparse@100k shape.
        cfg = SparseMembershipConfig(
            base=MembershipConfig(n=self.N, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),)),
            k_slots=self.K,
        )
        fn, args = abstract_sweep_program(
            "sparse", cfg, 3, u, ("base.loss",), (42,)
        )
        return estimate_peak(jax.make_jaxpr(fn)(*args)).chip_bytes

    def test_batched_footprint_pinned_at_u8(self, big_traces):
        # The registry big set carries the U in {1, 8} twins; U=8 must
        # cost at least 7 extra copies of the five [n, K] slot planes
        # over U=1 (the carry is the stacked state) while staying
        # inside the 16 GB J6 budget the zero-findings gate enforces.
        p1 = estimate_peak(big_traces["sweep_sparse@100k/U1"]).chip_bytes
        p8 = estimate_peak(big_traces["sweep_sparse@100k/U8"]).chip_bytes
        planes = 5 * self.N * self.K * 4
        assert p8 - p1 >= int(0.99 * 7 * planes), (p1, p8)
        assert p8 <= BUDGET_16GB

    def test_estimator_scales_linearly_in_u(self, big_traces):
        # Three points U in {1, 4, 8}: the U=4 peak predicted from the
        # (U=1, U=8) line must match the traced U=4 peak within 5% —
        # the linear model behind max_u = (budget - fixed) / per_u.
        p1 = estimate_peak(big_traces["sweep_sparse@100k/U1"]).chip_bytes
        p8 = estimate_peak(big_traces["sweep_sparse@100k/U8"]).chip_bytes
        per_u = (p8 - p1) / 7.0
        assert per_u > 0
        p4 = self._peak_at(4)
        predicted = p1 + 3.0 * per_u
        assert abs(p4 - predicted) / p4 < 0.05, (p1, p4, p8, predicted)


# Program-size pinning moved to the golden fingerprint gate: exact
# per-program eqn counts (not +-20% hand pins) now live in
# tests/golden/programs.json, diffed by equivlint E2 on every
# `cli check` and asserted in tests/test_equivlint.py.


# ---------------------------------------------------------------------------
# CLI contract (mirrors cli lint: nonzero on findings, file:line-style
# provenance, --format json for CI).
# ---------------------------------------------------------------------------


_FIXTURE_MODULE = """\
import jax
import jax.numpy as jnp
from consul_tpu.sim.engine import SimProgram

_SCALAR = jax.ShapeDtypeStruct((), jnp.float32)
_VEC = jax.ShapeDtypeStruct((16,), jnp.float32)

def _j1(c, xs):
    def tick(carry, x):
        jax.debug.print("tick {}", carry)
        return carry + x, carry
    return jax.lax.scan(tick, c, xs)

def _j2(x):
    return x.astype(jnp.float64) * 2.0

def _j4_build():
    from consul_tpu.parallel import make_mesh
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    fn = shard_map(
        lambda x: jnp.sum(x), mesh=make_mesh(jax.devices()[:2]),
        in_specs=(P("nodes"),), out_specs=P(), check_rep=False,
    )
    return fn, (_VEC,)

JAXLINT_PROGRAMS = {
    "planted@j1": SimProgram(
        name="planted@j1", entrypoint="planted",
        build=lambda: (_j1, (_SCALAR,
                             jax.ShapeDtypeStruct((4,), jnp.float32))),
        n=4),
    "planted@j2": SimProgram(
        name="planted@j2", entrypoint="planted",
        build=lambda: (_j2, (_VEC,)), n=16, x64=True),
}
if len(jax.devices()) >= 2:
    JAXLINT_PROGRAMS["planted@j4"] = SimProgram(
        name="planted@j4", entrypoint="planted", build=_j4_build, n=16)
"""


class TestCli:
    def _run(self, argv):
        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(argv)
        return asyncio.run(args.fn(args))

    def test_list_rules(self, capsys):
        assert self._run(["jaxlint", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in RULES:
            assert rule in out

    def test_planted_violations_exit_nonzero(self, tmp_path, capsys):
        # The acceptance fixture: planted J1, J2, and J4 violations
        # all surface through the CLI with a nonzero exit.
        fixture = tmp_path / "planted.py"
        fixture.write_text(_FIXTURE_MODULE)
        assert self._run(["jaxlint", "--module", str(fixture)]) == 1
        out = capsys.readouterr().out
        assert "planted@j1" in out and "J1" in out
        assert "planted@j2" in out and "J2" in out
        if len(jax.devices()) >= 2:
            assert "planted@j4" in out and "J4" in out

    def test_planted_violation_json(self, tmp_path, capsys):
        fixture = tmp_path / "planted.py"
        fixture.write_text(_FIXTURE_MODULE)
        assert self._run(["jaxlint", "--module", str(fixture),
                          "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        rules = {f["rule"] for f in payload["findings"]}
        expected = {"J1", "J2"} | (
            {"J4"} if len(jax.devices()) >= 2 else set()
        )
        assert rules == expected
        assert payload["peak_bytes"]["planted@j1"] > 0

    def test_real_repo_small_set_clean(self, capsys):
        # The acceptance gate's CLI half: zero findings, exit 0 on the
        # real registry (the big set is covered by TestRepoGate).
        assert self._run(["jaxlint", "--set", "small"]) == 0

    def test_rule_filter_rejects_unknown(self, capsys):
        assert self._run(["jaxlint", "--rules", "J99",
                          "--set", "small"]) == 2
