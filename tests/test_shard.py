"""Sharded multi-chip simulation plane (consul_tpu/parallel/shard.py).

Runs on the virtual 8-device CPU mesh the session-wide conftest forces
(``XLA_FLAGS=--xla_force_host_platform_device_count=8`` is set before
the FIRST jax import — XLA reads it at backend init, so it cannot be a
per-test fixture), standing in for the v5e-8.

Exactness contract under test, mirroring the sparse==dense K==n pin:
  * D == 1 sharded scans are BIT-EQUAL to the unsharded scans for the
    broadcast, dense-membership, and sparse-membership models.
  * At D == 2 the outbox/all_to_all routing must deliver exactly what a
    single chip would: ``overflow == 0`` at default budgets, and the
    per-tick metric curves match D == 1 (the replicated-draw RNG
    discipline makes them identical when nothing is dropped).
  * The outbox pack/exchange path itself is property-tested against a
    numpy brute-force router (random global targets, shard-crossing
    duplicates, budget-overflow accounting).

Tier-1 budget note: every scan config below is shared across its D1 /
D2 / engine-wiring tests on purpose — identical (cfg, steps, track,
mesh) tuples reuse one compiled program (Mesh hashes by value), so the
module pays one XLA compile per distinct program, not per test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from consul_tpu.models.broadcast import BroadcastConfig, broadcast_init
from consul_tpu.models.membership import (
    MembershipConfig,
    membership_init,
)
from consul_tpu.models.membership_sparse import (
    SparseMembershipConfig,
    sparse_membership_init,
)
from consul_tpu.parallel import make_mesh, mesh_for
from consul_tpu.parallel.mesh import NODE_AXIS, block_size
from consul_tpu.parallel.shard import (
    exchange_outbox,
    outbox_budget,
    pack_outbox,
    sharded_broadcast_scan,
    sharded_membership_scan,
    sharded_sparse_membership_scan,
)

# One config per model, shared by every test in this module (see the
# budget note above).
BCAST_CFG = BroadcastConfig(n=256, fanout=3, loss=0.2)
BCAST_STEPS = 20
DENSE_CFG = MembershipConfig(
    n=64, loss=0.1, fail_at=((3, 4),), leave_at=((40, 6),)
)
# The drift-guard twin: exercises the round stages the main config
# can't — join_at schedules (a joiner's unknown rows/cols + the
# needs_join immediate push/pull) — since the sharded ticks mirror the
# unsharded rounds line-for-line and only these pins catch divergence.
DENSE_CFG_JOIN = MembershipConfig(
    n=64, loss=0.1, fail_at=((3, 4),), join_at=((50, 6),)
)
DENSE_STEPS, DENSE_TRACK = 25, (3,)
SPARSE_CFG = SparseMembershipConfig(
    base=MembershipConfig(n=64, loss=0.05, fail_at=((5, 3),)),
    k_slots=12,
)
# Anti-entropy off: the gossip-only tick (no pp exchange legs, no
# initiator budget) must also match bit-for-bit.
SPARSE_CFG_NOPP = SparseMembershipConfig(
    base=MembershipConfig(n=64, loss=0.05, fail_at=((5, 3),),
                          push_pull_enabled=False),
    k_slots=12,
)
SPARSE_STEPS, SPARSE_TRACK = 20, (5,)


@pytest.fixture(scope="session", autouse=True)
def forced_host_devices():
    """The multi-device contract this module rides on: conftest.py set
    XLA_FLAGS before the first JAX import, so ≥ 2 (virtual) devices
    exist even in single-chip CPU containers."""
    devs = jax.devices()
    assert len(devs) >= 2, (
        "test_shard needs ≥2 devices; set XLA_FLAGS="
        "--xla_force_host_platform_device_count=8 before importing jax"
    )
    return devs


def _mesh(d):
    return make_mesh(jax.devices()[:d])


# ---------------------------------------------------------------------------
# Outbox pack/exchange vs a numpy brute-force router.
# ---------------------------------------------------------------------------


def _numpy_router(recv, val, ok, d_shards, blk, budget):
    """Brute-force reference: per (src, dst) shard pair, remote-destined
    messages land in stream order until the budget; the rest drop.
    Returns (delivered lists per dst, dropped count)."""
    inboxes = [[] for _ in range(d_shards)]
    dropped = 0
    counts = {}
    for src in range(d_shards):
        for i in range(recv.shape[1]):
            if not ok[src, i]:
                continue
            dst = int(recv[src, i]) // blk
            if dst == src:
                continue  # local: never routed
            c = counts.get((src, dst), 0)
            if c < budget:
                inboxes[dst].append((int(recv[src, i]), int(val[src, i])))
                counts[(src, dst)] = c + 1
            else:
                dropped += 1
    return inboxes, dropped


class TestOutboxRouter:
    # (d_shards, budget) x exchange backend: a tight budget that forces
    # overflow on a 2-mesh, and a roomy one on the widest routing
    # (4-mesh — three ring hops, so the ring kernel's double-buffered
    # slots genuinely cycle).  Both transports must route identically.
    @pytest.mark.parametrize("backend", ["alltoall", "ring"])
    @pytest.mark.parametrize("d_shards,budget", [(2, 3), (4, 64)])
    def test_pack_exchange_matches_numpy(self, d_shards, budget, backend):
        n, a_len = 64, 120
        blk = n // d_shards
        mesh = _mesh(d_shards)

        def body(recv, val, ok):
            me = jax.lax.axis_index(NODE_AXIS)
            r = recv.reshape(-1)
            v = val.reshape(-1)
            o = ok.reshape(-1)
            dest = r // blk
            remote = o & (dest != me)
            packed, dropped = pack_outbox(
                dest, remote, (r, v), d_shards, budget
            )
            ib_r, ib_v = exchange_outbox(packed, backend=backend)
            return (
                ib_r[None], ib_v[None],
                jax.lax.psum(dropped, NODE_AXIS)[None],
            )

        from jax.experimental.shard_map import shard_map

        run = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(NODE_AXIS, None),) * 3,
            out_specs=(P(NODE_AXIS, None), P(NODE_AXIS, None),
                       P(NODE_AXIS)),
            check_rep=False,
        ))

        overflowed = False
        for seed in range(3):  # same shapes: ONE compile, three datasets
            rng = np.random.default_rng(seed)
            # Random GLOBAL targets, duplicates included; ~70% valid.
            recv = rng.integers(0, n, (d_shards, a_len)).astype(np.int32)
            val = rng.integers(0, 1000, (d_shards, a_len)).astype(np.int32)
            ok = rng.random((d_shards, a_len)) < 0.7
            ib_r, ib_v, dropped = run(
                jnp.asarray(recv), jnp.asarray(val), jnp.asarray(ok)
            )
            ib_r, ib_v = np.asarray(ib_r), np.asarray(ib_v)

            ref_inboxes, ref_dropped = _numpy_router(
                recv, val, ok, d_shards, blk, budget
            )
            assert int(np.asarray(dropped)[0]) == ref_dropped
            overflowed |= ref_dropped > 0
            for dst in range(d_shards):
                got = sorted(
                    (int(r), int(v))
                    for r, v in zip(ib_r[dst], ib_v[dst]) if r >= 0
                )
                assert got == sorted(ref_inboxes[dst]), f"dst {dst}"
                # Every routed message really belongs to dst's block.
                for r, _ in got:
                    assert r // blk == dst
        if budget == 3:
            assert overflowed, "tight budget must exercise the drop path"

    def test_budget_formula(self):
        # c x mean with a floor; degenerate single-shard mesh needs none.
        assert outbox_budget(1000, 1) == 1
        assert outbox_budget(8000, 8) == 2000       # 2 * 8000/8
        assert outbox_budget(100, 8) == 64          # floor
        assert outbox_budget(16, 8, floor=64) == 16  # never above stream

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="exchange backend"):
            exchange_outbox(
                (jnp.zeros((2, 4), jnp.int32),), backend="carrier-pigeon"
            )


# ---------------------------------------------------------------------------
# D == 1 bit-equality pins (dense, sparse, broadcast).
# ---------------------------------------------------------------------------


def _assert_state_equal(a, b):
    for fld in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, fld)), np.asarray(getattr(b, fld)),
            err_msg=fld,
        )


class TestD1BitEquality:
    """equivlint's E1 ladder now witnesses the D=1 == unsharded rung
    for every family in tier-1 (tests/test_equivlint.py TestPairGate),
    so these full-size runtime duplicates ride the slow tier — they
    still pin the larger configs/steps the tiny witness doesn't."""

    @pytest.mark.slow
    @pytest.mark.parametrize("delivery", ["edges", "aggregate"])
    def test_broadcast(self, delivery):
        import dataclasses

        from consul_tpu.sim.engine import broadcast_scan

        cfg = dataclasses.replace(BCAST_CFG, delivery=delivery)
        key = jax.random.PRNGKey(3)
        f1, inf1 = broadcast_scan(
            broadcast_init(cfg), key, cfg, BCAST_STEPS
        )
        f2, (inf2, ov) = sharded_broadcast_scan(
            broadcast_init(cfg), key, cfg, BCAST_STEPS, _mesh(1)
        )
        np.testing.assert_array_equal(np.asarray(inf1), np.asarray(inf2))
        _assert_state_equal(f1, f2)
        assert int(ov) == 0

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "cfg", [DENSE_CFG, DENSE_CFG_JOIN], ids=["leave", "join"],
    )
    def test_membership_dense(self, cfg):
        from consul_tpu.sim.engine import membership_scan

        key = jax.random.PRNGKey(9)
        f1, o1 = membership_scan(
            membership_init(cfg), key, cfg, DENSE_STEPS, DENSE_TRACK
        )
        f2, o2 = sharded_membership_scan(
            membership_init(cfg), key, cfg, DENSE_STEPS,
            _mesh(1), DENSE_TRACK,
        )
        for a, b in zip(o1, o2[:-1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)
        assert int(o2[-1]) == 0  # no overflow path exists at D == 1

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "cfg", [SPARSE_CFG, SPARSE_CFG_NOPP], ids=["pp", "nopp"],
    )
    def test_membership_sparse(self, cfg):
        from consul_tpu.sim.engine import sparse_membership_scan

        key = jax.random.PRNGKey(4)
        f1, o1 = sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg,
            SPARSE_STEPS, SPARSE_TRACK,
        )
        f2, o2 = sharded_sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg,
            SPARSE_STEPS, _mesh(1), SPARSE_TRACK,
        )
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)

    def test_sparse_rejects_k_equals_n(self):
        # K == n is the unsharded dense-parity mode; the sharded plane
        # refuses it loudly instead of silently densifying.
        cfg = SparseMembershipConfig(
            base=MembershipConfig(n=16), k_slots=16
        )
        with pytest.raises(ValueError, match="k_slots < n"):
            sharded_sparse_membership_scan(
                sparse_membership_init(cfg), jax.random.PRNGKey(0),
                cfg, 2, _mesh(1), ()
            )


# ---------------------------------------------------------------------------
# D == 2: the collectives actually route, nothing drops, metrics match.
# ---------------------------------------------------------------------------


class TestD2:
    def test_broadcast_edges_matches_d1_and_overflow0(self):
        key = jax.random.PRNGKey(3)
        _, (inf1, _) = sharded_broadcast_scan(
            broadcast_init(BCAST_CFG), key, BCAST_CFG, BCAST_STEPS,
            _mesh(1),
        )
        f2, (inf2, ov2) = sharded_broadcast_scan(
            broadcast_init(BCAST_CFG), key, BCAST_CFG, BCAST_STEPS,
            _mesh(2),
        )
        assert int(ov2) == 0, "default budget must not drop messages"
        # With nothing dropped, the replicated-draw discipline makes the
        # distributional metric exactly equal, not merely within
        # tolerance.
        np.testing.assert_array_equal(np.asarray(inf1), np.asarray(inf2))
        assert int(np.asarray(inf2)[-1]) == BCAST_CFG.n
        # The final state is genuinely block-sharded over the mesh.
        assert not f2.knows.sharding.is_fully_replicated

    def test_membership_dense_matches_d1(self):
        key = jax.random.PRNGKey(9)
        _, o1 = sharded_membership_scan(
            membership_init(DENSE_CFG), key, DENSE_CFG, DENSE_STEPS,
            _mesh(1), DENSE_TRACK,
        )
        _, o2 = sharded_membership_scan(
            membership_init(DENSE_CFG), key, DENSE_CFG, DENSE_STEPS,
            _mesh(2), DENSE_TRACK,
        )
        assert int(o2[-1]) == 0
        for a, b in zip(o1[:-1], o2[:-1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_membership_sparse_matches_d1(self):
        key = jax.random.PRNGKey(4)
        f1, o1 = sharded_sparse_membership_scan(
            sparse_membership_init(SPARSE_CFG), key, SPARSE_CFG,
            SPARSE_STEPS, _mesh(1), SPARSE_TRACK,
        )
        f2, o2 = sharded_sparse_membership_scan(
            sparse_membership_init(SPARSE_CFG), key, SPARSE_CFG,
            SPARSE_STEPS, _mesh(2), SPARSE_TRACK,
        )
        assert int(f2.overflow) == 0
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)


# ---------------------------------------------------------------------------
# Ring exchange backend (ops/ring_exchange.py): the Pallas
# make_async_remote_copy kernel, interpret mode on this CPU mesh, must
# be BIT-EQUAL to the all_to_all transport — same inbox layout by
# construction, so the whole D == 1 / D == 2 exactness ladder rides
# through unchanged.  The alltoall twins reuse the programs compiled by
# the pin classes above (same cfg/steps/mesh tuples).
# ---------------------------------------------------------------------------


class TestRingBackend:
    # The D=2 ring == alltoall rung is witnessed in tier-1 by the
    # equivlint ladder (tests/test_equivlint.py TestPairGate), so the
    # full-size D=2 runtime duplicates ride the slow tier; D=1 ring is
    # NOT a declared pair (the kernel degenerates to the local copy),
    # so it keeps its tier-1 runtime pin.
    @pytest.mark.parametrize(
        "d", [1, pytest.param(2, marks=pytest.mark.slow)])
    def test_broadcast_matches_alltoall(self, d):
        key = jax.random.PRNGKey(3)
        f1, (inf1, ov1) = sharded_broadcast_scan(
            broadcast_init(BCAST_CFG), key, BCAST_CFG, BCAST_STEPS,
            _mesh(d),
        )
        f2, (inf2, ov2) = sharded_broadcast_scan(
            broadcast_init(BCAST_CFG), key, BCAST_CFG, BCAST_STEPS,
            _mesh(d), "ring",
        )
        np.testing.assert_array_equal(np.asarray(inf1), np.asarray(inf2))
        _assert_state_equal(f1, f2)
        assert int(ov2) == int(ov1) == 0

    @pytest.mark.parametrize(
        "d", [1, pytest.param(2, marks=pytest.mark.slow)])
    def test_membership_dense_matches_alltoall(self, d):
        key = jax.random.PRNGKey(9)
        f1, o1 = sharded_membership_scan(
            membership_init(DENSE_CFG), key, DENSE_CFG, DENSE_STEPS,
            _mesh(d), DENSE_TRACK,
        )
        f2, o2 = sharded_membership_scan(
            membership_init(DENSE_CFG), key, DENSE_CFG, DENSE_STEPS,
            _mesh(d), DENSE_TRACK, "ring",
        )
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)
        assert int(o2[-1]) == 0  # overflow ladder unchanged

    # Both params slow: D=1 was already offloaded (tier-1 budget
    # policy; the dense/broadcast D=1 pins above cover the plumbing)
    # and D=2 is now witnessed by the equivlint ladder in tier-1.
    @pytest.mark.slow
    @pytest.mark.parametrize("d", [1, 2])
    def test_membership_sparse_matches_alltoall(self, d):
        key = jax.random.PRNGKey(4)
        f1, o1 = sharded_sparse_membership_scan(
            sparse_membership_init(SPARSE_CFG), key, SPARSE_CFG,
            SPARSE_STEPS, _mesh(d), SPARSE_TRACK,
        )
        f2, o2 = sharded_sparse_membership_scan(
            sparse_membership_init(SPARSE_CFG), key, SPARSE_CFG,
            SPARSE_STEPS, _mesh(d), SPARSE_TRACK, "ring",
        )
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)
        assert int(f2.overflow) == int(f1.overflow)

    def test_engine_exchange_requires_mesh(self):
        from consul_tpu.sim.engine import run_broadcast

        # exchange is a multichip-plane knob: asking for the ring
        # transport without a mesh must fail loudly, never silently
        # run the unsharded scan.
        with pytest.raises(ValueError, match="requires mesh"):
            run_broadcast(BCAST_CFG, steps=2, exchange="ring")

    @pytest.mark.slow
    def test_broadcast_multihop_long_horizon(self):
        # D = 4: three ring hops per round over a long horizon — the
        # double-buffered send/recv slots wrap repeatedly and the
        # full epidemic still matches all_to_all bit-for-bit.
        import dataclasses

        cfg = dataclasses.replace(BCAST_CFG, retransmit_mult=2)
        key = jax.random.PRNGKey(11)
        f1, (inf1, ov1) = sharded_broadcast_scan(
            broadcast_init(cfg), key, cfg, 60, _mesh(4)
        )
        f2, (inf2, ov2) = sharded_broadcast_scan(
            broadcast_init(cfg), key, cfg, 60, _mesh(4), "ring"
        )
        np.testing.assert_array_equal(np.asarray(inf1), np.asarray(inf2))
        _assert_state_equal(f1, f2)
        assert int(ov1) == int(ov2) == 0


# ---------------------------------------------------------------------------
# Engine wiring + retrace discipline.
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def test_run_broadcast_mesh_reports_overflow(self):
        # Same (cfg, steps, mesh) as the D2 pin: the engine path must
        # reuse the compiled program, and its report carries overflow.
        from consul_tpu.sim.engine import run_broadcast

        rep = run_broadcast(BCAST_CFG, steps=BCAST_STEPS, seed=3,
                            mesh=_mesh(2), warmup=False)
        assert rep.overflow == 0
        assert int(rep.infected[-1]) == BCAST_CFG.n
        # The legacy GSPMD path stays overflow-less and agrees.
        rep2 = run_broadcast(BCAST_CFG, steps=BCAST_STEPS, seed=3,
                             warmup=False)
        assert rep2.overflow is None
        np.testing.assert_array_equal(rep.infected, rep2.infected)

    def test_run_membership_sparse_mesh(self):
        from consul_tpu.sim.engine import run_membership_sparse

        rep, ov = run_membership_sparse(
            SPARSE_CFG, steps=SPARSE_STEPS, seed=4, track=SPARSE_TRACK,
            warmup=False, mesh=_mesh(2),
        )
        assert ov == 0
        assert rep.overflow is None  # sparse reports overflow separately
        # The crash at tick 3 is eventually suspected by live observers.
        assert int(np.asarray(rep.suspecting)[:, 0].max()) > 0

    @pytest.mark.single_trace(
        entrypoints=("sharded_broadcast_scan",), max_traces=2
    )
    def test_resharding_compiles_once_per_mesh(self, retrace_guard):
        # One XLA program per distinct mesh, and re-running on a mesh
        # already seen must NOT retrace — resharding is never a silent
        # recompile treadmill (max_traces=2 covers D ∈ {1, 2}).
        cfg = BroadcastConfig(n=128, fanout=3)
        key = jax.random.PRNGKey(0)
        for d in (1, 2, 1, 2):
            sharded_broadcast_scan(
                broadcast_init(cfg), key, cfg, 6, _mesh(d)
            )
        assert retrace_guard["sharded_broadcast_scan"].traces == 2


class TestMeshHelpers:
    def test_block_size_divisibility(self):
        assert block_size(64, _mesh(2)) == 32
        with pytest.raises(ValueError, match="divide"):
            block_size(65, _mesh(2))

    def test_mesh_for_bounds(self):
        assert int(mesh_for(2).devices.size) == 2
        with pytest.raises(ValueError):
            mesh_for(len(jax.devices()) + 1)


# ---------------------------------------------------------------------------
# Compacted gossip lanes + owned pp legs at scales where the budgets
# actually ENGAGE (ISSUE 13 satellite): below ~2048 rows per shard the
# sender budget clamps to full width and the compaction is the
# identity — these pins run it with budget < blk.
# ---------------------------------------------------------------------------


class TestSparseLaneCompaction:
    def _cfg(self, n, k):
        # Short horizon + early crash: the detection wave stays well
        # under the sender budget, so compaction is structurally
        # active (bounded gather shapes) but never defers — the
        # overflow==0 reading of the exactness ladder.
        return SparseMembershipConfig(
            base=MembershipConfig(n=n, loss=0.01, fail_at=((7, 2),)),
            k_slots=k,
        )

    @pytest.mark.slow
    def test_d1_bit_equal_with_active_sender_budget(self):
        # Slow tier: n=4608 pays ~24s of compile; the small-n D=1 pin
        # above keeps the bit-equality claim tier-1 (budgets clamp to
        # full width there, so the compacted path is the identity).
        from consul_tpu.models.membership_sparse import (
            gossip_sender_budget,
        )
        from consul_tpu.sim.engine import sparse_membership_scan

        cfg = self._cfg(4608, 16)
        assert gossip_sender_budget(4608) < 4608  # budget engages
        key = jax.random.PRNGKey(4)
        f1, o1 = sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg, 5, (7,),
        )
        f2, o2 = sharded_sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg, 5, _mesh(1), (7,),
        )
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_state_equal(f1, f2)
        assert int(np.asarray(f2.overflow)) == 0

    @pytest.mark.slow
    def test_d4_matches_d1_with_active_budgets(self):
        # D=4 engages BOTH per-shard compactions (gossip sender budget
        # 2048 < blk=2176; pp_owned = i_slots/2): with no deferral the
        # compacted streams carry exactly the messages D=1 carries.
        cfg = self._cfg(8704, 32)
        key = jax.random.PRNGKey(4)
        f1, o1 = sharded_sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg, 6, _mesh(1), (7,),
        )
        f4, o4 = sharded_sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg, 6, _mesh(4), (7,),
        )
        for a, b in zip(o1, o4):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(np.asarray(f4.overflow)) == 0
        np.testing.assert_array_equal(
            np.asarray(f1.slot_subj), np.asarray(f4.slot_subj)
        )
