"""Host-plane WAN federation: per-DC LAN pools + one WAN pool, the
LAN→WAN flooder, router areas, and cross-DC RPC forwarding.

Parity model: agent/consul/server.go:506,534 (two serf pools),
flood.go:27-60 (flooder), router/router.go (DC→servers map +
GetDatacentersByDistance), rpc.go:577-655 (forward → forwardDC).  Each
DC gets its OWN in-memory LAN network — segments are physically
separate gossip domains — while the WAN and RPC planes are shared.
"""

import asyncio
import base64

import pytest

from helpers import wait_for as wait_until
from helpers import wait_for_leader

from consul_tpu.agent.server import Server, ServerConfig
from consul_tpu.net.transport import InMemoryNetwork
from consul_tpu.protocol import LAN


def make_dc_server(lan_net, wan_net, rpc_net, name, dc, expect):
    cfg = ServerConfig(
        node_name=name,
        datacenter=dc,
        bootstrap_expect=expect,
        gossip_interval_scale=0.05,
        reconcile_interval_s=0.2,
        coordinate_update_period_s=0.1,
        session_ttl_sweep_s=0.1,
        flood_interval_s=0.1,
    )
    return Server(
        cfg,
        gossip_transport=lan_net.new_transport(f"{name}.{dc}:gossip"),
        rpc_transport=rpc_net.new_transport(f"{name}.{dc}:rpc"),
        wan_transport=wan_net.new_transport(f"{name}.{dc}:wan"),
    )


async def start_two_dcs(n1=2, n2=1):
    """dc1 with n1 servers, dc2 with n2; one explicit WAN join bridges
    them, the flooder federates the rest."""
    lan1, lan2 = InMemoryNetwork(), InMemoryNetwork()
    wan, rpc = InMemoryNetwork(), InMemoryNetwork()
    dc1 = [
        make_dc_server(lan1, wan, rpc, f"a{i}", "dc1", n1) for i in range(n1)
    ]
    dc2 = [
        make_dc_server(lan2, wan, rpc, f"b{i}", "dc2", n2) for i in range(n2)
    ]
    for s in dc1 + dc2:
        await s.start()
    for s in dc1[1:]:
        await s.join([f"a0.dc1:gossip"])
    for s in dc2[1:]:
        await s.join([f"b0.dc2:gossip"])
    await wait_for_leader(dc1)
    await wait_for_leader(dc2)
    # One WAN join from dc2's first server to dc1's (consul join -wan).
    assert await dc2[0].join_wan(["a0.dc1:wan"]) == 1
    return dc1, dc2


async def shutdown_all(*servers):
    for s in servers:
        await s.shutdown()
    await asyncio.sleep(0)


class TestWANFederation:
    async def test_flooder_federates_every_server(self):
        dc1, dc2 = await start_two_dcs(n1=2, n2=1)
        # Only a0<->b0 joined explicitly; the flooder must pull a1 into
        # the WAN pool via its advertised wan_addr (flood.go:27-60).
        await wait_until(
            lambda: all(
                {"dc1", "dc2"} <= set(s.router.servers_by_dc())
                and len(s.router.servers_by_dc().get("dc1", [])) == 2
                for s in dc1 + dc2
            ),
            timeout=10,
            msg="every server sees 2 dc1 + 1 dc2 servers on the WAN",
        )
        await shutdown_all(*dc1, *dc2)

    async def test_lan_pools_stay_isolated(self):
        dc1, dc2 = await start_two_dcs()
        # LAN membership never leaks across DCs (separate pools —
        # server.go:506,534 keeps them distinct by construction).
        assert all(
            not any(m.startswith("b") for m in s.serf.members) for s in dc1
        )
        assert all(
            not any(m.startswith("a") for m in s.serf.members) for s in dc2
        )
        await shutdown_all(*dc1, *dc2)

    async def test_cross_dc_kv_write_and_read(self):
        dc1, dc2 = await start_two_dcs()
        entry = dc1[0]
        # A write addressed to dc2 submitted to a dc1 server must land
        # in dc2's replicated store (rpc.go forwardDC).
        out = await entry.rpc_client.call(
            "a0.dc1:rpc",
            "KVS.Apply",
            {"op": "set", "entry": {"key": "wan", "value": b"x"}, "dc": "dc2"},
        )
        assert out["result"] is True
        assert dc2[0].store.kv_get("wan")[1]["value"] == b"x"
        # And it is NOT in dc1's store.
        assert dc1[0].store.kv_get("wan")[1] is None

        got = await entry.rpc_client.call(
            "a0.dc1:rpc", "KVS.Get", {"key": "wan", "dc": "dc2"}
        )
        assert got["entries"][0]["value"] == b"x"
        await shutdown_all(*dc1, *dc2)

    async def test_datacenters_listed_local_first(self):
        dc1, dc2 = await start_two_dcs()
        out = await dc1[0].rpc_client.call(
            "a0.dc1:rpc", "Catalog.ListDatacenters", {}
        )
        assert out["datacenters"][0] == "dc1"
        assert set(out["datacenters"]) == {"dc1", "dc2"}
        out2 = await dc2[0].rpc_client.call(
            "b0.dc2:rpc", "Catalog.ListDatacenters", {}
        )
        assert out2["datacenters"][0] == "dc2"
        await shutdown_all(*dc1, *dc2)

    async def test_http_dc_param_routes_write_and_read(self):
        """PUT/GET /v1/kv/...?dc=dc2 against a dc1 agent crosses the WAN
        (http.go parseDC → rpc.go forwardDC)."""
        from test_http_dns import http_call

        from consul_tpu.agent.agent import Agent, AgentConfig
        from consul_tpu.agent.http import HTTPApi

        lan1, lan2 = InMemoryNetwork(), InMemoryNetwork()
        wan, rpc = InMemoryNetwork(), InMemoryNetwork()
        mk = lambda name, dc, lan: Agent(
            AgentConfig(node_name=name, datacenter=dc, bootstrap_expect=1,
                        gossip_interval_scale=0.05, sync_interval_s=0.3,
                        sync_retry_interval_s=0.2, reconcile_interval_s=0.2),
            gossip_transport=lan.new_transport(f"{name}:gossip"),
            rpc_transport=rpc.new_transport(f"{name}:rpc"),
            wan_transport=wan.new_transport(f"{name}:wan"),
        )
        a1, a2 = mk("h1", "dc1", lan1), mk("h2", "dc2", lan2)
        await a1.start()
        await a2.start()
        await wait_until(lambda: a1.delegate.is_leader(), msg="dc1 leader")
        await wait_until(lambda: a2.delegate.is_leader(), msg="dc2 leader")
        await a2.delegate.join_wan(["h1:wan"])

        api = HTTPApi(a1)
        addr = await api.start()
        status, _, ok = await http_call(
            addr, "PUT", "/v1/kv/xdc?dc=dc2", b"remote"
        )
        assert status == 200 and ok is True
        assert a2.delegate.store.kv_get("xdc")[1]["value"] == b"remote"
        assert a1.delegate.store.kv_get("xdc")[1] is None

        status, _, rows = await http_call(addr, "GET", "/v1/kv/xdc?dc=dc2")
        assert status == 200
        assert base64.b64decode(rows[0]["Value"]) == b"remote"

        await api.stop()
        await a1.shutdown()
        await a2.shutdown()

    async def test_wan_coordinates_populate(self):
        """The WAN pool's ping/ack piggyback fills the Vivaldi cache,
        the input to GetDatacentersByDistance (ping_delegate.go:46-90,
        router.go:534)."""
        dc1, dc2 = await start_two_dcs()
        await wait_until(
            lambda: len(dc1[0].serf_wan.coord_cache) > 0,
            timeout=15,
            msg="WAN probe acks carried coordinates",
        )
        await shutdown_all(*dc1, *dc2)
