"""Storage kernel tests: iradix semantics, memdb indexes/watches, and
the domain StateStore (catalog / KV / sessions / coordinates).

Models the reference's state-store test style (state/catalog_test.go,
state/kvs_test.go, state/session_test.go): every write is tagged with a
raft index, reads return (index, data), radix watches fire on writes
under the watched prefix.
"""

import asyncio

import pytest

from consul_tpu.store import StateStore, WatchSet
from consul_tpu.store.iradix import Tree
from consul_tpu.store.state import (
    HEALTH_CRITICAL,
    HEALTH_PASSING,
    SESSION_BEHAVIOR_DELETE,
)


# ---------------------------------------------------------------------------
# iradix
# ---------------------------------------------------------------------------


class TestIradix:
    def test_insert_get_delete(self):
        t = Tree()
        txn = t.txn()
        for k in [b"foo", b"foobar", b"fizz", b"", b"f"]:
            txn.insert(k, k.decode() or "root")
        t2 = txn.commit()
        assert len(t2) == 5
        assert t2.get(b"foobar") == ("foobar", True)
        assert t2.get(b"fo") == (None, False)
        assert t2.get(b"") == ("root", True)
        # Old tree unchanged (snapshot isolation).
        assert len(t) == 0
        txn = t2.txn()
        old, deleted = txn.delete(b"foo")
        assert (old, deleted) == ("foo", True)
        assert txn.delete(b"nope") == (None, False)
        t3 = txn.commit()
        assert t3.get(b"foo") == (None, False)
        assert t3.get(b"foobar") == ("foobar", True)
        assert t2.get(b"foo") == ("foo", True)

    def test_ordered_iteration_and_prefix(self):
        t = Tree()
        txn = t.txn()
        keys = [b"b", b"a", b"ab", b"abc", b"abd", b"ac", b"b/1", b"b/2"]
        for k in keys:
            txn.insert(k, 1)
        t = txn.commit()
        assert t.keys() == sorted(keys)
        assert t.keys(b"ab") == [b"ab", b"abc", b"abd"]
        assert t.keys(b"b/") == [b"b/1", b"b/2"]
        assert t.keys(b"zz") == []

    def test_delete_prefix(self):
        t = Tree()
        txn = t.txn()
        for k in [b"a/1", b"a/2", b"a/2/x", b"b/1"]:
            txn.insert(k, 1)
        t = txn.commit()
        txn = t.txn()
        assert txn.delete_prefix(b"a/") == 3
        t = txn.commit()
        assert t.keys() == [b"b/1"]

    def test_fuzz_against_dict(self):
        import random

        rng = random.Random(42)
        t = Tree()
        model: dict[bytes, int] = {}
        alphabet = b"abc/"
        for step in range(2000):
            k = bytes(rng.choice(alphabet) for _ in range(rng.randint(0, 6)))
            txn = t.txn()
            if rng.random() < 0.6:
                txn.insert(k, step)
                model[k] = step
            else:
                _, deleted = txn.delete(k)
                assert deleted == (k in model)
                model.pop(k, None)
            t = txn.commit()
            assert len(t) == len(model)
        assert t.keys() == sorted(model)
        for k, v in model.items():
            assert t.get(k) == (v, True)

    def test_watch_fires_on_write_below_prefix(self):
        async def run():
            t = Tree()
            txn = t.txn()
            txn.insert(b"a/1", 1)
            txn.insert(b"b/1", 1)
            t = txn.commit()
            w_a = t.watch_prefix(b"a/")
            w_b = t.watch_prefix(b"b/")
            txn = t.txn()
            txn.insert(b"a/2", 2)
            txn.commit()
            assert w_a.is_set()
            assert not w_b.is_set()

        asyncio.run(run())

    def test_watch_fires_on_key_creation(self):
        async def run():
            t = Tree()
            txn = t.txn()
            txn.insert(b"foo/bar", 1)
            t = txn.commit()
            ev, _, found = t.get_watch(b"foo/baz")
            assert not found
            txn = t.txn()
            txn.insert(b"foo/baz", 2)
            txn.commit()
            assert ev.is_set()

        asyncio.run(run())


# ---------------------------------------------------------------------------
# memdb
# ---------------------------------------------------------------------------


class TestMemDB:
    def _db(self):
        from consul_tpu.store import IndexSchema, MemDB, TableSchema

        return MemDB(
            [
                TableSchema(
                    "t",
                    primary=lambda r: r["id"].encode(),
                    indexes=(
                        IndexSchema("u", key=lambda r: r["u"].encode(), unique=True),
                    ),
                )
            ]
        )

    def test_concurrent_write_txns_rejected(self):
        db = self._db()
        a = db.txn(write=True)
        with pytest.raises(RuntimeError):
            db.txn(write=True)
        a.abort()
        db.txn(write=True).commit()  # lock released after abort

    def test_unique_index_violation_raises(self):
        db = self._db()
        tx = db.txn(write=True)
        tx.insert("t", {"id": "r1", "u": "K"})
        with pytest.raises(ValueError):
            tx.insert("t", {"id": "r2", "u": "K"})
        # Same record updating itself is fine.
        tx.insert("t", {"id": "r1", "u": "K"})
        tx.commit()

    def test_writer_lock_survives_failed_write(self):
        s = StateStore()
        with pytest.raises(KeyError):
            s.ensure_registration(1, {"node": "n1", "service": {"id": "x"}})
        # The abandoned txn must not wedge the single-writer lock.
        s.kv_set(2, {"key": "ok", "value": b"1"})
        assert s.kv_get("ok")[1]["value"] == b"1"

    def test_read_txn_pins_roots(self):
        db = self._db()
        w = db.txn(write=True)
        w.insert("t", {"id": "r1", "u": "a"})
        w.commit()
        reader = db.txn()
        w2 = db.txn(write=True)
        w2.insert("t", {"id": "r2", "u": "b"})
        w2.commit()
        # The reader's view is frozen at txn start.
        assert len(reader.records("t")) == 1
        assert len(db.txn().records("t")) == 2


# ---------------------------------------------------------------------------
# StateStore: catalog
# ---------------------------------------------------------------------------


def _register(store, idx, node="n1", service=None, checks=None, address="10.0.0.1"):
    req = {"node": node, "address": address}
    if service:
        req["service"] = service
    if checks:
        req["checks"] = checks
    store.ensure_registration(idx, req)


class TestCatalog:
    def test_registration_and_queries(self):
        s = StateStore()
        _register(
            s,
            1,
            node="n1",
            service={"id": "web1", "service": "web", "tags": ["v1"], "port": 80},
            checks=[
                {
                    "check_id": "web1-http",
                    "name": "http",
                    "status": HEALTH_PASSING,
                    "service_id": "web1",
                }
            ],
        )
        _register(
            s,
            2,
            node="n2",
            address="10.0.0.2",
            service={"id": "web2", "service": "web", "tags": ["v2"], "port": 81},
        )
        idx, nodes = s.nodes()
        assert idx == 2 and [n["node"] for n in nodes] == ["n1", "n2"]
        idx, svcs = s.services()
        assert svcs == {"web": ["v1", "v2"]}
        idx, inst = s.service_nodes("web")
        assert len(inst) == 2
        assert inst[0]["node_address"] == "10.0.0.1"
        idx, inst = s.service_nodes("web", tag="v2")
        assert [i["id"] for i in inst] == ["web2"]

    def test_check_service_nodes_passing_filter(self):
        s = StateStore()
        _register(
            s, 1, node="n1",
            service={"id": "api1", "service": "api"},
            checks=[{"check_id": "c1", "status": HEALTH_PASSING, "service_id": "api1"}],
        )
        _register(
            s, 2, node="n2",
            service={"id": "api2", "service": "api"},
            checks=[{"check_id": "c2", "status": HEALTH_CRITICAL, "service_id": "api2"}],
        )
        _, all_nodes = s.check_service_nodes("api")
        assert len(all_nodes) == 2
        _, healthy = s.check_service_nodes("api", passing_only=True)
        assert [h["service"]["id"] for h in healthy] == ["api1"]

    def test_singular_check_and_checks_list_both_register(self):
        s = StateStore()
        _register(s, 1, node="n1")
        s.ensure_registration(
            2,
            {
                "node": "n1",
                "checks": [{"check_id": "c1", "status": HEALTH_PASSING}],
                "check": {"check_id": "c2", "status": HEALTH_PASSING},
            },
        )
        _, checks = s.node_checks("n1")
        assert sorted(c["check_id"] for c in checks) == ["c1", "c2"]

    def test_service_nodes_watch_covers_node_changes(self):
        async def run():
            s = StateStore()
            _register(s, 1, node="n1", service={"id": "w1", "service": "web"})
            ws = WatchSet()
            s.service_nodes("web", ws=ws)
            # Node address change alone (services untouched) must wake it.
            _register(s, 2, node="n1", address="10.9.9.9")
            assert await ws.wait(timeout=0.5)

        asyncio.run(run())

    def test_idempotent_registration_does_not_bump(self):
        s = StateStore()
        _register(s, 1, node="n1", service={"id": "s1", "service": "s"})
        idx1, _ = s.nodes()
        _register(s, 5, node="n1", service={"id": "s1", "service": "s"})
        idx2, _ = s.nodes()
        assert idx1 == idx2 == 1  # catalog.go ensureNodeTxn idempotency

    def test_delete_node_cascades(self):
        s = StateStore()
        _register(
            s, 1, node="n1",
            service={"id": "s1", "service": "s"},
            checks=[{"check_id": "c1", "status": HEALTH_PASSING}],
        )
        assert s.delete_node(2, "n1")
        assert s.nodes()[1] == []
        assert s.node_services("n1")[1] == []
        assert s.node_checks("n1")[1] == []
        assert not s.delete_node(3, "n1")

    def test_checks_in_state_index(self):
        s = StateStore()
        _register(s, 1, node="n1", checks=[{"check_id": "c1", "status": HEALTH_PASSING}])
        _register(s, 2, node="n2", checks=[{"check_id": "c2", "status": HEALTH_CRITICAL}])
        _, crit = s.checks_in_state(HEALTH_CRITICAL)
        assert [c["check_id"] for c in crit] == ["c2"]


# ---------------------------------------------------------------------------
# StateStore: KV
# ---------------------------------------------------------------------------


class TestKV:
    def test_set_get_list_delete(self):
        s = StateStore()
        s.kv_set(1, {"key": "foo/bar", "value": b"1"})
        s.kv_set(2, {"key": "foo/baz", "value": b"2", "flags": 42})
        idx, rec = s.kv_get("foo/bar")
        assert idx == 2 and rec["value"] == b"1"
        assert rec["create_index"] == 1 and rec["modify_index"] == 1
        idx, recs = s.kv_list("foo/")
        assert [r["key"] for r in recs] == ["foo/bar", "foo/baz"]
        assert s.kv_delete(3, "foo/bar")
        idx, rec = s.kv_get("foo/bar")
        assert rec is None
        # Tombstone keeps the prefix index at the delete index.
        idx, recs = s.kv_list("foo/")
        assert idx == 3 and len(recs) == 1
        # Reap tombstones -> index stays (kvs index still 3 via delete bump).
        assert s.tombstone_reap(4, up_to=3) == 1

    def test_cas(self):
        s = StateStore()
        assert s.kv_set_cas(1, {"key": "k", "value": b"a"}, cas_index=0)
        assert not s.kv_set_cas(2, {"key": "k", "value": b"b"}, cas_index=0)
        assert not s.kv_set_cas(2, {"key": "k", "value": b"b"}, cas_index=99)
        assert s.kv_set_cas(2, {"key": "k", "value": b"b"}, cas_index=1)
        assert s.kv_get("k")[1]["value"] == b"b"
        assert not s.kv_delete_cas(3, "k", cas_index=1)
        assert s.kv_delete_cas(3, "k", cas_index=2)

    def test_keys_with_separator(self):
        s = StateStore()
        for i, k in enumerate(["a/1", "a/2", "a/sub/x", "b", "c/d/e"]):
            s.kv_set(i + 1, {"key": k, "value": b""})
        _, keys = s.kv_keys("", separator="/")
        assert keys == ["a/", "b", "c/"]
        _, keys = s.kv_keys("a/", separator="/")
        assert keys == ["a/1", "a/2", "a/sub/"]

    def test_delete_tree(self):
        s = StateStore()
        for i, k in enumerate(["x/1", "x/2", "y/1"]):
            s.kv_set(i + 1, {"key": k, "value": b""})
        assert s.kv_delete_tree(4, "x/") == 2
        _, recs = s.kv_list("")
        assert [r["key"] for r in recs] == ["y/1"]
        idx, _ = s.kv_list("x/")
        assert idx == 4  # tombstones report the delete

    def test_blocking_watch_fires(self):
        async def run():
            s = StateStore()
            s.kv_set(1, {"key": "watch/me", "value": b"a"})
            ws = WatchSet()
            s.kv_get("watch/me", ws=ws)

            async def writer():
                await asyncio.sleep(0.01)
                s.kv_set(2, {"key": "watch/me", "value": b"b"})

            w = asyncio.create_task(writer())
            fired = await ws.wait(timeout=1.0)
            assert fired
            await w
            # Unrelated write does not wake a prefix watch elsewhere.
            ws2 = WatchSet()
            s.kv_list("watch/", ws=ws2)
            s.kv_set(3, {"key": "other/key", "value": b""})
            assert not await ws2.wait(timeout=0.05)

        asyncio.run(run())


# ---------------------------------------------------------------------------
# StateStore: sessions + locks
# ---------------------------------------------------------------------------


class TestSessions:
    def _store_with_node(self):
        s = StateStore()
        _register(
            s, 1, node="n1",
            checks=[{"check_id": "serfHealth", "status": HEALTH_PASSING}],
        )
        return s

    def test_create_requires_node_and_healthy_checks(self):
        s = StateStore()
        with pytest.raises(ValueError):
            s.session_create(1, {"id": "s1", "node": "ghost"})
        s = self._store_with_node()
        s.session_create(2, {"id": "s1", "node": "n1", "checks": ["serfHealth"]})
        assert s.session_get("s1")[1]["behavior"] == "release"

    def test_lock_release_behavior(self):
        s = self._store_with_node()
        s.session_create(2, {"id": "s1", "node": "n1", "checks": []})
        assert s.kv_lock(3, {"key": "lock", "value": b"me"}, "s1")
        rec = s.kv_get("lock")[1]
        assert rec["session"] == "s1" and rec["lock_index"] == 1
        # Second session cannot steal.
        s.session_create(4, {"id": "s2", "node": "n1", "checks": []})
        assert not s.kv_lock(5, {"key": "lock", "value": b"you"}, "s2")
        # Destroy releases (behavior=release) and keeps the key.
        assert s.session_destroy(6, "s1")
        rec = s.kv_get("lock")[1]
        assert rec["session"] is None
        # Now s2 acquires; lock_index increments (KVSLock).
        assert s.kv_lock(7, {"key": "lock", "value": b"you"}, "s2")
        assert s.kv_get("lock")[1]["lock_index"] == 2

    def test_delete_behavior_and_check_invalidation(self):
        s = self._store_with_node()
        s.session_create(
            2,
            {"id": "s1", "node": "n1", "checks": ["serfHealth"],
             "behavior": SESSION_BEHAVIOR_DELETE},
        )
        assert s.kv_lock(3, {"key": "ephemeral", "value": b"x"}, "s1")
        # serfHealth going critical destroys the session -> key deleted.
        _register(
            s, 4, node="n1",
            checks=[{"check_id": "serfHealth", "status": HEALTH_CRITICAL}],
        )
        assert s.session_get("s1")[1] is None
        assert s.kv_get("ephemeral")[1] is None

    def test_default_serfhealth_check_is_validated(self):
        s = StateStore()
        _register(s, 1, node="n1")  # no serfHealth check registered
        with pytest.raises(ValueError):
            s.session_create(2, {"id": "s1", "node": "n1"})  # default checks
        s2 = self._store_with_node()
        _register(
            s2, 3, node="n1",
            checks=[{"check_id": "serfHealth", "status": HEALTH_CRITICAL}],
        )
        with pytest.raises(ValueError):
            s2.session_create(4, {"id": "s1", "node": "n1"})

    def test_delete_service_invalidates_bound_sessions(self):
        s = self._store_with_node()
        _register(
            s, 2, node="n1",
            service={"id": "web1", "service": "web"},
            checks=[{"check_id": "c1", "status": HEALTH_PASSING, "service_id": "web1"}],
        )
        s.session_create(3, {"id": "s1", "node": "n1", "checks": ["c1"]})
        assert s.delete_service(4, "n1", "web1")
        assert s.session_get("s1")[1] is None

    def test_node_delete_destroys_sessions(self):
        s = self._store_with_node()
        s.session_create(2, {"id": "s1", "node": "n1", "checks": []})
        s.delete_node(3, "n1")
        assert s.session_get("s1")[1] is None


# ---------------------------------------------------------------------------
# StateStore: coordinates, snapshot/restore
# ---------------------------------------------------------------------------


class TestMisc:
    def test_coordinate_batch_skips_unknown_nodes(self):
        s = StateStore()
        _register(s, 1, node="n1")
        coord = {"vec": [0.0] * 8, "error": 1.5, "height": 1e-5, "adjustment": 0.0}
        s.coordinate_batch_update(
            2,
            [{"node": "n1", "coord": coord}, {"node": "ghost", "coord": coord}],
        )
        idx, coords = s.coordinates()
        assert idx == 2 and [c["node"] for c in coords] == ["n1"]
        assert s.coordinate("n1") == coord
        assert s.coordinate("ghost") is None

    def test_snapshot_restore_roundtrip(self):
        s = StateStore()
        _register(s, 1, node="n1", service={"id": "w", "service": "web"})
        s.kv_set(2, {"key": "a", "value": b"1"})
        s.kv_delete(3, "a")
        s.kv_set(4, {"key": "b", "value": b"2"})
        snap = s.snapshot()

        s2 = StateStore()
        s2.restore(snap)
        assert s2.nodes() == s.nodes()
        assert s2.kv_get("b")[1]["value"] == b"2"
        assert s2.kv_list("")[0] == 4
        # Tombstone for "a" came along.
        assert s2.kv_list("a")[0] == 4
        _, svcs = s2.services()
        assert svcs == {"web": []}

    def test_config_entries_and_prepared_queries(self):
        s = StateStore()
        s.config_entry_set(1, {"kind": "service-defaults", "name": "web", "protocol": "http"})
        idx, e = s.config_entry_get("service-defaults", "web")
        assert idx == 1 and e["protocol"] == "http"
        _, by_kind = s.config_entries_by_kind("service-defaults")
        assert len(by_kind) == 1
        assert s.config_entry_delete(2, "service-defaults", "web")

        s.prepared_query_set(3, {"id": "q1", "name": "prod", "service": {"service": "web"}})
        assert s.prepared_query_resolve("prod")["id"] == "q1"
        assert s.prepared_query_resolve("q1")["name"] == "prod"
        assert s.prepared_query_delete(4, "q1")
        assert s.prepared_query_resolve("prod") is None
