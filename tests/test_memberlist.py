"""Host-plane memberlist tests over the in-memory network — the
counterpart of memberlist's MockTransport-based tests (SURVEY.md §4.2).
All clusters run at interval_scale=0.02 (50x faster than LAN timing)."""

import asyncio

import pytest

from helpers import wait_until

from consul_tpu.net import (
    InMemoryNetwork,
    Memberlist,
    MemberlistConfig,
)
from consul_tpu.net.memberlist import NodeStatus

SCALE = 0.02


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def make_cluster(net, n, joined=True, **cfg_kw):
    nodes = []
    for i in range(n):
        t = net.new_transport(f"mem://n{i}")
        m = Memberlist(
            MemberlistConfig(name=f"n{i}", interval_scale=SCALE, **cfg_kw), t
        )
        await m.start()
        nodes.append(m)
    if joined:
        for m in nodes[1:]:
            assert await m.join(["mem://n0"]) == 1
    return nodes


async def stop_all(nodes):
    for m in nodes:
        await m.shutdown()


def test_three_node_cluster_forms():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 3)
        ok = await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes)
        )
        assert ok, [len(m.members()) for m in nodes]
        # Everyone sees everyone alive by name.
        names = {m.config.name for m in nodes}
        for m in nodes:
            assert {x.name for x in m.members()} == names
        await stop_all(nodes)

    run(main())


def test_failure_detection_marks_dead():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 4)
        assert await wait_until(
            lambda: all(len(m.members()) == 4 for m in nodes)
        )
        # Crash n3: its transport vanishes from the network.
        await nodes[3].shutdown()
        survivors = nodes[:3]
        ok = await wait_until(
            lambda: all(
                m.nodes["n3"].status in (NodeStatus.DEAD,) for m in survivors
            ),
            timeout=40.0,
        )
        assert ok, [m.nodes["n3"].status for m in survivors]
        await stop_all(survivors)

    run(main())


def test_graceful_leave_is_left_not_dead():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes)
        )
        await nodes[2].leave()
        await nodes[2].shutdown()
        ok = await wait_until(
            lambda: all(
                m.nodes["n2"].status == NodeStatus.LEFT for m in nodes[:2]
            )
        )
        assert ok, [m.nodes["n2"].status for m in nodes[:2]]
        await stop_all(nodes[:2])

    run(main())


def test_false_suspicion_is_refuted():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes)
        )
        # Inject a false suspicion about n1 directly into n0's state
        # machine (the serf messageDropper-style fault injection).
        victim_inc = nodes[0].nodes["n1"].incarnation
        nodes[0]._suspect_node(
            {"inc": victim_inc, "node": "n1", "from": "n0"}
        )
        assert nodes[0].nodes["n1"].status == NodeStatus.SUSPECT
        # n1 must hear the gossiped suspicion, refute with a higher
        # incarnation, and everyone returns to alive.
        ok = await wait_until(
            lambda: all(
                m.nodes["n1"].status == NodeStatus.ALIVE
                and m.nodes["n1"].incarnation > victim_inc
                for m in nodes
            ),
            timeout=40.0,
        )
        assert ok, [
            (m.nodes["n1"].status, m.nodes["n1"].incarnation) for m in nodes
        ]
        await stop_all(nodes)

    run(main())


def test_cluster_survives_30pct_packet_loss():
    async def main():
        net = InMemoryNetwork(loss=0.30, seed=7)
        nodes = await make_cluster(net, 4)
        ok = await wait_until(
            lambda: all(len(m.members()) == 4 for m in nodes), timeout=40.0
        )
        assert ok
        # Under loss, transient suspicion may occur, but nobody should be
        # declared dead while all transports are up: give it a while and
        # confirm views return to/stay alive.
        await asyncio.sleep(2.0)
        for m in nodes:
            assert all(
                x.status in (NodeStatus.ALIVE, NodeStatus.SUSPECT)
                for x in m.nodes.values()
            ), f"{m.config.name} sees a dead node despite all being up"
        await stop_all(nodes)

    run(main())


def test_push_pull_converges_without_gossip():
    async def main():
        # Drop every gossip/user datagram except ping/ack traffic: the
        # periodic TCP push/pull must still converge membership.
        from consul_tpu.net import wire

        def drop(payload, src, dst):
            t = payload[0]
            return t in (
                wire.MessageType.SUSPECT,
                wire.MessageType.ALIVE,
                wire.MessageType.DEAD,
                wire.MessageType.COMPOUND,
            )

        net = InMemoryNetwork(drop_fn=drop)
        nodes = await make_cluster(net, 3)
        ok = await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes), timeout=50.0
        )
        assert ok, [len(m.members()) for m in nodes]
        await stop_all(nodes)

    run(main())


def test_stale_alive_does_not_clear_suspicion():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes)
        )
        m0 = nodes[0]
        inc = m0.nodes["n1"].incarnation
        m0._suspect_node({"inc": inc, "node": "n1", "from": "n0"})
        assert m0.nodes["n1"].status == NodeStatus.SUSPECT
        # A stale alive at the SAME incarnation must not clear it
        # (refutation needs a strictly higher incarnation).
        m0._alive_node(
            {"name": "n1", "addr": "mem://n1", "inc": inc,
             "status": 0, "meta": b""}
        )
        assert m0.nodes["n1"].status == NodeStatus.SUSPECT
        await stop_all(nodes)

    run(main())


def test_late_joiner_sees_left_not_dead_via_push_pull():
    async def main():
        net = InMemoryNetwork()
        nodes = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(m.members()) == 3 for m in nodes)
        )
        await nodes[2].leave()
        await nodes[2].shutdown()
        assert await wait_until(
            lambda: nodes[0].nodes["n2"].status == NodeStatus.LEFT
        )
        # A late joiner merges n0's state.  Like the reference
        # (mergeState -> deadNode ignores unknown nodes,
        # state.go:1297-1300 + 1222-1230), it must never resurrect the
        # departed node as ALIVE; it either never materializes or is LEFT.
        t = net.new_transport("mem://n3")
        late = Memberlist(
            MemberlistConfig(name="n3", interval_scale=SCALE), t
        )
        await late.start()
        assert await late.join(["mem://n0"]) == 1
        await asyncio.sleep(1.0)
        n2 = late.nodes.get("n2")
        assert n2 is None or n2.status == NodeStatus.LEFT, n2
        # And the nodes that do appear are the real live ones.
        assert {m.name for m in late.members()} == {"n0", "n1", "n3"}
        await stop_all(nodes[:2] + [late])

    run(main())


def test_udp_transport_smoke():
    async def main():
        from consul_tpu.net import UDPTransport

        ts = []
        ms = []
        for i in range(3):
            t = UDPTransport("127.0.0.1", 0)
            await t.start()
            ts.append(t)
            m = Memberlist(
                MemberlistConfig(name=f"u{i}", interval_scale=SCALE), t
            )
            await m.start()
            ms.append(m)
        for m in ms[1:]:
            assert await m.join([ts[0].local_addr()]) == 1
        ok = await wait_until(
            lambda: all(len(m.members()) == 3 for m in ms), timeout=30.0
        )
        assert ok, [len(m.members()) for m in ms]
        await stop_all(ms)

    run(main())
