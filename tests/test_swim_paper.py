"""SWIM-paper curve reproduction (the north-star acceptance test).

BASELINE.json: "reproduce the SWIM paper's first-detection-time curve
within 5%".  The SWIM paper (Das, Gupta, Motivala 2002, §5) predicts:

  * First detection of a crashed member: each of the n-1 live members
    probes one uniformly random member per protocol period
    (memberlist/state.go:214-256), so the probability some member
    probes the crashed one in a period is p = 1-(1-1/(n-1))^(n-1)
    -> 1-1/e, and the first-detection time (in periods, counting the
    failed probe's own period since suspicion lands at its end —
    direct timeout + indirect probes fill the interval,
    state.go:283-497) is Geometric(p) with mean 1/p -> e/(e-1) ~ 1.58,
    INDEPENDENT of n.
  * Epidemic dissemination: with per-round fanout F over uniform
    targets, the infected fraction follows the mean-field recursion
    x' = x + (1-x)(1-exp(-F x)) and reaches ~all members in O(log n)
    rounds (the same math behind retransmit_limit, util.go:72-76).

All runs are fixed-seed, so the 5% assertions are deterministic.
"""

import functools

import jax
import numpy as np
import pytest

from consul_tpu.models.broadcast import BroadcastConfig, broadcast_init
from consul_tpu.models.swim import SwimConfig, swim_init
from consul_tpu.sim.engine import broadcast_scan, swim_scan


@functools.lru_cache(maxsize=None)
def _first_detection_periods(n: int, seeds: int, seed0: int = 0) -> np.ndarray:
    """Detection time in probe periods for ``seeds`` independent
    universes (vmapped over the PRNG key), for one crashed subject.

    Cached per (n, seeds, seed0): the mean and CDF tests read the SAME
    400-universe run, so the ~30s simulation is paid once per session
    (the returned array is marked read-only to keep the cache safe)."""
    cfg = SwimConfig(n=n, subject=7, fail_at_tick=0)
    P = cfg.probe_interval_ticks
    steps = 30 * P

    def one(k):
        _, (sus, _dead) = swim_scan(swim_init(cfg), k, cfg, steps)
        return sus

    keys = jax.random.split(jax.random.PRNGKey(seed0), seeds)
    sus = np.asarray(jax.vmap(one)(keys))          # [seeds, steps]
    assert (sus.max(axis=1) > 0).all(), "subject never detected"
    first_tick = np.argmax(sus > 0, axis=1)
    # Suspicion matures exactly one period after the failed probe's
    # tick, i.e. at the END of the period containing the failed probe —
    # the paper's accounting.  first_tick/P is therefore the period
    # count, starting at 1.
    periods = first_tick / P
    periods.setflags(write=False)
    return periods


def geometric_p(n: int) -> float:
    return 1.0 - (1.0 - 1.0 / (n - 1)) ** (n - 1)


@pytest.mark.slow
def test_first_detection_mean_within_5pct():
    # Slow tier (tier-1 budget policy, PR 13): the 400-universe
    # long-horizon band (mean + CDF share one cached ~30s run, so
    # BOTH ride the slow tier together) — the U=96 sweep twin
    # (test_sweep.TestSeedSweepDistribution) and the two-n ladder
    # below keep the SWIM-paper detection band covered there, and the
    # infection-curve/mean-field pins stay tier-1.
    n, seeds = 512, 400
    periods = _first_detection_periods(n, seeds)
    expected = 1.0 / geometric_p(n)               # ~1.582
    rel_err = abs(periods.mean() - expected) / expected
    assert rel_err < 0.05, (periods.mean(), expected, rel_err)


@pytest.mark.slow  # shares the cached 400-universe run with the mean
def test_first_detection_cdf_within_5pct():
    n, seeds = 512, 400
    periods = _first_detection_periods(n, seeds)
    p = geometric_p(n)
    for k in range(1, 7):
        emp = (periods <= k).mean()
        geo = 1.0 - (1.0 - p) ** k
        assert abs(emp - geo) < 0.05, (k, emp, geo)


@pytest.mark.slow  # ~100s at CPU: 600 long-horizon universes at two n
def test_first_detection_independent_of_n():
    """The paper's headline property: expected detection time does not
    grow with group size (SWIM §2: constant expected detection time).

    Behind -m slow per the tier-1 budget policy for long-horizon
    distributional bands (PR 3): the n=512 mean/CDF tests above keep
    the paper band pinned in tier-1; this 600-universe two-n ladder
    rides the slow tier with the U=256 acceptance sweep."""
    small = _first_detection_periods(128, 300, seed0=1).mean()
    large = _first_detection_periods(1024, 300, seed0=2).mean()
    assert abs(small - large) / small < 0.10, (small, large)


def test_infection_curve_matches_mean_field():
    n = 20_000
    cfg = BroadcastConfig(n=n, fanout=4, delivery="edges")
    steps = 16
    _, infected = broadcast_scan(
        broadcast_init(cfg, origin=0), jax.random.PRNGKey(3), cfg, steps
    )
    x = np.asarray(infected) / n

    mf = [1.0 / n]
    for _ in range(steps):
        xt = mf[-1]
        mf.append(xt + (1 - xt) * (1 - np.exp(-cfg.fanout * xt)))
    mf = np.array(mf[1:])

    # Pointwise agreement through the whole epidemic (0 -> ~1), well
    # inside the 5% target.
    assert np.abs(x - mf).max() < 0.02, np.abs(x - mf).max()

    # And convergence is O(log n): 99% infection within ~log_F-ish
    # rounds of the mean-field prediction.
    t99_sim = int(np.argmax(x >= 0.99))
    t99_mf = int(np.argmax(mf >= 0.99))
    assert abs(t99_sim - t99_mf) <= 1, (t99_sim, t99_mf)


def test_infection_t99_grows_logarithmically():
    """Dissemination latency grows ~log(n): quadrupling n adds at most
    ~log_2(4)=2 rounds at fanout 4 (paper §5.2 / util.go:72-76)."""
    t99 = {}
    for n, seed in ((5_000, 4), (80_000, 5)):
        cfg = BroadcastConfig(n=n, fanout=4, delivery="edges")
        _, infected = broadcast_scan(
            broadcast_init(cfg, origin=0), jax.random.PRNGKey(seed), cfg, 24
        )
        frac = np.asarray(infected) / n
        assert frac[-1] >= 0.999
        t99[n] = int(np.argmax(frac >= 0.99))
    assert t99[80_000] - t99[5_000] <= 3, t99
