"""Planted Pallas DMA-discipline fixtures for equivlint's P1-P3 rules.

Each entry is a tiny Mosaic kernel with ONE deliberate violation (or
none, for the clean controls): the bad/clean pairs pin that
``consul_tpu.analysis.equivlint.pallas_findings`` catches exactly the
planted defect with file:line provenance into THIS file, and nothing
else.  ``EQUIVLINT_PROGRAMS`` (name -> (fn, args)) is the
``cli equivlint --module`` contract, mirroring jaxlint's
``JAXLINT_PROGRAMS`` fixture seam — tracing only, nothing here is ever
executed, so the deadlocking kernels are safe to import.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SHAPE = (8, 128)


def _call(kernel, *, sems, interpret=True, collective_id=None):
    """pallas_call wrapper shared by every fixture: ANY-space refs (the
    ring kernel's convention) and DMA scratch semaphores."""
    params = {}
    if collective_id is not None:
        params["compiler_params"] = pltpu.TPUCompilerParams(
            collective_id=collective_id
        )

    def fn(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(_SHAPE, jnp.int32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=list(sems),
            interpret=interpret,
            **params,
        )(x)

    return fn


def _clean_local(in_ref, out_ref, sem):
    copy = pltpu.make_async_copy(in_ref, out_ref, sem)
    copy.start()
    copy.wait()


def _p1_missing_wait(in_ref, out_ref, sem):
    copy = pltpu.make_async_copy(in_ref, out_ref, sem)
    copy.start()  # planted P1: never waited


def _p1_wait_without_start(in_ref, out_ref, sem):
    copy = pltpu.make_async_copy(in_ref, out_ref, sem)
    copy.wait()  # planted P1: nothing in flight


def _p2_slot_reuse(in_ref, out_ref, sem):
    # Double-buffered semaphore used WITHOUT the discipline: slot 0 is
    # restarted while its first copy is still in flight — the h%2 race
    # the ring kernel's start(h+1)-before-wait(h) pipeline avoids by
    # alternating slots.
    first = pltpu.make_async_copy(in_ref.at[0], out_ref.at[0],
                                  sem.at[0])
    first.start()
    second = pltpu.make_async_copy(in_ref.at[1], out_ref.at[1],
                                   sem.at[0])
    second.start()  # planted P2: slot 0 still in flight
    second.wait()
    first.wait()


def _p2_clean_double_buffer(in_ref, out_ref, sem):
    # The correct spelling of the same pipeline: alternate slots, so
    # two copies are in flight on DIFFERENT slots (the ring kernel's
    # schedule) — must NOT fire.
    first = pltpu.make_async_copy(in_ref.at[0], out_ref.at[0],
                                  sem.at[0])
    first.start()
    second = pltpu.make_async_copy(in_ref.at[1], out_ref.at[1],
                                   sem.at[1])
    second.start()
    first.wait()
    second.wait()


def _p2_touch_dst(in_ref, out_ref, sem):
    copy = pltpu.make_async_copy(in_ref, out_ref, sem)
    copy.start()
    out_ref[0, 0]  # planted P2: read of the in-flight destination
    copy.wait()


def _barrier_kernel(in_ref, out_ref, sem):
    bar = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(bar, 1)
    pltpu.semaphore_wait(bar, 1)
    copy = pltpu.make_async_copy(in_ref, out_ref, sem)
    copy.start()
    copy.wait()


_ARGS = (jax.ShapeDtypeStruct(_SHAPE, jnp.int32),)
_DMA = pltpu.SemaphoreType.DMA
_DMA2 = pltpu.SemaphoreType.DMA((2,))

EQUIVLINT_PROGRAMS = {
    "clean_local": (_call(_clean_local, sems=(_DMA,)), _ARGS),
    "p1_missing_wait": (_call(_p1_missing_wait, sems=(_DMA,)), _ARGS),
    "p1_wait_without_start": (
        _call(_p1_wait_without_start, sems=(_DMA,)), _ARGS),
    "p2_slot_reuse": (_call(_p2_slot_reuse, sems=(_DMA2,)), _ARGS),
    "p2_clean_double_buffer": (
        _call(_p2_clean_double_buffer, sems=(_DMA2,)), _ARGS),
    "p2_touch_dst": (_call(_p2_touch_dst, sems=(_DMA,)), _ARGS),
    # P3 pair: the SAME barrier kernel, once under interpret=True (the
    # interpreter neither supports nor needs the barrier) and once on
    # "hardware" without a collective_id (Mosaic cannot match the
    # barrier across programs).  Tracing only — never lowered.
    "p3_barrier_under_interpret": (
        _call(_barrier_kernel, sems=(_DMA,), interpret=True,
              collective_id=7), _ARGS),
    "p3_barrier_no_collective_id": (
        _call(_barrier_kernel, sems=(_DMA,), interpret=False), _ARGS),
}
