"""Surface-fill features: telemetry counters, bexpr result filtering,
AES-GCM gossip encryption + keyring rotation, alias checks.

Parity models: armon/go-metrics inmem_test.go, go-bexpr evaluate_test,
memberlist/security_test.go + keyring_test.go, serf/keymanager_test.go,
agent/checks alias_test.go.
"""

import asyncio
import json

import pytest

from helpers import wait_for as wait_until
from helpers import requires_crypto

from consul_tpu.telemetry import Metrics
from consul_tpu.agent.bexpr import FilterError, create_filter
from consul_tpu.net.security import (
    Keyring,
    SecurityError,
    decode_key,
    generate_key,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def test_metrics_aggregate_and_snapshot():
    m = Metrics()
    m.incr_counter("rpc.queries_blocking")
    m.incr_counter("rpc.queries_blocking")
    m.set_gauge("memberlist.health.score", 3)
    m.add_sample("consul.fsm.kvs", 1.5)
    m.add_sample("consul.fsm.kvs", 2.5)
    snap = m.snapshot()
    counters = {c["Name"]: c for c in snap["Counters"]}
    assert counters["rpc.queries_blocking"]["Count"] == 2
    gauges = {g["Name"]: g["Value"] for g in snap["Gauges"]}
    assert gauges["memberlist.health.score"] == 3
    samples = {s["Name"]: s for s in snap["Samples"]}
    assert samples["consul.fsm.kvs"]["Mean"] == 2.0
    assert samples["consul.fsm.kvs"]["Max"] == 2.5


def test_metrics_emitted_by_live_cluster():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call
        from consul_tpu.telemetry import metrics

        metrics().reset()
        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            await http_call(addr, "PUT", "/v1/kv/m1", b"x")
            status, _, snap = await http_call(addr, "GET",
                                              "/v1/agent/metrics")
            assert status == 200
            names = {c["Name"] for c in snap["Counters"]}
            assert "http.PUT" in names and "http.GET" in names
            sample_names = {s["Name"] for s in snap["Samples"]}
            assert "consul.fsm.kvs" in sample_names
            assert "http.request" in sample_names

    run(main())


# ---------------------------------------------------------------------------
# bexpr ?filter=
# ---------------------------------------------------------------------------

ROWS = [
    {"ServiceName": "web", "ServicePort": 80,
     "ServiceTags": ["primary", "v2"],
     "Node": {"Meta": {"env": "prod"}},
     "Checks": [{"Status": "passing"}, {"Status": "warning"}]},
    {"ServiceName": "db", "ServicePort": 5432,
     "ServiceTags": [],
     "Node": {"Meta": {}},
     "Checks": [{"Status": "critical"}]},
]


def test_bexpr_operators():
    f = create_filter('ServiceName == "web"')
    assert f.apply(ROWS) == [ROWS[0]]
    assert create_filter('ServiceName != "web"').apply(ROWS) == [ROWS[1]]
    assert create_filter('"primary" in ServiceTags').apply(ROWS) == [ROWS[0]]
    assert create_filter('"primary" not in ServiceTags').apply(ROWS) == [ROWS[1]]
    assert create_filter('ServiceTags is empty').apply(ROWS) == [ROWS[1]]
    assert create_filter('Node.Meta.env == "prod"').apply(ROWS) == [ROWS[0]]
    assert create_filter('ServicePort == 5432').apply(ROWS) == [ROWS[1]]
    assert create_filter('ServiceName matches "^w.b$"').apply(ROWS) == [ROWS[0]]
    assert create_filter(
        'Checks.Status == "critical" or ServicePort == 80'
    ).apply(ROWS) == ROWS
    assert create_filter(
        'not (ServiceName == "db") and Checks.Status == "passing"'
    ).apply(ROWS) == [ROWS[0]]


def test_bexpr_errors():
    with pytest.raises(FilterError):
        create_filter('ServiceName == == "x"')
    with pytest.raises(FilterError):
        create_filter('ServiceName ==')
    with pytest.raises(FilterError):
        create_filter("")


def test_http_filter_param():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            for name, port in (("web", 80), ("db", 5432)):
                st, _, _x = await http_call(
                    addr, "PUT", "/v1/catalog/register",
                    json.dumps({"Node": f"n-{name}", "Address": "10.0.0.1",
                                "Service": {"Service": name, "Port": port}}
                               ).encode(),
                )
                assert st == 200
            import urllib.parse

            flt = urllib.parse.quote('ServiceName == "web"')
            st, _, rows = await http_call(
                addr, "GET", f"/v1/catalog/service/web?filter={flt}")
            assert st == 200 and len(rows) == 1
            st, _, rows = await http_call(
                addr, "GET",
                f"/v1/catalog/service/web?filter="
                + urllib.parse.quote('ServicePort == 9999'))
            assert st == 200 and rows == []
            st, _, err = await http_call(
                addr, "GET", "/v1/catalog/nodes?filter="
                + urllib.parse.quote('Bogus =='))
            assert st == 400

    run(main())


# ---------------------------------------------------------------------------
# gossip encryption + keyring
# ---------------------------------------------------------------------------


@requires_crypto
def test_keyring_seal_open_and_rotation():
    k1, k2 = generate_key(), generate_key()
    ring = Keyring.from_b64(k1)
    blob = ring.encrypt(b"gossip payload")
    assert blob != b"gossip payload"
    assert ring.decrypt(blob) == b"gossip payload"

    # Rotation: install k2, switch primary, old ciphertext still opens.
    ring.install(k2)
    old_ct = ring.encrypt(b"before switch")
    ring.use(k2)
    assert ring.decrypt(old_ct) == b"before switch"
    assert ring.primary_b64() == k2
    with pytest.raises(ValueError):
        ring.remove(k2)  # primary is protected
    ring.remove(k1)
    with pytest.raises(SecurityError):
        ring.decrypt(old_ct)  # k1 is gone

    stranger = Keyring.from_b64(generate_key())
    with pytest.raises(SecurityError):
        stranger.decrypt(ring.encrypt(b"secret"))


@requires_crypto
def test_encrypted_cluster_forms_and_rejects_plaintext():
    async def main():
        from consul_tpu.eventing.cluster import Cluster, ClusterConfig
        from consul_tpu.net.transport import InMemoryNetwork

        key = generate_key()
        net = InMemoryNetwork()

        def mk(name, keyring):
            return Cluster(
                ClusterConfig(name=name, interval_scale=0.02,
                              keyring=keyring),
                net.new_transport(f"mem://{name}"),
            )

        c1 = mk("e1", Keyring.from_b64(key))
        c2 = mk("e2", Keyring.from_b64(key))
        intruder = mk("e3", None)  # no key
        for c in (c1, c2, intruder):
            await c.start()
        assert await c2.join(["mem://e1"]) == 1
        await wait_until(
            lambda: len(c1.alive_members()) == 2
            and len(c2.alive_members()) == 2,
            msg="encrypted pair converges",
        )
        # A keyless node cannot join (its push/pull is rejected).
        assert await intruder.join(["mem://e1"]) == 0
        assert len(intruder.alive_members()) == 1
        for c in (c1, c2, intruder):
            await c.shutdown()

    run(main())


@requires_crypto
def test_cluster_wide_key_rotation_via_queries():
    async def main():
        from consul_tpu.eventing.cluster import Cluster, ClusterConfig
        from consul_tpu.net.transport import InMemoryNetwork

        k1, k2 = generate_key(), generate_key()
        net = InMemoryNetwork()
        nodes = [
            Cluster(
                ClusterConfig(name=f"k{i}", interval_scale=0.02,
                              keyring=Keyring.from_b64(k1)),
                net.new_transport(f"mem://k{i}"),
            )
            for i in range(3)
        ]
        for c in nodes:
            await c.start()
        for c in nodes[1:]:
            await c.join(["mem://k0"])
        await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in nodes),
            msg="encrypted trio",
        )
        # KeyManager dance: install -> use -> remove old, everywhere.
        out = await nodes[0].install_key(k2)
        assert not out["errors"] and out["num_resp"] >= 2
        out = await nodes[0].use_key(k2)
        assert not out["errors"]
        out = await nodes[0].remove_key(k1)
        assert not out["errors"]
        out = await nodes[0].list_keys()
        assert set(out["keys"]) == {k2}
        # Gossip still flows on the new key.
        await nodes[0].user_event("rotated", b"ok")
        for c in nodes:
            await c.shutdown()

    run(main())


def test_ui_served():
    """/ui serves the single-page dashboard; / redirects to it
    (http.go handleUI)."""

    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            st, hdrs, body = await http_call(addr, "GET", "/ui")
            assert st == 200
            assert hdrs.get("content-type", "").startswith("text/html")
            text = body.decode() if isinstance(body, bytes) else str(body)
            assert "consul-tpu" in text and "/v1/catalog/services" in text
            st, hdrs, _b = await http_call(addr, "GET", "/")
            assert st == 307 and hdrs.get("location") == "/ui"

    run(main())


def test_agent_host_and_gzip():
    """/v1/agent/host (debug/host.go) + gzip responses on
    Accept-Encoding (http.go gziphandler)."""

    async def main():
        import gzip
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            st, _, host = await http_call(addr, "GET", "/v1/agent/host")
            assert st == 200
            assert host["Host"]["Hostname"] and host["CPU"]["Count"] >= 1

            # Big responses compress when the client asks (http_call
            # transparently decompresses; the header proves it).
            st, hdrs, decoded = await http_call(
                addr, "GET", "/v1/agent/metrics",
                headers={"Accept-Encoding": "gzip"},
            )
            assert st == 200
            assert hdrs.get("content-encoding") == "gzip"
            assert "Counters" in decoded

    run(main())


def test_query_relay_factor_survives_blocked_direct_path():
    """serf query.go relayResponse: with relay_factor, responses also
    travel through random members, surviving a broken direct path."""

    async def main():
        from consul_tpu.eventing.cluster import (
            Cluster,
            ClusterConfig,
            EventType,
        )
        from consul_tpu.net.transport import InMemoryNetwork

        blocked: set = set()
        net = InMemoryNetwork(
            drop_fn=lambda payload, src, dst: (src, dst) in blocked
        )

        def responder(cluster):
            def on_event(ev):
                if ev.type == EventType.QUERY and ev.query:
                    asyncio.ensure_future(
                        ev.query.respond(cluster.config.name.encode())
                    )
            return on_event

        nodes = []
        for i in range(3):
            c = Cluster(
                ClusterConfig(name=f"q{i}", interval_scale=0.02),
                net.new_transport(f"mem://q{i}"),
            )
            c.config.on_event = responder(c)
            await c.start()
            nodes.append(c)
        for c in nodes[1:]:
            await c.join(["mem://q0"])
        await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in nodes),
            msg="trio forms",
        )

        # Sever the direct q1 -> q0 path.
        blocked.add(("mem://q1", "mem://q0"))

        # Without relay, q1's response is lost.
        res = await nodes[0].query("ping", b"", timeout_s=1.0)
        assert "q1" not in {n for n, _ in res.responses}

        # With relay_factor, it arrives through q2 — acks included
        # (query.go relays acks the same way).
        res = await nodes[0].query("ping", b"", timeout_s=2.0,
                                   relay_factor=2, want_ack=True)
        assert {n for n, _ in res.responses} >= {"q1", "q2"}
        assert "q1" in res.acks
        for c in nodes:
            await c.shutdown()

    run(main())


# ---------------------------------------------------------------------------
# alias checks
# ---------------------------------------------------------------------------


def test_alias_check_mirrors_service_health():
    async def main():
        import sys

        sys.path.insert(0, "tests")
        from test_http_dns import dev_stack
        from consul_tpu.store.state import HEALTH_CRITICAL, HEALTH_PASSING

        async with dev_stack() as (agent, _addr, _dns, _dns_addr):
            agent.add_service(
                {"id": "web1", "service": "web", "port": 80},
                checks=[{"check_id": "web-ttl", "name": "web ttl",
                         "ttl": "60s"}],
            )
            agent.add_check({"check_id": "alias-web", "name": "alias web",
                             "alias_service": "web1", "interval": "1s"})

            def alias_status():
                lc = agent.local.checks.get("alias-web")
                return lc.check.get("status") if lc else None

            # TTL check starts critical (untouched) -> alias critical.
            await wait_until(
                lambda: alias_status() == HEALTH_CRITICAL,
                msg="alias mirrors critical",
            )
            # Heartbeat the TTL -> alias flips passing.
            agent.update_ttl_check("web-ttl", HEALTH_PASSING, "beat")
            await wait_until(
                lambda: alias_status() == HEALTH_PASSING,
                msg="alias mirrors passing",
            )

    run(main())


def test_force_leave_converts_failed_to_left():
    """serf.go RemoveFailedNode via /v1/agent/force-leave: a failed
    member is converted to graceful LEFT cluster-wide."""

    async def main():
        from consul_tpu.eventing.cluster import (
            Cluster,
            ClusterConfig,
            MemberStatus,
        )
        from consul_tpu.net.transport import InMemoryNetwork

        net = InMemoryNetwork()
        nodes = []
        for i in range(3):
            c = Cluster(ClusterConfig(name=f"f{i}", interval_scale=0.02),
                        net.new_transport(f"mem://f{i}"))
            await c.start()
            nodes.append(c)
        for c in nodes[1:]:
            await c.join(["mem://f0"])
        await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in nodes),
            msg="trio forms",
        )
        await nodes[2].shutdown()
        await wait_until(
            lambda: nodes[0].members["f2"].status == MemberStatus.FAILED,
            timeout=30, msg="f2 failed",
        )
        assert await nodes[0].remove_failed_node("f2") is True
        await wait_until(
            lambda: nodes[0].members["f2"].status == MemberStatus.LEFT
            and nodes[1].members["f2"].status == MemberStatus.LEFT,
            timeout=15, msg="force-leave propagates",
        )
        # Re-issuing is allowed (the reference broadcasts without a
        # local-status precondition); only unknown names are refused.
        assert await nodes[0].remove_failed_node("f2") is True
        assert await nodes[0].remove_failed_node("ghost") is False
        for c in nodes[:2]:
            await c.shutdown()

    run(main())
