"""HTTP API + DNS interface tests.

Parity model: ``agent/http_test.go`` / ``agent/kvs_endpoint_test.go``
(status codes, blocking headers, KV flags) and ``agent/dns_test.go``
(node/service/SRV lookups, NXDOMAIN, only-passing filtering).
"""

import asyncio
import base64
import contextlib
import json
import struct

import pytest

from helpers import wait_for as wait_until

from consul_tpu.agent.agent import Agent, AgentConfig
from consul_tpu.agent.dns import (
    DNSServer,
    TYPE_A,
    TYPE_SRV,
    build_query,
    parse_response,
)
from consul_tpu.agent.http import HTTPApi
from consul_tpu.net.transport import InMemoryNetwork


async def http_call(addr, method, path, body=b"", headers=None):
    """Minimal HTTP/1.1 client: returns (status, headers, parsed-json|bytes)."""
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.open_connection(host, int(port))
    lines = [f"{method} {path} HTTP/1.1", f"Host: {host}",
             f"Content-Length: {len(body)}", "Connection: close"]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    head_lines = head.decode().split("\r\n")
    status = int(head_lines[0].split()[1])
    hdrs = {}
    for line in head_lines[1:]:
        k, _, v = line.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    if hdrs.get("content-encoding") == "gzip":
        import gzip as _gzip

        payload = _gzip.decompress(payload)
    if hdrs.get("content-type", "").startswith("application/json"):
        data = json.loads(payload) if payload.strip() else None
    else:
        data = payload
    return status, hdrs, data


@contextlib.asynccontextmanager
async def dev_stack():
    """One dev-mode server agent with HTTP + DNS attached (the
    ``consul agent -dev`` analogue)."""
    net = InMemoryNetwork()
    agent = Agent(
        AgentConfig(node_name="dev", bootstrap_expect=1,
                    gossip_interval_scale=0.05, sync_interval_s=0.3,
                    sync_retry_interval_s=0.2, reconcile_interval_s=0.2),
        gossip_transport=net.new_transport("dev:gossip"),
        rpc_transport=net.new_transport("dev:rpc"),
    )
    await agent.start()
    await wait_until(lambda: agent.delegate.is_leader(), msg="leader")
    api = HTTPApi(agent)
    addr = await api.start()
    dns = DNSServer(agent)
    dns_addr = await dns.start()
    try:
        yield agent, addr, dns, dns_addr
    finally:
        await api.stop()
        await dns.stop()
        await agent.shutdown()


async def dns_query(dns_addr, name, qtype=TYPE_A):
    host, port = dns_addr.rsplit(":", 1)
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(build_query(7, name, qtype))

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=(host, int(port))
    )
    try:
        raw = await asyncio.wait_for(fut, 5)
    finally:
        transport.close()
    return parse_response(raw)


class TestHTTPKV:
    async def test_put_get_delete_roundtrip(self):
        async with dev_stack() as (_, addr, _, _):
            status, _, ok = await http_call(addr, "PUT", "/v1/kv/app/config",
                                            b"hello")
            assert status == 200 and ok is True
            status, hdrs, data = await http_call(addr, "GET", "/v1/kv/app/config")
            assert status == 200
            assert int(hdrs["x-consul-index"]) >= 1
            assert base64.b64decode(data[0]["Value"]) == b"hello"
            assert data[0]["Key"] == "app/config"

            status, _, raw = await http_call(addr, "GET", "/v1/kv/app/config?raw")
            assert status == 200 and raw == b"hello"

            status, _, _ = await http_call(addr, "DELETE", "/v1/kv/app/config")
            assert status == 200
            status, _, _ = await http_call(addr, "GET", "/v1/kv/app/config")
            assert status == 404

    async def test_recurse_keys_and_cas(self):
        async with dev_stack() as (_, addr, _, _):
            for k, v in [("a/1", b"x"), ("a/2", b"y"), ("b/1", b"z")]:
                await http_call(addr, "PUT", f"/v1/kv/{k}", v)
            status, _, data = await http_call(addr, "GET", "/v1/kv/a?recurse")
            assert status == 200 and [e["Key"] for e in data] == ["a/1", "a/2"]
            status, _, keys = await http_call(addr, "GET",
                                              "/v1/kv/?keys&separator=/")
            assert status == 200 and keys == ["a/", "b/"]

            _, _, entry = await http_call(addr, "GET", "/v1/kv/a/1")
            idx = entry[0]["ModifyIndex"]
            status, _, ok = await http_call(addr, "PUT", f"/v1/kv/a/1?cas={idx}",
                                            b"new")
            assert ok is True
            status, _, ok = await http_call(addr, "PUT", "/v1/kv/a/1?cas=1",
                                            b"stale")
            assert ok is False

    async def test_percent_encoded_key(self):
        # Standard clients encode '/' in keys as %2F; the server must
        # decode the path like Go's net/http does.
        async with dev_stack() as (_, addr, _, _):
            status, _, ok = await http_call(addr, "PUT",
                                            "/v1/kv/app%2Fconfig", b"v")
            assert status == 200 and ok is True
            status, _, data = await http_call(addr, "GET", "/v1/kv/app/config")
            assert status == 200 and data[0]["Key"] == "app/config"

    async def test_blocking_query_via_http(self):
        async with dev_stack() as (_, addr, _, _):
            await http_call(addr, "PUT", "/v1/kv/watch", b"v1")
            _, hdrs, _ = await http_call(addr, "GET", "/v1/kv/watch")
            idx = hdrs["x-consul-index"]

            async def blocked():
                return await http_call(
                    addr, "GET", f"/v1/kv/watch?index={idx}&wait=5s"
                )

            task = asyncio.create_task(blocked())
            await asyncio.sleep(0.1)
            assert not task.done()
            await http_call(addr, "PUT", "/v1/kv/watch", b"v2")
            status, hdrs2, data = await asyncio.wait_for(task, 5)
            assert base64.b64decode(data[0]["Value"]) == b"v2"
            assert int(hdrs2["x-consul-index"]) > int(idx)


class TestHTTPCatalogHealthAgent:
    async def test_service_register_and_health(self):
        async with dev_stack() as (agent, addr, _, _):
            body = json.dumps({
                "Name": "web", "Port": 8080, "Tags": ["v1"],
                "Check": {"TTL": "10s"},
            }).encode()
            status, _, _ = await http_call(addr, "PUT",
                                           "/v1/agent/service/register", body)
            assert status == 200
            status, _, _ = await http_call(addr, "PUT",
                                           "/v1/agent/check/pass/service:web")
            assert status == 200
            await wait_until(
                lambda: agent.delegate.store.service_nodes("web")[1],
                msg="synced to catalog",
            )
            status, _, nodes = await http_call(addr, "GET",
                                               "/v1/health/service/web?passing")
            assert status == 200 and len(nodes) == 1
            assert nodes[0]["Service"]["Port"] == 8080
            status, _, svcs = await http_call(addr, "GET", "/v1/catalog/services")
            assert "web" in svcs

            status, _, data = await http_call(addr, "GET", "/v1/catalog/node/dev")
            assert status == 200 and data["Node"]["Node"] == "dev"

    async def test_agent_metrics_memberlist_hot_path(self):
        """/v1/agent/metrics (agent_endpoint.go AgentMetrics) carries
        the memberlist hot-path gauges in the reference InmemSink
        DisplayMetrics shape: the Lifeguard ``memberlist.health.score``
        gauge (awareness.go:50 — wired at awareness construction, so a
        healthy agent reports 0 rather than nothing) with the Labels
        field, and Stddev on every aggregated sample."""
        from consul_tpu.telemetry import metrics

        metrics().reset()
        async with dev_stack() as (_agent, addr, _, _):
            # A first request so its http.request timer sample is
            # aggregated before the snapshot below reads it.
            await http_call(addr, "GET", "/v1/agent/self")
            status, _, snap = await http_call(addr, "GET",
                                              "/v1/agent/metrics")
            assert status == 200
            gauges = {g["Name"]: g for g in snap["Gauges"]}
            score = gauges["memberlist.health.score"]
            assert score["Value"] == 0  # healthy dev agent
            assert score["Labels"] == {}
            # DisplayMetrics sample shape (Stddev + Labels) on the
            # timer samples the HTTP hot path just emitted.
            samples = {s["Name"]: s for s in snap["Samples"]}
            req = samples["http.request"]
            for field in ("Count", "Sum", "Min", "Max", "Mean",
                          "Stddev", "Labels"):
                assert field in req

    async def test_status_and_members(self):
        async with dev_stack() as (_, addr, _, _):
            status, _, leader = await http_call(addr, "GET", "/v1/status/leader")
            assert status == 200 and leader  # dev server is its own leader
            status, _, members = await http_call(addr, "GET", "/v1/agent/members")
            assert [m["Name"] for m in members] == ["dev"]
            status, _, self_info = await http_call(addr, "GET", "/v1/agent/self")
            assert self_info["Config"]["NodeName"] == "dev"

    async def test_session_and_lock_over_http(self):
        async with dev_stack() as (_, addr, _, _):
            status, _, sess = await http_call(
                addr, "PUT", "/v1/session/create",
                json.dumps({"TTL": "10s"}).encode(),
            )
            assert status == 200
            sid = sess["ID"]
            status, _, ok = await http_call(
                addr, "PUT", f"/v1/kv/locks/x?acquire={sid}", b"me")
            assert ok is True
            status, _, data = await http_call(addr, "GET", "/v1/kv/locks/x")
            assert data[0]["Session"] == sid
            status, _, ok = await http_call(
                addr, "PUT", f"/v1/kv/locks/x?release={sid}", b"")
            assert ok is True

    async def test_txn_endpoint(self):
        async with dev_stack() as (_, addr, _, _):
            ops = [
                {"KV": {"Verb": "set", "Key": "t/1",
                        "Value": base64.b64encode(b"v").decode()}},
                {"KV": {"Verb": "get", "Key": "t/1"}},
            ]
            status, _, out = await http_call(addr, "PUT", "/v1/txn",
                                             json.dumps(ops).encode())
            assert status == 200
            assert out["Errors"] == []
            assert len(out["Results"]) == 2

    async def test_unknown_route_and_method(self):
        async with dev_stack() as (_, addr, _, _):
            status, _, _ = await http_call(addr, "GET", "/v1/nope")
            assert status == 404
            status, hdrs, _ = await http_call(addr, "DELETE", "/v1/status/leader")
            assert status == 405 and "GET" in hdrs.get("allow", "")

    async def test_event_fire_and_list(self):
        async with dev_stack() as (agent, addr, _, _):
            status, _, out = await http_call(addr, "PUT", "/v1/event/fire/deploy",
                                             b"payload")
            assert status == 200 and out["Name"] == "deploy"

            async def listed():
                _, _, events = await http_call(
                    addr, "GET", "/v1/event/list?name=deploy"
                )
                return events

            await wait_until(
                lambda: listed(), msg="event propagated through serf loopback"
            )
            status, hdrs, events = await http_call(
                addr, "GET", "/v1/event/list?name=deploy"
            )
            assert status == 200 and events
            assert base64.b64decode(events[0]["Payload"]) == b"payload"
            idx = int(hdrs["x-consul-index"])
            assert idx >= 1

            # Long-poll: blocks until the next event fires.
            async def blocked():
                return await http_call(
                    addr, "GET", f"/v1/event/list?index={idx}&wait=5s"
                )

            task = asyncio.create_task(blocked())
            await asyncio.sleep(0.1)
            assert not task.done()
            await http_call(addr, "PUT", "/v1/event/fire/deploy2", b"x")
            status, hdrs2, events2 = await asyncio.wait_for(task, 5)
            assert int(hdrs2["x-consul-index"]) > idx
            assert any(e["Name"] == "deploy2" for e in events2)


class TestDNS:
    async def test_node_lookup(self):
        async with dev_stack() as (agent, addr, dns, dns_addr):
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps({"Node": "db-1",
                                        "Address": "10.9.9.9"}).encode())
            txid, rcode, answers = await dns_query(dns_addr, "db-1.node.consul")
            assert txid == 7 and rcode == 0
            assert answers[0].rtype == TYPE_A
            assert bytes(answers[0].rdata) == bytes([10, 9, 9, 9])

    async def test_service_lookup_filters_unhealthy(self):
        async with dev_stack() as (agent, addr, dns, dns_addr):
            reg = {
                "Node": "web-1", "Address": "10.0.0.1",
                "Service": {"Service": "web", "Port": 80},
                "Checks": [{"CheckID": "web-alive", "ServiceID": "web",
                            "Status": "passing"}],
            }
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps(reg).encode())
            bad = {
                "Node": "web-2", "Address": "10.0.0.2",
                "Service": {"Service": "web", "Port": 80},
                "Checks": [{"CheckID": "web-alive", "ServiceID": "web",
                            "Status": "critical"}],
            }
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps(bad).encode())

            _, rcode, answers = await dns_query(dns_addr, "web.service.consul")
            assert rcode == 0
            ips = {bytes(a.rdata) for a in answers if a.rtype == TYPE_A}
            assert bytes([10, 0, 0, 1]) in ips
            assert bytes([10, 0, 0, 2]) not in ips  # critical filtered

    async def test_srv_records(self):
        async with dev_stack() as (agent, addr, dns, dns_addr):
            reg = {
                "Node": "api-1", "Address": "10.1.0.1",
                "Service": {"Service": "api", "Port": 9090},
            }
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps(reg).encode())
            _, rcode, answers = await dns_query(dns_addr, "api.service.consul",
                                                TYPE_SRV)
            assert rcode == 0
            srv = next(a for a in answers if a.rtype == TYPE_SRV)
            import struct as _s

            prio, weight, port = _s.unpack(">HHH", srv.rdata[:6])
            assert port == 9090
            extra_a = [a for a in answers if a.rtype == TYPE_A]
            assert extra_a and extra_a[0].name.startswith("api-1.node")

    async def test_nxdomain(self):
        async with dev_stack() as (_, addr, _, dns_addr):
            _, rcode, answers = await dns_query(dns_addr, "ghost.service.consul")
            assert rcode == 3 and answers == []
            _, rcode, _ = await dns_query(dns_addr, "example.com")
            assert rcode == 3
            # Label-boundary: a different zone sharing the suffix string
            # is NOT ours.
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps({"Node": "x", "Address": "10.0.0.9",
                                        "Service": {"Service": "web"}}).encode())
            _, rcode, _ = await dns_query(dns_addr, "web.service.notconsul")
            assert rcode == 3
            _, rcode, _ = await dns_query(dns_addr, "anythingconsul")
            assert rcode == 3

    async def test_prepared_query_lookup(self):
        async with dev_stack() as (agent, addr, dns, dns_addr):
            reg = {
                "Node": "cache-1", "Address": "10.3.0.1",
                "Service": {"Service": "cache", "Port": 6379},
            }
            await http_call(addr, "PUT", "/v1/catalog/register",
                            json.dumps(reg).encode())
            status, _, out = await http_call(
                addr, "POST", "/v1/query",
                json.dumps({"Name": "cache-q",
                            "Service": {"Service": "cache"}}).encode(),
            )
            assert status == 200
            _, rcode, answers = await dns_query(dns_addr, "cache-q.query.consul")
            assert rcode == 0
            assert bytes(answers[0].rdata) == bytes([10, 3, 0, 1])


# ---------------------------------------------------------------------------
# PTR / recursors / EDNS0 (dns.go:199 handlePtr, :427 handleRecurse,
# setEDNS)
# ---------------------------------------------------------------------------


def _build_edns_query(txid, name, qtype, payload):
    """A query advertising an EDNS payload budget (OPT in additional)."""
    from consul_tpu.agent.dns import CLASS_IN, TYPE_OPT, _rd_name
    header = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 1)
    q = _rd_name(name) + struct.pack(">HH", qtype, CLASS_IN)
    opt = b"\x00" + struct.pack(">HHIH", TYPE_OPT, payload, 0, 0)
    return header + q + opt


async def _raw_dns(dns_addr, payload_bytes):
    host, port = dns_addr.rsplit(":", 1)
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(payload_bytes)

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=(host, int(port))
    )
    try:
        return await asyncio.wait_for(fut, 5)
    finally:
        transport.close()


class TestPtrRecursorsEdns:
    async def test_ptr_for_node_and_service_addresses(self):
        from consul_tpu.agent.dns import TYPE_PTR

        async with dev_stack() as (agent, addr, _dns, dns_addr):
            st, _, _x = await http_call(
                addr, "PUT", "/v1/catalog/register",
                json.dumps({"Node": "n1", "Address": "10.1.2.3",
                            "Service": {"Service": "web",
                                        "Address": "10.9.9.9",
                                        "Port": 80}}).encode())
            assert st == 200
            # Node address → <node>.node.consul
            _, rcode, answers = await dns_query(
                dns_addr, "3.2.1.10.in-addr.arpa", TYPE_PTR)
            assert rcode == 0 and answers
            assert answers[0].rtype == TYPE_PTR
            assert b"n1" in answers[0].rdata
            # Service address → <service>.service.consul
            _, rcode, answers = await dns_query(
                dns_addr, "9.9.9.10.in-addr.arpa", TYPE_PTR)
            assert rcode == 0 and answers
            assert b"web" in answers[0].rdata
            # Unknown address → NXDOMAIN (no recursors configured)
            _, rcode, answers = await dns_query(
                dns_addr, "1.0.0.127.in-addr.arpa", TYPE_PTR)
            assert rcode == 3 and not answers

    async def test_recursor_forwarding(self):
        """Non-.consul names forward to the configured recursor and the
        upstream's raw reply is relayed (dns.go handleRecurse)."""

        async with dev_stack() as (agent, addr, _dns, dns_addr):
            # A fake upstream resolver answering everything 1.2.3.4.
            from consul_tpu.agent.dns import (
                DNSQuestion, DNSRecord, TYPE_A, build_response,
                parse_query,
            )
            loop = asyncio.get_running_loop()

            class Upstream(asyncio.DatagramProtocol):
                def connection_made(self, transport):
                    self.transport = transport

                def datagram_received(self, data, src):
                    txid, questions = parse_query(data)
                    resp = build_response(
                        txid, questions,
                        [DNSRecord(questions[0].name, TYPE_A, 60,
                                   bytes([1, 2, 3, 4]))],
                        [], 0)
                    self.transport.sendto(resp, src)

            upstream, _ = await loop.create_datagram_endpoint(
                Upstream, local_addr=("127.0.0.1", 0))
            up_host, up_port = upstream.get_extra_info("sockname")[:2]
            try:
                agent.dns_recursors = [f"{up_host}:{up_port}"]
                _, rcode, answers = await dns_query(
                    dns_addr, "example.com")
                assert rcode == 0 and answers
                assert answers[0].rdata == bytes([1, 2, 3, 4])
                # Without recursors the same name is NXDOMAIN.
                agent.dns_recursors = []
                _, rcode, _x = await dns_query(dns_addr, "example.com")
                assert rcode == 3
            finally:
                upstream.close()

    async def test_edns_payload_lifts_truncation(self):
        """A 512-byte answer set truncates for plain clients but fits
        when the client advertises an EDNS budget (RFC 6891 payload
        negotiation replacing the fixed 512 B cap)."""

        from consul_tpu.agent.dns import TYPE_OPT, parse_response

        async with dev_stack() as (agent, addr, _dns, dns_addr):
            for i in range(30):
                st, _, _x = await http_call(
                    addr, "PUT", "/v1/catalog/register",
                    json.dumps({
                        "Node": f"bulk-{i}",
                        "Address": f"10.0.{i // 250}.{i % 250}",
                        "Service": {"Service": "bulk", "Port": 80},
                    }).encode())
                assert st == 200
            # Plain 512-byte query: TC bit set, partial answers.
            raw = await _raw_dns(
                dns_addr, build_query(7, "bulk.service.consul"))
            flags = struct.unpack(">H", raw[2:4])[0]
            assert flags & 0x0200, "expected TC for plain client"
            # EDNS query with a 4k budget: all answers, no TC, and
            # an OPT RR echoed in the additional section.
            raw = await _raw_dns(dns_addr, _build_edns_query(
                8, "bulk.service.consul", TYPE_A, 4096))
            flags = struct.unpack(">H", raw[2:4])[0]
            assert not (flags & 0x0200), "EDNS reply must not truncate"
            arcount = struct.unpack(">H", raw[10:12])[0]
            assert arcount == 1
            assert raw[-11:-9] == b"\x00" + bytes([TYPE_OPT >> 8])
            _, rcode, answers = parse_response(raw)
            assert rcode == 0 and len(answers) == 30


class TestAgentMonitor:
    async def test_monitor_streams_live_log_lines(self):
        """/v1/agent/monitor (agent_endpoint.go:1140): chunked stream of
        log lines at the requested level, fed by the consul_tpu logger
        tree (logging/monitor/monitor.go sink)."""
        import logging as _logging

        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write((
                "GET /v1/agent/monitor?loglevel=debug HTTP/1.1\r\n"
                f"Host: {host}\r\n\r\n").encode())
            await writer.drain()
            status_line = await reader.readline()
            assert b"200" in status_line
            hdrs = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                hdrs[k.strip().lower()] = v.strip()
            assert hdrs.get("transfer-encoding") == "chunked"

            async def read_chunk():
                size = int((await reader.readline()).strip() or b"0", 16)
                data = await reader.readexactly(size)
                await reader.readexactly(2)
                return data

            # Emit a log line INTO the tree and watch it stream out.
            _logging.getLogger("consul_tpu.test").warning("hello-monitor")
            got = b""
            while b"hello-monitor" not in got:
                got += await asyncio.wait_for(read_chunk(), 10)
            assert b"WARNING" in got and b"consul_tpu.test" in got

            # Level filtering: a debug record under loglevel=warn never
            # surfaces (checked via a second subscription).
            writer.close()
            reader2, writer2 = await asyncio.open_connection(
                host, int(port))
            writer2.write((
                "GET /v1/agent/monitor?loglevel=warn HTTP/1.1\r\n"
                f"Host: {host}\r\n\r\n").encode())
            await writer2.drain()
            while (await reader2.readline()) not in (b"\r\n", b""):
                pass

            async def read_chunk2():
                size = int((await reader2.readline()).strip() or b"0", 16)
                data = await reader2.readexactly(size)
                await reader2.readexactly(2)
                return data

            _logging.getLogger("consul_tpu.test").debug("too-quiet")
            _logging.getLogger("consul_tpu.test").error("loud-enough")
            got = b""
            while b"loud-enough" not in got:
                got += await asyncio.wait_for(read_chunk2(), 10)
            assert b"too-quiet" not in got
            writer2.close()

    async def test_monitor_bad_level_and_acl(self):
        async with dev_stack() as (_agent, addr, _dns, _dns_addr):
            st, _, err = await http_call(
                addr, "GET", "/v1/agent/monitor?loglevel=nope")
            assert st == 400, err
