"""Sweep x shard composition + closed-loop autotuning (ISSUE 13).

The composed exactness ladder, weakest precondition first:

  * U=1 x D=1 — the composed program (vmap over the shard_map inner
    study) reproduces the unsharded sweep AND the plain scan
    bit-for-bit, per sharded-twin family.  Everything both planes pin
    transfers to the composed plane through this.
  * D=2 == D=1 with outbox overflow 0 — sharding the inner study under
    the universe batch changes placement, nothing else.
  * ring == alltoall at a composed config — the exchange backend stays
    a pure transport knob under vmap (the Pallas kernel batches).
  * one program per (entrypoint, U, D, exchange) — the composition
    axes are positional-static; knob values and seeds never retrace.

Optimizer (consul_tpu/sweep/optimize.py): driven against brute-force
grid references through the ``evaluate`` injection seam — argmin
within one grid cell, knee within one grid cell at <= half the grid's
evaluations, NaN objectives never win — plus the real streamload
knee end-to-end.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax

from consul_tpu.models.broadcast import BroadcastConfig, broadcast_init
from consul_tpu.models.membership import MembershipConfig, membership_init
from consul_tpu.models.membership_sparse import (
    SparseMembershipConfig,
    sparse_membership_init,
)
from consul_tpu.models.swim import SwimConfig
from consul_tpu.geo import GeoConfig, geo_init
from consul_tpu.parallel.mesh import mesh_for
from consul_tpu.sim.engine import (
    broadcast_scan,
    geo_scan,
    membership_scan,
    run_sweep,
    sparse_membership_scan,
    streamcast_scan,
)
from consul_tpu.streamcast import StreamcastConfig, streamcast_init
from consul_tpu.sweep import Universe
from consul_tpu.sweep.optimize import knob_space, optimize_sweep
from consul_tpu.sweep.universe import make_sweep, stacked_init

# One config per sharded-twin family (mirrors test_sweep._SMALL shapes;
# sparse keeps K < n — the sharded plane's requirement).
_FAMS = {
    "broadcast": (BroadcastConfig(n=64, fanout=3, loss=0.05),
                  lambda c: broadcast_init(c, origin=0),
                  broadcast_scan, 10, None),
    "membership": (MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),)),
                   membership_init, membership_scan, 8, (3,)),
    "sparse": (SparseMembershipConfig(
        base=MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),)),
        k_slots=8), sparse_membership_init,
        sparse_membership_scan, 8, (3,)),
    "streamcast": (StreamcastConfig(n=64, events=10, chunks=2,
                                    window=3, fanout=3, chunk_budget=2,
                                    rate=0.4, names=3, loss=0.05,
                                    delivery="edges"),
                   streamcast_init, streamcast_scan, 10, None),
    "geo": (GeoConfig(n=64, segments=8, bridges_per_segment=2,
                      events=4, wan_window=4, wan_msg_bytes=100,
                      wan_capacity_bytes=800.0, wan_queue_bytes=1600.0,
                      ae_batch=4, loss_wan=0.05),
            geo_init, geo_scan, 8, None),
}


def _np_tree(x):
    return jax.tree_util.tree_map(np.asarray, x)


def _trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(la, lb))


@functools.lru_cache(maxsize=None)
def _plain(model):
    cfg, init, scan, steps, track = _FAMS[model]
    args = (init(cfg), jax.random.PRNGKey(5), cfg, steps)
    if track is not None:
        args = args + (tuple(track),)
    final, outs = scan(*args)
    return _np_tree(final), _np_tree(outs)


def _uni(model, seeds):
    cfg, _i, _s, steps, track = _FAMS[model]
    return Universe(entrypoint=model, cfg=cfg, steps=steps,
                    seeds=seeds, track=tuple(track) if track else ())


@functools.lru_cache(maxsize=None)
def _sweep_run(model, U, d, exchange="alltoall"):
    """One composed (d >= 1) or unsharded (d == 0) sweep run; cached so
    the module pays one compile per distinct program."""
    uni = _uni(model, tuple(5 + 2 * u for u in range(U)))
    mesh = mesh_for(d) if d else None
    sweep = make_sweep(model, U, False, mesh, exchange)
    out = sweep(stacked_init(uni), uni.keys(), (), uni.cfg, uni.steps,
                (), uni.track)
    if d:
        final, outs, ov = out
        return _np_tree(final), _np_tree(outs), np.asarray(ov)
    final, outs = out
    return _np_tree(final), _np_tree(outs), None


class TestComposedU1D1Ladder:
    """The acceptance pin: U=1 x D=1 composed == unsharded sweep ==
    plain scan, for every registered sharded-twin family."""

    @pytest.mark.parametrize("model", sorted(_FAMS))
    def test_u1_d1_bit_equal(self, model):
        pf, po = _plain(model)
        uf, uo, _ = _sweep_run(model, 1, 0)
        cf, co, ov = _sweep_run(model, 1, 1)
        # composed == unsharded sweep: full final state + outs.
        assert _trees_equal(uf, cf), f"{model}: final state (D1)"
        assert _trees_equal(uo, co), f"{model}: outs (D1)"
        assert int(ov.sum()) == 0
        # unsharded sweep u=0 == plain scan (the U=1 leg of the pin).
        assert _trees_equal(
            po, jax.tree_util.tree_map(lambda x: x[0], uo)
        ), f"{model}: sweep vs plain outs"
        assert _trees_equal(
            pf, jax.tree_util.tree_map(lambda x: x[0], uf)
        ), f"{model}: sweep vs plain final"

    def test_composed_run_sweep_reports_overflow(self):
        uni = _uni("broadcast", (5,))
        rep = run_sweep(uni, warmup=False, mesh=mesh_for(1))
        assert rep.outbox_overflow is not None
        assert rep.devices == 1
        assert int(np.asarray(rep.outbox_overflow).sum()) == 0
        assert rep.summary()["overflow_total"] == 0


class TestComposedD2:
    """D=2 == D=1 with outbox overflow 0 (placement-only), at U=2 —
    both parallelism axes live at once."""

    # One family tier-1 (the exact-scatter representative, cheap
    # compiles); the other four ride the slow tier with the same
    # ladder (tier-1 wall-clock budget policy — the sparse composed
    # programs alone cost ~40s of compile).
    @pytest.mark.parametrize("model", ["broadcast"])
    def test_d2_equals_d1_overflow_zero(self, model):
        f1, o1, ov1 = _sweep_run(model, 2, 1)
        f2, o2, ov2 = _sweep_run(model, 2, 2)
        assert int(ov2.sum()) == 0, f"{model}: D2 outbox overflow"
        assert _trees_equal(o1, o2), f"{model}: outs D2 vs D1"
        assert _trees_equal(f1, f2), f"{model}: final D2 vs D1"

    @pytest.mark.slow
    @pytest.mark.parametrize("model", ["sparse", "membership",
                                       "streamcast", "geo"])
    def test_d2_equals_d1_overflow_zero_slow(self, model):
        f1, o1, ov1 = _sweep_run(model, 2, 1)
        f2, o2, ov2 = _sweep_run(model, 2, 2)
        assert int(ov2.sum()) == 0
        assert _trees_equal(o1, o2)
        assert _trees_equal(f1, f2)


class TestComposedTelemetry:
    @pytest.mark.slow
    def test_composed_telemetry_trace_matches_unsharded(self):
        # telemetry=True composed: the [U, steps, M] trace assembles
        # through the sharded psum seam under vmap — bit-equal to the
        # unsharded sweep's trace at D=1 (the obs parity pins compose).
        uni = _uni("broadcast", (5,))
        mesh = mesh_for(1)
        su = make_sweep("broadcast", 1, True)
        sc = make_sweep("broadcast", 1, True, mesh)
        _, ou = su(stacked_init(uni), uni.keys(), (), uni.cfg,
                   uni.steps, (), uni.track)
        _, oc, ov = sc(stacked_init(uni), uni.keys(), (), uni.cfg,
                       uni.steps, (), uni.track)
        assert _trees_equal(_np_tree(ou), _np_tree(oc))
        assert int(np.asarray(ov).sum()) == 0


class TestRingBackend:
    def test_ring_equals_alltoall_composed(self):
        fa, oa, ova = _sweep_run("broadcast", 2, 2)
        fr, orr, ovr = _sweep_run("broadcast", 2, 2, "ring")
        assert _trees_equal(oa, orr)
        assert _trees_equal(fa, fr)
        assert int(ovr.sum()) == 0


class TestComposedRetraceDiscipline:
    def test_one_program_per_u_d_exchange(self):
        from consul_tpu.analysis.guards import TraceGuard

        mesh = mesh_for(2)
        cfg = _FAMS["broadcast"][0]
        sweep = make_sweep("broadcast", 3, False, mesh, "alltoall")
        assert make_sweep("broadcast", 3, False, mesh,
                          "alltoall") is sweep
        guard = TraceGuard(sweep, max_traces=1,
                           name="sweep_broadcast_U3_D2")
        for seeds, losses in [((0, 1, 2), (0.0, 0.1, 0.2)),
                              ((3, 4, 5), (0.3, 0.4, 0.05))]:
            uni = Universe(entrypoint="broadcast", cfg=cfg, steps=4,
                           seeds=seeds, knobs=("loss",),
                           values=(losses,))
            run_sweep(uni, warmup=False, mesh=mesh)
        guard.check()
        assert guard.traces == 1

    def test_axis_points_are_distinct_programs(self):
        mesh1, mesh2 = mesh_for(1), mesh_for(2)
        base = make_sweep("broadcast", 2)
        assert make_sweep("broadcast", 2, False, mesh1) is not base
        assert make_sweep("broadcast", 2, False, mesh2) is not (
            make_sweep("broadcast", 2, False, mesh1)
        )
        assert make_sweep("broadcast", 2, False, mesh2, "ring") is not (
            make_sweep("broadcast", 2, False, mesh2, "alltoall")
        )

    def test_no_sharded_twin_rejected_loudly(self):
        with pytest.raises(ValueError, match="no sharded twin"):
            make_sweep("swim", 2, False, mesh_for(1))
        with pytest.raises(ValueError, match="no sharded twin"):
            make_sweep("lifeguard", 2, False, mesh_for(1))

    def test_exchange_without_mesh_rejected(self):
        with pytest.raises(ValueError, match="requires mesh="):
            make_sweep("broadcast", 2, False, None, "ring")

    def test_unknown_exchange_rejected(self):
        with pytest.raises(ValueError, match="unknown exchange"):
            make_sweep("broadcast", 2, False, mesh_for(1), "carrier")


# ---------------------------------------------------------------------------
# Composed registry footprint: J6 pin — composed ~ U x per-shard study
# + replicated knobs (the max-U-per-chip table's scaling assumption).
# ---------------------------------------------------------------------------


class TestComposedFootprint:
    def test_composed_footprint_scales_linearly_in_u(self):
        from consul_tpu.analysis.jaxlint import estimate_peak
        from consul_tpu.sweep.universe import abstract_sweep_program

        cfg = _FAMS["sparse"][0]
        mesh = mesh_for(2)
        peaks = {}
        for u in (1, 4, 8):
            fn, args = abstract_sweep_program(
                "sparse", cfg, 4, u, ("base.loss",), (3,), False, mesh
            )
            peaks[u] = estimate_peak(jax.make_jaxpr(fn)(*args)).chip_bytes
        per_u_tail = (peaks[8] - peaks[4]) / 4.0
        per_u_head = (peaks[4] - peaks[1]) / 3.0
        assert per_u_tail > 0 and per_u_head > 0
        # ~linear in U: the two marginal estimates agree within 25%
        # (the fixed part — replicated knob/key planes — cancels).
        assert abs(per_u_tail - per_u_head) <= 0.25 * per_u_head, peaks
        # And the composed U8 program really holds ~8 studies' state.
        assert peaks[8] >= peaks[1] + 6 * per_u_head


# ---------------------------------------------------------------------------
# Optimizer: brute-force references via the evaluate injection seam.
# ---------------------------------------------------------------------------


def _grid_universe(grid):
    return Universe(
        entrypoint="swim", cfg=SwimConfig(n=64, subject=1), steps=4,
        seeds=(0,) * len(grid), knobs=("loss",), values=(tuple(grid),),
    )


class TestOptimizer:
    GRID = tuple(np.round(np.linspace(0.0, 0.6, 16), 4))

    def test_min_mode_matches_grid_argmin_within_one_cell(self):
        uni = _grid_universe(self.GRID)
        for target in (0.07, 0.37, 0.55):
            calls = []

            def ev(rows, target=target):
                x = np.asarray(rows[0], float)
                calls.append(x)
                return (x - target) ** 2

            res = optimize_sweep(uni, "first_suspect_ms",
                                 minimize=True, evaluate=ev)
            gx = np.asarray(self.GRID)
            gbest = float(gx[np.argmin((gx - target) ** 2)])
            cell = float(gx[1] - gx[0])
            assert abs(res.best["loss"] - gbest) <= cell + 1e-9
            # Constant-U generations: the program-reuse contract.
            assert all(len(c) == len(calls[0]) for c in calls)

    def test_max_mode(self):
        uni = _grid_universe(self.GRID)
        res = optimize_sweep(
            uni, "first_suspect_ms",
            evaluate=lambda rows: -np.abs(
                np.asarray(rows[0], float) - 0.22),
        )
        assert abs(res.best["loss"] - 0.22) <= 0.04 + 1e-9

    def test_knee_within_cell_at_half_grid_cost(self):
        uni = _grid_universe(self.GRID)
        gx = np.asarray(self.GRID)
        cell = float(gx[1] - gx[0])
        for knee_x in (0.11, 0.4133, 0.52):
            def ev(rows, knee_x=knee_x):
                x = np.asarray(rows[0], float)
                return np.where(x <= knee_x, 0.0, (x - knee_x) * 100)

            res = optimize_sweep(uni, "first_suspect_ms", knee_at=0.0,
                                 evaluate=ev)
            grid_knee = float(gx[np.flatnonzero(
                ev((gx,)) <= 0)[-1]])
            assert abs(res.best["loss"] - grid_knee) <= cell + 1e-9, (
                knee_x, res.best
            )
            assert res.evaluations <= res.grid_evaluations // 2, (
                knee_x, res.evaluations, res.grid_evaluations
            )

    def test_nan_objective_never_wins(self):
        uni = _grid_universe(self.GRID)

        def ev(rows):
            x = np.asarray(rows[0], float)
            out = (x - 0.05) ** 2   # best region is NaN-poisoned
            out[x < 0.3] = np.nan
            return out

        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             evaluate=ev)
        assert res.best["loss"] >= 0.3

    def test_multi_knob_min(self):
        cfg = SwimConfig(n=64, subject=1, delivery="aggregate")
        lg = [(ls, sc) for ls in (0.0, 0.2, 0.4, 0.6)
              for sc in (0.2, 0.6, 1.0, 1.4)]
        uni = Universe(
            entrypoint="swim", cfg=cfg, steps=4, seeds=(0,) * len(lg),
            knobs=("loss", "suspicion_scale"),
            values=(tuple(v[0] for v in lg), tuple(v[1] for v in lg)),
        )

        def ev(rows):
            x = np.asarray(rows[0], float)
            y = np.asarray(rows[1], float)
            return (x - 0.4) ** 2 + (y - 0.6) ** 2

        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             evaluate=ev)
        assert abs(res.best["loss"] - 0.4) <= 0.2 + 1e-9
        assert abs(res.best["suspicion_scale"] - 0.6) <= 0.4 + 1e-9

    def test_bimodal_endpoints_terminate_without_stalling(self):
        # Survivors at opposite lattice ends leave the clamped box
        # unchanged; the driver must detect the identical next lattice
        # and stop instead of re-paying U evaluations per generation
        # until max_generations.
        uni = _grid_universe(self.GRID)
        calls = []

        def ev(rows):
            x = np.asarray(rows[0], float)
            calls.append(x)
            return -np.abs(x - 0.3)   # best points ARE the endpoints

        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             evaluate=ev)
        assert res.generations < 12, res.generations
        assert res.best["loss"] in (0.0, 0.6)
        # No two generations evaluated the identical lattice.
        as_tuples = [tuple(c) for c in calls]
        assert len(set(as_tuples)) == len(as_tuples)

    def test_minimize_and_knee_at_rejected(self):
        uni = _grid_universe(self.GRID)

        def boom(rows):
            raise AssertionError("evaluator must not run")

        with pytest.raises(ValueError, match="contradictory"):
            optimize_sweep(uni, "first_suspect_ms", minimize=True,
                           knee_at=0.0, evaluate=boom)

    def test_points_per_gen_is_a_ceiling_on_multi_knob_lattices(self):
        # points_per_gen sizes the batched program (the composed
        # max-U-per-chip bound) — the lattice must never exceed it.
        cfg = SwimConfig(n=64, subject=1, delivery="aggregate")
        lg = [(ls, sc) for ls in (0.0, 0.2, 0.4, 0.6)
              for sc in (0.2, 0.6, 1.0, 1.4)]
        uni = Universe(
            entrypoint="swim", cfg=cfg, steps=4, seeds=(0,) * len(lg),
            knobs=("loss", "suspicion_scale"),
            values=(tuple(v[0] for v in lg), tuple(v[1] for v in lg)),
        )
        calls = []

        def ev(rows):
            x = np.asarray(rows[0], float)
            calls.append(x)
            return (x - 0.4) ** 2

        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             points_per_gen=5, evaluate=ev)
        assert res.points_per_gen == 4     # largest g**2 <= 5
        assert all(len(c) <= 5 for c in calls)
        # And too small to lattice at all rejects loudly.
        with pytest.raises(ValueError, match="2\\*\\*2"):
            optimize_sweep(uni, "first_suspect_ms", minimize=True,
                           points_per_gen=3, evaluate=ev)

    def test_knee_integer_axis_lays_distinct_interior_points(self):
        from consul_tpu.models import LifeguardConfig

        ladder = tuple(float(v) for v in range(2, 31))
        uni = Universe(
            entrypoint="lifeguard",
            cfg=LifeguardConfig(n=64, subject=1, delivery="aggregate"),
            steps=4, seeds=(0,) * len(ladder),
            knobs=("profile.gossip_nodes",), values=(ladder,),
        )
        calls = []

        def ev(rows):
            x = np.asarray(rows[0], float)
            calls.append(x)
            assert np.array_equal(x, np.round(x))   # int axis stays int
            return np.where(x <= 9, 0.0, 100.0)

        res = optimize_sweep(uni, "detect_t90_ms", knee_at=0.0,
                             evaluate=ev)
        assert res.best["profile.gossip_nodes"] == 9.0
        # Refinement generations lay strictly-interior integers (the
        # measured bracket endpoints are never re-paid), distinct
        # while the bracket holds >= U interior integers — naive
        # rounding collided them onto each other and the endpoints.
        first_refine = calls[1]
        assert len(set(first_refine)) == len(first_refine)
        assert 2.0 not in first_refine and 30.0 not in first_refine

    def test_nonpositive_points_per_gen_rejected(self):
        uni = _grid_universe(self.GRID)

        def boom(rows):
            raise AssertionError("evaluator must not run")

        with pytest.raises(ValueError, match="points_per_gen"):
            optimize_sweep(uni, "first_suspect_ms", minimize=True,
                           points_per_gen=0, evaluate=boom)

    def test_split_from_universes_rejected(self):
        # split_from folds a distinct key per universe slot, so the
        # same knob value would measure differently across lattice
        # slots — the grid semantics the bracket logic relies on.
        uni = Universe(
            entrypoint="swim", cfg=SwimConfig(n=64, subject=1),
            steps=4, split_from=3, universes=len(self.GRID),
            knobs=("loss",), values=(tuple(self.GRID),),
        )

        def boom(rows):
            raise AssertionError("evaluator must not run")

        with pytest.raises(ValueError, match="split_from"):
            optimize_sweep(uni, "first_suspect_ms", minimize=True,
                           evaluate=boom)

    def test_grid_cost_is_the_presets_own_universe_count(self):
        # Diagonal (jointly-laddered) 2-knob preset: the fixed grid
        # cli sweep burns is its 3 universes, NOT the 3x3 per-axis
        # product and NOT a span/cell reconstruction.
        uni = Universe(
            entrypoint="swim", cfg=SwimConfig(n=64, subject=1),
            steps=4, seeds=(0,) * 3,
            knobs=("loss", "suspicion_scale"),
            values=((0.0, 0.2, 0.4), (0.5, 1.0, 1.5)),
        )
        res = optimize_sweep(
            uni, "first_suspect_ms", minimize=True,
            evaluate=lambda rows: np.asarray(rows[0], float),
        )
        assert res.grid_evaluations == 3

    def test_overflow_total_surfaces_in_summary(self):
        uni = _grid_universe(self.GRID)
        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             evaluate=lambda rows:
                             np.asarray(rows[0], float))
        # Injected evaluator: no outbox exists, key stays absent.
        assert res.overflow_total is None
        assert "overflow_total" not in res.summary()
        # Composed runs sum it across generations (loud contract).
        noisy = dataclasses.replace(res, overflow_total=7)
        assert noisy.summary()["overflow_total"] == 7

    def test_unknown_objective_rejected_before_any_program(self):
        uni = _grid_universe(self.GRID)

        def boom(rows):
            raise AssertionError("evaluator must not run")

        with pytest.raises(ValueError, match="unknown objective"):
            optimize_sweep(uni, "detect_t90_mss", evaluate=boom)

    def test_knee_needs_one_varying_knob(self):
        cfg = SwimConfig(n=64, subject=1)
        uni = Universe(
            entrypoint="swim", cfg=cfg, steps=4, seeds=(0,) * 4,
            knobs=("loss", "suspicion_scale"),
            values=((0.0, 0.1, 0.0, 0.1), (0.5, 0.5, 1.0, 1.0)),
        )
        with pytest.raises(ValueError, match="ONE knob axis"):
            optimize_sweep(uni, "first_suspect_ms", knee_at=0.0,
                           evaluate=lambda rows: np.zeros(4))

    def test_nothing_to_optimize_rejected(self):
        uni = Universe(
            entrypoint="swim", cfg=SwimConfig(n=64, subject=1),
            steps=4, seeds=(0, 1), knobs=("loss",),
            values=((0.1, 0.1),),
        )
        with pytest.raises(ValueError, match="nothing to optimize"):
            optimize_sweep(uni, "first_suspect_ms",
                           evaluate=lambda rows: np.zeros(2))

    def test_knob_space_reads_the_ladder(self):
        uni = _grid_universe(self.GRID)
        varying, fixed, bounds, cell = knob_space(uni)
        assert varying == ("loss",)
        assert bounds["loss"] == (0.0, 0.6)
        assert cell["loss"] == pytest.approx(0.04)

    def test_fixed_knobs_ride_along_pinned(self):
        cfg = SwimConfig(n=64, subject=1, delivery="aggregate")
        uni = Universe(
            entrypoint="swim", cfg=cfg, steps=4, seeds=(0,) * 4,
            knobs=("loss", "suspicion_scale"),
            values=((0.0, 0.2, 0.4, 0.6), (0.7, 0.7, 0.7, 0.7)),
        )
        seen = {}

        def ev(rows):
            seen["scale"] = tuple(rows[1])
            return np.asarray(rows[0], float)

        res = optimize_sweep(uni, "first_suspect_ms", minimize=True,
                             evaluate=ev)
        assert set(seen["scale"]) == {0.7}
        assert res.fixed == {"suspicion_scale": 0.7}


class TestOptimizerEndToEnd:
    """The real closed loop: bisection over a fine streamload ladder
    lands on the fixed grid's knee at a fraction of its cost (the
    acceptance claim, at test-scale n)."""

    @pytest.mark.slow
    def test_streamload_knee_vs_fixed_grid(self):
        from consul_tpu.sweep.presets import stream_load_curve

        rates = tuple(round(0.02 + 0.03 * i, 4) for i in range(16))
        uni = stream_load_curve(n=512, rates=rates, steps=100)
        grid_rep = run_sweep(uni, warmup=False)
        ov = np.asarray(grid_rep.metrics["window_overflow"])
        passing = np.flatnonzero(ov <= 0)
        assert passing.size, "ladder floor already overflows"
        assert (ov > 0).any(), "ladder never overflows — no knee"
        grid_knee = float(rates[passing[-1]])
        res = optimize_sweep(uni, "window_overflow", knee_at=0.0)
        cell = res.cell["rate"]
        assert abs(res.best["rate"] - grid_knee) <= cell + 1e-9, (
            res.best, grid_knee
        )
        assert res.evaluations <= res.grid_evaluations // 2

    def test_cli_optimize_contract(self, capsys, monkeypatch):
        import json as _json

        from consul_tpu import cli
        from consul_tpu.sweep import optimize as opt_mod

        # Typo objective dies before any program (ValueError path).
        rc = cli.main(["sweep", "streamload", "--optimize",
                       "--objective", "window_overfloww"])
        assert rc == 1
        assert "unknown objective" in capsys.readouterr().err

        # Optimizer-only flags without --optimize reject loudly
        # instead of silently burning the full fixed grid.
        rc = cli.main(["sweep", "streamload",
                       "--objective", "window_overflow",
                       "--knee-at", "0"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "require(s) --optimize" in err
        assert "--objective" in err and "--knee-at" in err

        # Missing objective names the registry.
        rc = cli.main(["sweep", "streamload", "--optimize"])
        assert rc == 1
        assert "requires --objective" in capsys.readouterr().err

        # Happy path with a stubbed driver: summary JSON round-trips.
        def fake(uni, objective, **kw):
            return opt_mod.OptimizeResult(
                entrypoint=uni.entrypoint, objective=objective,
                mode="knee", knee_at=0.0, knobs=("rate",), fixed={},
                best={"rate": 0.3, "objective": 0.0},
                bracket={"rate": [0.3, 0.32]}, cell={"rate": 0.02},
                evaluations=8, generations=2, grid_evaluations=16,
                points_per_gen=4, history=[],
            )

        monkeypatch.setattr(opt_mod, "optimize_sweep", fake)
        rc = cli.main(["sweep", "streamload", "--optimize",
                       "--objective", "window_overflow",
                       "--knee-at", "0"])
        assert rc == 0
        out = _json.loads(capsys.readouterr().out)
        assert out["best"]["rate"] == 0.3
        assert out["evaluations_saved_vs_grid"] == 8

    def test_cli_devices_rejects_unsharded_entrypoint(self, capsys):
        from consul_tpu import cli

        # seeds4k is a swim preset — no sharded twin, loud pre-run.
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--devices", "2"])
        assert rc == 1
        assert "no sharded twin" in capsys.readouterr().err

    def test_cli_exchange_requires_devices(self, capsys):
        from consul_tpu import cli

        rc = cli.main(["sweep", "streamload", "--exchange", "ring"])
        assert rc == 1
        assert "requires --devices" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# amortize= escape hatch (ops/sortmerge dispatch pin at model level).
# ---------------------------------------------------------------------------


class TestAmortizeEscapeHatch:
    @pytest.mark.slow
    def test_amortize_false_is_bit_equal(self):
        # Slow tier: the model-level twin of the tier-1 ops pin
        # (test_sortmerge.TestPrioritizedAdmission.test_amortize_
        # false_pins_slow_branch_bit_equal) — a fresh sparse compile.
        cfg = _FAMS["sparse"][0]
        key = jax.random.PRNGKey(5)
        f1, o1 = sparse_membership_scan(
            sparse_membership_init(cfg), key, cfg, 8, (3,))
        cfg2 = dataclasses.replace(cfg, amortize=False)
        f2, o2 = sparse_membership_scan(
            sparse_membership_init(cfg2), key, cfg2, 8, (3,))
        assert _trees_equal(_np_tree(o1), _np_tree(o2))
        assert _trees_equal(_np_tree(f1), _np_tree(f2))

    @staticmethod
    def _count_conds(jaxpr):
        n = [0]

        def walk(j):
            for e in j.eqns:
                if e.primitive.name == "cond":
                    n[0] += 1
                for v in e.params.values():
                    for cj in (v if isinstance(v, (list, tuple))
                               else (v,)):
                        if hasattr(cj, "jaxpr"):
                            walk(cj.jaxpr)
        walk(jaxpr)
        return n[0]

    def test_amortize_auto_pins_slow_branch_for_sweeps(self):
        # amortize=None (the default) is AUTO: make_sweep resolves it
        # to False for the vmapped sparse plane — the measured-1.5x
        # both-branches escape hatch applied by default — while an
        # explicit amortize=True stays honored.  Under vmap the
        # dispatch cond lowers to select with BOTH branches inlined
        # (the tax itself), so the pin is program identity: the auto
        # program IS the explicit-False program, and the explicit-True
        # program carries the extra dead-branch equations.  Abstract
        # traces only.
        from consul_tpu.analysis.jaxlint import eqn_count
        from consul_tpu.sweep.universe import abstract_sweep_program

        def sweep_jaxpr(cfg):
            fn, args = abstract_sweep_program("sparse", cfg, 2, 1, (),
                                              (3,))
            return jax.make_jaxpr(fn)(*args)

        auto = _FAMS["sparse"][0]
        assert auto.amortize is None
        j_auto = sweep_jaxpr(auto)
        j_false = sweep_jaxpr(dataclasses.replace(auto, amortize=False))
        j_true = sweep_jaxpr(dataclasses.replace(auto, amortize=True))
        assert str(j_auto) == str(j_false)
        assert eqn_count(j_true) > eqn_count(j_auto)

    def test_amortize_auto_keeps_plain_scans_amortized(self):
        # The plain-scan side of the auto: None resolves to the
        # amortized dispatch (cond present), explicit values win.
        from consul_tpu.models.membership_sparse import resolve_amortize
        from consul_tpu.sim import engine

        auto = _FAMS["sparse"][0]
        assert resolve_amortize(auto) is True
        assert resolve_amortize(
            dataclasses.replace(auto, amortize=False)) is False
        assert resolve_amortize(auto, vmapped=True) is False
        assert resolve_amortize(
            dataclasses.replace(auto, amortize=True), vmapped=True
        ) is True
        state = jax.eval_shape(lambda: sparse_membership_init(auto))
        jaxpr = jax.make_jaxpr(
            lambda s, k: engine._sparse_membership_scan(
                s, k, auto, 2, (3,))
        )(state, jax.random.PRNGKey(0))
        assert self._count_conds(jaxpr.jaxpr) > 0

    def test_amortize_is_shape_denied_for_sweeps(self):
        with pytest.raises(ValueError,
                           match="shapes or trace-time structure"):
            Universe(entrypoint="sparse", cfg=_FAMS["sparse"][0],
                     steps=4, seeds=(0,), knobs=("amortize",),
                     values=((0,),))

    @pytest.mark.slow
    def test_amortize_false_reaches_the_chunked_driver(self):
        # The >=2M-row regime routes delivery through _deliver_chunked;
        # amortize=False must pin the slow branch THERE too (abstract
        # trace only — count the dispatch conds, zero device memory).
        from consul_tpu.models.membership import LAN, MembershipConfig
        from consul_tpu.models.membership_sparse import (
            _CHUNK_A, arrival_count)
        from consul_tpu.sim import engine

        def conds(amortize):
            cfg = SparseMembershipConfig(
                base=MembershipConfig(n=3_000_000, loss=0.01,
                                      profile=LAN, fail_at=((42, 5),)),
                k_slots=64, amortize=amortize)
            assert arrival_count(cfg) > _CHUNK_A
            state = jax.eval_shape(
                lambda: sparse_membership_init(cfg))
            jaxpr = jax.make_jaxpr(
                lambda s, k: engine._sparse_membership_scan(
                    s, k, cfg, 2, (42,))
            )(state, jax.random.PRNGKey(0))
            n = [0]

            def walk(j):
                for e in j.eqns:
                    if e.primitive.name == "cond":
                        n[0] += 1
                    for v in e.params.values():
                        for cj in (v if isinstance(v, (list, tuple))
                                   else (v,)):
                            if hasattr(cj, "jaxpr"):
                                walk(cj.jaxpr)
            walk(jaxpr.jaxpr)
            return n[0]

        assert conds(False) == 0 < conds(True)
