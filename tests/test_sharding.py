"""Sharding tests on the virtual 8-device CPU mesh.

The simulator must be *reproducible across shardings* (SURVEY.md §7 hard
part (e)): a study sharded over 8 devices must produce bit-identical
convergence curves to the single-device run, because all randomness is a
pure function of (round, node) PRNG streams, never of data placement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.models import (
    BroadcastConfig,
    SwimConfig,
    broadcast_init,
    swim_init,
)
from consul_tpu.parallel import make_mesh, node_sharding, shard_state
from consul_tpu.sim import run_broadcast, run_swim
from consul_tpu.sim.engine import broadcast_scan


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_state_sharding_places_node_axis():
    mesh = make_mesh()
    cfg = BroadcastConfig(n=1024)
    st = shard_state(broadcast_init(cfg), mesh)
    assert st.knows.sharding == node_sharding(mesh)
    # Scalars stay replicated.
    assert st.tick.sharding.is_fully_replicated


def test_broadcast_sharded_matches_unsharded():
    cfg = BroadcastConfig(n=2048, fanout=3, loss=0.2)
    r1 = run_broadcast(cfg, steps=25, seed=3, sharded=False, warmup=False)
    r2 = run_broadcast(cfg, steps=25, seed=3, sharded=True, warmup=False)
    assert np.array_equal(r1.infected, r2.infected)


def test_swim_sharded_matches_unsharded():
    cfg = SwimConfig(n=2048, subject=5, loss=0.1)
    r1 = run_swim(cfg, steps=60, seed=4, sharded=False, warmup=False)
    r2 = run_swim(cfg, steps=60, seed=4, sharded=True, warmup=False)
    assert np.array_equal(r1.dead_known, r2.dead_known)
    assert np.array_equal(r1.suspecting, r2.suspecting)


def test_scan_preserves_sharding():
    mesh = make_mesh()
    cfg = BroadcastConfig(n=1024)
    st = shard_state(broadcast_init(cfg), mesh)
    final, infected = broadcast_scan(st, jax.random.PRNGKey(0), cfg, 5)
    jnp.asarray(infected)
    # The carry must not silently gather to one device.
    assert not final.knows.sharding.is_fully_replicated


@pytest.mark.slow
def test_graft_dryrun_smoke():
    # Slow tier (tier-1 budget policy, PR 13): the dryrun is the
    # driver's own entrypoint and every subsystem it touches keeps a
    # direct tier-1 twin (scan pins, sharded D-pins, check gates) —
    # this end-to-end rerun is the single largest tier-1 test at ~55s.
    import __graft_entry__ as g

    g.dryrun_multichip(8)


@pytest.mark.slow
def test_membership_sharded_matches_unsharded():
    # Slow tier (tier-1 budget policy, PR 13): the legacy GSPMD
    # placement path — the explicit multi-chip plane's D-pins
    # (tests/test_shard.py) carry the sharded-equality story in
    # tier-1; this 40-step n=256 dense pair costs ~14s of compile.
    from consul_tpu.models import MembershipConfig
    from consul_tpu.sim import run_membership

    cfg = MembershipConfig(n=256, loss=0.1, fail_at=((3, 5), (100, 5)))
    r1 = run_membership(cfg, steps=40, seed=9, track=(3, 100),
                        sharded=False, warmup=False)
    r2 = run_membership(cfg, steps=40, seed=9, track=(3, 100),
                        sharded=True, warmup=False)
    assert np.array_equal(r1.dead_known, r2.dead_known)
    assert np.array_equal(r1.suspecting, r2.suspecting)
    assert np.array_equal(r1.suspect_cells, r2.suspect_cells)
