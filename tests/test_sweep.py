"""Universe sweeps (consul_tpu/sweep): the vmapped protocol family.

The ladder of guarantees, weakest precondition first:

  * U=1 BIT-EQUALITY — the batched program at U=1 reproduces every
    unbatched entrypoint bit-for-bit, per model.  Everything the
    unbatched suite pins transfers to the sweep plane through this.
  * one program per (entrypoint, U) — knob VALUES and seeds never
    retrace (the config-stacking footgun is rejected at construction,
    not discovered as a retrace storm).
  * distribution — a 64-seed sweep reproduces the SWIM-paper
    first-detection mean within the band test_swim_paper pins, from
    ONE compiled program.
  * frontier — Pareto extraction matches a brute-force numpy
    reference, and the knob-grid preset yields a non-degenerate
    robustness/latency frontier.
  * coverage — every severity rung of the fault-matrix preset
    actually changes the dynamics (no silently-dead fault knob).
"""

import functools

import numpy as np
import pytest

import jax

from consul_tpu.models.broadcast import BroadcastConfig, broadcast_init
from consul_tpu.models.lifeguard import LifeguardConfig, lifeguard_init
from consul_tpu.models.membership import MembershipConfig, membership_init
from consul_tpu.models.membership_sparse import (
    SparseMembershipConfig,
    sparse_membership_init,
)
from consul_tpu.models.swim import SwimConfig, swim_init
from consul_tpu.geo import GeoConfig, geo_init
from consul_tpu.sim.engine import (
    broadcast_scan,
    geo_scan,
    lifeguard_scan,
    membership_scan,
    run_sweep,
    sparse_membership_scan,
    streamcast_scan,
    swim_scan,
)
from consul_tpu.streamcast import StreamcastConfig, streamcast_init
from consul_tpu.sweep import Universe, make_preset, pareto_mask
from consul_tpu.sweep.frontier import ENTRYPOINT_METRICS, SweepReport
from consul_tpu.sweep.universe import make_sweep, stacked_init


def _leaves_equal(a, b, batched_b=True):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        y = np.asarray(y)[0] if batched_b else np.asarray(y)
        if not (np.asarray(x) == y).all():
            return False
    return True


# ---------------------------------------------------------------------------
# U=1 bit-equality pins: one per model.  The sweep program (vmapped,
# donated stacked carry, knob machinery in place) must reproduce the
# unbatched entrypoint exactly.
# ---------------------------------------------------------------------------


_SMALL = {
    "swim": (SwimConfig(n=64, subject=1, loss=0.05), swim_init,
             swim_scan, 10, None),
    "lifeguard": (LifeguardConfig(n=64, subject=1, subject_alive=True,
                                  ack_late=0.05), lifeguard_init,
                  lifeguard_scan, 10, None),
    "broadcast": (BroadcastConfig(n=64, fanout=3, loss=0.05),
                  lambda c: broadcast_init(c, origin=0),
                  broadcast_scan, 10, None),
    "membership": (MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),)),
                   membership_init, membership_scan, 8, (3,)),
    "sparse": (SparseMembershipConfig(
        base=MembershipConfig(n=48, loss=0.05, fail_at=((3, 2),)),
        k_slots=8), sparse_membership_init,
        sparse_membership_scan, 8, (3,)),
    "streamcast": (StreamcastConfig(n=64, events=10, chunks=2,
                                    window=3, fanout=3, chunk_budget=2,
                                    rate=0.4, names=3, loss=0.05,
                                    delivery="edges"),
                   streamcast_init, streamcast_scan, 10, None),
    "geo": (GeoConfig(n=64, segments=8, bridges_per_segment=2,
                      events=4, wan_window=4, wan_msg_bytes=100,
                      wan_capacity_bytes=800.0, wan_queue_bytes=1600.0,
                      ae_batch=4, loss_wan=0.05),
            geo_init, geo_scan, 8, None),
}


class TestU1BitEquality:
    @pytest.mark.parametrize("model", sorted(_SMALL))
    def test_u1_bit_equal_to_unbatched(self, model):
        cfg, init, scan, steps, track = _SMALL[model]
        key = jax.random.PRNGKey(5)
        args = (init(cfg), key, cfg, steps)
        if track is not None:
            args = args + (tuple(track),)
        final, outs = scan(*args)
        outs = jax.tree_util.tree_map(np.asarray, outs)
        final = jax.tree_util.tree_map(np.asarray, final)

        uni = Universe(entrypoint=model, cfg=cfg, steps=steps,
                       seeds=(5,), track=tuple(track) if track else ())
        sweep = make_sweep(model, 1)
        final2, outs2 = sweep(
            stacked_init(uni), uni.keys(), (), cfg, steps, (),
            uni.track,
        )
        assert _leaves_equal(outs, outs2), f"{model}: per-tick outputs"
        assert _leaves_equal(final, final2), f"{model}: final state"

    def test_u1_pipeline_policy_bit_equal_to_unbatched(self):
        # The policy x load acceptance ladder's U=1 rung: the batched
        # pipeline-policy program (cursor plane in the stacked carry)
        # reproduces the plain pipeline scan exactly — composing with
        # the sharded D=1 pins in tests/test_streamcast.py this closes
        # U=1 ≡ plain scan for the paper schedule.
        import dataclasses as _dc

        cfg0, init, scan, steps, _ = _SMALL["streamcast"]
        cfg = _dc.replace(cfg0, policy="pipeline")
        key = jax.random.PRNGKey(5)
        final, outs = scan(init(cfg), key, cfg, steps)
        outs = jax.tree_util.tree_map(np.asarray, outs)
        final = jax.tree_util.tree_map(np.asarray, final)

        uni = Universe(entrypoint="streamcast", cfg=cfg, steps=steps,
                       seeds=(5,))
        sweep = make_sweep("streamcast", 1)
        final2, outs2 = sweep(
            stacked_init(uni), uni.keys(), (), cfg, steps, (), (),
        )
        assert _leaves_equal(outs, outs2)
        assert _leaves_equal(final, final2)

    def test_u1_with_knob_at_default_is_bit_equal(self):
        # The knob-rebuild path itself (traced scalar spliced into the
        # config) must not perturb the program's arithmetic: a loss
        # knob pinned at the static config's own value reproduces the
        # static program bit-for-bit.
        cfg, init, scan, steps, _ = _SMALL["swim"]
        key = jax.random.PRNGKey(5)
        _, outs = scan(init(cfg), key, cfg, steps)
        uni = Universe(entrypoint="swim", cfg=cfg, steps=steps,
                       seeds=(5,), knobs=("loss",),
                       values=((cfg.loss,),))
        _, outs2 = make_sweep("swim", 1)(
            stacked_init(uni), uni.keys(), uni.knob_arrays(), cfg,
            steps, uni.knobs, (),
        )
        assert _leaves_equal(outs, outs2)


# ---------------------------------------------------------------------------
# Retrace discipline: one program per (entrypoint, U); values never
# retrace.
# ---------------------------------------------------------------------------


class TestRetraceDiscipline:
    def test_one_program_per_entrypoint_u(self):
        from consul_tpu.analysis.guards import TraceGuard

        cfg = _SMALL["swim"][0]
        sweep3 = make_sweep("swim", 3)
        assert make_sweep("swim", 3) is sweep3  # lru-cached wrapper
        guard = TraceGuard(sweep3, max_traces=1, name="sweep_swim_U3")
        for seeds, losses in [
            ((0, 1, 2), (0.0, 0.1, 0.2)),
            ((3, 4, 5), (0.3, 0.4, 0.05)),
            ((0, 0, 0), (0.5, 0.5, 0.5)),
        ]:
            run_sweep(Universe(
                entrypoint="swim", cfg=cfg, steps=4, seeds=seeds,
                knobs=("loss",), values=(losses,),
            ), warmup=False)
        guard.check()
        assert guard.traces == 1

    def test_new_u_is_a_distinct_program_object(self):
        # U is positional-static: a new U is a NEW cached wrapper (and
        # therefore a separate jit cache), while repeated calls at the
        # same (entrypoint, U) share one — the compile-side twin is
        # test_tracelint's sweep-builder guard, so no extra XLA
        # programs are built here.
        assert make_sweep("swim", 2) is not make_sweep("swim", 3)
        assert make_sweep("swim", 3) is make_sweep("swim", 3)
        assert make_sweep("lifeguard", 3) is not make_sweep("swim", 3)

    def test_unknown_entrypoint_rejected(self):
        with pytest.raises(ValueError, match="unknown sweep entrypoint"):
            make_sweep("multidc", 2)


# ---------------------------------------------------------------------------
# The config-stacking footgun: shape-feeding fields are rejected loudly
# at Universe construction.
# ---------------------------------------------------------------------------


class TestKnobValidation:
    def _mk(self, cfg, knob, value=0.1, entrypoint="swim"):
        return Universe(entrypoint=entrypoint, cfg=cfg, steps=4,
                        seeds=(0,), knobs=(knob,), values=((value,),))

    @pytest.mark.parametrize("knob", ["n", "subject", "delivery",
                                      "profile.suspicion_mult",
                                      "profile.probe_interval_ms",
                                      "fail_at_tick"])
    def test_shape_feeding_fields_rejected(self, knob):
        cfg = SwimConfig(n=64, subject=1)
        with pytest.raises(ValueError,
                           match="shapes or trace-time structure"):
            self._mk(cfg, knob)

    def test_rejection_message_names_the_sweepable_family(self):
        with pytest.raises(ValueError, match="sweepable for 'swim'"):
            self._mk(SwimConfig(n=64, subject=1), "n")

    def test_fanout_rejected_under_edges_delivery(self):
        cfg = SwimConfig(n=64, subject=1)  # delivery="edges"
        with pytest.raises(ValueError,
                           match=r"\[n, fanout\].*aggregate"):
            self._mk(cfg, "profile.gossip_nodes", 4)

    def test_fanout_allowed_under_aggregate(self):
        cfg = SwimConfig(n=64, subject=1, delivery="aggregate")
        self._mk(cfg, "profile.gossip_nodes", 4)  # no raise

    def test_wrong_int_knob_under_aggregate_names_the_right_path(self):
        # Already in aggregate mode with the wrong path: the message
        # must point at the rate-entering knob, not tell the user to
        # switch to the mode they are already in.
        cfg = SwimConfig(n=64, subject=1, delivery="aggregate")
        with pytest.raises(ValueError,
                           match=r"only via \['profile\.gossip_nodes'\]"):
            self._mk(cfg, "fanout", 4)

    def test_dense_membership_shape_fields_rejected(self):
        cfg = MembershipConfig(n=48, fail_at=((3, 2),))
        for knob in ("piggyback", "fanout"):
            with pytest.raises(ValueError):
                self._mk(cfg, knob, 4, entrypoint="membership")

    def test_sparse_k_slots_rejected(self):
        cfg = SparseMembershipConfig(
            base=MembershipConfig(n=48, fail_at=((3, 2),)), k_slots=8)
        with pytest.raises(ValueError,
                           match="shapes or trace-time structure"):
            self._mk(cfg, "k_slots", 16, entrypoint="sparse")

    def test_unknown_path_rejected(self):
        with pytest.raises(ValueError, match="has no field"):
            self._mk(SwimConfig(n=64, subject=1), "losss")

    def test_streamcast_rate_and_budget_sweepable(self):
        # The offered load and the pipelined bandwidth cap are the
        # streamcast tuning family; neither feeds a shape (rate is
        # jnp arithmetic in the arrival derivation, chunk_budget a
        # rank comparison).  The adversarial-load severities
        # (sim/load.py) are jnp arithmetic on the schedule too.
        cfg = _SMALL["streamcast"][0]
        self._mk(cfg, "rate", 0.5, entrypoint="streamcast")  # no raise
        self._mk(cfg, "chunk_budget", 3, entrypoint="streamcast")
        self._mk(cfg, "size_tail", 1.0, entrypoint="streamcast")
        self._mk(cfg, "hotspot", 0.5, entrypoint="streamcast")

    def test_streamcast_shape_fields_rejected(self):
        # policy is a trace-time branch (one program per policy — the
        # policy x load grid is one batched program PER policy, never
        # a knob), backlog picks which schedule entries move, and the
        # hot node is a scatter target: all structure, all refused.
        cfg = _SMALL["streamcast"][0]
        for knob in ("window", "chunks", "events", "names", "policy",
                     "backlog", "hotspot_node"):
            with pytest.raises(ValueError,
                               match="shapes or trace-time structure"):
                self._mk(cfg, knob, 4, entrypoint="streamcast")

    def test_streamcast_fanout_rejected_under_edges(self):
        cfg = _SMALL["streamcast"][0]  # delivery="edges"
        with pytest.raises(ValueError,
                           match=r"\[n, fanout\].*aggregate"):
            self._mk(cfg, "fanout", 4, entrypoint="streamcast")

    def test_fault_severity_paths_allowed_for_lifeguard(self):
        from consul_tpu.sim.faults import (
            DegradedSet,
            FaultSchedule,
            LossRamp,
        )

        cfg = LifeguardConfig(
            n=64, subject=1, subject_alive=True,
            faults=FaultSchedule(
                ramps=(LossRamp(pieces=((2, 0.3),)),),
                degraded=(DegradedSet(frac=0.1),),
            ),
        )
        for knob in ("faults.ramps[0].scale", "faults.degraded[0].drop",
                     "faults.degraded[0].frac"):
            Universe(entrypoint="lifeguard", cfg=cfg, steps=4,
                     seeds=(0,), knobs=(knob,), values=((0.5,),))
        with pytest.raises(ValueError):  # schedule STRUCTURE stays static
            Universe(entrypoint="lifeguard", cfg=cfg, steps=4,
                     seeds=(0,), knobs=("faults.degraded[0].seed",),
                     values=((1,),))

    def test_universe_seed_modes_are_exclusive(self):
        cfg = SwimConfig(n=64, subject=1)
        with pytest.raises(ValueError, match="exactly one of"):
            Universe(entrypoint="swim", cfg=cfg, steps=4)
        with pytest.raises(ValueError, match="exactly one of"):
            Universe(entrypoint="swim", cfg=cfg, steps=4, seeds=(0,),
                     split_from=1, universes=2)

    def test_value_row_length_must_match_u(self):
        cfg = SwimConfig(n=64, subject=1)
        with pytest.raises(ValueError, match="values for U="):
            Universe(entrypoint="swim", cfg=cfg, steps=4, seeds=(0, 1),
                     knobs=("loss",), values=((0.1,),))


# ---------------------------------------------------------------------------
# Distribution: 64 seed universes from ONE program reproduce the
# SWIM-paper first-detection mean inside test_swim_paper's band.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _sweep_first_detection(n: int, U: int) -> np.ndarray:
    cfg = SwimConfig(n=n, subject=7, fail_at_tick=0)
    P = cfg.probe_interval_ticks
    uni = Universe(entrypoint="swim", cfg=cfg, steps=30 * P,
                   split_from=0, universes=U)
    rep = run_sweep(uni, warmup=False)
    fs = rep.metrics["first_suspect_ms"]
    assert not np.isnan(fs).any(), "some universe never detected"
    periods = (fs / cfg.profile.gossip_interval_ms - 1.0) / P
    periods.setflags(write=False)
    return periods


@pytest.mark.slow
class TestSeedSweepDistribution:
    """Behind -m slow per the PR 3 policy for long-horizon
    distributional band tests (the 96-universe 30-probe-period sweep
    is ~23s; the deterministic U=1 bit-equality pins above are the
    tier-1 guarantee the sweep plane rides on)."""

    def test_mean_within_swim_paper_band(self):
        # Same band as test_swim_paper.test_first_detection_mean_
        # within_5pct, measured over 500 universes from one batched
        # program (fold_in keys are prefix-stable, so these ARE the
        # first universes of a larger error-bar sweep).  500, not 96:
        # the per-universe std is ~0.61x the mean, so the 5% band is
        # only ~0.8 sigma at U=96 — the owned-draws derivation's
        # deterministic fold_in prefix lands 2.2 sigma high there
        # (verified converging: rel_err 16.7% @96 -> 2.4% @500 ->
        # 1.3% @2000), so the band needs ~1.8 sigma of room to be a
        # statistics claim instead of a seed-luck claim.
        n = 256
        periods = _sweep_first_detection(n, 500)
        p = 1.0 - (1.0 - 1.0 / (n - 1)) ** (n - 1)
        expected = 1.0 / p
        rel_err = abs(periods.mean() - expected) / expected
        assert rel_err < 0.05, (periods.mean(), expected, rel_err)

    def test_universe_slices_match_unbatched_runs(self):
        # Bit-level spot check: universes 0 and 3 of the batched run
        # equal standalone swim_scan runs at the same fold_in keys.
        n = 256
        cfg = SwimConfig(n=n, subject=7, fail_at_tick=0)
        P = cfg.probe_interval_ticks
        periods = _sweep_first_detection(n, 500)  # shares the cached run
        base = jax.random.PRNGKey(0)
        for u in (0, 3):
            _, (sus, _dead) = swim_scan(
                swim_init(cfg), jax.random.fold_in(base, u), cfg, 30 * P
            )
            sus = np.asarray(sus)
            assert sus.max() > 0
            first = int(np.argmax(sus > 0))
            assert periods[u] == first / P

    def test_split_from_keys_are_prefix_stable(self):
        # The error-bar contract: the first 16 universes of a U=64
        # sweep ARE the U=16 sweep's universes (fold_in derivation is
        # U-independent; jax.random.split's keys are not).
        cfg = SwimConfig(n=64, subject=1)
        k16 = Universe(entrypoint="swim", cfg=cfg, steps=1,
                       split_from=0, universes=16).keys()
        k64 = Universe(entrypoint="swim", cfg=cfg, steps=1,
                       split_from=0, universes=64).keys()
        assert (np.asarray(k16) == np.asarray(k64)[:16]).all()


# ---------------------------------------------------------------------------
# Frontier extraction: property-test vs a brute-force numpy reference.
# ---------------------------------------------------------------------------


def _pareto_reference(pts):
    """O(U^2) reference: keep points no other valid point dominates."""
    pts = np.asarray(pts, float)
    keep = []
    for i, p in enumerate(pts):
        if np.isnan(p).any():
            keep.append(False)
            continue
        dominated = False
        for j, q in enumerate(pts):
            if i == j or np.isnan(q).any():
                continue
            if (q <= p).all() and (q < p).any():
                dominated = True
                break
        keep.append(not dominated)
    return np.asarray(keep)


class TestParetoFrontier:
    def test_matches_reference_on_random_point_sets(self):
        rng = np.random.default_rng(0)
        for trial in range(20):
            U = int(rng.integers(1, 40))
            pts = rng.normal(size=(U, 2))
            # duplicates + NaN rows exercised
            if U > 4:
                pts[1] = pts[0]
                pts[2, 0] = np.nan
            got = pareto_mask(pts)
            want = _pareto_reference(pts)
            assert (got == want).all(), (trial, pts[got != want])

    def test_frontier_points_are_mutually_nondominating(self):
        rng = np.random.default_rng(1)
        pts = rng.random((64, 2))
        front = pts[pareto_mask(pts)]
        for i, p in enumerate(front):
            for j, q in enumerate(front):
                if i != j:
                    assert not ((q <= p).all() and (q < p).any())

    def test_detect_metrics_ignore_precrash_false_dead(self):
        # A hair-trigger universe whose false-DEAD views a refute later
        # repairs must not register negative-latency "detections": only
        # ticks at/after the crash count (the time_to_true_dead_ms
        # contract in sim/metrics.py).
        from consul_tpu.sweep.frontier import _detect_metrics

        # Universe 0: 9 observers false-DEAD before the crash at tick
        # 10 (repaired at tick 8), real detection from tick 12.
        # Universe 1: never detects after the crash.
        dead = np.zeros((2, 20))
        dead[0, 2:8] = 9.0
        dead[0, 12:] = 9.0
        dead[1, 0:8] = 9.0
        m = _detect_metrics(dead, n=10, tick_ms=100.0, fail_at=10.0,
                            defined=True)
        assert m["detect_first_ms"][0] == pytest.approx(300.0)  # tick 12
        assert m["detect_t90_ms"][0] == pytest.approx(300.0)
        assert np.isnan(m["detect_first_ms"][1])
        for v in m.values():
            ok = v[~np.isnan(v)]
            assert (ok > 0).all(), m

    def test_crash_at_or_past_horizon_yields_nan_not_crash(self):
        # fail_at >= steps leaves a zero-width detection window: the
        # sweep must summarize to NaN metrics like every never-detected
        # case (first_tick in sim/metrics.py), not die in an argmax
        # over an empty slice.
        uni = Universe(
            entrypoint="swim",
            cfg=SwimConfig(n=32, subject=1, fail_at_tick=20),
            steps=10, seeds=(0,),
        )
        rep = run_sweep(uni, warmup=False)
        for name in ("detect_first_ms", "detect_t90_ms"):
            assert np.isnan(rep.metrics[name]).all()

    def test_frontier_unknown_axis_raises_clear_error(self):
        rep = SweepReport(entrypoint="swim", n=32, U=2, steps=4,
                          tick_ms=200.0, knobs=(), values={},
                          metrics={"first_suspect_ms":
                                   np.array([200.0, 400.0])},
                          wall_s=0.01)
        # Default axes belong to lifeguard FP studies — on any other
        # report they must name the problem, not KeyError from
        # np.stack.
        with pytest.raises(ValueError, match="fp_rate.*swim"):
            rep.frontier()
        with pytest.raises(ValueError, match="defined: first_suspect_ms"):
            rep.frontier(x="first_suspect_ms", y="nope")

    def test_all_nan_yields_empty_frontier(self):
        assert pareto_mask(np.full((4, 2), np.nan)).sum() == 0

    def test_knob_grid_frontier_is_nondegenerate(self):
        # A tiny fanout x suspicion-scale grid must produce >= 2
        # frontier points: hair-trigger scales buy latency at a
        # false-dead cost, long scales the reverse.  Same preset
        # factory (and shapes) as __graft_entry__'s dryrun sweep.
        from consul_tpu.sweep.presets import tuning_grid

        rep = run_sweep(tuning_grid(
            n=192, fanouts=(3, 6), scales=(0.1, 1.0), loss=0.40,
            ack_late=0.30, fail_at=60, steps=140,
        ), warmup=False)
        front = rep.frontier(x="false_dead_mean", y="detect_t90_ms")
        assert len(front) >= 2, (front, rep.metrics)
        # The tradeoff direction: the lowest-latency frontier point
        # pays a strictly higher false-dead cost than the most robust.
        assert front[0]["detect_t90_ms"] >= front[-1]["detect_t90_ms"]


# ---------------------------------------------------------------------------
# Fault-matrix coverage: every severity rung changes the dynamics.
# ---------------------------------------------------------------------------


class TestFaultMatrixCoverage:
    def test_every_rung_fires(self):
        uni = make_preset("faultmatrix")
        rungs = sorted({v for row in uni.values for v in row})
        sweep = make_sweep("lifeguard", uni.U)
        _, outs = sweep(
            stacked_init(uni), uni.keys(), uni.knob_arrays(), uni.cfg,
            uni.steps, uni.knobs, (),
        )
        sus = np.asarray(outs[0])  # [U, steps] suspicion curves
        vals = [np.asarray(row) for row in uni.values]
        # For each knob and each nonzero rung there must exist a
        # universe pair differing ONLY in that knob whose dynamics
        # differ — i.e. no severity knob is silently dead.
        for k in range(len(uni.knobs)):
            others = [i for i in range(len(uni.knobs)) if i != k]
            for rung in rungs:
                if rung == min(rungs):
                    continue
                fired = False
                for a in range(uni.U):
                    if vals[k][a] != rung:
                        continue
                    for b in range(uni.U):
                        if (vals[k][b] == min(rungs) and all(
                                vals[o][a] == vals[o][b]
                                for o in others)):
                            if not (sus[a] == sus[b]).all():
                                fired = True
                    if fired:
                        break
                assert fired, (
                    f"knob {uni.knobs[k]} rung {rung} never changed "
                    "the dynamics"
                )

    def test_grid_presets_reject_universe_override(self):
        with pytest.raises(ValueError, match="grid preset"):
            make_preset("faultmatrix", universes=5)
        with pytest.raises(ValueError, match="grid preset"):
            make_preset("tuning", universes=5)
        with pytest.raises(ValueError, match="grid preset"):
            make_preset("streamload", universes=5)
        with pytest.raises(ValueError, match="grid preset"):
            make_preset("streamadv", universes=5)

    def test_seed_preset_universe_override(self):
        uni = make_preset("seeds4k", universes=3)
        assert uni.U == 3

    def test_seed_preset_rejects_zero_universes(self):
        # --universes 0 must die in Universe's >= 1 guard, not fall
        # through a falsy `or` into the full U=256 default sweep.
        with pytest.raises(ValueError, match="universes must be >= 1"):
            make_preset("seeds4k", universes=0)


# ---------------------------------------------------------------------------
# ENTRYPOINT_METRICS registry pin + the cli sweep frontier-axis
# contract: typos die BEFORE the batched program runs, explicit axis
# requests are never silently dropped.
# ---------------------------------------------------------------------------


class TestEntrypointMetricsRegistry:
    @pytest.mark.parametrize("model", sorted(_SMALL))
    def test_registry_matches_emitted_metrics(self, model):
        # cli sweep validates --frontier-x/-y against this registry
        # BEFORE running the sweep, so it must stay exactly what
        # summarize_sweep emits (the _SMALL studies exercise every
        # branch: crash track for membership/sparse, FP counters for
        # lifeguard).
        cfg, _init, _scan, steps, track = _SMALL[model]
        uni = Universe(entrypoint=model, cfg=cfg, steps=steps,
                       seeds=(5,), track=tuple(track) if track else ())
        rep = run_sweep(uni, warmup=False)
        assert set(rep.metrics) == ENTRYPOINT_METRICS[model]

    def test_cli_default_axes_are_registered(self):
        for ep in ("swim", "lifeguard"):
            assert {"false_dead_mean", "detect_t90_ms",
                    "first_suspect_ms"} <= ENTRYPOINT_METRICS[ep]


class TestCliSweep:
    def _report(self, metrics):
        return SweepReport(entrypoint="swim", n=64, U=2, steps=4,
                           tick_ms=200.0, knobs=(), values={},
                           metrics=metrics, wall_s=0.01)

    def test_list_presets(self, capsys):
        from consul_tpu import cli
        assert cli.main(["sweep", "--list"]) == 0
        out = capsys.readouterr().out
        for name in ("seeds4k", "tuning", "faultmatrix"):
            assert name in out

    def test_unknown_axis_rejected_before_the_sweep_runs(
            self, capsys, monkeypatch):
        from consul_tpu import cli
        from consul_tpu.sim import engine

        def _boom(*a, **k):
            raise AssertionError("run_sweep must not be reached")

        monkeypatch.setattr(engine, "run_sweep", _boom)
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--frontier-x", "detect_t90_mss"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "unknown frontier metric" in err
        assert "detect_t90_mss" in err
        assert "must not be reached" not in err

    def test_explicit_axis_without_partner_errors(self, capsys,
                                                  monkeypatch):
        # seeds4k crashes the subject at tick 0, so the robustness
        # default (false_dead_mean) is all-NaN: an explicit -y request
        # must error loudly, not silently drop the frontier.
        from consul_tpu import cli
        from consul_tpu.sim import engine

        rep = self._report({
            "false_dead_mean": np.full(2, np.nan),
            "detect_t90_ms": np.array([800.0, 1000.0]),
            "first_suspect_ms": np.array([200.0, 400.0]),
        })
        monkeypatch.setattr(engine, "run_sweep", lambda u, **k: rep)
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--frontier-y", "detect_t90_ms"])
        assert rc == 1
        assert "no robustness axis" in capsys.readouterr().err

    def test_explicit_axis_undefined_for_study_errors(self, capsys,
                                                      monkeypatch):
        # A registered metric the study didn't emit is caught post-run
        # and named in the error.
        from consul_tpu import cli
        from consul_tpu.sim import engine

        rep = self._report({"detect_t90_ms": np.array([800.0, 1000.0])})
        monkeypatch.setattr(engine, "run_sweep", lambda u, **k: rep)
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--frontier-x", "false_dead_mean",
                       "--frontier-y", "detect_t90_ms"])
        assert rc == 1
        assert "'false_dead_mean' is not defined" in (
            capsys.readouterr().err
        )

    def test_explicit_all_nan_axis_errors(self, capsys, monkeypatch):
        # Emitted-but-all-NaN (seeds4k's false_dead_mean: the subject
        # crashes at tick 0, so there is no pre-crash window) must hit
        # the same loud error as an absent key — not print
        # "frontier": [] with rc 0.
        from consul_tpu import cli
        from consul_tpu.sim import engine

        rep = self._report({
            "false_dead_mean": np.full(2, np.nan),
            "detect_t90_ms": np.array([800.0, 1000.0]),
        })
        monkeypatch.setattr(engine, "run_sweep", lambda u, **k: rep)
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--frontier-x", "false_dead_mean",
                       "--frontier-y", "detect_t90_ms"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "'false_dead_mean' is not defined" in err
        assert "false_dead_mean" not in err.split("defined: ")[1]

    def test_explicit_axes_emit_frontier(self, capsys, monkeypatch):
        import json

        from consul_tpu import cli
        from consul_tpu.sim import engine

        rep = self._report({
            "false_dead_mean": np.array([0.0, 3.0]),
            "detect_t90_ms": np.array([1000.0, 600.0]),
            "first_suspect_ms": np.array([200.0, 400.0]),
        })
        monkeypatch.setattr(engine, "run_sweep", lambda u, **k: rep)
        rc = cli.main(["sweep", "seeds4k", "--universes", "2",
                       "--frontier-x", "false_dead_mean",
                       "--frontier-y", "detect_t90_ms"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["frontier_axes"] == ["false_dead_mean",
                                        "detect_t90_ms"]
        assert len(out["frontier"]) == 2  # mutually nondominating


# ---------------------------------------------------------------------------
# Long-horizon acceptance sweep (slow): U=256 seed universes, n=4096.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_acceptance_sweep_u256_n4096():
    rep = run_sweep(make_preset("seeds4k"), warmup=True)
    assert rep.U == 256
    assert rep.n == 4096
    assert rep.universes_per_sec > 0
    fs = rep.metrics["first_suspect_ms"]
    assert (~np.isnan(fs)).sum() == 256, "some universe never detected"
