"""rangelint: interval abstract interpretation over the jaxpr plane.

Per new rule a planted-defect fixture the rule must fire on (with
file:line provenance) and a clean twin it must stay silent on; the
interval transfer functions checked against a numpy exact-arithmetic
reference; scan fixpoint/widening unit tests; the zero-findings gates
over the full small+big registry; and the golden narrowing-certificate
table for sparse@1M with the applied CONF_DTYPE/TX_DTYPE narrowing's
J6 peak-HBM delta pinned via a dtype-monkeypatched baseline trace.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consul_tpu.analysis.jaxlint import analyze_jaxpr, estimate_peak
from consul_tpu.analysis.rangelint import (
    RULES,
    AV,
    Bound,
    IV,
    _Interp,
    analyze_program,
    analyze_spec,
    lint_registry,
    minimal_signed_dtype,
    narrowing_ledger,
)
from consul_tpu.sim.engine import jaxlint_registry, sparse_program_at

SDS = jax.ShapeDtypeStruct
F32 = jnp.float32
I32 = jnp.int32
I16 = jnp.int16


def _analyze(fn, args, bounds=None, names=None):
    jx = jax.make_jaxpr(fn)(*args)
    return analyze_program("t", jx, bounds=bounds, leaf_names=names)


def _rules(fn, args, bounds=None):
    return [f.rule for f in _analyze(fn, args, bounds).findings]


def _out_iv(fn, args, bounds):
    """Output interval of a traced fn under the given input bounds."""
    jx = jax.make_jaxpr(fn)(*args)
    interp = _Interp("t", frozenset(RULES))
    in_avs = [
        AV(IV(b[0], b[1], True)) if b is not None
        else AV(IV(float("-inf"), float("inf"), False))
        for b in bounds
    ]
    outs, _ = interp.eval_jaxpr(jx.jaxpr, tuple(jx.consts), in_avs)
    return outs[0].iv


# ---------------------------------------------------------------------------
# Interval transfer functions vs a numpy exact-arithmetic reference.
# ---------------------------------------------------------------------------


class TestIntervalReference:
    OPS = {
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b,
        "min": jnp.minimum,
        "max": jnp.maximum,
        "rem": lambda a, b: jax.lax.rem(a, b),
    }
    NP_OPS = {
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b,
        "min": np.minimum,
        "max": np.maximum,
        # lax.rem is C-style truncating remainder == np.fmod.
        "rem": lambda a, b: np.fmod(a, b),
    }
    RANGES = [(-7, 13), (0, 5), (3, 40), (-20, -2)]

    @pytest.mark.parametrize("op", sorted(OPS))
    def test_transfer_contains_every_concrete_result(self, op):
        for alo, ahi in self.RANGES:
            for blo, bhi in self.RANGES:
                if op == "rem" and blo <= 0:
                    continue  # divisor must be known-positive
                iv = _out_iv(
                    self.OPS[op], (SDS((), I32), SDS((), I32)),
                    [(alo, ahi), (blo, bhi)],
                )
                a = np.arange(alo, ahi + 1, dtype=np.int64)
                b = np.arange(blo, bhi + 1, dtype=np.int64)
                got = self.NP_OPS[op](a[:, None], b[None, :])
                assert iv.known
                assert iv.lo <= got.min() and got.max() <= iv.hi, (
                    op, (alo, ahi), (blo, bhi), iv,
                    (got.min(), got.max()),
                )

    def test_reduce_sum_scales_by_count(self):
        iv = _out_iv(
            lambda x: jnp.sum(x, dtype=jnp.int32),
            (SDS((10,), I32),), [(0, 3)],
        )
        assert iv.known and iv.lo == 0 and iv.hi == 30

    def test_iota_and_shift(self):
        iv = _out_iv(
            lambda x: (jnp.arange(16, dtype=jnp.int32) << 2) + x,
            (SDS((16,), I32),), [(0, 1)],
        )
        assert iv.known and iv.lo == 0 and iv.hi == 61

    def test_floor_mod_known_in_divisor_range(self):
        # jnp's % lowers to rem + sign fixup; the floor-mod pattern
        # must land in [0, d-1] even with an UNKNOWN dividend (the
        # ring-buffer index idiom).
        iv = _out_iv(
            lambda t: t % 8, (SDS((), I32),), [None],
        )
        assert iv.known and iv.lo == 0 and iv.hi == 7

    def test_clamp_with_interval_cap_is_sound(self):
        # Regression: clamp's LOWER bound caps at the cap's lo, not
        # its hi — an element whose cap is hi_b.lo can be pulled down
        # to it (clamp(0, 5, cap in [3, 4]) reaches 3).
        iv = _out_iv(
            lambda x, c: jnp.clip(x, 0, c),
            (SDS((2,), I32), SDS((2,), I32)),
            [(5, 5), (3, 4)],
        )
        a = np.array([5, 5])
        c = np.array([3, 4])
        got = np.clip(a, 0, c)
        assert iv.lo <= got.min() and got.max() <= iv.hi, iv

    def test_minimal_signed_dtype(self):
        assert minimal_signed_dtype(0, 100) == "int8"
        assert minimal_signed_dtype(-1, 127) == "int8"
        assert minimal_signed_dtype(0, 128) == "int16"
        assert minimal_signed_dtype(-40000, 0) == "int32"
        assert minimal_signed_dtype(0, 1 << 40) is None


# ---------------------------------------------------------------------------
# Scan fixpoint + widening.
# ---------------------------------------------------------------------------


class TestFixpointWidening:
    def _cert(self, fn, args, bounds, plane=0):
        rep = _analyze(fn, args, bounds,
                       names=[f"p{i}" for i in range(len(args))])
        return {c.plane: c for c in rep.certificates}.get(f"p{plane}")

    def test_counter_widens_to_trip_count(self):
        steps = 37

        def fn(c, xs):
            return jax.lax.scan(
                lambda carry, x: (carry + jnp.int32(2), carry), c, xs
            )

        cert = self._cert(
            fn, (SDS((4,), I32), SDS((steps,), F32)),
            [Bound(0, 0), Bound.any()],
        )
        # The widened interval must CONTAIN the true final value
        # (2 * steps) and stay within one extra tick of it.
        concrete = 2 * steps
        assert cert.lo <= 0 and concrete <= cert.hi <= concrete + 4

    def test_clamped_carry_converges_tight(self):
        def fn(c, xs):
            return jax.lax.scan(
                lambda carry, x: (
                    jnp.minimum(carry + jnp.int32(1), 5), carry
                ), c, xs,
            )

        cert = self._cert(
            fn, (SDS((4,), I32), SDS((200,), F32)),
            [Bound(0, 0), Bound.any()],
        )
        # min() closes the interval: the fixpoint is exact, not the
        # 200-tick extrapolation.
        assert cert.lo == 0 and cert.hi <= 6
        assert cert.minimal == "int8"

    def test_widened_interval_contains_concrete_run(self):
        steps = 25

        def body(carry, x):
            nxt = jnp.minimum(carry + (x > 0).astype(jnp.int32), 9)
            return nxt, nxt

        def fn(c, xs):
            return jax.lax.scan(body, c, xs)

        cert = self._cert(
            fn, (SDS((8,), I32), SDS((steps,), F32)),
            [Bound(0, 0), Bound.any()],
        )
        xs = jax.random.normal(jax.random.PRNGKey(0), (steps,))
        final, _ = jax.lax.scan(body, jnp.zeros((8,), jnp.int32), xs)
        final = np.asarray(final)
        assert cert.lo <= final.min() and final.max() <= cert.hi


# ---------------------------------------------------------------------------
# J7: planted overflow / clean twin.
# ---------------------------------------------------------------------------


class TestJ7Overflow:
    def test_fires_on_int16_counter_overflow(self):
        def bad(c, xs):
            return jax.lax.scan(
                lambda carry, x: (carry + jnp.int16(1000), carry),
                c, xs,
            )

        rep = _analyze(bad, (SDS((), I16), SDS((100,), F32)),
                       [Bound(0, 0), Bound.any()])
        found = [f for f in rep.findings if f.rule == "J7"]
        assert found, "planted int16 overflow must fire"
        # eqn provenance: the finding points at this test file.
        assert "test_rangelint" in found[0].where, found[0]

    def test_silent_on_int32_twin(self):
        def clean(c, xs):
            return jax.lax.scan(
                lambda carry, x: (carry + jnp.int32(1000), carry),
                c, xs,
            )

        assert _rules(clean, (SDS((), I32), SDS((100,), F32)),
                      [Bound(0, 0), Bound.any()]) == []

    def test_fires_on_proven_narrowing_cast(self):
        def bad(x):
            return x.astype(jnp.int8)

        assert "J7" in _rules(bad, (SDS((4,), I32),),
                              [Bound(0, 1000)])

    def test_silent_on_unknown_inputs(self):
        # A dtype-range top must never prove an overflow.
        def f(x, y):
            return x + y

        assert _rules(f, (SDS((4,), I32), SDS((4,), I32))) == []

    def test_unsigned_wraparound_exempt(self):
        def f(x):
            return x * jnp.uint32(0x9E3779B9)  # hash mix: wraps by design

        assert _rules(f, (SDS((4,), jnp.uint32),),
                      [Bound(0, 4_000_000_000)]) == []


# ---------------------------------------------------------------------------
# J8: PRNG key lineage.
# ---------------------------------------------------------------------------


class TestJ8KeyLineage:
    KEY = SDS((2,), jnp.uint32)

    def test_fires_on_double_draw(self):
        # Shape (41,) is deliberately unique: jax caches the traced
        # jaxpr of its internally-jitted uniform per (shape, dtype),
        # source info included, so a shape another module already
        # traced (e.g. the owned-draw helpers' per-row (fanout,)
        # draws) would carry THAT call site's provenance.
        def bad(key, x):
            return (jax.random.uniform(key, (41,))
                    + jax.random.uniform(key, (41,)) + x)

        rep = _analyze(bad, (self.KEY, SDS((41,), F32)))
        assert ["J8"] == [f.rule for f in rep.findings]
        assert "test_rangelint" in rep.findings[0].where

    def test_silent_on_split(self):
        def clean(key, x):
            k1, k2 = jax.random.split(key)
            return (jax.random.uniform(k1, (4,))
                    + jax.random.uniform(k2, (4,)) + x)

        assert _rules(clean, (self.KEY, SDS((4,), F32))) == []

    def test_fires_on_carry_reuse_across_ticks(self):
        def bad(key, xs):
            def tick(k, x):
                return k, jax.random.uniform(k, ())

            return jax.lax.scan(tick, key, xs)

        assert "J8" in _rules(bad, (self.KEY, SDS((8,), F32)))

    def test_silent_on_carry_split_discipline(self):
        def clean(key, xs):
            def tick(k, x):
                k, sub = jax.random.split(k)
                return k, jax.random.uniform(sub, ())

            return jax.lax.scan(tick, key, xs)

        assert _rules(clean, (self.KEY, SDS((8,), F32))) == []

    def test_salted_fold_in_discipline_is_legal(self):
        # The streamcast/sweep idiom: fold_in with a literal salt
        # ALONGSIDE the split — explicitly legal.
        def clean(key, xs):
            sched = jax.random.uniform(
                jax.random.fold_in(key, 0x5EED), (4,)
            )
            keys = jax.random.split(key, 8)

            def tick(c, k):
                return c + jax.random.uniform(k, ()), c

            return jax.lax.scan(tick, jnp.float32(0), keys), sched

        assert _rules(clean, (self.KEY, SDS((8,), F32))) == []


# ---------------------------------------------------------------------------
# J9: loud accounting.
# ---------------------------------------------------------------------------


class TestJ9LoudAccounting:
    def test_fires_on_silent_masked_drop(self):
        def bad(acc, xs):
            def tick(carry, x):
                ok = x > 0.5
                idx = jnp.where(ok, jnp.int32(1), jnp.int32(100))
                return carry.at[idx].add(1, mode="drop"), jnp.sum(carry)

            return jax.lax.scan(tick, acc, xs)

        rep = _analyze(bad, (SDS((8,), I32), SDS((5,), F32)),
                       [Bound(0, 0), Bound.any()])
        assert ["J9"] == [f.rule for f in rep.findings]
        assert "test_rangelint" in rep.findings[0].where

    def test_silent_when_drop_is_counted(self):
        def clean(state, xs):
            def tick(carry, x):
                acc, dropped = carry
                ok = x > 0.5
                idx = jnp.where(ok, jnp.int32(1), jnp.int32(100))
                acc = acc.at[idx].add(1, mode="drop")
                dropped = dropped + jnp.where(ok, 0, 1).astype(
                    jnp.int32
                )
                return (acc, dropped), jnp.sum(acc)

            return jax.lax.scan(tick, state, xs)

        assert _rules(
            clean,
            ((SDS((8,), I32), SDS((), I32)), SDS((5,), F32)),
            [Bound(0, 0), Bound(0, 0), Bound.any()],
        ) == []

    def test_silent_on_provably_in_bounds_scatter(self):
        def clean(acc, xs):
            def tick(carry, x):
                ok = x > 0.5
                idx = jnp.where(ok, jnp.int32(1), jnp.int32(3))
                return carry.at[idx].add(1), jnp.sum(carry)

            return jax.lax.scan(tick, acc, xs)

        assert _rules(clean, (SDS((8,), I32), SDS((5,), F32)),
                      [Bound(0, 0), Bound.any()]) == []


# ---------------------------------------------------------------------------
# The repo gates: small + big registries, zero findings.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def small_reports():
    programs = jaxlint_registry(include=("small",))
    return lint_registry(programs)


@pytest.fixture(scope="module")
def big_programs():
    return jaxlint_registry(include=("big",))


@pytest.fixture(scope="module")
def big_reports(big_programs):
    return lint_registry(big_programs)


@pytest.mark.slow
class TestRegistryGate:
    """Registry-wide gates ride -m slow (standing tier-1 budget
    policy): tracing the full small+big registry costs ~45 s of wall.
    The planted-fixture and interval-reference tests above stay in
    tier-1."""

    def test_small_registry_zero_findings(self, small_reports):
        findings, _ = small_reports
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_big_registry_zero_findings(self, big_reports):
        findings, _ = big_reports
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_small_certificates_cover_core_planes(self, small_reports):
        _, certs = small_reports
        by_plane = {c.plane: c for c in certs["sparse@small"]}
        assert by_plane["[0].confirms"].minimal == "int8"
        assert by_plane["[0].tx"].minimal == "int8"
        # The age packing replaced the NEVER sentinel: the plane now
        # proves within its declared int16 (tiny at the small trace).
        assert by_plane["[0].suspect_since"].dtype == "int16"

    def test_bounds_metadata_congruent_for_every_spec(self):
        # Each bounds() pytree must flatten congruently with build()'s
        # args — the contract rangelint's input mapping rides on.
        for name, spec in jaxlint_registry(include=("small",)).items():
            if spec.bounds is None:
                continue
            args = spec.build()[1]
            flat_args = jax.tree_util.tree_leaves(args)
            flat_bounds = jax.tree_util.tree_leaves(
                spec.bounds(), is_leaf=lambda x: isinstance(x, Bound)
            )
            assert len(flat_args) == len(flat_bounds), name


# ---------------------------------------------------------------------------
# The golden narrowing-certificate table for sparse@1M, and the applied
# narrowing's J6 peak-HBM delta.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def sparse_1m_report(big_programs):
    return analyze_spec("sparse@1m", big_programs["sparse@1m"])


@pytest.mark.slow
class TestGoldenSparse1M:
    """The certificate table rangelint proves for sparse@1m (n=1M,
    K=64, steps=3, LAN) — the ledger ROADMAP item 1(a) reads.
    -m slow with the registry gates above (big-registry traces)."""

    # plane -> (declared, lo, hi_max, proven minimal dtype)
    GOLDEN = {
        "[0].slot_subj": ("int32", -1, 1_000_000, "int32"),
        "[0].confirms": ("int8", 0, 2, "int8"),
        "[0].tx": ("int8", 0, 32, "int8"),
        "[0].awareness": ("int8", 0, 7, "int8"),
        # Age-packed sentinel plane (PR 12): -1 none, else ticks since
        # the suspicion started, saturating at AGE_CAP.  The 3-step
        # registry trace proves a tiny range (hence minimal int8); the
        # DECLARED int16 carries the real-horizon bound.
        "[0].suspect_since": ("int16", -1, 32000, "int8"),
        "[0].probe_subject": ("int32", 0, 999_999, "int32"),
        "[0].tick": ("int32", 0, 4, "int8"),
    }

    def test_golden_table(self, sparse_1m_report):
        by_plane = {c.plane: c for c in sparse_1m_report.certificates}
        for plane, (dtype, lo, hi_max, minimal) in self.GOLDEN.items():
            c = by_plane[plane]
            assert c.dtype == dtype, (plane, c)
            assert c.lo == lo, (plane, c)
            assert c.hi <= hi_max, (plane, c)
            assert c.minimal == minimal, (plane, c)

    def test_applied_narrowing_matches_certificates(self,
                                                    sparse_1m_report):
        # PR 12 applies every remaining certified narrowing: confirms,
        # tx and awareness at the certificate-minimal int8
        # (__post_init__ guards the bounds), and the age-packed
        # suspect_since at int16.
        from consul_tpu.models.membership_sparse import (
            AWARE_DTYPE,
            CONF_DTYPE,
            SINCE_DTYPE,
            TX_DTYPE,
        )

        assert CONF_DTYPE == jnp.int8 and TX_DTYPE == jnp.int8
        assert AWARE_DTYPE == jnp.int8 and SINCE_DTYPE == jnp.int16
        by_plane = {c.plane: c for c in sparse_1m_report.certificates}
        assert np.iinfo(by_plane["[0].confirms"].minimal).max >= \
            by_plane["[0].confirms"].hi
        assert np.iinfo("int8").max >= by_plane["[0].tx"].hi
        assert np.iinfo("int8").max >= by_plane["[0].awareness"].hi

    def test_ledger_at_10m_clean_and_priced(self, big_programs):
        led = narrowing_ledger(big_programs["sparse@1m"], 10_000_000)
        assert led.findings == [], "\n".join(
            f.format() for f in led.findings
        )
        by_plane = {c.plane: c for c in led.certificates}
        # The APPLIED dtypes hold at 10M: tx/confirms/awareness int8,
        # the age-packed suspect_since within int16.
        assert by_plane["[0].tx"].minimal == "int8"
        assert by_plane["[0].confirms"].minimal == "int8"
        assert np.iinfo("int8").max >= by_plane["[0].awareness"].hi
        assert by_plane["[0].suspect_since"].lo >= -1
        assert np.iinfo("int16").max > by_plane["[0].suspect_since"].hi
        assert by_plane["[0].tx"].elements == 10_000_000 * 64

    def test_j6_peak_delta_of_applied_narrowing_at_1m(self):
        """The acceptance pin: the applied narrowing/packing (confirms
        + tx int8, age-packed suspect_since int16) is worth at least
        one 7-bytes/cell state copy of J6 peak HBM at 1M — measured
        against the same program re-traced with the planes
        monkeypatched back to int32 (the round arithmetic is
        dtype-parametric, so the baseline trace IS the un-narrowed
        program)."""
        import consul_tpu.models.membership_sparse as ms

        now = estimate_peak(sparse_program_at(1_000_000).trace())
        old = (ms.CONF_DTYPE, ms.TX_DTYPE, ms.SINCE_DTYPE)
        ms.CONF_DTYPE = jnp.int32
        ms.TX_DTYPE = jnp.int32
        ms.SINCE_DTYPE = jnp.int32
        try:
            base = estimate_peak(sparse_program_at(1_000_000).trace())
        finally:
            ms.CONF_DTYPE, ms.TX_DTYPE, ms.SINCE_DTYPE = old
        delta = base.total_bytes - now.total_bytes
        cells = 1_000_000 * 64
        assert delta >= int(0.99 * 7 * cells), (
            base.total_bytes, now.total_bytes
        )

    def test_sparse_big_program_lints_clean_under_jaxlint(
            self, big_programs):
        # The narrowed program still passes J1-J6 within the 16 GB
        # budget (no widening crept back in).
        findings, _ = analyze_jaxpr(
            "sparse@1m", big_programs["sparse@1m"].trace(),
            budget_bytes=16 << 30,
        )
        assert findings == []


# ---------------------------------------------------------------------------
# CLI contract.
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, argv):
        import asyncio

        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(argv)
        return asyncio.run(args.fn(args))

    def test_list_rules(self, capsys):
        assert self._run(["rangelint", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in RULES:
            assert rule in out

    def test_rule_filter_rejects_unknown(self, capsys):
        assert self._run(["rangelint", "--rules", "J99",
                          "--set", "small"]) == 2

    @pytest.mark.slow
    def test_check_umbrella_json(self, capsys):
        # The merged four-pass payload + the shared exit contract.
        import json

        assert self._run(["check", "--set", "small",
                          "--format", "json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["clean"] is True
        assert payload["tracelint"]["violations"] == []
        assert payload["jaxlint"]["findings"] == []
        assert payload["rangelint"]["findings"] == []
        assert payload["rangelint"]["certificates"]
        assert payload["equivlint"]["findings"] == []
        assert payload["equivlint"]["failed"] == 0
        assert payload["equivlint"]["golden_diffs"] == 0
        assert (payload["equivlint"]["proved"]
                + payload["equivlint"]["witnessed"]
                == payload["equivlint"]["pairs"])
        assert set(payload["wall_s"]) >= {
            "tracelint", "jaxlint", "rangelint", "trace", "equivlint",
        }
