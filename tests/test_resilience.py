"""Resilience parity: serf gossip snapshot + auto-rejoin, failed-member
reconnect, autopilot dead-server pruning, and user snapshot
save/restore with SHA-256 verification.

Parity model: serf/snapshot_test.go (replay/compact/leave),
serf_test.go reconnect cases, consul/autopilot/autopilot_test.go
(CleanupDeadServers), snapshot/snapshot_test.go (round-trip + tamper).
"""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import wait_for_leader

from consul_tpu.eventing.cluster import Cluster, ClusterConfig, MemberStatus
from consul_tpu.eventing.snapshot import Snapshotter
from consul_tpu.net.transport import InMemoryNetwork

from test_cluster_agents import make_server, shutdown_all


def run(coro, timeout=90):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


# ---------------------------------------------------------------------------
# snapshotter unit (serf/snapshot.go)
# ---------------------------------------------------------------------------


def test_snapshot_replay_and_compact(tmp_path):
    path = tmp_path / "serf.snapshot"
    s = Snapshotter(path)
    s.alive("a", "mem://a")
    s.alive("b", "mem://b")
    s.not_alive("a")
    s.update_clock(5, 9, 2)
    s.close()

    s2 = Snapshotter(path)
    prev = s2.replay()
    assert prev.alive == {"b": "mem://b"}
    assert (prev.clock, prev.event_clock, prev.query_clock) == (5, 9, 2)
    assert not prev.left

    # Compaction rewrites just the live state.
    s2.compact()
    text = path.read_text()
    assert "not-alive" not in text and "alive: b: mem://b" in text
    s2.close()


def test_snapshot_leave_marker_blocks_rejoin(tmp_path):
    path = tmp_path / "serf.snapshot"
    s = Snapshotter(path)
    s.alive("a", "mem://a")
    s.leave()
    s.close()
    prev = Snapshotter(path).replay()
    assert prev.left and prev.alive == {}


# ---------------------------------------------------------------------------
# gossip-plane recovery
# ---------------------------------------------------------------------------

SCALE = 0.02


async def make_serf(net, name, tmp_path=None, **kw):
    kw.setdefault("reconnect_interval_s", 5.0)
    c = Cluster(
        ClusterConfig(
            name=name,
            interval_scale=SCALE,
            snapshot_path=str(tmp_path / f"{name}.snap") if tmp_path else None,
            **kw,
        ),
        net.new_transport(f"mem://{name}"),
    )
    await c.start()
    return c


class TestGossipRecovery:
    async def test_restart_rejoins_from_snapshot(self, tmp_path):
        net = InMemoryNetwork()
        c1 = await make_serf(net, "n1", tmp_path)
        c2 = await make_serf(net, "n2", tmp_path)
        c3 = await make_serf(net, "n3", tmp_path)
        await c2.join(["mem://n1"])
        await c3.join(["mem://n1"])
        await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in (c1, c2, c3)),
            msg="3-node serf cluster",
        )
        # Crash n3 (no leave) and bring it back with a fresh Cluster on
        # the same snapshot file: it must rejoin WITHOUT an explicit
        # join call (snapshot.go AliveNodes auto-rejoin).
        await c3.shutdown()
        c3b = await make_serf(net, "n3", tmp_path)
        assert c3b.previous is not None and c3b.previous.alive
        n = await c3b.auto_rejoin()
        assert n >= 1
        await wait_until(
            lambda: len(c3b.alive_members()) == 3,
            msg="restarted node sees everyone",
        )
        # Lamport clocks continued from the snapshot (no time travel).
        assert c3b.event_clock.time() >= 1
        await c1.shutdown()
        await c2.shutdown()
        await c3b.shutdown()

    async def test_graceful_leave_blocks_auto_rejoin(self, tmp_path):
        net = InMemoryNetwork()
        c1 = await make_serf(net, "m1", tmp_path)
        c2 = await make_serf(net, "m2", tmp_path)
        await c2.join(["mem://m1"])
        await wait_until(lambda: len(c2.alive_members()) == 2, msg="joined")
        await c2.leave()
        await c2.shutdown()
        c2b = await make_serf(net, "m2", tmp_path)
        assert await c2b.auto_rejoin() == 0  # left gracefully: stay out
        await c1.shutdown()
        await c2b.shutdown()

    async def test_reconnect_loop_recovers_failed_member(self, tmp_path):
        net = InMemoryNetwork()
        c1 = await make_serf(net, "r1", None, reconnect_interval_s=3.0)
        c2 = await make_serf(net, "r2", None, reconnect_interval_s=3.0)
        await c2.join(["mem://r1"])
        await wait_until(lambda: len(c1.alive_members()) == 2, msg="joined")
        # r2 crashes; r1 declares it failed.
        await c2.shutdown()
        await wait_until(
            lambda: c1.members["r2"].status == MemberStatus.FAILED,
            timeout=30,
            msg="r2 marked failed",
        )
        # r2 comes back at the same address but does NOT join; r1's
        # reconnect loop re-establishes contact (serf.go:1547-1612).
        c2b = await make_serf(net, "r2", None, reconnect_interval_s=3.0)
        await wait_until(
            lambda: c1.members["r2"].status == MemberStatus.ALIVE
            and len(c2b.alive_members()) == 2,
            timeout=30,
            msg="reconnect loop recovered r2",
        )
        await c1.shutdown()
        await c2b.shutdown()


# ---------------------------------------------------------------------------
# autopilot
# ---------------------------------------------------------------------------


class TestAutopilot:
    async def test_dead_server_pruned_from_raft(self):
        net = InMemoryNetwork()
        servers = [
            make_server(net, f"s{i}", expect=3,
                        autopilot_interval_s=0.3, autopilot_grace_s=0.5)
            for i in range(3)
        ]
        for s in servers:
            await s.start()
        for s in servers[1:]:
            await s.join(["s0:gossip"])
        leader = await wait_for_leader(servers)
        assert len(leader.raft.voters) == 3
        victim = next(s for s in servers if not s.is_leader())
        await victim.shutdown()
        await wait_until(
            lambda: len(leader.raft.voters) == 2
            and victim.node_id not in leader.raft.voters,
            timeout=30,
            msg="autopilot pruned the dead server",
        )
        await shutdown_all(*(s for s in servers if s is not victim))


# ---------------------------------------------------------------------------
# user snapshot save/restore
# ---------------------------------------------------------------------------


def test_archive_roundtrip_and_tamper_detection():
    from consul_tpu.agent.snapshot import (
        SnapshotError,
        read_archive,
        write_archive,
    )

    state = {"kvs": [{"key": "a", "value": b"1"}], "index": 42}
    blob = write_archive(state, index=42, term=3, node="s0")
    got, meta = read_archive(blob)
    assert got == state
    assert meta["index"] == 42 and meta["term"] == 3 and meta["node"] == "s0"

    # Flip one byte inside the gzip payload: checksum must catch it.
    import gzip
    import io

    raw = bytearray(gzip.decompress(blob))
    # Flip a byte of state.bin's CONTENT (tar content starts 512 bytes
    # past the file's header block).
    content = raw.find(b"state.bin") + 512
    raw[content + 4] ^= 0xFF
    tampered = gzip.compress(bytes(raw))
    with pytest.raises(SnapshotError):
        read_archive(tampered)


class TestSnapshotEndpoint:
    async def test_save_wipe_restore_roundtrip(self):
        net = InMemoryNetwork()
        servers = [make_server(net, f"s{i}", expect=3) for i in range(3)]
        for s in servers:
            await s.start()
        for s in servers[1:]:
            await s.join(["s0:gossip"])
        leader = await wait_for_leader(servers)
        addr = f"{leader.node_id}:rpc"

        for i in range(5):
            await leader.rpc_client.call(
                addr, "KVS.Apply",
                {"op": "set", "entry": {"key": f"app/k{i}",
                                        "value": f"v{i}".encode()}},
            )
        await leader.rpc_client.call(
            addr, "Catalog.Register",
            {"node": "n1", "address": "10.0.0.1",
             "service": {"id": "web1", "service": "web", "port": 80}},
        )

        out = await leader.rpc_client.call(addr, "Snapshot.Save", {})
        blob = out["archive"]
        assert isinstance(blob, bytes) and out["index"] > 0

        # Wipe: delete everything, then restore the archive.
        await leader.rpc_client.call(
            addr, "KVS.Apply", {"op": "delete-tree", "entry": {"key": ""}}
        )
        assert leader.store.kv_list("")[1] == []

        res = await leader.rpc_client.call(
            addr, "Snapshot.Restore", {"archive": blob}
        )
        assert res["result"] is True

        # Every replica has the snapshot's world again.
        await wait_until(
            lambda: all(
                len(s.store.kv_list("app/")[1]) == 5 for s in servers
            ),
            msg="kv restored on every server",
        )
        assert leader.store.kv_get("app/k3")[1]["value"] == b"v3"
        _, rows = leader.store.check_service_nodes("web")
        assert rows and rows[0]["service"]["id"] == "web1"

        # Restores forwarded from a follower work too (body intact).
        follower = next(s for s in servers if not s.is_leader())
        res2 = await follower.rpc_client.call(
            f"{follower.node_id}:rpc", "Snapshot.Restore", {"archive": blob}
        )
        assert res2["result"] is True
        await shutdown_all(*servers)


class TestAutopilotPromotion:
    async def test_late_joiner_stages_then_promotes(self):
        """A server joining an established cluster enters raft as a
        NON-voter and is promoted only after the stabilization window
        of continuous health (autopilot.go promoteStableServers)."""
        from test_cluster_agents import make_server as mk

        net = InMemoryNetwork()
        servers = [
            mk(net, f"s{i}", expect=3,
               autopilot_server_stabilization_s=1.0)
            for i in range(3)
        ]
        for s in servers:
            await s.start()
        for s in servers[1:]:
            await s.join(["s0:gossip"])
        leader = await wait_for_leader(servers)

        late = mk(net, "s9", expect=3,
                  autopilot_server_stabilization_s=1.0)
        await late.start()
        await late.join(["s0:gossip"])
        # Phase 1: staged as a non-voter (replicated to, no quorum).
        await wait_until(
            lambda: "s9" in leader.raft.non_voters,
            timeout=10, msg="late joiner staged as non-voter",
        )
        assert "s9" not in leader.raft.voters
        # Phase 2: promoted after the stabilization window.
        await wait_until(
            lambda: "s9" in leader.raft.voters
            and "s9" not in leader.raft.non_voters,
            timeout=15, msg="stable staging server promoted",
        )
        await shutdown_all(late, *servers)

    async def test_autopilot_config_and_health_surface(self):
        """/v1/operator/autopilot/{configuration,health}
        (operator_autopilot_endpoint.go)."""
        from test_http_dns import dev_stack, http_call

        async with dev_stack() as (agent, addr, _dns, _dns_addr):
            st, _, cfg = await http_call(
                addr, "GET", "/v1/operator/autopilot/configuration")
            assert st == 200 and cfg["CleanupDeadServers"] is True
            # Set: flip cleanup off, raise stabilization.
            st, _, ok = await http_call(
                addr, "PUT", "/v1/operator/autopilot/configuration",
                b'{"CleanupDeadServers": false, '
                b'"ServerStabilizationTimeS": 99}')
            assert st == 200 and ok is True
            st, _, cfg = await http_call(
                addr, "GET", "/v1/operator/autopilot/configuration")
            assert cfg["CleanupDeadServers"] is False
            assert cfg["ServerStabilizationTimeS"] == 99
            # The running server absorbed the override.
            assert agent.delegate.config.autopilot_cleanup_dead_servers \
                is False
            # Health roll-up: single healthy voter.
            st, _, health = await http_call(
                addr, "GET", "/v1/operator/autopilot/health")
            assert st == 200 and health["Healthy"] is True
            assert health["Servers"][0]["Voter"] is True
            assert health["Servers"][0]["Healthy"] is True
            assert health["FailureTolerance"] == 0
