"""The owned per-(round, node) randomness plane (ops/sampling.py).

Three property families:

  * the OWNED contract — a draw for global id i depends only on
    ``(site_key, i)``, so any block of ids reproduces the full
    population's rows exactly (what every sharded twin's bit-equality
    rides on), including non-contiguous and permuted blocks;
  * stream independence — draws for distinct (round, node, site,
    universe) coordinates are statistically independent, checked
    against plain-numpy moment/correlation references;
  * the counter-based round derivation — per-round keys are
    ``fold_in(scan_key, t)``, so trajectories are PREFIX-STABLE in the
    step count (a shorter scan is a prefix of a longer one), and the
    per-chip draw-plane footprint of a block is ~n/D (the J6 draw-term
    pin the composed max-U acceptance rides on).

compact_to_budget (ops/compact.py) is property-tested here too — it is
the one budget-compaction form every call site now shares.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consul_tpu.ops import (
    bernoulli_mask,
    bernoulli_mask_owned,
    compact_to_budget,
    owned_keys,
    owned_randint,
    owned_uniform,
    poissonized_arrivals,
    poissonized_arrivals_owned,
    sample_alive_peers,
    sample_alive_peers_owned,
    sample_peers,
    sample_peers_owned,
    sample_probe_targets,
    sample_probe_targets_owned,
)

KEY = jax.random.PRNGKey(1234)
N = 96


def _ids(kind):
    if kind == "contiguous":
        return jnp.arange(24, 72, dtype=jnp.int32)
    if kind == "strided":
        return jnp.arange(0, N, 3, dtype=jnp.int32)
    return jnp.asarray([7, 3, 91, 0, 44, 44, 12], jnp.int32)  # permuted+dup


# ---------------------------------------------------------------------------
# The owned contract: block rows == full-population rows.
# ---------------------------------------------------------------------------


class TestOwnedContract:
    @pytest.mark.parametrize("kind", ["contiguous", "strided", "permuted"])
    def test_owned_uniform_matches_full(self, kind):
        ids = _ids(kind)
        full = owned_uniform(KEY, jnp.arange(N, dtype=jnp.int32), (5,))
        own = owned_uniform(KEY, ids, (5,))
        assert np.array_equal(np.asarray(full)[np.asarray(ids)],
                              np.asarray(own))

    @pytest.mark.parametrize("kind", ["contiguous", "strided", "permuted"])
    def test_samplers_match_full(self, kind):
        ids = _ids(kind)
        idx = np.asarray(ids)
        pairs = [
            (sample_peers(KEY, N, 4),
             sample_peers_owned(KEY, ids, N, 4)),
            (sample_probe_targets(KEY, N),
             sample_probe_targets_owned(KEY, ids, N)),
            (bernoulli_mask(KEY, (N, 3), 0.7),
             bernoulli_mask_owned(KEY, ids, (3,), 0.7)),
            (owned_randint(KEY, jnp.arange(N, dtype=jnp.int32), (2,),
                           0, 17),
             owned_randint(KEY, ids, (2,), 0, 17)),
        ]
        alive = jnp.arange(N) % 5 != 0
        pairs.append((sample_alive_peers(KEY, alive, 4),
                      sample_alive_peers_owned(KEY, ids, alive, 4)))
        lam_full = jnp.linspace(0.1, 2.0, N)
        pairs.append((poissonized_arrivals(KEY, lam_full),
                      poissonized_arrivals_owned(KEY, ids,
                                                 lam_full[ids])))
        for full, own in pairs:
            assert np.array_equal(np.asarray(full)[idx], np.asarray(own))

    def test_sharded_block_union_is_full_population(self):
        # The D-shard picture verbatim: disjoint contiguous blocks
        # re-assemble the unsharded draw plane exactly.
        full = np.asarray(sample_peers(KEY, N, 3))
        for d in (2, 4):
            blk = N // d
            parts = [
                np.asarray(sample_peers_owned(
                    KEY, me * blk + jnp.arange(blk, dtype=jnp.int32),
                    N, 3))
                for me in range(d)
            ]
            assert np.array_equal(np.concatenate(parts), full)

    def test_self_exclusion_and_alive_pool(self):
        tgt = np.asarray(sample_peers(KEY, N, 6))
        assert (tgt != np.arange(N)[:, None]).all()
        assert ((tgt >= 0) & (tgt < N)).all()
        alive = jnp.arange(N) % 4 != 1
        at = np.asarray(sample_alive_peers(KEY, alive, 6))
        al = np.asarray(alive)
        assert al[at].all()
        assert (at != np.arange(N)[:, None])[al].all()


# ---------------------------------------------------------------------------
# Stream independence (numpy references on moments/correlations).
# ---------------------------------------------------------------------------


class TestStreamIndependence:
    def _round_site_plane(self, scan_key, t, site, cols=64):
        """The model derivation verbatim: round key = fold_in(scan_key,
        t), site keys = split(round key, 7), node streams owned."""
        k_site = jax.random.split(jax.random.fold_in(scan_key, t), 7)[site]
        return np.asarray(owned_uniform(
            k_site, jnp.arange(N, dtype=jnp.int32), (cols,)
        ))

    def test_reproducible_and_distinct_across_coordinates(self):
        base = self._round_site_plane(KEY, 3, 2)
        assert np.array_equal(base, self._round_site_plane(KEY, 3, 2))
        for other in (
            self._round_site_plane(KEY, 4, 2),       # round moved
            self._round_site_plane(KEY, 3, 5),       # site moved
            self._round_site_plane(jax.random.fold_in(KEY, 1), 3, 2),
        ):                                           # universe moved
            assert not np.array_equal(base, other)
            # distinct coordinates are fresh streams, not shifts: no
            # row collides either
            assert not (base == other).all(axis=1).any()

    def test_uniform_moments_match_numpy_reference(self):
        # Pool draws across rounds x nodes: mean/var of U(0,1) within
        # 5 sigma of the numpy reference bounds.
        planes = np.stack([
            self._round_site_plane(KEY, t, 1) for t in range(4)
        ])
        m = planes.size
        assert abs(planes.mean() - 0.5) < 5 * np.sqrt(1 / 12 / m)
        assert abs(planes.var() - 1 / 12) < 5 * np.sqrt(1 / 180 / m)

    def test_rounds_and_nodes_uncorrelated(self):
        a = self._round_site_plane(KEY, 0, 0).ravel()
        b = self._round_site_plane(KEY, 1, 0).ravel()
        # Pearson r ~ N(0, 1/sqrt(m)) under independence.
        r_rounds = np.corrcoef(a, b)[0, 1]
        assert abs(r_rounds) < 5 / np.sqrt(a.size)
        plane = self._round_site_plane(KEY, 0, 3)
        r_nodes = np.corrcoef(plane[:-1].ravel(), plane[1:].ravel())[0, 1]
        assert abs(r_nodes) < 5 / np.sqrt(plane[:-1].size)

    def test_peer_targets_uniform_over_population(self):
        # Frequency reference: pooled target counts over many rounds
        # are Binomial(m, 1/(n-1)) per (receiver != sender) cell.
        counts = np.zeros(N)
        rounds = 40
        for t in range(rounds):
            k = jax.random.split(jax.random.fold_in(KEY, t), 7)[1]
            tgt = np.asarray(sample_peers(k, N, 4)).ravel()
            counts += np.bincount(tgt, minlength=N)
        m = rounds * N * 4
        p = 1 / (N - 1)
        sigma = np.sqrt(m * p * (1 - p))
        assert (np.abs(counts - m * p) < 6 * sigma).all()


# ---------------------------------------------------------------------------
# Counter-based rounds: prefix stability + the ~n/D draw-term pin.
# ---------------------------------------------------------------------------


class TestCounterRounds:
    def test_scan_prefix_stability(self):
        # fold_in(scan_key, t) round keys make a shorter run a strict
        # prefix of a longer one — split(key, steps) could not (its
        # keys depend on steps).  Pinned on the cheapest scan family.
        from consul_tpu.models.broadcast import (
            BroadcastConfig,
            broadcast_init,
        )
        from consul_tpu.sim.engine import broadcast_scan

        cfg = BroadcastConfig(n=128, fanout=3, loss=0.2)
        key = jax.random.PRNGKey(9)
        _, short = broadcast_scan(broadcast_init(cfg), key, cfg, 6)
        _, full = broadcast_scan(broadcast_init(cfg), key, cfg, 14)
        assert np.array_equal(np.asarray(short), np.asarray(full)[:6])

    def test_draw_plane_footprint_scales_as_n_over_d(self):
        # The J6 draw-term pin: one round's draw planes for an owned
        # block, traced at blk = n/D — the term the replicated design
        # paid at O(n) per chip for every D.  Exact 1/D scaling up to
        # the vmap key constant.
        n, fanout, k_slots = 4096, 4, 32

        def draws(blk):
            def f(key):
                ids = jnp.arange(blk, dtype=jnp.int32)
                k1, k2, k3 = jax.random.split(key, 3)
                return (sample_peers_owned(k1, ids, n, fanout),
                        bernoulli_mask_owned(k2, ids, (fanout,), 0.9),
                        owned_uniform(k3, ids, (k_slots,)))

            from consul_tpu.analysis.jaxlint import estimate_peak

            return estimate_peak(
                jax.make_jaxpr(f)(jax.random.PRNGKey(0))
            ).chip_bytes

        full = draws(n)
        for d in (2, 4, 8):
            ratio = draws(n // d) / full
            assert abs(ratio - 1 / d) < 0.15 / d, (d, ratio)


# ---------------------------------------------------------------------------
# compact_to_budget: the one budget-compaction form (numpy reference).
# ---------------------------------------------------------------------------


class TestCompactToBudget:
    def _reference(self, want, budget, first=None):
        order = np.flatnonzero(want & first) if first is not None else None
        if first is None:
            admitted = np.flatnonzero(want)[:budget]
        else:
            admitted = np.concatenate([
                np.flatnonzero(want & first),
                np.flatnonzero(want & ~first),
            ])[:budget]
        kept = np.zeros(len(want), bool)
        kept[admitted] = True
        return admitted, kept, int(want.sum() - len(admitted))

    @pytest.mark.parametrize("seed", range(4))
    @pytest.mark.parametrize("budget", [1, 7, 40, 64])
    def test_matches_reference(self, seed, budget):
        rng = np.random.RandomState(seed)
        want = rng.rand(64) < rng.choice([0.05, 0.4, 0.95])
        idx, taken, kept, dropped = compact_to_budget(
            jnp.asarray(want), budget
        )
        adm, kept_ref, dropped_ref = self._reference(want, budget)
        assert np.array_equal(np.asarray(idx)[np.asarray(taken)], adm)
        assert np.array_equal(np.asarray(kept), kept_ref)
        assert int(dropped) == dropped_ref
        # Empty slots are gather-safe (clamped in range).
        assert (np.asarray(idx) < 64).all() and (np.asarray(idx) >= 0).all()

    @pytest.mark.parametrize("seed", range(4))
    def test_two_class_admission_matches_reference(self, seed):
        rng = np.random.RandomState(100 + seed)
        want = rng.rand(96) < 0.5
        first = rng.rand(96) < 0.3
        budget = 24
        idx, taken, kept, dropped = compact_to_budget(
            jnp.asarray(want), budget, jnp.asarray(first)
        )
        adm, kept_ref, dropped_ref = self._reference(want, budget, first)
        assert np.array_equal(np.asarray(idx)[np.asarray(taken)], adm)
        assert np.array_equal(np.asarray(kept), kept_ref)
        assert int(dropped) == dropped_ref
        # Priority property: no admitted class-1 entry while a class-0
        # entry dropped.
        k = np.asarray(kept)
        if (want & first & ~k).any():
            assert not (want & ~first & k).any()

    def test_degenerate_streams(self):
        none = jnp.zeros((16,), bool)
        idx, taken, kept, dropped = compact_to_budget(none, 4)
        assert not np.asarray(taken).any()
        assert int(dropped) == 0
        all_w = jnp.ones((16,), bool)
        idx, taken, kept, dropped = compact_to_budget(all_w, 16)
        assert np.array_equal(np.asarray(idx), np.arange(16))
        assert int(dropped) == 0
