"""WAN federation states + mesh-gateway locator.

Parity model: agent/consul/federation_state_endpoint.go (Apply always
lands in the primary; Get/List/ListMeshGateways reads),
leader_federation_state_ae.go (each DC's leader publishes its own
mesh-gateway set to the primary), federation_state_replication.go
(secondaries pull the full map back), gateway_locator.go (local LAN
gateways vs remote WAN gateways).
"""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import requires_crypto
from helpers import wait_for_leader

from consul_tpu.agent.server import Server, ServerConfig
from consul_tpu.net.transport import InMemoryNetwork


def make_dc_server(lan_net, wan_net, rpc_net, name, dc, expect):
    cfg = ServerConfig(
        node_name=name,
        datacenter=dc,
        primary_datacenter="dc1",
        bootstrap_expect=expect,
        gossip_interval_scale=0.05,
        reconcile_interval_s=0.2,
        coordinate_update_period_s=0.1,
        session_ttl_sweep_s=0.1,
        flood_interval_s=0.1,
        replication_interval_s=0.3,
        federation_state_ae_interval_s=0.3,
    )
    return Server(
        cfg,
        gossip_transport=lan_net.new_transport(f"{name}.{dc}:gossip"),
        rpc_transport=rpc_net.new_transport(f"{name}.{dc}:rpc"),
        wan_transport=wan_net.new_transport(f"{name}.{dc}:wan"),
    )


async def start_two_dcs():
    lan1, lan2 = InMemoryNetwork(), InMemoryNetwork()
    wan, rpc = InMemoryNetwork(), InMemoryNetwork()
    dc1 = [make_dc_server(lan1, wan, rpc, "a0", "dc1", 1)]
    dc2 = [make_dc_server(lan2, wan, rpc, "b0", "dc2", 1)]
    for s in dc1 + dc2:
        await s.start()
    await wait_for_leader(dc1)
    await wait_for_leader(dc2)
    assert await dc2[0].join_wan(["a0.dc1:wan"]) == 1
    return dc1, dc2


async def register_gateway(server, node, addr, lan_port, wan_addr,
                           wan_port, svc_id="gw1"):
    """Register a wan-federation mesh gateway into a DC's catalog."""
    await server.rpc_server.dispatch_local("Catalog.Register", {
        "node": node,
        "address": addr,
        "service": {
            "id": svc_id,
            "service": "mesh-gateway",
            "kind": "mesh-gateway",
            "port": lan_port,
            "tags": [],
            "meta": {"consul-wan-federation": "1"},
            "tagged_addresses": {
                "wan": {"address": wan_addr, "port": wan_port},
            },
        },
    })


async def shutdown_all(*servers):
    for s in servers:
        await s.shutdown()
    await asyncio.sleep(0)


class TestFederationStates:
    async def test_apply_routes_to_primary_and_replicates_back(self):
        dc1, dc2 = await start_two_dcs()
        p, s = dc1[0], dc2[0]
        # A write submitted in the SECONDARY must land in the primary's
        # raft (federation_state_endpoint.go:25-28), then replicate back.
        out = await s.rpc_server.dispatch_local("FederationState.Apply", {
            "op": "upsert",
            "state": {"datacenter": "dc3", "mesh_gateways": []},
        })
        assert out["result"] is True
        _, rec = p.store.federation_state_get("dc3")
        assert rec is not None
        await wait_until(
            lambda: s.store.federation_state_get("dc3")[1] is not None,
            timeout=10, msg="secondary replicated the federation state",
        )
        # Delete flows the same way and the replicator prunes.
        await s.rpc_server.dispatch_local("FederationState.Apply", {
            "op": "delete", "state": {"datacenter": "dc3"},
        })
        assert p.store.federation_state_get("dc3")[1] is None
        await wait_until(
            lambda: s.store.federation_state_get("dc3")[1] is None,
            timeout=10, msg="secondary pruned the deleted state",
        )
        await shutdown_all(p, s)

    async def test_ae_publishes_gateways_and_locator_resolves(self):
        dc1, dc2 = await start_two_dcs()
        p, s = dc1[0], dc2[0]
        await register_gateway(p, "gwnode1", "10.1.0.9", 8443,
                               "198.51.100.1", 443)
        await register_gateway(s, "gwnode2", "10.2.0.9", 8443,
                               "198.51.100.2", 443)

        # Each DC's AE loop pushes its own state to the PRIMARY.
        await wait_until(
            lambda: p.store.federation_state_get("dc1")[1] is not None
            and p.store.federation_state_get("dc2")[1] is not None,
            timeout=10, msg="primary holds both DCs' federation states",
        )
        # The secondary pulls the full map back.
        await wait_until(
            lambda: s.store.federation_state_get("dc1")[1] is not None,
            timeout=10, msg="secondary learned the primary's gateways",
        )

        # Locator: remote DC resolves to WAN addrs, own DC to LAN addrs.
        assert s.gateway_locator.gateways_for_dc("dc1") == \
            ["198.51.100.1:443"]
        assert s.gateway_locator.local_gateways() == ["10.2.0.9:8443"]
        assert p.gateway_locator.gateways_for_dc("dc2") == \
            ["198.51.100.2:443"]
        assert set(s.gateway_locator.known_datacenters()) == {"dc1", "dc2"}

        # ListMeshGateways aggregates the map (the data plane's view).
        out = await s.rpc_server.dispatch_local(
            "FederationState.ListMeshGateways", {})
        assert set(out["gateways"]) == {"dc1", "dc2"}
        assert out["gateways"]["dc1"][0]["service"] == "mesh-gateway"

        # Blocking read surface works.
        got = await s.rpc_server.dispatch_local(
            "FederationState.Get", {"target_dc": "dc1"})
        assert got["state"]["datacenter"] == "dc1"
        assert len(got["state"]["mesh_gateways"]) == 1
        await shutdown_all(p, s)

    async def test_non_wanfed_gateways_excluded(self):
        """Only gateways carrying the consul-wan-federation=1 meta are
        published (gateway_locator.go:44-47)."""
        dc1, dc2 = await start_two_dcs()
        p, s = dc1[0], dc2[0]
        # A mesh gateway WITHOUT the wanfed meta.
        await p.rpc_server.dispatch_local("Catalog.Register", {
            "node": "gwnode1", "address": "10.1.0.9",
            "service": {"id": "gw-plain", "service": "mesh-gateway",
                        "kind": "mesh-gateway", "port": 8443, "tags": []},
        })
        assert p.gateway_locator.local_gateways() == []
        assert p.gateway_locator.build_own_state()["mesh_gateways"] == []
        # And it never reaches the secondary through AE.
        await asyncio.sleep(1.0)
        _, rec = s.store.federation_state_get("dc1")
        assert rec is None or rec.get("mesh_gateways") == []
        await shutdown_all(p, s)


class TestFederationHTTP:
    async def test_http_surface(self):
        from test_http_dns import http_call

        from consul_tpu.agent.agent import Agent, AgentConfig
        from consul_tpu.agent.http import HTTPApi

        lan, rpc = InMemoryNetwork(), InMemoryNetwork()
        agent = Agent(
            AgentConfig(node_name="dev", bootstrap_expect=1,
                        gossip_interval_scale=0.05, sync_interval_s=0.3,
                        sync_retry_interval_s=0.2,
                        reconcile_interval_s=0.2),
            gossip_transport=lan.new_transport("dev:gossip"),
            rpc_transport=rpc.new_transport("dev:rpc"),
        )
        await agent.start()
        await wait_until(lambda: agent.delegate.is_leader(), msg="leader")
        api = HTTPApi(agent)
        addr = await api.start()
        try:
            await agent.delegate.rpc_server.dispatch_local(
                "FederationState.Apply", {
                    "op": "upsert",
                    "state": {"datacenter": "dc9", "mesh_gateways": [
                        {"service": "mesh-gateway", "id": "g",
                         "node": "n", "address": "10.9.0.1", "port": 8443,
                         "tags": []},
                    ]},
                })
            st, _, rows = await http_call(
                addr, "GET", "/v1/internal/federation-states")
            assert st == 200 and rows[0]["Datacenter"] == "dc9"
            st, _, one = await http_call(
                addr, "GET", "/v1/internal/federation-state/dc9")
            assert st == 200 and one["State"]["Datacenter"] == "dc9"
            st, _, gws = await http_call(
                addr, "GET", "/v1/internal/federation-states/mesh-gateways")
            # DC names are data keys — they must NOT be camelized.
            assert st == 200 and "dc9" in gws
            assert gws["dc9"][0]["Port"] == 8443
            st, _, _x = await http_call(
                addr, "GET", "/v1/internal/federation-state/nope")
            assert st == 404
        finally:
            await api.stop()
            await agent.shutdown()


class TestGatewayRoutedUpstreams:
    @requires_crypto
    async def test_proxycfg_routes_remote_target_through_gateways(self):
        from test_http_dns import dev_stack

        async def scenario(mode):
            async with dev_stack() as (agent, addr, _dns, _dns_addr):
                srv = agent.delegate
                # Chain config: db redirects to dc2; mesh-gateway mode
                # comes from service-defaults (compile.go:905-930).
                for entry in (
                    {"kind": "service-defaults", "name": "db",
                     "mesh_gateway": mode},
                    {"kind": "service-resolver", "name": "db",
                     "redirect": {"datacenter": "dc2"}},
                ):
                    await srv.rpc_server.dispatch_local(
                        "ConfigEntry.Apply", {"op": "set", "entry": entry})
                # A local mesh gateway in the catalog.  Deliberately
                # neither named "mesh-gateway" nor wanfed-tagged:
                # upstream routing discovers gateways by KIND (the
                # reference's kind-indexed catalog watch), and the
                # wanfed:1 meta gates only the server plane's
                # gateway_locator, not data-plane endpoints.
                await srv.rpc_server.dispatch_local("Catalog.Register", {
                    "node": "gwnode", "address": "10.0.0.7",
                    "service": {
                        "id": "mgw", "service": "my-gateway",
                        "kind": "mesh-gateway", "port": 8443, "tags": [],
                        "tagged_addresses": {
                            "wan": {"address": "192.0.2.7", "port": 443}},
                    },
                })
                # dc2's federation state (as replication would deliver).
                await srv.rpc_server.dispatch_local(
                    "FederationState.Apply", {
                        "op": "upsert",
                        "state": {"datacenter": "dc2", "mesh_gateways": [
                            {"id": "rgw", "service": "mesh-gateway",
                             "kind": "mesh-gateway", "node": "rnode",
                             "address": "10.2.0.7", "port": 8443,
                             "tags": [],
                             "meta": {"consul-wan-federation": "1"},
                             "tagged_addresses": {"wan": {
                                 "address": "198.51.100.7", "port": 443}}},
                        ]},
                    })
                agent.add_service({
                    "service": "web-proxy", "kind": "connect-proxy",
                    "port": 0,
                    "proxy": {"destination_service": "web",
                              "upstreams": [{"destination_name": "db"}]},
                })
                out = await agent.proxycfg.wait("web-proxy", 0, timeout=10)
                assert out is not None
                _, snap = out
                insts = snap["upstreams"]["db"]["instances"]["db@dc2"]
                assert len(insts) == 1 and insts[0]["mesh_gateway"]
                return insts[0]

        # local mode: dial this DC's own gateway at its LAN address.
        ep = await scenario("local")
        assert (ep["address"], ep["port"]) == ("10.0.0.7", 8443)
        # remote mode: dial the TARGET DC's gateway at its WAN address.
        ep = await scenario("remote")
        assert (ep["address"], ep["port"]) == ("198.51.100.7", 443)

    async def test_ae_prunes_after_last_gateway_leaves(self):
        dc1, dc2 = await start_two_dcs()
        p, s = dc1[0], dc2[0]
        await register_gateway(s, "gwnode2", "10.2.0.9", 8443,
                               "198.51.100.2", 443)
        await wait_until(
            lambda: (p.store.federation_state_get("dc2")[1] or {}
                     ).get("mesh_gateways"),
            timeout=10, msg="primary learned dc2's gateway",
        )
        # The gateway disappears from dc2's catalog.
        await s.rpc_server.dispatch_local("Catalog.Deregister", {
            "node": "gwnode2", "service_id": "gw1",
        })
        # AE must publish the EMPTY set — stale addresses are pruned
        # everywhere, not kept forever.
        await wait_until(
            lambda: (p.store.federation_state_get("dc2")[1] or {}
                     ).get("mesh_gateways") == [],
            timeout=10, msg="primary pruned dc2's dead gateway",
        )
        await shutdown_all(p, s)


def test_services_by_kind_passing_only_drops_failing_gateway():
    """A mesh gateway with a critical check must fall out of the
    kind-indexed health view the data plane watches (state/catalog.go
    CheckServiceNodes semantics)."""
    from consul_tpu.store.state import HEALTH_CRITICAL, StateStore

    store = StateStore()
    for i, status in enumerate(("passing", HEALTH_CRITICAL)):
        store.ensure_registration(i + 1, {
            "node": f"gw{i}", "address": f"10.0.0.{i}",
            "service": {"id": "mgw", "service": "mesh-gateway",
                        "kind": "mesh-gateway", "port": 8443, "tags": []},
            "check": {"check_id": "serf", "status": status,
                      "service_id": ""},
        })
    _, all_gws = store.services_by_kind("mesh-gateway")
    assert {g["node"] for g in all_gws} == {"gw0", "gw1"}
    _, live = store.services_by_kind("mesh-gateway", passing_only=True)
    assert {g["node"] for g in live} == {"gw0"}
