"""Vivaldi coordinate tests: convergence to ground-truth geometry,
error decay, invariants (height floor, validity)."""

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.models import (
    VivaldiConfig,
    euclidean_rtt_model,
    vivaldi_init,
    vivaldi_round,
)
from consul_tpu.models.vivaldi import raw_distance


def run(cfg, positions, rounds, seed=0):
    st = vivaldi_init(cfg)
    rtt_fn = euclidean_rtt_model(positions)
    key = jax.random.PRNGKey(seed)
    step = jax.jit(lambda s, k: vivaldi_round(s, k, cfg, rtt_fn))
    for i in range(rounds):
        st = step(st, jax.random.fold_in(key, i))
    return st


def rel_rtt_error(st, positions, n_pairs=2000, seed=99):
    """Median relative error of estimated vs true RTT over random pairs."""
    rng = np.random.default_rng(seed)
    n = positions.shape[0]
    i = rng.integers(0, n, n_pairs)
    j = (i + 1 + rng.integers(0, n - 1, n_pairs)) % n
    true = np.asarray(
        jnp.sqrt(jnp.sum((positions[i] - positions[j]) ** 2, axis=-1))
    )
    est = np.asarray(
        raw_distance(st.vec[i], st.height[i], st.vec[j], st.height[j])
    )
    return float(np.median(np.abs(est - true) / np.maximum(true, 1e-9)))


def test_coordinates_converge_to_geometry():
    # 64 nodes on a ring with ~10-50 ms RTTs; after a few hundred probe
    # rounds the coordinate system should predict pairwise RTTs well
    # (Vivaldi paper: median relative error ~ 10-25%).
    n = 64
    theta = jnp.linspace(0, 2 * jnp.pi, n, endpoint=False)
    positions = 0.025 * jnp.stack([jnp.cos(theta), jnp.sin(theta)], axis=-1)
    cfg = VivaldiConfig(n=n)
    st = run(cfg, positions, rounds=600)
    err = rel_rtt_error(st, positions)
    assert err < 0.30, f"median relative RTT error {err:.2%}"


def test_error_decays_from_max():
    n = 32
    positions = jax.random.uniform(jax.random.PRNGKey(1), (n, 3)) * 0.05
    cfg = VivaldiConfig(n=n)
    st0 = vivaldi_init(cfg)
    st = run(cfg, positions, rounds=200)
    assert float(jnp.mean(st.error)) < float(jnp.mean(st0.error))
    assert float(jnp.max(st.error)) <= cfg.vivaldi_error_max + 1e-6


def test_height_floor_and_validity():
    n = 32
    positions = jax.random.uniform(jax.random.PRNGKey(2), (n, 2)) * 0.02
    cfg = VivaldiConfig(n=n, rtt_jitter=0.2)
    st = run(cfg, positions, rounds=300)
    assert float(jnp.min(st.height)) >= cfg.height_min - 1e-12
    for leaf in st:
        assert bool(jnp.all(jnp.isfinite(jnp.asarray(leaf, jnp.float32))))


def test_jitter_tolerated():
    n = 64
    positions = jax.random.uniform(jax.random.PRNGKey(3), (n, 3)) * 0.04
    cfg = VivaldiConfig(n=n, rtt_jitter=0.1)
    st = run(cfg, positions, rounds=600)
    assert rel_rtt_error(st, positions) < 0.45
