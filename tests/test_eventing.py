"""Eventing (serf-equivalent) tests: user events, Lamport dedup, queries,
tags, intents — over the in-memory network at 50x speed."""

import asyncio

from helpers import wait_until

from consul_tpu.eventing import (
    Cluster,
    ClusterConfig,
    EventType,
    LamportClock,
)
from consul_tpu.eventing.cluster import MemberStatus
from consul_tpu.net import InMemoryNetwork

SCALE = 0.02


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def make_cluster(net, n, tags=None, **kw):
    out = []
    for i in range(n):
        t = net.new_transport(f"mem://e{i}")
        c = Cluster(
            ClusterConfig(
                name=f"e{i}",
                tags=(tags or {}) | {"idx": str(i)},
                interval_scale=SCALE,
                **kw,
            ),
            t,
        )
        await c.start()
        out.append(c)
    for c in out[1:]:
        assert await c.join(["mem://e0"]) == 1
    return out


async def collect_events(cluster, etype, bucket):
    while True:
        ev = await cluster.events.get()
        if ev.type == etype:
            bucket.append(ev)


async def stop_all(cs):
    for c in cs:
        await c.shutdown()


class TestLamport:
    def test_witness_and_increment(self):
        # serf/lamport.go semantics.
        c = LamportClock()
        assert c.time() == 0
        assert c.increment() == 1
        c.witness(41)
        assert c.time() == 42
        c.witness(10)  # older time: no effect
        assert c.time() == 42


def test_user_event_reaches_all_members_once():
    async def main():
        net = InMemoryNetwork()
        cs = await make_cluster(net, 4)
        assert await wait_until(
            lambda: all(len(c.alive_members()) == 4 for c in cs)
        )
        buckets = {c.config.name: [] for c in cs}
        tasks = [
            asyncio.create_task(
                collect_events(c, EventType.USER, buckets[c.config.name])
            )
            for c in cs
        ]
        await cs[0].user_event("deploy", b"v1.2.3")
        ok = await wait_until(
            lambda: all(len(b) >= 1 for b in buckets.values()), timeout=30.0
        )
        assert ok, {k: len(v) for k, v in buckets.items()}
        # Let any duplicate deliveries surface, then check dedup held.
        await asyncio.sleep(1.0)
        for name, b in buckets.items():
            assert len(b) == 1, f"{name} saw {len(b)} copies"
            assert b[0].name == "deploy" and b[0].payload == b"v1.2.3"
        for t in tasks:
            t.cancel()
        await stop_all(cs)

    run(main())


def test_event_size_limit_enforced():
    async def main():
        net = InMemoryNetwork()
        cs = await make_cluster(net, 1)
        try:
            await cs[0].user_event("x", b"y" * 600)
            raise AssertionError("expected ValueError for oversized event")
        except ValueError:
            pass
        await stop_all(cs)

    run(main())


def test_query_collects_responses():
    async def main():
        net = InMemoryNetwork()
        cs = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in cs)
        )

        async def responder(c):
            while True:
                ev = await c.events.get()
                if ev.type == EventType.QUERY and ev.name == "whoami":
                    await ev.query.respond(c.config.name.encode())

        tasks = [asyncio.create_task(responder(c)) for c in cs[1:]]
        result = await cs[0].query("whoami", b"", timeout_s=5.0, want_ack=True)
        names = {a[0] for a in result.responses}
        assert names == {"e1", "e2"}, names
        assert set(result.acks) == {"e1", "e2"}, result.acks
        for t in tasks:
            t.cancel()
        await stop_all(cs)

    run(main())


def test_tags_visible_on_members():
    async def main():
        net = InMemoryNetwork()
        cs = await make_cluster(net, 3, tags={"role": "server"})
        assert await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in cs)
        )
        for c in cs:
            for m in c.alive_members():
                assert m.tags["role"] == "server"
                assert m.tags["idx"] in {"0", "1", "2"}
        await stop_all(cs)

    run(main())


def test_graceful_leave_emits_member_leave_not_failed():
    async def main():
        net = InMemoryNetwork()
        cs = await make_cluster(net, 3)
        assert await wait_until(
            lambda: all(len(c.alive_members()) == 3 for c in cs)
        )
        leaves, fails = [], []
        t1 = asyncio.create_task(
            collect_events(cs[0], EventType.MEMBER_LEAVE, leaves)
        )
        t2 = asyncio.create_task(
            collect_events(cs[0], EventType.MEMBER_FAILED, fails)
        )
        await cs[2].leave()
        await cs[2].shutdown()
        ok = await wait_until(lambda: len(leaves) >= 1, timeout=30.0)
        assert ok
        assert not fails, "graceful leave must not be reported as a failure"
        assert cs[0].members["e2"].status == MemberStatus.LEFT
        t1.cancel()
        t2.cancel()
        await stop_all(cs[:2])

    run(main())


def test_event_convergence_via_push_pull_backstop():
    async def main():
        # Drop all user-event gossip datagrams; the TCP push/pull event
        # buffer exchange must still converge events (delegate.go:173-297).
        from consul_tpu.net import wire

        def drop(payload, src, dst):
            return payload[0] in (
                wire.MessageType.USER,
                wire.MessageType.COMPOUND,
            )

        net = InMemoryNetwork(drop_fn=drop)
        cs = await make_cluster(net, 2)
        assert await wait_until(
            lambda: all(len(c.alive_members()) == 2 for c in cs)
        )
        bucket = []
        t = asyncio.create_task(collect_events(cs[1], EventType.USER, bucket))
        await cs[0].user_event("quiet", b"payload")
        # push/pull interval = 30s * 0.02 = 0.6s scaled.
        ok = await wait_until(lambda: len(bucket) >= 1, timeout=30.0)
        assert ok, "event did not converge via push/pull"
        t.cancel()
        await stop_all(cs)

    run(main())
