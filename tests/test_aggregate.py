"""Distributional-equivalence tests: 'aggregate' (Poissonized receiver)
delivery must reproduce the 'edges' (exact per-message scatter) dynamics.

The aggregation is exact in the large-n limit (multinomial arrival counts
-> independent Poisson); at n=4096 the convergence curves of the two modes
must agree to within a tick or two.  Averaged over seeds to keep the test
stable."""

import dataclasses

import numpy as np

from consul_tpu.models import BroadcastConfig, SwimConfig
from consul_tpu.sim import run_broadcast, run_swim, time_to_fraction
import pytest

N = 4096
SEEDS = range(3)


def _mean_t(reports, frac):
    ts = [time_to_fraction(r.infected, N, frac) for r in reports]
    assert all(t is not None for t in ts)
    return np.mean(ts)


def test_broadcast_modes_agree_on_convergence():
    cfg_e = BroadcastConfig(n=N, fanout=3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=40, seed=s, warmup=False) for s in SEEDS]
    r_a = [run_broadcast(cfg_a, steps=40, seed=s, warmup=False) for s in SEEDS]
    for frac in (0.5, 0.99):
        assert abs(_mean_t(r_e, frac) - _mean_t(r_a, frac)) <= 2.0


def test_broadcast_modes_agree_under_loss():
    cfg_e = BroadcastConfig(n=N, fanout=3, loss=0.3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=60, seed=s, warmup=False) for s in SEEDS]
    r_a = [run_broadcast(cfg_a, steps=60, seed=s, warmup=False) for s in SEEDS]
    assert abs(_mean_t(r_e, 0.99) - _mean_t(r_a, 0.99)) <= 3.0


@pytest.mark.slow  # ~16s at CPU: multi-seed mode-agreement bands
def test_swim_modes_agree_on_detection():
    cfg_e = SwimConfig(n=N, subject=3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    sus_e, sus_a, dead_e, dead_a = [], [], [], []
    for s in SEEDS:
        re = run_swim(cfg_e, steps=150, seed=s, warmup=False)
        ra = run_swim(cfg_a, steps=150, seed=s, warmup=False)
        sus_e.append(re.first_tick(re.suspecting))
        sus_a.append(ra.first_tick(ra.suspecting))
        dead_e.append(re.first_tick(re.dead_known))
        dead_a.append(ra.first_tick(ra.dead_known))
    assert all(v is not None for v in sus_e + sus_a + dead_e + dead_a)
    # First-suspicion time is set by the probe plane (identical in both
    # modes); dead time by suspicion timing + gossip spread.
    assert abs(np.mean(sus_e) - np.mean(sus_a)) <= 5.0
    assert abs(np.mean(dead_e) - np.mean(dead_a)) <= 10.0


def test_aggregate_total_loss_never_spreads():
    cfg = BroadcastConfig(n=256, loss=1.0, delivery="aggregate")
    r = run_broadcast(cfg, steps=10, seed=0, warmup=False)
    assert r.infected[-1] == 1


# ---------------------------------------------------------------------------
# Quantile-band error bars at scale (VERDICT r4 weak #2): the headline's
# aggregate mode must track the exact edges path with a MEASURED
# distributional bound at n = 10^4..10^5, not just mean agreement at
# n = 4096.
#
# Statistic: time-to-fraction quantiles of the infection/detection CDF.
# A raw KS distance between mean curves is dominated by epidemic takeoff
# jitter — the knee covers ~80% of the population in two ticks, so a
# half-tick seed-to-seed offset reads as KS ~ 0.14 even for two runs of
# the SAME model.  Convergence TIMES are what BASELINE.json's 5% clause
# binds, and they are stable: we assert every quantile's mean
# time-to-fraction agrees within max(1 tick, 5%) — one tick being the
# simulation's resolution floor.
# ---------------------------------------------------------------------------

REL_BOUND = 0.05  # BASELINE.json acceptance clause
ABS_FLOOR = 1.0   # one gossip tick: the discretization floor


def _tq(reports, frac, denom, attr="infected"):
    ts = [time_to_fraction(np.asarray(getattr(r, attr)), denom, frac)
          for r in reports]
    assert all(t is not None for t in ts), f"no run reached {frac}"
    return float(np.mean(ts))


def _assert_quantile_band(r_e, r_a, denom, fracs, attr="infected"):
    for frac in fracs:
        te = _tq(r_e, frac, denom, attr)
        ta = _tq(r_a, frac, denom, attr)
        bound = max(ABS_FLOOR, REL_BOUND * te)
        assert abs(te - ta) <= bound, (
            f"t{int(frac * 100)}: edges {te:.2f} vs aggregate {ta:.2f} "
            f"ticks — gap {abs(te - ta):.2f} > bound {bound:.2f}"
        )


@pytest.mark.slow
def test_broadcast_quantile_band_at_10k():
    # Large-n distributional band (tier-1 budget policy): the
    # edges/aggregate agreement claims stay tier-1 at small n above.
    n = 10_000
    cfg_e = BroadcastConfig(n=n, fanout=4, loss=0.2, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=50, seed=s, warmup=False)
           for s in range(5)]
    r_a = [run_broadcast(cfg_a, steps=50, seed=s, warmup=False)
           for s in range(5)]
    _assert_quantile_band(r_e, r_a, n, (0.25, 0.5, 0.9, 0.99))


@pytest.mark.slow  # ~11s at CPU: 100k bands (10k twin stays tier-1)
def test_broadcast_quantile_band_at_100k():
    """The 10^5 regime the headline banks on."""
    n = 100_000
    cfg_e = BroadcastConfig(n=n, fanout=4, loss=0.2, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=50, seed=s, warmup=False)
           for s in range(3)]
    r_a = [run_broadcast(cfg_a, steps=50, seed=s, warmup=False)
           for s in range(3)]
    _assert_quantile_band(r_e, r_a, n, (0.25, 0.5, 0.9, 0.99))


@pytest.mark.slow  # ~32s at CPU: 6 x 220-tick n=10k studies
def test_swim_detection_quantile_band_at_10k():
    """Death-propagation CDF across observers, edges vs aggregate, at
    the scale band the VERDICT asked for.  Detection horizons are
    O(100) ticks here, so the 5% relative clause (not the 1-tick floor)
    is the operative bound.  Behind -m slow per the tier-1 budget
    policy for long-horizon distributional bands (PR 3); the n=4096
    swim agreement test and the 10k/100k broadcast bands above keep
    the edges==aggregate claim in tier-1."""
    n = 10_000
    cfg_e = SwimConfig(n=n, subject=3, loss=0.2, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_swim(cfg_e, steps=220, seed=s, warmup=False) for s in SEEDS]
    r_a = [run_swim(cfg_a, steps=220, seed=s, warmup=False) for s in SEEDS]
    _assert_quantile_band(r_e, r_a, n - 1, (0.5, 0.9, 0.99),
                          attr="dead_known")
    # Both modes fully converge (a vacuously-passing flat curve can't).
    assert np.asarray(r_e[0].dead_known)[-1] > 0.95 * (n - 1)
    assert np.asarray(r_a[0].dead_known)[-1] > 0.95 * (n - 1)
