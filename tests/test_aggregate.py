"""Distributional-equivalence tests: 'aggregate' (Poissonized receiver)
delivery must reproduce the 'edges' (exact per-message scatter) dynamics.

The aggregation is exact in the large-n limit (multinomial arrival counts
-> independent Poisson); at n=4096 the convergence curves of the two modes
must agree to within a tick or two.  Averaged over seeds to keep the test
stable."""

import dataclasses

import numpy as np

from consul_tpu.models import BroadcastConfig, SwimConfig
from consul_tpu.sim import run_broadcast, run_swim, time_to_fraction

N = 4096
SEEDS = range(3)


def _mean_t(reports, frac):
    ts = [time_to_fraction(r.infected, N, frac) for r in reports]
    assert all(t is not None for t in ts)
    return np.mean(ts)


def test_broadcast_modes_agree_on_convergence():
    cfg_e = BroadcastConfig(n=N, fanout=3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=40, seed=s, warmup=False) for s in SEEDS]
    r_a = [run_broadcast(cfg_a, steps=40, seed=s, warmup=False) for s in SEEDS]
    for frac in (0.5, 0.99):
        assert abs(_mean_t(r_e, frac) - _mean_t(r_a, frac)) <= 2.0


def test_broadcast_modes_agree_under_loss():
    cfg_e = BroadcastConfig(n=N, fanout=3, loss=0.3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    r_e = [run_broadcast(cfg_e, steps=60, seed=s, warmup=False) for s in SEEDS]
    r_a = [run_broadcast(cfg_a, steps=60, seed=s, warmup=False) for s in SEEDS]
    assert abs(_mean_t(r_e, 0.99) - _mean_t(r_a, 0.99)) <= 3.0


def test_swim_modes_agree_on_detection():
    cfg_e = SwimConfig(n=N, subject=3, delivery="edges")
    cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
    sus_e, sus_a, dead_e, dead_a = [], [], [], []
    for s in SEEDS:
        re = run_swim(cfg_e, steps=150, seed=s, warmup=False)
        ra = run_swim(cfg_a, steps=150, seed=s, warmup=False)
        sus_e.append(re.first_tick(re.suspecting))
        sus_a.append(ra.first_tick(ra.suspecting))
        dead_e.append(re.first_tick(re.dead_known))
        dead_a.append(ra.first_tick(ra.dead_known))
    assert all(v is not None for v in sus_e + sus_a + dead_e + dead_a)
    # First-suspicion time is set by the probe plane (identical in both
    # modes); dead time by suspicion timing + gossip spread.
    assert abs(np.mean(sus_e) - np.mean(sus_a)) <= 5.0
    assert abs(np.mean(dead_e) - np.mean(dead_a)) <= 10.0


def test_aggregate_total_loss_never_spreads():
    cfg = BroadcastConfig(n=256, loss=1.0, delivery="aggregate")
    r = run_broadcast(cfg, steps=10, seed=0, warmup=False)
    assert r.infected[-1] == 1
