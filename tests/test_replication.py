"""WAN replication: secondary datacenters pull config entries and ACL
policies/tokens from the primary (config_replication.go +
acl_replication.go, leader.go:834-979)."""

import asyncio

import pytest

from helpers import wait_for as wait_until
from helpers import wait_for_leader

from consul_tpu.agent.server import Server, ServerConfig
from consul_tpu.net.transport import InMemoryNetwork


def run(coro, timeout=90):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


def make_repl_server(lan, wan, rpc, name, dc, primary=""):
    cfg = ServerConfig(
        node_name=name,
        datacenter=dc,
        bootstrap_expect=1,
        gossip_interval_scale=0.05,
        reconcile_interval_s=0.2,
        coordinate_update_period_s=0.1,
        session_ttl_sweep_s=0.1,
        flood_interval_s=0.1,
        primary_datacenter=primary,
        replication_interval_s=0.2,
    )
    return Server(
        cfg,
        gossip_transport=lan.new_transport(f"{name}.{dc}:gossip"),
        rpc_transport=rpc.new_transport(f"{name}.{dc}:rpc"),
        wan_transport=wan.new_transport(f"{name}.{dc}:wan"),
    )


class TestWANReplication:
    async def test_config_and_acl_replicate_to_secondary(self):
        lan1, lan2 = InMemoryNetwork(), InMemoryNetwork()
        wan, rpc = InMemoryNetwork(), InMemoryNetwork()
        p = make_repl_server(lan1, wan, rpc, "p0", "dc1")
        s = make_repl_server(lan2, wan, rpc, "s0", "dc2", primary="dc1")
        await p.start()
        await s.start()
        await wait_for_leader([p])
        await wait_for_leader([s])
        await s.join_wan(["p0.dc1:wan"])

        # Writes land in the PRIMARY only.
        await p.rpc_client.call(
            "p0.dc1:rpc", "ConfigEntry.Apply",
            {"op": "set", "entry": {"kind": "service-defaults",
                                    "name": "web", "protocol": "http"}},
        )
        await p.rpc_client.call(
            "p0.dc1:rpc", "ACL.PolicySet",
            {"policy": {"id": "pol-1", "name": "ro", "rules": "{}"}},
        )
        await p.rpc_client.call(
            "p0.dc1:rpc", "ACL.TokenSet",
            {"acl_token": {"secret_id": "tok-1", "policies": ["pol-1"]}},
        )

        # The secondary's pull loop converges them.
        await wait_until(
            lambda: s.store.config_entry_get("service-defaults", "web")[1]
            is not None,
            timeout=15, msg="config entry replicated",
        )
        await wait_until(
            lambda: s.store.acl_policy_get("pol-1") is not None,
            timeout=15, msg="acl policy replicated",
        )
        await wait_until(
            lambda: s.store.acl_token_get("tok-1") is not None,
            timeout=15, msg="acl token replicated",
        )
        entry = s.store.config_entry_get("service-defaults", "web")[1]
        assert entry["protocol"] == "http"

        # Deletions replicate too.
        await p.rpc_client.call(
            "p0.dc1:rpc", "ConfigEntry.Apply",
            {"op": "delete",
             "entry": {"kind": "service-defaults", "name": "web"}},
        )
        await p.rpc_client.call(
            "p0.dc1:rpc", "ACL.PolicyDelete", {"id": "pol-1"}
        )
        await wait_until(
            lambda: s.store.config_entry_get("service-defaults", "web")[1]
            is None,
            timeout=15, msg="config entry deletion replicated",
        )
        await wait_until(
            lambda: s.store.acl_policy_get("pol-1") is None,
            timeout=15, msg="acl policy deletion replicated",
        )
        # The replicated world is usable locally: the token still
        # resolves in dc2 (tokens were not deleted).
        assert s.store.acl_token_get("tok-1") is not None

        await p.shutdown()
        await s.shutdown()

    async def test_primary_runs_no_replication(self):
        lan, wan, rpc = (InMemoryNetwork(), InMemoryNetwork(),
                         InMemoryNetwork())
        p = make_repl_server(lan, wan, rpc, "q0", "dc1", primary="dc1")
        await p.start()
        await wait_for_leader([p])
        # primary == own dc: the loop exits immediately (no self-pull).
        assert not p._is_secondary()
        await p.shutdown()
