"""Full-membership SWIM sim: concurrent failures, push/pull backstop,
joins/leaves, refutation, determinism.

Parity model: memberlist's own state-machine tests
(state_test.go TestMemberList_ProbeNode*, TestMemberlist_PushPull) plus
the BASELINE probe1k config — 1% concurrent failures in ONE program.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.models import (
    RANK_ALIVE,
    RANK_DEAD,
    RANK_LEFT,
    RANK_SUSPECT,
    MembershipConfig,
    key_inc,
    key_rank,
    make_key,
    membership_init,
    membership_round,
)
from consul_tpu.protocol.profiles import LAN
from consul_tpu.sim import membership_scan, run_membership

# A LAN-timing profile with the anti-entropy period shortened from 30s
# to 2s so push/pull effects land within test-sized tick budgets.
FAST_PP = dataclasses.replace(LAN, push_pull_interval_ms=2000)


def suspicion_min_ticks(n: int) -> float:
    # suspicionTimeout lower bound: mult * log10(n) * ProbeInterval
    # (memberlist/util.go:64-69), in gossip ticks.
    return 4 * math.log10(max(n, 10)) * (1000 / 200)


class TestSingleFailure:
    def test_detection_and_convergence(self):
        n, fail_tick = 128, 10
        cfg = MembershipConfig(n=n, fail_at=((5, fail_tick),))
        r = run_membership(cfg, steps=250, track=(5,), warmup=False)

        first_sus = r.first_tick(r.suspecting[:, 0])
        first_dead = r.first_tick(r.dead_known[:, 0])
        assert first_sus is not None and first_sus >= fail_tick
        # Nobody declares dead before the suspicion machinery can run
        # its minimum course after the first suspicion.
        assert first_dead is not None
        assert first_dead - first_sus >= suspicion_min_ticks(n) - 1
        # Every live observer (everyone but the crashed node) converges.
        assert r.dead_known[-1, 0] == n - 1
        # Suspicion fully resolves — no lingering suspect cells.
        assert r.suspect_cells[-1] == 0

    def test_no_failure_no_churn(self):
        cfg = MembershipConfig(n=64)
        r = run_membership(cfg, steps=150, track=(3,), warmup=False)
        assert r.suspecting[:, 0].max() == 0
        assert r.dead_known[:, 0].max() == 0


class TestConcurrentFailures:
    def test_ten_failures_one_program(self):
        """BASELINE config 2 shape: 1% of the pool fails at once; the
        failures share gossip bandwidth and confirmation traffic in ONE
        simulation (what the vmapped single-subject model couldn't do)."""
        n = 256
        failed = tuple(range(10))
        cfg = MembershipConfig(
            n=n, loss=0.01, fail_at=tuple((f, 10) for f in failed)
        )
        r = run_membership(cfg, steps=300, track=failed, warmup=False)
        live = n - len(failed)
        # Every live observer converges on every failed subject.
        assert (r.dead_known[-1] == live).all(), r.dead_known[-1]
        assert r.suspect_cells[-1] == 0


class TestPushPullBackstop:
    def test_dead_news_spreads_with_gossip_disabled(self):
        """Anti-entropy alone converges the view (state.go:622-657): a
        dead view planted at one node with NO transmit budget and NO
        probing can only travel via push/pull row merges."""
        n = 64
        cfg = MembershipConfig(
            n=n, profile=FAST_PP, probe_enabled=False,
            fail_at=((7, 0),),
        )
        state = membership_init(cfg)
        state = state._replace(
            key=state.key.at[0, 7].set(make_key(jnp.int32(0), RANK_DEAD))
        )
        final, _ = membership_scan(state, jax.random.PRNGKey(1), cfg, 200, ())
        ranks = np.asarray(key_rank(final.key))
        observers = [i for i in range(n) if i != 7]
        assert (ranks[observers, 7] == RANK_DEAD).all()

    def test_thirty_pct_loss_converges_fully(self):
        """Under 30% loss the gossip transmit budget alone leaves
        stragglers; the push/pull backstop still reaches 100%
        (the reference's convergence guarantee)."""
        n = 128
        cfg = MembershipConfig(
            n=n, loss=0.30, profile=FAST_PP, fail_at=((9, 10),)
        )
        r = run_membership(cfg, steps=300, track=(9,), warmup=False)
        assert r.dead_known[-1, 0] == n - 1


class TestJoinLeave:
    def test_join_via_push_pull(self):
        """A joiner knows only itself; its join-time push/pull plus the
        resulting alive broadcast make it known cluster-wide
        (Join -> pushPullNode, memberlist.go:249)."""
        n = 64
        cfg = MembershipConfig(n=n, profile=FAST_PP, join_at=((63, 5),))
        state = membership_init(cfg)
        # Before joining: nobody knows 63, 63 knows nobody.
        assert int((state.key[:, 63] >= 0).sum()) == 1
        assert int((state.key[63, :] >= 0).sum()) == 1
        final, _ = membership_scan(state, jax.random.PRNGKey(2), cfg, 120, ())
        ranks = np.asarray(key_rank(final.key))
        # Everyone sees the joiner alive; the joiner sees everyone.
        assert (ranks[:, 63] == RANK_ALIVE).all()
        assert (ranks[63, :] == RANK_ALIVE).all()

    def test_graceful_leave_is_left_not_dead(self):
        n = 64
        cfg = MembershipConfig(
            n=n, profile=FAST_PP, leave_at=((11, 10),),
            leave_grace_ticks=10,
        )
        state = membership_init(cfg)
        final, (sus, dead, _, _) = membership_scan(
            state, jax.random.PRNGKey(3), cfg, 250, (11,)
        )
        ranks = np.asarray(key_rank(final.key))
        observers = [i for i in range(n) if i != 11]
        assert (ranks[observers, 11] == RANK_LEFT).all()
        # A graceful departure never gets declared dead.
        assert np.asarray(dead).max() == 0


class TestRefutation:
    def test_false_suspicion_is_refuted(self):
        """A suspected-but-alive node bumps its incarnation and the
        alive broadcast overrides every suspect view
        (state.go:880-915, aliveNode override)."""
        n = 64
        cfg = MembershipConfig(n=n, probe_enabled=False)
        state = membership_init(cfg)
        # Plant a fresh suspicion of node 3 at node 0 with full budget.
        state = state._replace(
            key=state.key.at[0, 3].set(make_key(jnp.int32(0), RANK_SUSPECT)),
            suspect_since=state.suspect_since.at[0, 3].set(0),
            tx=state.tx.at[0, 3].set(cfg.tx_limit),
        )
        final, _ = membership_scan(state, jax.random.PRNGKey(4), cfg, 100, ())
        ranks = np.asarray(key_rank(final.key))
        incs = np.asarray(key_inc(final.key))
        assert int(final.own_inc[3]) >= 1
        assert (ranks[:, 3] == RANK_ALIVE).all()
        # Views converged on the refuted incarnation.
        assert (incs[:, 3] == int(final.own_inc[3])).all()


class TestDeterminism:
    def test_same_key_same_trajectory(self):
        cfg = MembershipConfig(n=48, loss=0.2, fail_at=((1, 5),))
        s1, o1 = membership_scan(
            membership_init(cfg), jax.random.PRNGKey(7), cfg, 60, (1,)
        )
        s2, o2 = membership_scan(
            membership_init(cfg), jax.random.PRNGKey(7), cfg, 60, (1,)
        )
        assert (np.asarray(s1.key) == np.asarray(s2.key)).all()
        assert (np.asarray(o1[0]) == np.asarray(o2[0])).all()

    def test_different_key_different_trajectory(self):
        cfg = MembershipConfig(n=48, loss=0.2, fail_at=((1, 5),))
        s1, _ = membership_scan(
            membership_init(cfg), jax.random.PRNGKey(7), cfg, 60, ()
        )
        s2, _ = membership_scan(
            membership_init(cfg), jax.random.PRNGKey(8), cfg, 60, ()
        )
        assert (np.asarray(s1.key) != np.asarray(s2.key)).any()


class TestAwareness:
    def test_failed_probes_degrade_health(self):
        """Lifeguard: probing crashed members raises the prober's
        awareness score (awareness.go ApplyDelta(+1) on probe
        timeout); with half the cluster down, scores move."""
        n = 64
        cfg = MembershipConfig(
            n=n, fail_at=tuple((i, 0) for i in range(n // 2))
        )
        state = membership_init(cfg)
        # Run a handful of probe cycles.
        final, _ = membership_scan(state, jax.random.PRNGKey(5), cfg, 30, ())
        aw = np.asarray(final.awareness)
        assert aw[n // 2:].max() >= 1

    def test_healthy_cluster_stays_at_zero(self):
        cfg = MembershipConfig(n=64)
        final, _ = membership_scan(
            membership_init(cfg), jax.random.PRNGKey(6), cfg, 30, ()
        )
        assert np.asarray(final.awareness).max() == 0


class TestScheduleValidation:
    def test_out_of_bounds_fail_at_raises_at_init(self):
        """A typoed node id must fail loudly at init — jnp's
        .at[].set silently drops out-of-bounds scatters, which would
        turn the fault schedule into a no-op and measure a
        failure-free cluster."""
        import pytest

        cfg = MembershipConfig(n=48, fail_at=((99, 5),))
        state = membership_init(cfg)
        with pytest.raises(IndexError, match=r"\(99, 5\).*n=48"):
            membership_scan(state, jax.random.PRNGKey(0), cfg, 4, ())

    def test_out_of_bounds_join_at_raises(self):
        import pytest

        with pytest.raises(IndexError, match="out of bounds"):
            membership_init(MembershipConfig(n=48, join_at=((-49, 3),)))
