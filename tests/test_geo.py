"""Geo-distributed WAN plane (consul_tpu/geo).

The ladder of guarantees, weakest precondition first:

  * link admission kernel == sequential numpy reference (capacity cap,
    bounded deferral, drop-tail overflow) — property-tested, with the
    conservation counts == admitted + deferred + overflow.
  * loud accounting: per link per tick, offered + queue_prev ==
    admitted + queue + overflow, under healthy AND browned-out links.
  * latency coupling: a unit admitted on a link with latency L lands
    at the destination exactly L ticks later (the delay ring).
  * Vivaldi derivation: the per-link latency matrix is deterministic
    per seed, symmetric, in-window, and the converged coordinates
    predict the latent RTTs (measured relative error).
  * adaptive anti-entropy beats the fixed baseline under a bandwidth
    brownout: faster t99, less overflow, less stale waste — the
    adaptive-SMR claim at small n.
  * sharded exactness: D=1 bit-equal to geo_scan, D=2 == D=1 with
    outbox overflow 0, ring == all_to_all.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consul_tpu.geo import (
    GeoConfig,
    admit_link_units,
    derive_wan_latency,
    geo_init,
)
from consul_tpu.protocol.profiles import WAN
from consul_tpu.sim.engine import geo_scan, run_geo
from consul_tpu.sim.faults import (
    BandwidthSchedule,
    ChurnWindow,
    FaultSchedule,
    link_capacity_at,
)

# WAN gossip disabled: the anti-entropy leg is the only cross-DC path,
# so link-level claims (latency, A/B) are not confounded by chatter.
WAN_NOGOSSIP = dataclasses.replace(WAN, gossip_nodes=0)

# One shared config for the engine + sharded-exactness tests, so the
# module pays one compile per DISTINCT program (unsharded, D1, D2,
# D2/ring) — the test_shard.py budget discipline.
_SHARDED_CFG = GeoConfig(
    n=256, segments=4, bridges_per_segment=2, events=6,
    wan_window=6, wan_latency_ticks=((0, 1, 2, 3), (1, 0, 2, 2),
                                     (2, 2, 0, 1), (3, 2, 1, 0)),
    wan_msg_bytes=100, wan_capacity_bytes=1600.0,
    wan_queue_bytes=3200.0, ae_batch=6, loss_wan=0.05,
)
_SHARDED_STEPS = 40


# ---------------------------------------------------------------------------
# BandwidthSchedule: the capacity evaluator vs a host reference.
# ---------------------------------------------------------------------------


def _cap_ref(scheds, tick, segments, base):
    cap = np.full((segments, segments), base, float)
    for bs in scheds:
        val = None
        for start, v in bs.pieces:
            if tick >= start:
                val = v * bs.scale
        if val is None:
            continue
        for s in range(segments):
            for d in range(segments):
                if bs.src >= 0 and s != bs.src:
                    continue
                if bs.dst >= 0 and d != bs.dst:
                    continue
                cap[s, d] = min(cap[s, d], val)
    return np.clip(cap, 0.0, base)


class TestBandwidthSchedule:
    def test_capacity_matches_reference(self):
        scheds = (
            BandwidthSchedule(pieces=((5, 300.0), (20, 1200.0))),
            BandwidthSchedule(pieces=((10, 150.0),), src=1, scale=0.5),
            BandwidthSchedule(pieces=((0, 900.0),), src=2, dst=0),
        )
        faults = FaultSchedule(bandwidth=scheds)
        for tick in (0, 4, 5, 9, 10, 19, 20, 50):
            got = np.asarray(
                link_capacity_at(faults, jnp.int32(tick), 3, base=1000.0)
            )
            np.testing.assert_allclose(
                got, _cap_ref(scheds, tick, 3, 1000.0), err_msg=str(tick)
            )

    def test_schedules_compose_by_min(self):
        a = FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((0, 700.0),)),))
        b = FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((0, 400.0),)),))
        cap = np.asarray(
            link_capacity_at(a.compose(b), jnp.int32(1), 2, base=1000.0)
        )
        assert (cap == 400.0).all()
        assert a.compose(b).has_faults

    def test_scale_never_admits_past_base(self):
        # A severity scale > 1 (or a huge piece) is clipped to the
        # static base — the bound the delivery slot planes are sized by.
        f = FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((0, 500.0),), scale=100.0),))
        cap = np.asarray(link_capacity_at(f, jnp.int32(3), 2, base=800.0))
        assert (cap == 800.0).all()

    def test_validation_is_loud(self):
        with pytest.raises(ValueError, match="sorted"):
            BandwidthSchedule(pieces=((10, 1.0), (5, 2.0)))
        with pytest.raises(ValueError, match=">= 0"):
            BandwidthSchedule(pieces=((0, -4.0),))
        with pytest.raises(ValueError, match="src=7"):
            link_capacity_at(
                FaultSchedule(bandwidth=(
                    BandwidthSchedule(pieces=((0, 1.0),), src=7),)),
                jnp.int32(0), 2, base=10.0,
            )


# ---------------------------------------------------------------------------
# Link admission kernel vs a sequential numpy reference.
# ---------------------------------------------------------------------------


def _admit_ref(counts, cap, qcap):
    """Greedy sequential reference: admit in stream order up to the
    link's capacity, defer up to the queue bound, drop the rest."""
    s2, m = counts.shape
    adm = np.zeros_like(counts)
    dfr = np.zeros_like(counts)
    ovf = np.zeros_like(counts)
    for link in range(s2):
        cap_left, q_left = int(cap[link]), int(qcap)
        for i in range(m):
            c = int(counts[link, i])
            a = min(c, cap_left)
            cap_left -= a
            d = min(c - a, q_left)
            q_left -= d
            adm[link, i], dfr[link, i] = a, d
            ovf[link, i] = c - a - d
    return adm, dfr, ovf


class TestAdmissionKernel:
    def test_matches_bruteforce_reference(self):
        rng = np.random.default_rng(0)
        kernel = jax.jit(admit_link_units, static_argnames=("queue_units",))
        for case in range(20):
            s2, m = int(rng.integers(1, 6)), int(rng.integers(1, 12))
            counts = rng.integers(0, 7, (s2, m)).astype(np.int32)
            cap = rng.integers(0, 12, (s2,)).astype(np.int32)
            qcap = int(rng.integers(0, 10))
            adm, dfr, ovf = kernel(
                jnp.asarray(counts), jnp.asarray(cap), qcap
            )
            adm, dfr, ovf = map(np.asarray, (adm, dfr, ovf))
            r_adm, r_dfr, r_ovf = _admit_ref(counts, cap, qcap)
            np.testing.assert_array_equal(adm, r_adm, err_msg=str(case))
            np.testing.assert_array_equal(dfr, r_dfr, err_msg=str(case))
            np.testing.assert_array_equal(ovf, r_ovf, err_msg=str(case))
            # Conservation: every offered unit is accounted somewhere.
            np.testing.assert_array_equal(counts, adm + dfr + ovf)


# ---------------------------------------------------------------------------
# Config validation: loud, never silent.
# ---------------------------------------------------------------------------


class TestConfigValidation:
    def test_latency_matrix_shape_and_range(self):
        with pytest.raises(ValueError, match="2x2"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      wan_latency_ticks=((0, 1),))
        with pytest.raises(ValueError, match="outside"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      wan_window=4,
                      wan_latency_ticks=((0, 9), (1, 0)))

    def test_capacity_slot_bound_is_loud(self):
        with pytest.raises(ValueError, match="wan_msg_bytes"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      wan_msg_bytes=1, wan_capacity_bytes=1e9)

    def test_node_fault_primitives_rejected(self):
        with pytest.raises(ValueError, match="membership dynamics"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      faults=FaultSchedule(
                          churn=(ChurnWindow(0, 5, 0.5),)))

    def test_origins_checked(self):
        with pytest.raises(ValueError, match="outside"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      events=1, origins=(64,))
        with pytest.raises(ValueError, match="origins"):
            GeoConfig(n=64, segments=2, bridges_per_segment=2,
                      events=2, origins=(0,))

    def test_default_origins_spread_and_non_bridge(self):
        cfg = GeoConfig(n=64, segments=4, bridges_per_segment=2,
                        events=4)
        segs = {o // cfg.seg_size for o in cfg.event_origins}
        assert segs == {0, 1, 2, 3}
        assert all(
            o % cfg.seg_size >= cfg.bridges_per_segment
            for o in cfg.event_origins
        )

    def test_default_origins_never_bridges_when_misaligned(self):
        # events > segments used to wrap raw node strides onto bridge
        # rows (segment offset 0 < B), silently skipping the
        # LAN -> bridge -> WAN climb the default documents.
        for n, s, b, e in ((64, 2, 2, 8), (96, 3, 2, 7), (64, 4, 3, 9)):
            cfg = GeoConfig(n=n, segments=s, bridges_per_segment=b,
                            events=e, wan_msg_bytes=100,
                            wan_capacity_bytes=800.0,
                            wan_queue_bytes=800.0)
            origins = cfg.event_origins
            assert len(set(origins)) == e, (origins, "collision")
            assert all(o % cfg.seg_size >= b for o in origins), origins
            assert {o // cfg.seg_size for o in origins} == set(range(s))

    def test_bandwidth_faults_rejected_by_non_geo_consumers(self):
        # A BandwidthSchedule on a model with no link plane would be
        # silently ignored — the user would believe they measured a
        # brownout.  Loud, never silent.
        from consul_tpu.models.lifeguard import LifeguardConfig
        from consul_tpu.streamcast import StreamcastConfig

        bw = FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((0, 100.0),)),))
        with pytest.raises(ValueError, match="geo/WAN plane"):
            LifeguardConfig(n=64, subject=1, subject_alive=True,
                            faults=bw)
        with pytest.raises(ValueError, match="loss ramps only"):
            StreamcastConfig(n=64, events=2, chunks=2, window=2,
                             rate=0.1, faults=bw)


# ---------------------------------------------------------------------------
# Latency coupling: the delay ring delivers exactly L ticks later.
# ---------------------------------------------------------------------------


class TestLatencyRing:
    def test_unit_lands_exactly_latency_ticks_later(self):
        # Origin 0 IS a bridge of segment 0, AE-only transfer, loss 0:
        # the single event is offered at tick 0, admitted at tick 0,
        # and MUST first appear in segment 1 after exactly lat ticks.
        lat = 3
        cfg = GeoConfig(
            n=64, segments=2, bridges_per_segment=2, events=1,
            wan_profile=WAN_NOGOSSIP, wan_window=5,
            wan_latency_ticks=((0, lat), (lat, 0)),
            wan_msg_bytes=100, wan_capacity_bytes=800.0,
            wan_queue_bytes=800.0, ae_batch=4, origins=(0,),
        )
        rep = run_geo(cfg, steps=10, seed=0, warmup=False)
        seg1 = rep.per_segment[:, 1]
        assert (seg1[:lat] == 0).all(), seg1
        assert seg1[lat] >= 1, seg1
        assert rep.accounting_ok()


# ---------------------------------------------------------------------------
# Loud accounting under pressure.
# ---------------------------------------------------------------------------


class TestAccounting:
    def test_identity_holds_and_overflow_is_loud(self):
        # Capacity 1 unit/tick, tiny queue, gossip chatter ON: the
        # links MUST overflow, and every unit must still be accounted:
        # offered + queue_prev == admitted + queue + overflow per link
        # per tick.
        cfg = GeoConfig(
            n=128, segments=4, bridges_per_segment=2, events=8,
            wan_window=4, wan_msg_bytes=100,
            wan_capacity_bytes=100.0, wan_queue_bytes=200.0,
            ae_batch=8,
        )
        rep = run_geo(cfg, steps=50, seed=1, warmup=False)
        assert rep.accounting_ok()
        assert rep.wan_overflow_units > 0
        # Loud never silent: offered is a census of every fresh unit.
        assert rep.offered.sum() == (
            rep.admitted.sum() + rep.overflow.sum()
            + rep.queued[-1].sum()
        )

    def test_ample_capacity_never_overflows(self):
        cfg = dataclasses.replace(
            _SHARDED_CFG, wan_capacity_bytes=25600.0,
            wan_queue_bytes=25600.0,
        )
        rep = run_geo(cfg, steps=30, seed=1, warmup=False)
        assert rep.wan_overflow_units == 0
        assert rep.accounting_ok()


# ---------------------------------------------------------------------------
# The adaptive-SMR claim: adaptive beats fixed under a brownout.
# ---------------------------------------------------------------------------


class TestAdaptiveAntiEntropy:
    def test_adaptive_beats_fixed_under_brownout(self):
        # A 2-DC transfer of 24 events over a link browned out to 2
        # units/tick (ticks 2..180): the fixed-size sender floods its
        # queue with picks that go stale behind the backlog (the
        # belief feedback is latency-delayed), so admitted capacity
        # drains duplicates and fresh offers overflow; the adaptive
        # sender sizes its offer to EWMA throughput minus backlog and
        # converges during the brownout.
        brownout = FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((2, 200.0), (180, 320000.0))),
        ))
        cfg = GeoConfig(
            n=256, segments=2, bridges_per_segment=2, events=24,
            wan_profile=WAN_NOGOSSIP, wan_window=5,
            wan_latency_ticks=((0, 3), (3, 0)),
            wan_msg_bytes=100, wan_capacity_bytes=3200.0,
            wan_queue_bytes=6400.0, ae_batch=24, ae_gain=0.3,
            adaptive=True, faults=brownout,
        )
        ra = run_geo(cfg, 220, seed=0, warmup=False)
        rf = run_geo(
            dataclasses.replace(cfg, adaptive=False), 220, seed=0,
            warmup=False,
        )
        t_ad, t_fx = ra.convergence_tick(0.99), rf.convergence_tick(0.99)
        assert t_ad is not None, "adaptive arm never converged"
        assert t_fx is None or t_ad < t_fx, (t_ad, t_fx)
        assert ra.wan_overflow_units < rf.wan_overflow_units
        assert ra.wan_wasted_units < rf.wan_wasted_units
        assert ra.accounting_ok() and rf.accounting_ok()


# ---------------------------------------------------------------------------
# Vivaldi-derived link matrix.
# ---------------------------------------------------------------------------


class TestVivaldiDerivation:
    def test_deterministic_per_seed_and_well_formed(self):
        kw = dict(tick_ms=200.0, rounds=150, wan_window=6)
        l0, info = derive_wan_latency(4, 2, seed=0, **kw)
        l0b, _ = derive_wan_latency(4, 2, seed=0, **kw)
        l3, _ = derive_wan_latency(4, 2, seed=3, **kw)
        assert l0 == l0b, "latency derivation is not deterministic"
        assert l0 != l3, "seed does not reach the placement"
        a = np.asarray(l0)
        assert (np.diag(a) == 0).all()
        assert (a == a.T).all(), "RTT-derived latency must be symmetric"
        off = a[~np.eye(4, dtype=bool)]
        assert off.min() >= 1 and off.max() <= 5
        # The convergence claim is measured, not assumed.
        assert info["rel_rtt_error"] < 0.35, info

    def test_feeds_geo_config(self):
        lat, _ = derive_wan_latency(4, 2, tick_ms=200.0, seed=0,
                                    rounds=150, wan_window=6)
        cfg = GeoConfig(n=64, segments=4, bridges_per_segment=2,
                        events=2, wan_window=6, wan_latency_ticks=lat,
                        wan_msg_bytes=100, wan_capacity_bytes=800.0,
                        wan_queue_bytes=800.0)
        assert len(cfg.latency_flat()) == 16


# ---------------------------------------------------------------------------
# Engine wiring + retrace discipline.
# ---------------------------------------------------------------------------


class TestEngine:
    @pytest.mark.single_trace(entrypoints=("geo_scan",))
    def test_run_geo_report_and_single_trace(self):
        # The exact (cfg, steps) the sharded ladder uses, so the whole
        # module pays ONE unsharded compile.
        rep = run_geo(_SHARDED_CFG, steps=_SHARDED_STEPS, seed=3,
                      warmup=False)
        s = rep.summary()
        assert s["accounting_ok"]
        assert s["converged_nodes_final"] > 0
        assert rep.per_segment.shape == (_SHARDED_STEPS, 4)
        assert rep.offered.shape == (_SHARDED_STEPS, 16)
        # Second run, same config: the jit cache serves it (the
        # single_trace marker fails the test otherwise).
        run_geo(_SHARDED_CFG, steps=_SHARDED_STEPS, seed=3,
                warmup=False)

    def test_exchange_without_mesh_rejected(self):
        with pytest.raises(ValueError, match="requires mesh"):
            run_geo(_SHARDED_CFG, steps=2, exchange="ring")

    def test_scenario_preset_registered(self):
        from consul_tpu.sim.scenarios import SCENARIOS, run_scenario

        assert "geo100k" in SCENARIOS
        with pytest.raises(ValueError, match="--devices"):
            run_scenario("geo100k", exchange="ring")

    def test_sweep_entrypoint_registered_and_validated(self):
        from consul_tpu.sweep import Universe
        from consul_tpu.sweep.frontier import ENTRYPOINT_METRICS
        from consul_tpu.sweep.universe import SWEEP_ENTRYPOINTS

        assert "geo" in SWEEP_ENTRYPOINTS
        assert "t99_ms" in ENTRYPOINT_METRICS["geo"]
        # Rate knobs pass, shape-feeding fields are rejected loudly.
        ok = Universe(entrypoint="geo", cfg=_SHARDED_CFG, steps=4,
                      seeds=(0,), knobs=("loss_wan",),
                      values=((0.1,),))
        assert ok.U == 1
        Universe(entrypoint="geo", cfg=_SHARDED_CFG, steps=4,
                 seeds=(0,), knobs=("ae_gain",), values=((0.3,),))
        for knob in ("wan_window", "ae_batch", "segments",
                     "wan_capacity_bytes", "events"):
            with pytest.raises(ValueError,
                               match="shapes or trace-time structure"):
                Universe(entrypoint="geo", cfg=_SHARDED_CFG, steps=4,
                         seeds=(0,), knobs=(knob,), values=((2,),))

    def test_wanbrownout_preset_constructs(self):
        from consul_tpu.sweep.presets import make_preset

        uni = make_preset("wanbrownout")
        assert uni.entrypoint == "geo"
        assert uni.knobs == ("faults.bandwidth[0].scale",)
        assert uni.U == 4
        with pytest.raises(ValueError, match="grid preset"):
            make_preset("wanbrownout", universes=8)


# ---------------------------------------------------------------------------
# Sharded exactness ladder: D=1 bit-equal, D=2 == D=1 (overflow 0),
# ring == all_to_all.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _sharded_runs():
    """One config, every plane: unsharded, D=1, D=2, D=2/ring — the
    whole ladder pays one compile per distinct program."""
    from consul_tpu.parallel import make_mesh
    from consul_tpu.parallel.shard import sharded_geo_scan

    key = jax.random.PRNGKey(3)
    runs = {}
    _, runs["unsharded"] = geo_scan(
        geo_init(_SHARDED_CFG), key, _SHARDED_CFG, _SHARDED_STEPS
    )
    for label, d, ex in (("D1", 1, "alltoall"), ("D2", 2, "alltoall"),
                         ("D2/ring", 2, "ring")):
        mesh = make_mesh(jax.devices()[:d])
        _, runs[label] = sharded_geo_scan(
            geo_init(_SHARDED_CFG), key, _SHARDED_CFG, _SHARDED_STEPS,
            mesh, ex,
        )
    return {
        k: tuple(np.asarray(x) for x in v) for k, v in runs.items()
    }


class TestSharded:
    def test_d1_bit_equal_to_unsharded(self):
        runs = _sharded_runs()
        for i, (a, b) in enumerate(zip(runs["unsharded"],
                                       runs["D1"][:-1])):
            np.testing.assert_array_equal(a, b, err_msg=f"out {i}")
        assert runs["D1"][-1][-1] == 0  # no outbox budget misses

    def test_d2_equals_d1_with_zero_outbox_overflow(self):
        runs = _sharded_runs()
        for i, (a, b) in enumerate(zip(runs["D1"], runs["D2"])):
            np.testing.assert_array_equal(a, b, err_msg=f"out {i}")
        assert runs["D2"][-1][-1] == 0

    def test_ring_bit_equal_to_alltoall(self):
        runs = _sharded_runs()
        for i, (a, b) in enumerate(zip(runs["D2"], runs["D2/ring"])):
            np.testing.assert_array_equal(a, b, err_msg=f"out {i}")

    def test_run_geo_mesh_reports_shard_overflow(self):
        from consul_tpu.parallel import make_mesh

        mesh = make_mesh(jax.devices()[:2])
        rep = run_geo(_SHARDED_CFG, steps=_SHARDED_STEPS, seed=3,
                      warmup=False, mesh=mesh)
        assert rep.shard_overflow == 0
        assert rep.accounting_ok()

    def test_segments_must_divide_over_devices(self):
        from consul_tpu.parallel import make_mesh
        from consul_tpu.parallel.shard import sharded_geo_scan

        cfg = GeoConfig(n=192, segments=3, bridges_per_segment=2,
                        events=2, wan_msg_bytes=100,
                        wan_capacity_bytes=800.0,
                        wan_queue_bytes=800.0)
        mesh = make_mesh(jax.devices()[:2])
        with pytest.raises(ValueError, match="does not divide"):
            sharded_geo_scan(geo_init(cfg), jax.random.PRNGKey(0),
                             cfg, 2, mesh)


# ---------------------------------------------------------------------------
# Long horizon: the 1M-scale study (accelerators; CPU via MemAvailable).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_geo_1m_brownout_end_to_end():
    """The bench 'geo' section's shape, end to end: 8 DCs under a
    scheduled brownout at large n (1M on accelerators; reduced on CPU
    under the MemAvailable guard), adaptive arm — convergence with the
    accounting identity intact."""
    from bench import _available_memory_gb
    from consul_tpu.geo.latency import derive_wan_latency
    from consul_tpu.protocol.profiles import LAN

    n = 1_000_000
    if jax.default_backend() == "cpu":
        avail = _available_memory_gb()
        n = 100_000 if (avail is None or avail < 24) else 1_000_000
    latency, info = derive_wan_latency(
        8, 5, tick_ms=LAN.gossip_interval_ms, seed=0, rounds=400,
        wan_window=8,
    )
    assert info["rel_rtt_error"] < 0.35
    base_bytes = 16 * 1400.0
    cfg = GeoConfig(
        n=n, segments=8, bridges_per_segment=5, events=16,
        wan_latency_ticks=latency, wan_window=8,
        wan_capacity_bytes=base_bytes, wan_msg_bytes=1400,
        wan_queue_bytes=2 * base_bytes, ae_batch=16, adaptive=True,
        loss_wan=0.05,
        faults=FaultSchedule(bandwidth=(
            BandwidthSchedule(pieces=((10, 0.1 * base_bytes),
                                      (110, 64 * base_bytes))),
        )),
    )
    rep = run_geo(cfg, steps=160, seed=0, warmup=False)
    assert rep.accounting_ok()
    assert rep.convergence_tick(0.99) is not None
    assert rep.wan_overflow_units >= 0
