"""Discovery-chain compiler golden cases.

Parity model: agent/consul/discoverychain/compile_test.go —
TestCompile's table (trivial leaf, redirects, circular redirect,
default subsets, failover expansion, splitter flattening, router
catch-all, protocol gating/mismatch, external-SNI validation).
"""

import pytest

from consul_tpu.connect.discoverychain import (
    ChainCompileError,
    compile_chain,
    entries_for_chain,
)


def chain(service="web", entries=None, **kw):
    return compile_chain(service, "dc1", entries or {}, **kw)


def resolver_entry(name, **kw):
    return {"kind": "service-resolver", "name": name, **kw}


# ---------------------------------------------------------------------------
# trivial / default
# ---------------------------------------------------------------------------


def test_default_chain_is_a_single_default_resolver():
    c = chain()
    assert c["protocol"] == "tcp"
    assert c["start_node"] == "resolver:web@dc1"
    node = c["nodes"]["resolver:web@dc1"]
    assert node["type"] == "resolver"
    assert node["resolver"]["default"] is True
    assert node["resolver"]["connect_timeout_s"] == 5.0
    assert set(c["targets"]) == {"web@dc1"}
    assert c["targets"]["web@dc1"]["datacenter"] == "dc1"


def test_connect_timeout_from_resolver_and_override():
    e = {"resolvers": {"web": resolver_entry("web", connect_timeout_s=33.0)}}
    assert chain(entries=e)["nodes"]["resolver:web@dc1"]["resolver"][
        "connect_timeout_s"] == 33.0
    c = chain(entries=e, override_connect_timeout_s=1.5)
    assert c["nodes"]["resolver:web@dc1"]["resolver"][
        "connect_timeout_s"] == 1.5


# ---------------------------------------------------------------------------
# redirects (compile.go:806-830)
# ---------------------------------------------------------------------------


def test_redirect_to_other_service_and_dc():
    e = {"resolvers": {"web": resolver_entry(
        "web", redirect={"service": "alt", "datacenter": "dc9"})}}
    c = chain(entries=e)
    assert c["start_node"] == "resolver:alt@dc9"
    assert set(c["targets"]) == {"alt@dc9"}


def test_redirect_chains_follow_through():
    e = {"resolvers": {
        "web": resolver_entry("web", redirect={"service": "mid"}),
        "mid": resolver_entry("mid", redirect={"service": "leaf"}),
    }}
    c = chain(entries=e)
    assert c["start_node"] == "resolver:leaf@dc1"


def test_circular_redirect_is_an_error():
    e = {"resolvers": {
        "web": resolver_entry("web", redirect={"service": "alt"}),
        "alt": resolver_entry("alt", redirect={"service": "web"}),
    }}
    with pytest.raises(ChainCompileError, match="circular resolver redirect"):
        chain(entries=e)


# ---------------------------------------------------------------------------
# subsets (compile.go:833-846)
# ---------------------------------------------------------------------------


def test_default_subset_rewrites_target():
    e = {"resolvers": {"web": resolver_entry(
        "web", default_subset="v2",
        subsets={"v2": {"filter": "Service.Meta.version == `2`"}})}}
    c = chain(entries=e)
    assert c["start_node"] == "resolver:web:v2@dc1"
    t = c["targets"]["web:v2@dc1"]
    assert t["filter"] == "Service.Meta.version == `2`"


def test_unknown_subset_is_an_error():
    e = {"resolvers": {"web": resolver_entry(
        "web", default_subset="v9", subsets={"v2": {}})}}
    with pytest.raises(ChainCompileError, match="does not have a subset"):
        chain(entries=e)


# ---------------------------------------------------------------------------
# failover (compile.go:946-1010)
# ---------------------------------------------------------------------------


def test_failover_datacenters_expand_to_targets():
    e = {"resolvers": {"web": resolver_entry(
        "web", failover={"*": {"datacenters": ["dc2", "dc3"]}})}}
    c = chain(entries=e)
    fo = c["nodes"]["resolver:web@dc1"]["resolver"]["failover"]
    assert fo["targets"] == ["web@dc2", "web@dc3"]
    # Failover targets are retained in the target set.
    assert set(c["targets"]) == {"web@dc1", "web@dc2", "web@dc3"}


def test_failover_to_other_service_skips_self():
    e = {"resolvers": {"web": resolver_entry(
        "web", failover={"*": {"service": "backup"}})}}
    c = chain(entries=e)
    fo = c["nodes"]["resolver:web@dc1"]["resolver"]["failover"]
    assert fo["targets"] == ["backup@dc1"]
    # Failover to yourself is dropped entirely (compile.go:983).
    e2 = {"resolvers": {"web": resolver_entry(
        "web", failover={"*": {"datacenters": ["dc1"]}})}}
    c2 = chain(entries=e2)
    assert c2["nodes"]["resolver:web@dc1"]["resolver"]["failover"] is None


def test_subset_specific_failover_beats_wildcard():
    e = {"resolvers": {"web": resolver_entry(
        "web", default_subset="v1",
        subsets={"v1": {}, "v2": {}},
        failover={"v1": {"datacenters": ["dc2"]},
                  "*": {"datacenters": ["dc9"]}})}}
    c = chain(entries=e)
    fo = c["nodes"]["resolver:web:v1@dc1"]["resolver"]["failover"]
    assert fo["targets"] == ["web:v1@dc2"]


# ---------------------------------------------------------------------------
# splitters (compile.go:682-760) — need an L7 protocol
# ---------------------------------------------------------------------------

HTTP_DEFAULTS = {"kind": "service-defaults", "name": "web",
                 "protocol": "http"}


def test_splitter_splits_to_subset_resolvers():
    e = {
        "services": {"web": HTTP_DEFAULTS},
        "splitters": {"web": {
            "kind": "service-splitter", "name": "web",
            "splits": [
                {"weight": 90, "service_subset": "v1"},
                {"weight": 10, "service_subset": "v2"},
            ]}},
        "resolvers": {"web": resolver_entry(
            "web", subsets={"v1": {}, "v2": {}})},
    }
    c = chain(entries=e)
    assert c["protocol"] == "http"
    assert c["start_node"] == "splitter:web"
    splits = c["nodes"]["splitter:web"]["splits"]
    assert [(s["weight"], s["next_node"]) for s in splits] == [
        (90, "resolver:web:v1@dc1"), (10, "resolver:web:v2@dc1")]


def test_adjacent_splitters_flatten_with_scaled_weights():
    e = {
        "services": {"web": HTTP_DEFAULTS,
                     "alt": {"kind": "service-defaults", "name": "alt",
                             "protocol": "http"}},
        "splitters": {
            "web": {"kind": "service-splitter", "name": "web",
                    "splits": [{"weight": 50, "service": "alt"},
                               {"weight": 50}]},
            "alt": {"kind": "service-splitter", "name": "alt",
                    "splits": [{"weight": 60, "service_subset": "a"},
                               {"weight": 40, "service_subset": "b"}]},
        },
        "resolvers": {"alt": resolver_entry(
            "alt", subsets={"a": {}, "b": {}})},
    }
    c = chain(entries=e)
    splits = c["nodes"]["splitter:web"]["splits"]
    assert [(s["weight"], s["next_node"]) for s in splits] == [
        (30.0, "resolver:alt:a@dc1"),
        (20.0, "resolver:alt:b@dc1"),
        (50, "resolver:web@dc1"),
    ]
    # The flattened-away splitter node is pruned.
    assert "splitter:alt" not in c["nodes"]


def test_mutually_referencing_splitters_error_not_hang():
    """compile.go:333 detectCircularReferences — a splitter cycle must
    fail the compile; the flatten pass would otherwise loop forever on
    the server event loop."""
    e = {
        "global_proxy": {"kind": "proxy-defaults", "name": "global",
                         "config": {"protocol": "http"}},
        "splitters": {
            "a": {"kind": "service-splitter", "name": "a",
                  "splits": [{"weight": 100, "service": "b"}]},
            "b": {"kind": "service-splitter", "name": "b",
                  "splits": [{"weight": 100, "service": "a"}]},
        },
    }
    with pytest.raises(ChainCompileError, match="circular reference"):
        chain("a", entries=e)


def test_splitter_on_tcp_protocol_is_an_error():
    e = {"splitters": {"web": {
        "kind": "service-splitter", "name": "web",
        "splits": [{"weight": 100}]}}}
    with pytest.raises(ChainCompileError, match="does not permit advanced"):
        chain(entries=e)


def test_l4_override_drops_router_and_splitter():
    e = {
        "services": {"web": HTTP_DEFAULTS},
        "splitters": {"web": {
            "kind": "service-splitter", "name": "web",
            "splits": [{"weight": 100}]}},
    }
    c = chain(entries=e, override_protocol="tcp")
    assert c["start_node"] == "resolver:web@dc1"
    assert c["protocol"] == "tcp"


# ---------------------------------------------------------------------------
# routers (compile.go:499-597)
# ---------------------------------------------------------------------------


def test_router_routes_plus_catch_all():
    e = {
        "services": {"web": HTTP_DEFAULTS,
                     "admin": {"kind": "service-defaults", "name": "admin",
                               "protocol": "http"}},
        "routers": {"web": {
            "kind": "service-router", "name": "web",
            "routes": [{
                "match": {"http": {"path_prefix": "/admin"}},
                "destination": {"service": "admin"},
            }]}},
    }
    c = chain(entries=e)
    assert c["start_node"] == "router:web"
    routes = c["nodes"]["router:web"]["routes"]
    assert len(routes) == 2  # configured + catch-all
    assert routes[0]["next_node"] == "resolver:admin@dc1"
    assert routes[1]["definition"]["match"]["http"]["path_prefix"] == "/"
    assert routes[1]["next_node"] == "resolver:web@dc1"
    assert set(c["targets"]) == {"admin@dc1", "web@dc1"}


def test_protocol_mismatch_across_chain_is_an_error():
    e = {
        "services": {"web": HTTP_DEFAULTS,
                     "admin": {"kind": "service-defaults", "name": "admin",
                               "protocol": "grpc"}},
        "routers": {"web": {
            "kind": "service-router", "name": "web",
            "routes": [{"match": {"http": {"path_prefix": "/a"}},
                        "destination": {"service": "admin"}}]}},
    }
    with pytest.raises(ChainCompileError, match="different protocols"):
        chain(entries=e)


def test_proxy_defaults_global_protocol_applies():
    e = {
        "global_proxy": {"kind": "proxy-defaults", "name": "global",
                         "config": {"protocol": "http"}},
        "splitters": {"web": {
            "kind": "service-splitter", "name": "web",
            "splits": [{"weight": 100}]}},
    }
    c = chain(entries=e)
    assert c["protocol"] == "http"
    assert c["start_node"] == "splitter:web"


# ---------------------------------------------------------------------------
# external SNI (compile.go:860-903)
# ---------------------------------------------------------------------------


def test_external_sni_sets_target_and_rejects_failover():
    e = {"services": {"web": {"kind": "service-defaults", "name": "web",
                              "external_sni": "web.example.com"}}}
    c = chain(entries=e)
    t = c["targets"]["web@dc1"]
    assert t["external"] and t["sni"] == "web.example.com"

    e["resolvers"] = {"web": resolver_entry(
        "web", failover={"*": {"datacenters": ["dc2"]}})}
    with pytest.raises(ChainCompileError, match="external SNI"):
        chain(entries=e)


# ---------------------------------------------------------------------------
# store plumbing
# ---------------------------------------------------------------------------


async def test_discovery_chain_http_end_to_end():
    """PUT /v1/config entries, then GET /v1/discovery-chain/:service
    returns the compiled graph (agent/discovery_chain_endpoint.go)."""
    import json
    import sys

    sys.path.insert(0, "tests")
    from test_http_dns import dev_stack, http_call

    async with dev_stack() as (_agent, addr, _dns, _dns_addr):
        for entry in (
            {"Kind": "service-defaults", "Name": "web", "Protocol": "http"},
            {"Kind": "service-resolver", "Name": "web",
             "Subsets": {"v1": {}, "v2": {}},
             "Failover": {"*": {"Datacenters": ["dc2"]}}},
            {"Kind": "service-splitter", "Name": "web",
             "Splits": [{"Weight": 90, "ServiceSubset": "v1"},
                        {"Weight": 10, "ServiceSubset": "v2"}]},
        ):
            st, _, ok = await http_call(
                addr, "PUT", "/v1/config", json.dumps(entry).encode())
            assert st == 200, ok

        st, _, out = await http_call(addr, "GET", "/v1/discovery-chain/web")
        assert st == 200
        chain = out["Chain"]
        assert chain["Protocol"] == "http"
        assert chain["StartNode"] == "splitter:web"
        # Failover rides along on each subset resolver.
        nodes = chain["Nodes"]
        v1 = nodes["resolver:web:v1@dc1"]
        assert v1["Resolver"]["Failover"]["Targets"] == ["web:v1@dc2"]

        # L4 override via POST compiles a plain resolver chain.
        st, _, out = await http_call(
            addr, "POST", "/v1/discovery-chain/web",
            json.dumps({"OverrideProtocol": "tcp"}).encode())
        assert st == 200
        assert out["Chain"]["StartNode"] == "resolver:web@dc1"


def test_entries_for_chain_indexes_store_entries():
    from consul_tpu.store.state import StateStore

    s = StateStore()
    s.config_entry_set(1, {"kind": "service-resolver", "name": "web",
                           "redirect": {"service": "alt"}})
    s.config_entry_set(2, {"kind": "proxy-defaults", "name": "global",
                           "config": {"protocol": "http"}})
    s.config_entry_set(3, {"kind": "service-defaults", "name": "alt",
                           "protocol": "http"})
    idx, e = entries_for_chain(s, "web")
    assert idx == 3
    assert "web" in e["resolvers"]
    assert e["global_proxy"]["name"] == "global"
    c = compile_chain("web", "dc1", e)
    assert c["start_node"] == "resolver:alt@dc1"
    assert c["protocol"] == "http"
