"""Lifeguard subsystem tests: awareness (NHM) transitions, shared
timeout math between sim and host planes, the degraded1m accuracy A/B
(the acceptance criterion: Lifeguard strictly lowers the false-positive
suspicion rate), aggregate-vs-edges distributional agreement of the
Lifeguard-augmented path, and the CLI scenario registry."""

import dataclasses
import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consul_tpu.models import (
    LifeguardConfig,
    lifeguard_init,
    lifeguard_round,
)
from consul_tpu.protocol import (
    LAN,
    WAN,
    awareness_clamp,
    awareness_probe_delta,
    awareness_scaled_timeout,
)
from consul_tpu.sim import (
    run_lifeguard,
    time_to_fraction,
)
from consul_tpu.sim.engine import lifeguard_scan
from consul_tpu.sim.scenarios import degraded1m, degraded1m_environment


def advance(st, cfg, steps, seed=0):
    """Advance through the jitted scan (one compile, the same code
    path the studies run)."""
    final, _ = lifeguard_scan(st, jax.random.PRNGKey(seed), cfg, steps)
    return final


# The degraded1m scenario's fault environment — imported, not copied,
# so the acceptance test pins the exact knobs the preset ships.
DEGRADED_FAULTS, DEGRADED_LOSS, DEGRADED_ACK_LATE = degraded1m_environment()


def degraded_cfg(n, lifeguard=True, **kw):
    return LifeguardConfig(
        n=n, subject=7 % n, subject_alive=True, loss=DEGRADED_LOSS,
        ack_late=DEGRADED_ACK_LATE, profile=WAN, delivery="aggregate",
        lifeguard=lifeguard, faults=DEGRADED_FAULTS, **kw,
    )


class TestAwarenessFormulas:
    """The shared protocol/formulas.py helpers both planes compute."""

    def test_scaled_timeout(self):
        assert awareness_scaled_timeout(500.0, 0) == 500.0
        assert awareness_scaled_timeout(500.0, 3) == 2000.0
        # Works elementwise on arrays (the sim plane's usage).
        got = awareness_scaled_timeout(
            jnp.float32(2.0), jnp.asarray([0, 1, 7], jnp.float32)
        )
        assert np.allclose(np.asarray(got), [2.0, 4.0, 16.0])

    def test_probe_delta_reference_cases(self):
        assert awareness_probe_delta(True) == -1
        assert awareness_probe_delta(True, expected_nacks=3, nacks=0) == -1
        # All nacks back: our links are fine, no penalty.
        assert awareness_probe_delta(False, expected_nacks=3, nacks=3) == 0
        assert awareness_probe_delta(False, expected_nacks=3, nacks=1) == 2
        # No relays available: flat +1 (the pre-Lifeguard penalty).
        assert awareness_probe_delta(False) == 1

    def test_clamp(self):
        assert awareness_clamp(-3, 8) == 0
        assert awareness_clamp(11, 8) == 7
        assert awareness_clamp(4, 8) == 4


class TestAwarenessTransitions:
    def test_round_is_pure_and_advances_tick(self):
        cfg = LifeguardConfig(n=32, subject=1, subject_alive=True)
        st = lifeguard_init(cfg)
        k = jax.random.PRNGKey(0)
        step = jax.jit(lifeguard_round, static_argnums=2)
        a = step(st, k, cfg)
        b = step(st, k, cfg)
        assert int(a.tick) == 1
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_clean_cluster_stays_healthy(self):
        cfg = LifeguardConfig(n=64, subject=0, subject_alive=True, loss=0.0)
        st = advance(lifeguard_init(cfg), cfg, 40)
        assert int(jnp.max(st.awareness)) == 0

    def test_loss_raises_awareness_and_bounds_hold(self):
        cfg = LifeguardConfig(
            n=64, subject=0, subject_alive=True, loss=0.5, profile=LAN
        )
        st = advance(lifeguard_init(cfg), cfg, 60, seed=2)
        aware = np.asarray(st.awareness)
        assert aware.max() >= 1, "heavy loss must degrade some scores"
        assert aware.min() >= 0
        assert aware.max() <= cfg.profile.awareness_max_multiplier - 1

    def test_lifeguard_off_freezes_awareness(self):
        cfg = LifeguardConfig(
            n=64, subject=0, subject_alive=True, loss=0.5, profile=LAN,
            lifeguard=False,
        )
        st = advance(lifeguard_init(cfg), cfg, 60, seed=2)
        assert int(jnp.max(st.awareness)) == 0

    def test_degraded_members_score_higher(self):
        # The 2% degraded population (dropped sends, late acks) must end
        # up with visibly worse health than the healthy majority —
        # Lifeguard identifying the slow members from local evidence.
        from consul_tpu.sim.faults import degraded_mask

        cfg = degraded_cfg(512)
        st = advance(lifeguard_init(cfg), cfg, 120, seed=0)
        mask = np.asarray(degraded_mask(cfg.faults, cfg.n))
        aware = np.asarray(st.awareness)
        assert mask.any()
        assert aware[mask].mean() > aware[~mask].mean() + 1.0


class TestDegradedAccuracy:
    """The acceptance criterion: on the degraded1m environment scaled
    to n=1024, Lifeguard strictly lowers the false-positive suspicion
    rate (and the incarnation flap count) versus the same universe with
    it disabled."""

    def test_fp_rate_strictly_lower_with_lifeguard(self):
        on = run_lifeguard(degraded_cfg(1024), steps=400, seed=0,
                           warmup=False)
        off = run_lifeguard(degraded_cfg(1024, lifeguard=False), steps=400,
                            seed=0, warmup=False)
        assert on.fp_total > 0, "the faulted universe must produce FPs"
        assert on.fp_rate < off.fp_rate, (on.fp_rate, off.fp_rate)
        assert on.flap_count <= off.flap_count

    @pytest.mark.single_trace(entrypoints=("lifeguard_scan",))
    def test_single_jit_trace_per_study(self, retrace_guard):
        # The whole study must compile as ONE lax.scan program: a second
        # run with the same static config may not retrace (the marker
        # also re-checks at teardown via analysis.guards).
        cfg = degraded_cfg(128)
        run_lifeguard(cfg, steps=20, seed=0, warmup=False)
        guard = retrace_guard["lifeguard_scan"]
        # Exactly one: the study really compiled through the jitted
        # entrypoint (0 would mean it bypassed lifeguard_scan).
        assert guard.traces == 1
        run_lifeguard(cfg, steps=20, seed=1, warmup=False)
        assert guard.traces == 1, (
            "same config retraced — not a single program"
        )

    def test_report_shapes_are_o_ticks(self):
        # Same (cfg, steps) as the trace-count test above — reuses its
        # compiled program.
        rep = run_lifeguard(degraded_cfg(128), steps=20, seed=0,
                            warmup=False)
        for col in (rep.suspecting, rep.dead_known, rep.fp_events,
                    rep.refutes, rep.mean_awareness):
            assert np.asarray(col).shape == (20,)

    def test_crash_study_still_detects(self):
        # Accuracy must not cost liveness: a real crash under the same
        # faults is still detected and propagated, Lifeguard on or off.
        # The crash lands at tick 100, deep into FP pressure: the
        # subject must refute every false accusation before its fail
        # tick (dynamic liveness in _merge_deliveries), so the first
        # DEAD view comes strictly after the real crash and
        # time_to_true_dead stays positive.
        for lg in (True, False):
            cfg = LifeguardConfig(
                n=256, subject=3, subject_alive=False, fail_at_tick=100,
                loss=DEGRADED_LOSS, ack_late=DEGRADED_ACK_LATE,
                profile=LAN, delivery="aggregate", lifeguard=lg,
                faults=DEGRADED_FAULTS,
            )
            rep = run_lifeguard(cfg, steps=300, seed=0, warmup=False)
            ttd = rep.time_to_true_dead_ms()
            assert ttd is not None and ttd > 0
            assert rep.dead_known[-1] >= 0.99 * (cfg.n - 1)


class TestScenario:
    def test_degraded1m_smoke_at_256(self):
        # Tier-1 smoke: the full scenario pipeline (both A/B runs) at
        # n=256 for 50 ticks.
        out = degraded1m(seed=0, n=256, steps=50)
        assert out["scenario"] == "degraded1m"
        assert out["n"] == 256 and out["ticks"] == 50
        for key in ("fp_rate_on", "fp_rate_off", "flaps_on", "flaps_off",
                    "fp_reduction", "sim_rounds_per_sec"):
            assert key in out

    @pytest.mark.slow
    def test_degraded1m_full_scale(self):
        # The 1M-node accuracy A/B (minutes of CPU; seconds on a chip).
        out = degraded1m(seed=0)
        assert out["n"] == 1_000_000
        assert out["fp_rate_on"] < out["fp_rate_off"]

    def test_cli_sim_list_enumerates_presets(self, capsys):
        import asyncio

        from consul_tpu.cli import build_parser
        from consul_tpu.sim import SCENARIOS

        args = build_parser().parse_args(["sim", "--list"])
        assert asyncio.run(args.fn(args)) == 0
        out = capsys.readouterr().out
        for name in SCENARIOS:
            assert name in out, f"sim --list must enumerate {name!r}"

    def test_cli_sim_requires_scenario(self, capsys):
        import asyncio

        from consul_tpu.cli import build_parser

        args = build_parser().parse_args(["sim"])
        assert asyncio.run(args.fn(args)) == 1


class TestHostPlaneParity:
    """net/suspicion.py minimums scale through the same shared helper
    (loaded by file path: the net package __init__ needs the optional
    cryptography dependency this environment lacks)."""

    @staticmethod
    def _load_suspicion():
        root = pathlib.Path(__file__).resolve().parents[1]
        spec = importlib.util.spec_from_file_location(
            "_suspicion_under_test", root / "consul_tpu/net/suspicion.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    async def test_suspicion_min_scales_with_health_score(self):
        susp = self._load_suspicion()
        s0 = susp.Suspicion("a", 2, 0.05, 0.3, lambda n: None,
                            health_score=0)
        s3 = susp.Suspicion("a", 2, 0.05, 0.3, lambda n: None,
                            health_score=3)
        try:
            assert s0.min_s == 0.05
            assert s3.min_s == awareness_scaled_timeout(0.05, 3) == 0.2
            # max never drops below the scaled min.
            s7 = susp.Suspicion("a", 2, 0.05, 0.3, lambda n: None,
                                health_score=7)
            assert s7.max_s >= s7.min_s == 0.4
            s7.stop()
        finally:
            s0.stop()
            s3.stop()

    async def test_scaled_min_delays_expiry(self):
        import asyncio

        susp = self._load_suspicion()
        fired = []
        # k=0: the timer sits at the min timeout; a health score of 4
        # must push 20ms to 100ms.
        s = susp.Suspicion("a", 0, 0.02, 0.12, fired.append,
                           health_score=4)
        try:
            await asyncio.sleep(0.05)
            assert not fired, "scaled minimum must delay the obituary"
            await asyncio.sleep(0.08)
            assert fired == [0]
        finally:
            s.stop()


class TestDeliveryModesAgree:
    """Small-N distributional cross-check (tests/test_aggregate.py
    style): the Lifeguard-augmented weighted-Poissonized aggregate path
    must reproduce the exact edges dynamics under the same fault
    schedule."""

    N = 2048
    REL_BOUND = 0.05
    ABS_FLOOR = 1.0

    def _quantile(self, reports, frac):
        ts = [time_to_fraction(np.asarray(r.dead_known), self.N - 1, frac)
              for r in reports]
        assert all(t is not None for t in ts), f"no run reached {frac}"
        return float(np.mean(ts))

    @pytest.mark.slow  # ~18s at CPU: quantile bands over seeds
    def test_crash_detection_quantile_band(self):
        cfg_e = LifeguardConfig(
            n=self.N, subject=3, subject_alive=False, fail_at_tick=0,
            loss=0.10, ack_late=0.15, profile=LAN, delivery="edges",
            faults=DEGRADED_FAULTS,
        )
        cfg_a = dataclasses.replace(cfg_e, delivery="aggregate")
        r_e = [run_lifeguard(cfg_e, steps=160, seed=s, warmup=False)
               for s in range(2)]
        r_a = [run_lifeguard(cfg_a, steps=160, seed=s, warmup=False)
               for s in range(2)]
        for frac in (0.5, 0.9):
            te = self._quantile(r_e, frac)
            ta = self._quantile(r_a, frac)
            bound = max(self.ABS_FLOOR, self.REL_BOUND * te)
            assert abs(te - ta) <= bound, (
                f"t{int(frac * 100)}: edges {te:.2f} vs aggregate "
                f"{ta:.2f} ticks — gap {abs(te - ta):.2f} > {bound:.2f}"
            )
        # Both modes fully converge (a flat curve can't pass vacuously).
        assert np.asarray(r_e[0].dead_known)[-1] > 0.95 * (self.N - 1)
        assert np.asarray(r_a[0].dead_known)[-1] > 0.95 * (self.N - 1)
