"""Shared async test helpers (one canonical copy for all suites)."""

import asyncio
import importlib.util

import pytest

# The optional crypto toolkit: gossip encryption, Connect CA and
# RS256/ES256 JWT tests need it; everything else runs without it
# (connect/ca.py, net/security.py, acl/jwt.py import it lazily).
HAVE_CRYPTO = importlib.util.find_spec("cryptography") is not None
requires_crypto = pytest.mark.skipif(
    not HAVE_CRYPTO,
    reason="needs the optional 'cryptography' package",
)


async def wait_until(pred, timeout=30.0, step=0.02):
    """Poll ``pred()`` until truthy; returns True/False (never raises) so
    callers can also assert that something does NOT happen."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return False


async def wait_for(pred, timeout=10.0, step=0.05, msg="condition"):
    """Like wait_until but raises with a message on timeout; accepts
    sync or async predicates."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        r = pred()
        if asyncio.iscoroutine(r):
            r = await r
        if r:
            return
        await asyncio.sleep(step)
    raise AssertionError(f"timeout waiting for {msg}")


def _leader_id(n):
    if hasattr(n, "leader_id"):  # RaftNode
        return n.leader_id
    raft = getattr(n, "raft", None)  # Server / Agent delegate
    return raft.leader_id if raft is not None else None


async def wait_for_leader(nodes, timeout=10.0):
    """One stable leader that every node agrees on; works for RaftNode
    and Server collections."""
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        leaders = [n for n in nodes if n.is_leader()]
        if len(leaders) == 1:
            want = _leader_id(leaders[0])
            if all(_leader_id(n) == want for n in nodes):
                return leaders[0]
        await asyncio.sleep(0.05)
    raise AssertionError(
        "no stable leader: "
        + str([(getattr(n, "id", getattr(n, "node_id", "?")), _leader_id(n))
               for n in nodes])
    )
