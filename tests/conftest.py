"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): the default unit of
testing is a deterministic in-process fake network — here, JAX CPU devices
standing in for TPU chips.  Must set env before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The baked axon sitecustomize force-registers the TPU platform at
# interpreter start; this config update (before first backend use) is the
# override that actually sticks.
jax.config.update("jax_platforms", "cpu")
