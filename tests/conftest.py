"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): the default unit of
testing is a deterministic in-process fake network — here, JAX CPU devices
standing in for TPU chips.  Must set env before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The baked axon sitecustomize force-registers the TPU platform at
# interpreter start; this config update (before first backend use) is the
# override that actually sticks.
jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# Minimal async test support (no pytest-asyncio in the image): any
# coroutine test function runs under asyncio.run with a fresh loop.
# ---------------------------------------------------------------------------

import inspect  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test via asyncio.run")
    config.addinivalue_line(
        "markers",
        "slow: >30s-at-CPU simulations, excluded from tier-1 "
        "(run with -m slow)",
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        import asyncio

        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
