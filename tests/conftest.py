"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): the default unit of
testing is a deterministic in-process fake network — here, JAX CPU devices
standing in for TPU chips.  Must set env before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The baked axon sitecustomize force-registers the TPU platform at
# interpreter start; this config update (before first backend use) is the
# override that actually sticks.
jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# Minimal async test support (no pytest-asyncio in the image): any
# coroutine test function runs under asyncio.run with a fresh loop.
# ---------------------------------------------------------------------------

import inspect  # noqa: E402
import pathlib  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test via asyncio.run")
    config.addinivalue_line(
        "markers",
        "slow: >30s-at-CPU simulations, excluded from tier-1 "
        "(run with -m slow)",
    )
    config.addinivalue_line(
        "markers",
        "single_trace(max_traces=1, entrypoints=...): fail the test if "
        "the jitted sim.engine scan entrypoints compile more than "
        "max_traces new programs during the test "
        "(consul_tpu.analysis.guards retrace counters)",
    )


@pytest.fixture(autouse=True)
def retrace_guard(request):
    """Retrace-count guard over the jitted study entrypoints.

    Opt in with ``@pytest.mark.single_trace`` (optionally
    ``max_traces=N`` / ``entrypoints=("swim_scan", ...)``); the fixture
    snapshots each entrypoint's compile cache before the test and fails
    it afterwards if any entrypoint compiled more than the budget —
    the "whole study = one XLA program" contract as a one-line marker.
    Request the fixture by name for mid-test ``.check()`` /
    ``.traces`` access (a dict of name -> TraceGuard, or None when the
    marker is absent).
    """
    marker = request.node.get_closest_marker("single_trace")
    if marker is None:
        yield None
        return
    from consul_tpu.analysis.guards import check_all, guard_entrypoints

    guards = guard_entrypoints(**marker.kwargs)
    yield guards
    check_all(guards)


# ---------------------------------------------------------------------------
# Tier-1 budget ordering.  These host-plane suites used to error at
# collection in minimal containers (module-level `cryptography` imports)
# and only recently became collectable; they run AFTER the long-
# established tier so a fixed wall-clock budget cuts the newest coverage
# first, never the baseline.
# ---------------------------------------------------------------------------

_LATE_MODULES = frozenset({
    "test_acl", "test_agent", "test_autoconfig", "test_cache",
    "test_cli_api", "test_cluster_agents", "test_config", "test_connect",
    "test_discoverychain", "test_eventing", "test_federation", "test_fsm",
    "test_http_dns", "test_memberlist", "test_multidc_host", "test_proxy",
    "test_realsocket_agent", "test_replication", "test_resilience",
    "test_sim_transport", "test_stream", "test_surface", "test_xds",
})


def pytest_collection_modifyitems(config, items):
    items.sort(  # stable: preserves order within each half
        key=lambda item: pathlib.Path(str(item.fspath)).stem
        in _LATE_MODULES
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        import asyncio

        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
