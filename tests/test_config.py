"""Config system: file+flag merge, HCL subset, validation, gossip
tuning blocks, and live reload of service/check definitions.

Parity model: agent/config/builder_test.go (merge order, validation),
runtime_test.go (frozen config), agent_test.go reload cases.
"""

import asyncio
import dataclasses
import json

import pytest

from helpers import wait_for as wait_until

from consul_tpu.agent.config import (
    Builder,
    ConfigError,
    RuntimeConfig,
    parse_hcl,
    reloadable_diff,
    thaw,
)


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------


def test_merge_order_later_wins_lists_append(tmp_path):
    f1 = tmp_path / "a.json"
    f1.write_text(json.dumps({
        "node_name": "n1", "datacenter": "dc1",
        "retry_join": ["x:1"],
        "service": {"name": "web", "port": 80},
    }))
    f2 = tmp_path / "b.json"
    f2.write_text(json.dumps({
        "datacenter": "dc9",
        "retry_join": ["y:2"],
        "service": {"name": "db", "port": 5432},
    }))
    rc = Builder().add_file(f1).add_file(f2).build()
    assert rc.node_name == "n1"
    assert rc.datacenter == "dc9"          # later file wins scalars
    assert rc.retry_join == ("x:1", "y:2")  # lists append
    assert len(rc.services) == 2

    # Flags merge last (highest precedence).
    rc2 = (Builder().add_file(f1).add_file(f2)
           .add_flags({"datacenter": "dcF"}).build())
    assert rc2.datacenter == "dcF"


def test_unknown_key_and_bad_values_rejected(tmp_path):
    f = tmp_path / "bad.json"
    f.write_text(json.dumps({"no_such_key": 1}))
    with pytest.raises(ConfigError, match="no_such_key"):
        Builder().add_file(f).build()

    with pytest.raises(ConfigError, match="bootstrap_expect"):
        Builder().add_flags({"bootstrap_expect": 0}).build()
    with pytest.raises(ConfigError, match="allow|deny"):
        Builder().add_flags(
            {"acl": None, "acl_default_policy": "maybe"}
        ).build()
    with pytest.raises(ConfigError, match="needs a name"):
        Builder().add_flags({"services": [{"port": 80}]}).build()


def test_config_dir_lexical_order(tmp_path):
    d = tmp_path / "conf.d"
    d.mkdir()
    (d / "10-base.json").write_text(json.dumps({"datacenter": "dc1"}))
    (d / "20-over.json").write_text(json.dumps({"datacenter": "dc2"}))
    (d / "ignored.txt").write_text("not config")
    rc = Builder().add_dir(d).build()
    assert rc.datacenter == "dc2"


def test_acl_and_ports_blocks(tmp_path):
    f = tmp_path / "acl.json"
    f.write_text(json.dumps({
        "acl": {"enabled": True, "default_policy": "deny",
                "tokens": {"master": "root", "agent": "agent-tok"}},
        "ports": {"http": 9500, "dns": 9600},
    }))
    rc = Builder().add_file(f).build()
    assert rc.acl_enabled and rc.acl_default_policy == "deny"
    assert rc.acl_master_token == "root"
    assert rc.acl_agent_token == "agent-tok"
    assert rc.ports_http == 9500 and rc.ports_dns == 9600


def test_gossip_tuning_block_produces_profile(tmp_path):
    f = tmp_path / "gossip.json"
    f.write_text(json.dumps({
        "gossip_lan": {"gossip_interval_ms": 100, "gossip_nodes": 5},
        "gossip_wan": {"probe_interval_ms": 9000},
    }))
    rc = Builder().add_file(f).build()
    lan = rc.gossip_profile()
    assert lan.gossip_interval_ms == 100 and lan.gossip_nodes == 5
    assert lan.probe_interval_ms == 1000      # untouched defaults
    wan = rc.gossip_profile(wan=True)
    assert wan.probe_interval_ms == 9000
    assert wan.gossip_interval_ms == 500

    bad = tmp_path / "badgossip.json"
    bad.write_text(json.dumps({"gossip_lan": {"bogus_knob": 1}}))
    with pytest.raises(ConfigError, match="bogus_knob"):
        Builder().add_file(bad).build()


# ---------------------------------------------------------------------------
# HCL subset
# ---------------------------------------------------------------------------


def test_hcl_equivalent_to_json(tmp_path):
    hcl = tmp_path / "agent.hcl"
    hcl.write_text("""
# consul-style config
node_name = "hclnode"
server = true
bootstrap_expect = 1
retry_join = ["a:1", "b:2"]
acl {
    enabled = true
    default_policy = "deny"
}
service {
    name = "web"
    port = 8080
}
gossip_lan {
    gossip_nodes = 4
}
""")
    rc = Builder().add_file(hcl).build()
    assert rc.node_name == "hclnode" and rc.server
    assert rc.retry_join == ("a:1", "b:2")
    assert rc.acl_enabled and rc.acl_default_policy == "deny"
    assert thaw(rc.services[0])["name"] == "web"
    assert rc.gossip_profile().gossip_nodes == 4


def test_hcl_repeated_service_blocks_accumulate():
    """Repeated `service { }` blocks accumulate (hcl list semantics),
    and the builder normalizes them into services."""
    cfg = parse_hcl("""
service { name = "a" port = 1 }
service { name = "b" port = 2 }
""")
    assert [s["name"] for s in cfg["service"]] == ["a", "b"]


def test_hcl_syntax_error():
    with pytest.raises(ConfigError):
        parse_hcl('key = = "x"')


# ---------------------------------------------------------------------------
# reload
# ---------------------------------------------------------------------------


def test_reloadable_diff_splits_fields():
    old = RuntimeConfig(node_name="n", dns_only_passing=False)
    new = dataclasses.replace(old, dns_only_passing=True)
    assert reloadable_diff(old, new) == {"dns_only_passing": True}

    renamed = dataclasses.replace(old, node_name="other")
    with pytest.raises(ConfigError, match="node_name"):
        reloadable_diff(old, renamed)


def test_cli_agent_boots_from_config_file_and_reloads(tmp_path):
    """Black-box: `cli agent -config-file X` boots a server whose HTTP
    API answers; SIGHUP re-reads the file and applies check changes
    (sdk/testutil.TestServer pattern, server.go:205-264)."""
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    cfg = tmp_path / "agent.json"
    cfg.write_text(json.dumps({
        "node_name": "cfg-node",
        "server": True,
        "ports": {"http": 0, "dns": 0},
        "service": {"name": "web", "port": 80},
        "check": {"id": "disk", "name": "disk", "ttl": "60s"},
    }))
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    proc = subprocess.Popen(
        [sys.executable, "-m", "consul_tpu.cli", "agent",
         "-config-file", str(cfg)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(tmp_path),
    )
    try:
        http_addr = None
        deadline = time.time() + 30
        lines = []
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if "HTTP addr:" in line:
                http_addr = line.split("HTTP addr:")[1].strip()
            if "RPC addr:" in line:
                break  # last line of the boot banner
        assert http_addr, "".join(lines)

        def get(path):
            with urllib.request.urlopen(
                f"http://{http_addr}{path}", timeout=5
            ) as resp:
                return json.loads(resp.read())

        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                if get("/v1/status/leader"):
                    break
            except Exception:
                pass
            time.sleep(0.3)
        assert get("/v1/status/leader"), "no leader elected"
        checks = get("/v1/agent/checks")
        assert "disk" in checks, checks

        # Reload: swap the disk check for a mem check.
        cfg.write_text(json.dumps({
            "node_name": "cfg-node",
            "server": True,
            "ports": {"http": 0, "dns": 0},
            "service": {"name": "web", "port": 80},
            "check": {"id": "mem", "name": "mem", "ttl": "60s"},
        }))
        proc.send_signal(signal.SIGHUP)
        deadline = time.time() + 10
        ok = False
        while time.time() < deadline:
            checks = get("/v1/agent/checks")
            if "mem" in checks and "disk" not in checks:
                ok = True
                break
            time.sleep(0.3)
        assert ok, checks
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_agent_reload_changes_check_definitions(tmp_path):
    """VERDICT r1 acceptance: reload changes check definitions without
    restart (agent.go reloadConfigInternal)."""

    async def main():
        from consul_tpu.agent.agent import Agent, AgentConfig
        from consul_tpu.net.transport import InMemoryNetwork

        cfg_file = tmp_path / "agent.json"
        cfg_file.write_text(json.dumps({
            "service": {"name": "web", "port": 80,
                        "checks": [{"id": "web-ttl", "name": "web ttl",
                                    "ttl": "60s"}]},
            "check": {"id": "disk", "name": "disk", "ttl": "60s"},
        }))
        rc1 = Builder().add_file(cfg_file).build()

        net = InMemoryNetwork()
        agent = Agent(
            AgentConfig(node_name="dev", bootstrap_expect=1,
                        gossip_interval_scale=0.05, sync_interval_s=0.3,
                        sync_retry_interval_s=0.2,
                        reconcile_interval_s=0.2),
            gossip_transport=net.new_transport("dev:gossip"),
            rpc_transport=net.new_transport("dev:rpc"),
        )
        await agent.start()
        await wait_until(lambda: agent.delegate.is_leader(), msg="leader")
        agent.load_definitions([thaw(s) for s in rc1.services],
                               [thaw(c) for c in rc1.checks])
        svc_names = {
            ls.service["service"] for ls in agent.local.services.values()
            if not ls.deleted
        }
        assert "web" in svc_names
        assert "disk" in agent.local.checks

        # Rewrite the file: the disk check is gone, a new http check
        # appears, the service stays.
        cfg_file.write_text(json.dumps({
            "service": {"name": "web", "port": 80,
                        "checks": [{"id": "web-ttl", "name": "web ttl",
                                    "ttl": "60s"}]},
            "check": {"id": "mem", "name": "mem", "ttl": "30s"},
        }))
        rc2 = Builder().add_file(cfg_file).build()
        agent.reload(reloadable_diff(rc1, rc2))

        assert "mem" in agent.local.checks
        disk = agent.local.checks.get("disk")
        assert disk is None or disk.deleted
        svc_names = {
            ls.service["service"] for ls in agent.local.services.values()
            if not ls.deleted
        }
        assert "web" in svc_names
        await agent.shutdown()

    asyncio.run(asyncio.wait_for(main(), 30))
