"""ConsulFSM: raft entry dispatch into the state store.

Parity model: ``agent/consul/fsm/fsm_test.go`` — apply commands as raft
entries, read back through the store, snapshot/restore round-trip,
unknown-type handling (``fsm/fsm.go:102-120``).
"""

import pytest

from consul_tpu.agent.fsm import IGNORE_UNKNOWN_FLAG, ConsulFSM, MessageType
from consul_tpu.consensus.raft import ENTRY_COMMAND, Entry


def ent(idx, msg_type, body):
    return Entry(index=idx, term=1, type=ENTRY_COMMAND,
                 data={"type": int(msg_type), "body": body})


@pytest.fixture
def fsm():
    return ConsulFSM()


def register(fsm, idx=1, node="n1", service=None, checks=None):
    body = {"node": node, "address": "10.0.0.1"}
    if service:
        body["service"] = service
    if checks:
        body["checks"] = checks
    return fsm.apply(ent(idx, MessageType.REGISTER, body))


class TestCatalogCommands:
    def test_register_and_read(self, fsm):
        register(fsm, 1, service={"service": "web", "port": 80})
        idx, nodes = fsm.store.nodes()
        assert idx == 1 and nodes[0]["node"] == "n1"
        _, svcs = fsm.store.service_nodes("web")
        assert svcs and svcs[0]["port"] == 80

    def test_deregister_service_only(self, fsm):
        register(fsm, 1, service={"service": "web"})
        fsm.apply(ent(2, MessageType.DEREGISTER,
                      {"node": "n1", "service_id": "web"}))
        _, svcs = fsm.store.service_nodes("web")
        assert svcs == []
        assert fsm.store.node("n1")[1] is not None

    def test_deregister_node(self, fsm):
        register(fsm, 1)
        assert fsm.apply(ent(2, MessageType.DEREGISTER, {"node": "n1"})) is True
        assert fsm.store.node("n1")[1] is None


class TestKVSCommands:
    def test_set_get_delete(self, fsm):
        fsm.apply(ent(1, MessageType.KVS,
                      {"op": "set", "entry": {"key": "a/b", "value": b"v"}}))
        _, rec = fsm.store.kv_get("a/b")
        assert rec["value"] == b"v" and rec["modify_index"] == 1
        assert fsm.apply(ent(2, MessageType.KVS,
                             {"op": "delete", "entry": {"key": "a/b"}})) is True
        assert fsm.store.kv_get("a/b")[1] is None

    def test_cas_semantics(self, fsm):
        ok = fsm.apply(ent(1, MessageType.KVS,
                           {"op": "cas",
                            "entry": {"key": "k", "value": b"1", "modify_index": 0}}))
        assert ok is True
        stale = fsm.apply(ent(2, MessageType.KVS,
                              {"op": "cas",
                               "entry": {"key": "k", "value": b"2", "modify_index": 99}}))
        assert stale is False
        assert fsm.store.kv_get("k")[1]["value"] == b"1"

    def test_invalid_op_is_domain_error(self, fsm):
        out = fsm.apply(ent(1, MessageType.KVS, {"op": "bogus", "entry": {}}))
        assert "error" in out


class TestSessionCommands:
    def test_create_and_destroy(self, fsm):
        register(fsm, 1, checks=[{"check_id": "serfHealth", "status": "passing"}])
        sid = fsm.apply(ent(2, MessageType.SESSION,
                            {"op": "create",
                             "session": {"id": "s1", "node": "n1"}}))
        assert sid == "s1"
        assert fsm.store.session_get("s1")[1] is not None
        assert fsm.apply(ent(3, MessageType.SESSION,
                             {"op": "destroy", "session": {"id": "s1"}})) is True

    def test_create_without_node_is_domain_error(self, fsm):
        out = fsm.apply(ent(1, MessageType.SESSION,
                            {"op": "create",
                             "session": {"id": "s1", "node": "ghost"}}))
        assert "error" in out

    def test_lock_released_on_destroy(self, fsm):
        register(fsm, 1, checks=[{"check_id": "serfHealth", "status": "passing"}])
        fsm.apply(ent(2, MessageType.SESSION,
                      {"op": "create", "session": {"id": "s1", "node": "n1"}}))
        assert fsm.apply(ent(3, MessageType.KVS,
                             {"op": "lock",
                              "entry": {"key": "lead", "value": b"n1",
                                        "session": "s1"}})) is True
        fsm.apply(ent(4, MessageType.SESSION,
                      {"op": "destroy", "session": {"id": "s1"}}))
        assert fsm.store.kv_get("lead")[1]["session"] is None


class TestTxnCommand:
    def test_atomic_all_or_nothing(self, fsm):
        out = fsm.apply(ent(1, MessageType.TXN, {"ops": [
            {"kv": {"verb": "set", "entry": {"key": "x", "value": b"1"}}},
            {"kv": {"verb": "check-index", "entry": {"key": "ghost",
                                                     "modify_index": 5}}},
        ]}))
        assert out["errors"] and out["results"] == []
        assert fsm.store.kv_get("x")[1] is None  # rolled back

    def test_malformed_op_is_per_op_error(self, fsm):
        # Missing verb / missing key must abort cleanly, not crash the FSM
        # or wedge the store's writer lock.
        out = fsm.apply(ent(1, MessageType.TXN, {"ops": [
            {"kv": {"entry": {"value": b"x"}}},
        ]}))
        assert out["errors"]
        # Store still writable after the failed txn.
        fsm.apply(ent(2, MessageType.KVS,
                      {"op": "set", "entry": {"key": "ok", "value": b"1"}}))
        assert fsm.store.kv_get("ok")[1]["value"] == b"1"

    def test_txn_unlock_updates_value_like_kv_unlock(self, fsm):
        register(fsm, 1, checks=[{"check_id": "serfHealth", "status": "passing"}])
        fsm.apply(ent(2, MessageType.SESSION,
                      {"op": "create", "session": {"id": "s1", "node": "n1"}}))
        out = fsm.apply(ent(3, MessageType.TXN, {"ops": [
            {"kv": {"verb": "lock",
                    "entry": {"key": "lead", "value": b"mine", "session": "s1"}}},
            {"kv": {"verb": "unlock",
                    "entry": {"key": "lead", "value": b"released", "session": "s1"}}},
        ]}))
        assert out["errors"] == []
        rec = fsm.store.kv_get("lead")[1]
        assert rec["session"] is None and rec["value"] == b"released"

    def test_txn_empty_delete_tree_keeps_index(self, fsm):
        fsm.apply(ent(1, MessageType.KVS,
                      {"op": "set", "entry": {"key": "a", "value": b"1"}}))
        before = fsm.store.kv_get("a")[0]
        out = fsm.apply(ent(2, MessageType.TXN, {"ops": [
            {"kv": {"verb": "delete-tree", "entry": {"key": "nomatch/"}}},
        ]}))
        assert out["errors"] == []
        assert fsm.store.kv_get("a")[0] == before  # no phantom index bump

    def test_commit_and_results(self, fsm):
        out = fsm.apply(ent(1, MessageType.TXN, {"ops": [
            {"kv": {"verb": "set", "entry": {"key": "x", "value": b"1"}}},
            {"kv": {"verb": "get", "entry": {"key": "x"}}},
        ]}))
        assert out["errors"] == []
        assert out["results"][1]["kv"]["value"] == b"1"


class TestOtherCommands:
    def test_coordinate_batch(self, fsm):
        register(fsm, 1)
        fsm.apply(ent(2, MessageType.COORDINATE_BATCH_UPDATE, {"updates": [
            {"node": "n1", "coord": {"vec": [0.0] * 8}},
            {"node": "ghost", "coord": {"vec": [1.0] * 8}},  # skipped
        ]}))
        assert fsm.store.coordinate("n1") is not None
        assert fsm.store.coordinate("ghost") is None

    def test_prepared_query_lifecycle(self, fsm):
        fsm.apply(ent(1, MessageType.PREPARED_QUERY,
                      {"op": "create",
                       "query": {"id": "q1", "name": "web", "service": {"service": "web"}}}))
        assert fsm.store.prepared_query_get("q1")[1]["name"] == "web"
        assert fsm.apply(ent(2, MessageType.PREPARED_QUERY,
                             {"op": "delete", "query": {"id": "q1"}})) is True

    def test_config_entry_cas(self, fsm):
        fsm.apply(ent(1, MessageType.CONFIG_ENTRY,
                      {"op": "set",
                       "entry": {"kind": "service-defaults", "name": "web",
                                 "protocol": "http"}}))
        bad = fsm.apply(ent(2, MessageType.CONFIG_ENTRY,
                            {"op": "cas", "modify_index": 42,
                             "entry": {"kind": "service-defaults", "name": "web",
                                       "protocol": "grpc"}}))
        assert bad is False
        good = fsm.apply(ent(3, MessageType.CONFIG_ENTRY,
                             {"op": "cas", "modify_index": 1,
                              "entry": {"kind": "service-defaults", "name": "web",
                                        "protocol": "grpc"}}))
        assert good is True

    def test_acl_commands(self, fsm):
        fsm.apply(ent(1, MessageType.ACL_POLICY_SET,
                      {"policy": {"id": "p1", "name": "ro", "rules": ""}}))
        fsm.apply(ent(2, MessageType.ACL_TOKEN_SET,
                      {"token": {"secret_id": "t1", "policies": ["p1"]}}))
        assert fsm.store.acl_token_get("t1")["policies"] == ["p1"]
        assert fsm.apply(ent(3, MessageType.ACL_TOKEN_DELETE,
                             {"secret_id": "t1"})) is True

    def test_tombstone_reap(self, fsm):
        fsm.apply(ent(1, MessageType.KVS,
                      {"op": "set", "entry": {"key": "k", "value": b"v"}}))
        fsm.apply(ent(2, MessageType.KVS, {"op": "delete", "entry": {"key": "k"}}))
        reaped = fsm.apply(ent(3, MessageType.TOMBSTONE, {"op": "reap", "index": 2}))
        assert reaped == 1


class TestUnknownTypes:
    def test_unknown_raises(self, fsm):
        with pytest.raises(ValueError):
            fsm.apply(ent(1, 99, {}))

    def test_ignore_flag_skips(self, fsm):
        assert fsm.apply(ent(1, 99 | IGNORE_UNKNOWN_FLAG, {})) is None


class TestSnapshotRestore:
    def test_round_trip(self, fsm):
        register(fsm, 1, service={"service": "web", "port": 80})
        fsm.apply(ent(2, MessageType.KVS,
                      {"op": "set", "entry": {"key": "a", "value": b"1"}}))
        snap = fsm.snapshot()

        other = ConsulFSM()
        other.restore(snap)
        assert other.store.node("n1")[1]["address"] == "10.0.0.1"
        idx, rec = other.store.kv_get("a")
        assert rec["value"] == b"1" and idx == 2  # indexes preserved
