"""RPC plumbing: mux, concurrent muxed calls, errors, blocking queries.

Parity model: the reference's rpc_test.go (conn mux byte routing,
method-not-found errors) and blockingQuery semantics (rpc.go:759-861).
"""

import asyncio

import pytest

from consul_tpu.agent.rpc import (
    QueryOptions,
    RPC_RAFT,
    RPCClient,
    RPCError,
    RPCServer,
    blocking_query,
    snake,
)
from consul_tpu.net.transport import InMemoryNetwork
from consul_tpu.store.state import StateStore


class Echo:
    async def say(self, body):
        return {"echo": body["msg"]}

    async def slow(self, body):
        await asyncio.sleep(body["delay"])
        return {"done": body["delay"]}

    async def boom(self, body):
        raise ValueError("kaboom")


@pytest.fixture
def net():
    return InMemoryNetwork()


async def start_server(net, name="srv"):
    t = net.new_transport(name)
    srv = RPCServer(t)
    srv.register("Echo", Echo())
    await srv.start()
    return srv, t


def test_snake_names():
    assert snake("Apply") == "apply"
    assert snake("ServiceNodes") == "service_nodes"
    assert snake("ListKeys") == "list_keys"
    assert snake("RPCAddr") == "rpc_addr"


class TestMuxedRPC:
    @pytest.mark.asyncio
    async def test_call_roundtrip(self, net):
        srv, _ = await start_server(net)
        client = RPCClient(net.new_transport("cli"))
        out = await client.call("srv", "Echo.Say", {"msg": "hi"})
        assert out == {"echo": "hi"}
        await client.shutdown()
        await srv.shutdown()

    @pytest.mark.asyncio
    async def test_concurrent_calls_one_conn(self, net):
        srv, _ = await start_server(net)
        client = RPCClient(net.new_transport("cli"))
        # The slow call is issued first but must not block the fast one:
        # requests are multiplexed by seq on a single stream.
        slow = asyncio.create_task(
            client.call("srv", "Echo.Slow", {"delay": 0.2})
        )
        fast = await client.call("srv", "Echo.Say", {"msg": "fast"})
        assert fast == {"echo": "fast"}
        assert not slow.done()
        assert await slow == {"done": 0.2}
        assert len(client._conns) == 1
        await client.shutdown()
        await srv.shutdown()

    @pytest.mark.asyncio
    async def test_remote_error(self, net):
        srv, _ = await start_server(net)
        client = RPCClient(net.new_transport("cli"))
        with pytest.raises(RPCError, match="kaboom"):
            await client.call("srv", "Echo.Boom", {})
        with pytest.raises(RPCError, match="can't find method"):
            await client.call("srv", "Echo.Nope", {})
        with pytest.raises(RPCError, match="can't find method"):
            await client.call("srv", "Ghost.Say", {})
        await client.shutdown()
        await srv.shutdown()

    @pytest.mark.asyncio
    async def test_raft_mux_byte(self, net):
        srv, _ = await start_server(net)
        seen = []

        async def raft_handler(method, body):
            seen.append(method)
            return {"term": 7}

        srv.bind_raft(raft_handler)
        raft_client = RPCClient(net.new_transport("peer"), rpc_type=RPC_RAFT)
        out = await raft_client.call("srv", "AppendEntries", {"term": 7})
        assert out == {"term": 7} and seen == ["AppendEntries"]
        await raft_client.shutdown()
        await srv.shutdown()

    @pytest.mark.asyncio
    async def test_call_timeout_keeps_connection(self, net):
        # A timed-out long-poll must not tear down the shared muxed conn
        # (other in-flight calls keep going).
        srv, _ = await start_server(net)
        client = RPCClient(net.new_transport("cli"))
        inflight = asyncio.create_task(
            client.call("srv", "Echo.Slow", {"delay": 0.3})
        )
        with pytest.raises(asyncio.TimeoutError):
            await client.call("srv", "Echo.Slow", {"delay": 5}, timeout=0.1)
        assert await inflight == {"done": 0.3}
        assert await client.call("srv", "Echo.Say", {"msg": "alive"}) == {
            "echo": "alive"
        }
        await client.shutdown()
        await srv.shutdown()

    @pytest.mark.asyncio
    async def test_server_death_fails_pending(self, net):
        srv, t = await start_server(net)
        client = RPCClient(net.new_transport("cli"))
        await client.call("srv", "Echo.Say", {"msg": "warm"})
        task = asyncio.create_task(
            client.call("srv", "Echo.Slow", {"delay": 5}, timeout=1.0)
        )
        await asyncio.sleep(0.05)
        await srv.shutdown()
        await t.shutdown()
        with pytest.raises((ConnectionError, asyncio.TimeoutError)):
            await task
        await client.shutdown()


class TestBlockingQuery:
    @pytest.mark.asyncio
    async def test_nonblocking_when_index_zero(self):
        store = StateStore()
        store.kv_set(3, {"key": "a", "value": b"1"})

        def run(ws):
            return store.kv_get("a", ws=ws)

        meta, rec = await blocking_query(store, QueryOptions(), run)
        assert meta.index == 3 and rec["value"] == b"1"

    @pytest.mark.asyncio
    async def test_write_wakes_blocked_reader(self):
        store = StateStore()
        store.kv_set(3, {"key": "a", "value": b"1"})

        def run(ws):
            return store.kv_get("a", ws=ws)

        async def blocked():
            return await blocking_query(
                store, QueryOptions(min_query_index=3, max_query_time=5), run
            )

        task = asyncio.create_task(blocked())
        await asyncio.sleep(0.05)
        assert not task.done()
        store.kv_set(4, {"key": "a", "value": b"2"})
        meta, rec = await asyncio.wait_for(task, 2)
        assert meta.index == 4 and rec["value"] == b"2"

    @pytest.mark.asyncio
    async def test_timeout_returns_unchanged_index(self):
        store = StateStore()
        store.kv_set(3, {"key": "a", "value": b"1"})

        def run(ws):
            return store.kv_get("a", ws=ws)

        meta, _ = await asyncio.wait_for(
            blocking_query(
                store, QueryOptions(min_query_index=3, max_query_time=0.1), run
            ),
            2,
        )
        assert meta.index == 3

    @pytest.mark.asyncio
    async def test_index_floor_is_one(self):
        store = StateStore()

        def run(ws):
            return store.kv_get("missing", ws=ws)

        meta, rec = await blocking_query(store, QueryOptions(), run)
        assert meta.index == 1 and rec is None

    @pytest.mark.asyncio
    async def test_store_abandon_wakes_reader(self):
        store = StateStore()
        store.kv_set(3, {"key": "a", "value": b"1"})

        def run(ws):
            return store.kv_get("a", ws=ws)

        task = asyncio.create_task(
            blocking_query(
                store, QueryOptions(min_query_index=3, max_query_time=5), run
            )
        )
        await asyncio.sleep(0.05)
        store.abandon()  # snapshot restore path
        meta, _ = await asyncio.wait_for(task, 2)
        assert meta.index == 3


class TestStreamFlowControl:
    @pytest.mark.asyncio
    async def test_producer_stalls_at_window_until_client_consumes(
        self, net
    ):
        """yamux-style credit window: a server-streaming producer must
        stop at STREAM_WINDOW unconsumed frames instead of buffering
        without bound, and resume as the client's application drains."""
        from consul_tpu.agent.rpc import STREAM_WINDOW

        produced = []

        class Feed:
            async def subscribe(self, body):
                i = 0
                while True:
                    produced.append(i)
                    yield {"i": i}
                    i += 1

        t = net.new_transport("feed-srv")
        srv = RPCServer(t)
        srv.register("Feed", Feed())
        await srv.start()
        client = RPCClient(net.new_transport("feed-cli"))

        gen = client.stream("feed-srv", "Feed.Subscribe", {})
        # Consume ONE item, then stop consuming entirely.
        first = await asyncio.wait_for(gen.__anext__(), 5)
        assert first == {"i": 0}
        await asyncio.sleep(0.3)
        # The producer ran ahead by at most the window (+ a small queue
        # in flight), NOT unboundedly.
        assert len(produced) <= STREAM_WINDOW + 2, produced[-1]

        # Draining the stream grants credit and the producer resumes.
        for _ in range(STREAM_WINDOW * 2):
            await asyncio.wait_for(gen.__anext__(), 5)
        await asyncio.sleep(0.1)
        assert len(produced) > STREAM_WINDOW
        await gen.aclose()
