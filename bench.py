"""Headline benchmark: simulated gossip rounds/sec at 1M nodes.

Runs the north-star workload (BASELINE.json config 4): a 1,000,000-node
SWIM suspicion/dead-propagation study with 30% packet loss on the WAN
timing profile, as a single jitted lax.scan on whatever accelerator JAX
finds (one TPU chip under the driver).

Prints ONE JSON line:
  metric       sim_gossip_rounds_per_sec_1M
  value        steady-state simulated gossip rounds per wall-clock second
  vs_baseline  speedup over the real protocol's wall-clock rate: a real
               WAN-profile cluster advances one gossip round per
               GossipInterval (500 ms) regardless of hardware
               (memberlist/config.go:322), i.e. 2 rounds/sec; the
               reference has no faster way to study convergence than
               running (or the serf.io simulator, which is not in-repo).
               vs_baseline = value / 2.0.
"""

from __future__ import annotations

import json

from consul_tpu.models import SwimConfig
from consul_tpu.protocol import WAN
from consul_tpu.sim import run_swim

N = 1_000_000
STEPS = 100
REALTIME_ROUNDS_PER_SEC = 1000.0 / WAN.gossip_interval_ms  # 2.0


def main() -> None:
    # Aggregate (receiver-side Poissonized) delivery: the TPU-idiomatic
    # network model — elementwise RNG instead of 4M-message scatters.
    # Distributional equivalence to the exact per-message 'edges' mode is
    # pinned by tests/test_aggregate.py.
    cfg = SwimConfig(
        n=N, subject=42, loss=0.30, profile=WAN, delivery="aggregate"
    )
    report = run_swim(cfg, steps=STEPS, seed=0, warmup=True)
    value = report.rounds_per_sec
    print(
        json.dumps(
            {
                "metric": "sim_gossip_rounds_per_sec_1M",
                "value": round(value, 2),
                "unit": "rounds/s",
                "vs_baseline": round(value / REALTIME_ROUNDS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
