"""Headline benchmark: the north-star workloads at 1M nodes.

Primary metric (BASELINE.json config 4): a 1,000,000-node SWIM
suspicion/dead-propagation study with 30% packet loss on the WAN timing
profile, as a single jitted lax.scan on whatever accelerator JAX finds
(one TPU chip under the driver), in the TPU-idiomatic *aggregate*
(receiver-side Poissonized) network model whose distributional
equivalence to the exact per-message path is pinned by
tests/test_aggregate.py.

The ``extra`` field carries the honest companions VERDICT r1 asked for:
  edges_1M_rounds_per_sec   the EXACT per-message scatter path at the
                            same 1M/WAN/30%-loss config — no
                            approximation, every ping/suspect/dead
                            message materialized
  t99_dead_known_ms         simulated ms until 99% of live observers
                            view the subject DEAD (headline study)
  bcast_1M_t99_ms           simulated ms for a 1M-node LAN user-event
                            broadcast to reach 99% infection
                            (BASELINE config 3 scaled 10x) + its wall_s
  nodes_per_chip            population per device at the headline run
  fp_rate_1M / flaps_1M     Lifeguard accuracy A/B (sim/scenarios.py
                            degraded1m at reduced tick count): the 1M
                            false-positive suspicion rate and
                            incarnation-flap count with Lifeguard ON,
                            plus the _off twins and the reduction ratio
  jaxlint_peak_bytes        estimated peak-HBM per big-config program
                            (jaxlint J6, abstract eval only) — the
                            memory axis alongside wall-clock

vs_baseline: speedup over the real protocol's wall-clock rate — a real
WAN-profile cluster advances one gossip round per GossipInterval
(500 ms) regardless of hardware (memberlist/config.go:322), i.e. 2
rounds/sec; the reference has no faster way to study convergence than
running (the serf.io simulator is not in-repo).  vs_baseline = value/2.

Runtime guard: every section runs under per-section wall-clock
accounting (``section_wall_s`` in the JSON).  Setting
``BENCH_SECTION_BUDGET_S=<seconds>`` makes the run self-limiting: once
the cumulative wall clock passes the budget, remaining sections are
skipped cleanly — listed under ``"skipped"`` — instead of the whole
process being killed mid-section by an outer ``timeout`` (which loses
every datapoint already measured).

The ``multichip`` block is the real multi-device datapoint (the
sharded plane, consul_tpu/parallel/shard.py): on a multi-chip host the
exact per-message broadcast runs in-process across all devices at 1M
nodes/chip (8M aggregate on a v5e-8); on single-device CPU containers
it validates the same plane in a subprocess over 8 forced host devices
at small n (``python -m consul_tpu.parallel.shard``).
"""

from __future__ import annotations

import json
import os
import time

from consul_tpu.models import SwimConfig
from consul_tpu.models.broadcast import BroadcastConfig
from consul_tpu.protocol import LAN, WAN
from consul_tpu.sim import run_broadcast, run_swim

N = 1_000_000
# 450 WAN ticks = 225 s simulated: enough to cross the 1M-node suspicion
# timeout (6*log10(1e6)*5s = 180 s, memberlist/util.go:64-69) plus dead
# dissemination, so t99_dead_known is measurable in the headline run.
STEPS = 450
STEPS_EDGES = 100  # exact path: rate measurement only
REALTIME_ROUNDS_PER_SEC = 1000.0 / WAN.gossip_interval_ms  # 2.0


def _available_memory_gb():
    """MemAvailable from /proc/meminfo, or None when unreadable."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) / 1e6
    except (OSError, ValueError, IndexError):
        pass
    return None


def _sparse_arrival_count(mcfg) -> int:
    """Arrival-stream length of one sparse tick (gossip + compacted
    push/pull) — the model's own static-shape accounting."""
    from consul_tpu.models.membership_sparse import arrival_count

    return arrival_count(mcfg)


def _sparse_phase_times(mcfg, rounds_per_sec: float) -> dict:
    """Per-phase wall split of a sparse round: the jitted sort-merge
    delivery kernel timed alone on a synthetic stream of the round's
    exact shapes, vs everything else (gossip emit + probe/suspicion
    planes) as the remainder of the measured round time."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from consul_tpu.models.membership_sparse import (
        _merge_arrivals,
        sparse_membership_init,
    )

    base = mcfg.base
    n, K = base.n, min(mcfg.k_slots, base.n)
    A = _sparse_arrival_count(mcfg)
    st = sparse_membership_init(mcfg)
    rng = np.random.default_rng(0)
    stream = (
        jnp.asarray(rng.integers(0, n, A), jnp.int32),   # recv
        jnp.asarray(rng.integers(0, n, A), jnp.int32),   # subj
        jnp.asarray(rng.integers(0, 8, A), jnp.int32),   # val
        jnp.full((A,), -1, jnp.int32),                   # sus
        jnp.asarray(rng.random(A) < 0.5),                # ok
        jnp.ones((A,), bool),                            # alloc
    )

    @jax.jit
    def merge_once(slots, recv, subj, val, sus, ok, alloc):
        slots_t, key_rx, sus_rx, ov, fg = _merge_arrivals(
            slots, recv, subj, val, sus, ok, alloc, n, K,
            jnp.int32(0), jnp.int32(0),
        )
        return slots_t, key_rx, sus_rx, ov, fg

    slots = (st.slot_subj, st.key, st.suspect_since, st.confirms, st.tx)
    out = merge_once(slots, *stream)                     # compile once
    jax.tree_util.tree_map(np.asarray, out)
    iters = 2
    t0 = time.perf_counter()
    for _ in range(iters):
        out = merge_once(slots, *stream)
    jax.tree_util.tree_map(np.asarray, out)
    merge_s = (time.perf_counter() - t0) / iters
    total_s = 1.0 / rounds_per_sec if rounds_per_sec > 0 else float("inf")
    return {
        "sparse_phase_merge_s": round(merge_s, 4),
        "sparse_phase_other_s": round(max(total_s - merge_s, 0.0), 4),
    }


def _sweep_max_u(budget_bytes: int = 16 << 30) -> dict:
    """Max universes per chip under the J6 16 GB gate, per sweepable
    model at its bench shape: per-universe bytes from the U=8 vs U=1
    estimator delta (abstract traces, no device memory), then
    max_U = (budget - fixed) / per_universe.  U is the knob that blows
    the HBM budget first — this is the table that says how far each
    sweep can scale before it must shard."""
    import jax

    from consul_tpu.analysis.jaxlint import estimate_peak
    from consul_tpu.models import SparseMembershipConfig
    from consul_tpu.models.lifeguard import LifeguardConfig
    from consul_tpu.models.membership import MembershipConfig
    from consul_tpu.sweep.universe import abstract_sweep_program

    shapes = {
        "swim@4096": ("swim",
                      SwimConfig(n=4096, subject=7, fail_at_tick=0,
                                 loss=0.05),
                      10, ("loss",), ()),
        "lifeguard@1024": ("lifeguard",
                           LifeguardConfig(n=1024, subject=7,
                                           subject_alive=False,
                                           fail_at_tick=40, loss=0.40,
                                           ack_late=0.15,
                                           delivery="aggregate"),
                           10, ("loss",), ()),
        "membership@16k": ("membership",
                           MembershipConfig(n=16384, loss=0.01,
                                            profile=LAN,
                                            fail_at=((42, 5),)),
                           3, ("loss",), (42,)),
        "sparse@100k": ("sparse",
                        SparseMembershipConfig(
                            base=MembershipConfig(n=100_000, loss=0.01,
                                                  profile=LAN,
                                                  fail_at=((42, 5),)),
                            k_slots=64),
                        3, ("base.loss",), (42,)),
    }
    rows = {}
    for label, (model, cfg, steps, knobs, track) in shapes.items():
        peaks = {}
        for u in (1, 8):
            fn, args = abstract_sweep_program(model, cfg, steps, u,
                                              knobs, track)
            peaks[u] = estimate_peak(jax.make_jaxpr(fn)(*args)).chip_bytes
        per_u = max((peaks[8] - peaks[1]) / 7.0, 1.0)
        fixed = max(peaks[1] - per_u, 0.0)
        rows[label] = {
            "per_universe_bytes": int(per_u),
            "max_u_per_chip": int((budget_bytes - fixed) // per_u),
        }
    return rows


#: The streaming-bench workload (one shared shape for every policy so
#: the knees compare): W=7 slots of E=4-chunk events, fanout 4, a
#: 4-slot-per-round budget, delivery bar 99%.  Budget and bar are set
#: so the knee measures CHUNK CHOICE, not policy-blind overheads:
#: chunk_budget=2 made every policy collapse identically past the knee
#: (at a full window a node serviced each slot once per W/2 rounds —
#: service dilution, not duplicate waste, pinned slot lifetime), a
#: 99.9% bar pads every lifetime with the pure-Poisson straggler tail
#: that no schedule can shorten, and W calibrates the window so the
#: uniform baseline saturates at its historical 0.3 ev/tick anchor
#: (PR 8's knee).  The knee curve runs the PACED (staggered) arrival
#: stream — same mean load, zero burst variance — so the knee is the
#: deterministic capacity boundary rate x lifetime = W, not Poisson
#: burst luck.  The per-round bandwidth bound is chunk_budget x
#: fanout = 16 chunk copies/node under every policy.
_STREAM_WORK = dict(window=7, chunks=4, fanout=4, chunk_budget=4,
                    done_frac=0.99)

# The streaming section curves every chunk-selection policy, in
# registry order (streamcast.model.POLICIES, imported lazily inside
# _streaming_curve — bench keeps module import jax-free): the
# original uniform draw, the paper's round-robin pipeline, the greedy
# lowest-index twin.


def _stream_points(rep, rates) -> tuple:
    """(curve points, knee) off a streamload SweepReport — knee = the
    first offered load whose window overflowed."""
    import numpy as _np

    points, knee = [], None
    for i, rate in enumerate(rates):
        ov = int(rep.metrics["window_overflow"][i])
        t50 = rep.metrics["t50_ms"][i]
        t99 = rep.metrics["t99_ms"][i]
        points.append({
            "offered_rate_events_per_tick": rate,
            "offered_events_per_sim_s": round(
                float(rep.metrics["offered_events_per_sim_s"][i]), 3),
            "delivered_events_per_sim_s": round(
                float(rep.metrics["delivered_events_per_sim_s"][i]), 3),
            "t50_ms": None if _np.isnan(t50) else float(t50),
            "t99_ms": None if _np.isnan(t99) else float(t99),
            "window_overflow": ov,
        })
        if knee is None and ov > 0:
            knee = rate
    return points, knee


def _streaming_curve() -> dict:
    """The sustained-load throughput curve (consul_tpu/streamcast),
    PER SELECTION POLICY: delivered events/sec at the north-star n=1M
    versus the offered-load ladder, with per-event t50/t99 quantiles
    per point and the saturation knee — the first offered load whose
    pipeline window overflows.  Each policy's whole ladder runs in ONE
    vmapped program (``rate`` is a traced knob; the policy is static,
    so policy × load is exactly len(POLICIES) compiled programs).

    The deliverable headline is the KNEE MOVE: the paper's round-robin
    pipeline schedule stops wasting the fixed per-round budget on
    duplicate chunk re-draws, so its knee must sit at >= 2x uniform's
    (ROADMAP item 5 acceptance).  A second, adversarial ladder per
    policy (the streamadv preset: standing backlog = W, hotspot 0.5,
    heavy-tail severity as the knob) shows which schedule survives
    production-shaped traffic.

    CPU containers run at reduced n under the same MemAvailable
    discipline as the sparse-1M section — the curve's SHAPE and the
    knee (measured in offered-load units) are the deliverable there;
    the 1M magnitude belongs to accelerators.
    """
    import jax as _jax

    from consul_tpu.sim.engine import run_sweep
    from consul_tpu.streamcast.model import POLICIES as _STREAM_POLICIES
    from consul_tpu.sweep.presets import (
        stream_adversarial_ladder,
        stream_load_curve,
    )

    # The ladder brackets both knees: uniform first overflows at 0.3,
    # the pipeline schedule must stay clean there and knee at >= 0.6.
    rates = (0.1, 0.3, 0.6, 1.2)
    steps = 150
    n = 1_000_000
    out: dict = {}
    if _jax.default_backend() == "cpu":
        # CPU containers measure the curve's SHAPE at reduced n (the
        # 1M x U transient draw planes would cost minutes per round);
        # MemAvailable picks how reduced.  ~14 bytes per (universe,
        # node, slot, chunk) covers the uniform draws + bool planes
        # with slack.
        n = 100_000
        need_gb = len(rates) * n * 8 * 4 * 14 / 1e9
        avail_gb = _available_memory_gb()
        if avail_gb is not None and avail_gb < need_gb:
            n = 25_000
        out["streaming_reduced_n"] = (
            f"cpu backend: curve measured at n={n} "
            f"({'unknown' if avail_gb is None else round(avail_gb, 1)}"
            "GB available)"
        )
    policies: dict = {}
    for pol in _STREAM_POLICIES:
        uni = stream_load_curve(n=n, rates=rates, steps=steps,
                                policy=pol, arrivals="paced",
                                **_STREAM_WORK)
        rep = run_sweep(uni, warmup=False)
        points, knee = _stream_points(rep, rates)
        policies[pol] = {
            "curve": points,
            "knee_rate": knee,
            "wall_s": round(rep.wall_s, 2),
        }
    out.update({
        "streaming_n": n,
        "streaming_steps": steps,
        "streaming_window": _STREAM_WORK["window"],
        "streaming_chunks_per_event": _STREAM_WORK["chunks"],
        "streaming_chunk_budget": _STREAM_WORK["chunk_budget"],
        "streaming_policies": policies,
        # Legacy top-level keys ride the uniform arm.  NOT continuous
        # with BENCH_r05-r14: the workload was recalibrated for the
        # policy comparison (window 8→7, budget 2→4, done_frac
        # 0.999→0.99, Poisson→paced, rate ladder 0.02-1.0→0.1-1.2) —
        # compare knees across revisions only within one workload.
        "streaming_workload_note":
            "recalibrated in PR 15 (policy seam): knees are NOT "
            "comparable to pre-PR-15 BENCH_r* values",
        "streaming_curve": policies["uniform"]["curve"],
        "streaming_knee_rate": policies["uniform"]["knee_rate"],
        "streaming_knee_rate_pipeline":
            policies["pipeline"]["knee_rate"],
        # The uniform arm's wall (the historical meaning of this key);
        # the per-policy walls ride streaming_policies[*].wall_s.
        "streaming_wall_s": policies["uniform"]["wall_s"],
    })

    # Adversarial ladder per policy: the window starts the run FULL
    # (backlog = W), half the arrivals publish from one hot node, and
    # the heavy-tail severity ladders as the traced knob — one vmapped
    # program per policy (streamadv preset).
    tails = (0.25, 0.5, 1.0, 2.0)
    adv: dict = {}
    for pol in _STREAM_POLICIES:
        uni = stream_adversarial_ladder(
            n=n, tails=tails, steps=steps, rate=0.3, policy=pol,
            **_STREAM_WORK,
        )
        rep = run_sweep(uni, warmup=False)
        rungs = []
        for i, tail in enumerate(tails):
            rungs.append({
                "size_tail": tail,
                "delivered_events_per_sim_s": round(float(
                    rep.metrics["delivered_events_per_sim_s"][i]), 3),
                "window_overflow": int(
                    rep.metrics["window_overflow"][i]),
                "events_quiesced": int(
                    rep.metrics["events_quiesced"][i]),
            })
        adv[pol] = {"rungs": rungs, "wall_s": round(rep.wall_s, 2)}
    out["streaming_adversarial"] = {
        "backlog": _STREAM_WORK["window"],
        "hotspot": 0.5,
        "offered_rate_events_per_tick": 0.3,
        "policies": adv,
    }
    return out


def _geo_section() -> dict:
    """The geo/WAN plane (consul_tpu/geo): adaptive vs fixed
    anti-entropy under a scheduled bandwidth brownout at the
    north-star n=1M (8 DCs, Vivaldi-derived link latencies), plus the
    Vivaldi coordinate relative error at convergence — the first bench
    datapoints for models/multidc-style and models/vivaldi workloads.

    Both arms run the SAME faulted universe and seed; the only delta
    is ``adaptive`` (the one-knob A/B seam).  The deliverable is the
    per-segment convergence split (t50/t99) and the loud per-link
    accounting: admitted WAN bytes, overflow, and stale waste.  CPU
    containers reduce n under the same MemAvailable discipline as the
    sparse/streaming sections — the A/B's SHAPE is the deliverable
    there; the 1M magnitude belongs to accelerators.
    """
    import dataclasses as _dc

    import jax as _jax

    from consul_tpu.geo.latency import derive_wan_latency
    from consul_tpu.geo.model import GeoConfig
    from consul_tpu.sim.engine import run_geo
    from consul_tpu.sim.faults import BandwidthSchedule, FaultSchedule

    n = 1_000_000
    steps = 160
    out: dict = {}
    if _jax.default_backend() == "cpu":
        # ~13 bytes per (node, event) covers the LAN draw + bool/int32
        # planes with slack at E=16.
        n = 100_000
        avail_gb = _available_memory_gb()
        if avail_gb is not None and avail_gb < n * 16 * 13 / 1e9:
            n = 25_000
        out["geo_reduced_n"] = (
            f"cpu backend: A/B measured at n={n} "
            f"({'unknown' if avail_gb is None else round(avail_gb, 1)}"
            "GB available)"
        )
    latency, vinfo = derive_wan_latency(
        8, 5, tick_ms=LAN.gossip_interval_ms, seed=0, rounds=400,
        wan_window=8,
    )
    base_bytes = 16 * 1400.0
    # Brownout to 10% capacity over ticks [5, 120), healed after.
    faults = FaultSchedule(bandwidth=(
        BandwidthSchedule(pieces=((5, 0.1 * base_bytes),
                                  (120, 64 * base_bytes))),
    ))
    # All events originate in DC 0 (non-bridge nodes): the primary-DC
    # publish pattern, so every outbound link must carry the FULL
    # event set through the brownout — the regime the adaptive
    # transfer exists for.
    seg_size, bridges, events = n // 8, 5, 16
    origins = tuple(
        bridges + e * (seg_size - bridges) // events
        for e in range(events)
    )
    cfg = GeoConfig(
        n=n, segments=8, bridges_per_segment=bridges, events=events,
        wan_latency_ticks=latency, wan_window=8,
        wan_capacity_bytes=base_bytes, wan_msg_bytes=1400,
        wan_queue_bytes=2 * base_bytes, ae_batch=16, adaptive=True,
        loss_wan=0.05, origins=origins, faults=faults,
    )
    arms = {}
    for label, adaptive in (("adaptive", True), ("fixed", False)):
        rep = run_geo(
            _dc.replace(cfg, adaptive=adaptive), steps=steps, seed=0,
            warmup=False,
        )
        s = rep.summary()
        arms[label] = {
            "t50_ms": s["t50_ms"],
            "t99_ms": s["t99_ms"],
            "segment_t99_ms": s["segment_t99_ms"],
            "wan_admitted_bytes": s["wan_admitted_bytes"],
            "wan_overflow_units": s["wan_overflow_units"],
            "wan_wasted_units": s["wan_wasted_units"],
            "accounting_ok": s["accounting_ok"],
        }
    out.update({
        "geo_n": n,
        "geo_steps": steps,
        "geo_segments": cfg.segments,
        "geo_events": cfg.events,
        "geo_arms": arms,
        "geo_adaptive_t99_ms": arms["adaptive"]["t99_ms"],
        "geo_fixed_t99_ms": arms["fixed"]["t99_ms"],
        "vivaldi_rel_rtt_error": round(vinfo["rel_rtt_error"], 4),
        "vivaldi_mean_cross_rtt_ms": round(
            vinfo["mean_cross_rtt_ms"], 1
        ),
    })
    return out


def _run_multichip() -> dict:
    """The sharded-plane datapoint (consul_tpu/parallel/shard.py)."""
    import subprocess
    import sys

    import jax

    if jax.device_count() > 1 and jax.default_backend() != "cpu":
        # Real multi-device host (accelerator backend): 1M nodes per
        # chip, exact per-message path, in-process (8M aggregate on a
        # v5e-8).  Forced host devices on a CPU container must NOT take
        # this branch — 8M in-process edges would run for hours; they
        # get the small-n subprocess validation below instead.  BOTH
        # exchange backends run at the same shapes, each with the
        # standalone exchange-vs-merge wall split, so the ring
        # kernel's overlap win is measured, not assumed.
        from consul_tpu.parallel import make_mesh
        from consul_tpu.parallel.shard import exchange_phase_walls

        mesh = make_mesh()
        ndev = int(mesh.devices.size)
        cfg = BroadcastConfig(
            n=1_000_000 * ndev, fanout=4, profile=LAN, delivery="edges"
        )
        backends = {}
        for ex in ("alltoall", "ring"):
            rep = run_broadcast(cfg, steps=30, seed=0, mesh=mesh,
                                warmup=True, exchange=ex)
            backends[ex] = {
                "rounds_per_sec": round(rep.rounds_per_sec, 2),
                "overflow": rep.overflow,
                **exchange_phase_walls(cfg, mesh, ex),
            }
            if ex == "alltoall":
                t99_ms = rep.summary()["t99_ms"]
        return {"multichip": {
            "devices": ndev,
            "nodes_aggregate": cfg.n,
            "nodes_per_device": cfg.n // ndev,
            "rounds_per_sec": backends["alltoall"]["rounds_per_sec"],
            "overflow": backends["alltoall"]["overflow"],
            "exchange_backend": "alltoall",
            "exchange_backends": backends,
            "t99_ms": t99_ms,
            "host_devices_forced": False,
        }}
    # Single-device container: validate the plane over 8 forced host
    # devices at small n, in a subprocess (XLA_FLAGS must be set before
    # the child's first backend use — impossible in THIS process).
    # --exchange both: the child times all_to_all AND the Pallas ring
    # kernel (interpret mode) at identical shapes, with per-round
    # exchange/merge wall splits in "exchange_backends".
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "consul_tpu.parallel.shard",
         "--devices", "8", "--n", "4096", "--steps", "30",
         "--exchange", "both"],
        capture_output=True, text=True, timeout=600, check=True, env=env,
    )
    return {"multichip": json.loads(out.stdout.strip().splitlines()[-1])}


def _owned_draws_section(sweepshard: dict, membership: dict) -> dict:
    """The owned per-(round, node) randomness plane datapoints:

      draw_term       the J6 draw-term before/after pin — one round's
                      draw planes at sparse@100k shapes, traced as the
                      pre-owned REPLICATED set (full [n, .] planes, the
                      O(n)/chip term every shard used to pay) vs the
                      owned set at blk = n/D for D in {1, 2, 4, 8}: the
                      per-chip draw bytes fall ~n/D.
      composed_max_u  the acceptance headline — composed sparse@100k
                      universes per 8-device mesh (live from the
                      sweepshard section's J6 table when it ran) vs the
                      PR 13 replicated-draw baseline, PINNED as a
                      historical constant (the code that produced it is
                      gone, the sparse_1m_flops precedent).
      rounds_per_sec  the steady-state sparse@100k throughput next to
                      its PR 12 pinned baseline — owned derivation adds
                      one vmapped fold_in per draw site, so this is the
                      "did the counter-based keys cost wall clock"
                      honesty row.

    Abstract J6 tracing only (zero device memory) except the reused
    live numbers; rides BENCH_SECTION_BUDGET_S like every section.
    """
    import jax as _jax
    import jax.numpy as _jnp

    from consul_tpu.analysis.jaxlint import estimate_peak
    from consul_tpu.ops import (
        bernoulli_mask_owned,
        owned_uniform,
        sample_peers_owned,
    )

    n, fanout, k_slots = 100_000, 3, 64

    def _peak(fn):
        return estimate_peak(
            _jax.make_jaxpr(fn)(_jax.random.PRNGKey(0))
        ).chip_bytes

    def replicated(key):
        # The pre-owned draw set of one sparse round, full-population
        # on EVERY chip (PR 4's slice-per-block design).
        k1, k2, k3, k4, k5 = _jax.random.split(key, 5)
        return (_jax.random.uniform(k1, (n, k_slots)),
                _jax.random.randint(k2, (n, fanout), 0, n - 1,
                                    dtype=_jnp.int32),
                _jax.random.uniform(k3, (n, fanout)),
                _jax.random.uniform(k4, (n,)),
                _jax.random.uniform(k5, (n,)))

    def owned(blk):
        def f(key):
            ids = _jnp.arange(blk, dtype=_jnp.int32)
            k1, k2, k3, k4, k5 = _jax.random.split(key, 5)
            return (owned_uniform(k1, ids, (k_slots,)),
                    sample_peers_owned(k2, ids, n, fanout),
                    bernoulli_mask_owned(k3, ids, (fanout,), 0.9),
                    owned_uniform(k4, ids),
                    owned_uniform(k5, ids))
        return f

    repl_bytes = _peak(replicated)
    table = {
        "replicated_full_population_bytes": int(repl_bytes),
        "owned_bytes_per_chip": {
            f"D{d}": int(_peak(owned(n // d))) for d in (1, 2, 4, 8)
        },
    }
    d8 = table["owned_bytes_per_chip"]["D8"]
    table["owned_D8_vs_replicated"] = round(d8 / repl_bytes, 4)

    out: dict = {"draw_term_sparse100k": table}

    # Composed max-U: live from sweepshard's compose table; baseline
    # pinned (PR 13, replicated draws: 58.1 MB/universe/chip -> 295
    # universes per 8-device mesh).
    comp = (sweepshard or {}).get("composed", {})
    live = (comp.get("max_u_table", comp) or {}).get("sparse@100k", {})
    max_u = {
        "composed_max_u_pr13_baseline_pinned": 295,
        "per_universe_mb_per_chip_pr13_baseline_pinned": 58.1,
    }
    composed_live = live.get("composed_D8") or next(
        (v for k, v in live.items() if k.startswith("composed_D")), None
    )
    if composed_live:
        max_u["composed_max_u_live"] = composed_live["max_u"]
        max_u["per_universe_mb_per_chip_live"] = round(
            composed_live["per_universe_bytes_per_chip"] / 1e6, 1
        )
        max_u["max_u_vs_pr13_baseline"] = round(
            composed_live["max_u"] / 295, 2
        )
    out["composed_sparse100k_max_u"] = max_u

    # Wall-clock honesty row (the steady-state number is measured by
    # the membership_sparse_100k section; reused, not re-run).
    rps = (membership or {}).get("membership_sparse_rounds_per_sec")
    out["sparse100k_steady_rounds_per_sec"] = {
        "pr12_baseline_pinned": 1.31,
        "live": rps,
    }
    return out


def _sweepshard_section() -> dict:
    """The sweep x shard composition datapoints (ROADMAP item 4):

      composed        J6-derived max-U table for the composed
                      sparse@100k program (universes per 8-device mesh
                      vs the single-chip cap) plus a REAL composed run
                      (U x n/D per device) with its loud overflow
                      column — in-process on a multi-device
                      accelerator, via the forced-host-device
                      subprocess on CPU containers.
      optimizer       evaluations-to-knee: ``--optimize`` bisection on
                      a fine streamload ladder vs the fixed grid's
                      cost, with the knee error in grid cells.
      vmap_cond_cost  the vmap-pays-both-cond-branches datapoint
                      (select semantics): sweep-sparse rounds/s vs the
                      unsharded single study x U, with the static
                      ``amortize=False`` escape hatch measured
                      alongside.
    """
    import subprocess
    import sys as _sys

    import jax as _jax
    import numpy as _np

    out: dict = {}

    # -- composed max-U + real run ---------------------------------
    try:
        if _jax.device_count() > 1 and _jax.default_backend() != "cpu":
            from consul_tpu.sweep.compose import (
                _compose_max_u,
                _compose_real_run,
            )

            d = _jax.device_count()
            out["composed"] = {
                "devices": d,
                "max_u_table": _compose_max_u(d),
                "real_run": _compose_real_run(d, 100_000, 64, 4, 4, 0),
                "host_devices_forced": False,
            }
        else:
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)
            child = subprocess.run(
                [_sys.executable, "-m", "consul_tpu.sweep.compose",
                 "--devices", "8", "--n", "16384", "--k", "32",
                 "--universes", "4", "--steps", "4"],
                capture_output=True, text=True, timeout=900,
                check=True, env=env,
            )
            out["composed"] = json.loads(
                child.stdout.strip().splitlines()[-1]
            )
    except Exception as e:  # noqa: BLE001 - keep the other datapoints
        out["composed_error"] = str(e)[:300]

    # -- optimizer: evaluations-to-knee vs the fixed grid ----------
    try:
        from consul_tpu.sim.engine import run_sweep
        from consul_tpu.sweep.optimize import optimize_sweep
        from consul_tpu.sweep.presets import stream_load_curve

        n_opt = 1024 if _jax.default_backend() == "cpu" else 100_000
        rates = tuple(round(0.02 + 0.03 * i, 4) for i in range(16))
        grid_uni = stream_load_curve(n=n_opt, rates=rates, steps=120)
        grid_rep = run_sweep(grid_uni, warmup=False)
        ov = _np.asarray(grid_rep.metrics["window_overflow"])
        passing = _np.flatnonzero(ov <= 0)
        grid_knee = float(rates[passing[-1]]) if passing.size else None
        res = optimize_sweep(grid_uni, "window_overflow", knee_at=0.0)
        opt_knee = res.best.get("rate")
        cell = res.cell["rate"]
        out["optimizer"] = {
            "n": n_opt,
            "grid_points": len(rates),
            "grid_knee_rate": grid_knee,
            "optimize_knee_rate": opt_knee,
            "knee_error_cells": (
                None if grid_knee is None or opt_knee is None
                else round(abs(opt_knee - grid_knee) / cell, 2)
            ),
            "evaluations": res.evaluations,
            "grid_evaluations": res.grid_evaluations,
            "evaluations_saved_vs_grid": (
                res.grid_evaluations - res.evaluations
            ),
            "generations": res.generations,
        }
    except Exception as e:  # noqa: BLE001
        out["optimizer_error"] = str(e)[:300]

    # -- vmap cond cost: sweep-sparse vs U x unsharded -------------
    try:
        import dataclasses as _dc
        import time as _time

        from consul_tpu.models import SparseMembershipConfig
        from consul_tpu.models.membership import MembershipConfig
        from consul_tpu.sim.engine import run_membership_sparse, run_sweep
        from consul_tpu.sweep.universe import Universe

        U, n_s, k_s, steps_s = 4, 4096, 16, 20
        scfg = SparseMembershipConfig(
            base=MembershipConfig(n=n_s, loss=0.01, profile=LAN,
                                  fail_at=((42, 5),)),
            k_slots=k_s,
        )
        single, _ov = run_membership_sparse(
            scfg, steps_s, seed=0, track=(42,), warmup=True
        )
        rows = {}
        for amortize in (True, False):
            cfg_a = _dc.replace(scfg, amortize=amortize)
            uni = Universe(
                entrypoint="sparse", cfg=cfg_a, steps=steps_s,
                seeds=tuple(range(U)), track=(42,),
                knobs=("base.loss",),
                values=(tuple(0.01 + 0.002 * u for u in range(U)),),
            )
            t0 = _time.perf_counter()
            rep = run_sweep(uni, warmup=True)
            rows[f"amortize_{str(amortize).lower()}"] = {
                "rounds_per_sec_aggregate": round(rep.rounds_per_sec, 2),
                "wall_s": round(_time.perf_counter() - t0, 2),
            }
        single_rps = steps_s / single.wall_s if single.wall_s else None
        out["vmap_cond_cost"] = {
            "universes": U,
            "n": n_s,
            "k_slots": k_s,
            "unsharded_single_rounds_per_sec": (
                round(single_rps, 2) if single_rps else None
            ),
            "u_x_single_rounds_per_sec": (
                round(U * single_rps, 2) if single_rps else None
            ),
            **rows,
            # < 1.0 means the sweep pays MORE than U independent
            # studies per round — the both-branches select tax the
            # amortize=False hatch exists to dodge.
            "sweep_efficiency_vs_u_singles": (
                round(rows["amortize_true"]["rounds_per_sec_aggregate"]
                      / (U * single_rps), 3)
                if single_rps else None
            ),
        }
    except Exception as e:  # noqa: BLE001
        out["vmap_cond_cost_error"] = str(e)[:300]
    return out


def main() -> None:
    budget_s = float(os.environ.get("BENCH_SECTION_BUDGET_S", "0") or 0)
    t_start = time.monotonic()
    section_wall: dict = {}
    skipped: list = []

    def section(name, fn, default=None):
        """One bench section under the global wall-clock budget: runs
        ``fn`` with its wall time recorded, or skips it (recorded in
        ``skipped``) once the cumulative clock passes
        BENCH_SECTION_BUDGET_S."""
        if budget_s and (time.monotonic() - t_start) > budget_s:
            skipped.append(name)
            return default
        t0 = time.monotonic()
        try:
            return fn()
        finally:
            section_wall[name] = round(time.monotonic() - t0, 1)

    # Headline: aggregate delivery (elementwise RNG, no scatters).
    # Always first — the budget can only cut the companions after it.
    def _headline():
        cfg = SwimConfig(
            n=N, subject=42, loss=0.30, profile=WAN, delivery="aggregate"
        )
        return run_swim(cfg, steps=STEPS, seed=0, warmup=True)

    report = section("swim_aggregate_1m", _headline)
    value = report.rounds_per_sec if report else None
    summary = report.summary() if report else {}

    # The exact path at the same config: every message a scatter.
    def _edges():
        edges_cfg = SwimConfig(
            n=N, subject=42, loss=0.30, profile=WAN, delivery="edges"
        )
        return run_swim(edges_cfg, steps=STEPS_EDGES, seed=0, warmup=True)

    edges_report = section("swim_edges_1m", _edges)

    # 1M-node event broadcast (BASELINE config 3 at 10x), LAN fanout 4.
    def _bcast():
        bcast_cfg = BroadcastConfig(
            n=N, fanout=4, profile=LAN, delivery="aggregate"
        )
        return run_broadcast(bcast_cfg, steps=60, seed=0, warmup=True)

    bcast_report = section("broadcast_1m", _bcast)
    bcast_summary = bcast_report.summary() if bcast_report else {}

    # Full-membership study past the dense O(N²) wall: 100k observers ×
    # 100k subjects via the top-K sparse model (models/
    # membership_sparse.py) — five dense [n, n] arrays would need
    # ~200 GB; the slot representation fits one chip.  overflow == 0
    # certifies the run dropped nothing (exactness ladder in the module
    # docstring).
    def _sparse_steady_state(mcfg, dead: int, tick: int = 200):
        """The converged post-detection state of the fail-at study:
        every live observer holds {self, dead@0-DEAD}, gossip has
        quiesced (tx == 0), no timers pending.  Starting here measures
        the amortized kernel's STEADY-STATE tick — the regime the
        sorted-row invariant is amortized for — without paying the
        multi-minute convergence wave first."""
        import jax.numpy as jnp

        from consul_tpu.models.membership import (
            NEVER,
            RANK_DEAD,
            make_key,
        )
        from consul_tpu.models.membership_sparse import (
            AGE_NONE,
            AWARE_DTYPE,
            CONF_DTYPE,
            SINCE_DTYPE,
            TX_DTYPE,
            SparseMembershipState,
        )

        n, K = mcfg.base.n, mcfg.k_slots
        ids = jnp.arange(n, dtype=jnp.int32)
        lo = jnp.minimum(ids, dead)
        hi = jnp.maximum(ids, dead)
        slot_subj = jnp.full((n, K), -1, jnp.int32)
        slot_subj = slot_subj.at[:, 0].set(lo)
        slot_subj = slot_subj.at[:, 1].set(
            jnp.where(ids == dead, -1, hi)
        )
        dead_key = jnp.int32(make_key(0, RANK_DEAD))
        key = jnp.zeros((n, K), jnp.int32)
        key = key.at[:, 1].set(
            jnp.where((hi == dead) & (ids != dead), dead_key, 0)
        )
        key = key.at[:, 0].set(
            jnp.where((lo == dead) & (ids != dead), dead_key, 0)
        )
        return SparseMembershipState(
            slot_subj=slot_subj,
            key=key,
            suspect_since=jnp.full((n, K), AGE_NONE, SINCE_DTYPE),
            confirms=jnp.zeros((n, K), CONF_DTYPE),
            tx=jnp.zeros((n, K), TX_DTYPE),
            own_inc=jnp.zeros((n,), jnp.int32),
            awareness=jnp.zeros((n,), AWARE_DTYPE),
            probe_pending_at=jnp.full((n,), NEVER, jnp.int32),
            probe_subject=jnp.zeros((n,), jnp.int32),
            overflow=jnp.int32(0),
            forgotten=jnp.int32(0),
            tick=jnp.int32(tick),
        )

    def _sparse_100k():
        try:
            import jax as _jax

            from consul_tpu.models import SparseMembershipConfig
            from consul_tpu.models.membership import MembershipConfig
            from consul_tpu.sim import run_membership_sparse
            from consul_tpu.sim.engine import sparse_membership_scan

            mcfg = SparseMembershipConfig(
                base=MembershipConfig(n=100_000, loss=0.01, profile=LAN,
                                      fail_at=((42, 5),)),
                k_slots=64,
            )
            out = {
                "membership_sparse_n": 100_000,
                "membership_sparse_k": 64,
            }
            # HEADLINE: steady-state rounds/s from the converged
            # post-detection state (amortized invariant: no slot
            # allocations, so every tick rides the sort-free fast
            # branch).  One warmup scan compiles + drains any residual
            # transient; the second identical program is timed.
            steps = 8
            st = _sparse_steady_state(mcfg, dead=42)
            st, _ = sparse_membership_scan(
                st, _jax.random.PRNGKey(1), mcfg, steps, (42,)
            )
            _jax.block_until_ready(st)
            t0 = time.perf_counter()
            st, souts = sparse_membership_scan(
                st, _jax.random.PRNGKey(2), mcfg, steps, (42,)
            )
            _jax.block_until_ready(souts)
            steady_s = (time.perf_counter() - t0) / steps
            out["membership_sparse_rounds_per_sec"] = round(
                1.0 / steady_s, 3)
            out["membership_sparse_steady_overflow"] = int(st.overflow)
            # Continuity datapoint: the legacy cold 30-tick run from
            # scratch (detection wave included — allocation ticks pay
            # the lex-sort, so this is the kernel's WORST regime).
            mreport, moverflow = run_membership_sparse(
                mcfg, steps=30, track=(42,), warmup=False
            )
            out["membership_sparse_cold_rounds_per_sec"] = round(
                mreport.rounds_per_sec, 2)
            out["membership_sparse_overflow"] = int(moverflow)
            try:
                # Merge-kernel vs emit/probe split of one ALLOCATION
                # round (synthetic half-unseated stream forces the
                # slow branch; the kernel timed standalone at round
                # shapes).  Own guard: a diagnostic failure must not
                # discard the headline metrics measured above.
                out.update(
                    _sparse_phase_times(mcfg, mreport.rounds_per_sec)
                )
            except Exception as e:  # noqa: BLE001 - keep the datapoint
                out["sparse_phase_error"] = str(e)[:200]
            return out
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"membership_sparse_error": str(e)[:200]}

    membership = section("membership_sparse_100k", _sparse_100k, {})

    # The configuration the sparse representation exists for: one
    # MILLION observers (dense state would need ~20 TB).  The arrival
    # sort peaks well past small-host RAM, so CPU containers without
    # headroom skip cleanly instead of OOMing; accelerators (device
    # memory, not MemAvailable) always try, with their own guard.
    def _sparse_1m():
        out = {}
        try:
            import jax as _jax

            from consul_tpu.models import SparseMembershipConfig
            from consul_tpu.models.membership import MembershipConfig
            from consul_tpu.sim import run_membership_sparse

            mcfg1m = SparseMembershipConfig(
                base=MembershipConfig(n=1_000_000, loss=0.01, profile=LAN,
                                      fail_at=((42, 5),)),
                k_slots=64,
            )
            need_gb = (
                _sparse_arrival_count(mcfg1m) * 4 * 24
                + 5 * 1_000_000 * 64 * 4 * 3
            ) / 1e9
            avail_gb = _available_memory_gb()
            if _jax.default_backend() == "cpu" and (
                avail_gb is None or avail_gb < need_gb
            ):
                out["membership_sparse_1m_skipped"] = (
                    f"cpu backend: ~{need_gb:.0f}GB needed, "
                    f"{'unknown' if avail_gb is None else round(avail_gb, 1)}"
                    "GB available"
                )
            else:
                r1m, ov1m = run_membership_sparse(
                    mcfg1m, steps=3, track=(42,), warmup=False
                )
                out["membership_sparse_1m_rounds_per_sec"] = round(
                    r1m.rounds_per_sec, 3
                )
                out["membership_sparse_1m_overflow"] = int(ov1m)
        except Exception as e:  # noqa: BLE001 - report, keep headline
            out["membership_sparse_1m_error"] = str(e)[:200]
        return out

    membership.update(section("membership_sparse_1m", _sparse_1m, {}))

    # The 10M-nodes-per-chip capacity claim, read ABSTRACTLY (zero
    # device memory: eval_shape traces + the J6 live-buffer estimator
    # + the rangelint interval ledger) — the v5e 16 GB gate PR 12's
    # narrowing/packing targets — plus the measured flops delta of the
    # amortized sort-merge kernel at 1M via the obs profile harness.
    def _sparse_capacity():
        out = {}
        try:
            import jax as _jax

            from consul_tpu.analysis.jaxlint import estimate_peak
            from consul_tpu.analysis.rangelint import narrowing_ledger
            from consul_tpu.sim.engine import sparse_program_at

            for nn, tag in ((1_000_000, "1m"), (10_000_000, "10m")):
                spec = sparse_program_at(nn)
                fn, args = spec.build()
                pk = estimate_peak(_jax.make_jaxpr(fn)(*args))
                out[f"sparse_{tag}_j6_peak_gib"] = round(
                    pk.total_bytes / 2**30, 3)
            out["sparse_10m_j6_budget_gib"] = 16
            from consul_tpu.sim.engine import jaxlint_registry as _reg

            led = narrowing_ledger(
                _reg(include=("big",))["sparse@1m"], 10_000_000
            )
            out["sparse_10m_rangelint_findings"] = len(led.findings)
            out["sparse_10m_certified_dtypes"] = {
                c.plane.replace("[0].", ""): c.dtype
                for c in led.certificates
                if c.plane in ("[0].tx", "[0].confirms",
                               "[0].awareness", "[0].suspect_since")
            }
        except Exception as e:  # noqa: BLE001 - report, keep headline
            out["sparse_capacity_error"] = str(e)[:200]
        try:
            from consul_tpu.obs.profile import profile_program
            from consul_tpu.sim.engine import jaxlint_registry

            prog = jaxlint_registry(include=("big",))["sparse@1m"]
            pf = profile_program(prog)
            # Baseline = the PR 10/11 obs-ledger reading of the same
            # program (full lex-sort + two argsort re-sorts per tick).
            # A PINNED historical constant, not re-measured here — the
            # key name says so; only flops_per_program is live.
            out["sparse_1m_flops_pr11_baseline_pinned"] = 56.4e9
            out["sparse_1m_flops_per_program"] = pf.flops
            out["sparse_1m_bytes_accessed"] = pf.bytes_accessed
        except Exception as e:  # noqa: BLE001 - report, keep headline
            out["sparse_flops_error"] = str(e)[:200]
        return out

    membership.update(
        section("sparse_capacity_10m", _sparse_capacity, {})
    )

    # Lifeguard accuracy A/B at the headline scale: degraded1m (2%
    # degraded members, WAN ack tail) at a reduced tick count so bench
    # wall time stays bounded — the FP-rate question only needs enough
    # probe cycles for the on/off split, not dead-propagation horizons.
    def _lifeguard():
        try:
            from consul_tpu.sim.scenarios import degraded1m

            lg = degraded1m(seed=0, steps=160)
            return {
                "fp_rate_1M": round(lg["fp_rate_on"], 4),
                "fp_rate_1M_off": round(lg["fp_rate_off"], 4),
                "fp_reduction_1M": (
                    round(lg["fp_reduction"], 4)
                    if lg["fp_reduction"] is not None else None
                ),
                "flaps_1M": lg["flaps_on"],
                "flaps_1M_off": lg["flaps_off"],
            }
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"lifeguard_error": str(e)[:200]}

    lifeguard = section("lifeguard_1m", _lifeguard, {})

    # Universe sweeps (consul_tpu/sweep): hundreds of (seed, knob,
    # fault) universes per compiled program.  Three numbers start the
    # batched-throughput trajectory: universes/sec on the U=256 seed
    # sweep (error bars from ONE program), the robustness/latency
    # Pareto frontier from the fanout x suspicion-scale grid, and the
    # max-U-per-chip table from jaxlint's J6 estimator (U is the knob
    # that blows the 16 GB budget first).
    def _sweep():
        try:
            import numpy as _np

            from consul_tpu.sim.engine import run_sweep
            from consul_tpu.sweep.presets import seed_sweep, tuning_grid

            out = {}
            rep = run_sweep(seed_sweep(universes=256), warmup=True)
            fs = rep.metrics["first_suspect_ms"]
            fs = fs[~_np.isnan(fs)]
            out.update({
                "sweep_universes": rep.U,
                "sweep_n": rep.n,
                "sweep_steps": rep.steps,
                "universes_per_sec": round(rep.universes_per_sec, 2),
                "sweep_rounds_per_sec_per_universe": round(
                    rep.rounds_per_sec_per_universe, 2),
                "sweep_rounds_per_sec_aggregate": round(
                    rep.rounds_per_sec, 1),
                # The error-bar payoff: first-detection stats over 256
                # independent seed universes.
                "first_suspect_ms_mean": round(float(fs.mean()), 1),
                "first_suspect_ms_p95": round(
                    float(_np.percentile(fs, 95)), 1),
                "first_suspect_defined": int(fs.size),
            })
            tun = run_sweep(tuning_grid(), warmup=True)
            frontier = tun.frontier(x="false_dead_mean",
                                    y="detect_t90_ms")
            out["sweep_grid_universes"] = tun.U
            out["sweep_frontier_points"] = len(frontier)
            out["sweep_frontier"] = frontier
            try:
                out["sweep_max_u_per_chip"] = _sweep_max_u()
            except Exception as e:  # noqa: BLE001 - keep the datapoints
                out["sweep_max_u_error"] = str(e)[:200]
            return out
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"sweep_error": str(e)[:200]}

    sweep = section("sweep", _sweep, {})

    # The sustained-load workload (consul_tpu/streamcast): the
    # throughput CURVE that replaces the one-shot bcast_1M_t99_ms
    # number — delivered events/sec vs offered load, t50/t99 delivery
    # quantiles per point, and the window-overflow saturation knee.
    def _streaming():
        try:
            return _streaming_curve()
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"streaming_error": str(e)[:200]}

    streaming = section("streaming", _streaming, {})

    # The geo/WAN plane (consul_tpu/geo): the adaptive-vs-fixed
    # anti-entropy A/B under a scheduled bandwidth brownout — the
    # multi-DC scenario axis, with Vivaldi coordinate error as the
    # latency-derivation evidence.
    def _geo():
        try:
            return _geo_section()
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"geo_error": str(e)[:200]}

    geo = section("geo", _geo, {})

    # The multichip datapoint: the sharded plane across real devices,
    # or its forced-host-device validation on single-chip containers —
    # replaces the dryrun-only multichip story.
    def _multichip():
        try:
            return _run_multichip()
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"multichip_error": str(e)[:200]}

    multichip = section("multichip", _multichip, {})

    # Sweep x shard composition + closed-loop autotuning datapoints
    # (consul_tpu/sweep: make_sweep(mesh=), optimize.py): composed
    # max-U-per-chip, evaluations-to-knee, and the vmapped-cond cost
    # with its amortize= escape hatch.
    def _sweepshard():
        try:
            return _sweepshard_section()
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"sweepshard_error": str(e)[:300]}

    sweepshard = section("sweepshard", _sweepshard, {})

    # The owned-draws randomness plane: the J6 draw-term ~n/D pin
    # (replicated-baseline trace vs owned blocks), the composed max-U
    # headline vs the PR 13 pinned baseline, and the steady-state
    # rounds/s honesty row (ops/sampling.py owned streams).
    def _owned():
        try:
            return _owned_draws_section(sweepshard, membership)
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"owned_draws_error": str(e)[:300]}

    owned_draws = section("owned_draws", _owned, {})

    # The memory axis of the perf trajectory: estimated peak-HBM per
    # benchmarked program from jaxlint's J6 estimator (consul_tpu/
    # analysis/jaxlint.py) over the big-config entrypoint registry.
    # Abstract eval only — eval_shape states + make_jaxpr programs, no
    # execution — so this costs seconds, not device time.  On a
    # single-device process the registry's sharded entries register at
    # D=1 (per-chip numbers still meaningful: blocks == whole arrays).
    def _jaxlint():
        try:
            from consul_tpu.analysis.jaxlint import peak_bytes_report

            return {"jaxlint_peak_bytes": peak_bytes_report(
                include=("big",)
            )}
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"jaxlint_error": str(e)[:200]}

    jaxlint_peaks = section("jaxlint", _jaxlint, {})

    # Program analysis (consul_tpu/analysis): wall time per static
    # pass — tracelint (AST), jaxlint (jaxpr shapes/bytes), rangelint
    # (jaxpr values) — over the big registry, plus the certified-
    # narrowing table for the sparse slot planes: per plane the proven
    # minimal dtype and the per-state-copy HBM delta at 1M AND at the
    # 10M-node capacity target (the registry scale hook).  Abstract
    # tracing only; the section rides BENCH_SECTION_BUDGET_S like
    # every other.
    def _analysis():
        try:
            import time as _t

            from consul_tpu.analysis import rangelint as _rl
            from consul_tpu.analysis import tracelint as _tl
            from consul_tpu.analysis.jaxlint import analyze_jaxpr
            from consul_tpu.sim.engine import jaxlint_registry

            out = {}
            t0 = _t.monotonic()
            viols = _tl.lint_paths(_tl.default_paths())
            out["tracelint_wall_s"] = round(_t.monotonic() - t0, 2)
            out["tracelint_violations"] = len(viols)
            programs = jaxlint_registry(include=("big",))
            n_jl = n_rl = 0
            t_jl = t_rl = t_tr = 0.0
            certs_1m = {}
            for name, spec in programs.items():
                t0 = _t.monotonic()
                traced = spec.trace()
                t_tr += _t.monotonic() - t0
                t0 = _t.monotonic()
                found, _peak = analyze_jaxpr(
                    name, traced, budget_bytes=16 << 30
                )
                n_jl += len(found)
                t_jl += _t.monotonic() - t0
                t0 = _t.monotonic()
                rep = _rl.analyze_spec(name, spec, traced=traced)
                n_rl += len(rep.findings)
                t_rl += _t.monotonic() - t0
                if name == "sparse@1m":
                    certs_1m = {c.plane: c for c in rep.certificates}
            out.update({
                "trace_wall_s": round(t_tr, 2),
                "jaxlint_wall_s": round(t_jl, 2),
                "rangelint_wall_s": round(t_rl, 2),
                "jaxlint_findings": n_jl,
                "rangelint_findings": n_rl,
            })
            led = _rl.narrowing_ledger(
                programs["sparse@1m"], 10_000_000
            )
            certs_10m = {c.plane: c for c in led.certificates}
            out["rangelint_findings_at_10m"] = len(led.findings)
            table = []
            for plane, c in sorted(certs_1m.items()):
                c10 = certs_10m.get(plane)
                table.append({
                    "plane": plane,
                    "dtype": c.dtype,
                    "proven_dtype": c.minimal,
                    "range": [c.lo, c.hi],
                    "hbm_delta_per_copy_1m": c.saved_bytes,
                    "hbm_delta_per_copy_10m": (
                        c10.saved_bytes if c10 else None
                    ),
                })
            out["narrowing_certificates_sparse"] = table

            # equivlint (consul_tpu/analysis/equivlint.py): the
            # exactness-ladder prover over the declared EQUIV_PAIRS.
            # Structural-only here (witness=False): the witnessed
            # ladder costs ~2 min of executions and has its own tier-1
            # home (tests/test_equivlint.py); bench reports what the
            # canonicalizer closes for free plus the trace+prove wall.
            # Pairs live on the small tier; trace it fresh (the big
            # traces above don't cover the pair programs).
            from consul_tpu.analysis import equivlint as _el

            t0 = _t.monotonic()
            small = jaxlint_registry(include=("small",))
            verdicts = _el.prove_pairs(small, witness=False)
            out["equivlint_wall_s"] = round(_t.monotonic() - t0, 2)
            out["equivlint_pairs"] = len(verdicts)
            for verdict in ("PROVED", "WITNESSED", "FAILED", "SKIPPED"):
                out[f"equivlint_{verdict.lower()}"] = sum(
                    1 for v in verdicts if v.verdict == verdict
                )
            return {"analysis": out}
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"analysis_error": str(e)[:200]}

    analysis = section("analysis", _analysis, {})

    # Program-level observability (consul_tpu/obs/profile.py): lower +
    # compile every big-registry entrypoint and report what XLA says —
    # cost_analysis flops/bytes-accessed per execution and the
    # trace/compile wall split; execution wall is additionally
    # measured per program under two LOUD budgets (skips recorded
    # per-entry, never silent): OBS_EXECUTE_BUDGET_S (default 60 s
    # cumulative execute wall; big CPU containers can't afford to
    # re-run every 1M study) and the global BENCH_SECTION_BUDGET_S
    # deadline, plus the MemAvailable guard the 1M sections use.
    def _observability():
        try:
            import jax as _jax

            from consul_tpu.obs.profile import profile_registry
            from consul_tpu.sim.engine import jaxlint_registry

            # Execution is an accelerator measurement: a CPU container
            # re-running every 1M study would eat the whole bench
            # budget, so it opts in via OBS_EXECUTE_BUDGET_S; real
            # accelerators execute by default under a 60 s cumulative
            # execute-wall budget (skips recorded per entry).
            exec_env = os.environ.get("OBS_EXECUTE_BUDGET_S", "")
            on_accel = _jax.default_backend() != "cpu"
            exec_budget = float(exec_env or ("60" if on_accel else "0"))
            mem_gb = _available_memory_gb()
            mem_ok = mem_gb is None or mem_gb > 12.0
            execute = exec_budget > 0 and mem_ok
            # Why execution did NOT run, stamped per entry below —
            # the guards themselves must not skip silently either.
            exec_off_reason = None
            if exec_budget > 0 and not mem_ok:
                exec_off_reason = (
                    f"MemAvailable {mem_gb:.1f} GB <= 12 GB guard"
                )
            elif exec_budget <= 0:
                exec_off_reason = (
                    "execution opt-in only on CPU backends "
                    "(set OBS_EXECUTE_BUDGET_S)"
                )
            # The section bounds its own wall too (compiling the big
            # sparse/dense programs costs minutes on CPU): headline
            # program first so its flops number always lands, heavy
            # compiles last, entries past the deadline skipped loudly.
            obs_budget = float(
                os.environ.get("OBS_SECTION_BUDGET_S", "240") or 0
            )
            deadline = (
                time.monotonic() + obs_budget if obs_budget else None
            )
            if budget_s:
                hard = t_start + budget_s
                deadline = min(deadline or hard, hard)
            programs = jaxlint_registry(include=("big",))
            # sparse@10m is an ABSTRACT-ONLY capacity gate (its own
            # "sparse_capacity_10m" section reads it through J6 +
            # rangelint): compiling or executing it here would burn
            # the obs budget on a program that must never run in CI.
            programs.pop("sparse@10m", None)
            order = sorted(
                programs,
                key=lambda k: (
                    k != "swim@1m",
                    ("sparse" in k) or ("membership@16k" in k),
                    k,
                ),
            )
            profiles = profile_registry(
                {k: programs[k] for k in order},
                execute=execute,
                execute_budget_s=exec_budget,
                deadline=deadline,
            )
            out = {}
            for p in profiles:
                if (p.execute_s is None and p.execute_skipped is None
                        and exec_off_reason):
                    p.execute_skipped = exec_off_reason
                entry = {
                    "flops": p.flops,
                    "bytes_accessed": p.bytes_accessed,
                    "trace_s": round(p.trace_s, 3),
                    "compile_s": round(p.compile_s, 3),
                }
                if p.execute_s is not None:
                    entry["execute_s"] = round(p.execute_s, 3)
                if p.execute_skipped:
                    entry["execute_skipped"] = p.execute_skipped
                if p.temp_bytes is not None:
                    entry["temp_bytes"] = p.temp_bytes
                out[p.name] = entry
            return {"observability": out}
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"observability_error": str(e)[:200]}

    observability = section("observability", _observability, {})

    # Host-plane KV/HTTP throughput vs the reference's published numbers
    # (bench/results-0.7.1.md: 3,780 PUT/s, 9,774 stale GET/s).  Run in
    # a clean subprocess: the host plane never touches JAX, and this
    # process's TPU-tunnel service threads would otherwise steal ~1/3
    # of the asyncio loop and understate the numbers.
    def _kv():
        import json as _json
        import subprocess
        import sys

        try:
            return _json.loads(
                subprocess.run(
                    [sys.executable, "-m", "consul_tpu.bench_kv"],
                    capture_output=True, text=True, timeout=120,
                    check=True,
                ).stdout.strip().splitlines()[-1]
            )
        except Exception as e:  # noqa: BLE001 - report, keep headline
            return {"kv_bench_error": str(e)}

    kv = section("kv_host_plane", _kv, {})

    print(
        json.dumps(
            {
                "metric": "sim_gossip_rounds_per_sec_1M",
                "value": round(value, 2) if value is not None else None,
                "unit": "rounds/s",
                "vs_baseline": (
                    round(value / REALTIME_ROUNDS_PER_SEC, 2)
                    if value is not None else None
                ),
                "skipped": skipped,
                "section_wall_s": section_wall,
                "extra": {
                    **({
                        "edges_1M_rounds_per_sec": round(
                            edges_report.rounds_per_sec, 2
                        ),
                        "edges_vs_realtime": round(
                            edges_report.rounds_per_sec
                            / REALTIME_ROUNDS_PER_SEC,
                            2,
                        ),
                    } if edges_report else {}),
                    "t99_dead_known_ms": summary.get("t99_dead_known_ms"),
                    "first_suspect_ms": summary.get("first_suspect_ms"),
                    **({
                        "bcast_1M_t99_ms": bcast_summary["t99_ms"],
                        "bcast_1M_wall_s": round(bcast_report.wall_s, 3),
                    } if bcast_report else {}),
                    # The headline scan is unsharded: the whole 1M-node
                    # population lives and steps on ONE chip; the
                    # multichip block is where the mesh earns its keep.
                    "nodes_per_chip": N,
                    **lifeguard,
                    **sweep,
                    **streaming,
                    **geo,
                    **membership,
                    **multichip,
                    "sweepshard": sweepshard,
                    "owned_draws": owned_draws,
                    **jaxlint_peaks,
                    **analysis,
                    **observability,
                    **kv,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
