"""Closed-loop autotuning: drive ``make_sweep`` from the frontier.

A grid preset (consul_tpu/sweep/presets.py) names a knob SPACE — the
paths, the bounds, and the resolution its ladder was drawn at — and
``cli sweep`` today burns the whole fixed grid even when the question
is "where is the knee".  This module closes the loop: a successive-
halving / bisection driver seeds one coarse batched generation (one
vmapped program, U points), culls to the surviving bracket HOST-side,
and re-batches the next generation inside the shrunken box — so the
answer costs a few generations of U evaluations instead of the full
grid.

Program-reuse discipline: every generation evaluates the SAME number
of points U, so the lru-cached sweep program (make_sweep — keyed on
(entrypoint, U, telemetry, mesh, exchange)) is traced ONCE and every
later generation re-runs it with new knob values — the knob-values-
never-retrace contract the sweep plane already pins.  Composed
mesh=/exchange= sweeps ride through unchanged (the driver is host
logic over run_sweep).

Three modes:

  min / max   successive halving toward the objective's arg-optimum:
              each generation keeps the best ~third of its lattice and
              shrinks the box to their bounding interval (one current
              grid-cell of margin per side), until every axis reaches
              the preset's own resolution.
  knee        1-D bisection for a threshold crossing: the largest knob
              value whose objective stays <= ``knee_at`` (e.g. the
              largest offered load with window_overflow == 0 — the
              saturation knee of the streamload ladder).  Each
              generation lays U points across the (pass, fail)
              bracket and tightens it to the adjacent pair.

NaN objectives (a universe where the metric is undefined) rank WORST
in every mode — an optimizer must never converge onto a universe that
failed to measure.

All host-side numpy; the device programs stay exactly the batched
sweeps.  Deterministic by construction: generations derive points from
the bracket arithmetic alone (no RNG), so a rerun retraces the same
trajectory.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from consul_tpu.sweep.frontier import ENTRYPOINT_METRICS
from consul_tpu.sweep.universe import Universe, knob_dtype

import jax.numpy as jnp


@dataclasses.dataclass
class OptimizeResult:
    """One closed-loop tuning answer, plus its full audit trail."""

    entrypoint: str
    objective: str
    mode: str                    # "min" | "max" | "knee"
    knee_at: float               # threshold (knee mode; else NaN)
    knobs: tuple                 # the VARYING knob paths searched
    fixed: dict                  # non-varying knob paths -> pinned value
    best: dict                   # knob values + objective at the answer
    bracket: dict                # path -> [lo, hi] final bracket
    cell: dict                   # path -> the preset grid's resolution
    evaluations: int             # universe-evaluations actually spent
    generations: int
    grid_evaluations: int        # the preset's own fixed-grid cost
    points_per_gen: int
    history: list                # per-generation {points, objective}
    overflow_total: int = None   # composed runs: summed outbox
                                 # overflow over EVERY generation
                                 # (None = unsharded / injected
                                 # evaluator — no outbox exists)

    def summary(self) -> dict:
        """JSON-ready (cli sweep --optimize / bench sweepshard)."""
        return {
            "entrypoint": self.entrypoint,
            "objective": self.objective,
            "mode": self.mode,
            **({"knee_at": self.knee_at}
               if self.mode == "knee" else {}),
            "knobs": list(self.knobs),
            "fixed": self.fixed,
            "best": self.best,
            "bracket": self.bracket,
            "cell": self.cell,
            "evaluations": self.evaluations,
            "generations": self.generations,
            "grid_evaluations": self.grid_evaluations,
            "points_per_gen": self.points_per_gen,
            "evaluations_saved_vs_grid": (
                self.grid_evaluations - self.evaluations
            ),
            # Overflow loud as always: a composed optimizer answer
            # derived from budget-overflowing generations must say so.
            **({"overflow_total": self.overflow_total}
               if self.overflow_total is not None else {}),
        }


def knob_space(universe: Universe) -> tuple:
    """(varying, fixed, bounds, cell) of a grid preset's knob space.

    ``varying`` — paths with >= 2 distinct ladder values (the search
    axes); ``fixed`` — single-valued paths pinned at their value;
    ``bounds[path]`` = (lo, hi) of the ladder; ``cell[path]`` = the
    ladder's finest adjacent spacing — the resolution the fixed grid
    was drawn at, and the optimizer's convergence target (landing
    "within one grid-cell" is exactly matching the grid's answer)."""
    varying, fixed, bounds, cell = [], {}, {}, {}
    for path, row in zip(universe.knobs, universe.values):
        vals = sorted(set(float(v) for v in row))
        if len(vals) < 2:
            fixed[path] = vals[0] if vals else None
            continue
        varying.append(path)
        bounds[path] = (vals[0], vals[-1])
        cell[path] = min(
            b - a for a, b in zip(vals, vals[1:])
        )
    return tuple(varying), fixed, bounds, cell


def _axis_points(lo: float, hi: float, g: int, is_int: bool) -> list:
    """g evenly spaced points over [lo, hi] (ints rounded, deduped by
    repetition so the count STAYS g — the program-reuse contract)."""
    if g == 1:
        pts = [0.5 * (lo + hi)]
    else:
        pts = [lo + (hi - lo) * i / (g - 1) for i in range(g)]
    if is_int:
        pts = [float(int(round(p))) for p in pts]
    return pts


def _grid_cost(universe: Universe) -> int:
    """Evaluations the preset's own fixed grid pays: its universe
    count — exactly what `cli sweep` without --optimize burns.  Not a
    span/cell or per-axis-product reconstruction: both invent phantom
    points on non-uniform or jointly-laddered (diagonal) presets."""
    return len(universe.values[0])


def _rebuild(universe: Universe, paths_to_rows: dict, U: int) -> Universe:
    """A U-point generation Universe: the preset's structure with its
    knob rows replaced (varying axes from the lattice, fixed axes
    repeated), seeds normalized to U copies of the preset's base seed
    (grid semantics: points differ only in their knob coordinates)."""
    values = tuple(
        tuple(paths_to_rows[p]) for p in universe.knobs
    )
    # seeds-only by construction: optimize_sweep rejects split_from=
    # universes up front (per-slot folded keys break grid semantics).
    return dataclasses.replace(
        universe, seeds=(universe.seeds[0],) * U, values=values
    )


def optimize_sweep(
    universe: Universe,
    objective: str,
    *,
    minimize: bool = False,
    knee_at: float = None,
    points_per_gen: int = None,
    max_generations: int = 12,
    mesh=None,
    exchange: str = "alltoall",
    telemetry: bool = False,
    evaluate=None,
) -> OptimizeResult:
    """Find the objective's optimum (or knee) over a grid preset's
    knob space in a few batched generations.

    ``universe`` is a GRID preset (>= 1 knob with >= 2 ladder values —
    the ladder defines bounds and the convergence cell).  ``objective``
    must be a registered metric of the entrypoint
    (frontier.ENTRYPOINT_METRICS — validated BEFORE any program runs,
    the cli sweep typo contract).  ``knee_at`` switches to knee mode:
    the answer is the largest value of the single varying knob whose
    objective stays <= knee_at.  ``mesh=``/``exchange=`` run every
    generation on the composed sweep x shard plane.

    ``evaluate`` (tests): a callable ``(values_rows: tuple) ->
    float[U]`` replacing the real run_sweep evaluator — the optimizer
    unit tests drive it against brute-force grid argmins on
    deterministic objectives."""
    if universe.entrypoint not in ENTRYPOINT_METRICS:
        raise ValueError(
            f"unknown entrypoint {universe.entrypoint!r}"
        )
    known = ENTRYPOINT_METRICS[universe.entrypoint]
    if objective not in known:
        raise ValueError(
            f"unknown objective {objective!r} for "
            f"{universe.entrypoint!r} sweeps "
            f"(have: {', '.join(sorted(known))})"
        )
    if universe.split_from is not None:
        raise ValueError(
            "optimize needs ONE shared key per generation (grid "
            "semantics: points differ only in their knob "
            "coordinates), but split_from= folds a DISTINCT key into "
            "every universe slot — the same knob value would measure "
            "differently depending on which lattice slot it lands "
            "in.  Build the grid preset with seeds=(s,) * U instead."
        )
    varying, fixed, bounds, cell = knob_space(universe)
    if not varying:
        raise ValueError(
            "nothing to optimize: every knob of this universe has a "
            "single ladder value — grid presets define the search "
            "space through their ladders"
        )
    if knee_at is not None and minimize:
        raise ValueError(
            "--minimize and --knee-at are contradictory: knee mode "
            "finds the largest knob value whose objective stays <= "
            "the threshold, not an arg-minimum — pick one"
        )
    mode = "knee" if knee_at is not None else (
        "min" if minimize else "max"
    )
    if mode == "knee" and len(varying) != 1:
        raise ValueError(
            f"knee mode bisects ONE knob axis; this space has "
            f"{len(varying)}: {list(varying)} — pin the others to a "
            "single ladder value"
        )
    is_int = {
        p: knob_dtype(p) == jnp.int32 for p in varying
    }

    k = len(varying)
    if points_per_gen is None:
        points_per_gen = 4 if k == 1 else max(2, round(9 ** (1 / k))) ** k
    if points_per_gen < 1:
        raise ValueError(
            f"points_per_gen must be >= 1, got {points_per_gen}"
        )
    if mode == "knee" and points_per_gen < 2:
        raise ValueError("knee mode needs >= 2 points per generation")
    # Per-axis lattice counts whose product is the (constant) U.
    # points_per_gen is a CEILING: it sizes the batched program (the
    # composed max-U-per-chip tables are exactly this bound), so the
    # lattice must never exceed it — reject rather than round up.
    if k == 1:
        per_axis = {varying[0]: points_per_gen}
        U = points_per_gen
    else:
        g = int(points_per_gen ** (1 / k))
        while (g + 1) ** k <= points_per_gen:
            g += 1
        if g < 2:
            raise ValueError(
                f"points_per_gen {points_per_gen} cannot lattice "
                f"{k} knob axes: the smallest shrinking lattice is "
                f"2**{k} = {2 ** k} points per generation"
            )
        per_axis = {p: g for p in varying}
        U = g ** k

    overflow_seen: list = []   # composed generations' outbox overflow
    if evaluate is None:
        def evaluate(values_rows):
            from consul_tpu.sim import engine

            gen = _rebuild(
                universe, dict(zip(universe.knobs, values_rows)), U
            )
            rep = engine.run_sweep(gen, warmup=False,
                                   telemetry=telemetry,
                                   mesh=mesh, exchange=exchange)
            if rep.outbox_overflow is not None:
                overflow_seen.append(
                    int(np.asarray(rep.outbox_overflow).sum())
                )
            return np.asarray(rep.metrics[objective], float)

    box = {p: list(bounds[p]) for p in varying}
    history = []
    evaluations = 0
    seen_pts: list = []   # (coords tuple, objective) over ALL gens
    generations = 0

    for _gen in range(max_generations):
        # Lattice over the current box (axis-major cartesian product).
        # Knee refinements lay points strictly INSIDE the bracket —
        # its endpoints were measured by the previous generation, and
        # re-paying them would halve the bisection rate (the bracket
        # shrinks by 1/(U+1) per interior generation instead of
        # 1/(U-1)).
        if mode == "knee" and _gen > 0:
            p0 = varying[0]
            lo, hi = box[p0]
            if is_int[p0]:
                # Integer axis: lay points over the DISTINCT interior
                # integers — naive rounding of evenly spaced reals
                # collides them onto each other and back onto the
                # already-measured bracket endpoints.  Repeats happen
                # only when the bracket holds < U interior integers
                # (inherent to the constant-U program-reuse contract;
                # a batch costs a batch either way).
                cands = [float(v) for v in
                         range(int(math.floor(lo)) + 1,
                               int(math.ceil(hi)))]
                if not cands:
                    cands = [float(int(round(0.5 * (lo + hi))))]
                pts = [cands[round(i * (len(cands) - 1) / (U - 1))]
                       if U > 1 else cands[len(cands) // 2]
                       for i in range(U)]
            else:
                pts = [lo + (hi - lo) * (i + 1) / (U + 1)
                       for i in range(U)]
            axes = {p0: pts}
        else:
            axes = {
                p: _axis_points(box[p][0], box[p][1], per_axis[p],
                                is_int[p])
                for p in varying
            }
        coords = [()]
        for p in varying:
            coords = [c + (v,) for c in coords for v in axes[p]]
        assert len(coords) == U
        rows = {
            p: [c[i] for c in coords] for i, p in enumerate(varying)
        }
        # Fixed axes repeat their pinned value; unknown paths cannot
        # exist (knob_space covered every preset knob).
        for p, v in fixed.items():
            rows[p] = [v] * U
        obj = np.asarray(evaluate(
            tuple(tuple(rows[p]) for p in universe.knobs)
        ), float)
        if obj.shape != (U,):
            raise ValueError(
                f"evaluator returned shape {obj.shape}, wanted ({U},)"
            )
        evaluations += U
        generations += 1
        history.append({
            "points": {p: list(rows[p]) for p in varying},
            "objective": [None if math.isnan(o) else float(o)
                          for o in obj],
        })
        seen_pts.extend(zip(coords, obj))

        if mode == "knee":
            p0 = varying[0]
            xs = np.asarray(rows[p0], float)
            order = np.argsort(xs)
            xs_s, obj_s = xs[order], obj[order]
            passing = ~np.isnan(obj_s) & (obj_s <= knee_at)
            # The bracket invariant: lo is the largest KNOWN-passing
            # value (or the box floor, unproven), hi the smallest
            # known-failing value above it (or the box ceiling).
            new_lo = (float(xs_s[np.flatnonzero(passing)[-1]])
                      if passing.any() else box[p0][0])
            fail_xs = xs_s[~passing]
            fail_xs = fail_xs[fail_xs > new_lo]
            new_hi = (float(fail_xs.min()) if fail_xs.size
                      else box[p0][1])
            box[p0] = [new_lo, new_hi]
            if new_hi - new_lo <= cell[p0] + 1e-12:
                break
        else:
            score = np.where(np.isnan(obj), np.inf, obj)
            if mode == "max":
                score = np.where(np.isnan(obj), np.inf, -obj)
            keep = np.argsort(score, kind="stable")[
                : max(1, -(-U // 3))
            ]
            done = True
            shrunk = False
            for i, p in enumerate(varying):
                vals = [coords[j][i] for j in keep]
                # Survivor bounding box + HALF a current-cell of
                # margin per side, clamped to the current box.  When
                # the survivors span the whole lattice the clamp keeps
                # the box unchanged — `shrunk` detects that below.
                span = 0.5 * (
                    axes[p][1] - axes[p][0]
                    if len(axes[p]) > 1 else cell[p]
                )
                lo = max(box[p][0], min(vals) - span)
                hi = min(box[p][1], max(vals) + span)
                if hi <= lo:   # degenerate (int axis collapsed)
                    lo, hi = box[p]
                if (lo, hi) != tuple(box[p]):
                    shrunk = True
                box[p] = [lo, hi]
                if hi - lo > cell[p] + 1e-12:
                    done = False
            # No axis moved: the next lattice would be IDENTICAL and
            # the evaluator is deterministic — re-paying U evaluations
            # per generation buys nothing.  The global argmin over
            # seen_pts is already this lattice's best answer.
            if done or not shrunk:
                break

    # The answer, over EVERY evaluated point (generations only narrow
    # where to look next; the argmin itself is global over the trail).
    if mode == "knee":
        passing = [(c, o) for c, o in seen_pts
                   if not math.isnan(o) and o <= knee_at]
        if not passing:
            best_c, best_o = None, float("nan")
        else:
            best_c, best_o = max(passing, key=lambda t: t[0][0])
    else:
        valid = [(c, o) for c, o in seen_pts if not math.isnan(o)]
        if not valid:
            best_c, best_o = None, float("nan")
        else:
            best_c, best_o = (min if mode == "min" else max)(
                valid, key=lambda t: t[1]
            )
    best = {"objective": None if math.isnan(best_o) else float(best_o)}
    if best_c is not None:
        for i, p in enumerate(varying):
            best[p] = best_c[i]
    return OptimizeResult(
        entrypoint=universe.entrypoint,
        objective=objective,
        mode=mode,
        knee_at=float("nan") if knee_at is None else float(knee_at),
        knobs=tuple(varying),
        fixed=fixed,
        best=best,
        bracket={p: [float(box[p][0]), float(box[p][1])]
                 for p in varying},
        cell={p: float(cell[p]) for p in varying},
        evaluations=evaluations,
        generations=generations,
        grid_evaluations=_grid_cost(universe),
        points_per_gen=U,
        history=history,
        overflow_total=(sum(overflow_seen) if overflow_seen else None),
    )
