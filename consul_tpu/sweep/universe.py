"""The :class:`Universe` spec and the batched-scan builder.

A universe is one (seed, knob values, fault severities) point of a
study family.  The spec splits a swept configuration into

  * positional-static structure — the base config object, steps, the
    tracked-subject tuple, and the entrypoint/U choice: anything that
    feeds array shapes or trace-time constants.  These hash into the
    jit cache exactly like the unswept entrypoints (the established
    positional-static-args discipline), so one program serves every
    knob value.
  * vmapped-array knobs — rate-like config fields (loss,
    suspicion_scale, ack_late, aggregate-mode fanout, fault-schedule
    severities) passed as [U] arrays and rebuilt into per-universe
    config objects INSIDE the trace via :func:`apply_knobs`.  The
    models consume them through ordinary jnp arithmetic, so a traced
    scalar flows where a Python float used to fold.
  * per-universe PRNG keys — an explicit seed tuple (U independent
    PRNGKeys; U=1 with seed s is bit-equal to the unbatched run at
    seed s) or a ``split_from`` base key folded in per universe
    (prefix-stable: the first U keys of a larger sweep are identical).

The config-stacking footgun is rejected loudly: a field that feeds
shapes (n, k_slots, piggyback, profile tick counts, ...) silently
vmapped would retrace per universe — :func:`validate_knob` refuses it
with the reason, at :class:`Universe` construction time, never at
trace time.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from consul_tpu.models.broadcast import broadcast_init
from consul_tpu.models.membership import membership_init
from consul_tpu.models.swim import swim_init
from consul_tpu.sim import engine

# Final-field names that feed array shapes or trace-time structure
# anywhere in the model family: vmapping one of these would compile a
# distinct program per universe (or crash at trace time), so the
# validator rejects them by name with the reason.
_SHAPE_FIELDS = frozenset({
    # array extents / budgets
    "n", "k_slots", "piggyback", "stage_width", "segments", "seg_size",
    "bridges_per_segment", "indirect_checks", "udp_buffer_size",
    "event_buffer_size", "query_buffer_size", "max_user_event_size",
    "events", "chunks", "window", "names",
    # streamcast policy seam + backlog: the selection policy and the
    # arrival process are trace-time branches (one program per choice
    # — sweep policy × load by building one batched program per
    # policy, <= 3 total), the standing backlog picks WHICH schedule
    # entries pin to tick 0 (structure, not a rate), and the hot node
    # is a scatter target.
    "policy", "arrivals", "backlog", "hotspot_node",
    # geo/WAN plane: the link slot planes, ring window, and queue
    # bound are all sized by these (consul_tpu/geo/model.py)
    "wan_latency_ticks", "wan_window", "wan_capacity_bytes",
    "wan_msg_bytes", "wan_queue_bytes", "ae_batch", "adaptive",
    "origins", "lan_profile", "wan_profile", "src", "dst",
    # schedule structure (host-validated scatter indices)
    "fail_at", "leave_at", "join_at", "pieces", "subject", "schedule",
    "fail_at_tick", "start", "heal", "end", "seed", "leave_grace_ticks",
    # trace-time constants and branch selectors
    "delivery", "profile", "base", "faults", "lifeguard", "done_frac",
    "subject_alive", "probe_enabled", "push_pull_enabled", "name",
    "amortize",
    "probe_interval_ms", "probe_timeout_ms", "gossip_interval_ms",
    "push_pull_interval_ms", "gossip_to_the_dead_ms",
    "suspicion_mult", "suspicion_max_timeout_mult",
    "awareness_max_multiplier", "retransmit_mult",
})

# Fault-schedule severity fields sweepable through "faults.…" paths
# (sim/faults.py evaluators consume them as jnp arithmetic).
_FAULT_KNOB_FIELDS = frozenset({
    "drop", "late", "frac", "severity", "p_offline", "scale",
})

# Knobs that are integer-valued in the models (transmission counts);
# everything else stacks as float32.  chunk_budget is streamcast's
# serviced-slots-per-round cap — it only ever enters as a rank
# comparison, never a shape, so it is sweepable despite being a count.
_INT_KNOB_FIELDS = frozenset({"fanout", "gossip_nodes", "chunk_budget"})


@dataclasses.dataclass(frozen=True)
class _EntrypointSpec:
    """One sweepable scan entrypoint: its init, its unjitted impl, and
    the knob paths legal for it."""

    name: str
    init: Callable[[Any], Any]
    call: Callable  # (state, key, cfg, steps, track) -> (final, outs)
    base_cfg: Callable[[Any], Any]  # cfg -> the profile/n-carrying config
    knob_paths: frozenset
    aggregate_only: frozenset  # legal only under delivery="aggregate"
    fault_paths: bool = False  # "faults.…" severity paths legal
    # "faults.bandwidth[*].…" paths legal: only the geo plane has the
    # per-link byte accounting a BandwidthSchedule caps — sweeping its
    # severity on any other entrypoint would ladder identical universes.
    bandwidth_paths: bool = False
    # The sweep x shard composition seam: the UNJITTED sharded twin
    # (consul_tpu/parallel/shard.py), normalized to
    #   (state, key, ucfg, steps, track, telemetry, mesh, exchange)
    #     -> (final, outs_core, outbox_overflow)
    # where ``outs_core`` has EXACTLY the unsharded impl's output
    # structure (trace last when telemetry) — so U=1 x D=1 composed is
    # bit-equal to the unsharded sweep by the sharded plane's D == 1
    # pins — and ``outbox_overflow`` is the study's loud overflow
    # scalar.  None: the entrypoint has no sharded twin (swim,
    # lifeguard) and make_sweep(mesh=) rejects it loudly.
    sharded: Optional[Callable] = None


def _sparse_init(cfg):
    from consul_tpu.models.membership_sparse import sparse_membership_init

    return sparse_membership_init(cfg)


def _lifeguard_init(cfg):
    from consul_tpu.models.lifeguard import lifeguard_init

    return lifeguard_init(cfg)


def _streamcast_init(cfg):
    from consul_tpu.streamcast.model import streamcast_init

    return streamcast_init(cfg)


def _geo_init(cfg):
    from consul_tpu.geo.model import geo_init

    return geo_init(cfg)


# --- sharded-twin adapters (the sweep x shard composition seam) ------
# Each wraps the UNJITTED sharded impl (parallel/shard.py) — the
# jitted twins hash cfg statically, which a traced knob inside cfg can
# never satisfy — and normalizes the family's native overflow output
# into (final, outs_core, outbox_overflow) with outs_core shaped
# exactly like the unsharded impl's outputs (trace stays LAST under
# telemetry).  Imports are lazy like the inits above (shard.py pulls
# in the model trees).


def _sharded_broadcast(s, k, c, steps, track, telemetry, mesh, ex):
    from consul_tpu.parallel.shard import _sharded_broadcast_scan

    final, outs = _sharded_broadcast_scan(s, k, c, steps, mesh, ex,
                                          telemetry)
    if telemetry:
        infected, ov, trace = outs
        return final, (infected, trace), ov
    infected, ov = outs
    return final, infected, ov


def _sharded_membership(s, k, c, steps, track, telemetry, mesh, ex):
    from consul_tpu.parallel.shard import _sharded_membership_scan

    final, outs = _sharded_membership_scan(s, k, c, steps, mesh, track,
                                           ex, telemetry)
    if telemetry:
        *core, ov, trace = outs
        return final, (*core, trace), ov
    *core, ov = outs
    return final, tuple(core), ov


def _sharded_sparse(s, k, c, steps, track, telemetry, mesh, ex):
    from consul_tpu.parallel.shard import _sharded_sparse_membership_scan

    final, outs = _sharded_sparse_membership_scan(
        s, k, c, steps, mesh, track, ex, telemetry
    )
    # The sparse plane carries its loud counter in the state (model
    # overflow + outbox misses, one ledger as unsharded).
    return final, outs, final.overflow


def _sharded_streamcast(s, k, c, steps, track, telemetry, mesh, ex):
    from consul_tpu.parallel.shard import _sharded_streamcast_scan

    final, outs = _sharded_streamcast_scan(s, k, c, steps, mesh, ex,
                                           telemetry)
    if telemetry:
        *core, ov_t, trace = outs
        return final, (*core, trace), ov_t[-1]
    *core, ov_t = outs
    # ob_ov rides the per-tick outs; the final tick holds the total.
    return final, tuple(core), ov_t[-1]


def _sharded_geo(s, k, c, steps, track, telemetry, mesh, ex):
    from consul_tpu.parallel.shard import _sharded_geo_scan

    final, outs = _sharded_geo_scan(s, k, c, steps, mesh, ex, telemetry)
    if telemetry:
        *core, ov_t, trace = outs
        return final, (*core, trace), ov_t[-1]
    *core, ov_t = outs
    return final, tuple(core), ov_t[-1]


SWEEP_ENTRYPOINTS: dict = {
    "swim": _EntrypointSpec(
        name="swim",
        init=swim_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._swim_scan(s, k, c, steps, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss", "suspicion_scale"}),
        aggregate_only=frozenset({"profile.gossip_nodes"}),
    ),
    "lifeguard": _EntrypointSpec(
        name="lifeguard",
        init=_lifeguard_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._lifeguard_scan(s, k, c, steps, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss", "suspicion_scale", "ack_late"}),
        aggregate_only=frozenset({"profile.gossip_nodes"}),
        fault_paths=True,
    ),
    "broadcast": _EntrypointSpec(
        name="broadcast",
        init=lambda cfg: broadcast_init(cfg, origin=0),
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._broadcast_scan(s, k, c, steps, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss"}),
        aggregate_only=frozenset({"fanout"}),
        sharded=_sharded_broadcast,
    ),
    "membership": _EntrypointSpec(
        name="membership",
        init=membership_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._membership_scan(s, k, c, steps, track, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss", "suspicion_scale"}),
        aggregate_only=frozenset(),
        sharded=_sharded_membership,
    ),
    "sparse": _EntrypointSpec(
        name="sparse",
        init=_sparse_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._sparse_membership_scan(
                s, k, c, steps, track, telemetry),
        base_cfg=lambda c: c.base,
        knob_paths=frozenset({"base.loss", "base.suspicion_scale"}),
        aggregate_only=frozenset(),
        sharded=_sharded_sparse,
    ),
    # The sustained-load plane (consul_tpu/streamcast): ``rate`` is the
    # offered load — per-universe arrival schedules derive from the
    # per-universe keys, so ONE batched program measures a whole
    # throughput curve; ``chunk_budget`` is the pipelined bandwidth
    # cap (a rank comparison, never a shape); ``size_tail`` and
    # ``hotspot`` are the adversarial-load severities (sim/load.py —
    # both enter the Poisson schedule as ordinary jnp arithmetic, so a
    # heavy-tail or hotspot ladder is one vmapped program).  The
    # selection ``policy`` is trace-time static — sweep policy × load
    # as one batched program PER policy, never as a knob.
    "streamcast": _EntrypointSpec(
        name="streamcast",
        init=_streamcast_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._streamcast_scan(s, k, c, steps, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss", "rate", "chunk_budget",
                              "size_tail", "hotspot"}),
        aggregate_only=frozenset({"fanout"}),
        fault_paths=True,
        sharded=_sharded_streamcast,
    ),
    # The geo/WAN plane (consul_tpu/geo): LAN/WAN loss and the
    # adaptive controller's EWMA gain are rate knobs, and the
    # bandwidth-brownout severity rides ``faults.bandwidth[*].scale``
    # — one static schedule shape, a per-universe traced severity, so
    # a whole brownout ladder is ONE vmapped program (the wanbrownout
    # preset).  Everything sizing the link planes (window, capacity,
    # latency matrix, batch, adaptive) is shape-denied.
    "geo": _EntrypointSpec(
        name="geo",
        init=_geo_init,
        call=lambda s, k, c, steps, track, telemetry=False:
            engine._geo_scan(s, k, c, steps, telemetry),
        base_cfg=lambda c: c,
        knob_paths=frozenset({"loss_lan", "loss_wan", "ae_gain"}),
        aggregate_only=frozenset(),
        fault_paths=True,
        bandwidth_paths=True,
        sharded=_sharded_geo,
    ),
}


_SEGMENT_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_]*)(?:\[([0-9]+)\])?$")


def _path_segments(path: str) -> list:
    """'faults.degraded[0].drop' -> [('faults', None), ('degraded', 0),
    ('drop', None)]; raises on malformed paths."""
    segments = []
    for raw in path.split("."):
        m = _SEGMENT_RE.match(raw)
        if m is None:
            raise ValueError(f"malformed knob path segment {raw!r} in "
                             f"{path!r}")
        name, idx = m.group(1), m.group(2)
        segments.append((name, None if idx is None else int(idx)))
    return segments


def _resolve_path(cfg, path: str):
    """(owner object, final field name) of a knob path, validating that
    every segment exists on the base config."""
    segments = _path_segments(path)
    obj = cfg
    for name, idx in segments[:-1]:
        if not hasattr(obj, name):
            raise ValueError(
                f"knob path {path!r}: {type(obj).__name__} has no field "
                f"{name!r}"
            )
        obj = getattr(obj, name)
        if idx is not None:
            if idx >= len(obj):
                raise ValueError(
                    f"knob path {path!r}: index [{idx}] out of range "
                    f"(len {len(obj)})"
                )
            obj = obj[idx]
    final, fidx = segments[-1]
    if fidx is not None:
        raise ValueError(
            f"knob path {path!r} must end on a field, not an index"
        )
    if not hasattr(obj, final):
        raise ValueError(
            f"knob path {path!r}: {type(obj).__name__} has no field "
            f"{final!r}"
        )
    return obj, final


def _replace_path(obj, segments, value):
    """Functional update of a nested frozen-dataclass/tuple path."""
    (name, idx), rest = segments[0], segments[1:]
    cur = getattr(obj, name)
    if idx is None:
        new = value if not rest else _replace_path(cur, rest, value)
        return dataclasses.replace(obj, **{name: new})
    item = cur[idx]
    new_item = value if not rest else _replace_path(item, rest, value)
    return dataclasses.replace(
        obj, **{name: cur[:idx] + (new_item,) + cur[idx + 1:]}
    )


def apply_knobs(cfg, knobs: tuple, values: tuple):
    """Rebuild ``cfg`` with each knob path set to its (possibly traced)
    per-universe value — called INSIDE the vmapped trace, one scalar
    per knob per universe."""
    for path, value in zip(knobs, values):
        cfg = _replace_path(cfg, _path_segments(path), value)
    return cfg


def knob_dtype(path: str):
    """Stacking dtype of a knob: int32 for transmission-count knobs
    (fanout), float32 for every rate."""
    final = _path_segments(path)[-1][0]
    return jnp.int32 if final in _INT_KNOB_FIELDS else jnp.float32


def validate_knob(entrypoint: str, cfg, path: str) -> None:
    """Reject non-sweepable knob paths loudly, at Universe construction
    time.

    The config-stacking footgun this guards: a field that feeds array
    shapes or trace-time structure (n, k_slots, piggyback, profile tick
    counts, schedule tuples, …), silently vmapped, would compile a
    distinct program per universe — the exact retrace explosion the
    sweep exists to avoid.  Rate-like fields (loss, suspicion_scale,
    ack_late, fault severities, aggregate-mode fanout) are the
    sweepable family.
    """
    spec = SWEEP_ENTRYPOINTS[entrypoint]
    owner, final = _resolve_path(cfg, path)
    base = spec.base_cfg(cfg)
    # Dense/sparse membership gossip is always the exact per-message
    # scatter, i.e. edges-shaped.
    delivery = getattr(base, "delivery", "edges")
    allowed = set(spec.knob_paths)
    if delivery == "aggregate":
        allowed |= set(spec.aggregate_only)

    if path in allowed:
        return
    if path.startswith("faults.bandwidth") and not spec.bandwidth_paths:
        raise ValueError(
            f"knob {path!r}: BandwidthSchedule severities only act on "
            "the geo/WAN link plane — sweeping one on "
            f"{entrypoint!r} would ladder identical universes "
            "(the model has no per-link byte accounting to cap)"
        )
    if spec.fault_paths and path.startswith("faults.") and (
        final in _FAULT_KNOB_FIELDS
    ):
        return
    if path in spec.aggregate_only or final in _INT_KNOB_FIELDS:
        if spec.aggregate_only:
            if delivery == "aggregate":
                # Already in aggregate mode: the PATH is wrong, not the
                # mode — name the knob that enters as a rate.
                raise ValueError(
                    f"knob {path!r} is not the aggregate-mode "
                    f"transmission knob for {entrypoint!r}; fanout "
                    "enters as a Poisson arrival rate only via "
                    f"{sorted(spec.aggregate_only)}"
                )
            raise ValueError(
                f"knob {path!r} feeds the [n, fanout] gossip-target "
                f"shapes under delivery={delivery!r}; fanout is only "
                "sweepable under delivery='aggregate', where it enters "
                "as a Poisson arrival rate via "
                f"{sorted(spec.aggregate_only)}"
            )
        # Dense/sparse membership has no aggregate mode — don't send
        # the user hunting for a config field that doesn't exist.
        raise ValueError(
            f"knob {path!r} feeds the [n, fanout] gossip-target "
            f"shapes; transmission-count knobs are not sweepable for "
            f"{entrypoint!r} (sweepable: {sorted(allowed)})"
        )
    if final in _SHAPE_FIELDS:
        raise ValueError(
            f"knob {path!r}: field {final!r} of {type(owner).__name__} "
            "feeds array shapes or trace-time structure; a vmapped "
            "sweep over it would retrace per universe — sweep "
            "rate-like knobs instead (sweepable for "
            f"{entrypoint!r}: {sorted(allowed)}"
            + (", faults.*.{%s}" % "/".join(sorted(_FAULT_KNOB_FIELDS))
               if spec.fault_paths else "") + ")"
        )
    raise ValueError(
        f"unknown or unsweepable knob {path!r} for entrypoint "
        f"{entrypoint!r} (sweepable: {sorted(allowed)})"
    )


@dataclasses.dataclass(frozen=True)
class Universe:
    """Positional-static structure + per-universe axes of one sweep.

    ``seeds`` stacks one independent PRNGKey per universe (U = len);
    ``split_from``/``universes`` instead folds one base key in per
    universe (prefix-stable, the error-bar mode).  ``values`` carries one
    U-tuple per knob path in ``knobs``; every path is validated against
    the shape-feeding denylist at construction.
    """

    entrypoint: str
    cfg: Any
    steps: int
    seeds: tuple = ()
    split_from: Optional[int] = None
    universes: int = 0
    knobs: tuple = ()
    values: tuple = ()   # one U-length tuple of scalars per knob
    track: tuple = ()

    def __post_init__(self):
        if self.entrypoint not in SWEEP_ENTRYPOINTS:
            raise ValueError(
                f"unknown sweep entrypoint {self.entrypoint!r} "
                f"(have: {sorted(SWEEP_ENTRYPOINTS)})"
            )
        if (self.split_from is None) == (not self.seeds):
            raise ValueError(
                "exactly one of seeds=(…) or split_from=/universes= "
                "must be given"
            )
        if self.seeds and self.universes:
            raise ValueError(
                f"seeds= fixes U=len(seeds)={len(self.seeds)}; "
                f"universes={self.universes} would be silently ignored "
                "— pass exactly one seed mode"
            )
        if self.split_from is not None and self.universes < 1:
            raise ValueError("universes must be >= 1 with split_from")
        if len(self.knobs) != len(self.values):
            raise ValueError(
                f"{len(self.knobs)} knobs but {len(self.values)} value "
                "rows"
            )
        if len(set(self.knobs)) != len(self.knobs):
            raise ValueError(f"duplicate knob paths in {self.knobs}")
        for path, row in zip(self.knobs, self.values):
            validate_knob(self.entrypoint, self.cfg, path)
            if len(row) != self.U:
                raise ValueError(
                    f"knob {path!r} has {len(row)} values for U="
                    f"{self.U} universes"
                )
        if self.track and self.entrypoint not in ("membership", "sparse"):
            raise ValueError(
                f"track= only applies to membership/sparse, not "
                f"{self.entrypoint!r}"
            )

    @property
    def U(self) -> int:
        return len(self.seeds) if self.seeds else self.universes

    def keys(self) -> jax.Array:
        """uint32[U, 2] stacked per-universe PRNG keys.

        ``split_from`` mode derives key u as ``fold_in(base, u)`` —
        U-INDEPENDENT, so the first 64 universes of a U=256 sweep ARE
        the U=64 sweep's universes (``jax.random.split(base, U)``
        would not be: its keys depend on U)."""
        if self.seeds:
            return jnp.stack(
                [jax.random.PRNGKey(s) for s in self.seeds]
            )
        base = jax.random.PRNGKey(self.split_from)
        return jax.vmap(
            lambda u: jax.random.fold_in(base, u)
        )(jnp.arange(self.U, dtype=jnp.uint32))

    def knob_arrays(self) -> tuple:
        """One [U] device array per knob, at the knob's dtype."""
        return tuple(
            jnp.asarray(row, knob_dtype(path))
            for path, row in zip(self.knobs, self.values)
        )


def stacked_init(universe: Universe):
    """The [U, …] initial carry: the per-universe init state broadcast
    over the universe axis.  Valid because no sweepable knob reaches an
    init (validate_knob keeps tx-budget/shape fields static), so every
    universe starts from the same state; the stacked copy is a real
    buffer (donated to the sweep program)."""
    spec = SWEEP_ENTRYPOINTS[universe.entrypoint]
    state = spec.init(universe.cfg)
    U = universe.U
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (U,) + x.shape), state
    )


def make_sweep(entrypoint: str, U: int, telemetry: bool = False,
               mesh=None, exchange: str = "alltoall"):
    """The batched scan program for (entrypoint, U, telemetry, mesh,
    exchange) — all positional-static, mirroring the engine's
    jit-cache discipline.  ``telemetry=True`` threads the in-scan
    metrics seam (consul_tpu/obs) through the vmapped impl, so the
    stacked outputs gain one [U, steps, M] trace plane as their LAST
    element — every existing output stays bit-equal.

    ``mesh=`` composes the two parallelism axes: the U-universe vmap
    wraps the SHARDED scan twin (parallel/shard.py) — one program
    holding U universes x n/D nodes per device, owned per-(round, node)
    draws and per-universe folded keys exactly as unsharded, outbox
    budgets sized from the per-universe per-shard emission bound
    (every pack_outbox call batches per universe).  The composed
    program returns a THIRD element — the per-universe loud overflow
    scalar (outbox misses + the family's own budget deferrals) — and
    U=1 x D=1 is bit-equal to the unsharded sweep and to the plain
    scan (the sharded plane's D == 1 pins compose with the sweep's
    U=1 pins; tests/test_sweepshard.py).  ``exchange`` picks the
    outbox transport (``"alltoall"`` | ``"ring"``), bit-equal by
    construction.  Entrypoints without a sharded twin (swim,
    lifeguard) reject mesh= loudly.

    Returns ONE jitted callable per (entrypoint, U, telemetry, mesh,
    exchange) (lru-cached, so repeated calls share the jit cache and
    the knob *values* never retrace — only a new axis point compiles
    a new program):

        sweep(stacked_state, keys, values, cfg, steps, knobs, track)
          -> (stacked_final, stacked_outs[, overflow])

    ``stacked_state`` is donated (the [U, …] carry dominates the
    footprint exactly as the unbatched carries do — jaxlint J3);
    ``values`` is a tuple of [U] knob arrays matching the static
    ``knobs`` path tuple; ``cfg``/``steps``/``track`` are the static
    structure.  Per universe, :func:`apply_knobs` rebuilds the config
    with that universe's traced knob scalars and runs the unjitted
    scan impl; U=1 is bit-equal to the unbatched entrypoint (pinned
    per model in tests/test_sweep.py).
    """
    # Normalized here (not via lru_cache on this function) so the
    # 2-arg legacy call and an explicit telemetry=False share ONE
    # cache entry — the one-program-per-(entrypoint, U) guard.
    return _make_sweep(entrypoint, U, bool(telemetry), mesh, exchange)


@functools.lru_cache(maxsize=None)
def _make_sweep(entrypoint: str, U: int, telemetry: bool, mesh,
                exchange: str):
    if entrypoint not in SWEEP_ENTRYPOINTS:
        raise ValueError(
            f"unknown sweep entrypoint {entrypoint!r} "
            f"(have: {sorted(SWEEP_ENTRYPOINTS)})"
        )
    if U < 1:
        raise ValueError(f"U must be >= 1, got {U}")
    spec = SWEEP_ENTRYPOINTS[entrypoint]
    if mesh is None:
        if exchange != "alltoall":
            raise ValueError(
                f"exchange={exchange!r} requires mesh= (the outbox "
                "transport only exists on the composed multi-chip "
                "plane)"
            )
    elif spec.sharded is None:
        raise ValueError(
            f"entrypoint {entrypoint!r} has no sharded twin — "
            "sweep x shard composition covers: "
            f"{sorted(n for n, s in SWEEP_ENTRYPOINTS.items() if s.sharded)}"
        )
    elif exchange not in ("alltoall", "ring"):
        raise ValueError(
            f"unknown exchange backend {exchange!r}; "
            "choose 'alltoall' or 'ring'"
        )

    def _sweep_scan(stacked_state, keys, values, cfg, steps,
                    knobs=(), track=()):
        if keys.shape[0] != U:
            raise ValueError(
                f"this sweep program is built for U={U}, got "
                f"{keys.shape[0]} keys"
            )
        if entrypoint == "sparse" and cfg.amortize is None:
            # Auto-pin the slow branch for the vmapped plane: under
            # vmap the amortized dispatch cond lowers to both-branches
            # select, so sparse sweeps would pay the cold-path sort on
            # top of the dead fast branch (the measured 1.5x tax,
            # bench "sweepshard").  An explicit amortize=True/False is
            # honored — only the None auto resolves here, through the
            # ONE policy function (resolve_amortize), so the plain-scan
            # and vmapped sides of the auto can never diverge.
            from consul_tpu.models.membership_sparse import (
                resolve_amortize,
            )

            cfg = dataclasses.replace(
                cfg, amortize=resolve_amortize(cfg, vmapped=True)
            )

        def one(state, key, vals):
            ucfg = apply_knobs(cfg, knobs, vals)
            if mesh is None:
                return spec.call(state, key, ucfg, steps, track,
                                 telemetry)
            return spec.sharded(state, key, ucfg, steps, track,
                                telemetry, mesh, exchange)

        return jax.vmap(one)(stacked_state, keys, tuple(values))

    tag = "" if mesh is None else f"_D{int(mesh.devices.size)}"
    _sweep_scan.__name__ = f"sweep_{entrypoint}_U{U}{tag}"
    return jax.jit(
        _sweep_scan, static_argnames=("cfg", "steps", "knobs", "track"),
        donate_argnums=(0,),
    )


def abstract_sweep_program(entrypoint: str, cfg, steps: int, U: int,
                           knobs: tuple = (), track: tuple = (),
                           telemetry: bool = False,
                           mesh=None, exchange: str = "alltoall"):
    """(fn, abstract args) of the batched program — the jaxlint-
    registry build shape (sim/engine.py jaxlint_registry) and the
    bench max-U-per-chip estimator both trace it: eval_shape states,
    zero device memory.  ``mesh=``/``exchange=`` build the composed
    sweep x shard program (same seam as :func:`make_sweep`)."""
    spec = SWEEP_ENTRYPOINTS[entrypoint]
    sweep = make_sweep(entrypoint, U, telemetry, mesh, exchange)
    state = jax.eval_shape(lambda: spec.init(cfg))
    stacked = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct((U,) + s.shape, s.dtype), state
    )
    keys = jax.ShapeDtypeStruct((U, 2), jnp.uint32)
    values = tuple(
        jax.ShapeDtypeStruct((U,), knob_dtype(p)) for p in knobs
    )
    fn = lambda s, k, v: sweep(s, k, v, cfg, steps, knobs, track)  # noqa: E731
    return fn, (stacked, keys, values)
