"""Universe sweeps: the whole protocol family as ONE XLA program.

Every study used to run one (seed, config, fault schedule) per
compiled program; this package wraps the scan entrypoints of
``consul_tpu.sim.engine`` in ``jax.vmap`` over a leading *universe*
axis of size U, so one jitted program advances hundreds of universes
concurrently — seeds for real error bars, protocol knobs (probe
fanout, suspicion-timeout scale, loss) for tuning curves, and
fault-schedule severities for coverage matrices.  "Robust and
Tuneable Family of Gossiping Algorithms" (PAPERS.md) is the blueprint:
map the tunable family in one sweep and publish the
robustness/latency frontier.

  universe.py   the :class:`Universe` spec (per-universe PRNG keys,
                vmapped-array knobs vs positional-static structure,
                stacked fault-schedule severities) and
                :func:`make_sweep` — one compiled program per
                (entrypoint, U, telemetry, mesh, exchange), knob
                *values* never retrace; ``mesh=`` composes the
                universe axis with the sharded inner study
  frontier.py   per-universe metric reduction into a
                :class:`SweepReport` + Pareto-frontier extraction
  presets.py    seed sweeps, knob grids, fault-severity matrices
  optimize.py   closed-loop autotuning: successive-halving/bisection
                generations over a grid preset's knob space, reusing
                one cached sweep program (``cli sweep --optimize``)
  compose.py    the standalone composed max-U / real-run datapoint
                (``python -m consul_tpu.sweep.compose``)
"""

from consul_tpu.sweep.universe import (
    SWEEP_ENTRYPOINTS,
    Universe,
    apply_knobs,
    make_sweep,
    stacked_init,
    validate_knob,
)
from consul_tpu.sweep.frontier import (
    SweepReport,
    pareto_mask,
    summarize_sweep,
)
from consul_tpu.sweep.optimize import OptimizeResult, optimize_sweep
from consul_tpu.sweep.presets import PRESETS, make_preset

__all__ = [
    "SWEEP_ENTRYPOINTS",
    "Universe",
    "apply_knobs",
    "make_sweep",
    "stacked_init",
    "validate_knob",
    "SweepReport",
    "pareto_mask",
    "summarize_sweep",
    "OptimizeResult",
    "optimize_sweep",
    "PRESETS",
    "make_preset",
]
