"""Sweep presets: seed sweeps, knob grids, fault-severity matrices.

Each preset is a factory returning a :class:`Universe`; ``cli sweep
--preset NAME`` and bench.py's sweep section run them through
``sim.engine.run_sweep``.  Three families:

  seeds4k      U independent seeds of the flagship swim crash study —
               real error bars on first-detection time from ONE
               compiled program (the acceptance sweep: U=256 at
               n=4096, per-node dense state).
  tuning       the fanout × suspicion-scale Lifeguard grid: the
               "Robust and Tuneable Family of Gossiping Algorithms"
               experiment — every grid point is one universe, and the
               Pareto frontier over (fp_rate, detection latency) is
               the published tuning curve.
  faultmatrix  severity ladders of the three fault primitives
               (LossRamp scale × DegradedSet drop × Partition
               severity) crossed into a coverage matrix over the
               Lifeguard FP study.
"""

from __future__ import annotations

import itertools
import math

from consul_tpu.models.lifeguard import LifeguardConfig
from consul_tpu.models.swim import SwimConfig
from consul_tpu.sim.faults import (
    DegradedSet,
    FaultSchedule,
    LossRamp,
    Partition,
)
from consul_tpu.sweep.universe import Universe


def seed_sweep(universes=None, seed=0, n=4096, steps=60,
               loss=0.05) -> Universe:
    """U-seed error-bar sweep of the swim crash study (exact edges
    delivery): one batched program, U first-detection samples.  The
    per-universe keys fold one base key in per universe index
    (prefix-stable), so U=64 reads the same universes as the first 64
    of U=256."""
    cfg = SwimConfig(n=n, subject=7, fail_at_tick=0, loss=loss,
                     delivery="edges")
    return Universe(
        entrypoint="swim", cfg=cfg, steps=steps,
        split_from=seed,
        universes=256 if universes is None else universes,
    )


def tuning_grid(universes=None, seed=0, n=1024,
                fanouts=(2, 3, 4, 6), scales=(0.05, 0.15, 0.5, 1.5),
                loss=0.40, ack_late=0.15, fail_at=120,
                steps=None) -> Universe:
    """Fanout × suspicion-scale Lifeguard grid: a crash study under
    heavy loss and WAN tail latency, so every universe yields BOTH a
    robustness cost (false-DEAD views of the still-live subject before
    the crash — sub-1.0 scales expire suspicions before the delayed
    refutes land) and a detection latency (after it) — the two
    frontier axes.  Aggregate delivery: fanout enters as a Poisson
    rate, which is what makes it sweepable at all (see validate_knob).
    One shared seed across the grid isolates the knob effect."""
    if universes is not None:
        raise ValueError(
            "tuning is a grid preset: U = len(fanouts) x len(scales), "
            "not --universes"
        )
    cfg = LifeguardConfig(
        n=n, subject=7, subject_alive=False, fail_at_tick=fail_at,
        loss=loss, ack_late=ack_late, delivery="aggregate",
    )
    if steps is None:
        # Enough horizon for the slowest universe to declare the
        # subject dead: crash tick + the max-scaled minimum suspicion
        # bound (confirmations drive the timeout toward the minimum)
        # plus one unscaled bound of dissemination margin.
        lo, _hi = cfg.suspicion_bounds_ticks
        steps = (fail_at + int(math.ceil(lo * max(scales)))
                 + int(math.ceil(lo)) + 60)
    grid = list(itertools.product(fanouts, scales))
    return Universe(
        entrypoint="lifeguard", cfg=cfg, steps=steps,
        # One shared key: universes differ ONLY in their knob point, so
        # the grid isolates the knob effect from sampling noise.
        seeds=(seed,) * len(grid),
        knobs=("profile.gossip_nodes", "suspicion_scale"),
        values=(
            tuple(f for f, _ in grid),
            tuple(s for _, s in grid),
        ),
    )


def fault_matrix(universes=None, seed=0, n=192, steps=80,
                 rungs=(0.0, 0.45, 0.9)) -> Universe:
    """Severity coverage matrix: a static fault-schedule SHAPE (one
    loss ramp, one degraded set, one partition) whose severities ride
    as per-universe knobs — every (ramp, drop, partition) rung
    combination is one universe of the Lifeguard FP study."""
    if universes is not None:
        raise ValueError(
            "faultmatrix is a grid preset: U = len(rungs)^3, not "
            "--universes"
        )
    faults = FaultSchedule(
        ramps=(LossRamp(pieces=((10, 0.35),)),),
        degraded=(DegradedSet(frac=0.12, drop=0.5, late=0.25, seed=1),),
        partitions=(Partition(start=20, heal=45, segments=2,
                              severity=0.5),),
    )
    cfg = LifeguardConfig(
        n=n, subject=7, subject_alive=True, loss=0.02, ack_late=0.05,
        delivery="aggregate", faults=faults,
    )
    grid = list(itertools.product(rungs, repeat=3))
    return Universe(
        entrypoint="lifeguard", cfg=cfg, steps=steps,
        seeds=(seed,) * len(grid),
        knobs=(
            "faults.ramps[0].scale",
            "faults.degraded[0].drop",
            "faults.partitions[0].severity",
        ),
        values=tuple(
            tuple(g[i] for g in grid) for i in range(3)
        ),
    )


def stream_load_curve(universes=None, seed=0, n=4096, window=8,
                      chunks=4, fanout=4, chunk_budget=2,
                      rates=(0.1, 0.3, 0.6, 1.2), steps=150,
                      loss=0.05, policy="uniform", backlog=0,
                      size_tail=0.0, hotspot=0.0,
                      done_frac=0.999,
                      arrivals="poisson") -> Universe:
    """Offered-load ladder over the streamcast plane
    (consul_tpu/streamcast): each universe is one offered load
    (events/tick), all other knobs shared, so ONE batched program
    measures the whole sustained-throughput curve — delivered
    events/sec vs offered, with the window-overflow saturation knee
    where the curve flattens.  The frontier axes are
    (undelivered_frac, t99_ms): universes past the knee pay on the
    throughput axis, universes before it compete on latency.

    ``policy`` picks the chunk-selection schedule (streamcast.model
    POLICIES) — trace-time static, so a policy × load grid is one
    batched program per policy, never a retrace per load point.
    ``backlog``/``size_tail``/``hotspot`` shape the offered stream
    adversarially (sim/load.py): a standing tick-0 backlog,
    heavy-tailed per-event chunk counts, and hot-node origin
    concentration — the same ladder re-run against production-shaped
    traffic."""
    if universes is not None:
        raise ValueError(
            "streamload is a grid preset: U = len(rates), not "
            "--universes"
        )
    from consul_tpu.streamcast.model import StreamcastConfig

    cfg = StreamcastConfig(
        n=n, events=int(max(rates) * steps * 1.5), chunks=chunks,
        window=window, fanout=fanout, chunk_budget=chunk_budget,
        rate=rates[0], loss=loss, delivery="aggregate",
        policy=policy, backlog=backlog, size_tail=size_tail,
        hotspot=hotspot, arrivals=arrivals,
        # Sustained-load semantics: an event is delivered at a
        # NEAR-TOTAL fraction of nodes (default 99.9%) — the epidemic
        # tail means the LAST straggler of a big n may never land
        # before budgets drain, and a slot pinned on it would leak the
        # window (model.StreamcastConfig.done_frac).  The bench knee
        # curves use 0.99: past 99% the straggler tail is pure Poisson
        # thinning, identical under every selection policy, and a
        # delivery bar inside it just pads every slot lifetime with
        # policy-blind ticks.
        done_frac=done_frac,
    )
    return Universe(
        entrypoint="streamcast", cfg=cfg, steps=steps,
        # One shared key: the load points differ ONLY in rate (the
        # Poisson schedule still differs per universe because rate
        # scales the same exponential gap draws).
        seeds=(seed,) * len(rates),
        knobs=("rate",),
        values=(tuple(rates),),
    )


def stream_adversarial_ladder(universes=None, seed=0, n=4096,
                              window=8, chunks=4, fanout=4,
                              chunk_budget=2, rate=0.3,
                              tails=(0.25, 0.5, 1.0, 2.0), steps=150,
                              loss=0.05, policy="uniform",
                              backlog=None, hotspot=0.5,
                              done_frac=0.999) -> Universe:
    """Adversarial-severity ladder over the streamcast plane: a
    STANDING BACKLOG (the window starts the run full — ``backlog``
    defaults to the window width), a hotspot origin concentration, and
    a heavy-tail severity ladder — ``size_tail`` is the per-universe
    knob (sim/load.py: the Pareto tail index of per-event chunk
    counts, SMALLER = heavier), so the whole backlog × heavy-tail
    grid at one offered load is ONE vmapped program.  Run it per
    ``policy`` to see which schedule survives production-shaped
    traffic: delivered events/sec, t50/t99 and the loud window
    accounting per rung."""
    if universes is not None:
        raise ValueError(
            "streamadv is a grid preset: U = len(tails), not "
            "--universes"
        )
    from consul_tpu.streamcast.model import StreamcastConfig

    if backlog is None:
        backlog = window
    cfg = StreamcastConfig(
        n=n, events=max(int(rate * steps * 1.5), backlog),
        chunks=chunks, window=window, fanout=fanout,
        chunk_budget=chunk_budget, rate=rate, loss=loss,
        delivery="aggregate", policy=policy, backlog=backlog,
        size_tail=tails[0], hotspot=hotspot, done_frac=done_frac,
    )
    return Universe(
        entrypoint="streamcast", cfg=cfg, steps=steps,
        # One shared key: rungs differ ONLY in tail severity.
        seeds=(seed,) * len(tails),
        knobs=("size_tail",),
        values=(tuple(tails),),
    )


def wan_brownout(universes=None, seed=0, n=2048, segments=8,
                 scales=(1.0, 0.5, 0.2, 0.05), steps=160,
                 brownout_at=4, heal_at=120) -> Universe:
    """Bandwidth-brownout severity ladder over the geo/WAN plane
    (consul_tpu/geo): ONE static BandwidthSchedule shape whose
    ``scale`` rides as the per-universe severity knob, so the whole
    ladder — healthy control (scale 1.0) down to a 5%-capacity
    brownout — runs as ONE vmapped program.  Per rung: convergence
    t50/t99, the worst segment's t99, and the loud per-link accounting
    (admitted bytes, overflow, stale waste).  Frontier axes:
    (wan_admitted_bytes, t99_ms) — WAN byte cost vs convergence
    latency, both minimized."""
    if universes is not None:
        raise ValueError(
            "wanbrownout is a grid preset: U = len(scales), not "
            "--universes"
        )
    from consul_tpu.geo.latency import derive_wan_latency
    from consul_tpu.geo.model import GeoConfig
    from consul_tpu.protocol.profiles import LAN
    from consul_tpu.sim.faults import BandwidthSchedule

    base_bytes = 16 * 1400.0
    # The piece VALUES are scaled by the severity knob: during the
    # brownout window the link carries scale x base; after heal_at the
    # piece value is far above base so min(base, scale * heal) == base
    # for every rung >= 0.05 — the ladder heals to full capacity.
    faults = FaultSchedule(bandwidth=(
        BandwidthSchedule(
            pieces=((brownout_at, base_bytes), (heal_at, 64 * base_bytes))
        ),
    ))
    latency, _info = derive_wan_latency(
        segments, 3, tick_ms=LAN.gossip_interval_ms, seed=seed,
        rounds=300, wan_window=8,
    )
    cfg = GeoConfig(
        n=n, segments=segments, bridges_per_segment=3, events=16,
        wan_latency_ticks=latency, wan_window=8,
        wan_capacity_bytes=base_bytes, wan_msg_bytes=1400,
        wan_queue_bytes=2 * base_bytes, ae_batch=16, adaptive=True,
        loss_wan=0.05, faults=faults,
    )
    return Universe(
        entrypoint="geo", cfg=cfg, steps=steps,
        # One shared key: rungs differ ONLY in severity.
        seeds=(seed,) * len(scales),
        knobs=("faults.bandwidth[0].scale",),
        values=(tuple(scales),),
    )


PRESETS: dict = {
    "seeds4k": seed_sweep,
    "tuning": tuning_grid,
    "faultmatrix": fault_matrix,
    "streamload": stream_load_curve,
    "streamadv": stream_adversarial_ladder,
    "wanbrownout": wan_brownout,
}


def make_preset(name: str, universes=None, seed: int = 0) -> Universe:
    """Build a preset's Universe (``--universes`` overrides U for seed
    presets; grid presets derive U from their ladders and reject it)."""
    if name not in PRESETS:
        raise ValueError(
            f"unknown sweep preset {name!r} (have: {sorted(PRESETS)})"
        )
    return PRESETS[name](universes=universes, seed=seed)
