"""Standalone composed sweep x shard datapoint.

``python -m consul_tpu.sweep.compose`` emits ONE JSON line measuring
the tentpole composition claim (ROADMAP item: sweep x shard): how many
universes fit per chip once the inner study shards over the ``nodes``
mesh, and a REAL composed run (U universes x n/D nodes per device in
one program) with its loud overflow column.

Like ``python -m consul_tpu.parallel.shard``, this is bench.py's
subprocess on single-device (CPU) containers — XLA_FLAGS must force
the host devices before the child's first backend use, which is
impossible in the parent — and runs in-process on a real v5e-8.

Two measurements:

  max_u_table   J6-derived (abstract traces, zero device memory): the
                composed sparse@100k program's per-chip peak at U=1 vs
                U=8 on the D-device mesh gives bytes/universe/chip;
                max-U = the 16 GB v5e budget divided by it.  Every
                universe occupies that footprint on EVERY chip (the
                mesh shards nodes, not universes), so this is the
                whole mesh's capacity — do NOT multiply by D.  The
                unsharded single-chip number (the PR 7
                table's sparse@100k = 53) is recomputed live alongside
                so the multiplication factor is measured, not quoted.
  real_run      a composed sparse sweep actually executed on the mesh
                (U x n/D per device), reporting rounds/s and the
                per-universe overflow column — 0 means every message a
                single chip would have delivered was delivered.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


def _compose_max_u(d_devices: int, budget_bytes: int = 16 << 30) -> dict:
    """The J6 table: composed sparse@100k bytes/universe/chip at
    D = ``d_devices`` vs the unsharded program's, and the max-U each
    implies under the 16 GB v5e gate."""
    import jax

    from consul_tpu.analysis.jaxlint import estimate_peak
    from consul_tpu.models import SparseMembershipConfig
    from consul_tpu.models.membership import MembershipConfig
    from consul_tpu.parallel.mesh import mesh_for
    from consul_tpu.protocol.profiles import LAN
    from consul_tpu.sweep.universe import abstract_sweep_program

    cfg = SparseMembershipConfig(
        base=MembershipConfig(n=100_000, loss=0.01, profile=LAN,
                              fail_at=((42, 5),)),
        k_slots=64,
    )
    knobs, track, steps = ("base.loss",), (42,), 3

    def peak_per_u(mesh):
        peaks = {}
        for u in (1, 8):
            fn, args = abstract_sweep_program(
                "sparse", cfg, steps, u, knobs, track, False, mesh
            )
            peaks[u] = estimate_peak(
                jax.make_jaxpr(fn)(*args)
            ).chip_bytes
        per_u = max((peaks[8] - peaks[1]) / 7.0, 1.0)
        fixed = max(peaks[1] - per_u, 0.0)
        return per_u, fixed

    per_u0, fixed0 = peak_per_u(None)
    max_u0 = int((budget_bytes - fixed0) // per_u0)
    mesh = mesh_for(d_devices)
    per_ud, fixedd = peak_per_u(mesh)
    max_ud = int((budget_bytes - fixedd) // per_ud)
    return {
        "sparse@100k": {
            "single_chip": {
                "per_universe_bytes": int(per_u0),
                "max_u": max_u0,
            },
            f"composed_D{d_devices}": {
                "per_universe_bytes_per_chip": int(per_ud),
                "max_u_per_chip": max_ud,
                # One program over the whole mesh holds max_u
                # universes at n/D nodes per device — the capacity
                # the composition multiplies.
                "max_u": max_ud,
                "devices": d_devices,
            },
            "multiplier_vs_single_chip": round(max_ud / max(max_u0, 1),
                                               2),
        }
    }


def _compose_real_run(d_devices: int, n: int, k_slots: int, U: int,
                      steps: int, seed: int) -> dict:
    """One composed sparse sweep EXECUTED on the mesh: U universes x
    n/D nodes per device, loss knob laddered, overflow reported loudly
    per universe."""
    import numpy as np

    from consul_tpu.models import SparseMembershipConfig
    from consul_tpu.models.membership import MembershipConfig
    from consul_tpu.parallel.mesh import mesh_for
    from consul_tpu.protocol.profiles import LAN
    from consul_tpu.sim.engine import run_sweep
    from consul_tpu.sweep.universe import Universe

    cfg = SparseMembershipConfig(
        base=MembershipConfig(n=n, loss=0.01, profile=LAN,
                              fail_at=((42, min(2, steps - 1)),)),
        k_slots=k_slots,
    )
    losses = tuple(0.01 + 0.01 * u for u in range(U))
    uni = Universe(entrypoint="sparse", cfg=cfg, steps=steps,
                   seeds=(seed,) * U, track=(42,),
                   knobs=("base.loss",), values=(losses,))
    mesh = mesh_for(d_devices)
    t0 = time.perf_counter()
    rep = run_sweep(uni, warmup=True, mesh=mesh)
    wall = time.perf_counter() - t0
    ov = np.asarray(rep.outbox_overflow)
    return {
        "entrypoint": "sparse",
        "nodes": n,
        "k_slots": k_slots,
        "universes": U,
        "devices": d_devices,
        "steps": steps,
        "rounds_per_sec": round(U * steps / rep.wall_s, 2)
        if rep.wall_s > 0 else None,
        "wall_s": round(wall, 2),
        "overflow_per_universe": [int(v) for v in ov],
        "overflow_total": int(ov.sum()),
        "dead_known_final": [
            int(v) for v in rep.metrics["dead_known_final"]
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="consul_tpu.sweep.compose")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--n", type=int, default=16384,
                        help="real-run aggregate nodes across the mesh")
    parser.add_argument("--k", type=int, default=32)
    parser.add_argument("--universes", type=int, default=4)
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--skip-real-run", action="store_true",
                        help="J6 table only (abstract traces)")
    args = parser.parse_args(argv)

    forced = False
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}"
        ).strip()
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
            forced = True
        except RuntimeError:
            pass  # backend already initialized; use whatever exists
    elif int(m.group(1)) < args.devices:
        # Loud pre-run contract: a pre-set smaller count would make
        # mesh_for(D) raise deep inside the J6 tracing instead.
        print(
            f"Error: XLA_FLAGS already forces "
            f"{m.group(1)} host device(s) < --devices {args.devices}; "
            f"unset it or re-run with a matching count",
            file=sys.stderr,
        )
        return 1

    out = {
        "devices": args.devices,
        "max_u_table": _compose_max_u(args.devices),
        "host_devices_forced": forced,
    }
    if not args.skip_real_run:
        out["real_run"] = _compose_real_run(
            args.devices, args.n, args.k, args.universes, args.steps,
            args.seed,
        )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
