"""Per-universe metric reduction and the robustness/latency frontier.

A sweep's raw output is the stacked per-tick counter pytree the scan
entrypoints already emit ([U, steps, …] on the host); this module
reduces it to per-universe scalars — false-positive rate, incarnation
flaps, detection-latency quantiles, convergence tick — and extracts
the Pareto frontier over (robustness, latency): the tuning-curve
deliverable of "Robust and Tuneable Family of Gossiping Algorithms"
(PAPERS.md).  All host-side numpy: the device program stays exactly
the batched scan.

Conventions: metrics are float64 [U] arrays with NaN where a quantity
is undefined for the study (e.g. detection latency in a
subject-alive FP study, fp_rate for models without an FP counter).
Times follow the report classes in sim/metrics.py: tick t's counters
describe the state AFTER tick t, so the wall-clock time of an event
first visible at index t is ``(t + 1) * tick_ms``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

#: Detection-latency quantiles reported per universe: the fraction of
#: the n-1 observers that must hold the DEAD view.
DETECT_FRACS = (0.50, 0.90, 0.99)

_DETECT_NAMES = ("detect_first_ms",) + tuple(
    f"detect_t{int(f * 100)}_ms" for f in DETECT_FRACS
)
_SWIM_NAMES = _DETECT_NAMES + (
    "false_dead_mean", "false_dead_max", "first_suspect_ms",
    "suspecting_final", "dead_known_final",
)

#: Every metric key :func:`summarize_sweep` can emit, per entrypoint —
#: the superset ``cli sweep`` validates requested frontier axes
#: against BEFORE running the sweep (a typo must not cost a
#: multi-minute batched program).  Pinned against real reports in
#: tests/test_sweep.py.
ENTRYPOINT_METRICS: dict = {
    "swim": frozenset(_SWIM_NAMES),
    "lifeguard": frozenset(_SWIM_NAMES + (
        "fp_total", "fp_rate", "flaps", "mean_awareness_final",
    )),
    "broadcast": frozenset({
        "infected_final", "t50_ms", "t99_ms", "converged_tick",
    }),
    "membership": frozenset(_DETECT_NAMES + (
        "suspecting_final", "dead_known_final", "suspect_cells_mean",
        "known_members_final",
    )),
    "sparse": frozenset(_DETECT_NAMES + (
        "suspecting_final", "dead_known_final", "suspect_cells_mean",
        "known_members_final",
    )),
    # Streamcast (consul_tpu/streamcast): throughput/latency axes.
    # pareto_mask MINIMIZES every column, so the throughput axis of a
    # (throughput, t99) frontier is ``undelivered_frac`` (fraction of
    # offered events not fully delivered — 0 is perfect throughput);
    # the raw rates ride along for reading the curve.
    "streamcast": frozenset({
        "events_offered", "events_delivered", "events_quiesced",
        "events_coalesced", "window_overflow",
        "offered_events_per_sim_s", "delivered_events_per_sim_s",
        "undelivered_frac", "t50_ms", "t99_ms",
    }),
    # Geo/WAN plane (consul_tpu/geo): convergence latency vs WAN byte
    # cost — ``cli sweep`` Paretos (wan_admitted_bytes, t99_ms), both
    # minimized; overflow/waste ride along as the loud-accounting
    # columns of the brownout ladder.
    "geo": frozenset({
        "converged_frac", "t50_ms", "t99_ms", "seg_t99_ms_worst",
        "wan_offered_bytes", "wan_admitted_bytes",
        "wan_overflow_units", "wan_wasted_units",
        "wan_queue_final_units",
    }),
}


def first_tick_at_least(counts: np.ndarray, threshold: float) -> np.ndarray:
    """float64[U]: first tick index where counts[u, t] >= threshold, NaN
    if never.  ``counts`` is [U, steps]; a zero-width window (e.g. a
    crash tick at/past the sweep horizon) is "never" for every
    universe, matching first_tick in sim/metrics.py — not an argmax
    error."""
    counts = np.asarray(counts)
    if counts.shape[1] == 0:
        return np.full(counts.shape[0], np.nan)
    hit = counts >= threshold
    any_hit = hit.any(axis=1)
    idx = hit.argmax(axis=1).astype(float)
    idx[~any_hit] = np.nan
    return idx


def pareto_mask(points: np.ndarray) -> np.ndarray:
    """bool[U]: Pareto-minimal rows of a [U, D] objective matrix (every
    column minimized).  A row is on the frontier iff no other valid row
    is <= it in every column and < in at least one; rows with any NaN
    are never on the frontier.  Duplicated points are all kept (they
    dominate nothing about each other)."""
    pts = np.asarray(points, float)
    if pts.ndim != 2:
        raise ValueError(f"points must be [U, D], got shape {pts.shape}")
    U = pts.shape[0]
    valid = ~np.isnan(pts).any(axis=1)
    mask = np.zeros(U, bool)
    for i in range(U):
        if not valid[i]:
            continue
        dominated = False
        for j in range(U):
            if i == j or not valid[j]:
                continue
            if (pts[j] <= pts[i]).all() and (pts[j] < pts[i]).any():
                dominated = True
                break
        mask[i] = not dominated
    return mask


@dataclasses.dataclass
class SweepReport:
    """One sweep's measured family: U universes, their knob coordinates,
    and per-universe metrics, plus the batched program's wall time."""

    entrypoint: str
    n: int
    U: int
    steps: int
    tick_ms: float
    knobs: tuple                 # knob paths
    values: dict                 # path -> np[U] knob values
    metrics: dict                # name -> np[U] per-universe metrics
    wall_s: float
    # telemetry=True sweeps only (consul_tpu/obs): the batched
    # [U, steps, M] Consul-named metrics trace + its column names.
    metric_names: tuple = ()
    metrics_trace: "np.ndarray" = None
    # Composed (mesh=) sweeps only: the per-universe loud overflow
    # scalar — outbox budget misses plus the family's own budget
    # deferrals (run_sweep(mesh=); None for unsharded sweeps).
    outbox_overflow: "np.ndarray" = None
    # Composed sweeps: device count of the mesh (1 for unsharded).
    devices: int = 1

    @property
    def universes_per_sec(self) -> float:
        return self.U / self.wall_s if self.wall_s > 0 else float("inf")

    @property
    def rounds_per_sec(self) -> float:
        """Aggregate simulated rounds/s across the whole sweep (U
        universes advance one tick each per round)."""
        total = self.U * self.steps
        return total / self.wall_s if self.wall_s > 0 else float("inf")

    @property
    def rounds_per_sec_per_universe(self) -> float:
        return self.steps / self.wall_s if self.wall_s > 0 else float("inf")

    def frontier(self, x: str = "fp_rate", y: str = "detect_t90_ms"):
        """Pareto-minimal universes over (metrics[x], metrics[y]) —
        robustness vs latency; the defaults fit lifeguard FP studies,
        other entrypoints pass their own axes (cli sweep validates
        against ENTRYPOINT_METRICS).  Returns a list of dicts (universe
        index, both coordinates, the universe's knob values), sorted
        by x."""
        for m in (x, y):
            if m not in self.metrics:
                raise ValueError(
                    f"frontier axis {m!r} is not a metric of this "
                    f"{self.entrypoint!r} sweep "
                    f"(defined: {', '.join(sorted(self.metrics))})"
                )
        pts = np.stack(
            [np.asarray(self.metrics[x], float),
             np.asarray(self.metrics[y], float)], axis=1
        )
        mask = pareto_mask(pts)
        out = [
            {
                "universe": int(i),
                x: float(pts[i, 0]),
                y: float(pts[i, 1]),
                **{k: _scalar(v[i]) for k, v in self.values.items()},
            }
            for i in np.nonzero(mask)[0]
        ]
        return sorted(out, key=lambda d: d[x])

    def summary(self) -> dict:
        """JSON-ready sweep summary (bench.py / cli sweep)."""
        def _stats(a):
            a = np.asarray(a, float)
            ok = a[~np.isnan(a)]
            if ok.size == 0:
                return None
            return {
                "mean": round(float(ok.mean()), 4),
                "min": round(float(ok.min()), 4),
                "max": round(float(ok.max()), 4),
                "defined": int(ok.size),
            }

        out = {
            "entrypoint": self.entrypoint,
            "n": self.n,
            "universes": self.U,
            "steps": self.steps,
            "knobs": list(self.knobs),
            "wall_s": round(self.wall_s, 3),
            "universes_per_sec": round(self.universes_per_sec, 3),
            "rounds_per_sec": round(self.rounds_per_sec, 2),
            "rounds_per_sec_per_universe": round(
                self.rounds_per_sec_per_universe, 3
            ),
            "metrics": {k: _stats(v) for k, v in self.metrics.items()},
        }
        if self.outbox_overflow is not None:
            # The composed plane's loud column: per-universe overflow
            # (outbox misses + budget deferrals), never silent.
            out["devices"] = self.devices
            out["overflow_total"] = int(
                np.asarray(self.outbox_overflow).sum()
            )
        return out


def _scalar(v):
    return float(v) if isinstance(v, (np.floating, float)) else int(v)


def _detect_metrics(dead: np.ndarray, n: int, tick_ms: float,
                    fail_at: float, defined: bool) -> dict:
    """Detection metrics from a [U, steps] dead-observer curve: first
    detection plus the DETECT_FRACS quantiles of the n-1 observers,
    each as latency-from-crash in ms (NaN when not a crash study or
    never reached).

    Only ticks at/after the crash count, the contract
    FalsePositiveReport.time_to_true_dead_ms pins: a pre-crash
    false-DEAD view that a refute later repairs must not register as a
    (negative-latency) detection — a hair-trigger suspicion scale pays
    for its false positives on the robustness axis, never by winning
    the latency axis."""
    U = dead.shape[0]
    nan = np.full(U, np.nan)
    out = {}
    start = max(int(fail_at), 0)
    targets = [("detect_first_ms", 1)] + [
        (f"detect_t{int(f * 100)}_ms", f * (n - 1)) for f in DETECT_FRACS
    ]
    for name, thresh in targets:
        if not defined:
            out[name] = nan.copy()
            continue
        t = first_tick_at_least(dead[:, start:], thresh)
        out[name] = (t + 1.0 + start - fail_at) * tick_ms
    return out


def summarize_sweep(universe, outs, wall_s: float) -> SweepReport:
    """Reduce a sweep's stacked host outputs into a SweepReport.

    ``outs`` is the per-tick output pytree of the entrypoint, stacked
    [U, steps, …] and already on the host (np.asarray'd by run_sweep).
    """
    from consul_tpu.sweep.universe import SWEEP_ENTRYPOINTS

    spec = SWEEP_ENTRYPOINTS[universe.entrypoint]
    base = spec.base_cfg(universe.cfg)
    n = base.n
    tick_ms = float(base.profile.gossip_interval_ms)
    steps = universe.steps
    metrics: dict = {}

    if universe.entrypoint in ("swim", "lifeguard"):
        if universe.entrypoint == "swim":
            sus, dead = outs
        else:
            sus, dead, fp, refutes, aware = outs
            sim_s = steps * tick_ms / 1000.0
            metrics["fp_total"] = np.asarray(fp).sum(axis=1).astype(
                float
            )
            metrics["fp_rate"] = metrics["fp_total"] / sim_s
            metrics["flaps"] = np.asarray(refutes).sum(axis=1).astype(
                float
            )
            metrics["mean_awareness_final"] = np.asarray(
                aware, float
            )[:, -1]
        crash = not base.subject_alive
        dead_np = np.asarray(dead)
        metrics.update(_detect_metrics(
            dead_np, n, tick_ms,
            fail_at=float(base.fail_at_tick), defined=crash,
        ))
        # False-DEAD pressure — the robustness axis of the suspicion-
        # timeout family: observers holding a DEAD view of the still-
        # live subject (pre-crash window for crash studies, the whole
        # run for FP studies).  A short timeout (suspicion_scale << 1)
        # buys detection latency at exactly this cost.
        window = dead_np[:, :int(base.fail_at_tick)] if crash else dead_np
        if window.shape[1] > 0:
            metrics["false_dead_mean"] = window.mean(axis=1).astype(
                float
            )
            metrics["false_dead_max"] = window.max(axis=1).astype(
                float
            )
        else:
            metrics["false_dead_mean"] = np.full(dead_np.shape[0], np.nan)
            metrics["false_dead_max"] = np.full(dead_np.shape[0], np.nan)
        # First suspicion is defined for crash AND FP studies (raw sim
        # time, matching SwimReport.summary's first_suspect_ms).
        t = first_tick_at_least(np.asarray(sus), 1)
        metrics["first_suspect_ms"] = (t + 1.0) * tick_ms
        metrics["suspecting_final"] = np.asarray(sus, float)[:, -1]
        metrics["dead_known_final"] = np.asarray(dead, float)[:, -1]
    elif universe.entrypoint == "broadcast":
        infected = np.asarray(outs)
        metrics["infected_final"] = infected[:, -1].astype(float)
        for frac in (0.50, 0.99):
            t = first_tick_at_least(infected, frac * n)
            metrics[f"t{int(frac * 100)}_ms"] = (t + 1.0) * tick_ms
        metrics["converged_tick"] = first_tick_at_least(infected, n)
    elif universe.entrypoint == "streamcast":
        from consul_tpu.streamcast.report import per_event_latency

        (slot_event, slot_birth, done_count, offered, delivered,
         quiesced, overflow, coalesced, _sent) = outs
        U = np.asarray(offered).shape[0]
        sim_s = steps * tick_ms / 1000.0
        metrics["events_offered"] = np.asarray(offered, float)[:, -1]
        metrics["events_delivered"] = np.asarray(
            delivered, float
        )[:, -1]
        metrics["events_quiesced"] = np.asarray(quiesced, float)[:, -1]
        metrics["events_coalesced"] = np.asarray(
            coalesced, float
        )[:, -1]
        metrics["window_overflow"] = np.asarray(overflow, float)[:, -1]
        metrics["offered_events_per_sim_s"] = (
            metrics["events_offered"] / sim_s
        )
        metrics["delivered_events_per_sim_s"] = (
            metrics["events_delivered"] / sim_s
        )
        off = metrics["events_offered"]
        metrics["undelivered_frac"] = np.where(
            off > 0, 1.0 - metrics["events_delivered"] / np.maximum(
                off, 1.0
            ), np.nan,
        )
        # Per-universe median of the per-event latency to frac*n —
        # the same reduction StreamcastReport.summary performs.
        for frac, name in ((0.50, "t50_ms"), (0.99, "t99_ms")):
            med = np.full(U, np.nan)
            for u in range(U):
                lat = np.asarray(
                    list(per_event_latency(
                        np.asarray(slot_event)[u],
                        np.asarray(slot_birth)[u],
                        np.asarray(done_count)[u],
                        n, tick_ms, frac,
                    ).values()),
                    dtype=float,
                )
                ok = lat[~np.isnan(lat)]
                if ok.size:
                    med[u] = float(np.median(ok))
            metrics[name] = med
    elif universe.entrypoint == "geo":
        per_segment, offered, admitted, queued, overflow, wasted = outs
        per_segment = np.asarray(per_segment)   # [U, steps, S]
        total = per_segment.sum(axis=2)         # [U, steps]
        seg_size = n // base.segments
        msg_bytes = base.wan_msg_bytes
        metrics["converged_frac"] = total[:, -1].astype(float) / n
        for frac in (0.50, 0.99):
            t = first_tick_at_least(total, frac * n)
            metrics[f"t{int(frac * 100)}_ms"] = (t + 1.0) * tick_ms
        # Worst segment's t99: the per-DC convergence straggler.
        seg_t = np.stack([
            first_tick_at_least(per_segment[:, :, s], 0.99 * seg_size)
            for s in range(base.segments)
        ], axis=1)                              # [U, S]
        metrics["seg_t99_ms_worst"] = (
            np.max(seg_t, axis=1) + 1.0
        ) * tick_ms                             # NaN propagates: any
        #                                         never-converged DC
        #                                         marks the universe
        metrics["wan_offered_bytes"] = (
            np.asarray(offered, float).sum(axis=(1, 2)) * msg_bytes
        )
        metrics["wan_admitted_bytes"] = (
            np.asarray(admitted, float).sum(axis=(1, 2)) * msg_bytes
        )
        metrics["wan_overflow_units"] = np.asarray(
            overflow, float
        ).sum(axis=(1, 2))
        metrics["wan_wasted_units"] = np.asarray(wasted, float)[:, -1]
        metrics["wan_queue_final_units"] = np.asarray(
            queued, float
        )[:, -1].sum(axis=1)
    else:  # membership / sparse
        sus_t, dead_t, sus_cells, known = outs
        if universe.track:
            dead0 = np.asarray(dead_t)[:, :, 0]
            sus0 = np.asarray(sus_t)[:, :, 0]
            fail_at = dict(base.fail_at).get(universe.track[0])
            metrics.update(_detect_metrics(
                dead0, n, tick_ms,
                fail_at=float(fail_at if fail_at is not None else 0),
                defined=fail_at is not None,
            ))
            metrics["suspecting_final"] = sus0[:, -1].astype(float)
            metrics["dead_known_final"] = dead0[:, -1].astype(float)
        metrics["suspect_cells_mean"] = np.asarray(
            sus_cells, float
        ).mean(axis=1)
        metrics["known_members_final"] = np.asarray(
            known, float
        )[:, -1]

    return SweepReport(
        entrypoint=universe.entrypoint,
        n=n,
        U=universe.U,
        steps=steps,
        tick_ms=tick_ms,
        knobs=tuple(universe.knobs),
        values={
            path: np.asarray(row)
            for path, row in zip(universe.knobs, universe.values)
        },
        metrics=metrics,
        wall_s=wall_s,
    )
