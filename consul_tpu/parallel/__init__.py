"""Device-mesh and sharding helpers (node-axis data parallelism).

Two planes: ``mesh.py`` places the UNSHARDED program's arrays over a
mesh (GSPMD, legacy ``sharded=True`` path), ``shard.py`` is the
explicit multi-chip simulation plane — per-device node blocks, outbox
message routing over ``lax.all_to_all``, whole studies inside one
``shard_map`` region.
"""

from consul_tpu.parallel.mesh import (
    block_size,
    make_mesh,
    mesh_for,
    node_sharding,
    replicated,
    shard_state,
)
from consul_tpu.parallel.shard import (
    exchange_outbox,
    outbox_budget,
    pack_outbox,
    sharded_broadcast_scan,
    sharded_membership_scan,
    sharded_sparse_membership_scan,
)

__all__ = [
    "block_size",
    "make_mesh",
    "mesh_for",
    "node_sharding",
    "replicated",
    "shard_state",
    "exchange_outbox",
    "outbox_budget",
    "pack_outbox",
    "sharded_broadcast_scan",
    "sharded_membership_scan",
    "sharded_sparse_membership_scan",
]
