"""Device-mesh and sharding helpers (node-axis data parallelism)."""

from consul_tpu.parallel.mesh import (
    make_mesh,
    node_sharding,
    replicated,
    shard_state,
)

__all__ = ["make_mesh", "node_sharding", "replicated", "shard_state"]
