"""Sharded multi-chip simulation plane: ``shard_map`` gossip over the mesh.

Every study so far ran on ONE chip; this module is the plane that
multiplies node capacity by the device count.  Each device owns a
CONTIGUOUS block of ``n/D`` nodes (global ids ``[me*blk, (me+1)*blk)``)
and the whole study — ``lax.scan`` over ticks included — runs inside a
single ``shard_map`` region, so cross-shard traffic compiles to XLA
collectives over ICI instead of host round-trips.

One sharded gossip round decomposes exactly like the real protocol's
traffic (nodes are independent actors exchanging messages — the
parallel-replication structure of "Rethinking State-Machine Replication
for Parallelism", pipelined cross-shard per "The Algorithm of Pipelined
Gossiping"):

  1. **Sample owned.**  Probe/gossip targets are GLOBAL node ids, but
     every draw is generated for the shard's OWNED rows only: node i's
     values derive from the per-(round, node) keyed streams
     ``fold_in(site_key, i)`` (ops/sampling.py), so the shard evaluates
     the same functions the unsharded scan evaluates over ``arange(n)``
     — bit-identical values at any D with O(n/D) per-chip draw cost,
     the property the D == 1 equality pin rides on.  No replicated
     full-population draw plane exists anywhere in the round.
  2. **Route.**  Messages whose receiver lives on another shard are
     packed into a fixed per-destination **outbox** (budget =
     c x the Poissonized mean arrivals per destination,
     :func:`outbox_budget`); misses are counted into ``overflow`` —
     never silent, same exactness-ladder discipline as the sparse
     model's compacted push/pull — and exchanged once per round
     through the backend seam :func:`exchange_outbox`
     (``exchange="alltoall"``: one ``lax.all_to_all``;
     ``exchange="ring"``: the Pallas ``make_async_remote_copy`` ring
     kernel, ``ops/ring_exchange.py``, whose double-buffered DMA hops
     overlap each other and the local delivery work).  Backends are
     bit-equal by construction.
  3. **Merge.**  Inbound arrivals join the local stream and land
     through the same delivery kernels the single-chip models use —
     the sparse plane's sort-merge kernel (``ops/sortmerge.py``)
     UNCHANGED, operating on the local row block.

Exactness ladder:
  D == 1          bit-equal to the unsharded scan (dense, sparse, and
                  broadcast models; pinned by tests/test_shard.py) —
                  the same pin strategy as sparse == dense at K == n.
  overflow == 0   the sharded run delivered every message a single
                  chip would have; the only difference from D == 1 is
                  placement.
  overflow > 0    outbox budget misses (bigger c or fewer shards is
                  the remedy) or push/pull initiator-budget misses
                  (the Poissonized schedule retries next interval).

Owned-draw memory note: the per-(round, node) keyed streams make every
per-node random plane O(n/D)/chip — the [n, fanout] target and loss
planes and the sparse plane's [n, K] gossip-priority tie-break (the
term that dominated the composed sweep's per-universe footprint) are
generated at [blk, .].  What remains replicated is static cfg-derived
structure (fail/leave schedules, the participates masks) and the geo
link plane — pure functions every shard steps identically — plus the
O(i_slots) push/pull initiator id lists exchanged by all_gather
(:func:`_global_initiators`), never an [n]-scale draw.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from consul_tpu.parallel.mesh import NODE_AXIS, block_size

OUTBOX_SAFETY = 2   # c: budget multiple of the per-destination mean
OUTBOX_FLOOR = 64   # never fewer slots than this (small-n studies)

# Equivalence-ladder pair metadata (consul_tpu/analysis/equivlint.py):
# sharded registry-key prefix -> the unsharded family it must be
# bit-equal to at D == 1.  sim.engine.EQUIV_PAIRS expands this into
# the declared D=1 and ring==alltoall rungs, so adding a sharded twin
# here is what puts it ON the ladder — one dict line, not one runtime
# test per axis point.
SHARDED_TWINS = {
    "sharded_broadcast": "broadcast",
    "sharded_membership": "membership",
    "sharded_sparse": "sparse",
    "sharded_streamcast": "streamcast",
    "sharded_geo": "geo",
}

# Sharded twins whose outs tuple appends ONE trailing leaf (the outbox
# overflow counter) relative to the unsharded program — the D=1
# witness compares through a drop-last projection for these.  The
# sparse twin folds outbox misses into the family's own overflow
# output, so its outputs align 1:1 with the unsharded scan.
SHARDED_EXTRA_OVERFLOW = frozenset({
    "sharded_broadcast", "sharded_membership", "sharded_streamcast",
    "sharded_geo",
})


# ---------------------------------------------------------------------------
# Outbox: fixed-budget cross-shard message routing.
# ---------------------------------------------------------------------------


def outbox_budget(stream_len: int, n_shards: int,
                  c: int = OUTBOX_SAFETY, floor: int = OUTBOX_FLOOR) -> int:
    """Per-destination outbox slots for a shard emitting ``stream_len``
    messages a round.  Uniform global targeting sends a message to each
    of the D shards with probability 1/D, so the Poissonized mean per
    destination is stream_len/D; the budget is ``c`` times that (floor
    ``floor``), and misses are counted — the same c x-mean discipline as
    ``pp_initiator_budget`` in models/membership_sparse.py."""
    if n_shards <= 1:
        return 1  # degenerate: remote traffic cannot exist
    return min(stream_len, max(floor, -(-c * stream_len // n_shards)))


def pack_outbox(dest: jax.Array, ok: jax.Array, cols: tuple,
                n_shards: int, budget: int):
    """Pack a flat message stream into per-destination outbox slots.

    ``dest`` int32[A] — destination shard per message; ``ok`` bool[A] —
    message exists (False slots of the static stream are dropped);
    ``cols`` — int32[A] payload planes (first is conventionally the
    global receiver id).  Messages sort by destination, take their rank
    within the destination's segment, and claim slot ``rank`` of that
    destination's ``budget`` slots; unpacked slots hold -1.  Messages
    ranked past the budget are dropped and counted.

    Returns ``(outbox_cols, dropped)`` with each outbox plane shaped
    [n_shards, budget]."""
    # Reuse the sort-merge kernel's segmented prefix sum: the outbox is
    # the same rank-matched allocation, with destination shards as the
    # segments and slot index as the claim order.
    from consul_tpu.ops.sortmerge import _segmented_sum

    a_len = dest.shape[0]
    idx = jnp.arange(a_len, dtype=jnp.int32)
    d = jnp.where(ok, dest.astype(jnp.int32), n_shards)
    d_sorted, perm = jax.lax.sort((d, idx), num_keys=1)
    seg_start = (idx == 0) | (d_sorted != jnp.roll(d_sorted, 1))
    rank = _segmented_sum(
        seg_start, jnp.ones((a_len,), jnp.int32)
    ) - 1
    valid = d_sorted < n_shards
    can = valid & (rank < budget)
    slot = jnp.where(can, d_sorted * budget + rank, n_shards * budget)
    packed = tuple(
        jnp.full((n_shards * budget,), -1, jnp.int32)
        .at[slot].set(c_[perm].astype(jnp.int32), mode="drop")
        .reshape(n_shards, budget)
        for c_ in cols
    )
    dropped = jnp.sum((valid & ~can).astype(jnp.int32))
    return packed, dropped


def exchange_outbox(planes: tuple, axis_name: str = NODE_AXIS,
                    backend: str = "alltoall") -> tuple:
    """Move row d of each [D, budget] outbox plane to shard d; the
    result flattens to the [D*budget] inbox (row d = what shard d
    addressed to us, -1 slots empty).

    ``backend`` selects the transport — identical results by
    construction, pinned by tests/test_shard.py:

      alltoall  one ``lax.all_to_all`` per payload plane (XLA's
                collective; the baseline)
      ring      the Pallas ``make_async_remote_copy`` ring kernel
                (``ops/ring_exchange.py``): D−1 double-buffered DMA
                hops that overlap each other and whatever local work
                XLA schedules alongside — interpret-mode on non-TPU
                backends, so the same code path runs everywhere
    """
    if backend == "ring":
        from consul_tpu.ops.ring_exchange import ring_exchange

        return ring_exchange(planes, axis_name)
    if backend != "alltoall":
        raise ValueError(
            f"unknown exchange backend {backend!r}; "
            "choose 'alltoall' or 'ring'"
        )
    return tuple(
        jax.lax.all_to_all(p, axis_name, 0, 0, tiled=True).reshape(-1)
        for p in planes
    )


def _rows(x: jax.Array, start: jax.Array, blk: int) -> jax.Array:
    """This shard's row block of a replicated full-population array."""
    return jax.lax.dynamic_slice_in_dim(x, start, blk, axis=0)


def _global_initiators(pp_ok_l: jax.Array, partner_l: jax.Array,
                       rows_g: jax.Array, n: int, i_slots: int):
    """Assemble the global budgeted push/pull initiator set from OWNED
    per-row draws — the replicated [n] initiate/partner planes'
    replacement.

    Each shard compacts its own initiators (ascending global id,
    ``ops.compact_to_budget``) into ``min(i_slots, blk)`` slots — a
    LOSSLESS cap for the global first-``i_slots`` cut, since no single
    shard can contribute more than the budget — all_gathers the
    (initiator, partner) id lists (2 x D x i_slots int32 per chip,
    O(i_slots), never O(n)), and compacts the concatenation, which is
    already globally ascending because shards own contiguous ascending
    blocks, down to the final budget.  The selected set is therefore
    EXACTLY the unsharded compaction's prefix at every D; empty slots
    hold the sentinel ``n`` and ``sel`` False.  Returns
    ``(who, pwho, sel, missed)`` with ``missed`` the global initiators
    past the budget (loud, retried by the Poissonized schedule)."""
    from consul_tpu.ops import compact_to_budget

    blk = rows_g.shape[0]
    li, lt, _, _ = compact_to_budget(pp_ok_l, min(i_slots, blk))
    who_l = jnp.where(lt, rows_g[li], n)
    pwho_l = jnp.where(lt, partner_l[li], n)
    who_all = jax.lax.all_gather(who_l, NODE_AXIS, tiled=True)
    pwho_all = jax.lax.all_gather(pwho_l, NODE_AXIS, tiled=True)
    gi, sel, _, _ = compact_to_budget(who_all < n, i_slots)
    who = jnp.where(sel, who_all[gi], n)
    pwho = jnp.where(sel, pwho_all[gi], n)
    missed = (
        jax.lax.psum(jnp.sum(pp_ok_l.astype(jnp.int32)), NODE_AXIS)
        - jnp.sum(sel.astype(jnp.int32))
    )
    return who, pwho, sel, missed


# ---------------------------------------------------------------------------
# Sharded broadcast (serf user-event epidemic).
# ---------------------------------------------------------------------------


def _sharded_broadcast_scan(state, key: jax.Array, cfg, steps: int,
                            mesh: Mesh, exchange: str = "alltoall",
                            telemetry: bool = False):
    """Sharded twin of ``sim.engine.broadcast_scan``: returns
    ``(final_state, (infected[steps], overflow))`` with every per-node
    plane block-sharded over the mesh and ``overflow`` the total outbox
    budget misses (0 at D == 1 by construction).  ``exchange`` selects
    the outbox transport (:func:`exchange_outbox`); backends are
    bit-equal, so the choice is purely a perf knob.

    ``telemetry`` appends the [steps, M] metrics trace
    (consul_tpu/obs/spec.py) as the LAST output: the local block's
    int32 emission combined with ONE integer ``psum`` over the mesh,
    so D == 1 is bit-equal to the unsharded trace and D == 2 == D == 1
    — same contract on every sharded scan below."""
    from consul_tpu.models.broadcast import BroadcastState
    from consul_tpu.obs.spec import emit_local, reduce_over_mesh
    from consul_tpu.ops import (
        bernoulli_mask_owned,
        deliver_or,
        owned_uniform,
        sample_peers_owned,
    )

    n, fanout = cfg.n, cfg.fanout
    d_shards = int(mesh.devices.size)
    blk = block_size(n, mesh)
    budget = (
        outbox_budget(blk * fanout, d_shards)
        if cfg.delivery == "edges" else 1
    )

    def tick(carry, k):
        st, ov = carry
        me = jax.lax.axis_index(NODE_AXIS)
        start = me * blk
        rows_g = start + jnp.arange(blk, dtype=jnp.int32)
        k_sel, k_loss = jax.random.split(k)
        senders = st.knows & (st.tx_left > 0)

        if cfg.delivery == "edges":
            # Owned draws: each shard generates draws for ITS global
            # ids only — the same per-(round, node) streams the
            # unsharded round evaluates over arange(n), so values are
            # bit-identical at any D with no replicated [n, F] plane.
            targets = sample_peers_owned(k_sel, rows_g, n, fanout)
            ok = senders[:, None] & bernoulli_mask_owned(
                k_loss, rows_g, (fanout,), 1.0 - cfg.loss
            )
            recv = targets.ravel()
            okf = ok.ravel()
            dest = recv // blk
            local = okf & (dest == me)
            new_knows = deliver_or(
                st.knows, jnp.where(local, recv - start, blk), local
            )
            (ob_recv,), dropped = pack_outbox(
                dest, okf & (dest != me), (recv,), d_shards, budget
            )
            (ib_recv,) = exchange_outbox(
                (ob_recv,), backend=exchange
            )
            got_in = ib_recv >= 0
            new_knows = deliver_or(
                new_knows, jnp.where(got_in, ib_recv - start, blk), got_in
            )
            ov = ov + jax.lax.psum(dropped, NODE_AXIS)
        else:
            # Poissonized aggregate delivery: the only cross-shard
            # traffic is ONE scalar — the live sender count.
            s_total = jax.lax.psum(
                jnp.sum(senders, dtype=jnp.float32), NODE_AXIS
            )
            lam = (
                (s_total - senders.astype(jnp.float32))
                * fanout
                * (1.0 - cfg.loss)
                / max(n - 1, 1)
            )
            u = owned_uniform(k_loss, rows_g)
            new_knows = st.knows | (u < -jnp.expm1(-lam))

        spent = jnp.where(senders, fanout, 0).astype(jnp.int32)
        tx_left = jnp.maximum(st.tx_left - spent, 0)
        newly = new_knows & ~st.knows
        tx_left = jnp.where(newly, cfg.tx_limit, tx_left)
        nxt = BroadcastState(
            knows=new_knows, tx_left=tx_left, tick=st.tick + 1
        )
        infected = jax.lax.psum(
            jnp.sum(new_knows, dtype=jnp.int32), NODE_AXIS
        )
        out = infected
        if telemetry:
            out = (infected, reduce_over_mesh(
                "broadcast",
                emit_local("broadcast", st, nxt, infected, cfg),
                NODE_AXIS,
            ))
        return (nxt, ov), out

    def body(st, key):
        (final, ov), outs = jax.lax.scan(
            lambda carry, t: tick(carry, jax.random.fold_in(key, t)),
            (st, jnp.int32(0)), jnp.arange(steps, dtype=jnp.int32),
        )
        return final, outs, ov

    state_spec = BroadcastState(P(NODE_AXIS), P(NODE_AXIS), P())
    run = shard_map(
        body, mesh=mesh,
        in_specs=(state_spec, P()),
        out_specs=(state_spec, (P(), P()) if telemetry else P(), P()),
        check_rep=False,
    )
    final, outs, ov = run(state, key)
    if telemetry:
        infected, trace = outs
        return final, (infected, ov, trace)
    return final, (outs, ov)


# The jitted public twins live at module bottom (all statics positional-
# hashable); the unjitted ``_sharded_*_scan`` impls above/below exist so
# the sweep plane (consul_tpu/sweep) can vmap them with TRACED knob
# fields inside cfg — the same unjitted/jitted split as sim.engine's
# scan entrypoints.
sharded_broadcast_scan = jax.jit(
    _sharded_broadcast_scan,
    static_argnames=("cfg", "steps", "mesh", "exchange", "telemetry"),
)


# ---------------------------------------------------------------------------
# Sharded dense membership (full N x N view matrix, row blocks).
# ---------------------------------------------------------------------------


def _sharded_membership_scan(state, key: jax.Array, cfg, steps: int,
                             mesh: Mesh, track: tuple = (),
                             exchange: str = "alltoall",
                             telemetry: bool = False):
    """Sharded twin of ``sim.engine.membership_scan``: each device owns
    ``n/D`` observer ROWS of every [n, n] plane.  Gossip scatters route
    through the outbox; the push/pull row exchange gathers the budgeted
    initiator/partner rows with a ``pmax`` over the mesh (rows are
    [n]-wide, so dense sharding shards STATE and the probe/suspicion
    planes — scale itself belongs to the sparse model).  Returns
    ``(final_state, (outs..., overflow))`` with the same per-tick
    counters as the unsharded scan.

    ``state`` is donated (jaxlint J3, same contract as the unsharded
    scan): callers pass a fresh state positionally and read only the
    returned one."""
    from consul_tpu.models.membership import (
        NEVER,
        RANK_ALIVE,
        RANK_DEAD,
        RANK_LEFT,
        RANK_SUSPECT,
        MembershipState,
        _lifeguard_timeout_ticks,
        _schedule_array,
        key_inc,
        key_rank,
        make_key,
    )
    from consul_tpu.models.membership_sparse import pp_initiator_budget
    from consul_tpu.obs.spec import emit_local, reduce_over_mesh
    from consul_tpu.ops import (
        bernoulli_mask_owned,
        owned_uniform,
        sample_peers_owned,
        sample_probe_targets_owned,
    )

    n, fanout = cfg.n, cfg.fanout
    m_drain = min(cfg.piggyback, n)
    d_shards = int(mesh.devices.size)
    blk = block_size(n, mesh)
    budget = outbox_budget(blk * fanout * m_drain, d_shards)
    track_idx = jnp.asarray(track, jnp.int32) if track else jnp.zeros(
        (0,), jnp.int32
    )

    def tick(carry, k_rng):
        st, ov = carry
        me = jax.lax.axis_index(NODE_AXIS)
        start = me * blk
        t = st.tick
        (k_tie, k_tgt, k_loss, k_pp, k_ppsel, k_probe, k_pfail) = (
            jax.random.split(k_rng, 7)
        )
        rows_l = jnp.arange(blk, dtype=jnp.int32)
        rows_g = start + rows_l

        # Ground truth (replicated [n] schedules — static cfg-derived,
        # not draws; local boolean slices).
        fail_tick = _schedule_array(n, cfg.fail_at, NEVER)
        leave_tick = _schedule_array(n, cfg.leave_at, NEVER)
        join_tick = _schedule_array(n, cfg.join_at, 0)
        present = t >= join_tick
        crashed = t >= fail_tick
        leaving = present & (t >= leave_tick) & ~crashed
        departed = present & ~crashed & (
            t >= jnp.minimum(leave_tick, NEVER - cfg.leave_grace_ticks)
            + cfg.leave_grace_ticks
        )
        participates = present & ~crashed & ~departed
        part_l = _rows(participates, start, blk)
        present_l = _rows(present, start, blk)
        leaving_l = _rows(leaving, start, blk)

        key_m = st.key
        tx = st.tx
        suspect_since = st.suspect_since
        confirms = st.confirms
        own_inc = st.own_inc
        awareness = st.awareness

        # Leave intent: re-stamp the self cell (column = global id).
        diag = key_m[rows_l, rows_g]
        diag_val = jnp.where(
            leaving_l,
            make_key(own_inc, RANK_LEFT), make_key(own_inc, RANK_ALIVE),
        )
        diag_val = jnp.maximum(diag, diag_val)
        key_m = key_m.at[rows_l, rows_g].set(
            jnp.where(present_l, diag_val, diag)
        )
        tx = tx.at[rows_l, rows_g].set(
            jnp.where(diag_val > diag, cfg.tx_limit, tx[rows_l, rows_g])
        )

        # -- 1. gossip (owned draws: [blk, .] streams keyed by global
        # id — no replicated [n, .] planes) -----------------------------
        prio = tx.astype(jnp.float32) + owned_uniform(
            k_tie, rows_g, (n,)
        )
        _, subj = jax.lax.top_k(prio, m_drain)
        subj = subj.astype(jnp.int32)                  # [blk, M] global
        msg_key = jnp.take_along_axis(key_m, subj, axis=1)
        msg_valid = (
            (jnp.take_along_axis(tx, subj, axis=1) > 0)
            & (msg_key >= 0)
            & part_l[:, None]
        )

        targets = sample_peers_owned(k_tgt, rows_g, n, fanout)
        tgt_view = jnp.take_along_axis(key_m, targets, axis=1)
        tgt_sendable = (
            (tgt_view >= 0) & (key_rank(tgt_view) <= RANK_SUSPECT)
        )
        packet_ok = (
            part_l[:, None]
            & tgt_sendable
            & bernoulli_mask_owned(
                k_loss, rows_g, (fanout,), 1.0 - cfg.loss
            )
            & participates[targets]
        )

        shape3 = (blk, fanout, m_drain)
        recv = jnp.broadcast_to(targets[:, :, None], shape3).ravel()
        subj3 = jnp.broadcast_to(subj[:, None, :], shape3).ravel()
        val3 = jnp.broadcast_to(msg_key[:, None, :], shape3).ravel()
        ok3 = (
            packet_ok[:, :, None] & msg_valid[:, None, :]
        ).ravel()
        sus3 = jnp.where(
            key_rank(val3) == RANK_SUSPECT, key_inc(val3), -1
        )

        # Local deliveries scatter straight into the row block; remote
        # ones ride the outbox.
        dest = recv // blk
        local = ok3 & (dest == me)
        flat = jnp.where(local, (recv - start) * n + subj3, blk * n)
        key_rx = (
            jnp.full((blk * n,), -1, jnp.int32)
            .at[flat].max(val3, mode="drop").reshape(blk, n)
        )
        sus_rx = (
            jnp.full((blk * n,), -1, jnp.int32)
            .at[flat].max(sus3, mode="drop").reshape(blk, n)
        )
        packed, dropped = pack_outbox(
            dest, ok3 & (dest != me), (recv, subj3, val3, sus3),
            d_shards, budget,
        )
        ib_recv, ib_subj, ib_val, ib_sus = exchange_outbox(
            packed, backend=exchange
        )
        got_in = ib_recv >= 0
        flat_in = jnp.where(
            got_in, (ib_recv - start) * n + ib_subj, blk * n
        )
        key_rx = (
            key_rx.ravel().at[flat_in].max(ib_val, mode="drop")
            .reshape(blk, n)
        )
        sus_rx = (
            sus_rx.ravel().at[flat_in].max(ib_sus, mode="drop")
            .reshape(blk, n)
        )
        ov_local = dropped

        spend = jnp.where(msg_valid, fanout, 0)
        tx = jnp.maximum(
            tx.at[jnp.repeat(rows_l, m_drain), subj.ravel()]
            .add(-spend.ravel()),
            0,
        )

        # -- 2. push/pull (owned draws; the initiation coin and the
        # partner pick exist only for the owned rows — the global
        # initiator set assembles from per-shard compacted id lists,
        # never from a replicated [n] draw plane) ----------------------
        ov_repl = jnp.int32(0)
        if cfg.push_pull_enabled:
            known_l = jnp.sum(
                (key_m >= 0) & (key_rank(key_m) <= RANK_SUSPECT), axis=1
            )
            needs_join_l = part_l & (known_l <= 1)
            initiate_l = part_l & (
                needs_join_l
                | bernoulli_mask_owned(
                    k_pp, rows_g, (), 1.0 / cfg.push_pull_ticks
                )
            )
            partner_l = sample_probe_targets_owned(k_ppsel, rows_g, n)
            pp_ok_l = initiate_l & participates[partner_l]
            if d_shards == 1:
                # Full-width exchange — bit-equal to the unsharded
                # round (the D == 1 pin, like sparse == dense at K == n):
                # at D == 1 the owned rows ARE the population.
                pp_ok, partner = pp_ok_l, partner_l
                key_rx = jnp.maximum(
                    key_rx,
                    jnp.where(pp_ok[:, None], key_m[partner], -1),
                )
                prow = jnp.where(pp_ok, partner, n)
                key_rx = key_rx.at[prow].max(key_m, mode="drop")
            else:
                # Budgeted initiators (pp_initiator_budget, the sparse
                # model's discipline) via _global_initiators; the
                # [I, n] initiator and partner rows assemble by pmax —
                # each shard contributes the rows it owns, -1 elsewhere.
                i_slots = pp_initiator_budget(n, cfg.push_pull_ticks)
                who, pwho, sel, missed = _global_initiators(
                    pp_ok_l, partner_l, rows_g, n, i_slots
                )
                ov_repl = ov_repl + missed

                def rows_of(ids, live):
                    loc = ids - start
                    own = (loc >= 0) & (loc < blk) & live
                    vals = key_m[jnp.clip(loc, 0, blk - 1)]
                    return jax.lax.pmax(
                        jnp.where(own[:, None], vals, -1), NODE_AXIS
                    ), loc, own

                init_rows, li, own_i = rows_of(who, sel)
                partner_rows, lp, own_p = rows_of(pwho, sel)
                # Pull: a locally-owned initiator merges its partner's
                # row; push: a locally-owned partner merges the
                # initiator's.
                key_rx = key_rx.at[jnp.where(own_i, li, blk)].max(
                    partner_rows, mode="drop"
                )
                key_rx = key_rx.at[jnp.where(own_p, lp, blk)].max(
                    init_rows, mode="drop"
                )

        # -- 3. refutation ---------------------------------------------
        self_rx = key_rx[rows_l, rows_g]
        accused = jnp.where(
            key_rank(self_rx) >= RANK_SUSPECT, key_inc(self_rx), -1
        )
        refuting = part_l & ~leaving_l & (accused >= own_inc)
        own_inc = jnp.where(refuting, accused + 1, own_inc)
        awareness = jnp.clip(
            awareness + refuting.astype(jnp.int32),
            0, cfg.profile.awareness_max_multiplier - 1,
        )
        key_rx = key_rx.at[rows_l, rows_g].set(-1)
        self_key = jnp.where(
            leaving_l,
            make_key(own_inc, RANK_LEFT), make_key(own_inc, RANK_ALIVE),
        )
        key_after_refute = key_m.at[rows_l, rows_g].max(
            jnp.where(present_l, self_key, -1)
        )
        tx = tx.at[rows_l, rows_g].set(
            jnp.where(refuting, cfg.tx_limit, tx[rows_l, rows_g])
        )

        # -- 4. merge --------------------------------------------------
        old_key = key_after_refute
        new_key = jnp.maximum(old_key, key_rx)
        changed = new_key > old_key
        fresh_suspect = changed & (key_rank(new_key) == RANK_SUSPECT)
        suspect_since = jnp.where(
            fresh_suspect, t, jnp.where(changed, NEVER, suspect_since)
        )
        confirming = (
            ~changed
            & (key_rank(old_key) == RANK_SUSPECT)
            & (sus_rx >= key_inc(old_key))
        )
        new_confirms = jnp.minimum(
            confirms + confirming.astype(jnp.int32), cfg.confirmations_k
        )
        gained_conf = confirming & (new_confirms > confirms)
        confirms = jnp.where(changed, 0, new_confirms)
        tx = jnp.where(changed | gained_conf, cfg.tx_limit, tx)
        key_m = new_key

        # -- 5. probes (owned draws) -----------------------------------
        if cfg.probe_enabled:
            is_probe_tick = (t % cfg.probe_interval_ticks) == 0
            ptarget = sample_probe_targets_owned(k_probe, rows_g, n)
            pt_view = key_m[rows_l, ptarget]
            probing = (
                is_probe_tick
                & part_l
                & (pt_view >= 0)
                & (key_rank(pt_view) <= RANK_SUSPECT)
            )
            target_up = participates[ptarget]
            p_fail = jnp.where(
                target_up, jnp.float32(cfg.probe_fail_prob_alive), 1.0
            )
            failed = probing & (
                owned_uniform(k_pfail, rows_g) < p_fail
            )
            can_pend = failed & (st.probe_pending_at == NEVER)
            matures_at = (
                t + cfg.probe_interval_ticks
                + awareness * cfg.probe_timeout_ticks
            )
            awareness = jnp.clip(
                awareness + failed.astype(jnp.int32)
                - (probing & ~failed).astype(jnp.int32),
                0, cfg.profile.awareness_max_multiplier - 1,
            )
            probe_pending_at = jnp.where(
                can_pend, matures_at, st.probe_pending_at
            )
            probe_subject = jnp.where(can_pend, ptarget, st.probe_subject)

            mature = (probe_pending_at <= t) & part_l
            mview = key_m[rows_l, probe_subject]
            apply_sus = mature & (key_rank(mview) == RANK_ALIVE)
            sus_key = make_key(key_inc(mview), RANK_SUSPECT)
            scol = jnp.where(apply_sus, probe_subject, n)
            key_m = key_m.at[rows_l, scol].set(
                jnp.where(apply_sus, sus_key, 0), mode="drop"
            )
            suspect_since = suspect_since.at[rows_l, scol].set(
                jnp.where(apply_sus, t, 0), mode="drop"
            )
            confirms = confirms.at[rows_l, scol].set(0, mode="drop")
            tx = tx.at[rows_l, scol].set(cfg.tx_limit, mode="drop")
            probe_pending_at = jnp.where(mature, NEVER, probe_pending_at)
        else:
            probe_pending_at = st.probe_pending_at
            probe_subject = st.probe_subject

        # -- 6. suspicion expiry ---------------------------------------
        timeout = _lifeguard_timeout_ticks(cfg, confirms)
        elapsed = (t - suspect_since).astype(jnp.float32)
        expire = (
            (key_rank(key_m) == RANK_SUSPECT)
            & (suspect_since != NEVER)
            & (elapsed >= timeout)
            & part_l[:, None]
        )
        key_m = jnp.where(
            expire, make_key(key_inc(key_m), RANK_DEAD), key_m
        )
        suspect_since = jnp.where(expire, NEVER, suspect_since)
        tx = jnp.where(expire, cfg.tx_limit, tx)

        nxt = MembershipState(
            key=key_m,
            suspect_since=suspect_since,
            confirms=confirms,
            tx=tx,
            own_inc=own_inc,
            awareness=awareness,
            probe_pending_at=probe_pending_at,
            probe_subject=probe_subject,
            tick=t + 1,
        )
        ranks = key_rank(key_m)
        cols = ranks[:, track_idx] if track else jnp.zeros(
            (blk, 0), jnp.int32
        )
        out = (
            jax.lax.psum(
                jnp.sum(cols == RANK_SUSPECT, axis=0, dtype=jnp.int32),
                NODE_AXIS,
            ),
            jax.lax.psum(
                jnp.sum(cols == RANK_DEAD, axis=0, dtype=jnp.int32),
                NODE_AXIS,
            ),
            jax.lax.psum(
                jnp.sum(ranks == RANK_SUSPECT, dtype=jnp.int32),
                NODE_AXIS,
            ),
            jax.lax.psum(
                jnp.sum(
                    (key_m >= 0) & (ranks <= RANK_SUSPECT),
                    dtype=jnp.int32,
                ),
                NODE_AXIS,
            ),
        )
        if telemetry:
            out = (*out, reduce_over_mesh(
                "membership",
                emit_local("membership", st, nxt, out, cfg),
                NODE_AXIS,
            ))
        ov = ov + jax.lax.psum(ov_local, NODE_AXIS) + ov_repl
        return (nxt, ov), out

    state_spec = MembershipState(
        key=P(NODE_AXIS, None),
        suspect_since=P(NODE_AXIS, None),
        confirms=P(NODE_AXIS, None),
        tx=P(NODE_AXIS, None),
        own_inc=P(NODE_AXIS),
        awareness=P(NODE_AXIS),
        probe_pending_at=P(NODE_AXIS),
        probe_subject=P(NODE_AXIS),
        tick=P(),
    )

    def body(st, key):
        (final, ov), outs = jax.lax.scan(
            lambda carry, t: tick(carry, jax.random.fold_in(key, t)),
            (st, jnp.int32(0)), jnp.arange(steps, dtype=jnp.int32),
        )
        return final, outs, ov

    n_outs = 5 if telemetry else 4
    run = shard_map(
        body, mesh=mesh,
        in_specs=(state_spec, P()),
        out_specs=(state_spec, tuple(P() for _ in range(n_outs)), P()),
        check_rep=False,
    )
    final, outs, ov = run(state, key)
    if telemetry:
        *outs, trace = outs
        return final, (*outs, ov, trace)
    return final, (*outs, ov)


sharded_membership_scan = jax.jit(
    _sharded_membership_scan,
    static_argnames=("cfg", "steps", "mesh", "track", "exchange",
                     "telemetry"),
    donate_argnums=(0,),
)


# ---------------------------------------------------------------------------
# Sharded sparse membership (top-K slots, sort-merge delivery).
# ---------------------------------------------------------------------------


def _sharded_sparse_membership_scan(state, key: jax.Array, cfg,
                                    steps: int, mesh: Mesh,
                                    track: tuple = (),
                                    exchange: str = "alltoall",
                                    telemetry: bool = False):
    """Sharded twin of ``sim.engine.sparse_membership_scan``: each
    device owns ``n/D`` observer rows of the [n, K] slot planes; the
    whole inbound stream — local gossip, compacted push/pull, and the
    outbox inbox — lands through ONE call to the sort-merge delivery
    kernel per tick (``ops/sortmerge.py``, unchanged, on the local
    block).  Requires K < n (the K == n identity layout is the
    unsharded parity mode).  Returns ``(final_state, outs)`` shaped
    like the unsharded scan; ``state.overflow`` additionally counts
    outbox budget misses.

    Gossip emission compacts to the same static sender budget as the
    unsharded plane (``gossip_sender_budget`` over the LOCAL block, so
    D == 1 keeps the exact unsharded budget): steady-state ticks carry
    ~no live senders, and the per-chip lane expansion — the dominant
    per-round bytes once sweeps ride this scan — tracks real traffic
    instead of ``blk * F * M`` ~all-masked slots.  Unselected senders
    spend no tx, count into ``overflow``, and retry next tick."""
    from consul_tpu.models.membership import (
        NEVER,
        RANK_ALIVE,
        RANK_DEAD,
        RANK_LEFT,
        RANK_SUSPECT,
        _lifeguard_timeout_ticks,
        _schedule_array,
        key_inc,
        key_rank,
        make_key,
    )
    from consul_tpu.models.membership_sparse import (
        AGE_CAP,
        AGE_NONE,
        COUNTER_CAP,
        DEFAULT_KEY,
        SINCE_DTYPE,
        SparseMembershipState,
        _claim_one,
        _merge_arrivals,
        _view_of,
        gossip_sender_budget,
        pp_initiator_budget,
        resolve_amortize,
        settled_of,
    )
    from consul_tpu.obs.spec import emit_local, reduce_over_mesh
    from consul_tpu.ops import (
        bernoulli_mask_owned,
        compact_to_budget,
        owned_uniform,
        row_locate,
        sample_peers_owned,
        sample_probe_targets_owned,
    )

    base = cfg.base
    n, fanout = base.n, base.fanout
    k_slots = min(cfg.k_slots, n)
    if k_slots >= n:
        raise ValueError(
            "sharded sparse plane requires k_slots < n (K == n is the "
            "unsharded dense-parity mode)"
        )
    m_drain = min(base.piggyback, k_slots)
    d_shards = int(mesh.devices.size)
    blk = block_size(n, mesh)
    i_slots = pp_initiator_budget(n, base.push_pull_ticks)
    # Compacted gossip lanes: the per-shard emission bound is the LOCAL
    # sender budget (gossip_sender_budget over blk rows — at D == 1
    # this IS the unsharded plane's budget), not the full block width.
    s_budget = gossip_sender_budget(blk)
    # Owned-leg budget of the push/pull exchange: a shard SOURCES only
    # the legs whose row it owns — mean ~n/(push_pull_ticks * D) per
    # leg class under uniform placement — so the per-chip [., K] leg
    # gathers compact to i_slots/D (floor 64).  i_slots is already 8x
    # the GLOBAL Poissonized mean (pp_initiator_budget), so i_slots/D
    # keeps the same 8x safety margin per shard; the former 2x on top
    # of that doubled the composed plane's stream, outbox, and merge
    # temps for tail mass that is already negligible at 8x.  At
    # D == 1 this is exactly i_slots (bit-equality); misses count
    # into overflow and the Poissonized schedule retries them.
    pp_owned = min(i_slots, max(64, i_slots // d_shards))
    stream_len = s_budget * fanout * m_drain
    if base.push_pull_enabled:
        stream_len += 2 * pp_owned * k_slots
    budget = outbox_budget(stream_len, d_shards)
    track_idx = jnp.asarray(track, jnp.int32) if track else jnp.zeros(
        (0,), jnp.int32
    )

    def tick(st, k_rng):
        me = jax.lax.axis_index(NODE_AXIS)
        start = me * blk
        t = st.tick
        (k_tie, k_tgt, k_loss, k_pp, k_ppsel, k_probe, k_pfail) = (
            jax.random.split(k_rng, 7)
        )
        rows_l = jnp.arange(blk, dtype=jnp.int32)
        rows_g = start + rows_l

        fail_tick = _schedule_array(n, base.fail_at, NEVER)
        leave_tick = _schedule_array(n, base.leave_at, NEVER)
        present = jnp.ones((n,), bool)
        crashed = t >= fail_tick
        leaving = present & (t >= leave_tick) & ~crashed
        departed = present & ~crashed & (
            t >= jnp.minimum(leave_tick, NEVER - base.leave_grace_ticks)
            + base.leave_grace_ticks
        )
        participates = present & ~crashed & ~departed
        part_l = _rows(participates, start, blk)
        leaving_l = _rows(leaving, start, blk)

        slot_subj = st.slot_subj
        key_m = st.key
        tx = st.tx
        suspect_since = st.suspect_since
        confirms = st.confirms
        own_inc = st.own_inc
        awareness = st.awareness
        overflow = st.overflow
        forgotten = st.forgotten

        occupied = slot_subj >= 0
        self_slot = row_locate(slot_subj, rows_l, rows_g)

        diag = key_m[rows_l, self_slot]
        diag_val = jnp.where(
            leaving_l,
            make_key(own_inc, RANK_LEFT), make_key(own_inc, RANK_ALIVE),
        )
        diag_val = jnp.maximum(diag, diag_val)
        key_m = key_m.at[rows_l, self_slot].set(diag_val)
        tx = tx.at[rows_l, self_slot].set(
            jnp.where(
                diag_val > diag, base.tx_limit, tx[rows_l, self_slot]
            )
        )

        # -- 1. gossip (owned draws: [blk, .] streams keyed by global
        # id — the [n, K] tie-break plane that dominated the composed
        # per-universe footprint no longer exists) ---------------------
        prio = jnp.where(
            occupied, tx.astype(jnp.float32), -jnp.inf
        ) + owned_uniform(k_tie, rows_g, (k_slots,))
        _, sslot = jax.lax.top_k(prio, m_drain)
        sslot = sslot.astype(jnp.int32)
        msg_subj = jnp.take_along_axis(slot_subj, sslot, axis=1)
        msg_key = jnp.take_along_axis(key_m, sslot, axis=1)
        msg_valid = (
            (jnp.take_along_axis(tx, sslot, axis=1) > 0)
            & (msg_subj >= 0)
            & part_l[:, None]
        )

        targets = sample_peers_owned(k_tgt, rows_g, n, fanout)
        tgt_view = _view_of(slot_subj, key_m, rows_l[:, None], targets)
        tgt_sendable = key_rank(tgt_view) <= RANK_SUSPECT
        packet_ok = (
            part_l[:, None]
            & tgt_sendable
            & bernoulli_mask_owned(
                k_loss, rows_g, (fanout,), 1.0 - base.loss
            )
            & participates[targets]
        )

        # Compacted emission (gossip_sender_budget over the local
        # block — the unsharded K < n discipline verbatim): local rows
        # holding a live message compact into s_budget sender slots
        # BEFORE the [., F, M] lane expansion; unselected senders keep
        # their tx (pure deferral), count into overflow, and retry.
        has_msg = jnp.any(msg_valid, axis=1)
        sndc, sel_s, sel_mask, ov_gossip = compact_to_budget(
            has_msg, s_budget
        )
        msg_valid = msg_valid & sel_mask[:, None]

        shape3 = (s_budget, fanout, m_drain)
        g_targets = targets[sndc]
        g_packet_ok = packet_ok[sndc] & sel_s[:, None]
        g_msg_subj = msg_subj[sndc]
        g_msg_key = msg_key[sndc]
        g_msg_valid = msg_valid[sndc]
        recv_g = jnp.broadcast_to(g_targets[:, :, None], shape3).ravel()
        subj_g = jnp.broadcast_to(
            g_msg_subj[:, None, :], shape3).ravel()
        val_g = jnp.broadcast_to(g_msg_key[:, None, :], shape3).ravel()
        ok_g = (
            g_packet_ok[:, :, None] & g_msg_valid[:, None, :]
        ).ravel()
        sus_g = jnp.where(
            key_rank(val_g) == RANK_SUSPECT, key_inc(val_g), -1
        )
        alloc_g = jnp.ones(recv_g.shape, bool)

        spend = jnp.where(msg_valid, fanout, 0).astype(tx.dtype)
        # unique_indices: distinct top_k slots per row (see the
        # unsharded twin's note — the J7-certified TX_DTYPE bound).
        # Unselected senders were masked out of msg_valid above, so
        # deferred messages spend nothing.
        tx = jnp.maximum(
            tx.at[jnp.repeat(rows_l, m_drain), sslot.ravel()]
            .add(-spend.ravel(), unique_indices=True),
            0,
        )

        # -- 2. push/pull (owned draws; compacted; sources emit,
        # outbox routes) -----------------------------------------------
        ov_repl = jnp.int32(0)
        ov_legs = jnp.int32(0)
        streams = [(recv_g, subj_g, val_g, sus_g, ok_g, alloc_g)]
        if base.push_pull_enabled:
            dead_cnt_l = jnp.sum(
                occupied & (key_rank(key_m) > RANK_SUSPECT), axis=1
            )
            known_l = n - dead_cnt_l
            needs_join_l = part_l & (known_l <= 1)
            initiate_l = part_l & (
                needs_join_l
                | bernoulli_mask_owned(
                    k_pp, rows_g, (), 1.0 / base.push_pull_ticks
                )
            )
            partner_l = sample_probe_targets_owned(k_ppsel, rows_g, n)
            pp_ok_l = initiate_l & participates[partner_l]
            who, pwho, sel, missed = _global_initiators(
                pp_ok_l, partner_l, rows_g, n, i_slots
            )
            ov_repl = ov_repl + missed

            # Each shard emits the exchange legs whose SOURCE row it
            # owns, COMPACTED into pp_owned slots (the budget note
            # above; ops.compact_to_budget) — legs past the budget
            # drop LOUDLY into the overflow ledger.  At D == 1 every
            # leg is owned and pp_owned == i_slots, so the selected
            # legs keep their positions (the compacted sel is an index
            # prefix) and the stream is bit-identical to the unsharded
            # exchange after masking.
            def owned_legs(src_g, recv_g_ids):
                loc = src_g - start
                own = (loc >= 0) & (loc < blk) & sel
                j, taken, _, d_legs = compact_to_budget(own, pp_owned)
                src_l = jnp.clip(src_g[j] - start, 0, blk - 1)
                return taken, src_l, recv_g_ids[j], d_legs

            tk_p, src_p, recv_p, d_p = owned_legs(pwho, who)
            subj_pull = slot_subj[src_p].ravel()
            val_pull = key_m[src_p].ravel()
            recv_pull = jnp.repeat(recv_p, k_slots)
            ok_pull = jnp.repeat(tk_p, k_slots) & (subj_pull >= 0)
            tk_i, src_i, recv_i, d_i = owned_legs(who, pwho)
            subj_push = slot_subj[src_i].ravel()
            val_push = key_m[src_i].ravel()
            recv_push = jnp.repeat(recv_i, k_slots)
            ok_push = jnp.repeat(tk_i, k_slots) & (subj_push >= 0)
            ov_legs = d_p + d_i
            minus1 = jnp.full(recv_pull.shape, -1, jnp.int32)
            # Settled alive@inc pp rows merge but never allocate (the
            # evict->relearn amplification gate, as unsharded).
            alloc_pull = key_rank(val_pull) >= RANK_SUSPECT
            alloc_push = key_rank(val_push) >= RANK_SUSPECT
            streams.append((recv_pull, subj_pull, val_pull, minus1,
                            ok_pull, alloc_pull))
            streams.append((recv_push, subj_push, val_push, minus1,
                            ok_push, alloc_push))

        recv = jnp.concatenate([s[0] for s in streams])
        subj = jnp.concatenate([s[1] for s in streams])
        val = jnp.concatenate([s[2] for s in streams])
        sus = jnp.concatenate([s[3] for s in streams])
        ok = jnp.concatenate([s[4] for s in streams])
        alloc = jnp.concatenate([s[5] for s in streams])

        # -- 3. route: local stream + outbox exchange ------------------
        dest = recv // blk
        local = ok & (dest == me)
        packed, dropped = pack_outbox(
            dest, ok & (dest != me),
            (recv, subj, val, sus, alloc.astype(jnp.int32)),
            d_shards, budget,
        )
        ib_recv, ib_subj, ib_val, ib_sus, ib_alloc = exchange_outbox(
            packed, backend=exchange
        )
        ib_ok = ib_recv >= 0
        recv_l = jnp.concatenate([
            jnp.where(local, recv - start, 0),
            jnp.where(ib_ok, ib_recv - start, 0),
        ])
        subj_l = jnp.concatenate([subj, ib_subj])
        val_l = jnp.concatenate([val, ib_val])
        sus_l = jnp.concatenate([sus, ib_sus])
        ok_l = jnp.concatenate([local, ib_ok])
        alloc_l = jnp.concatenate([alloc, ib_alloc > 0])

        slots_t, key_rx, sus_rx, overflow_l, forgotten_l = (
            _merge_arrivals(
                (slot_subj, key_m, suspect_since, confirms, tx),
                recv_l, subj_l, val_l, sus_l, ok_l, alloc_l, n, k_slots,
                jnp.int32(0), jnp.int32(0), row_ids=rows_g,
                amortize=resolve_amortize(cfg),
            )
        )
        slot_subj, key_m, suspect_since, confirms, tx = slots_t
        overflow = jnp.minimum(overflow, COUNTER_CAP) + ov_repl + (
            jax.lax.psum(ov_gossip + ov_legs + overflow_l + dropped,
                         NODE_AXIS)
        )
        forgotten = jnp.minimum(forgotten, COUNTER_CAP) + jax.lax.psum(
            forgotten_l, NODE_AXIS
        )
        self_slot = row_locate(slot_subj, rows_l, rows_g)

        # -- 4. refutation + merge -------------------------------------
        self_rx = key_rx[rows_l, self_slot]
        accused = jnp.where(
            key_rank(self_rx) >= RANK_SUSPECT, key_inc(self_rx), -1
        )
        refuting = part_l & ~leaving_l & (accused >= own_inc)
        own_inc = jnp.where(refuting, accused + 1, own_inc)
        awareness = jnp.clip(
            awareness + refuting.astype(awareness.dtype),
            0, base.profile.awareness_max_multiplier - 1,
        )
        key_rx = key_rx.at[rows_l, self_slot].set(-1)
        self_key = jnp.where(
            leaving_l,
            make_key(own_inc, RANK_LEFT), make_key(own_inc, RANK_ALIVE),
        )
        key_after_refute = key_m.at[rows_l, self_slot].max(self_key)
        tx = tx.at[rows_l, self_slot].set(
            jnp.where(refuting, base.tx_limit, tx[rows_l, self_slot])
        )

        old_key = key_after_refute
        # Confirmation leg first so sus_rx dies before new_key exists
        # (the unsharded twin's J6 note); changed == (rx > old).
        changed = key_rx > old_key
        confirming = (
            ~changed
            & (key_rank(old_key) == RANK_SUSPECT)
            & (sus_rx >= key_inc(old_key))
        )
        new_confirms = jnp.minimum(
            confirms + confirming.astype(confirms.dtype),
            base.confirmations_k,
        )
        gained_conf = confirming & (new_confirms > confirms)
        confirms = jnp.where(changed, 0, new_confirms)
        new_key = jnp.maximum(old_key, key_rx)
        fresh_suspect = changed & (key_rank(new_key) == RANK_SUSPECT)
        # Age-packed timer (models/membership_sparse.py narrowing
        # note): fresh suspicion = age 0, view change clears to -1.
        suspect_since = jnp.where(
            fresh_suspect, 0, jnp.where(changed, AGE_NONE, suspect_since)
        ).astype(SINCE_DTYPE)
        tx = jnp.where(changed | gained_conf, base.tx_limit, tx)
        key_m = new_key

        # -- 5. probes (owned draws) -----------------------------------
        if base.probe_enabled:
            is_probe_tick = (t % base.probe_interval_ticks) == 0
            ptarget = sample_probe_targets_owned(k_probe, rows_g, n)
            pt_view = _view_of(slot_subj, key_m, rows_l, ptarget)
            probing = (
                is_probe_tick
                & part_l
                & (key_rank(pt_view) <= RANK_SUSPECT)
            )
            target_up = participates[ptarget]
            p_fail = jnp.where(
                target_up, jnp.float32(base.probe_fail_prob_alive), 1.0
            )
            failed = probing & (
                owned_uniform(k_pfail, rows_g) < p_fail
            )
            can_pend = failed & (st.probe_pending_at == NEVER)
            matures_at = (
                t + base.probe_interval_ticks
                # Widen the narrowed awareness before tick arithmetic.
                + awareness.astype(jnp.int32) * base.probe_timeout_ticks
            )
            awareness = jnp.clip(
                awareness + failed.astype(awareness.dtype)
                - (probing & ~failed).astype(awareness.dtype),
                0, base.profile.awareness_max_multiplier - 1,
            )
            probe_pending_at = jnp.where(
                can_pend, matures_at, st.probe_pending_at
            )
            probe_subject = jnp.where(can_pend, ptarget, st.probe_subject)

            mature = (probe_pending_at <= t) & part_l
            mslot = row_locate(slot_subj, rows_l, probe_subject)
            # Bounded-insertion claim behind lax.cond — steady-state
            # ticks skip it (amortized invariant, as unsharded).
            need = mature & (mslot < 0)
            slots_p = (slot_subj, key_m, suspect_since, confirms, tx)
            slots_p, can, pos, forgot, ov = _claim_one(
                slots_p, need, probe_subject, row_ids=rows_g,
                amortize=resolve_amortize(cfg),
            )
            slot_subj, key_m, suspect_since, confirms, tx = slots_p
            forgotten = jnp.minimum(forgotten, COUNTER_CAP) + (
                jax.lax.psum(forgot, NODE_AXIS)
            )
            overflow = jnp.minimum(overflow, COUNTER_CAP) + jax.lax.psum(
                ov, NODE_AXIS
            )
            mslot = jnp.where(can, pos, mslot)
            mview = jnp.where(
                mslot >= 0,
                key_m[rows_l, jnp.maximum(mslot, 0)], DEFAULT_KEY,
            )
            apply_sus = mature & (mslot >= 0) & (
                key_rank(mview) == RANK_ALIVE
            )
            sus_key = make_key(key_inc(mview), RANK_SUSPECT)
            scol = jnp.where(apply_sus, mslot, k_slots)
            key_m = key_m.at[rows_l, scol].set(
                jnp.where(apply_sus, sus_key, 0), mode="drop"
            )
            suspect_since = suspect_since.at[rows_l, scol].set(
                jnp.zeros((blk,), SINCE_DTYPE), mode="drop"
            )
            confirms = confirms.at[rows_l, scol].set(0, mode="drop")
            tx = tx.at[rows_l, scol].set(base.tx_limit, mode="drop")
            probe_pending_at = jnp.where(mature, NEVER, probe_pending_at)
        else:
            probe_pending_at = st.probe_pending_at
            probe_subject = st.probe_subject

        # -- 6. suspicion expiry ---------------------------------------
        # Per-class int16 threshold table (the unsharded twin's note:
        # exact, and no [blk, K] float temps).
        thr_table = jnp.minimum(
            jnp.ceil(_lifeguard_timeout_ticks(
                base,
                jnp.arange(base.confirmations_k + 1, dtype=jnp.int32),
            )).astype(jnp.int32),
            AGE_CAP + 1,
        ).astype(SINCE_DTYPE)
        threshold = jnp.take(
            thr_table, confirms.astype(jnp.uint8), axis=0
        )
        expire = (
            (key_rank(key_m) == RANK_SUSPECT)
            & (suspect_since >= 0)
            & (suspect_since >= threshold)
            & part_l[:, None]
        )
        key_m = jnp.where(
            expire, make_key(key_inc(key_m), RANK_DEAD), key_m
        )
        suspect_since = jnp.where(
            expire, jnp.asarray(AGE_NONE, SINCE_DTYPE), suspect_since
        )
        tx = jnp.where(expire, base.tx_limit, tx)

        # Live timers age one tick (saturating); no trailing re-sort —
        # merge and probe claims kept the rows sorted (amortized
        # invariant, models/membership_sparse.py).
        suspect_since = jnp.where(
            suspect_since >= 0,
            jnp.minimum(suspect_since + 1, AGE_CAP).astype(SINCE_DTYPE),
            suspect_since,
        )

        nxt = SparseMembershipState(
            slot_subj=slot_subj,
            key=key_m,
            suspect_since=suspect_since,
            confirms=confirms,
            tx=tx,
            own_inc=own_inc,
            awareness=awareness,
            probe_pending_at=probe_pending_at,
            probe_subject=probe_subject,
            overflow=overflow,
            forgotten=forgotten,
            tick=t + 1,
        )

        ranks = key_rank(key_m)
        if track:
            hit = slot_subj[:, :, None] == track_idx[None, None, :]
            sus_t = jax.lax.psum(
                jnp.sum(
                    hit & (ranks == RANK_SUSPECT)[:, :, None],
                    axis=(0, 1), dtype=jnp.int32,
                ),
                NODE_AXIS,
            )
            dead_t = jax.lax.psum(
                jnp.sum(
                    hit & (ranks == RANK_DEAD)[:, :, None],
                    axis=(0, 1), dtype=jnp.int32,
                ),
                NODE_AXIS,
            )
        else:
            sus_t = jnp.zeros((0,), jnp.int32)
            dead_t = jnp.zeros((0,), jnp.int32)
        occ = slot_subj >= 0
        dead_cells = jax.lax.psum(
            jnp.sum(occ & (ranks > RANK_SUSPECT), dtype=jnp.float32),
            NODE_AXIS,
        )
        out = (
            sus_t,
            dead_t,
            jax.lax.psum(
                jnp.sum(occ & (ranks == RANK_SUSPECT), dtype=jnp.int32),
                NODE_AXIS,
            ),
            jnp.float32(n) * n - dead_cells,
        )
        if telemetry:
            out = (*out, reduce_over_mesh(
                "sparse", emit_local("sparse", st, nxt, out, cfg),
                NODE_AXIS,
            ))
        return nxt, out

    state_spec = SparseMembershipState(
        slot_subj=P(NODE_AXIS, None),
        key=P(NODE_AXIS, None),
        suspect_since=P(NODE_AXIS, None),
        confirms=P(NODE_AXIS, None),
        tx=P(NODE_AXIS, None),
        own_inc=P(NODE_AXIS),
        awareness=P(NODE_AXIS),
        probe_pending_at=P(NODE_AXIS),
        probe_subject=P(NODE_AXIS),
        overflow=P(),
        forgotten=P(),
        tick=P(),
    )

    def body(st, key):
        return jax.lax.scan(
            lambda carry, t: tick(carry, jax.random.fold_in(key, t)),
            st, jnp.arange(steps, dtype=jnp.int32),
        )

    n_outs = 5 if telemetry else 4
    run = shard_map(
        body, mesh=mesh,
        in_specs=(state_spec, P()),
        out_specs=(state_spec, tuple(P() for _ in range(n_outs))),
        check_rep=False,
    )
    return run(state, key)


sharded_sparse_membership_scan = jax.jit(
    _sharded_sparse_membership_scan,
    static_argnames=("cfg", "steps", "mesh", "track", "exchange",
                     "telemetry"),
    donate_argnums=(0,),
)


# ---------------------------------------------------------------------------
# Sharded streamcast (pipelined chunked event stream, windowed).
# ---------------------------------------------------------------------------


def _sharded_streamcast_scan(state, key: jax.Array, cfg, steps: int,
                             mesh: Mesh, exchange: str = "alltoall",
                             telemetry: bool = False):
    """Sharded twin of ``sim.engine.streamcast_scan``: each device owns
    ``n/D`` rows of the [n, W, E] chunk plane and the [n, W] budget
    plane; the in-flight window (slot_event/slot_birth and every
    counter) is REPLICATED — the allocator is a pure function of the
    replicated arrival schedule, so all shards step it identically.
    Edges-mode chunk messages whose receiver lives on another shard
    ride the per-destination outbox (pack_outbox -> exchange_outbox,
    ``exchange`` = ``"alltoall"`` | ``"ring"``); aggregate mode needs
    only a [W, E] psum of per-class sender counts.  Returns
    ``(final_state, (*outs, outbox_overflow))`` with the unsharded
    scan's per-tick outs; D == 1 is bit-equal by the owned-draw
    discipline (per-(round, node) keyed streams over the block's
    global ids).

    ``state`` is donated (jaxlint J3, same contract as the unsharded
    scan): callers pass a fresh init positionally."""
    from consul_tpu.obs.spec import emit_local, reduce_over_mesh
    from consul_tpu.ops import (
        bernoulli_mask_owned,
        owned_uniform,
        sample_peers_owned,
    )
    from consul_tpu.streamcast.model import (
        _AUX_SALT,
        _SCHED_SALT,
        StreamcastState,
        _p_live,
        arrival_arrays,
        chunk_validity,
        cursor_phase,
        select_chunk,
    )
    from consul_tpu.streamcast.window import admit, retire

    n, w_slots, e_chunks = cfg.n, cfg.window, cfg.chunks
    fanout = cfg.fanout
    d_shards = int(mesh.devices.size)
    blk = block_size(n, mesh)
    budget = (
        outbox_budget(blk * w_slots * fanout, d_shards)
        if cfg.delivery == "edges" else 1
    )

    def tick(carry, k, sched):
        st, ob_ov = carry
        me = jax.lax.axis_index(NODE_AXIS)
        start = me * blk
        t = st.tick
        k_sel, k_loss = jax.random.split(k)
        k_tie, k_chunk = jax.random.split(
            jax.random.fold_in(k, _AUX_SALT)
        )
        rows_l = jnp.arange(blk, dtype=jnp.int32)
        rows_g = start + rows_l

        # -- 1. arrivals + window admission (replicated) -------------
        ev_tick, ev_origin, ev_name, ev_chunks = sched
        arrive = ev_tick == t
        slot_event, slot_birth, filled, freed, ov, co = admit(
            st.slot_event, st.slot_birth, arrive, ev_name, t
        )
        chunks = st.chunks & ~(freed | filled)[None, :, None]
        tx_left = jnp.where((freed | filled)[None, :], 0, st.tx_left)
        cursor = jnp.where(
            (freed | filled)[None, :],
            cursor_phase(rows_g, e_chunks, st.cursor.dtype)[:, None],
            st.cursor,
        )
        org = ev_origin[jnp.maximum(slot_event, 0)]
        seed = filled[None, :] & (rows_g[:, None] == org[None, :])
        # Heavy-tail chunk-validity mask (replicated — a pure function
        # of the replicated window/schedule): padding chunks born
        # delivered on every shard's block (model.streamcast_round).
        occ = slot_event >= 0
        cvalid = chunk_validity(slot_event, ev_chunks, e_chunks)
        born = occ[:, None] & ~cvalid
        chunks = chunks | seed[:, :, None] | born[None, :, :]
        tx_left = jnp.where(seed, cfg.tx_limit, tx_left)

        # -- 2. transmit (owned draws: [blk, .] streams keyed by
        # global id) -------------------------------------------------
        held_real = chunks & cvalid[None, :, :]
        eligible = (
            jnp.any(held_real, axis=2) & (tx_left > 0) & occ[None, :]
        )
        prio = jnp.where(
            eligible, tx_left.astype(jnp.float32), -jnp.inf
        ) + owned_uniform(k_tie, rows_g, (w_slots,))
        # Slot-index tie-break: float32 tie draws collide at scale and
        # would breach the chunk_budget bound (see the unsharded round).
        widx = jnp.arange(w_slots, dtype=jnp.int32)
        ahead = (prio[:, None, :] > prio[:, :, None]) | (
            (prio[:, None, :] == prio[:, :, None])
            & (widx[None, None, :] < widx[None, :, None])
        )
        rank = jnp.sum(ahead.astype(jnp.int32), axis=2)
        serviced = eligible & (rank < cfg.chunk_budget)
        sel, cursor = select_chunk(
            cfg, k_chunk, rows_g, held_real, cursor, serviced
        )
        p_live = _p_live(cfg, t)
        dropped = jnp.int32(0)

        if cfg.delivery == "edges":
            targets = sample_peers_owned(k_sel, rows_g, n, fanout)
            ok = serviced[:, :, None] & bernoulli_mask_owned(
                k_loss, rows_g, (w_slots, fanout), p_live
            )
            recv = jnp.broadcast_to(
                targets[:, None, :], (blk, w_slots, fanout)
            ).ravel()
            wix = jnp.broadcast_to(
                jnp.arange(w_slots, dtype=jnp.int32)[None, :, None],
                (blk, w_slots, fanout),
            ).ravel()
            cix = jnp.broadcast_to(
                sel[:, :, None], (blk, w_slots, fanout)
            ).ravel()
            okf = ok.ravel()
            dest = recv // blk
            local = okf & (dest == me)
            flat = jnp.where(
                local,
                ((recv - start) * w_slots + wix) * e_chunks + cix,
                blk * w_slots * e_chunks,
            )
            hits = (
                jnp.zeros((blk * w_slots * e_chunks,), jnp.bool_)
                .at[flat].set(True, mode="drop")
            )
            packed, dropped = pack_outbox(
                dest, okf & (dest != me), (recv, wix, cix),
                d_shards, budget,
            )
            ib_recv, ib_w, ib_c = exchange_outbox(
                packed, backend=exchange
            )
            got_in = ib_recv >= 0
            flat_in = jnp.where(
                got_in,
                ((ib_recv - start) * w_slots + ib_w) * e_chunks + ib_c,
                blk * w_slots * e_chunks,
            )
            hits = hits.at[flat_in].set(True, mode="drop")
            new_chunks = chunks | hits.reshape(
                blk, w_slots, e_chunks
            )
        else:
            # Aggregate: the only cross-shard traffic is the [W, E]
            # per-class sender count.
            onehot = held_real & (
                sel[:, :, None]
                == jnp.arange(e_chunks, dtype=jnp.int32)[None, None, :]
            )
            contrib = (serviced[:, :, None] & onehot).astype(
                jnp.float32
            )
            s_tot = jax.lax.psum(
                jnp.sum(contrib, axis=0), NODE_AXIS
            )
            lam = (
                (s_tot[None, :, :] - contrib) * fanout * p_live
                / max(n - 1, 1)
            )
            u = owned_uniform(k_loss, rows_g, (w_slots, e_chunks))
            new_chunks = chunks | (u < -jnp.expm1(-lam))

        sent = jax.lax.psum(
            jnp.sum(serviced, dtype=jnp.int32), NODE_AXIS
        ) * fanout
        spent = jnp.where(serviced, fanout, 0).astype(jnp.int32)
        tx_left = jnp.maximum(tx_left - spent, 0)
        newly = jnp.any(new_chunks & ~chunks, axis=2)
        tx_left = jnp.where(newly, cfg.tx_limit, tx_left)

        # -- 3. completion + retirement (replicated decisions) -------
        full = jnp.all(new_chunks, axis=2) & occ[None, :]
        done_count = jax.lax.psum(
            jnp.sum(full, axis=0, dtype=jnp.int32), NODE_AXIS
        )
        active = jax.lax.psum(
            jnp.sum(
                jnp.any(new_chunks & cvalid[None, :, :], axis=2)
                & (tx_left > 0),
                axis=0, dtype=jnp.int32,
            ),
            NODE_AXIS,
        )
        cleared, complete, quiesced = retire(
            slot_event, done_count, active, slot_birth, t,
            cfg.done_target,
        )

        offered = st.offered + jnp.sum(arrive, dtype=jnp.int32)
        delivered = st.delivered + jnp.sum(complete, dtype=jnp.int32)
        quiesced_ct = st.quiesced + jnp.sum(quiesced, dtype=jnp.int32)
        overflow = st.window_overflow + ov
        coalesced = st.coalesced + co
        ob_ov = ob_ov + jax.lax.psum(dropped, NODE_AXIS)

        outs = (
            slot_event, slot_birth, done_count,
            offered, delivered, quiesced_ct, overflow, coalesced,
            sent, ob_ov,
        )
        nxt = StreamcastState(
            chunks=new_chunks & ~cleared[None, :, None],
            tx_left=jnp.where(cleared[None, :], 0, tx_left),
            cursor=jnp.where(
                cleared[None, :], jnp.asarray(0, cursor.dtype), cursor
            ),
            slot_event=jnp.where(cleared, -1, slot_event),
            slot_birth=slot_birth,
            offered=offered,
            delivered=delivered,
            quiesced=quiesced_ct,
            window_overflow=overflow,
            coalesced=coalesced,
            tick=t + 1,
        )
        if telemetry:
            outs = (*outs, reduce_over_mesh(
                "streamcast",
                emit_local("streamcast", st, nxt, outs[:9], cfg),
                NODE_AXIS,
            ))
        return (nxt, ob_ov), outs

    def body(st, key):
        # The arrival schedule is a pure function of the replicated
        # key, so every shard derives the identical stream.
        sched = arrival_arrays(
            cfg, jax.random.fold_in(key, _SCHED_SALT)
        )
        (final, _ov), outs = jax.lax.scan(
            lambda carry, t: tick(
                carry, jax.random.fold_in(key, t), sched
            ),
            (st, jnp.int32(0)), jnp.arange(steps, dtype=jnp.int32),
        )
        return final, outs

    state_spec = StreamcastState(
        chunks=P(NODE_AXIS, None, None),
        tx_left=P(NODE_AXIS, None),
        cursor=P(NODE_AXIS, None),
        slot_event=P(),
        slot_birth=P(),
        offered=P(),
        delivered=P(),
        quiesced=P(),
        window_overflow=P(),
        coalesced=P(),
        tick=P(),
    )
    n_outs = 11 if telemetry else 10
    run = shard_map(
        body, mesh=mesh,
        in_specs=(state_spec, P()),
        out_specs=(state_spec, tuple(P() for _ in range(n_outs))),
        check_rep=False,
    )
    return run(state, key)


sharded_streamcast_scan = jax.jit(
    _sharded_streamcast_scan,
    static_argnames=("cfg", "steps", "mesh", "exchange", "telemetry"),
    donate_argnums=(0,),
)


# ---------------------------------------------------------------------------
# Sharded geo/WAN plane (multi-DC, latency-delayed bandwidth-capped links).
# ---------------------------------------------------------------------------


def _sharded_geo_scan(state, key: jax.Array, cfg, steps: int,
                      mesh: Mesh, exchange: str = "alltoall",
                      telemetry: bool = False):
    """Sharded twin of ``sim.engine.geo_scan``: segments are laid out
    CONTIGUOUSLY over the mesh (``segments % D == 0``, each device
    owning ``segments/D`` whole DCs), so ALL LAN traffic — the
    receiver-side Poissonized per-segment gossip — is device-local and
    only WAN units (anti-entropy + bridge gossip) cross the mesh: the
    ICI/DCN ↔ LAN/WAN analogy of SURVEY.md §5 stated as a layout.

    The link plane (beliefs, offers, admission against the bandwidth
    schedule, the latency ring, the EWMA controller) is REPLICATED —
    it is a pure function of the replicated per-segment bridge-known
    masks and the replicated round keys, so every shard steps it
    bit-identically.  Delivery slots are link-plane draws (replicated
    by design, S²-scale); each shard
    emits only the slots whose SOURCE segment it owns, local
    deliveries scatter directly, and remote ones ride the
    per-destination outbox (pack_outbox -> exchange_outbox,
    ``exchange`` = ``"alltoall"`` | ``"ring"``).  D == 1 is bit-equal
    to the unsharded scan; returns ``(final_state, (*outs,
    outbox_overflow))``.

    ``state`` is donated (jaxlint J3, same contract as the unsharded
    scan): callers pass a fresh init positionally."""
    from consul_tpu.geo.model import (
        GeoState,
        _p_wan,
        admit_link_units,
        expand_delivery_slots,
    )
    from consul_tpu.obs.spec import emit_local, reduce_over_mesh
    from consul_tpu.ops import bernoulli_mask, owned_uniform
    from consul_tpu.sim.faults import link_capacity_at

    n, S, ss = cfg.n, cfg.segments, cfg.seg_size
    B, E, L = cfg.bridges_per_segment, cfg.events, cfg.wan_window
    S2, U = cfg.n_links, cfg.cap_units
    d_shards = int(mesh.devices.size)
    if S % d_shards:
        raise ValueError(
            f"segments={S} does not divide over {d_shards} devices — "
            "the geo layout owns whole DCs per device"
        )
    spd = S // d_shards
    blk = block_size(n, mesh)
    # Per-shard emission bound: a shard sends only the slots whose
    # SOURCE segment it owns — spd * S links x U slots (the c x-mean
    # discipline of outbox_budget wants the per-shard stream length).
    budget = outbox_budget(spd * S * U, d_shards)

    def tick(carry, k):
        st, ob_ov = carry
        me = jax.lax.axis_index(NODE_AXIS)
        start = me * blk
        t = st.tick
        k_lan, k_gossip, k_tgt, k_loss = jax.random.split(k, 4)
        knows = st.knows
        rows_l = jnp.arange(blk, dtype=jnp.int32)
        rows_g = start + rows_l
        seg_l = rows_l // ss                       # local segment index

        # -- 1. LAN gossip: per-segment Poissonized, device-local ----
        senders = knows & (st.tx_lan > 0)
        per_seg_senders = jnp.sum(
            senders.reshape(spd, ss, E).astype(jnp.int32), axis=1
        ).astype(jnp.float32)
        lam = (
            (per_seg_senders[seg_l] - senders.astype(jnp.float32))
            * cfg.fanout_lan
            * (1.0 - jnp.asarray(cfg.loss_lan, jnp.float32))
            / max(ss - 1, 1)
        )
        # Owned LAN draws ([blk, E], keyed by global id); the WAN link
        # plane's [S2, .] draws below stay REPLICATED by design — the
        # link plane is a pure function every shard must step
        # identically, and it is S²-scale, not n-scale.
        got_lan = (
            owned_uniform(k_lan, rows_g, (E,)) < -jnp.expm1(-lam)
        ) & ~knows

        # -- 2. bridge-known masks: local slices, replicated assembly -
        bridge_rows = knows.reshape(spd, ss, E)[:, :B, :]
        seg_slot = me * spd + jnp.arange(spd, dtype=jnp.int32)
        bk = jax.lax.psum(
            jnp.zeros((S, E), jnp.int32)
            .at[seg_slot].set(jnp.any(bridge_rows, axis=1)
                              .astype(jnp.int32)),
            NODE_AXIS,
        ) > 0
        bk_cnt = jax.lax.psum(
            jnp.zeros((S, E), jnp.int32)
            .at[seg_slot].set(jnp.sum(bridge_rows.astype(jnp.int32),
                                      axis=1)),
            NODE_AXIS,
        ).astype(jnp.float32)
        known_hist = st.known_hist.at[t % L].set(bk)
        lat = jnp.asarray(cfg.latency_flat(), jnp.int32)
        link = jnp.arange(S2, dtype=jnp.int32)
        src_idx, dst_idx = link // S, link % S
        cross = src_idx != dst_idx
        belief = known_hist[(t - lat) % L, dst_idx]
        src_bk = bk[src_idx]

        # -- 3-5. offers + admission (replicated, as unsharded) ------
        missing = src_bk & ~belief & cross[:, None]
        rank = jnp.cumsum(missing.astype(jnp.int32), axis=1) - missing
        if cfg.adaptive:
            # EWMA-throughput minus the standing backlog (+1 probe):
            # the adaptive-SMR sizing rule — see geo.model.geo_round.
            backlog = jnp.sum(st.queue, axis=1)
            batch = jnp.clip(
                jnp.floor(st.ewma).astype(jnp.int32) + 1 - backlog,
                0, cfg.ae_batch,
            )
        else:
            batch = jnp.full((S2,), cfg.ae_batch, jnp.int32)
        ae = (missing & (rank < batch[:, None])).astype(jnp.int32)
        lam_g = (
            bk_cnt[src_idx]
            * (cfg.wan_rate * cfg.fanout_wan / max(S - 1, 1))
            * cross[:, None].astype(jnp.float32)
        )
        gossip = jax.random.poisson(k_gossip, lam_g).astype(jnp.int32)
        cap_f = link_capacity_at(
            cfg.faults, t, S, base=cfg.wan_capacity_bytes
        ).reshape(S2)
        cap_units = jnp.clip(
            jnp.floor(cap_f / cfg.wan_msg_bytes), 0, U
        ).astype(jnp.int32)
        cap_units = jnp.where(cross, cap_units, 0)
        stream = jnp.concatenate([st.queue, ae, gossip], axis=1)
        adm, deferred, ovf = admit_link_units(
            stream, cap_units, cfg.queue_units
        )
        admitted_e = adm[:, :E] + adm[:, E:2 * E] + adm[:, 2 * E:]
        # Congested links DROP gossip (loudly) — only the AE stream
        # defers into the queue; see geo.model.geo_round.
        queue = deferred[:, :E] + deferred[:, E:2 * E]
        offered_fresh = jnp.sum(ae + gossip, axis=1)
        admitted_tot = jnp.sum(admitted_e, axis=1)
        overflow_tot = jnp.sum(ovf, axis=1) + jnp.sum(
            deferred[:, 2 * E:], axis=1
        )

        # -- 6. latency ring + delivery over the outbox seam ---------
        arriving = st.ring[t % L]
        ring = st.ring.at[t % L].set(0)
        ring = ring.at[(t + lat) % L, link].add(admitted_e)
        ev_slot, valid = expand_delivery_slots(arriving, U)
        tb = jax.random.randint(k_tgt, (S2, U), 0, B, dtype=jnp.int32)
        recv = dst_idx[:, None] * ss + tb
        live = valid & bernoulli_mask(k_loss, (S2, U), _p_wan(cfg, t))
        # Each slot is emitted by exactly ONE shard — its source
        # segment's owner; locals scatter directly, remotes ride the
        # outbox, so the union over shards is the unsharded slot set.
        okf = (live & ((src_idx // spd) == me)[:, None]).ravel()
        recv_f = recv.ravel()
        ev_f = ev_slot.ravel()
        dest = recv_f // blk
        local = okf & (dest == me)
        flat = jnp.where(local, (recv_f - start) * E + ev_f, blk * E)
        hits = (
            jnp.zeros((blk * E,), jnp.bool_)
            .at[flat].set(True, mode="drop")
        )
        packed, dropped = pack_outbox(
            dest, okf & (dest != me), (recv_f, ev_f), d_shards, budget
        )
        ib_recv, ib_ev = exchange_outbox(packed, backend=exchange)
        got_in = ib_recv >= 0
        flat_in = jnp.where(
            got_in, (ib_recv - start) * E + ib_ev, blk * E
        )
        hits = hits.at[flat_in].set(True, mode="drop").reshape(blk, E)
        got_wan = hits & ~knows
        wasted = st.wasted + jnp.sum(
            arriving * bk[dst_idx].astype(jnp.int32), dtype=jnp.int32
        )
        ob_ov = ob_ov + jax.lax.psum(dropped, NODE_AXIS)

        # -- 7. merge + budgets --------------------------------------
        newly = got_lan | got_wan
        new_knows = knows | newly
        tx_lan = jnp.maximum(
            st.tx_lan - jnp.where(senders, cfg.fanout_lan, 0), 0
        )
        tx_lan = jnp.where(newly, cfg.tx_limit_lan, tx_lan)
        gain = jnp.asarray(cfg.ae_gain, jnp.float32)
        ewma = (
            (1.0 - gain) * st.ewma
            + gain * admitted_tot.astype(jnp.float32)
        )
        per_segment = jax.lax.psum(
            jnp.zeros((S,), jnp.int32).at[seg_slot].set(
                jnp.sum(
                    jnp.all(new_knows, axis=1)
                    .reshape(spd, ss).astype(jnp.int32),
                    axis=1,
                )
            ),
            NODE_AXIS,
        )
        outs = (
            per_segment, offered_fresh, admitted_tot,
            jnp.sum(queue, axis=1), overflow_tot, wasted, ob_ov,
        )
        nxt = GeoState(
            knows=new_knows, tx_lan=tx_lan, ring=ring, queue=queue,
            known_hist=known_hist, ewma=ewma, wasted=wasted,
            tick=t + 1,
        )
        if telemetry:
            outs = (*outs, reduce_over_mesh(
                "geo", emit_local("geo", st, nxt, outs[:6], cfg),
                NODE_AXIS,
            ))
        return (nxt, ob_ov), outs

    def body(st, key):
        (final, _ov), outs = jax.lax.scan(
            lambda carry, t: tick(carry, jax.random.fold_in(key, t)),
            (st, jnp.int32(0)), jnp.arange(steps, dtype=jnp.int32),
        )
        return final, outs

    state_spec = GeoState(
        knows=P(NODE_AXIS, None),
        tx_lan=P(NODE_AXIS, None),
        ring=P(),
        queue=P(),
        known_hist=P(),
        ewma=P(),
        wasted=P(),
        tick=P(),
    )
    n_outs = 8 if telemetry else 7
    run = shard_map(
        body, mesh=mesh,
        in_specs=(state_spec, P()),
        out_specs=(state_spec, tuple(P() for _ in range(n_outs))),
        check_rep=False,
    )
    return run(state, key)


sharded_geo_scan = jax.jit(
    _sharded_geo_scan,
    static_argnames=("cfg", "steps", "mesh", "exchange", "telemetry"),
    donate_argnums=(0,),
)


# ---------------------------------------------------------------------------
# Standalone multichip datapoint: python -m consul_tpu.parallel.shard
# ---------------------------------------------------------------------------


def exchange_phase_walls(cfg, mesh: Mesh, backend: str,
                         iters: int = 20) -> dict:
    """Per-round wall-clock split of one broadcast-shaped gossip round:
    the pack+exchange program and the local delivery scatter, each
    timed standalone at the round's exact shapes.  This is how the
    overlap win of the ring backend is *measured* instead of assumed —
    ``exchange_wall_s`` is what the round pays when the transport does
    NOT hide behind the merge, ``merge_wall_s`` is the local work it
    can hide behind."""
    import time

    import numpy as np

    from consul_tpu.ops import deliver_or, sample_peers

    n, fanout = cfg.n, cfg.fanout
    d_shards = int(mesh.devices.size)
    blk = block_size(n, mesh)
    budget = outbox_budget(blk * fanout, d_shards)

    def ex_body(recv, ok):
        me = jax.lax.axis_index(NODE_AXIS)
        r = recv.reshape(-1)
        o = ok.reshape(-1)
        dest = r // blk
        packed, dropped = pack_outbox(
            dest, o & (dest != me), (r,), d_shards, budget
        )
        (ib,) = exchange_outbox(packed, backend=backend)
        return (jnp.sum(ib, dtype=jnp.int32) + dropped)[None]

    def mg_body(knows, recv, ok):
        me = jax.lax.axis_index(NODE_AXIS)
        r = recv.reshape(-1)
        o = ok.reshape(-1)
        local = o & (r // blk == me)
        return deliver_or(
            knows, jnp.where(local, r - me * blk, blk), local
        )

    spec2 = P(NODE_AXIS, None)
    run_ex = jax.jit(shard_map(
        ex_body, mesh=mesh, in_specs=(spec2, spec2),
        out_specs=P(NODE_AXIS), check_rep=False,
    ))
    run_mg = jax.jit(shard_map(
        mg_body, mesh=mesh, in_specs=(P(NODE_AXIS), spec2, spec2),
        out_specs=P(NODE_AXIS), check_rep=False,
    ))

    key = jax.random.PRNGKey(7)
    recv = sample_peers(key, n, fanout)
    ok = jnp.ones((n, fanout), bool)
    knows = jnp.zeros((n,), bool)

    def timed(fn, *args):
        np.asarray(fn(*args))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        np.asarray(out)
        return (time.perf_counter() - t0) / iters

    return {
        "exchange_wall_s": round(timed(run_ex, recv, ok), 6),
        "merge_wall_s": round(timed(run_mg, knows, recv, ok), 6),
    }


def main(argv=None) -> int:
    """Emit one multichip datapoint as a JSON line: the sharded
    broadcast study over ``--devices`` mesh devices at ``--n``
    AGGREGATE nodes.

    This is bench.py's subprocess on single-device (CPU) containers —
    like ``__graft_entry__.dryrun_multichip``, when the process doesn't
    already expose enough devices it forces virtual host devices via
    ``xla_force_host_platform_device_count`` before first backend use.
    On a real v5e-8 bench runs the same study in-process instead."""
    import argparse
    import json
    import os
    import time

    import numpy as np

    parser = argparse.ArgumentParser(prog="consul_tpu.parallel.shard")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--n", type=int, default=4096,
                        help="aggregate nodes across the mesh")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--exchange", default="both",
                        choices=("alltoall", "ring", "both"),
                        help="outbox transport(s) to measure "
                             "(default: both, so the ring/all_to_all "
                             "comparison ships in one datapoint)")
    args = parser.parse_args(argv)

    forced = False
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}"
        ).strip()
        try:
            jax.config.update("jax_platforms", "cpu")
            forced = True
        except RuntimeError:
            pass  # backend already initialized; use whatever exists

    from consul_tpu.models.broadcast import (
        BroadcastConfig,
        broadcast_init,
    )
    from consul_tpu.parallel.mesh import mesh_for

    # mesh_for raises on a device shortfall (pre-set XLA_FLAGS with a
    # smaller count, or a backend initialized before the forcing above)
    # — a quietly-shrunk mesh would emit a "multichip" datapoint that
    # isn't, violating the loud-never-silent discipline.
    mesh = mesh_for(args.devices)
    cfg = BroadcastConfig(n=args.n, fanout=4, delivery="edges")
    key = jax.random.PRNGKey(args.seed)
    backends = (
        ("alltoall", "ring") if args.exchange == "both"
        else (args.exchange,)
    )
    per_backend: dict = {}
    for ex in backends:
        # Warmup compiles the program; the timed pass is steady-state.
        _, (infected, ov) = sharded_broadcast_scan(
            broadcast_init(cfg), key, cfg, args.steps, mesh, ex
        )
        np.asarray(infected)
        t0 = time.perf_counter()
        _, (infected, ov) = sharded_broadcast_scan(
            broadcast_init(cfg), key, cfg, args.steps, mesh, ex
        )
        infected = np.asarray(infected)
        wall = time.perf_counter() - t0
        per_backend[ex] = {
            "rounds_per_sec": (
                round(args.steps / wall, 2) if wall > 0 else None
            ),
            "infected_final": int(infected[-1]),
            "overflow": int(np.asarray(ov)),
            # The measured split the overlap claim rides on.
            **exchange_phase_walls(cfg, mesh, ex),
        }
    head = per_backend[backends[0]]
    print(json.dumps({
        "devices": int(mesh.devices.size),
        "nodes_aggregate": cfg.n,
        "nodes_per_device": cfg.n // int(mesh.devices.size),
        "rounds": args.steps,
        "rounds_per_sec": head["rounds_per_sec"],
        "infected_final": head["infected_final"],
        "overflow": head["overflow"],
        "exchange_backend": backends[0],
        "exchange_backends": per_backend,
        "host_devices_forced": forced,
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
