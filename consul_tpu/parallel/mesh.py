"""Mesh construction and node-axis sharding.

The simulator's execution strategy is data parallelism over the node
population (SURVEY.md §2.4): every per-node array is sharded along a
single ``nodes`` mesh axis; random cross-shard gossip edges become XLA
collectives over ICI.  Segments/datacenters (the reference's LAN
partitions, agent/consul/server_serf.go:50) map onto contiguous node
ranges so that one segment lives on one device and WAN edges are the only
cross-device traffic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis name ``nodes``."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def mesh_for(n_devices: int) -> Mesh:
    """1-D ``nodes`` mesh over the FIRST ``n_devices`` devices — the
    ``cli sim --devices D`` entry point.  On a v5e-8 all eight chips
    form the mesh; on CPU containers the virtual host devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=8``) stand in.
    """
    devs = jax.devices()
    if n_devices < 1 or n_devices > len(devs):
        raise ValueError(
            f"need 1..{len(devs)} devices, asked for {n_devices} "
            "(force host devices with XLA_FLAGS="
            "--xla_force_host_platform_device_count=N before JAX import)"
        )
    return make_mesh(devs[:n_devices])


def block_size(n: int, mesh: Mesh) -> int:
    """Nodes per device under contiguous-block sharding; the node axis
    must divide evenly (same constraint shard_state's placement rule
    encodes as 'shape[0] % n_dev == 0')."""
    d = int(mesh.devices.size)
    if n % d:
        raise ValueError(f"n={n} does not divide over {d} devices")
    return n // d


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a per-node array: first dim split across the mesh."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_state(state, mesh: Mesh):
    """Place a model state pytree: per-node arrays (ndim >= 1, leading dim
    divisible by mesh size) sharded on the node axis, scalars replicated."""
    n_dev = mesh.devices.size
    shard, repl = node_sharding(mesh), replicated(mesh)

    def place(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] % n_dev == 0:
            return jax.device_put(x, shard)
        return jax.device_put(x, repl)

    return jax.tree_util.tree_map(place, state)
